// Internal header of the native RPC runtime — the seams of the brpc core
// (SURVEY.md §2.4), one translation unit per seam like the reference's
// socket.cpp / event_dispatcher.cpp / input_messenger.cpp / channel.cpp /
// server.cpp split:
//
//   nat_socket.cpp     NatSocket + versioned-id registry + ring datapath
//   nat_messenger.cpp  tpu_std cut loop, frame builders, console HTTP
//   nat_server.cpp     Dispatcher loops, NatServer lifecycle, py lane C API
//   nat_channel.cpp    NatChannel, dial/health-check, call paths C API
//   nat_bench.cpp      client bench harnesses
//
// See nat_socket.cpp's header comment for the design map to the reference.
#pragma once

#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <stdio.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

#include "iobuf.h"
#include "nat_api.h"
#include "nat_dump.h"
#include "nat_fault.h"
#include "nat_lockrank.h"
#include "nat_refown.h"
#include "nat_stats.h"
#include "nat_wstack.h"
#include "ring_listener.h"
#include "rpc_meta.h"
#include "scheduler.h"
#include "timer_thread.h"

// ---- wiretrust annotation surface (tools/natcheck/wiretrust.py) ----
//
// NAT_WIRE(expr) marks `expr` as wire-origin bytes at the point where
// attacker- or corruption-controlled data enters a parser: socket drain
// fill buffers, shm descriptor cells, recordio loads, TDEV credentials.
// The macro is a compile-time no-op; the wiretrust static pass taints
// the value and verifies every use as a memcpy/memmove length,
// allocation size, container resize, array index, pointer offset or
// loop bound sits behind a dominating bounds check against a trusted
// limit. `// natcheck:wire: a, b` marks identifiers the same way where
// a macro is awkward (e.g. struct fields loaded from a mapped
// segment). Suppress a deliberate use with
// `// natcheck:allow(wiretrust): <bounds argument>`.
#ifndef NAT_WIRE
#define NAT_WIRE(x) (x)
#endif

namespace brpc_tpu {

// error codes shared with brpc_tpu/rpc/errors.py
inline constexpr int kENOSERVICE = 1001;
inline constexpr int kENOMETHOD = 1002;
inline constexpr int kEREQUEST = 1003;
inline constexpr int kETOOMANYFAILS = 1005;  // fan-out fail_limit reached
inline constexpr int kERPCTIMEDOUT = 1008;
inline constexpr int kEFAILEDSOCKET = 1009;
inline constexpr int kELIMIT = 2004;  // max concurrency reached

inline constexpr char kMagicRpc[4] = {'T', 'R', 'P', 'C'};

inline uint32_t rd_be32(const char* p) {
  return ((uint32_t)(uint8_t)p[0] << 24) | ((uint32_t)(uint8_t)p[1] << 16) |
         ((uint32_t)(uint8_t)p[2] << 8) | (uint32_t)(uint8_t)p[3];
}
inline void wr_be32(char* p, uint32_t v) {
  p[0] = (char)(v >> 24);
  p[1] = (char)(v >> 16);
  p[2] = (char)(v >> 8);
  p[3] = (char)v;
}

class Dispatcher;
class NatServer;
class NatChannel;
struct HttpSessionN;
struct H2SessionN;
struct SslSessionN;
struct HttpCliSessN;
struct H2CliSessN;
struct RedisSessN;
struct RedisStoreN;
struct PyRequest;

// ---------------------------------------------------------------------------
// NatSocket + versioned-id registry (socket_inl.h:28-185 shape)
// ---------------------------------------------------------------------------

// One queued socket write — a node of the wait-free MPSC write stack
// (the reference's WriteRequest, socket.cpp:115). Pooled per thread.
struct WriteReq {
  nat::atomic<WriteReq*> wnext{nullptr};
  IOBuf data;
};

WriteReq* wreq_alloc();
void wreq_free(WriteReq* r);

struct NatSocket {
  int fd = -1;
  // atomic: the server-stop scan reads ids of slots that sock_create may
  // concurrently be recycling (relaxed loads compile to plain loads here)
  std::atomic<uint64_t> id{0};
  Dispatcher* disp = nullptr;
  NatServer* server = nullptr;    // set on accepted connections
  NatChannel* channel = nullptr;  // set on client connections

  std::atomic<bool> failed{false};
  // (version<<32)|refcount in ONE atomic (the _versioned_ref of
  // socket_inl.h:28-78): addressing CAS-increments the refcount only
  // while the version matches, so a stale id can never revive a recycled
  // socket, and no registry lock is needed on the per-event/per-call path.
  std::atomic<uint64_t> versioned_ref{0};
  uint32_t next_version = 1;  // owner-only; assigned at (re)creation

  // read side: drained inline by the owning dispatcher loop (single
  // reader per socket by construction)
  IOBuf in_buf;

  // write side — the wait-free MPSC write stack (nat_wstack.h, the
  // reference Socket's write discipline): writers push whole frames with
  // one atomic exchange; the empty-head winner becomes the SINGLE
  // drainer. The fields below the stack are DRAINER-OWNED — only the
  // current role holder (inline caller, KeepWrite fiber, ring-send
  // completion, retry pass) touches them, and role handoffs synchronize
  // through scheduler queues / the ring completion queue, so they need
  // no lock at all.
  WStack<WriteReq> wstack;
  WriteReq* wcur = nullptr;   // FIFO-chain terminator (== last observed
                              // stack head); kept alive for grab_more
  IOBuf wbuf;                 // gathered-but-unwritten bytes (drainer)
  bool ring_sending = false;  // a fixed-buffer send is in flight (the
                              // role is parked on its completion)
  size_t ring_inflight = 0;   // bytes submitted, awaiting completion
  Butex epollout;       // bumped by the dispatcher on EPOLLOUT
  // epoll_ctl MOD arbitration for EPOLLOUT arm/disarm — COLD path only
  // (kernel socket buffer full); guards epoll_events so a finished
  // KeepWrite fiber's disarm cannot clobber its successor's arm.
  NatMutex<kLockRankSockEpoll> epollctl_mu;
  uint32_t epoll_events = 0;  // currently-armed event mask
  // Deferred-write mode (the fork's io_uring submission-batching
  // discipline, ring_listener.h): write() only queues; a writer fiber
  // scheduled behind the currently-ready fibers drains everything they
  // appended in ONE writev. Throughput over per-call latency.
  bool defer_writes = false;

  // Raw python-lane mode (the multi-protocol-port sniff-once-and-remember
  // discipline, input_messenger.h:33-154): once non-tpu_std bytes are
  // seen on a raw-fallback server, ALL further input on this connection
  // is shovelled to the Python protocol stack as ordered raw chunks.
  // atomic: set by the reading thread, read by set_failed from any
  // thread (server stop, nat_sock_set_failed). py_raw_seq stays plain —
  // only the single reading thread touches it.
  std::atomic<bool> py_raw{false};
  uint64_t py_raw_seq = 0;
  // streaming frames cut natively on this socket (kind-5 ordering);
  // py_streams mirrors py_raw's close-notice duty for stream sessions
  std::atomic<bool> py_streams{false};
  uint64_t stream_seq = 0;
  // Large-payload fill mode (the IOBuf→HBM zero-copy north star's
  // socket leg): a big TSTR DATA payload fills its PyRequest buffer
  // STRAIGHT from the socket/ring-buffer — in_buf (and its copy) is
  // bypassed for the payload bytes. Owned by the reading thread; freed
  // on socket teardown.
  PyRequest* fill_req = nullptr;
  size_t fill_off = 0;
  // tpu_std bulk-frame fill mode (read-side arena blocks, the
  // registered-pool read path of the reference's rdma config): when a
  // frame header announces a body >= kBulkFillMin that is not yet
  // buffered, the remaining bytes read STRAIGHT into one pooled bulk
  // slab (iob_bulk_acquire) that joins in_buf as a single arena-backed
  // USER block on completion — the whole frame body is then one
  // contiguous ref, so meta/payload/attachment cut zero-copy and the
  // echo/write path emits one iovec instead of ~128 8KB blocks per MB.
  // Owned by the reading thread; released on socket teardown.
  char* bulk_buf = nullptr;
  size_t bulk_cap = 0;  // slab capacity (the pool-release key)
  size_t bulk_len = 0;  // frame body length (fill target)
  size_t bulk_off = 0;  // filled prefix

  // Native protocol sessions (the per-connection parse state the
  // reference keeps in Socket::_parsing_context, socket.h:793): owned by
  // the single reading thread; freed on recycle. Sniffed once per
  // connection like py_raw.
  HttpSessionN* http = nullptr;  // native HTTP/1.1 session
  H2SessionN* h2 = nullptr;      // native h2/gRPC session
  RedisSessN* redis = nullptr;   // native RESP session
  // client-side protocol sessions (the reference's client half of
  // http_rpc_protocol.cpp / http2_rpc_protocol.cpp): attached when the
  // owning channel speaks HTTP/h2 instead of tpu_std
  HttpCliSessN* httpc = nullptr;
  H2CliSessN* h2c = nullptr;

  // Graceful close (Connection: close semantics): once set, the socket
  // fails as soon as the write queue drains — queued bytes flush first,
  // then shutdown sends FIN.
  std::atomic<bool> close_after_drain{false};

  // The connection has carried at least one tpu_std frame: the quiesce
  // lame-duck pass may speak tpu_std back on it (a SHUTDOWN control
  // frame would poison any other protocol). Reading thread stores,
  // quiesce scan reads — atomic for the cross-thread read only.
  std::atomic<bool> spoke_tpu_std{false};

  // TLS (the Socket-level SSLState of socket.h:539-540): set when the
  // first record on a TLS-enabled server port sniffs as a handshake;
  // in_buf then holds PLAINTEXT only (read paths feed ciphertext through
  // the session), and write() encrypts before queueing. ssl_declined
  // remembers a plaintext peer so the sniff runs once.
  SslSessionN* ssl_sess = nullptr;
  bool ssl_declined = false;

  // Per-connection observability (the native /connections row,
  // connections_service.cpp role): relaxed atomics — each is written by
  // one thread at a time (reader loop / drain-role holder) and read by
  // the snapshot walker. c_unwritten tracks bytes queued on the write
  // stack that the kernel has not yet accepted (the UnwrittenBytes
  // column); saturating-subtracted so a reset racing a push can never
  // wrap it negative. c_in_msgs counts protocol messages parsed off the
  // wire; c_out_msgs counts messages emitted INTO the session/write
  // stack — batch emit sites (http/h2/redis reorder windows, tpu_std
  // batches) count before the flush outcome is known, so on a socket
  // that fails mid-flush out_msgs may exceed what reached the wire
  // (failed sockets are excluded from /connections, so the skew is
  // only ever visible through a raw snapshot).
  // /connections visibility gate: set (release) only after the creating
  // thread finished setup (fd, peer, disp, channel/server, client
  // session attach), so the snapshot walker — which can pin the socket
  // the instant sock_create publishes versioned_ref — never reads those
  // plain fields mid-write. Server-side protocol session pointers are
  // sniffed later and stay outside the gate (see conn_fill_row).
  std::atomic<bool> conn_visible{false};
  std::atomic<uint64_t> c_in_bytes{0};
  std::atomic<uint64_t> c_out_bytes{0};
  std::atomic<uint64_t> c_in_msgs{0};
  std::atomic<uint64_t> c_out_msgs{0};
  std::atomic<uint64_t> c_read_calls{0};
  std::atomic<uint64_t> c_write_calls{0};
  std::atomic<uint64_t> c_unwritten{0};
  // per-socket approximate memory (ISSUE 14's /connections column):
  // c_rdbuf = buffered-but-unparsed read bytes, settled once per drain
  // by the reading thread; c_parked = reorder-window bytes parked on
  // the protocol session (http/h2/redis out-of-order responses and
  // flow-control-blocked h2 sends), adjusted under the session lock.
  // mem_bytes in the snapshot row = unwritten + rdbuf + parked.
  std::atomic<uint64_t> c_rdbuf{0};
  std::atomic<uint64_t> c_parked{0};

  void conn_parked_add(uint64_t n) {
    c_parked.fetch_add(n, std::memory_order_relaxed);
  }
  void conn_parked_sub(uint64_t n) {
    uint64_t v = c_parked.load(std::memory_order_relaxed);
    while (!c_parked.compare_exchange_weak(
        v, v > n ? v - n : 0, std::memory_order_relaxed)) {
    }
  }
  // "ip:port" peer, written once at accept/dial before the socket joins
  // its dispatcher; snapshot readers may see "" during setup
  char peer[24] = {0};

  void conn_unwritten_sub(uint64_t n) {
    uint64_t v = c_unwritten.load(std::memory_order_relaxed);
    while (!c_unwritten.compare_exchange_weak(
        v, v > n ? v - n : 0, std::memory_order_relaxed)) {
    }
  }

  // io_uring datapath: (generation<<32 | file index) on the OWNING
  // dispatcher's ring when this socket's reads ride the provided-buffer
  // ring (-1 = epoll lane); the generation lets the ring reject stale
  // rearms/sends after the slot is recycled. `ring` is the per-loop
  // RingListener the slot lives in (loops never share a ring). Send
  // state lives in the drainer-owned block above: one in-flight
  // fixed-buffer send at a time keeps ordering (the fork's
  // io_uring_write_req_, socket.h:632-636).
  std::atomic<int64_t> ring_ref{-1};  // atomic: drain workers read it
                                      // while accept/set_failed write it
  RingListener* ring = nullptr;  // set at adopt, before ring_ref publishes

  void add_ref() { versioned_ref.fetch_add(1, std::memory_order_relaxed); }
  void release();
  void reset_for_reuse();
  int write(IOBuf&& frame);      // encrypts first on TLS sockets
  int write_raw(IOBuf&& frame);  // wire bytes as-is (TLS records)
  // wait-free enqueue only (no drain): true = the caller became the
  // drainer and MUST follow up with wdrive()/flush_chain(). The ordered
  // protocol lanes push under their session locks (order on the wire ==
  // emission order) and drive the drain after unlocking.
  bool write_push(IOBuf&& frame);
  // head == nullptr: nothing queued, nobody draining — the "everything
  // flushed" predicate of the graceful-close paths.
  bool write_idle() const { return wstack.empty(); }
  // Graceful close, race-free against the drain role's release: store
  // the flag, seq_cst fence, THEN check idleness — pairs with the
  // role-release side (fence between grab_more's head CAS and its
  // close_after_drain load), so one side always sees the other (the
  // Dekker pairing write_mu used to provide). Idempotent.
  void arm_close_after_drain();
  // role-holder entries (see nat_socket.cpp)
  void wdrive();            // dispatch: ring submit / inline writev
  bool flush_chain();       // epoll lane; false = EAGAIN (role retained)
  void wring_continue();    // ring lane submission step
  void write_release_all(); // failed socket: free chain + release role
  void wgather();           // fold linked nodes into wbuf (keep terminator)
  bool wrefill();           // true = role released (stack empty)
  void set_failed();
  void arm_epollout();
  void disarm_epollout();
};

// Socket registry — ResourcePool discipline (butil/resource_pool.h +
// socket_inl.h): NatSocket objects are slab-allocated and NEVER freed, so
// a slot index is a permanently-valid pointer; liveness is governed solely
// by the (version, refcount) atomic inside the socket. Lookups take no
// lock; the alloc mutex only guards slab growth and the index freelist.
inline constexpr uint32_t kSockSlabBits = 10;
inline constexpr uint32_t kSockSlabSize = 1u << kSockSlabBits;  // 1024
inline constexpr uint32_t kSockSlabs = 1024;                    // 1M max

// slab entries are atomic: sock_create publishes a new socket with a
// release store that a concurrent sock_at (server-stop scan) acquires —
// no reader can observe a half-constructed NatSocket (ADVICE r3 #1)
extern std::atomic<std::atomic<NatSocket*>*> g_sock_slab[kSockSlabs];
extern NatMutex<kLockRankSockAlloc> g_sock_alloc_mu;
extern std::vector<uint32_t>& g_sock_free;  // leaked: see nat_socket.cpp
extern uint32_t g_sock_next_idx;

inline NatSocket* sock_at(uint32_t idx) {
  std::atomic<NatSocket*>* slab =
      g_sock_slab[idx >> kSockSlabBits].load(std::memory_order_acquire);
  if (slab == nullptr) return nullptr;
  return slab[idx & (kSockSlabSize - 1)].load(std::memory_order_acquire);
}

NatSocket* sock_create();
NatSocket* sock_address(uint64_t id);
// Pin `s` regardless of its id version (the /connections walker: any
// live refcount qualifies, even mid-teardown) — the second borrow
// primitive beside sock_address; nullptr when the slot holds no
// reference. The returned pin is a sock.borrow like sock_address's.
NatSocket* sock_try_pin(NatSocket* s);
void sock_unregister(NatSocket* s);

// /connections peer column: "ip:port" formatted once at socket setup.
inline void sock_set_peer(NatSocket* s, const char* ip, int port) {
  snprintf(s->peer, sizeof(s->peer), "%s:%d", ip, port);
}
// getpeername variant for accepted fds (defined in nat_socket.cpp).
void sock_set_peer_fd(NatSocket* s);

// ring datapath seams (defined in nat_socket.cpp). One RingListener per
// dispatcher loop (the event_dispatcher_num x io_uring product of the
// fork): loops never share an SQ, so submissions from different cores
// never contend on one sq_mu_. g_rings is leaked for the usual exit
// reasons; entries are created once under g_rt_mu and never removed.
extern std::vector<RingListener*>& g_rings;
extern std::atomic<bool> g_rings_ready;  // build complete; gates readers
extern std::atomic<bool> g_use_ring;
bool ring_drain();                         // drain every ring (idle hook)
bool ring_drain_one(RingListener* ring);   // poller inline drain
bool try_ring_adopt(NatSocket* s);
void keep_write_fiber(void* arg);

// ---------------------------------------------------------------------------
// Dispatcher — one epoll loop feeding the fiber scheduler
// ---------------------------------------------------------------------------

class Dispatcher {
 public:
  int epfd = -1;
  int wake_fd = -1;  // eventfd to break epoll_wait on stop
  int idx = 0;       // position in g_disps (the /connections disp column)
  std::thread thread;
  std::atomic<bool> stop{false};
  // listen sockets: fd -> server
  NatMutex<kLockRankListen> listen_mu;
  std::unordered_map<int, NatServer*> listeners;
  // Listener fds whose CLOSE is deferred to the loop thread: the loop
  // may be inside accept_loop(fd) when a stop/quiesce tears the
  // listener down — closing from the caller thread lets the fd number
  // be recycled under a concurrently-running accept (the acceptor
  // teardown race). remove_listener unregisters + parks the fd here;
  // run() closes parked fds at the top of its next round, when no
  // accept_loop on this loop can still reference them.
  NatMutex<kLockRankDispClose> pend_close_mu;
  std::vector<int> pend_close_fds;
  // per-loop io_uring instance (nullptr = epoll only); owned by g_rings
  RingListener* ring = nullptr;
  // observability (/vars nat_dispatcher_* rows): connections this loop
  // owns right now, and epoll_wait rounds that delivered events
  std::atomic<int64_t> sockets_owned{0};
  std::atomic<uint64_t> wakeups{0};

  int start();
  void shutdown();

  // Register a connection socket for edge-triggered reads. The socket id
  // (not the pointer) rides in epoll data so stale events can't touch a
  // recycled socket.
  void add_consumer(NatSocket* s);
  void add_listener(int fd, NatServer* srv);
  // Unregister the listener and defer the fd close to the loop thread
  // (see pend_close_fds). Safe from any thread; idempotent per fd.
  void remove_listener(int fd);

  void run();
  void accept_loop(int listen_fd, NatServer* srv);
};

// Dispatcher pool (-event_dispatcher_num analog, event_dispatcher.cpp:30)
extern std::vector<Dispatcher*>& g_disps;  // leaked: see nat_server.cpp
extern Dispatcher* g_disp;  // g_disps[0]: listeners + console
extern NatServer* g_rpc_server;
extern NatMutex<kLockRankRuntime> g_rt_mu;

// Shard a new socket across the loop pool. With >= 2 loops, accepted
// (server) and dialed (client) sockets round-robin over DISJOINT halves
// of the pool so an in-process loopback bench never multiplexes both
// runtimes' hot sockets through one loop (the cross-runtime
// interference the single-core bench lanes used to include).
Dispatcher* pick_dispatcher(bool client_side = false);
int ensure_runtime(int nworkers);
// Unregister every nat_rpc_server_add_port listener (stop + quiesce
// teardown). Caller holds g_rt_mu. Defined in nat_server.cpp.
void server_remove_extra_ports_locked(NatServer* srv);

// ---------------------------------------------------------------------------
// NatServer
// ---------------------------------------------------------------------------

// Native handler: fills response payload/attachment (zero-copy IOBuf) or an
// error. Runs inline in the reader fiber — must not block.
struct NativeHandlerCtx {
  IOBuf* req_payload = nullptr;
  IOBuf* req_attachment = nullptr;
  IOBuf resp_payload;
  IOBuf resp_attachment;
  int32_t error_code = 0;
  std::string error_text;
};
using NativeHandler = std::function<void(NativeHandlerCtx&)>;

// Native HTTP handler (the builtin-service-in-C++ discipline of
// server.cpp:468-563): runs inline in the reading thread — must not block.
struct HttpHandlerCtxN {
  std::string_view verb;
  std::string_view path;
  std::string_view body;
  int status = 200;
  const char* content_type = "text/plain";
  IOBuf resp_body;
};
using HttpHandlerN = std::function<void(HttpHandlerCtxN&)>;

// A request handed to the Python lane (usercode_backup_pool discipline:
// Python user code runs on pthreads, not fiber stacks).
// kind: 0 = parsed tpu_std request; 1 = raw bytes for the Python protocol
// stack (cid = per-socket sequence number for in-order reassembly across
// the pthread pool); 2 = connection closed (session cleanup); 3 = parsed
// HTTP/1.1 request (service = method verb, method = path, meta_bytes =
// "k:v\n" header lines, cid = native http session token); 4 = parsed
// gRPC-over-h2 request (method = ":path", payload = de-framed message,
// meta_bytes = header lines, cid = h2 stream id); 5 = streaming frame
// (aux = dest stream id, compress_type = frame type DATA/FEEDBACK/CLOSE,
// cid = per-socket sequence for ordered delivery, payload = frame body);
// 8 = bulk tensor record (shm descriptor lane, aux = caller tag; the
// connection-less sock_id/cid fields carry the pusher's ambient trace
// context: sock_id = trace_id, cid = parent span id).
struct PyRequest;

// shm descriptor lane (nat_shm_lane.cpp): release the blob-arena span an
// arena-backed PyRequest's field views point into (no-op otherwise).
void shm_req_span_release(PyRequest* r);

// ---------------------------------------------------------------------------
// overload protection (nat_overload.cpp): native server admission control
// — constant + gradient ("auto") limiters ported from
// brpc_tpu/rpc/concurrency_limiter.py, real ELIMIT wire responses, and a
// queue-deadline drop (expired requests rejected before dispatch).
// ---------------------------------------------------------------------------

// Nonzero while a limiter OR a queue deadline is configured: the
// enqueue-side gate is one relaxed load when everything is off.
extern std::atomic<uint32_t> g_overload_on;

// Admission gate for one work request (kinds 0/3/4/6): stamps
// enqueue_ns, and when the limiter votes to reject, emits the per-lane
// ELIMIT wire response, frees `r` and returns false. On admit, marks
// r->admitted (the accounting token released by admission_on_complete).
bool overload_admit(PyRequest* r);
// True when a configured queue deadline has expired for `r`.
bool overload_expired(const PyRequest* r, uint64_t now_ns);
// Reject an expired queued request: ELIMIT response, accounting, free.
// Must be called with NO server/session locks held (it writes responses).
void overload_expire(PyRequest* r);
// One admitted request left the system; `latency_ns` feeds the gradient
// limiter when ok. Callers: ~PyRequest (in-process lane), the shm
// in-flight table's erase sites, overload_expire.
void admission_on_complete(uint64_t latency_ns, bool ok);
// Server (re)start hygiene: zero the in-flight count.
void overload_server_reset();

// ---------------------------------------------------------------------------
// graceful quiesce (nat_quiesce.cpp): the Server::Stop(timeout)/Join
// lifecycle for the native runtime — stop accepting, lame-duck every
// live connection per protocol, drain admitted work under a deadline,
// reject new arrivals with the PR-5 ELIMIT/503/RESOURCE_EXHAUSTED wire
// shapes (never a reset), then close sockets once their wstack is idle.
// ---------------------------------------------------------------------------

// Nonzero from quiesce start until the server is stopped/restarted: the
// enqueue gate rejects new WORK arrivals while set (one relaxed load on
// the hot path, colocated with the overload gate).
extern std::atomic<uint32_t> g_draining;
// Live kind-0 (tpu_std py-lane) work requests: created at enqueue,
// retired by ~PyRequest — the tpu_std half of the drain predicate (the
// HTTP/h2/RESP halves live in their session reorder windows).
extern std::atomic<int64_t> g_tpu_work_live;
// Reject one work request during the drain window: per-lane ELIMIT /
// 503 / RESOURCE_EXHAUSTED wire response ("server draining"); tpu_std
// rejections carry the SHUTDOWN meta bit so a client that missed the
// lame-duck frame still learns to redial. Frees `r`. Defined in
// nat_overload.cpp (shares the detached reject fiber).
void drain_reject(PyRequest* r);

// A py-lane request that represents admitted WORK (an RPC a client is
// waiting on) as opposed to lifecycle chatter: the one predicate the
// overload admitter, the drain enqueue gate, and the drain-deadline
// straggler sweep all share — a new work kind added here gates/503s
// everywhere at once.
inline bool is_work_kind(int32_t kind) {
  return kind == 0 || kind == 3 || kind == 4 || kind == 6;
}

// per-protocol lame-duck + drain-quiet hooks (each defined in its TU)
void h2_send_goaway(NatSocket* s);        // GOAWAY(last client sid seen)
bool h2_session_busy(NatSocket* s);       // streams/pending not yet quiet
void http_session_lame_duck(NatSocket* s);// next response: Connection: close
bool http_session_busy(NatSocket* s);     // responses still owed/parked
void redis_session_lame_duck(NatSocket* s);// close once window drains
bool redis_session_busy(NatSocket* s);    // replies still owed/parked
// meta-only tpu_std control frame carrying the SHUTDOWN bit
// (correlation_id 0) — the lame-duck signal on tpu_std connections.
void build_shutdown_frame(IOBuf* out);
// ELIMIT-class rejection that ALSO carries the SHUTDOWN bit (drain
// window: reject + "redial elsewhere" in one frame).
void build_reject_draining_frame(IOBuf* out, int64_t cid,
                                 int32_t error_code, const char* text);
// shm worker lane: no request is riding the rings right now
bool shm_lane_inflight_empty();
// client half: a peer signaled lame duck on `s` — detach it from the
// channel (in-flight completes here, new calls re-dial/re-balance) with
// no breaker penalty and no retry-budget burn. Defined nat_channel.cpp.
void channel_note_lame_duck(NatChannel* ch, NatSocket* s);
void channel_detach_socket(NatChannel* ch, NatSocket* s);

struct PyRequest {
  int32_t kind = 0;
  uint64_t sock_id = 0;
  int64_t cid = 0;
  int32_t compress_type = 0;
  uint64_t aux = 0;
  std::string service;
  std::string method;
  std::string payload;
  std::string attachment;
  std::string meta_bytes;  // full RpcMeta wire bytes: Python re-parses for
                           // log/trace ids, auth_data, timeout, tensors…
  // Large stream payloads (fill mode) live in a malloc'd buffer instead
  // of `payload`: malloc'd pages are lazily mapped, so no zero-fill pass
  // precedes the reads that populate them. nat_req_field(2) serves it.
  // The buffer GROWS with received bytes (big_cap doubles toward
  // big_len) so a 17-byte header claiming a huge body cannot reserve
  // the whole allocation up front (claim-without-send exhaustion).
  char* big_payload = nullptr;
  size_t big_len = 0;  // final payload size (frame-declared)
  size_t big_cap = 0;  // currently allocated
  // shm descriptor-ring backing (nat_shm_lane.cpp): slot >= 0 marks an
  // arena-resident record — the field views below point INTO the mapped
  // blob arena (read in place, no per-record copy) and stay valid until
  // this request is freed, which releases the span back to the arena.
  int32_t shm_slot = -1;
  uint64_t shm_span = 0;   // span-start offset (monotone) for the release
  // span-lease bookkeeping (tensor fabric, ISSUE 15): shm_span_bytes is
  // the leased payload size (the shm.span nat_res ledger row — payload
  // bytes are accounted ONCE per transfer, the structural zero-copy
  // witness); shm_lease marks a receiver-side fabric lease whose release
  // must be epoch-guarded (the producer slot may have been recovered
  // from under it) and decrement the slot's outstanding-lease count.
  uint32_t shm_span_bytes = 0;
  uint32_t shm_epoch = 0;
  bool shm_lease = false;
  const char* shm_view[5] = {nullptr, nullptr, nullptr, nullptr, nullptr};
  size_t shm_view_len[5] = {0, 0, 0, 0, 0};
  // trace context parsed off the wire (RpcMeta trace fields /
  // x-bd-trace-* headers / gRPC metadata): trace_id = the caller's
  // trace, parent_span_id = the caller's span — consumed by the shm
  // lane's server-span records (shm_lane_offer / emit_response)
  uint64_t trace_id = 0;
  uint64_t parent_span_id = 0;
  // overload accounting (nat_overload.cpp): enqueue_ns stamped when a
  // limiter/deadline is configured; admitted = this request holds one
  // in-flight slot, released exactly once (dtor, or transferred to the
  // shm in-flight table when the request rides the worker rings).
  // admit_ok mirrors AutoLimiter.on_response's error filter: responders
  // that complete a request with an error clear it so the failure-storm
  // latency profile never inflates the gradient limiter's window.
  uint64_t enqueue_ns = 0;
  bool admitted = false;
  bool admit_ok = true;
  // quiesce drain accounting: this kind-0 request is counted in
  // g_tpu_work_live until freed (responders free at respond-time, so
  // liveness == "response not yet queued")
  bool drain_counted = false;
  // resource ledger (nat_res.h): PyRequests are allocated at five lanes'
  // cut loops and freed at four release sites — self-accounting in the
  // ctor/dtor covers every one of them with a single seam (allocation
  // sites carry natcheck:allow(resacct) notes pointing here). The
  // big_payload fill buffer accounts its grows in stream_fill_reserve.
  PyRequest() { NAT_RES_ALLOC(NR_SRV_PYREQ, sizeof(PyRequest), this); }
  ~PyRequest() {
    if (big_cap > 0) NAT_RES_FREE(NR_SRV_PYREQ, big_cap, big_payload);
    ::free(big_payload);
    if (shm_slot >= 0) shm_req_span_release(this);
    if (admitted) {
      NAT_REF_RELEASED(nat_ref_adm_anchor(), adm.pyreq);
      admission_on_complete(
          enqueue_ns != 0 ? nat_now_ns() - enqueue_ns : 0, admit_ok);
    }
    if (drain_counted) {
      g_tpu_work_live.fetch_sub(1, std::memory_order_acq_rel);
    }
    NAT_RES_FREE(NR_SRV_PYREQ, sizeof(PyRequest), this);
  }
};

// shm usercode lane (nat_shm_lane.cpp): true = request consumed by the
// worker-process rings (kinds 3/4 only, when enabled).
bool shm_lane_offer(PyRequest* r);

class NatServer {
 public:
  int listen_fd = -1;
  int port = 0;
  Dispatcher* disp = nullptr;
  std::atomic<uint64_t> requests{0};
  std::atomic<uint64_t> connections{0};
  // Lifetime (replaces the round-2 graveyard): the global registration
  // holds one reference, every accepted socket one, every py-lane taker
  // one while inside take_py — a stopped server is deleted when the last
  // connection/taker lets go, and stop->start cycles no longer leak
  // (server.h:426-441 Stop/Join-then-Start-again semantics).
  std::atomic<int> ref{1};

  void add_ref() { ref.fetch_add(1, std::memory_order_relaxed); }
  void release() {
    if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      NAT_REF_DEAD(this);  // refguard: every tag balanced before delete
      delete this;
    }
  }

  ~NatServer();  // drains py_q: late kind-2 notices enqueue after stop

  // frozen at start; std::less<> enables allocation-free string_view find
  std::map<std::string, NativeHandler, std::less<>> handlers;
  // native HTTP handlers keyed by exact path (checked before the py lane)
  std::map<std::string, HttpHandlerN, std::less<>> http_handlers;
  // flat view of `handlers` built at start: with a handful of handlers a
  // length-check + memcmp scan beats the per-request red-black-tree walk
  // the r04 profile surfaced
  std::vector<std::pair<std::string, const NativeHandler*>> handler_vec;

  void freeze_handlers() {
    handler_vec.clear();
    for (const auto& kv : handlers) {
      handler_vec.emplace_back(kv.first, &kv.second);
    }
  }

  const NativeHandler* find_handler(std::string_view key) const {
    for (const auto& kv : handler_vec) {
      if (kv.first.size() == key.size() &&
          memcmp(kv.first.data(), key.data(), key.size()) == 0) {
        return kv.second;
      }
    }
    return nullptr;
  }
  // Extra listening ports (nat_rpc_server_add_port — the swarm-backend
  // seam): port -> (listen fd, owning dispatcher). Guarded by g_rt_mu
  // like the primary listener registration; torn down with the server.
  std::map<int, std::pair<int, Dispatcher*>> extra_ports;

  bool py_lane_enabled = false;
  // Route unrecognized framing to the Python protocol stack instead of
  // failing the socket (set when a Python server with a full protocol
  // registry is mounted on this port).
  bool raw_fallback = false;
  // Parse HTTP/1.1 and h2/gRPC natively (kind 3/4 py-lane requests)
  // instead of shovelling raw bytes; set with nat_rpc_server_native_http.
  bool native_http = false;
  // Parse RESP natively (policy/redis_protocol.cpp role): 0 = off,
  // 1 = py-lane dispatch (kind 6), 2 = + native in-memory store for the
  // GET/SET command family (unknown commands still go to py handlers).
  int native_redis = 0;
  RedisStoreN* redis_store = nullptr;  // owned; freed in ~NatServer
  // TLS context (opaque SSL_CTX*, nat_ssl.cpp) — when set, connections
  // whose first record sniffs as a TLS handshake get a native SSL
  // session; plaintext peers keep working on the same port.
  void* ssl_ctx = nullptr;

  // Python lane MPSC queue (py_cv waits under py_mu: stays std::mutex)
  std::mutex py_mu;  // natcheck:rank(server.py, 57)
  std::condition_variable py_cv;
  std::deque<PyRequest*> py_q;
  bool py_stopping = false;

  void enqueue_py(PyRequest* r) {
    // graceful drain (nat_quiesce.cpp): after the lame-duck pass, new
    // WORK arrivals are rejected with the overload wire shapes instead
    // of dying with the socket — one relaxed load when not draining
    if (g_draining.load(std::memory_order_relaxed) != 0 &&
        is_work_kind(r->kind)) {
      drain_reject(r);
      return;
    }
    // admission control (nat_overload.cpp): one relaxed load when off;
    // a rejected request already answered ELIMIT on the wire and is gone
    if (g_overload_on.load(std::memory_order_relaxed) != 0 &&
        !overload_admit(r)) {
      return;
    }
    // drain predicate bookkeeping for the tpu_std py lane: these live
    // until the responder frees them, so a live count IS "responses
    // still owed" (the other lanes count via their reorder windows)
    if (r->kind == 0) {
      r->drain_counted = true;
      g_tpu_work_live.fetch_add(1, std::memory_order_acq_rel);
    }
    // counted AFTER the gate: kind 2 is a connection-drop control
    // message and admission-rejected requests never enter the lane —
    // neither inflates nat_py_dispatches. (Queue-deadline drops DO
    // count: they entered the lane and expired inside it; the drop
    // shows up in nat_queue_deadline_drops.)
    if (r->kind != 2) nat_counter_add(NS_PY_DISPATCHES, 1);
    // worker-process lane first (kinds 3/4 when enabled): usercode runs
    // across N interpreters instead of behind this process's GIL
    if ((r->kind == 3 || r->kind == 4) && shm_lane_offer(r)) return;
    {
      std::lock_guard g(py_mu);
      py_q.push_back(r);
    }
    py_cv.notify_one();
  }

  PyRequest* take_py(int timeout_ms) {
    // queue-deadline drop: requests that sat longer than the configured
    // budget are rejected HERE, before a Python worker spends usercode
    // time on them — the ELIMIT emits happen after py_mu is released
    // (the responders take session locks that rank below it).
    PyRequest* r = nullptr;
    PyRequest* expired[8];
    int nexp = 0;
    {
      std::unique_lock lk(py_mu);
      if (py_q.empty() && !py_stopping) {
        nat_cv_wait_for(py_cv, lk, std::chrono::milliseconds(timeout_ms));
      }
      uint64_t now = g_overload_on.load(std::memory_order_relaxed) != 0
                         ? nat_now_ns()
                         : 0;
      while (!py_q.empty()) {
        PyRequest* f = py_q.front();
        if (now == 0 || !overload_expired(f, now)) {
          py_q.pop_front();
          r = f;
          break;
        }
        // expired: never hand it to usercode — when this call's drop
        // budget is spent, leave the rest queued for the next take
        if (nexp >= 8) break;
        py_q.pop_front();
        expired[nexp++] = f;
      }
    }
    for (int i = 0; i < nexp; i++) overload_expire(expired[i]);
    return r;
  }

  // Batch take: one condvar round + one FFI crossing covers a whole
  // burst (the py lane's per-item wakeup was measurable at qps scale).
  int take_py_batch(PyRequest** out, int max, int timeout_ms) {
    PyRequest* expired[16];
    int nexp = 0;
    int n = 0;
    {
      std::unique_lock lk(py_mu);
      if (py_q.empty() && !py_stopping) {
        nat_cv_wait_for(py_cv, lk, std::chrono::milliseconds(timeout_ms));
      }
      uint64_t now = g_overload_on.load(std::memory_order_relaxed) != 0
                         ? nat_now_ns()
                         : 0;
      while (n < max && !py_q.empty()) {
        PyRequest* f = py_q.front();
        if (now != 0 && overload_expired(f, now)) {
          // expired work never reaches usercode; once this call's drop
          // budget is spent, stop (the rest drains on the next take)
          if (nexp >= 16) break;
          py_q.pop_front();
          expired[nexp++] = f;
          continue;
        }
        py_q.pop_front();
        out[n++] = f;
      }
    }
    for (int i = 0; i < nexp; i++) overload_expire(expired[i]);
    return n;
  }
};

// ---------------------------------------------------------------------------
// NatChannel (client half)
// ---------------------------------------------------------------------------

struct PendingCall {
  Butex done;  // 0 = in flight, 1 = complete
  int32_t error_code = 0;
  // protocol-level status riding beside the RPC error: HTTP status code
  // on the native HTTP client lane, grpc-status on the h2 client lane
  int32_t aux_status = 0;
  std::string error_text;
  IOBuf response;
  IOBuf attachment;
  // Small responses land here instead of the IOBuf: the typical RPC
  // reply is tens of bytes, and an inline copy skips the block
  // add_ref/release pair plus the ref bookkeeping entirely (the same
  // trade the short-buffer flat-copy makes in iobuf.cpp).
  uint8_t inline_len = 0;
  char inline_resp[56];

  const char* resp_data() const {
    return inline_len > 0 ? inline_resp : nullptr;
  }
  // Asynchronous completion (brpc's done-closure, controller.h): when
  // set, the response path invokes cb (which owns pc) instead of waking
  // a parked caller — the async RPC surface sync calls are built on.
  void (*cb)(PendingCall*, void*) = nullptr;
  void* cb_arg = nullptr;
  // Slot machinery (the versioned CallId discipline of bthread/id.h:38-60
  // + controller.h:655-664): calls live in never-freed slabs owned by
  // the channel; the correlation id packs (version, slot index), and a
  // single atomic word (version<<1 | pending) arbitrates completion —
  // whoever CASes the pending bit off owns the call. No lock, no map,
  // no allocation on the per-call path, and a late/duplicate response
  // (stale version) can never touch a recycled call.
  NatChannel* owner = nullptr;
  uint32_t slot_idx = 0;
  uint32_t next_free = 0;  // freelist link, encoded idx+1
  std::atomic<uint64_t> state{0};  // (version << 1) | pending_bit
  // call-begin timestamp (nat_stats client-lane latency: the round trip
  // lands in NL_CLIENT when the completion wins take_pending)
  uint64_t start_ns = 0;
  // client-span state (rpcz): copied from the caller's NatCallTrace by
  // begin_call BEFORE the pending bit publishes (after publish a racing
  // fail_all may complete + recycle this slot, so nothing may touch
  // these fields post-publish); the protocol lanes stamp the SAME
  // NatCallTrace's ids into the wire metadata, and the ok-completion in
  // take_pending submits the span.
  uint64_t trace_id = 0;        // 0 = no trace propagation for this call
  uint64_t span_id = 0;         // THIS call's span (the callee's parent)
  uint64_t parent_span_id = 0;  // the ambient span this call nests under
  bool span_sampled = false;
  uint8_t span_method_len = 0;
  char span_method[40];
};

// Per-call trace decision, taken ONCE on the caller's stack before
// begin_call: sampling stride + this thread's ambient context
// (tls_nat_trace) + the span label the lane knows. The lanes read wire
// ids from THIS struct (never from the PendingCall after publish).
struct NatCallTrace {
  uint64_t trace_id = 0;
  uint64_t span_id = 0;
  uint64_t parent_span_id = 0;
  bool sampled = false;
  uint8_t label_len = 0;
  char label[40];

  // "a<sep>b" span label (only when sampled: the snprintf is off the
  // untraced hot path)
  void set_label(const char* a, const char* sep, const char* b) {
    if (!sampled) return;
    int n = snprintf(label, sizeof(label), "%s%s%s", a, sep, b);
    label_len = (uint8_t)(n <= 0 ? 0
                          : (n < (int)sizeof(label) ? n
                                                    : (int)sizeof(label) - 1));
  }
};

inline NatCallTrace nat_begin_call_trace() {
  NatCallTrace tr;
  tr.sampled = nat_span_tick();
  const NatTraceCtx& tc = tls_nat_trace;
  if (tr.sampled || tc.trace_id != 0) {
    tr.trace_id = tc.trace_id != 0 ? tc.trace_id : nat_span_id63();
    tr.span_id = nat_span_id63();
    tr.parent_span_id = tc.span_id;
  }
  return tr;
}

void pc_free(PendingCall* pc);  // returns the slot to its channel

class NatChannel {
 public:
  static const uint32_t kIdxBits = 20;  // 1M concurrent calls per channel
  static const uint32_t kIdxMask = (1u << kIdxBits) - 1;
  static const uint32_t kSlabBits = 8;  // 256 calls per slab
  static const uint32_t kSlabSize = 1u << kSlabBits;
  static const uint32_t kMaxSlabs = 1u << (kIdxBits - kSlabBits);

  std::atomic<uint64_t> sock_id{0};
  // Wire protocol this channel speaks: 0 = tpu_std, 1 = HTTP/1.1,
  // 2 = h2/gRPC (the reference's per-channel protocol option,
  // channel.h ChannelOptions.protocol).
  int protocol = 0;
  std::string authority;  // Host / :authority for the HTTP/h2 lanes
  // Reconnect state (single-connection Channel semantics: the reference
  // re-establishes a failed single connection on use, and the health
  // checker revives it in the background — health_check.cpp:146-237).
  std::string peer_ip;
  int peer_port = 0;
  int connect_timeout_ms = 0;     // 0 = default guard
  int health_check_interval_ms = 0;  // 0 = no background revival
  bool defer_writes_flag = false;
  std::atomic<bool> closed{false};
  std::atomic<bool> hc_pending{false};
  // Lame-duck bookkeeping (graceful server churn): CLOCK_MONOTONIC ms of
  // the last lame-duck signal from the peer. While recent, drain-window
  // ELIMIT rejections are retried WITHOUT spending the retry budget and
  // the planned socket death feeds no breaker sample — planned churn is
  // routine, not failure.
  std::atomic<int64_t> lame_duck_ms{0};

  bool draining_recent() const {
    int64_t t = lame_duck_ms.load(std::memory_order_relaxed);
    return t != 0 &&
           (int64_t)(nat_now_ns() / 1000000ull) - t < 10000;
  }
  // Health-check re-dial backoff: the CURRENT chain's exponent (reset to
  // 0 when a chain starts and on revival, so the first retry stays fast;
  // only the single hc fiber advances it — atomic for the cross-thread
  // reset from set_failed).
  std::atomic<int> hc_backoff_shift{0};
  // Retry budget (brpc retry-dispersal discipline in token form): deci-
  // tokens; a retry spends 10, every success replenishes 1 up to the
  // cap, so an injected failure burst can spend at most budget/10
  // retries before new retries need fresh successes to pay for them.
  static const int kRetryBudgetCap = 100;
  std::atomic<int> retry_budget_decis{100};
  // Circuit breaker (two-EMA-window port of rpc/circuit_breaker.py):
  // default off; enabled via nat_channel_set_breaker. While broken and
  // inside the isolation window, channel_socket fails fast (no dial);
  // the health-check chain re-dials after expiry and resets the breaker.
  std::atomic<bool> breaker_enabled{false};
  std::atomic<bool> breaker_broken{false};
  std::atomic<int64_t> breaker_until_ms{0};  // CLOCK_MONOTONIC ms
  NatMutex<kLockRankBreaker> breaker_mu;
  double brk_short_ema = 0.0;          // under breaker_mu
  double brk_long_ema = 0.0;           // under breaker_mu
  int brk_isolation_ms = 0;            // under breaker_mu
  int64_t brk_last_isolation_ms = 0;   // under breaker_mu
  NatMutex<kLockRankReconnect> reconnect_mu;
  // Lifetime: the owning socket holds one reference (released in
  // ~NatSocket) and the opener holds one (released in nat_channel_close),
  // so a reader fiber mid-process_input can never see a freed channel.
  std::atomic<int> ref{1};

  void add_ref() { ref.fetch_add(1, std::memory_order_relaxed); }
  void release() {
    if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) {
      NAT_REF_DEAD(this);  // refguard: every tag balanced before delete
      delete this;
    }
  }

  // resource ledger: channels are allocated by channel_open (client
  // lane) and channel_create_lazy (cluster backends) and freed by the
  // refcount chain — ctor/dtor self-accounting covers every site (the
  // raw news carry natcheck:allow(resacct) notes pointing here).
  NatChannel() { NAT_RES_ALLOC(NR_CLUSTER, sizeof(NatChannel), this); }
  ~NatChannel() {
    for (uint32_t i = 0; i < kMaxSlabs; i++) {
      PendingCall* slab = slabs_[i].load(std::memory_order_acquire);
      if (slab != nullptr) {
        NAT_RES_FREE(NR_CLUSTER, kSlabSize * sizeof(PendingCall), slab);
        delete[] slab;
      }
    }
    NAT_RES_FREE(NR_CLUSTER, sizeof(NatChannel), this);
  }

  PendingCall* slot_at(uint32_t idx) {
    return &slabs_[idx >> kSlabBits].load(std::memory_order_acquire)
                [idx & (kSlabSize - 1)];
  }

  PendingCall* begin_call(int64_t* cid_out,
                          void (*cb)(PendingCall*, void*) = nullptr,
                          void* cb_arg = nullptr,
                          const NatCallTrace* tr = nullptr) {
    uint32_t idx = pop_free();
    if (idx == UINT32_MAX) return nullptr;  // slot space exhausted
    PendingCall* pc = slot_at(idx);
    uint64_t version =
        (pc->state.load(std::memory_order_relaxed) >> 1) + 1;
    pc->done.value.store(0, std::memory_order_relaxed);
    pc->error_code = 0;
    pc->aux_status = 0;
    pc->error_text.clear();
    pc->response.clear();
    pc->attachment.clear();
    pc->inline_len = 0;
    pc->cb = cb;
    pc->cb_arg = cb_arg;
    pc->owner = this;
    pc->slot_idx = idx;
    pc->start_ns = nat_now_ns();
    // client span + trace propagation, fully written BEFORE the pending
    // bit publishes (a racing fail_all may complete and recycle the
    // slot the instant the bit is visible). Callers that pass no trace
    // (bench harnesses) fall back to the stride decision with no label.
    if (tr != nullptr) {
      pc->span_sampled = tr->sampled;
      pc->trace_id = tr->trace_id;
      pc->span_id = tr->span_id;
      pc->parent_span_id = tr->parent_span_id;
      pc->span_method_len = tr->label_len;
      memcpy(pc->span_method, tr->label, tr->label_len);
    } else {
      pc->span_sampled = nat_span_tick();
      pc->span_method_len = 0;
      const NatTraceCtx& tc = tls_nat_trace;
      if (pc->span_sampled || tc.trace_id != 0) {
        pc->trace_id = tc.trace_id != 0 ? tc.trace_id : nat_span_id63();
        pc->span_id = nat_span_id63();
        pc->parent_span_id = tc.span_id;
      } else {
        pc->trace_id = 0;
        pc->span_id = 0;
        pc->parent_span_id = 0;
      }
    }
    nat_counter_add(NS_CLIENT_CALLS, 1);
    // everything above must be visible before the pending bit: a racing
    // fail_all completes through cb/butex the instant it sees the bit
    pc->state.store((version << 1) | 1, std::memory_order_release);
    *cid_out = (int64_t)((version << kIdxBits) | idx);
    return pc;
  }

  // Non-consuming peek: true while the call is still awaiting its first
  // completion (used by the backup-request timer to decide whether a
  // duplicate send is still useful).
  bool is_pending(int64_t cid) {
    uint32_t idx = (uint32_t)cid & kIdxMask;
    if (idx >= nslots_.load(std::memory_order_acquire)) return false;
    uint64_t expected = (((uint64_t)cid >> kIdxBits) << 1) | 1;
    return slot_at(idx)->state.load(std::memory_order_acquire) == expected;
  }

  // CAS the pending bit off; the winner owns the call. Stale cids (old
  // version) and double-completions lose the CAS and get nullptr.
  // `ok=false` marks an error completion (timeout, failed send, refused
  // stream): counted into nat_client_errors and kept OUT of the client
  // latency histogram — a 30s timeout is not a round trip. `planned`
  // marks a completion caused by the peer's GRACEFUL drain (GOAWAY-
  // refused stream, lame-duck retire): still an error to the caller,
  // but not a breaker sample — planned churn must not isolate a peer.
  PendingCall* take_pending(int64_t cid, bool ok = true,
                            bool planned = false) {
    uint32_t idx = (uint32_t)cid & kIdxMask;
    if (idx >= nslots_.load(std::memory_order_acquire)) return nullptr;
    PendingCall* pc = slot_at(idx);
    uint64_t expected = (((uint64_t)cid >> kIdxBits) << 1) | 1;
    if (pc->state.compare_exchange_strong(expected, expected & ~1ull,
                                          std::memory_order_acq_rel)) {
      if (ok) {
        nat_counter_add(NS_CLIENT_RESPONSES, 1);
        uint64_t now = nat_now_ns();
        if (pc->start_ns != 0) {
          nat_lat_record(NL_CLIENT, now - pc->start_ns);
        }
        if (pc->span_sampled) {
          // the caller still owns pc here (the CAS handed it to us), so
          // the span fields are stable; error/status details land after
          // take_pending, so the client span records the round trip only
          NatSpanRec rec;
          memset(&rec, 0, sizeof(rec));
          rec.trace_id = pc->trace_id;
          rec.span_id = pc->span_id;
          rec.parent_span_id = pc->parent_span_id;
          rec.recv_ns = pc->start_ns;
          rec.parse_ns = pc->start_ns;
          rec.dispatch_ns = now;
          rec.write_ns = now;
          rec.protocol = NL_CLIENT;
          size_t n = pc->span_method_len;
          memcpy(rec.method, pc->span_method, n);
          rec.method[n] = '\0';
          nat_span_submit(rec);
        }
        // breaker verdict + retry-budget replenish are fed by the
        // protocol layers (messenger / client-lane finishers), which
        // inspect the response's ACTUAL status — a transport-level
        // "ok" here may still be a server error frame / 5xx / grpc 8
      } else {
        nat_counter_add(NS_CLIENT_ERRORS, 1);
        if (!planned &&
            breaker_enabled.load(std::memory_order_relaxed)) {
          breaker_on_call_end(false);
        }
      }
      return pc;
    }
    return nullptr;
  }

  // Retry-budget replenish: +1 deci-token per success, capped. At the
  // cap (steady state) this is one relaxed load, no RMW.
  void note_call_success() {
    int v = retry_budget_decis.load(std::memory_order_relaxed);
    while (v < kRetryBudgetCap &&
           !retry_budget_decis.compare_exchange_weak(
               v, v + 1, std::memory_order_relaxed)) {
    }
  }

  // Circuit-breaker surface (nat_channel.cpp): feed one finished call;
  // a trip fails the socket and arms the health-check revival chain.
  void breaker_on_call_end(bool call_ok);
  void breaker_reset(bool revived);

  void fail_all(int32_t code, const char* text) {
    uint32_t n = nslots_.load(std::memory_order_acquire);
    for (uint32_t idx = 0; idx < n; idx++) {
      PendingCall* pc = slot_at(idx);
      uint64_t st = pc->state.load(std::memory_order_acquire);
      if (!(st & 1)) continue;
      if (!pc->state.compare_exchange_strong(st, st & ~1ull,
                                             std::memory_order_acq_rel)) {
        continue;  // a response beat us to it
      }
      nat_counter_add(NS_CLIENT_ERRORS, 1);
      // every swept call is an error sample for the breaker (brpc feeds
      // OnCallEnd from socket sweeps too); a trip from here re-enters
      // set_failed, which is idempotent via its failed.exchange
      if (breaker_enabled.load(std::memory_order_relaxed)) {
        breaker_on_call_end(false);
      }
      pc->error_code = code;
      pc->error_text = text;
      if (pc->cb != nullptr) {
        pc->cb(pc, pc->cb_arg);  // cb owns pc
        continue;
      }
      pc->done.value.store(1, std::memory_order_release);
      Scheduler::butex_wake(&pc->done, INT32_MAX);
    }
  }

  void release_slot(uint32_t idx) { push_free(idx); }

 private:
  std::atomic<PendingCall*> slabs_[kMaxSlabs] = {};
  std::atomic<uint32_t> nslots_{0};
  std::atomic<uint64_t> free_head_{0};  // (aba_tag<<32) | (idx+1)
  NatMutex<kLockRankChanGrow> grow_mu_;
  // Consumer-side cache: pop_free grabs the WHOLE free chain in one
  // exchange and walks it privately, so steady-state allocation costs no
  // CAS at all (completions still CAS-push). pop_cache_lock_ arbitrates
  // the rare case of concurrent begin_call callers — losers fall back to
  // the shared-head CAS pop.
  std::atomic<bool> pop_cache_lock_{false};
  uint32_t pop_cache_ = 0;  // encoded idx+1 chain head; under the lock

  uint32_t pop_free() {
    if (!pop_cache_lock_.exchange(true, std::memory_order_acquire)) {
      uint32_t idx = UINT32_MAX;
      if (pop_cache_ == 0) {
        // refill: take the entire shared chain in one exchange
        uint64_t head = free_head_.exchange(0, std::memory_order_acq_rel);
        pop_cache_ = (uint32_t)head;
      }
      if (pop_cache_ != 0) {
        idx = pop_cache_ - 1;
        pop_cache_ = slot_at(idx)->next_free;
      }
      pop_cache_lock_.store(false, std::memory_order_release);
      if (idx != UINT32_MAX) return idx;
      if (!grow()) return UINT32_MAX;
      return pop_free();
    }
    while (true) {
      uint64_t head = free_head_.load(std::memory_order_acquire);
      while ((uint32_t)head != 0) {
        uint32_t idx = (uint32_t)head - 1;
        uint32_t next = slot_at(idx)->next_free;
        uint64_t nhead = ((head >> 32) + 1) << 32 | next;
        if (free_head_.compare_exchange_weak(head, nhead,
                                             std::memory_order_acq_rel)) {
          return idx;
        }
      }
      if (!grow()) return UINT32_MAX;
    }
  }

  void push_free(uint32_t idx) {
    PendingCall* pc = slot_at(idx);
    uint64_t head = free_head_.load(std::memory_order_acquire);
    while (true) {
      pc->next_free = (uint32_t)head;
      uint64_t nhead = ((head >> 32) + 1) << 32 | (idx + 1);
      if (free_head_.compare_exchange_weak(head, nhead,
                                           std::memory_order_acq_rel)) {
        return;
      }
    }
  }

  bool grow() {
    std::lock_guard g(grow_mu_);
    uint32_t n = nslots_.load(std::memory_order_acquire);
    if ((uint32_t)free_head_.load(std::memory_order_acquire) != 0) {
      return true;  // another thread grew while we waited
    }
    uint32_t slab_i = n >> kSlabBits;
    if (slab_i >= kMaxSlabs) return false;
    PendingCall* slab = new PendingCall[kSlabSize];
    NAT_RES_ALLOC(NR_CLUSTER, kSlabSize * sizeof(PendingCall), slab);
    slabs_[slab_i].store(slab, std::memory_order_release);
    nslots_.store(n + kSlabSize, std::memory_order_release);
    // seed indices [n+1, n+kSlabSize) through the freelist; hand out n
    // implicitly by pushing it too
    for (uint32_t i = 0; i < kSlabSize; i++) push_free(n + i);
    return true;
  }
};

// channel internals shared across nat_channel.cpp / nat_client.cpp /
// nat_bench.cpp
int dial_nonblocking(const char* ip, int port, int timeout_ms);
NatSocket* channel_socket(NatChannel* ch, int max_dial_ms = 0);
void health_check_fire(void* raw);
void arm_call_timeout(NatChannel* ch, int64_t cid, int timeout_ms);

// ---------------------------------------------------------------------------
// Messenger seam (nat_messenger.cpp)
// ---------------------------------------------------------------------------

// Large stream payloads fill their request buffer directly from the
// socket/ring (in_buf bypass); frames at least this big use it.
inline constexpr size_t kStreamFillMin = 64u << 10;
size_t stream_fill_feed(NatSocket* s, const char* data, size_t n);

void build_response_frame(IOBuf* out, int64_t cid, int32_t error_code,
                          const std::string& error_text, IOBuf&& payload,
                          IOBuf&& attachment);
void build_request_frame(IOBuf* out, int64_t cid, const std::string& service,
                         const std::string& method, const char* payload,
                         size_t payload_len, const char* att, size_t att_len,
                         uint64_t trace_id = 0, uint64_t span_id = 0);
// zero-copy build: the attachment's refs splice into the frame (no
// payload memcpy; user blocks ride straight into writev)
void build_request_frame_iobuf(IOBuf* out, int64_t cid,
                               const std::string& service,
                               const std::string& method,
                               IOBuf&& attachment, uint64_t trace_id = 0,
                               uint64_t span_id = 0);
bool process_input(NatSocket* s, IOBuf* defer_out = nullptr);
bool drain_socket_inline(NatSocket* s);
// tpu_std bulk-frame fill mode (nat_messenger.cpp): frames with a body
// >= kBulkFillMin read their remaining payload straight into one pooled
// bulk slab (iob_bulk_acquire) consumed as a single IOBuf user block.
inline constexpr size_t kBulkFillMin = 128u << 10;
// Feed freshly-received bytes into the armed fill; returns the count
// consumed (the rest belongs to the next frame). Reading thread only.
size_t bulk_fill_feed(NatSocket* s, const char* data, size_t n);
// Teardown: release a half-filled slab back to the pool.
void bulk_fill_abort(NatSocket* s);

// Native HTTP/1.1 session (nat_http.cpp).
// try_process returns: 1 = session active (consumed what it could),
// 2 = sniff needs more bytes, 0 = not HTTP / protocol error.
int http_try_process(NatSocket* s, IOBuf* batch_out);
void http_round_end(NatSocket* s);
void http_session_free(HttpSessionN* h);
// Zero-copy variant of nat_http_respond: `data` is the complete serialized
// response, possibly carried by arena-backed user blocks (the shm drainer's
// large-payload path) — the reorder window parks the IOBuf itself and the
// socket writev consumes the refs without copying.
int http_respond_iobuf(uint64_t sock_id, int64_t seq, IOBuf&& data,
                       int close_after);
// Sniff a few leading bytes: 1 = HTTP verb, 2 = could become one (need
// more bytes), 0 = definitely not HTTP.
int http_sniff(const char* p, size_t n);
// Native h2/gRPC session (nat_h2.cpp); same conventions.
int h2_try_process(NatSocket* s, IOBuf* batch_out);
void h2_session_free(H2SessionN* h);
int h2_sniff(const char* p, size_t n);
// Static-table HPACK encode primitives (stateless; used by the h2
// response framer and the bench client).
void hp_enc_int(std::string* out, uint64_t v, int prefix, uint8_t first);
void hp_enc_str(std::string* out, std::string_view s);
void hp_enc_header(std::string* out, std::string_view name,
                   std::string_view value);

// Native Redis lane (nat_redis.cpp): RESP parse + ordered replies +
// native store / kind-6 py dispatch.
int redis_try_process(NatSocket* s, IOBuf* batch_out);
void redis_round_end(NatSocket* s);
void redis_session_free(RedisSessN* h);
void redis_store_free(RedisStoreN* st);
RedisStoreN* redis_store_new();
int redis_sniff(const char* p, size_t n);

// Native client protocol lanes (nat_client.cpp): HTTP/1.1 and h2/gRPC
// request framing + response parsing for channel-owned sockets.
// *_process conventions mirror the server lanes: 1 = consumed what it
// could, 0 = protocol error (socket dies).
int http_client_process(NatSocket* s);
int h2_client_process(NatSocket* s, IOBuf* batch_out);
// EOF hook for read-until-close response bodies (HTTP/1.0 / Connection:
// close with no framing): called by set_failed BEFORE fail_all so the
// FIFO-head call completes successfully with the accumulated body.
void http_cli_on_socket_fail(NatSocket* s);
void http_cli_free(HttpCliSessN* c);
void h2_cli_free(H2CliSessN* c);
// Fail ONLY the pending calls whose streams still ride this socket's h2
// client session (used when a GOAWAY-drained socket dies after the
// channel has already moved to a replacement — a channel-wide fail_all
// would spuriously kill calls in flight on the new socket).
void h2c_fail_own_streams(NatSocket* s, int32_t code, const char* text);
// HTTP twin for a detached (lame-duck drained) http client socket:
// complete the pipeline FIFO's remaining calls as planned errors.
void http_cli_fail_own(NatSocket* s, int32_t code, const char* text,
                       bool teardown = false);
// Teardown variant (try_lock sweep): for set_failed when the scheduler
// is stopped and no sweep fiber can run.
void h2c_fail_own_streams_teardown(NatSocket* s, int32_t code,
                                   const char* text);
// Attach the channel's protocol session to a (re)dialed socket; for h2
// this also queues the connection preface + SETTINGS.
void channel_attach_client_session(NatChannel* ch, NatSocket* s);

// h2 shared primitives (implemented in nat_h2.cpp, reused by the client
// lane): frame header emitter and an opaque stateful HPACK decoder.
void h2_frame_header(std::string* out, size_t len, uint8_t type,
                     uint8_t flags, uint32_t sid);
void* hpack_decoder_new();
bool hpack_decoder_decode(void* dec, const uint8_t* d, size_t n,
                          std::string* flat, std::string* path);
void hpack_decoder_free(void* dec);

// Native TLS session (nat_ssl.cpp).
bool ssl_accept_begin(NatSocket* s);
bool ssl_feed(NatSocket* s, const char* data, size_t n);
bool ssl_encrypt(NatSocket* s, IOBuf&& plain, IOBuf* cipher_out);
int ssl_encrypt_and_write(NatSocket* s, IOBuf&& plain);
void ssl_session_free(SslSessionN* s);

// The full extern "C" surface (response emitters the shm drainer reuses,
// channel open/call paths the bench harness shares, the nat_acall*_cb
// typedefs) lives in nat_api.h, included at the top of this header.

}  // namespace brpc_tpu
