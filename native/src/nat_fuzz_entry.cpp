// Fuzz seams: one extern "C" entry per hand-rolled wire parser, each
// driving the REAL production path — not a reimplementation — so a
// fuzzer (native/fuzz/, libFuzzer or the bundled deterministic driver)
// and the regress replay test (tests/test_fuzz_regress.py, via ctypes)
// exercise exactly the code the runtime runs against hostile bytes.
//
// The protocol seams (http/h2/redis) run the messenger-style cut over a
// fake-socket fill: a heap NatSocket whose fd is /dev/null (writev of
// any control response succeeds, so no EAGAIN keep-write fiber and no
// set_failed teardown) owned by a handler-less NatServer with the py
// lane disabled — every request parses through the full session
// machinery and is answered by the native 404 / UNIMPLEMENTED /
// unknown-command arms, all deferred into a local batch IOBuf. The
// session object is freed after every input so each exec is
// reproducible standalone (a crash input replays without history).
//
// Return value is 0/1 (input rejected/consumed) purely for corpus
// statistics; the interesting outcome is the sanitizer's.

#include <fcntl.h>
#include <unistd.h>

#include "nat_internal.h"

namespace brpc_tpu {
namespace {

// One scheduler for the process: some write paths spawn a detached
// fiber (batch mode, EAGAIN requeue) and must find a live scheduler
// even though the fuzz inputs should never reach them.
void fuzz_runtime_init() {
  static bool once = [] {
    nat_sched_start(1);
    return true;
  }();
  (void)once;
}

struct FuzzConn {
  NatServer* srv = nullptr;
  NatSocket* sock = nullptr;

  explicit FuzzConn(int redis_mode) {
    srv = new NatServer();
    NAT_REF_ACQUIRED(srv, srv.fuzz);  // refs{1} = this FuzzConn
    srv->py_lane_enabled = false;  // native error arms answer everything
    srv->native_http = true;
    srv->native_redis = redis_mode;
    if (redis_mode != 0) srv->redis_store = redis_store_new();
    srv->freeze_handlers();  // empty maps: every lookup misses
    sock = new NatSocket();
    NAT_REF_ACQUIRED(sock, sock.fuzz);  // refs{1} = this FuzzConn
    sock->fd = open("/dev/null", O_WRONLY);
    sock->server = srv;
  }

  void feed(const char* data, size_t len) {
    sock->in_buf.clear();
    if (len != 0) sock->in_buf.append(data, len);
  }

  void reset_sessions() {
    if (sock->http != nullptr) {
      http_session_free(sock->http);
      sock->http = nullptr;
    }
    if (sock->h2 != nullptr) {
      h2_session_free(sock->h2);
      sock->h2 = nullptr;
    }
    if (sock->redis != nullptr) {
      redis_session_free(sock->redis);
      sock->redis = nullptr;
    }
    sock->in_buf.clear();
  }

  ~FuzzConn() {
    reset_sessions();
    if (sock->fd >= 0) ::close(sock->fd);
    sock->fd = -1;
    sock->server = nullptr;
    // NatSocket::release never frees (ResourcePool slot discipline:
    // the slot returns to sock_create's freelist) — this heap socket
    // was never registered anywhere, so retire it directly
    NAT_REF_RELEASED(sock, sock.fuzz);
    delete sock;
    NAT_REF_RELEASE(srv, srv.fuzz);
  }
};

}  // namespace
}  // namespace brpc_tpu

using namespace brpc_tpu;

extern "C" {

// tpu_std RpcMeta varint decode (rpc_meta.h) straight over the input.
int nat_fuzz_rpc_meta(const char* data, size_t len) {
  RpcMetaN meta;
  return decode_meta(data, len, &meta) ? 1 : 0;
}

// HTTP/1 server parse: sniff + header scan + body framing + the native
// 404 respond arm, through http_try_process's real session.
int nat_fuzz_http(const char* data, size_t len) {
  fuzz_runtime_init();
  FuzzConn c(0);
  c.feed(data, len);
  IOBuf batch;
  int rc = http_try_process(c.sock, &batch);
  return rc != 0 ? 1 : 0;
}

// h2 frame cut + HPACK into the session's real dynamic table + gRPC
// de-frame + UNIMPLEMENTED respond arm. The client preface is
// prepended so arbitrary inputs reach the frame loop instead of dying
// in the sniff.
int nat_fuzz_h2(const char* data, size_t len) {
  fuzz_runtime_init();
  static const char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
  FuzzConn c(0);
  c.sock->in_buf.append(kPreface, sizeof(kPreface) - 1);
  if (len != 0) c.sock->in_buf.append(data, len);
  IOBuf batch;
  int rc = h2_try_process(c.sock, &batch);
  return rc != 0 ? 1 : 0;
}

// RESP command parse + the native store execute arm (no py lane).
int nat_fuzz_redis(const char* data, size_t len) {
  fuzz_runtime_init();
  FuzzConn c(2);
  c.feed(data, len);
  IOBuf batch;
  int rc = redis_try_process(c.sock, &batch);
  return rc != 0 ? 1 : 0;
}

// HPACK decode in isolation: a fresh decoder (static + dynamic table +
// huffman + size updates) over the raw block — narrower than nat_fuzz_h2
// so coverage isn't gated on valid frame framing.
int nat_fuzz_hpack(const char* data, size_t len) {
  void* dec = hpack_decoder_new();
  std::string flat, path;
  bool ok = hpack_decoder_decode(dec, (const uint8_t*)data, len, &flat,
                                 &path);
  hpack_decoder_free(dec);
  return ok ? 1 : 0;
}

// Forged shm segment image: the cross-process attach validation
// (magic/version/slots/arena vs claimed length) over arbitrary bytes.
int nat_fuzz_shm_seg(const char* data, size_t len) {
  return nat_shm_seg_validate(data, len);
}

}  // extern "C"
