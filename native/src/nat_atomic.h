// nat::atomic — the atomic-type seam between the production build and
// the dsched deterministic interleaving checker (native/model/).
//
// The lock-free primitives that the model explores (wsq.h's Chase-Lev
// deque, nat_desc_ring.h's Vyukov descriptor ring + blob arena) declare
// their atomics as nat::atomic<T> instead of std::atomic<T>:
//
//   * production / sanitizer / lockrank builds: nat::atomic IS
//     std::atomic (alias template, zero cost, identical layout);
//   * the model build (-DNAT_MODEL=1): nat::atomic is dsched::atomic,
//     whose every load/store/RMW is a schedule point of the cooperative
//     virtual-thread scheduler, with store-history + vector-clock
//     modeling so relaxed loads can return stale values the real
//     hardware is allowed to produce.
//
// The same source files compile unmodified under both.
#pragma once

#if defined(NAT_MODEL)

#include "dsched_atomic.h"  // model build adds -Imodel; defines nat::*

#else

#include <atomic>

namespace nat {

template <typename T>
using atomic = std::atomic<T>;

inline void atomic_thread_fence(std::memory_order o) {
  std::atomic_thread_fence(o);
}

}  // namespace nat

#endif
