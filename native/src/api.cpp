// C API for the native core — consumed by brpc_tpu/native via ctypes.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstring>

#include "iobuf.h"
#include "nat_api.h"
#include "rpc_meta.h"
#include "scheduler.h"

using namespace brpc_tpu;

extern "C" {

// ---- scheduler ----

int nat_sched_start(int nworkers) {
  return Scheduler::instance()->start(nworkers);
}

void nat_sched_stop() { Scheduler::instance()->stop(); }

int nat_sched_workers() { return Scheduler::instance()->nworkers(); }

uint64_t nat_sched_switches() {
  return Scheduler::instance()->total_switches();
}

// spawn N fibers each incrementing a shared counter M times with yields;
// returns the final counter (correctness probe for spawn/steal/yield).
static std::atomic<uint64_t> g_counter{0};
struct CountArg {
  int rounds;
};
static void count_fiber(void* a) {
  CountArg* ca = (CountArg*)a;
  for (int i = 0; i < ca->rounds; i++) {
    g_counter.fetch_add(1, std::memory_order_relaxed);
    if ((i & 15) == 0) Scheduler::yield();
  }
}

uint64_t nat_bench_spawn_join(int nfibers, int rounds) {
  g_counter.store(0, std::memory_order_relaxed);
  std::vector<Fiber*> fibers;
  CountArg arg{rounds};
  for (int i = 0; i < nfibers; i++) {
    fibers.push_back(Scheduler::instance()->spawn(count_fiber, &arg));
  }
  for (Fiber* f : fibers) Scheduler::instance()->join(f);
  return g_counter.load(std::memory_order_relaxed);
}

// ping-pong: two fibers alternating through butexes
// (bthread_ping_pong_unittest shape); returns ns per round-trip.
struct PingPongArg {
  Butex* a;
  Butex* b;
  int rounds;
  bool is_ping;
};
static void ping_pong_fiber(void* p) {
  PingPongArg* arg = (PingPongArg*)p;
  // the fetch_adds below are butex WAKE-PROTOCOL value bumps, not
  // reference counts — they are outside the NAT_REF_* ownership surface
  // (tools/natcheck refown) by design
  for (int i = 0; i < arg->rounds; i++) {
    if (arg->is_ping) {
      arg->b->value.fetch_add(1, std::memory_order_release);
      Scheduler::butex_wake(arg->b, 1);
      Scheduler::butex_wait(arg->a, i);
    } else {
      Scheduler::butex_wait(arg->b, i);
      arg->a->value.fetch_add(1, std::memory_order_release);
      Scheduler::butex_wake(arg->a, 1);
    }
  }
}

double nat_bench_ping_pong(int rounds) {
  Butex a, b;
  PingPongArg ping{&a, &b, rounds, true};
  PingPongArg pong{&a, &b, rounds, false};
  auto t0 = std::chrono::steady_clock::now();
  Fiber* f1 = Scheduler::instance()->spawn(ping_pong_fiber, &ping);
  Fiber* f2 = Scheduler::instance()->spawn(ping_pong_fiber, &pong);
  Scheduler::instance()->join(f1);
  Scheduler::instance()->join(f2);
  auto t1 = std::chrono::steady_clock::now();
  return std::chrono::duration<double, std::nano>(t1 - t0).count() / rounds;
}

// ---- self tests (return 0 on success) ----

int nat_wsq_selftest() {
  WorkStealingQueue<int> q(64);
  for (int i = 0; i < 50; i++) {
    if (!q.push(i)) return 1;
  }
  int v;
  if (!q.pop(&v) || v != 49) return 2;   // owner LIFO
  if (!q.steal(&v) || v != 0) return 3;  // thief FIFO
  int count = 2;
  while (q.pop(&v)) count++;
  if (count != 50) return 4;
  return 0;
}

int nat_iobuf_selftest() {
  IOBuf a;
  a.append("hello ", 6);
  a.append("world", 5);
  if (a.length() != 11) return 1;
  IOBuf b;
  a.cut_into(&b, 6);
  if (b.to_string() != "hello " || a.to_string() != "world") return 2;
  IOBuf c(b);  // ref-sharing copy
  if (c.to_string() != "hello ") return 3;
  std::string big(100000, 'z');
  IOBuf d;
  d.append(big);
  if (d.length() != big.size() || d.to_string() != big) return 4;
  d.pop_front(99999);
  if (d.length() != 1) return 5;
  // arena-backed user blocks: foreign memory rides the IOBuf zero-copy;
  // the release action fires exactly once, on the LAST ref drop
  static int user_frees = 0;
  user_frees = 0;
  std::string arena(70000, 'u');
  {
    IOBuf e;
    e.append("hdr:", 4);
    e.append_user(arena.data(), arena.size(),
                  [](void*) { user_frees++; }, nullptr);
    if (e.length() != 4 + arena.size()) return 6;
    IOBuf f;
    e.cut_into(&f, 40000);  // split mid-user-block: shared refs
    if (user_frees != 0) return 7;
    if (f.to_string() != "hdr:" + arena.substr(0, 39996)) return 8;
    f.clear();
    if (user_frees != 0) return 9;  // e still holds the tail ref
  }
  if (user_frees != 1) return 10;
  return 0;
}

int nat_meta_selftest() {
  RpcMetaN m;
  m.has_request = true;
  m.request.service_name = "EchoService";
  m.request.method_name = "Echo";
  m.correlation_id = 12345678901LL;
  m.attachment_size = 42;
  std::string enc = encode_request_meta(m);
  RpcMetaN out;
  if (!decode_meta(enc.data(), enc.size(), &out)) return 1;
  if (!out.has_request || out.request.service_name != "EchoService" ||
      out.request.method_name != "Echo" ||
      out.correlation_id != 12345678901LL || out.attachment_size != 42)
    return 2;
  RpcMetaN r;
  r.has_response = true;
  r.response.error_code = 1008;
  r.response.error_text = "rpc timed out";
  r.correlation_id = 7;
  std::string enc2 = encode_response_meta(r);
  RpcMetaN out2;
  if (!decode_meta(enc2.data(), enc2.size(), &out2)) return 3;
  if (!out2.has_response || out2.response.error_code != 1008 ||
      out2.response.error_text != "rpc timed out" ||
      out2.correlation_id != 7)
    return 4;
  return 0;
}

}  // extern "C"
