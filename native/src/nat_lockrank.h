// Lock ranks — the repo's total lock order, checked two ways:
//
//   * statically by tools/natcheck/lockorder.py, which parses every
//     NatMutex<kLockRank...> declaration (and the `natcheck:rank` comment
//     annotations on the few raw mutexes below), builds the
//     acquires-while-holding graph across all TUs and requires the rank
//     to strictly increase on every nested acquisition;
//   * at runtime under -DNAT_LOCKRANK=1 (`make -C native lockrank`, run
//     by `make -C native check`): every NatMutex::lock pushes its rank
//     on a thread-local held stack and aborts if the new rank is not
//     strictly greater than the deepest held one. try_lock acquisitions
//     are exempt from the order assert (a failed try_lock cannot
//     deadlock — that is exactly why the hot paths use them) but still
//     tracked while held.
//
// The discipline re-grows brpc's strict lock ranks around Socket/bthread
// internals as checkable tooling: outer control-plane locks rank low,
// per-session protocol locks mid, socket/ring/stat leaves high, and the
// scheduler's own locks highest (anything may wake a fiber while holding
// its own lock, never the reverse).
//
// Raw (non-NatMutex) locks and their ranks — condition-variable partners
// must stay std::mutex (std::condition_variable demands it), and the shm
// lifetime fence is a cross-process robust pthread mutex:
//
//   15  shm.fence    ShmWorkerHdr::fence   (nat_shm_lane.cpp)
//   57  server.py    NatServer::py_mu      (nat_internal.h)
//   86  timer.run    TimerThread::run_mu_  (timer_thread.h)
//   90  butex        Butex::mu             (scheduler.h)
//   94  sched.park   Worker::park_mu       (scheduler.h)
#pragma once

#include <mutex>

namespace brpc_tpu {

enum : int {
  kLockRankMuSelftest = 4,    // nat_mu_contend_selftest's burn mutex
                              // (holds nothing, held under nothing)
  kLockRankDumpCtl = 5,       // nat_dump g_dump_ctl_mu: flight-recorder
                              // start/stop/status (control path only;
                              // held across the writer join, which
                              // takes no NatMutex of its own)
  kLockRankProfCtl = 6,       // nat_prof g_ctl_mu: start/stop/reset
                              // serialization (control path only; held
                              // across the collector join, which takes
                              // g_report_mu on its own thread)
  kLockRankResReport = 7,     // nat_res g_res_report_mu: allocation-site
                              // collector/report + ledger snapshots
                              // (control path only; the record seams are
                              // lock-free — they run under registry
                              // locks of arbitrary rank)
  kLockRankProfReport = 8,    // nat_prof g_report_mu: collector/report
                              // serialization (holds no other lock while
                              // symbolizing), outermost
  kLockRankMuProfReport = 9,  // nat_prof g_mu_report_mu: contention-
                              // profiler aggregate/report (control path)
  kLockRankShmProbe = 10,     // g_probe_mu: fence probing, outermost
  // 15: shm.fence (raw robust pthread mutex, see header comment)
  kLockRankShmReq = 20,       // g_req_mu[i]: per-worker request producer
  kLockRankShmResp = 22,      // g_resp_mu: worker-side response producer
  kLockRankShmFabric = 24,    // g_fab_mu: producer-side tensor-fabric
                              // push lock (kind-8 records onto the
                              // producer slot's own request ring)
  kLockRankCluster = 28,      // NatCluster::mu: naming-feed diff/publish
                              // (creates channels under it: below the
                              // runtime lock; the LB read path takes NO
                              // lock — the DoublyBufferedData gate)
  kLockRankRuntime = 30,      // g_rt_mu: runtime/server registry
  kLockRankListen = 34,       // Dispatcher::listen_mu
  kLockRankDispClose = 35,    // Dispatcher::pend_close_mu: deferred
                              // listener-fd closes (teardown-race fix)
  kLockRankReconnect = 36,    // NatChannel::reconnect_mu
  kLockRankHttpSess = 40,     // HttpSessionN::http_mu
  kLockRankH2Sess = 42,       // H2SessionN::h2_mu
  kLockRankRedisSess = 44,    // RedisSessN::redis_mu
  kLockRankRedisStore = 46,   // RedisStoreN::store_mu
  kLockRankHttpCli = 50,      // HttpCliSessN::httpc_mu
  kLockRankH2Cli = 52,        // H2CliSessN::h2c_mu
  kLockRankSslSess = 54,      // SslSessionN::ssl_mu (sessions write
                              // through the TLS session: session < ssl)
  kLockRankBreaker = 55,      // NatChannel::breaker_mu (fed from
                              // take_pending, which client-lane readers
                              // may reach while holding session locks)
  kLockRankChanGrow = 56,     // NatChannel::grow_mu_
  // 57: server.py (raw, cv partner)
  kLockRankShmInflight = 58,  // g_inflight_mu: reaper table
  kLockRankOverload = 59,     // g_adm_mu: auto-limiter window (completion
                              // accounting runs under py_mu/inflight)
  kLockRankSockAlloc = 60,    // g_sock_alloc_mu: registry slab/freelist
  kLockRankSockEpoll = 62,    // NatSocket::epollctl_mu: EPOLLOUT
                              // arm/disarm arbitration (cold path; the
                              // write hot path itself is the wait-free
                              // MPSC stack of nat_wstack.h — lockless)
  kLockRankRingRetry = 64,    // g_ring_retry_mu
  kLockRankRingFiles = 66,    // RingListener::files_mu_
  kLockRankRingSq = 68,       // RingListener::sq_mu_
  kLockRankRingSend = 70,     // RingListener::send_mu_ (the SQ-full
                              // failure path returns its send buffer
                              // while still holding sq_mu_)
  kLockRankRingComp = 72,     // RingListener::comp_mu_
  kLockRankRingBuf = 74,      // RingListener::buf_mu_
  kLockRankStatsSpan = 76,    // g_span_drain_mu: span-ring drain (its
                              // dropped-span accounting can enter the
                              // cell registry: span < cell)
  kLockRankChanReg = 77,      // g_chan_reg_mu: open-channel registry for
                              // the builtin.stats snapshot (near-leaf:
                              // the walk reads channel atomics only; the
                              // register/unregister sites hold no lock)
  kLockRankStatsCell = 78,    // g_cell_mu: stat-cell registry
  kLockRankTimerStart = 80,   // TimerThread::start_mu_
  kLockRankTimerBucket = 82,  // TimerThread::Bucket::bucket_mu
  kLockRankTimerCancel = 84,  // TimerThread::cancel_mu_
  // 86: timer.run (raw, cv partner)
  kLockRankSchedHooks = 88,   // Scheduler::hooks_mu_
  // 90: butex (raw, cv partner)
  kLockRankSchedRemote = 92,  // Worker::remote_mu
  kLockRankBulkPool = 93,     // iobuf bulk-slab freelist (read-side
                              // arena blocks for bulk frames): leaf
  // 94: sched.park (raw, cv partner)
  kLockRankBlockPool = 95,    // iobuf central block pool (batch steal/
                              // return under ANY runtime lock: leaf)
  kLockRankStackPool = 96,    // g_stack_pool_mu, innermost
};

#if defined(NAT_LOCKRANK)
namespace lockrank {
// Blocking acquisition about to happen: assert rank > deepest held,
// then push. Called BEFORE the underlying lock so an actual inversion
// aborts with a report instead of deadlocking silently.
void note_acquire(int rank);
// Successful try_lock: push without the order assert (non-blocking
// acquisitions cannot deadlock; brpc's try_lock-out-of-rank idiom).
void note_acquired(int rank);
void note_release(int rank);
// Fiber-switch hook (scheduler.cpp): no NatMutex may be held across a
// context switch — the fiber can resume on another thread while this
// thread's TLS still claims the rank.
void assert_none_held(const char* where);
}  // namespace lockrank
#endif

// Contended-acquisition slow path (defined in nat_prof.cpp): measures
// the blocking wait, feeds the always-on per-rank wait totals, and —
// when the contention profiler is armed — threshold/rate-samples a
// frame-pointer stack weighted by the wait into the per-thread rings
// surfaced at /hotspots/contention. MUST acquire no NatMutex itself (it
// runs inside an acquisition of arbitrary rank).
void nat_mu_contended_wait(std::mutex* m, int rank);

// Drop-in std::mutex wrapper carrying its declared rank. Zero overhead
// unless NAT_LOCKRANK is defined. Use with CTAD guards:
//   NatMutex<kLockRankSockEpoll> epollctl_mu;
//   std::lock_guard g(epollctl_mu);
template <int Rank>
class NatMutex {
 public:
  static constexpr int kRank = Rank;

  void lock() {
#if defined(NAT_LOCKRANK)
    lockrank::note_acquire(Rank);
#endif
    // uncontended fast path: one CAS, exactly what m_.lock() would do.
    // A failed try_lock IS contention — the out-of-line slow path
    // blocks in m_.lock() with the wait measured (the lock behavior
    // lockorder/dsched prove safe, finally measured for cost).
    if (m_.try_lock()) return;
    nat_mu_contended_wait(&m_, Rank);
  }

  bool try_lock() {
    if (!m_.try_lock()) return false;
#if defined(NAT_LOCKRANK)
    lockrank::note_acquired(Rank);
#endif
    return true;
  }

  void unlock() {
    m_.unlock();
#if defined(NAT_LOCKRANK)
    lockrank::note_release(Rank);
#endif
  }

 private:
  // natcheck:allow(lock-undeclared): NatMutex's own backing mutex — the
  // rank lives in the template parameter of each declaration site
  std::mutex m_;
};

}  // namespace brpc_tpu
