// Runtime refcount-contract validator (see nat_refown.h). The ledger is
// compiled into the library only under -DNAT_REFGUARD=1 (`make -C native
// refguard`); production builds get the exported stubs and nothing else.
//
// Per tracked object (keyed by pointer — socket slabs are never freed,
// and heap objects revive their ledger entry on the next annotated
// acquire after malloc reuse): a generation, a dead bit, and a small
// per-tag balance table. Every NAT_REF_* macro feeds it:
//
//   op(+1)/op(-1)   tag balance moves; a release that would drive a tag
//                   negative is a release-after-final / wrong-tag pair
//   transfer        from_tag balance moves to to_tag (no total change);
//                   a transfer out of an empty tag is a violation
//   borrow          the object must not be invalidated (dead)
//   dead            every tag must balance to ZERO; the generation bumps
//                   and the object is invalid until re-acquired
//
// Violations abort with the failing tag pair and the object's full
// ledger printed — the refcount twin of nat_lockrank.cpp's report.
#include "nat_refown.h"

#include "nat_api.h"

#if defined(NAT_REFGUARD)

#include <cstdio>
#include <cstdlib>
#include <cstring>

#include <atomic>
#include <mutex>
#include <unordered_map>
#include <vector>

namespace brpc_tpu {
namespace refguard {

namespace {

std::atomic<uint64_t> g_ops{0};

struct TagBal {
  const char* tag;
  int64_t balance;
};

struct ObjLedger {
  uint32_t gen = 0;
  bool dead = false;
  std::vector<TagBal> tags;

  int64_t* find(const char* tag, bool create) {
    for (TagBal& t : tags) {
      if (t.tag == tag || strcmp(t.tag, tag) == 0) return &t.balance;
    }
    if (!create) return nullptr;
    tags.push_back(TagBal{tag, 0});
    return &tags.back().balance;
  }
  bool all_zero() const {
    for (const TagBal& t : tags) {
      if (t.balance != 0) return false;
    }
    return true;
  }
};

// 64-way sharded by pointer hash: the ledger op is on every ref
// operation in the instrumented build, and one global lock would
// serialize the whole runtime. Only ONE shard lock is ever held at a
// time, and the hooks acquire no other lock, so any rank may hold it —
// rank 99, past the rank-96 innermost production lock.
constexpr int kShards = 64;
struct Shard {
  // natcheck:rank(refguard, 99)
  std::mutex refguard_mu;
  std::unordered_map<const void*, ObjLedger> objs;
};
Shard& shard_for(const void* obj) {
  // natcheck:leak(refguard_shards): the ledger must survive exit() —
  // detached runtime threads keep releasing references through static
  // destruction (the PR-1 class).
  static Shard* shards = new Shard[kShards];
  uintptr_t p = (uintptr_t)obj;
  return shards[(p >> 4) % kShards];
}

[[noreturn]] void violation(const void* obj, const ObjLedger* led,
                            const char* what, const char* tag_a,
                            const char* tag_b) {
  fprintf(stderr, "nat_refguard: %s obj=%p tag=%s%s%s (ledger:", what,
          obj, tag_a, tag_b != nullptr ? " vs " : "",
          tag_b != nullptr ? tag_b : "");
  if (led != nullptr) {
    for (const TagBal& t : led->tags) {
      fprintf(stderr, " %s=%lld", t.tag, (long long)t.balance);
    }
    if (led->dead) fprintf(stderr, " [dead gen=%u]", led->gen);
  }
  fprintf(stderr, ")\n");
  fflush(stderr);
  abort();
}

}  // namespace

void op(const void* obj, const char* tag, int delta) {
  g_ops.fetch_add(1, std::memory_order_relaxed);
  Shard& sh = shard_for(obj);
  std::lock_guard g(sh.refguard_mu);
  ObjLedger& led = sh.objs[obj];
  if (delta > 0 && led.dead) {
    // a fresh acquire revives a recycled slot / reused allocation
    led.dead = false;
    led.gen++;
    led.tags.clear();
  }
  int64_t* bal = led.find(tag, /*create=*/true);
  *bal += delta;
  if (*bal < 0) {
    violation(obj, &led, "release with no owning acquire "
              "(release-after-final or wrong tag)", tag, nullptr);
  }
  if (delta < 0 && led.all_zero() && !led.dead) {
    // balanced and alive: drop the entry so short-lived objects
    // (PyRequests, WriteReq nodes) don't grow the table forever
    sh.objs.erase(obj);
  }
}

void transfer(const void* obj, const char* from_tag, const char* to_tag) {
  g_ops.fetch_add(1, std::memory_order_relaxed);
  Shard& sh = shard_for(obj);
  std::lock_guard g(sh.refguard_mu);
  auto it = sh.objs.find(obj);
  if (it == sh.objs.end()) {
    violation(obj, nullptr, "transfer on an untracked object", from_tag,
              to_tag);
  }
  ObjLedger& led = it->second;
  int64_t* from = led.find(from_tag, /*create=*/false);
  if (from == nullptr || *from <= 0) {
    violation(obj, &led, "transfer from a tag with no held reference",
              from_tag, to_tag);
  }
  (*from)--;
  (*led.find(to_tag, /*create=*/true))++;
}

void borrow(const void* obj) {
  g_ops.fetch_add(1, std::memory_order_relaxed);
  Shard& sh = shard_for(obj);
  std::lock_guard g(sh.refguard_mu);
  auto it = sh.objs.find(obj);
  if (it != sh.objs.end() && it->second.dead) {
    violation(obj, &it->second, "borrow after invalidate", "(borrow)",
              nullptr);
  }
}

void dead(const void* obj) {
  g_ops.fetch_add(1, std::memory_order_relaxed);
  Shard& sh = shard_for(obj);
  std::lock_guard g(sh.refguard_mu);
  auto it = sh.objs.find(obj);
  if (it == sh.objs.end()) {
    // every tag already balanced to zero (the entry was dropped): mark
    // the identity dead so a late borrow still aborts
    ObjLedger& fresh = sh.objs[obj];
    fresh.dead = true;
    fresh.gen++;
    return;
  }
  ObjLedger& led = it->second;
  if (led.dead) {
    violation(obj, &led, "double destruction", "(dead)", nullptr);
  }
  if (!led.all_zero()) {
    violation(obj, &led, "destroyed with unbalanced tags", "(dead)",
              nullptr);
  }
  led.dead = true;
  led.gen++;
  led.tags.clear();
}

}  // namespace refguard

const void* nat_ref_adm_anchor() {
  static const int anchor = 0;
  return &anchor;
}

}  // namespace brpc_tpu

extern "C" {

int nat_refguard_enabled(void) { return 1; }

uint64_t nat_refguard_ops(void) {
  return brpc_tpu::refguard::g_ops.load(std::memory_order_relaxed);
}

int nat_refguard_selftest(int scenario) {
  struct Dummy {
    int refs = 1;
    void add_ref() { refs++; }
    void release() { refs--; }
  };
  static Dummy d;  // stable identity across calls
  if (scenario == 0) {
    // balanced round: the full grammar on one object
    NAT_REF_ACQUIRED(&d, selftest.a);
    NAT_REF_ACQUIRE(&d, selftest.b);
    NAT_REF_TRANSFER(&d, selftest.a, selftest.c);
    NAT_REF_BORROW(&d);
    NAT_REF_RELEASE(&d, selftest.b);
    NAT_REF_RELEASED(&d, selftest.c);
    NAT_REF_DEAD(&d);
    return 0;
  }
  if (scenario == 1) {
    // deliberate double release: the guard must abort with the tag pair
    NAT_REF_ACQUIRED(&d, selftest.dbl);
    NAT_REF_RELEASED(&d, selftest.dbl);
    // natcheck:allow(refown-double-release): the deliberate defect
    NAT_REF_RELEASED(&d, selftest.dbl);  // aborts here
    return -2;                           // unreachable under refguard
  }
  return -1;
}

}  // extern "C"

#else  // !NAT_REFGUARD: exported stubs so the ABI is build-invariant

namespace brpc_tpu {
const void* nat_ref_adm_anchor() {
  static const int anchor = 0;
  return &anchor;
}
}  // namespace brpc_tpu

extern "C" {
int nat_refguard_enabled(void) { return 0; }
uint64_t nat_refguard_ops(void) { return 0; }
int nat_refguard_selftest(int scenario) {
  return scenario == 0 ? 0 : -1;
}
}  // extern "C"

#endif  // NAT_REFGUARD
