// Native RPC runtime — the framework data path in C++.
//
// This is the native counterpart of the brpc core runtime (SURVEY.md §2.4),
// built FROM the other native components rather than beside them:
//
//   NatSocket      ⇔ brpc::Socket (socket.cpp): versioned-id registry, a
//                    single-writer write queue with inline first attempt +
//                    KeepWrite fiber on partial writes (the lock+deque
//                    rendition of the wait-free design, socket.h:293-333),
//                    SetFailed draining queued writes.
//   Dispatcher     ⇔ EventDispatcher (event_dispatcher_epoll.cpp:249):
//                    one epoll loop, edge-triggered; EPOLLIN spawns a
//                    reader FIBER on the scheduler; EPOLLOUT wakes the
//                    socket's KeepWrite butex.
//   Messenger      ⇔ InputMessenger (input_messenger.cpp:331): reader
//                    fiber drains the fd into the socket's native IOBuf,
//                    cuts tpu_std frames, and processes them — requests
//                    inline in the reader fiber (the process-in-place
//                    discipline for non-blocking handlers; blocking user
//                    code belongs on the Python lane, the
//                    usercode_backup_pool analog), responses routed to the
//                    owning channel's pending-call table.
//   NatServer      ⇔ brpc::Server + Acceptor: native method registry
//                    dispatched on fibers/IOBuf, plus a Python lane — a
//                    condvar MPSC queue Python worker threads drain via
//                    ctypes (nat_take_request/nat_respond), so arbitrary
//                    Python services mount the native port while Python
//                    user code runs on pthreads, never on fiber stacks.
//   NatChannel     ⇔ brpc::Channel/Controller client half: correlation-id
//                    pending table; synchronous calls park on a butex
//                    (fiber) or its condvar path (pthread callers).
//
// Wire format: tpu_std ("TRPC" + body + meta_size + RpcMeta), identical to
// brpc_tpu/rpc/tpu_std_protocol.py — Python channels interoperate with the
// native port and vice versa.
#include <arpa/inet.h>
#include <errno.h>
#include <fcntl.h>
#include <poll.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <mutex>
#include <string>
#include <string_view>
#include <thread>
#include <unordered_map>
#include <vector>

#include "iobuf.h"
#include "ring_listener.h"
#include "rpc_meta.h"
#include "scheduler.h"
#include "timer_thread.h"

namespace brpc_tpu {

// error codes shared with brpc_tpu/rpc/errors.py
static const int kENOSERVICE = 1001;
static const int kENOMETHOD = 1002;
static const int kERPCTIMEDOUT = 1008;
static const int kEFAILEDSOCKET = 1009;

static const char kMagicRpc[4] = {'T', 'R', 'P', 'C'};

static uint32_t rd_be32(const char* p) {
  return ((uint32_t)(uint8_t)p[0] << 24) | ((uint32_t)(uint8_t)p[1] << 16) |
         ((uint32_t)(uint8_t)p[2] << 8) | (uint32_t)(uint8_t)p[3];
}
static void wr_be32(char* p, uint32_t v) {
  p[0] = (char)(v >> 24);
  p[1] = (char)(v >> 16);
  p[2] = (char)(v >> 8);
  p[3] = (char)v;
}

class Dispatcher;
class NatServer;
class NatChannel;
static Dispatcher* pick_dispatcher();
static void health_check_fire(void* raw);

// ---------------------------------------------------------------------------
// NatSocket + versioned-id registry (socket_inl.h:28-185 shape)
// ---------------------------------------------------------------------------

struct NatSocket {
  int fd = -1;
  // atomic: the server-stop scan reads ids of slots that sock_create may
  // concurrently be recycling (relaxed loads compile to plain loads here)
  std::atomic<uint64_t> id{0};
  Dispatcher* disp = nullptr;
  NatServer* server = nullptr;    // set on accepted connections
  NatChannel* channel = nullptr;  // set on client connections

  std::atomic<bool> failed{false};
  // (version<<32)|refcount in ONE atomic (the _versioned_ref of
  // socket_inl.h:28-78): addressing CAS-increments the refcount only
  // while the version matches, so a stale id can never revive a recycled
  // socket, and no registry lock is needed on the per-event/per-call path.
  std::atomic<uint64_t> versioned_ref{0};
  uint32_t next_version = 1;  // owner-only; assigned at (re)creation

  // read side: drained inline by the owning dispatcher loop (single
  // reader per socket by construction)
  IOBuf in_buf;

  // write side
  std::mutex write_mu;
  IOBuf write_q;        // queued-but-unwritten bytes (frames are appended
                        // whole, so content never interleaves)
  bool writing = false; // a writer (inline or KeepWrite fiber) is active
  Butex epollout;       // bumped by the dispatcher on EPOLLOUT
  uint32_t epoll_events = 0;  // currently-armed event mask
  // Deferred-write mode (the fork's io_uring submission-batching
  // discipline, ring_listener.h): write() only queues; a writer fiber
  // scheduled behind the currently-ready fibers drains everything they
  // appended in ONE writev. Throughput over per-call latency.
  bool defer_writes = false;

  // Raw python-lane mode (the multi-protocol-port sniff-once-and-remember
  // discipline, input_messenger.h:33-154): once non-tpu_std bytes are
  // seen on a raw-fallback server, ALL further input on this connection
  // is shovelled to the Python protocol stack as ordered raw chunks.
  // atomic: set by the reading thread, read by set_failed from any
  // thread (server stop, nat_sock_set_failed). py_raw_seq stays plain —
  // only the single reading thread touches it.
  std::atomic<bool> py_raw{false};
  uint64_t py_raw_seq = 0;

  // io_uring datapath (RingListener): (generation<<32 | file index) when
  // this socket's reads ride the provided-buffer ring (-1 = epoll lane);
  // the generation lets the ring reject stale rearms/sends after the
  // slot is recycled. Fixed-send state: one in-flight fixed-buffer send
  // at a time keeps ordering (the fork's io_uring_write_req_,
  // socket.h:632-636).
  std::atomic<int64_t> ring_ref{-1};  // atomic: drain workers read it
                                      // while accept/set_failed write it
  bool ring_sending = false;   // under write_mu
  size_t ring_inflight = 0;    // bytes submitted, awaiting completion

  void add_ref() { versioned_ref.fetch_add(1, std::memory_order_relaxed); }
  void release();
  void reset_for_reuse();
  int write(IOBuf&& frame);
  bool flush_some();  // true = drained/failed-and-drained, false = EAGAIN
  void set_failed();
  void arm_epollout();
  void disarm_epollout();
};

// Socket registry — ResourcePool discipline (butil/resource_pool.h +
// socket_inl.h): NatSocket objects are slab-allocated and NEVER freed, so
// a slot index is a permanently-valid pointer; liveness is governed solely
// by the (version, refcount) atomic inside the socket. Lookups take no
// lock; the mutex below only guards slab growth and the index freelist.
static const uint32_t kSockSlabBits = 10;
static const uint32_t kSockSlabSize = 1u << kSockSlabBits;  // 1024
static const uint32_t kSockSlabs = 1024;                    // 1M sockets max
static std::atomic<NatSocket**> g_sock_slab[kSockSlabs];
static std::mutex g_sock_alloc_mu;
static std::vector<uint32_t> g_sock_free;
static uint32_t g_sock_next_idx = 0;

static NatSocket* sock_at(uint32_t idx) {
  NatSocket** slab =
      g_sock_slab[idx >> kSockSlabBits].load(std::memory_order_acquire);
  if (slab == nullptr) return nullptr;
  return slab[idx & (kSockSlabSize - 1)];
}

// Allocate (or reuse) a socket slot; the returned socket has refcount 1
// (the registry/creator reference) and a fresh version in both its id and
// its versioned_ref.
static NatSocket* sock_create() {
  uint32_t idx;
  NatSocket* s = nullptr;
  {
    std::lock_guard<std::mutex> g(g_sock_alloc_mu);
    if (!g_sock_free.empty()) {
      idx = g_sock_free.back();
      g_sock_free.pop_back();
      s = sock_at(idx);
    } else {
      idx = g_sock_next_idx++;
      uint32_t slab_i = idx >> kSockSlabBits;
      if (slab_i >= kSockSlabs) return nullptr;
      if (g_sock_slab[slab_i].load(std::memory_order_relaxed) == nullptr) {
        NatSocket** slab = new NatSocket*[kSockSlabSize]();
        g_sock_slab[slab_i].store(slab, std::memory_order_release);
      }
    }
  }
  if (s == nullptr) {
    s = new NatSocket();  // lives forever in its slot
    g_sock_slab[idx >> kSockSlabBits].load(std::memory_order_acquire)
        [idx & (kSockSlabSize - 1)] = s;
  } else {
    s->reset_for_reuse();
  }
  uint32_t ver = s->next_version++;
  if (ver == 0) ver = s->next_version++;  // version 0 reserved (= dead)
  s->id = ((uint64_t)ver << 32) | idx;
  s->versioned_ref.store(((uint64_t)ver << 32) | 1,
                         std::memory_order_release);
  return s;
}

// Address with a borrowed reference (caller must release()); nullptr once
// the id generation is stale — use-after-free-proof, lock-free.
static NatSocket* sock_address(uint64_t id) {
  uint32_t idx = (uint32_t)(id & 0xffffffffu);
  uint32_t ver = (uint32_t)(id >> 32);
  NatSocket* s = sock_at(idx);
  if (s == nullptr) return nullptr;
  uint64_t vr = s->versioned_ref.load(std::memory_order_acquire);
  while ((uint32_t)(vr >> 32) == ver && (uint32_t)vr != 0) {
    if (s->versioned_ref.compare_exchange_weak(vr, vr + 1,
                                               std::memory_order_acq_rel)) {
      return s;
    }
  }
  return nullptr;
}

// Invalidate the id (bump the version, keeping the refcount) so future
// sock_address calls fail; existing references stay valid until released.
static void sock_unregister(NatSocket* s) {
  uint64_t vr = s->versioned_ref.load(std::memory_order_acquire);
  while (true) {
    uint64_t bumped = vr + (1ull << 32);
    if (s->versioned_ref.compare_exchange_weak(vr, bumped,
                                               std::memory_order_acq_rel)) {
      s->next_version = (uint32_t)(bumped >> 32) + 1;
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// Dispatcher — one epoll loop feeding the fiber scheduler
// ---------------------------------------------------------------------------


class Dispatcher {
 public:
  int epfd = -1;
  int wake_fd = -1;  // eventfd to break epoll_wait on stop
  std::thread thread;
  std::atomic<bool> stop{false};
  // listen sockets: fd -> server
  std::mutex listen_mu;
  std::unordered_map<int, NatServer*> listeners;

  int start() {
    epfd = epoll_create1(0);
    if (epfd < 0) return -1;
    wake_fd = eventfd(0, EFD_NONBLOCK);
    struct epoll_event ev;
    ev.events = EPOLLIN;
    ev.data.u64 = (uint64_t)-1;
    epoll_ctl(epfd, EPOLL_CTL_ADD, wake_fd, &ev);
    thread = std::thread([this] { run(); });
    return 0;
  }

  void shutdown() {
    stop = true;
    uint64_t one = 1;
    ssize_t rc = ::write(wake_fd, &one, 8);
    (void)rc;
    if (thread.joinable()) thread.join();
    ::close(wake_fd);
    ::close(epfd);
  }

  // Register a connection socket for edge-triggered reads. The socket id
  // (not the pointer) rides in epoll data so stale events can't touch a
  // recycled socket.
  void add_consumer(NatSocket* s) {
    struct epoll_event ev;
    ev.events = EPOLLIN | EPOLLET;
    ev.data.u64 = s->id;
    s->epoll_events = ev.events;
    epoll_ctl(epfd, EPOLL_CTL_ADD, s->fd, &ev);
  }

  void add_listener(int fd, NatServer* srv) {
    {
      std::lock_guard<std::mutex> g(listen_mu);
      listeners[fd] = srv;
    }
    struct epoll_event ev;
    ev.events = EPOLLIN;
    // Listener tags stay below 2^32; socket ids are version<<32|idx with
    // version >= 1, so the two ranges can never collide.
    ev.data.u64 = (uint64_t)fd;
    epoll_ctl(epfd, EPOLL_CTL_ADD, fd, &ev);
  }

  void run();
  void accept_loop(int listen_fd, NatServer* srv);
};

// ---------------------------------------------------------------------------
// NatServer
// ---------------------------------------------------------------------------

// Native handler: fills response payload/attachment (zero-copy IOBuf) or an
// error. Runs inline in the reader fiber — must not block.
struct NativeHandlerCtx {
  IOBuf* req_payload = nullptr;
  IOBuf* req_attachment = nullptr;
  IOBuf resp_payload;
  IOBuf resp_attachment;
  int32_t error_code = 0;
  std::string error_text;
};
using NativeHandler = std::function<void(NativeHandlerCtx&)>;

// A request handed to the Python lane (usercode_backup_pool discipline:
// Python user code runs on pthreads, not fiber stacks).
// kind: 0 = parsed tpu_std request; 1 = raw bytes for the Python protocol
// stack (cid = per-socket sequence number for in-order reassembly across
// the pthread pool); 2 = connection closed (session cleanup).
struct PyRequest {
  int32_t kind = 0;
  uint64_t sock_id = 0;
  int64_t cid = 0;
  int32_t compress_type = 0;
  std::string service;
  std::string method;
  std::string payload;
  std::string attachment;
  std::string meta_bytes;  // full RpcMeta wire bytes: Python re-parses for
                           // log/trace ids, auth_data, timeout, tensors…
};

class NatServer {
 public:
  int listen_fd = -1;
  int port = 0;
  Dispatcher* disp = nullptr;
  std::atomic<uint64_t> requests{0};
  std::atomic<uint64_t> connections{0};
  // Lifetime (replaces the round-2 graveyard): the global registration
  // holds one reference, every accepted socket one, every py-lane taker
  // one while inside take_py — a stopped server is deleted when the last
  // connection/taker lets go, and stop->start cycles no longer leak
  // (server.h:426-441 Stop/Join-then-Start-again semantics).
  std::atomic<int> ref{1};

  void add_ref() { ref.fetch_add(1, std::memory_order_relaxed); }
  void release() {
    if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) delete this;
  }

  ~NatServer();  // drains py_q: late kind-2 notices enqueue after stop

  // frozen at start; std::less<> enables allocation-free string_view find
  std::map<std::string, NativeHandler, std::less<>> handlers;
  bool py_lane_enabled = false;
  // Route unrecognized framing to the Python protocol stack instead of
  // failing the socket (set when a Python server with a full protocol
  // registry is mounted on this port).
  bool raw_fallback = false;

  // Python lane MPSC queue
  std::mutex py_mu;
  std::condition_variable py_cv;
  std::deque<PyRequest*> py_q;
  bool py_stopping = false;

  void enqueue_py(PyRequest* r) {
    {
      std::lock_guard<std::mutex> g(py_mu);
      py_q.push_back(r);
    }
    py_cv.notify_one();
  }

  PyRequest* take_py(int timeout_ms) {

    std::unique_lock<std::mutex> lk(py_mu);
    if (py_q.empty() && !py_stopping) {
      py_cv.wait_for(lk, std::chrono::milliseconds(timeout_ms));
    }
    if (py_q.empty()) return nullptr;
    PyRequest* r = py_q.front();
    py_q.pop_front();
    return r;
  }
};

NatServer::~NatServer() {
  // stop() drains py_q, but a raw-mode socket failing AFTER stop still
  // enqueues its kind-2 close notice; free whatever is left.
  for (PyRequest* r : py_q) delete r;
}

// ---------------------------------------------------------------------------
// NatChannel (client half)
// ---------------------------------------------------------------------------

class NatChannel;

struct PendingCall {
  Butex done;  // 0 = in flight, 1 = complete
  int32_t error_code = 0;
  std::string error_text;
  IOBuf response;
  IOBuf attachment;
  // Asynchronous completion (brpc's done-closure, controller.h): when
  // set, the response path invokes cb (which owns pc) instead of waking
  // a parked caller — the async RPC surface sync calls are built on.
  void (*cb)(PendingCall*, void*) = nullptr;
  void* cb_arg = nullptr;
  // Slot machinery (the versioned CallId discipline of bthread/id.h:38-60
  // + controller.h:655-664): calls live in never-freed slabs owned by
  // the channel; the correlation id packs (version, slot index), and a
  // single atomic word (version<<1 | pending) arbitrates completion —
  // whoever CASes the pending bit off owns the call. No lock, no map,
  // no allocation on the per-call path, and a late/duplicate response
  // (stale version) can never touch a recycled call.
  NatChannel* owner = nullptr;
  uint32_t slot_idx = 0;
  uint32_t next_free = 0;  // freelist link, encoded idx+1
  std::atomic<uint64_t> state{0};  // (version << 1) | pending_bit
};

static void pc_free(PendingCall* pc);  // returns the slot to its channel

class NatChannel {
 public:
  static const uint32_t kIdxBits = 20;  // 1M concurrent calls per channel
  static const uint32_t kIdxMask = (1u << kIdxBits) - 1;
  static const uint32_t kSlabBits = 8;  // 256 calls per slab
  static const uint32_t kSlabSize = 1u << kSlabBits;
  static const uint32_t kMaxSlabs = 1u << (kIdxBits - kSlabBits);

  std::atomic<uint64_t> sock_id{0};
  // Reconnect state (single-connection Channel semantics: the reference
  // re-establishes a failed single connection on use, and the health
  // checker revives it in the background — health_check.cpp:146-237).
  std::string peer_ip;
  int peer_port = 0;
  int connect_timeout_ms = 0;     // 0 = default guard
  int health_check_interval_ms = 0;  // 0 = no background revival
  bool defer_writes_flag = false;
  std::atomic<bool> closed{false};
  std::atomic<bool> hc_pending{false};
  std::mutex reconnect_mu;
  // Lifetime: the owning socket holds one reference (released in
  // ~NatSocket) and the opener holds one (released in nat_channel_close),
  // so a reader fiber mid-process_input can never see a freed channel.
  std::atomic<int> ref{1};

  void add_ref() { ref.fetch_add(1, std::memory_order_relaxed); }
  void release() {
    if (ref.fetch_sub(1, std::memory_order_acq_rel) == 1) delete this;
  }


  ~NatChannel() {
    for (uint32_t i = 0; i < kMaxSlabs; i++) {
      PendingCall* slab = slabs_[i].load(std::memory_order_acquire);
      if (slab != nullptr) delete[] slab;
    }
  }

  PendingCall* slot_at(uint32_t idx) {
    return &slabs_[idx >> kSlabBits].load(std::memory_order_acquire)
                [idx & (kSlabSize - 1)];
  }

  PendingCall* begin_call(int64_t* cid_out,
                          void (*cb)(PendingCall*, void*) = nullptr,
                          void* cb_arg = nullptr) {
    uint32_t idx = pop_free();
    if (idx == UINT32_MAX) return nullptr;  // slot space exhausted
    PendingCall* pc = slot_at(idx);
    uint64_t version =
        (pc->state.load(std::memory_order_relaxed) >> 1) + 1;
    pc->done.value.store(0, std::memory_order_relaxed);
    pc->error_code = 0;
    pc->error_text.clear();
    pc->response.clear();
    pc->attachment.clear();
    pc->cb = cb;
    pc->cb_arg = cb_arg;
    pc->owner = this;
    pc->slot_idx = idx;
    // everything above must be visible before the pending bit: a racing
    // fail_all completes through cb/butex the instant it sees the bit
    pc->state.store((version << 1) | 1, std::memory_order_release);
    *cid_out = (int64_t)((version << kIdxBits) | idx);
    return pc;
  }

  // CAS the pending bit off; the winner owns the call. Stale cids (old
  // version) and double-completions lose the CAS and get nullptr.
  // Non-consuming peek: true while the call is still awaiting its first
  // completion (used by the backup-request timer to decide whether a
  // duplicate send is still useful).
  bool is_pending(int64_t cid) {
    uint32_t idx = (uint32_t)cid & kIdxMask;
    if (idx >= nslots_.load(std::memory_order_acquire)) return false;
    uint64_t expected = (((uint64_t)cid >> kIdxBits) << 1) | 1;
    return slot_at(idx)->state.load(std::memory_order_acquire) == expected;
  }

  PendingCall* take_pending(int64_t cid) {
    uint32_t idx = (uint32_t)cid & kIdxMask;
    if (idx >= nslots_.load(std::memory_order_acquire)) return nullptr;
    PendingCall* pc = slot_at(idx);
    uint64_t expected = (((uint64_t)cid >> kIdxBits) << 1) | 1;
    if (pc->state.compare_exchange_strong(expected, expected & ~1ull,
                                          std::memory_order_acq_rel)) {
      return pc;
    }
    return nullptr;
  }

  void fail_all(int32_t code, const char* text) {
    uint32_t n = nslots_.load(std::memory_order_acquire);
    for (uint32_t idx = 0; idx < n; idx++) {
      PendingCall* pc = slot_at(idx);
      uint64_t st = pc->state.load(std::memory_order_acquire);
      if (!(st & 1)) continue;
      if (!pc->state.compare_exchange_strong(st, st & ~1ull,
                                             std::memory_order_acq_rel)) {
        continue;  // a response beat us to it
      }
      pc->error_code = code;
      pc->error_text = text;
      if (pc->cb != nullptr) {
        pc->cb(pc, pc->cb_arg);  // cb owns pc
        continue;
      }
      pc->done.value.store(1, std::memory_order_release);
      Scheduler::butex_wake(&pc->done, INT32_MAX);
    }
  }

  void release_slot(uint32_t idx) { push_free(idx); }

 private:
  std::atomic<PendingCall*> slabs_[kMaxSlabs] = {};
  std::atomic<uint32_t> nslots_{0};
  std::atomic<uint64_t> free_head_{0};  // (aba_tag<<32) | (idx+1)
  std::mutex grow_mu_;

  uint32_t pop_free() {
    while (true) {
      uint64_t head = free_head_.load(std::memory_order_acquire);
      while ((uint32_t)head != 0) {
        uint32_t idx = (uint32_t)head - 1;
        uint32_t next = slot_at(idx)->next_free;
        uint64_t nhead = ((head >> 32) + 1) << 32 | next;
        if (free_head_.compare_exchange_weak(head, nhead,
                                             std::memory_order_acq_rel)) {
          return idx;
        }
      }
      if (!grow()) return UINT32_MAX;
    }
  }

  void push_free(uint32_t idx) {
    PendingCall* pc = slot_at(idx);
    uint64_t head = free_head_.load(std::memory_order_acquire);
    while (true) {
      pc->next_free = (uint32_t)head;
      uint64_t nhead = ((head >> 32) + 1) << 32 | (idx + 1);
      if (free_head_.compare_exchange_weak(head, nhead,
                                           std::memory_order_acq_rel)) {
        return;
      }
    }
  }

  bool grow() {
    std::lock_guard<std::mutex> g(grow_mu_);
    uint32_t n = nslots_.load(std::memory_order_acquire);
    if ((uint32_t)free_head_.load(std::memory_order_acquire) != 0) {
      return true;  // another thread grew while we waited
    }
    uint32_t slab_i = n >> kSlabBits;
    if (slab_i >= kMaxSlabs) return false;
    PendingCall* slab = new PendingCall[kSlabSize];
    slabs_[slab_i].store(slab, std::memory_order_release);
    nslots_.store(n + kSlabSize, std::memory_order_release);
    // seed indices [n+1, n+kSlabSize) through the freelist; hand out n
    // implicitly by pushing it too
    for (uint32_t i = 0; i < kSlabSize; i++) push_free(n + i);
    return true;
  }
};

// Return the call slot to its owning channel. The slot memory is never
// freed while the channel lives, so a straggling butex_wake on a recycled
// slot is harmlessly spurious (waiters re-check the value) — the same
// never-free property the old global pool provided, now per channel.
static void pc_free(PendingCall* pc) {
  pc->response.clear();
  pc->attachment.clear();
  pc->owner->release_slot(pc->slot_idx);
}

// ---------------------------------------------------------------------------
// NatSocket implementation
// ---------------------------------------------------------------------------

void NatSocket::release() {
  uint64_t prev = versioned_ref.fetch_sub(1, std::memory_order_acq_rel);
  if ((uint32_t)prev == 1) {
    // Deferred close (brpc defers to refcount-zero too, socket.cpp): the
    // fd number is only recycled once no fiber can still syscall on it,
    // so a stale writev can never land on a reused descriptor. The object
    // itself is NEVER freed (ResourcePool discipline) — its slot goes
    // back to the freelist for the next sock_create.
    if (fd >= 0) {
      ::close(fd);
      fd = -1;
    }
    if (channel != nullptr) {
      channel->release();
      channel = nullptr;
    }
    if (server != nullptr) {
      server->release();
      server = nullptr;
    }
    in_buf.clear();
    {
      std::lock_guard<std::mutex> g(write_mu);
      write_q.clear();
    }
    uint32_t idx = (uint32_t)(id & 0xffffffffu);
    std::lock_guard<std::mutex> g(g_sock_alloc_mu);
    g_sock_free.push_back(idx);
  }
}

void NatSocket::reset_for_reuse() {
  fd = -1;
  disp = nullptr;
  server = nullptr;
  channel = nullptr;
  failed.store(false, std::memory_order_relaxed);
  writing = false;
  defer_writes = false;
  epoll_events = 0;
  epollout.value.store(0, std::memory_order_relaxed);
  ring_ref.store(-1, std::memory_order_relaxed);
  ring_sending = false;
  ring_inflight = 0;
  py_raw.store(false, std::memory_order_relaxed);
  py_raw_seq = 0;
}

static RingListener* g_ring = nullptr;
static std::atomic<bool> g_use_ring{false};
static std::mutex g_ring_retry_mu;
static std::vector<uint64_t> g_ring_retry;  // sockets with unsubmitted sends
static std::atomic<bool> g_ring_draining{false};

void NatSocket::set_failed() {
  bool was = failed.exchange(true);
  if (was) return;
  {
    int64_t rr = ring_ref.exchange(-1, std::memory_order_acq_rel);
    if (rr >= 0 && g_ring != nullptr) {
      g_ring->unregister_file((int)(rr & 0xffffffff));  // cancels recv
    }
  }
  {
    std::lock_guard<std::mutex> g(write_mu);
    write_q.clear();
    writing = false;
    ring_sending = false;
    ring_inflight = 0;
  }
  if (fd >= 0) {
    epoll_ctl(disp->epfd, EPOLL_CTL_DEL, fd, nullptr);
    // shutdown (not close): in-flight reader/KeepWrite syscalls return
    // with EOF/EPIPE instead of racing a recycled fd number.
    ::shutdown(fd, SHUT_RDWR);
  }
  // wake any KeepWrite parked on EPOLLOUT
  epollout.value.fetch_add(1, std::memory_order_release);
  Scheduler::butex_wake(&epollout, INT32_MAX);
  if (py_raw.load(std::memory_order_acquire) && server != nullptr) {
    // tell the Python protocol stack to drop this connection's session
    PyRequest* r = new PyRequest();
    r->kind = 2;
    r->sock_id = id;
    server->enqueue_py(r);
  }
  if (channel != nullptr) {
    channel->fail_all(kEFAILEDSOCKET, "socket failed");
    if (channel->health_check_interval_ms > 0 &&
        !channel->closed.load(std::memory_order_acquire) &&
        !channel->hc_pending.exchange(true, std::memory_order_acq_rel)) {
      channel->add_ref();  // held by the revival chain
      TimerThread::instance()->schedule(health_check_fire, channel,
                                        channel->health_check_interval_ms);
    }
  }
  if (server != nullptr) server->connections.fetch_sub(1);
  sock_unregister(this);
  release();  // drop the registry's reference
}

void NatSocket::arm_epollout() {
  std::lock_guard<std::mutex> g(write_mu);
  if (failed.load(std::memory_order_acquire)) return;
  uint32_t want = EPOLLIN | EPOLLET | EPOLLOUT;
  if (epoll_events == want) return;
  struct epoll_event ev;
  ev.events = want;
  ev.data.u64 = id;
  if (epoll_ctl(disp->epfd, EPOLL_CTL_MOD, fd, &ev) == 0) epoll_events = want;
}

void NatSocket::disarm_epollout() {
  std::lock_guard<std::mutex> g(write_mu);
  if (failed.load(std::memory_order_acquire)) return;
  uint32_t want = EPOLLIN | EPOLLET;
  if (epoll_events == want) return;
  struct epoll_event ev;
  ev.events = want;
  ev.data.u64 = id;
  if (epoll_ctl(disp->epfd, EPOLL_CTL_MOD, fd, &ev) == 0) epoll_events = want;
}

bool NatSocket::flush_some() {
  while (true) {
    IOBuf batch;
    {
      std::lock_guard<std::mutex> g(write_mu);
      if (write_q.empty()) {
        writing = false;
        return true;
      }
      batch.append(std::move(write_q));  // take the whole queue: syscall
                                         // batching across responses
    }
    while (!batch.empty()) {
      ssize_t n = batch.cut_into_fd(fd);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
          // put leftovers back at the FRONT (later writes are behind us)
          std::lock_guard<std::mutex> g(write_mu);
          batch.append(std::move(write_q));
          write_q = std::move(batch);
          return false;
        }
        set_failed();
        return true;
      }
    }
  }
}

static void keep_write_fiber(void* arg) {
  NatSocket* s = (NatSocket*)arg;
  while (!s->failed.load(std::memory_order_acquire)) {
    if (s->flush_some()) break;  // common case: drained, no epoll_ctl
    int32_t expected = s->epollout.value.load(std::memory_order_acquire);
    s->arm_epollout();
    // second attempt covers a became-writable-before-arm race
    if (s->flush_some()) break;
    Scheduler::butex_wait(&s->epollout, expected);
  }
  s->disarm_epollout();
  s->release();
}

// Submits the front of write_q as one fixed-buffer send. Requires
// write_mu. Returns false when no buffer/SQE was free (retry later via
// the drain loop's retry list).
static bool ring_submit_locked(NatSocket* s) {
  if (s->ring_sending || s->write_q.empty()
      || s->failed.load(std::memory_order_acquire)) {
    return true;
  }
  int64_t rr = s->ring_ref.load(std::memory_order_acquire);
  if (rr < 0) return true;  // demoted/failed; bytes drain elsewhere
  uint16_t buf;
  char* dst = g_ring->acquire_send_buffer(&buf);
  if (dst == nullptr) return false;
  size_t n = s->write_q.length();
  if (n > RingListener::kSendBufSize) n = RingListener::kSendBufSize;
  s->write_q.copy_to(dst, n);  // straight into registered memory
  if (!g_ring->submit_send((int)(rr & 0xffffffff), (uint32_t)(rr >> 32),
                           s->id, buf, n)) {
    return false;
  }
  s->ring_sending = true;
  s->ring_inflight = n;
  return true;
}

static void ring_retry_later(uint64_t sock_id) {
  std::lock_guard<std::mutex> g(g_ring_retry_mu);
  g_ring_retry.push_back(sock_id);
}

int NatSocket::write(IOBuf&& frame) {
  if (failed.load(std::memory_order_acquire)) return -1;
  if (ring_ref.load(std::memory_order_acquire) >= 0) {
    // io_uring lane: queue + submit from registered send memory; ordering
    // is kept by the single-in-flight discipline.
    bool need_retry;
    {
      std::lock_guard<std::mutex> g(write_mu);
      if (failed.load(std::memory_order_acquire)) return -1;
      write_q.append(std::move(frame));
      need_retry = !ring_submit_locked(this);
    }
    if (need_retry) ring_retry_later(id);
    return 0;
  }
  bool become_writer = false;
  {
    std::lock_guard<std::mutex> g(write_mu);
    if (failed.load(std::memory_order_acquire)) return -1;
    write_q.append(std::move(frame));
    if (!writing) {
      writing = true;
      become_writer = true;
    }
  }
  if (!become_writer) return 0;  // active writer will drain us
  if (defer_writes) {
    // Batch mode: the writer fiber runs AFTER the currently-ready fibers,
    // so their appends coalesce into one writev.
    add_ref();
    Scheduler::instance()->spawn_detached_back(keep_write_fiber, this);
    return 0;
  }
  // Inline first attempt on the caller's thread/fiber (socket.cpp:1287);
  // leftovers go to a KeepWrite fiber waiting on EPOLLOUT.
  if (!flush_some()) {
    add_ref();
    Scheduler::instance()->spawn_detached(keep_write_fiber, this);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// Messenger — tpu_std cut loop + dispatch (InputMessenger role)
// ---------------------------------------------------------------------------

// Header + meta are encoded into ONE stack buffer and appended in a single
// call (one memcpy into the TLS share block, zero allocations); oversized
// error texts spill to a heap scratch, never truncate.
static void build_response_frame(IOBuf* out, int64_t cid, int32_t error_code,
                                 const std::string& error_text,
                                 IOBuf&& payload, IOBuf&& attachment) {
  size_t bound = 12 + response_meta_bound(error_text.size());
  char stack_buf[320];
  char* buf = bound <= sizeof(stack_buf) ? stack_buf : (char*)malloc(bound);
  size_t mlen = encode_response_meta_to(buf + 12, error_code,
                                        error_text.data(), error_text.size(),
                                        cid, (int64_t)attachment.length());
  memcpy(buf, kMagicRpc, 4);
  wr_be32(buf + 4,
          (uint32_t)(mlen + payload.length() + attachment.length()));
  wr_be32(buf + 8, (uint32_t)mlen);
  out->append(buf, 12 + mlen);
  if (buf != stack_buf) free(buf);
  out->append(std::move(payload));
  out->append(std::move(attachment));
}

static void build_request_frame(IOBuf* out, int64_t cid,
                                const std::string& service,
                                const std::string& method,
                                const char* payload, size_t payload_len,
                                const char* att, size_t att_len) {
  size_t bound = 12 + request_meta_bound(service.size(), method.size());
  char stack_buf[320];
  char* buf = bound <= sizeof(stack_buf) ? stack_buf : (char*)malloc(bound);
  size_t mlen = encode_request_meta_to(buf + 12, service.data(),
                                       service.size(), method.data(),
                                       method.size(), cid, (int64_t)att_len);
  memcpy(buf, kMagicRpc, 4);
  wr_be32(buf + 4, (uint32_t)(mlen + payload_len + att_len));
  wr_be32(buf + 8, (uint32_t)mlen);
  out->append(buf, 12 + mlen);
  if (buf != stack_buf) free(buf);
  if (payload_len) out->append(payload, payload_len);
  if (att_len) out->append(att, att_len);
}

// Minimal HTTP console on the native port (the multi-protocol-port
// discipline of server.cpp: one port tries every protocol): GET
// /health /status /vars /version answer from native counters so the
// native runtime is self-observable without the Python lane.
// Returns 1 = handled a request, 2 = need more bytes, 0 = not HTTP.
static int try_process_http(NatSocket* s, IOBuf* batch_out) {
  char head[8] = {0};
  size_t n = s->in_buf.length() < 8 ? s->in_buf.length() : 8;
  s->in_buf.copy_to(head, n);
  bool is_head = memcmp(head, "HEAD", 4) == 0;
  if (memcmp(head, "GET ", 4) != 0 && !is_head) {
    return 0;
  }
  if (s->server == nullptr) return 0;
  std::string raw;
  raw.resize(s->in_buf.length());
  s->in_buf.copy_to(&raw[0], raw.size());
  size_t end = raw.find("\r\n\r\n");
  if (end == std::string::npos) {
    return raw.size() > (64u << 10) ? 0 : 2;  // oversized header: bail
  }
  std::string headers = raw.substr(0, end);  // THIS request only, not any
  for (char& c : headers) c = (char)tolower((unsigned char)c);
  // a body (Content-Length) must be consumed too, or its bytes would be
  // parsed as the next frame and poison the stream
  size_t body_len = 0;
  size_t clpos = headers.find("content-length:");
  if (clpos != std::string::npos) {
    body_len = (size_t)strtoul(headers.c_str() + clpos + 15, nullptr, 10);
    if (body_len > (64u << 10)) return 0;  // absurd for a console GET
  }
  if (raw.size() < end + 4 + body_len) return 2;  // body not buffered yet
  s->in_buf.pop_front(end + 4 + body_len);
  size_t p0 = raw.find(' ');
  size_t p1 = raw.find(' ', p0 + 1);
  std::string path = (p0 != std::string::npos && p1 != std::string::npos)
                         ? raw.substr(p0 + 1, p1 - p0 - 1)
                         : "/";
  bool keep_alive = headers.find("connection: close") == std::string::npos;
  std::string body;
  int status = 200;
  if (path == "/health") {
    body = "OK\n";
  } else if (path == "/version") {
    body = "brpc_tpu_native/1\n";
  } else if (path == "/status" || path == "/vars") {
    char buf[512];
    uint64_t ring_recv = g_ring != nullptr ? g_ring->recv_completions() : 0;
    uint64_t ring_send = g_ring != nullptr ? g_ring->send_completions() : 0;
    snprintf(buf, sizeof(buf),
             "nat_server_requests : %llu\n"
             "nat_server_connections : %llu\n"
             "nat_scheduler_workers : %d\n"
             "nat_scheduler_switches : %llu\n"
             "nat_ring_recv_completions : %llu\n"
             "nat_ring_send_completions : %llu\n",
             (unsigned long long)s->server->requests.load(),
             (unsigned long long)s->server->connections.load(),
             Scheduler::instance()->nworkers(),
             (unsigned long long)Scheduler::instance()->total_switches(),
             (unsigned long long)ring_recv,
             (unsigned long long)ring_send);
    body = buf;
  } else {
    status = 404;
    body = "no such page on the native port (try /status /vars /health)\n";
  }
  char hdr[256];
  snprintf(hdr, sizeof(hdr),
           "HTTP/1.1 %d %s\r\nServer: brpc_tpu_native\r\n"
           "Content-Type: text/plain\r\nContent-Length: %zu\r\n"
           "Connection: %s\r\n\r\n",
           status, status == 200 ? "OK" : "Not Found", body.size(),
           keep_alive ? "keep-alive" : "close");
  batch_out->append(hdr, strlen(hdr));
  if (!is_head) batch_out->append(body.data(), body.size());
  // Even for Connection: close we answer and let the PEER close (EOF
  // then fails the socket) — closing ourselves would race the
  // asynchronous write lanes (KeepWrite fiber / io_uring send) and could
  // drop the response bytes still queued.
  return 1;
}

// Cut + process every complete frame in s->in_buf. Server requests run
// inline (responses batched into ONE socket write per read burst); client
// responses complete pending calls.
// With defer_out != nullptr, response bytes are parked there instead of
// being written per read burst — the epoll dispatcher passes its per-round
// accumulator so one writev covers EVERY burst of the round (cross-burst
// syscall batching; the client-side defer_writes twin of this discipline).
// Forward everything buffered on a raw-mode socket to the py lane as one
// ordered chunk.
static void forward_raw_chunk(NatSocket* s) {
  if (s->in_buf.empty()) return;
  PyRequest* r = new PyRequest();
  r->kind = 1;
  r->sock_id = s->id;
  r->cid = (int64_t)(++s->py_raw_seq);
  r->payload = s->in_buf.to_string();
  s->in_buf.clear();
  s->server->enqueue_py(r);
}

static bool process_input(NatSocket* s, IOBuf* defer_out = nullptr) {
  if (s->py_raw.load(std::memory_order_relaxed)) {
    forward_raw_chunk(s);
    return true;
  }
  IOBuf batch_out;
  bool ok = true;
  while (true) {
    if (s->in_buf.length() < 12) {
      // Short first message (e.g. inline redis "PING\r\n"): if the bytes
      // already rule out the tpu_std magic, hand off to raw mode now
      // rather than deadlocking on a 12-byte header that never comes.
      if (!s->in_buf.empty() && s->server != nullptr &&
          s->server->raw_fallback && s->server->py_lane_enabled) {
        char pfx[4];
        size_t n = s->in_buf.length() < 4 ? s->in_buf.length() : 4;
        s->in_buf.copy_to(pfx, n);
        if (memcmp(pfx, kMagicRpc, n) != 0) {
          s->py_raw.store(true, std::memory_order_release);
          forward_raw_chunk(s);
        }
      }
      break;
    }
    char header[12];
    s->in_buf.copy_to(header, 12);
    if (memcmp(header, kMagicRpc, 4) != 0) {
      // Not tpu_std. On a raw-fallback server the Python protocol stack
      // takes over this connection for good (sniff once, remember);
      // otherwise try the native console, else protocol error.
      if (s->server != nullptr && s->server->raw_fallback &&
          s->server->py_lane_enabled) {
        s->py_raw.store(true, std::memory_order_release);
        forward_raw_chunk(s);
        break;
      }
      int hrc = try_process_http(s, &batch_out);
      if (hrc == 1) continue;   // handled; keep cutting
      if (hrc == 2) break;      // incomplete request: wait for bytes
      ok = false;  // not tpu_std, not HTTP: protocol error
      break;
    }
    uint32_t body = rd_be32(header + 4);
    uint32_t meta_size = rd_be32(header + 8);
    if (meta_size > body || body > (512u << 20)) {
      ok = false;
      break;
    }
    if (s->in_buf.length() < 12 + (size_t)body) break;
    s->in_buf.pop_front(12);
    // decode straight from the buffer (fetch: contiguous view or stack
    // copy; meta blobs are tens of bytes — no heap string per frame)
    char meta_stack[512];
    const char* meta_ptr;
    std::string meta_heap;
    if (meta_size <= sizeof(meta_stack)) {
      meta_ptr = s->in_buf.fetch(meta_stack, meta_size);
    } else {
      meta_heap.resize(meta_size);
      s->in_buf.copy_to(&meta_heap[0], meta_size);
      meta_ptr = meta_heap.data();
    }
    RpcMetaN meta;
    if (!decode_meta(meta_ptr, meta_size, &meta)) {
      ok = false;
      break;
    }
    size_t att_size = (size_t)meta.attachment_size;
    if (att_size > body - meta_size) {
      ok = false;
      break;
    }
    // handler lookup BEFORE the meta pop: the py lane needs a copy of the
    // raw meta bytes, but only requests that actually go to the py lane
    // should pay it — native-handled frames stay allocation-free
    NatServer* srv =
        (meta.has_request && s->server != nullptr) ? s->server : nullptr;
    auto it = srv != nullptr ? srv->handlers.end()
                             : decltype(srv->handlers.end())();
    std::string meta_copy;
    if (srv != nullptr) {
      char keybuf[256];
      const std::string& sn = meta.request.service_name;
      const std::string& mn = meta.request.method_name;
      if (sn.size() + mn.size() + 1 <= sizeof(keybuf)) {
        memcpy(keybuf, sn.data(), sn.size());
        keybuf[sn.size()] = '.';
        memcpy(keybuf + sn.size() + 1, mn.data(), mn.size());
        it = srv->handlers.find(
            std::string_view(keybuf, sn.size() + 1 + mn.size()));
      }
      if (it == srv->handlers.end() && srv->py_lane_enabled) {
        meta_copy.assign(meta_ptr, meta_size);  // py lane re-parses it
      }
    }
    s->in_buf.pop_front(meta_size);
    size_t payload_size = body - meta_size - att_size;
    IOBuf payload, attachment;
    s->in_buf.cut_into(&payload, payload_size);
    s->in_buf.cut_into(&attachment, att_size);

    if (srv != nullptr) {
      srv->requests.fetch_add(1, std::memory_order_relaxed);
      if (it != srv->handlers.end()) {
        NativeHandlerCtx ctx;
        ctx.req_payload = &payload;
        ctx.req_attachment = &attachment;
        it->second(ctx);
        build_response_frame(&batch_out, meta.correlation_id, ctx.error_code,
                             ctx.error_text, std::move(ctx.resp_payload),
                             std::move(ctx.resp_attachment));
      } else if (srv->py_lane_enabled) {
        PyRequest* r = new PyRequest();
        r->sock_id = s->id;
        r->cid = meta.correlation_id;
        r->compress_type = meta.compress_type;
        r->service = meta.request.service_name;
        r->method = meta.request.method_name;
        r->payload = payload.to_string();
        r->attachment = attachment.to_string();
        r->meta_bytes = std::move(meta_copy);
        srv->enqueue_py(r);
      } else {
        build_response_frame(&batch_out, meta.correlation_id, kENOSERVICE,
                             "no such service/method on native port",
                             IOBuf(), IOBuf());
      }
    } else if (s->channel != nullptr) {
      PendingCall* pc = s->channel->take_pending(meta.correlation_id);
      if (pc != nullptr) {
        pc->error_code = meta.has_response ? meta.response.error_code : 0;
        pc->error_text = meta.has_response ? meta.response.error_text : "";
        pc->response = std::move(payload);
        pc->attachment = std::move(attachment);
        if (pc->cb != nullptr) {
          pc->cb(pc, pc->cb_arg);  // async completion; cb owns pc
        } else {
          pc->done.value.store(1, std::memory_order_release);
          Scheduler::butex_wake(&pc->done, INT32_MAX);
        }
      }
    }
  }
  if (!batch_out.empty()) {
    if (defer_out != nullptr) {
      defer_out->append(std::move(batch_out));
    } else {
      s->write(std::move(batch_out));
    }
  }
  return ok;
}

// Drain an fd to EAGAIN and process every complete frame, ON THE CALLING
// THREAD. The epoll dispatcher calls this inline (the bypass-loop shape,
// and the fork's wait_task ring-drain discipline, task_group.cpp:158-169):
// every process_input consumer is non-blocking by contract — native
// handlers must not block, py-lane delivery is a brief mutex push, and
// client completions are a butex wake — so a reader-fiber handoff per
// event burst (spawn + remote-queue + futex wake) only added latency.
// Single-reader safety holds because a socket belongs to exactly one
// dispatcher loop.
// Returns true when response bytes were queued (the caller flushes them at
// end of round).
static bool drain_socket_inline(NatSocket* s) {
  IOBuf acc;  // responses of EVERY burst in this drain, flushed as one
  bool dead = false;
  while (!s->failed.load(std::memory_order_acquire)) {
    ssize_t n = s->in_buf.append_from_fd(s->fd, 65536);
    if (n > 0) {
      if (!process_input(s, &acc)) {
        dead = true;
        break;
      }
      continue;
    }
    if (n < 0 && (errno == EAGAIN || errno == EWOULDBLOCK)) break;
    if (n < 0 && errno == EINTR) continue;
    dead = true;  // EOF or hard error
    break;
  }
  bool queued = false;
  if (!acc.empty() && !dead) {
    std::lock_guard<std::mutex> g(s->write_mu);
    if (!s->failed.load(std::memory_order_acquire)) {
      s->write_q.append(std::move(acc));
      queued = true;
    }
  }
  if (dead || s->failed.load(std::memory_order_acquire)) {
    s->set_failed();
    return false;
  }
  return queued;
}

// After a socket leaves the ring lane with bytes still queued, no sender
// owns them (ring_submit_locked no-ops on demoted sockets): hand them to
// the epoll KeepWrite lane or the peer hangs waiting for a response.
static void kick_epoll_writer_if_stranded(NatSocket* s) {
  bool kick = false;
  {
    std::lock_guard<std::mutex> g(s->write_mu);
    if (s->ring_ref.load(std::memory_order_acquire) < 0 &&
        !s->write_q.empty() && !s->writing && !s->ring_sending &&
        !s->failed.load(std::memory_order_acquire)) {
      s->writing = true;
      kick = true;
    }
  }
  if (kick) {
    s->add_ref();
    Scheduler::instance()->spawn_detached(keep_write_fiber, s);
  }
}

// Moves a ring socket to the epoll lane (rearm impossible / multishot
// unsupported); the CAS makes demotion and set_failed mutually exclusive.
static void ring_demote_to_epoll(NatSocket* s, int64_t rr) {
  if (s->ring_ref.compare_exchange_strong(rr, -1)) {
    g_ring->unregister_file((int)(rr & 0xffffffff));
    s->disp->add_consumer(s);
    kick_epoll_writer_if_stranded(s);
  }
}

// Drains harvested ring completions — the wait_task drain of the fork
// (task_group.cpp:158-169): recv bytes feed the SAME cut loop the epoll
// readers use; send completions recycle fixed buffers and launch the next
// chunk. Registered as a scheduler idle hook; one worker drains at a time
// so per-socket completion order is preserved.
static bool ring_drain() {
  if (g_ring == nullptr) return false;
  if (g_ring_draining.exchange(true, std::memory_order_acquire)) {
    return false;
  }
  bool did = false;
  RingCompletion c;
  while (g_ring->pop_completion(&c)) {
    did = true;
    NatSocket* s = sock_address(c.tag);
    if (c.kind == 0) {  // recv
      if (c.res > 0) {
        if (s != nullptr && !s->failed.load(std::memory_order_acquire)) {
          s->in_buf.append(g_ring->buffer_data(c.buf_id), (size_t)c.res);
          g_ring->recycle_buffer(c.buf_id);
          int64_t rr = s->ring_ref.load(std::memory_order_acquire);
          if (!process_input(s)) {
            s->set_failed();
          } else if (!c.more && rr >= 0 &&
                     !g_ring->rearm_recv((int)(rr & 0xffffffff),
                                         (uint32_t)(rr >> 32), s->id)) {
            ring_demote_to_epoll(s, rr);  // SQ full: don't go deaf
          }
        } else {
          g_ring->recycle_buffer(c.buf_id);  // owner gone: recycle only
        }
      } else if (s != nullptr) {
        int64_t rr = s->ring_ref.load(std::memory_order_acquire);
        if (c.res == -ENOBUFS) {
          // provided buffers were exhausted; they're recycled as we
          // drain, so re-arm and keep going
          if (rr >= 0 && !g_ring->rearm_recv((int)(rr & 0xffffffff),
                                             (uint32_t)(rr >> 32), s->id)) {
            ring_demote_to_epoll(s, rr);
          }
        } else if (c.res == -EINVAL && rr >= 0) {
          // kernel lacks multishot recv (pre-6.0): demote this
          // connection to the epoll lane instead of killing it
          ring_demote_to_epoll(s, rr);
        } else if (!c.more) {
          s->set_failed();  // EOF (0) or hard error
        }
      }
    } else {  // send
      g_ring->recycle_send_buffer(c.send_buf);
      if (s != nullptr) {
        if (c.res < 0) {
          s->set_failed();
        } else {
          bool need_retry;
          {
            std::lock_guard<std::mutex> g(s->write_mu);
            size_t done = (size_t)c.res;
            if (done > s->ring_inflight) done = s->ring_inflight;
            s->write_q.pop_front(done);
            s->ring_sending = false;
            s->ring_inflight = 0;
            need_retry = !ring_submit_locked(s);
          }
          if (need_retry) ring_retry_later(s->id);
          // a demotion landing between completions leaves queued bytes
          // with no sender: hand them to the epoll write lane
          kick_epoll_writer_if_stranded(s);
        }
      }
    }
    if (s != nullptr) s->release();
  }
  // retry sends that couldn't get a buffer/SQE earlier
  std::vector<uint64_t> retry;
  {
    std::lock_guard<std::mutex> g(g_ring_retry_mu);
    retry.swap(g_ring_retry);
  }
  for (uint64_t sid : retry) {
    NatSocket* s = sock_address(sid);
    if (s == nullptr) continue;
    bool again;
    {
      std::lock_guard<std::mutex> g(s->write_mu);
      again = !ring_submit_locked(s);
    }
    if (again) ring_retry_later(sid);
    kick_epoll_writer_if_stranded(s);
    s->release();
  }
  g_ring_draining.store(false, std::memory_order_release);
  return did;
}


// Put a freshly-connected fd on the ring lane when it is enabled (both
// directions then ride io_uring and drain on the poller — the accept
// path's twin). Returns true when the ring owns the socket's reads.
static bool try_ring_adopt(NatSocket* s) {
  if (!g_use_ring.load(std::memory_order_acquire) || g_ring == nullptr) {
    return false;
  }
  uint32_t gen = 0;
  int fidx = g_ring->register_file(s->fd, &gen);
  if (fidx < 0) return false;
  int64_t rr = ((int64_t)gen << 32) | (uint32_t)fidx;
  s->ring_ref.store(rr, std::memory_order_release);
  if (g_ring->rearm_recv(fidx, gen, s->id)) return true;
  s->ring_ref.store(-1, std::memory_order_release);
  g_ring->unregister_file(fidx);
  return false;
}

void Dispatcher::accept_loop(int lfd, NatServer* srv) {
  while (true) {
    int cfd = accept4(lfd, nullptr, nullptr, SOCK_NONBLOCK);
    if (cfd < 0) break;
    int one = 1;
    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    NatSocket* s = sock_create();  // holds the initial reference
    if (s == nullptr) {
      ::close(cfd);
      break;
    }
    s->fd = cfd;
    s->disp = pick_dispatcher();  // shard across the loop pool
    s->server = srv;
    srv->add_ref();  // released when the socket slot is recycled
    srv->connections.fetch_add(1);
    if (try_ring_adopt(s)) continue;  // the ring owns this read path
    s->disp->add_consumer(s);
  }
}

void Dispatcher::run() {
  std::vector<struct epoll_event> events(256);
  std::vector<NatSocket*> flush_list;  // queued output; flushed per round
  std::vector<Fiber*> wake_batch;      // fibers readied this round
  while (!stop.load(std::memory_order_acquire)) {
    int n = epoll_wait(epfd, events.data(), (int)events.size(), 100);
    // every butex wake / spawn from this round coalesces into one
    // remote-queue push + one signal per worker (not per completion)
    Scheduler::instance()->arm_wake_batch(&wake_batch);
    for (int i = 0; i < n; i++) {
      uint64_t data = events[i].data.u64;
      if (data == (uint64_t)-1) {  // wake eventfd
        uint64_t drain;
        ssize_t rc = ::read(wake_fd, &drain, 8);
        (void)rc;
        continue;
      }
      if (data < (1ull << 32)) {  // listener (socket ids are >= 2^32)
        int lfd = (int)data;
        NatServer* srv;
        {
          std::lock_guard<std::mutex> g(listen_mu);
          auto it = listeners.find(lfd);
          srv = (it == listeners.end()) ? nullptr : it->second;
          // ref taken UNDER the lock: a racing server_stop erases the
          // listener then releases its registration reference — without
          // this, accept_loop could run on a freed server
          if (srv != nullptr) srv->add_ref();
        }
        if (srv != nullptr) {
          accept_loop(lfd, srv);
          srv->release();
        }
        continue;
      }
      NatSocket* s = sock_address(data);
      if (s == nullptr) continue;
      if (events[i].events & EPOLLOUT) {
        s->epollout.value.fetch_add(1, std::memory_order_release);
        Scheduler::butex_wake(&s->epollout, INT32_MAX);
      }
      if (events[i].events & (EPOLLIN | EPOLLHUP | EPOLLERR)) {
        if (drain_socket_inline(s)) {
          flush_list.push_back(s);  // keep the ref until the flush below
          continue;
        }
      }
      s->release();
    }
    // End-of-round flush: one writev per socket covering every burst the
    // round produced (cross-burst syscall batching).
    for (NatSocket* s : flush_list) {
      bool become_writer = false;
      {
        std::lock_guard<std::mutex> g(s->write_mu);
        if (!s->write_q.empty() && !s->writing &&
            !s->failed.load(std::memory_order_acquire)) {
          s->writing = true;
          become_writer = true;
        }
      }
      if (become_writer && !s->flush_some()) {
        s->add_ref();
        Scheduler::instance()->spawn_detached(keep_write_fiber, s);
      }
      s->release();
    }
    flush_list.clear();
    Scheduler::instance()->flush_wake_batch();
  }
}

// ---------------------------------------------------------------------------
// Server / channel lifecycle + C API
// ---------------------------------------------------------------------------

// Dispatcher pool (-event_dispatcher_num analog, event_dispatcher.cpp:30):
// sockets are sharded round-robin across N independent epoll loops so the
// inline read/process path scales past one core. Listeners live on
// loop 0; accepted/connected sockets go to the next loop in turn.
static std::vector<Dispatcher*> g_disps;
static Dispatcher* g_disp = nullptr;  // g_disps[0]: listeners + console
static std::atomic<uint32_t> g_disp_rr{0};
static int g_disp_count = 0;  // 0 = auto (set before first runtime use)
static NatServer* g_rpc_server = nullptr;
static std::mutex g_rt_mu;

static Dispatcher* pick_dispatcher() {
  if (g_disps.size() == 1) return g_disps[0];
  uint32_t i = g_disp_rr.fetch_add(1, std::memory_order_relaxed);
  return g_disps[i % g_disps.size()];
}

static int ensure_runtime(int nworkers) {
  std::lock_guard<std::mutex> g(g_rt_mu);
  if (!Scheduler::instance()->started()) {
    if (nworkers <= 0) {
      unsigned hw = std::thread::hardware_concurrency();
      nworkers = hw > 1 ? (int)hw : 1;
      if (nworkers > 16) nworkers = 16;  // brpc-class default; beyond
      // this the random-steal idle loops cost more than they serve
    }
    Scheduler::instance()->start(nworkers);
  }
  if (g_disps.empty()) {
    int n = g_disp_count;
    if (n <= 0) {
      unsigned hw = std::thread::hardware_concurrency();
      n = hw >= 16 ? 4 : hw >= 4 ? 2 : 1;
    }
    for (int i = 0; i < n; i++) {
      Dispatcher* d = new Dispatcher();
      if (d->start() != 0) {
        delete d;
        if (g_disps.empty()) return -1;
        break;  // run with what we have
      }
      g_disps.push_back(d);
    }
    g_disp = g_disps[0];
  }
  return 0;
}

extern "C" {
void* nat_channel_open(const char* ip, int port, int unused,
                       int batch_writes, int connect_timeout_ms,
                       int health_check_ms);
void nat_channel_close(void* h);
}  // forward decls for the bench harness

// Shared client-bench harness: channel open, timed run, stop broadcast,
// fiber join via done_count, and the stack-Butex destruction handshake
// (scheduler.cpp join(): once we hold/release the butex mutex, the last
// waker is done touching it). spawn(ch, stop, total, done) returns the
// number of fibers it started.
template <typename SpawnFn, typename OnStopFn>
static double run_client_bench(const char* ip, int port, int nconn,
                               double seconds, uint64_t* out_requests,
                               SpawnFn spawn, OnStopFn on_stop) {
  std::atomic<bool> stop{false};
  std::atomic<uint64_t> total{0};
  Butex done_count;
  std::vector<NatChannel*> channels;
  int nfibers = 0;
  for (int c = 0; c < nconn; c++) {
    NatChannel* ch = (NatChannel*)nat_channel_open(ip, port, 0, 1, 0, 0);
    if (ch == nullptr) continue;
    channels.push_back(ch);
    nfibers += spawn(ch, &stop, &total, &done_count);
  }
  auto t0 = std::chrono::steady_clock::now();
  std::this_thread::sleep_for(
      std::chrono::milliseconds((int64_t)(seconds * 1000)));
  stop.store(true);
  on_stop();
  while (done_count.value.load(std::memory_order_acquire) < nfibers) {
    Scheduler::butex_wait(&done_count,
                          done_count.value.load(std::memory_order_acquire));
  }
  // destruction handshake: the last fiber may still be inside butex_wake
  { std::lock_guard<std::mutex> g(done_count.mu); }
  auto t1 = std::chrono::steady_clock::now();
  double dt = std::chrono::duration<double>(t1 - t0).count();
  for (NatChannel* ch : channels) nat_channel_close(ch);
  if (out_requests) *out_requests = total.load();
  return dt > 0 ? (double)total.load() / dt : 0.0;
}


extern "C" {

// -event_dispatcher_num analog: set the epoll-loop pool size BEFORE the
// runtime starts (0 = auto from hardware_concurrency). Returns the count
// in effect.
int nat_rpc_set_dispatchers(int n) {
  std::lock_guard<std::mutex> g(g_rt_mu);
  if (g_disps.empty() && n >= 0) g_disp_count = n;
  return g_disps.empty() ? g_disp_count : (int)g_disps.size();
}

// Start the native RPC server. enable_native_echo registers the built-in
// EchoService.Echo handler (zero-copy: response payload/attachment share
// the request's IOBuf blocks). Python services ride the py lane.
int nat_rpc_server_start(const char* ip, int port, int nworkers,
                         int enable_native_echo) {
  {
    std::lock_guard<std::mutex> g(g_rt_mu);
    if (g_rpc_server != nullptr) return -1;
  }
  if (ensure_runtime(nworkers) != 0) return -1;
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, ip, &addr.sin_addr);
  if (bind(fd, (struct sockaddr*)&addr, sizeof(addr)) != 0 ||
      listen(fd, 1024) != 0) {
    ::close(fd);
    return -1;
  }
  socklen_t alen = sizeof(addr);
  getsockname(fd, (struct sockaddr*)&addr, &alen);

  NatServer* srv = new NatServer();
  srv->listen_fd = fd;
  srv->port = ntohs(addr.sin_port);
  srv->disp = g_disp;
  srv->py_lane_enabled = true;
  if (enable_native_echo) {
    srv->handlers["EchoService.Echo"] = [](NativeHandlerCtx& ctx) {
      // echo: hand the request blocks straight back (no copy)
      ctx.resp_payload.append(std::move(*ctx.req_payload));
      ctx.resp_attachment.append(std::move(*ctx.req_attachment));
    };
  }
  {
    std::lock_guard<std::mutex> g(g_rt_mu);
    if (g_rpc_server != nullptr) {  // lost a concurrent-start race
      ::close(fd);
      srv->release();
      return -1;
    }
    g_rpc_server = srv;
  }
  g_disp->add_listener(fd, srv);
  return srv->port;
}

void nat_rpc_server_stop() {
  NatServer* srv;
  {
    std::lock_guard<std::mutex> g(g_rt_mu);
    srv = g_rpc_server;
    if (srv == nullptr) return;
    g_rpc_server = nullptr;
  }
  // remove the listener before failing sockets so no new conns register
  epoll_ctl(g_disp->epfd, EPOLL_CTL_DEL, srv->listen_fd, nullptr);
  {
    std::lock_guard<std::mutex> g(g_disp->listen_mu);
    g_disp->listeners.erase(srv->listen_fd);
  }
  ::close(srv->listen_fd);
  // stop the python lane (wakes all waiters empty-handed)
  {
    std::lock_guard<std::mutex> g(srv->py_mu);
    srv->py_stopping = true;
  }
  srv->py_cv.notify_all();
  // fail remaining server-side connections: scan the slot space (bounded
  // by the high-water mark) and take a safe reference before failing
  uint32_t hwm;
  {
    std::lock_guard<std::mutex> g(g_sock_alloc_mu);
    hwm = g_sock_next_idx;
  }
  for (uint32_t idx = 0; idx < hwm; idx++) {
    NatSocket* cand = sock_at(idx);
    if (cand == nullptr) continue;
    uint64_t id = cand->id;  // racy snapshot; sock_address validates it
    NatSocket* s = sock_address(id);
    if (s == nullptr) continue;
    if (s->server == srv) s->set_failed();
    s->release();
  }
  // drain queued python-lane requests under the lane lock
  {
    std::lock_guard<std::mutex> g(srv->py_mu);
    for (PyRequest* r : srv->py_q) delete r;
    srv->py_q.clear();
  }
  srv->release();  // the registration reference; sockets/takers may
                   // still hold theirs — the last one deletes
}

// Enable the multi-protocol raw fallback on the running server: framing
// the native cut loop doesn't recognize is handed to the Python protocol
// stack as ordered raw chunks instead of failing the socket. Call right
// after nat_rpc_server_start, before clients connect.
int nat_rpc_server_enable_raw_fallback(int enable) {
  std::lock_guard<std::mutex> g(g_rt_mu);
  NatServer* srv = g_rpc_server;
  if (srv == nullptr) return -1;
  srv->raw_fallback = (enable != 0);
  return 0;
}

int32_t nat_req_kind(void* h) { return ((PyRequest*)h)->kind; }

uint64_t nat_rpc_server_requests() {
  std::lock_guard<std::mutex> g(g_rt_mu);
  return g_rpc_server ? g_rpc_server->requests.load() : 0;
}

uint64_t nat_rpc_server_connections() {
  std::lock_guard<std::mutex> g(g_rt_mu);
  return g_rpc_server ? g_rpc_server->connections.load() : 0;
}

// ---- Python lane (usercode on pthreads) ----

void* nat_take_request(int timeout_ms) {
  NatServer* srv;
  {
    std::lock_guard<std::mutex> g(g_rt_mu);
    srv = g_rpc_server;
    if (srv == nullptr) return nullptr;
    srv->add_ref();  // keeps the server alive across the blocking wait
  }
  void* r = srv->take_py(timeout_ms);
  srv->release();
  return r;
}

const char* nat_req_field(void* h, int which, size_t* len) {
  PyRequest* r = (PyRequest*)h;
  const std::string* s = nullptr;
  switch (which) {
    case 0: s = &r->service; break;
    case 1: s = &r->method; break;
    case 2: s = &r->payload; break;
    case 3: s = &r->attachment; break;
    case 4: s = &r->meta_bytes; break;
    default: *len = 0; return nullptr;
  }
  *len = s->size();
  return s->data();
}

int64_t nat_req_cid(void* h) { return ((PyRequest*)h)->cid; }
int32_t nat_req_compress(void* h) { return ((PyRequest*)h)->compress_type; }
uint64_t nat_req_sock_id(void* h) { return ((PyRequest*)h)->sock_id; }
void nat_req_free(void* h) { delete (PyRequest*)h; }

// Raw write of pre-framed bytes onto a live connection — lets the Python
// protocol layer (send_rpc_response with its full feature set) answer
// py-lane requests through the native Socket write queue.
int nat_sock_write(uint64_t sock_id, const char* data, size_t len) {
  NatSocket* s = sock_address(sock_id);
  if (s == nullptr) return -1;
  IOBuf out;
  out.append(data, len);
  int rc = s->write(std::move(out));
  s->release();
  return rc;
}

int nat_sock_set_failed(uint64_t sock_id) {
  NatSocket* s = sock_address(sock_id);
  if (s == nullptr) return -1;
  s->set_failed();
  s->release();
  return 0;
}

// Respond to a py-lane request and free it. Returns 0, or -1 if the
// connection is gone.
int nat_respond(void* h, int32_t error_code, const char* error_text,
                const char* payload, size_t payload_len, const char* att,
                size_t att_len) {
  PyRequest* r = (PyRequest*)h;
  NatSocket* s = sock_address(r->sock_id);
  int rc = -1;
  if (s != nullptr) {
    IOBuf out, pay, attach;
    if (payload_len) pay.append(payload, payload_len);
    if (att_len) attach.append(att, att_len);
    build_response_frame(&out, r->cid, error_code,
                         error_text ? error_text : "", std::move(pay),
                         std::move(attach));
    rc = s->write(std::move(out));
    s->release();
  }
  delete r;
  return rc;
}

}  // extern "C" (pause: the helpers below are C++ internals)

// ---- client channel ----

// Non-blocking connect with a deadline — the bthread_connect discipline
// (bthread/fd.cpp:119-170): EINPROGRESS, poll for writability, then
// SO_ERROR. Returns a connected nonblocking fd (TCP_NODELAY set) or -1.
static int dial_nonblocking(const char* ip, int port, int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM | SOCK_NONBLOCK, 0);
  if (fd < 0) return -1;
  struct sockaddr_in addr;
  memset(&addr, 0, sizeof(addr));
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, ip, &addr.sin_addr);
  int rc = connect(fd, (struct sockaddr*)&addr, sizeof(addr));
  if (rc != 0) {
    if (errno != EINPROGRESS) {
      ::close(fd);
      return -1;
    }
    struct pollfd p;
    p.fd = fd;
    p.events = POLLOUT;
    p.revents = 0;
    int t = timeout_ms > 0 ? timeout_ms : 10000;  // sane default guard
    if (poll(&p, 1, t) != 1) {
      ::close(fd);  // timed out (no blocking connect with no deadline:
      return -1;    // the round-2 nat_channel_open gap)
    }
    int err = 0;
    socklen_t l = sizeof(err);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &l);
    if (err != 0) {
      ::close(fd);
      return -1;
    }
  }
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// Borrow the channel's socket, re-dialing a failed single connection on
// demand (Channel reuse-after-failure semantics). Returns a referenced
// socket or nullptr (closed channel / peer unreachable).
static NatSocket* channel_socket(NatChannel* ch, int max_dial_ms = 0) {
  NatSocket* s = sock_address(ch->sock_id.load(std::memory_order_acquire));
  if (s != nullptr || ch->closed.load(std::memory_order_acquire) ||
      ch->peer_port == 0) {
    return s;
  }
  // Dial OUTSIDE reconnect_mu — poll() can block up to the connect
  // timeout, and close()/other callers must not wait behind it. The
  // publish step below re-checks under the lock; a losing racer just
  // closes its dial. Re-dials default to a 1s guard (not the 10s
  // first-open guard) so a blackholed peer doesn't pin a worker long;
  // callers with a deadline pass max_dial_ms to clamp further.
  int t_ms = ch->connect_timeout_ms > 0 ? ch->connect_timeout_ms : 1000;
  if (max_dial_ms > 0 && max_dial_ms < t_ms) t_ms = max_dial_ms;
  int fd = dial_nonblocking(ch->peer_ip.c_str(), ch->peer_port, t_ms);
  if (fd < 0) return nullptr;
  std::lock_guard<std::mutex> g(ch->reconnect_mu);
  s = sock_address(ch->sock_id.load(std::memory_order_acquire));
  if (s != nullptr || ch->closed.load(std::memory_order_acquire)) {
    ::close(fd);  // lost the race (or the channel closed mid-dial)
    return s;
  }
  NatSocket* ns = sock_create();
  if (ns == nullptr) {
    ::close(fd);
    return nullptr;
  }
  ns->fd = fd;
  ns->disp = pick_dispatcher();
  ns->channel = ch;
  ch->add_ref();  // the socket's channel reference
  ns->defer_writes = ch->defer_writes_flag;
  ch->sock_id.store(ns->id, std::memory_order_release);
  ns->add_ref();  // the caller's borrowed reference, taken BEFORE epoll
                  // can fail the socket
  ns->disp->add_consumer(ns);  // client sockets stay on epoll (above)
  return ns;
}

// Background revival of a failed channel connection (the health-check
// thread role, health_check.cpp:146-237): re-dial every interval until
// the channel closes or the connection is back. The dial can block up to
// connect_timeout_ms, so it runs on a scheduler FIBER — timer callbacks
// must not block (a blackholed peer would stall every armed deadline).
static void health_check_dial_fiber(void* raw) {
  NatChannel* ch = (NatChannel*)raw;
  if (ch->closed.load(std::memory_order_acquire)) {
    ch->hc_pending.store(false, std::memory_order_release);
    ch->release();
    return;
  }
  NatSocket* s = channel_socket(ch);
  if (s != nullptr) {  // revived (or never died)
    s->release();
    ch->hc_pending.store(false, std::memory_order_release);
    ch->release();
    return;
  }
  TimerThread::instance()->schedule(health_check_fire, ch,
                                    ch->health_check_interval_ms);
}

static void health_check_fire(void* raw) {
  Scheduler::instance()->spawn_detached(health_check_dial_fiber, raw);
}

extern "C" {

// Per-call deadline (the bthread_timer_add arming of controller.cpp:605):
// the timer races the response through the SAME pending-bit CAS — whoever
// wins owns the completion, so a late reply after a timeout (or a timeout
// firing after completion) is a harmless no-op. No unschedule needed.
struct CallTimeout {
  NatChannel* ch;  // holds a reference until the timer fires
  int64_t cid;
};

static void call_timeout_work(void* raw) {
  CallTimeout* t = (CallTimeout*)raw;
  PendingCall* pc = t->ch->take_pending(t->cid);
  if (pc != nullptr) {
    pc->error_code = kERPCTIMEDOUT;
    pc->error_text = "rpc timed out";
    if (pc->cb != nullptr) {
      pc->cb(pc, pc->cb_arg);  // cb owns pc
    } else {
      pc->done.value.store(1, std::memory_order_release);
      Scheduler::butex_wake(&pc->done, INT32_MAX);
    }
  }
  t->ch->release();
  delete t;
}

// The completion callback may run arbitrary embedder code (the Python
// acall trampoline takes the GIL): run it on a scheduler fiber — timer
// callbacks must not block or every later deadline fires late.
static void call_timeout_fire(void* raw) {
  Scheduler::instance()->spawn_detached(call_timeout_work, raw);
}

static void arm_call_timeout(NatChannel* ch, int64_t cid, int timeout_ms) {
  ch->add_ref();
  TimerThread::instance()->schedule(call_timeout_fire,
                                    new CallTimeout{ch, cid}, timeout_ms);
}

void* nat_channel_open(const char* ip, int port, int nworkers,
                       int batch_writes, int connect_timeout_ms,
                       int health_check_ms) {
  if (ensure_runtime(nworkers) != 0) return nullptr;
  int fd = dial_nonblocking(ip, port, connect_timeout_ms);
  if (fd < 0) return nullptr;

  NatChannel* ch = new NatChannel();
  ch->peer_ip = ip;
  ch->peer_port = port;
  ch->connect_timeout_ms = connect_timeout_ms;
  ch->health_check_interval_ms = health_check_ms;
  ch->defer_writes_flag = (batch_writes != 0);
  NatSocket* s = sock_create();
  if (s == nullptr) {
    ::close(fd);
    ch->release();
    return nullptr;
  }
  s->fd = fd;
  s->disp = pick_dispatcher();
  s->channel = ch;
  ch->add_ref();  // the socket's reference, dropped in NatSocket::release
  s->defer_writes = (batch_writes != 0);
  ch->sock_id.store(s->id, std::memory_order_release);
  // NOT ring-adopted: measured slower for clients — the one-in-flight
  // fixed-send discipline throttles request pipelining, while the epoll
  // lane's writer fiber flushes the whole queue per writev
  s->disp->add_consumer(s);
  return ch;
}

void nat_channel_close(void* h) {
  NatChannel* ch = (NatChannel*)h;
  {
    // serialize against an in-flight reconnect: once we hold
    // reconnect_mu, any racing channel_socket has either published its
    // new socket (we fail it below) or will see closed and not dial
    std::lock_guard<std::mutex> g(ch->reconnect_mu);
    ch->closed.store(true, std::memory_order_release);
  }
  NatSocket* s = sock_address(ch->sock_id);
  if (s != nullptr) {
    s->set_failed();  // fails pending calls via channel->fail_all
    s->release();
  }
  ch->fail_all(kEFAILEDSOCKET, "channel closed");
  ch->release();  // opener's reference; the socket may still hold one
}

// Backup request (the controller.cpp:1256 backup timer): when the timer
// fires and the call is STILL pending, the SAME frame (same correlation
// id) is re-sent on the channel's current socket — the pending-bit CAS
// makes whichever response lands first win and the loser a no-op, which
// is exactly the reference's duplicate-response discipline.
struct BackupCtx {
  NatChannel* ch;  // holds a reference until fired
  int64_t cid;
  std::string frame;
};

static void backup_fire_work(void* raw) {
  BackupCtx* b = (BackupCtx*)raw;
  if (b->ch->is_pending(b->cid) &&
      !b->ch->closed.load(std::memory_order_acquire)) {
    NatSocket* s = sock_address(b->ch->sock_id);
    if (s != nullptr) {
      IOBuf f;
      f.append(b->frame.data(), b->frame.size());
      s->write(std::move(f));
      s->release();
    }
  }
  b->ch->release();
  delete b;
}

static void backup_fire(void* raw) {
  Scheduler::instance()->spawn_detached(backup_fire_work, raw);
}

// One wire attempt: build, (optionally) arm deadline + backup, write,
// park, harvest. Returns the RPC error code.
static int call_attempt(NatChannel* ch, NatSocket* s, const char* service,
                        const char* method, const char* payload,
                        size_t payload_len, int timeout_ms, int backup_ms,
                        char** resp_out, size_t* resp_len,
                        char** err_text_out) {
  int64_t cid = 0;
  PendingCall* pc = ch->begin_call(&cid);
  if (pc == nullptr) {
    return kEFAILEDSOCKET;  // 1M calls already in flight on this channel
  }
  if (timeout_ms > 0) arm_call_timeout(ch, cid, timeout_ms);
  IOBuf frame;
  build_request_frame(&frame, cid, service, method, payload, payload_len,
                      nullptr, 0);
  if (backup_ms > 0 && (timeout_ms <= 0 || backup_ms < timeout_ms)) {
    ch->add_ref();
    BackupCtx* b = new BackupCtx{ch, cid, frame.to_string()};
    TimerThread::instance()->schedule(backup_fire, b, backup_ms);
  }
  if (s->write(std::move(frame)) != 0) {
    PendingCall* mine = ch->take_pending(cid);
    if (mine != nullptr) {
      pc_free(mine);
    } else {
      // fail_all consumed it and is completing through the wake path;
      // wait for that completion so the object isn't leaked
      while (pc->done.value.load(std::memory_order_acquire) == 0) {
        Scheduler::butex_wait(&pc->done, 0);
      }
      pc_free(pc);
    }
    return kEFAILEDSOCKET;
  }
  while (pc->done.value.load(std::memory_order_acquire) == 0) {
    Scheduler::butex_wait(&pc->done, 0);
  }
  int rc = pc->error_code;
  if (rc == 0 && resp_out != nullptr) {
    *resp_len = pc->response.length();
    *resp_out = (char*)malloc(*resp_len ? *resp_len : 1);
    pc->response.copy_to(*resp_out, *resp_len);
  } else if (resp_out != nullptr) {
    *resp_out = nullptr;
    *resp_len = 0;
  }
  if (err_text_out != nullptr) {
    if (rc != 0 && !pc->error_text.empty()) {
      *err_text_out = (char*)malloc(pc->error_text.size() + 1);
      memcpy(*err_text_out, pc->error_text.c_str(),
             pc->error_text.size() + 1);
    } else {
      *err_text_out = nullptr;
    }
  }
  pc_free(pc);
  return rc;
}

// Synchronous call. Returns 0 on success (out buffers malloc'd, caller
// frees with nat_buf_free), else an error code. timeout_ms > 0 arms a
// deadline covering ALL attempts (reference semantics); failed-socket
// attempts retry up to max_retry times with on-demand re-dial;
// backup_ms > 0 re-sends the request if no response arrived in time.
int nat_channel_call_full(void* h, const char* service, const char* method,
                          const char* payload, size_t payload_len,
                          int timeout_ms, int max_retry, int backup_ms,
                          char** resp_out, size_t* resp_len,
                          char** err_text_out) {
  NatChannel* ch = (NatChannel*)h;
  // out-params are read (and freed) by the retry loop below: they must
  // be defined regardless of which early path an attempt takes
  if (resp_out != nullptr) {
    *resp_out = nullptr;
    *resp_len = 0;
  }
  if (err_text_out != nullptr) *err_text_out = nullptr;
  int64_t deadline_us =
      timeout_ms > 0
          ? std::chrono::duration_cast<std::chrono::microseconds>(
                std::chrono::steady_clock::now().time_since_epoch())
                    .count() +
                (int64_t)timeout_ms * 1000
          : 0;
  int attempt = 0;
  while (true) {
    int remaining_ms = timeout_ms;
    if (deadline_us != 0) {
      int64_t now_us =
          std::chrono::duration_cast<std::chrono::microseconds>(
              std::chrono::steady_clock::now().time_since_epoch())
              .count();
      remaining_ms = (int)((deadline_us - now_us) / 1000);
      if (remaining_ms <= 0) return kERPCTIMEDOUT;
    }
    // NOTE: the socket reference is held until the attempt completes —
    // it pins the channel (socket->channel ref), so a concurrent close
    // can never delete the slot slabs under a parked caller (the
    // never-freed-butex discipline). The re-dial is clamped to the
    // remaining budget, and the budget is recomputed after it, so a
    // slow dial can't stretch the overall deadline.
    NatSocket* s = channel_socket(ch, remaining_ms);
    if (s == nullptr) {
      if (attempt++ < max_retry &&
          !ch->closed.load(std::memory_order_acquire)) {
        continue;  // the next channel_socket re-dials
      }
      return kEFAILEDSOCKET;
    }
    if (deadline_us != 0) {  // the dial may have consumed budget
      int64_t now_us =
          std::chrono::duration_cast<std::chrono::microseconds>(
              std::chrono::steady_clock::now().time_since_epoch())
              .count();
      remaining_ms = (int)((deadline_us - now_us) / 1000);
      if (remaining_ms <= 0) {
        s->release();
        return kERPCTIMEDOUT;
      }
    }
    int rc = call_attempt(ch, s, service, method, payload, payload_len,
                          remaining_ms, backup_ms, resp_out, resp_len,
                          err_text_out);
    s->release();
    if (rc != kEFAILEDSOCKET || attempt++ >= max_retry ||
        ch->closed.load(std::memory_order_acquire)) {
      return rc;
    }
    if (err_text_out != nullptr && *err_text_out != nullptr) {
      free(*err_text_out);  // superseded by the retry
      *err_text_out = nullptr;
    }
  }
}

int nat_channel_call(void* h, const char* service, const char* method,
                     const char* payload, size_t payload_len, int timeout_ms,
                     char** resp_out, size_t* resp_len,
                     char** err_text_out) {
  return nat_channel_call_full(h, service, method, payload, payload_len,
                               timeout_ms, 0, 0, resp_out, resp_len,
                               err_text_out);
}

void nat_buf_free(char* p) { free(p); }

// Asynchronous call for embedders (the done-closure surface): cb runs on
// a framework thread/fiber when the response (or failure) arrives —
// cb(user_arg, error_code, resp_bytes, resp_len). The response buffer is
// only valid during the callback; copy it out if needed.
typedef void (*nat_acall_cb)(void* arg, int32_t error_code,
                             const char* resp, size_t resp_len);

struct AcallCtx {
  nat_acall_cb cb;
  void* arg;
};

static void acall_complete(PendingCall* pc, void* raw) {
  AcallCtx* ctx = (AcallCtx*)raw;
  std::string resp = pc->response.to_string();
  ctx->cb(ctx->arg, pc->error_code, resp.data(), resp.size());
  pc_free(pc);
  delete ctx;
}

int nat_channel_acall(void* h, const char* service, const char* method,
                      const char* payload, size_t payload_len,
                      int timeout_ms, nat_acall_cb cb, void* arg) {
  NatChannel* ch = (NatChannel*)h;
  NatSocket* s = channel_socket(ch);
  if (s == nullptr) return kEFAILEDSOCKET;
  AcallCtx* ctx = new AcallCtx{cb, arg};
  int64_t cid = 0;
  if (ch->begin_call(&cid, acall_complete, ctx) == nullptr) {
    s->release();
    delete ctx;
    return kEFAILEDSOCKET;
  }
  if (timeout_ms > 0) arm_call_timeout(ch, cid, timeout_ms);
  IOBuf frame;
  build_request_frame(&frame, cid, service, method, payload, payload_len,
                      nullptr, 0);
  if (s->write(std::move(frame)) != 0) {
    PendingCall* mine = ch->take_pending(cid);  // s still pins the channel
    if (mine != nullptr) {
      // not yet consumed: complete through the SAME callback path so the
      // caller observes exactly ONE completion (returning an error here
      // while fail_all might also fire cb would double-complete, and the
      // caller would have no reason to keep the callback alive)
      mine->error_code = kEFAILEDSOCKET;
      mine->error_text = "socket failed before write";
      acall_complete(mine, ctx);
    }
    // else: fail_all already delivered the failure through cb
    s->release();
    return 0;
  }
  s->release();
  return 0;
}

// ---- framework-path benchmark ----
// F fibers per channel issue synchronous EchoService.Echo calls through the
// FULL native stack (Channel pending table -> Socket write queue ->
// dispatcher -> reader fibers -> server dispatch -> response completion).
// This is the multi_threaded_echo shape on fibers; the shared connection's
// write queue gives natural syscall batching.

struct BenchFiberArg {
  NatChannel* ch;
  std::atomic<bool>* stop;
  std::atomic<uint64_t>* total;
  const std::string* payload;
  Butex* done_count;  // incremented as each fiber exits
};

static void bench_call_fiber(void* a) {
  BenchFiberArg* arg = (BenchFiberArg*)a;
  NatChannel* ch = arg->ch;
  while (!arg->stop->load(std::memory_order_relaxed)) {
    NatSocket* s = sock_address(ch->sock_id);
    if (s == nullptr) break;
    int64_t cid = 0;
    PendingCall* pc = ch->begin_call(&cid);
    if (pc == nullptr) {
      s->release();
      break;
    }
    IOBuf frame;
    build_request_frame(&frame, cid, "EchoService", "Echo",
                        arg->payload->data(), arg->payload->size(), nullptr,
                        0);
    int wrc = s->write(std::move(frame));
    // the socket ref pins the channel until the slot access is done
    if (wrc != 0) {
      PendingCall* mine = ch->take_pending(cid);
      if (mine != nullptr) {
        pc_free(mine);
      } else {  // fail_all owns the completion; wait, then recycle
        while (pc->done.value.load(std::memory_order_acquire) == 0) {
          Scheduler::butex_wait(&pc->done, 0);
        }
        pc_free(pc);
      }
      s->release();
      break;
    }
    while (pc->done.value.load(std::memory_order_acquire) == 0) {
      Scheduler::butex_wait(&pc->done, 0);
    }
    bool ok = (pc->error_code == 0);
    pc_free(pc);
    s->release();
    if (!ok) break;
    arg->total->fetch_add(1, std::memory_order_relaxed);
  }
  arg->done_count->value.fetch_add(1, std::memory_order_release);
  Scheduler::butex_wake(arg->done_count, 1);
  delete arg;
}

double nat_rpc_client_bench(const char* ip, int port, int nconn,
                            int fibers_per_conn, double seconds,
                            int payload_size, uint64_t* out_requests) {
  std::string payload((size_t)payload_size, 'x');
  return run_client_bench(
      ip, port, nconn, seconds, out_requests,
      [&](NatChannel* ch, std::atomic<bool>* stop,
          std::atomic<uint64_t>* total, Butex* done) {
        for (int f = 0; f < fibers_per_conn; f++) {
          BenchFiberArg* arg = new BenchFiberArg{
              ch, stop, total, &payload, done};
          Scheduler::instance()->spawn_detached(bench_call_fiber, arg);
        }
        return fibers_per_conn;
      },
      [] {});
}

// Async windowed bench: each connection keeps `window` requests in
// flight through the REAL framework path (pending table -> Socket write
// queue -> dispatcher/ring -> server dispatch -> response completion),
// completing via PendingCall callbacks instead of parking a fiber per
// call — the async-RPC usage pattern (brpc done-closures) at bench scale.
struct AsyncBenchConn {
  NatChannel* ch = nullptr;
  std::atomic<bool>* stop = nullptr;
  std::atomic<uint64_t>* total = nullptr;
  std::string* payload = nullptr;
  Butex* done_count = nullptr;
  std::atomic<int> inflight{0};
  Butex room;  // bumped when the window opens / on stop
  int window = 64;
  // lifetime: the sender fiber holds one ref, every in-flight call one
  // more — the LAST completion callback may run after the fiber exited,
  // so neither side can own the object outright
  std::atomic<int> refs{1};

  void add_ref() { refs.fetch_add(1, std::memory_order_relaxed); }
  void release() {
    if (refs.fetch_sub(1, std::memory_order_acq_rel) == 1) delete this;
  }
};

static void async_bench_cb(PendingCall* pc, void* arg) {
  AsyncBenchConn* ab = (AsyncBenchConn*)arg;
  if (pc->error_code == 0) {
    ab->total->fetch_add(1, std::memory_order_relaxed);
  }
  pc_free(pc);
  ab->inflight.fetch_sub(1, std::memory_order_acq_rel);
  ab->room.value.fetch_add(1, std::memory_order_release);
  Scheduler::butex_wake(&ab->room, 1);
  ab->release();  // the in-flight reference
}

static void async_bench_fiber(void* a) {
  AsyncBenchConn* ab = (AsyncBenchConn*)a;
  NatChannel* ch = ab->ch;
  while (!ab->stop->load(std::memory_order_acquire)) {
    if (ab->inflight.load(std::memory_order_acquire) >= ab->window) {
      int32_t expected = ab->room.value.load(std::memory_order_acquire);
      if (ab->inflight.load(std::memory_order_acquire) >= ab->window) {
        Scheduler::butex_wait(&ab->room, expected);
      }
      continue;
    }
    NatSocket* s = sock_address(ch->sock_id);
    if (s == nullptr) break;
    int64_t cid = 0;
    ab->inflight.fetch_add(1, std::memory_order_acq_rel);
    ab->add_ref();  // released by async_bench_cb
    PendingCall* pc = ch->begin_call(&cid, async_bench_cb, ab);
    if (pc == nullptr) {
      ab->inflight.fetch_sub(1, std::memory_order_acq_rel);
      ab->release();
      s->release();
      break;
    }
    IOBuf frame;
    build_request_frame(&frame, cid, "EchoService", "Echo",
                        ab->payload->data(), ab->payload->size(), nullptr,
                        0);
    int wrc = s->write(std::move(frame));
    if (wrc != 0) {
      PendingCall* mine = ch->take_pending(cid);  // s pins the channel
      if (mine != nullptr) {  // not yet consumed by fail_all's cb path
        pc_free(mine);
        ab->inflight.fetch_sub(1, std::memory_order_acq_rel);
        ab->release();
      }
      s->release();
      break;
    }
    s->release();
  }
  // drain the window before reporting done
  while (ab->inflight.load(std::memory_order_acquire) > 0) {
    int32_t expected = ab->room.value.load(std::memory_order_acquire);
    if (ab->inflight.load(std::memory_order_acquire) == 0) break;
    Scheduler::butex_wait(&ab->room, expected);
  }
  Butex* done = ab->done_count;
  ab->release();  // the sender fiber's reference; cb refs may outlive us
  done->value.fetch_add(1, std::memory_order_release);
  Scheduler::butex_wake(done, INT32_MAX);
}


double nat_rpc_client_bench_async(const char* ip, int port, int nconn,
                                  int window, double seconds,
                                  int payload_size,
                                  uint64_t* out_requests) {
  std::string payload((size_t)payload_size, 'x');
  std::vector<AsyncBenchConn*> conns;
  double qps = run_client_bench(
      ip, port, nconn, seconds, out_requests,
      [&](NatChannel* ch, std::atomic<bool>* stop,
          std::atomic<uint64_t>* total, Butex* done) {
        AsyncBenchConn* ab = new AsyncBenchConn();
        ab->ch = ch;
        ab->stop = stop;
        ab->total = total;
        ab->payload = &payload;
        ab->done_count = done;
        ab->window = window > 0 ? window : 64;
        ab->add_ref();  // the harness's own reference (released below) —
                        // a conn whose fiber died early must outlive
                        // on_stop's wakeup sweep
        conns.push_back(ab);
        Scheduler::instance()->spawn_detached(async_bench_fiber, ab);
        return 1;
      },
      [&] {
        for (AsyncBenchConn* ab : conns) {  // unpark window-waiters
          ab->room.value.fetch_add(1, std::memory_order_release);
          Scheduler::butex_wake(&ab->room, INT32_MAX);
        }
      });
  for (AsyncBenchConn* ab : conns) ab->release();
  return qps;
}

// Bulk data-path bench (the streamed-attachment / device-push shape,
// VERDICT r2 #4): one sync caller pushes frames carrying `att_bytes` of
// attachment through the FULL native stack; the native echo handler
// bounces the blocks back zero-copy. Returns GB/s of echoed attachment
// payload (each byte crosses the wire twice; we count one direction).
double nat_rpc_client_bench_bulk(const char* ip, int port, int att_bytes,
                                 double seconds, uint64_t* out_bytes) {
  std::string att((size_t)att_bytes, 'b');
  uint64_t total_calls = 0;
  struct BulkArg {
    NatChannel* ch;
    std::atomic<bool>* stop;
    std::atomic<uint64_t>* total;
    const std::string* att;
    Butex* done_count;
  };
  double dt_qps = run_client_bench(
      ip, port, 1, seconds, &total_calls,
      [&](NatChannel* ch, std::atomic<bool>* stop,
          std::atomic<uint64_t>* total, Butex* done) {
        BulkArg* arg = new BulkArg{ch, stop, total, &att, done};
        Scheduler::instance()->spawn_detached(
            [](void* a) {
              BulkArg* arg = (BulkArg*)a;
              NatChannel* ch = arg->ch;
              while (!arg->stop->load(std::memory_order_relaxed)) {
                NatSocket* s = sock_address(ch->sock_id);
                if (s == nullptr) break;
                int64_t cid = 0;
                PendingCall* pc = ch->begin_call(&cid);
                if (pc == nullptr) {
                  s->release();
                  break;
                }
                IOBuf frame;
                build_request_frame(&frame, cid, "EchoService", "Echo",
                                    nullptr, 0, arg->att->data(),
                                    arg->att->size());
                int wrc = s->write(std::move(frame));
                if (wrc != 0) {
                  PendingCall* mine = ch->take_pending(cid);
                  if (mine != nullptr) {
                    pc_free(mine);
                  } else {
                    while (pc->done.value.load(std::memory_order_acquire) ==
                           0) {
                      Scheduler::butex_wait(&pc->done, 0);
                    }
                    pc_free(pc);
                  }
                  s->release();
                  break;
                }
                while (pc->done.value.load(std::memory_order_acquire) == 0) {
                  Scheduler::butex_wait(&pc->done, 0);
                }
                bool ok = (pc->error_code == 0 &&
                           pc->attachment.length() == arg->att->size());
                pc_free(pc);
                s->release();
                if (!ok) break;
                arg->total->fetch_add(1, std::memory_order_relaxed);
              }
              arg->done_count->value.fetch_add(1, std::memory_order_release);
              Scheduler::butex_wake(arg->done_count, 1);
              delete arg;
            },
            arg);
        return 1;
      },
      [] {});
  uint64_t bytes = total_calls * (uint64_t)att_bytes;
  if (out_bytes != nullptr) *out_bytes = bytes;
  // run_client_bench returns calls/sec; scale to GB/s of attachment
  return dt_qps * (double)att_bytes / 1e9;
}

// Enables the RingListener datapath for subsequently-accepted server
// connections. Returns 1 when the ring is live, 0 when the kernel/sandbox
// refuses io_uring (the runtime stays on epoll), -1 on runtime failure.
int nat_rpc_use_io_uring(int enable) {
  if (!enable) {
    g_use_ring.store(false, std::memory_order_release);
    return 0;
  }
  if (ensure_runtime(0) != 0) return -1;
  {
    std::lock_guard<std::mutex> g(g_rt_mu);
    if (g_ring == nullptr) {
      RingListener* ring = new RingListener();
      // wake a parked worker per completion batch (ExtWakeup role);
      // installed before init() so the poller never runs without it
      ring->set_wake_fn([] { Scheduler::instance()->wake_one(); });
      // the poller drains its own harvest inline (every completion
      // consumer is non-blocking), with butex wakes batched per drain —
      // the worker idle hook below stays as a backup drain path
      ring->set_drain_fn([]() -> bool {
        static thread_local std::vector<Fiber*> batch;
        if (g_ring_draining.load(std::memory_order_acquire)) {
          return false;  // a worker holds the baton: let the poller
        }                // wake one instead of silently dropping
        Scheduler::instance()->arm_wake_batch(&batch);
        bool did = ring_drain();
        Scheduler::instance()->flush_wake_batch();
        return did;
      });
      if (!ring->init()) {
        delete ring;
        return 0;  // io_uring unavailable here: keep epoll
      }
      g_ring = ring;
      // the wait_task drain seam (task_group.cpp:158-169)
      Scheduler::instance()->add_idle_hook(ring_drain);
    }
  }
  g_use_ring.store(true, std::memory_order_release);
  return 1;
}

// Ring observability for tests/bench: completion counts.
void nat_ring_counters(uint64_t* recv_out, uint64_t* send_out) {
  if (recv_out != nullptr)
    *recv_out = g_ring != nullptr ? g_ring->recv_completions() : 0;
  if (send_out != nullptr)
    *send_out = g_ring != nullptr ? g_ring->send_completions() : 0;
}

}  // extern "C"

}  // namespace brpc_tpu
