// nat_replay — native replay/press client of the traffic flight
// recorder (rpc_replay + rpc_press's C++ twin, SURVEY §2.11).
//
// Reads recordio capture files (nat_dump.cpp's writer, or the Python
// rpc_dump's — same format, butil/recordio.py), then re-fires the
// replayable records through the REAL native client lanes — tpu_std
// via NatChannel sync calls, HTTP via the native HTTP client lane,
// gRPC via the native h2 lane — from a pool of worker threads at a
// controlled (optionally ramped) rate, recording latency into a log2
// histogram. qps 0 = press mode: no throttle, `concurrency` callers
// back to back. This turns any production-shaped capture into a
// standing bench lane (ROADMAP item 4's load generator).
#include <dirent.h>
#include <math.h>
#include <unistd.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>

#include <algorithm>
#include <atomic>
#include <chrono>
#include <string>
#include <thread>
#include <vector>

#include "nat_api.h"
#include "nat_dump.h"
#include "nat_stats.h"

namespace brpc_tpu {
namespace {

// total payload bytes loaded into memory before loading stops (a
// multi-GB capture replays its first GB rather than OOMing the caller)
inline constexpr uint64_t kReplayMaxLoadBytes = 1ull << 30;

struct ReplayRec {
  int lane = NL_ECHO;
  std::string verb;     // http only ("" = derive from payload presence)
  std::string service;  // tpu_std only
  std::string method;   // tpu_std method / http path / grpc :path
  std::string payload;
};

// ---- minimal JSON field extraction over the flat meta object --------------
// (both writers emit one flat object with string/number values; a full
// parser would be dead weight here)

bool json_find_string(const std::string& meta, const char* key,
                      std::string* out) {
  std::string needle = std::string("\"") + key + "\"";
  size_t p = meta.find(needle);
  if (p == std::string::npos) return false;
  p += needle.size();
  while (p < meta.size() && (meta[p] == ' ' || meta[p] == ':')) p++;
  if (p >= meta.size() || meta[p] != '"') return false;
  p++;
  out->clear();
  while (p < meta.size() && meta[p] != '"') {
    char c = meta[p];
    if (c == '\\' && p + 1 < meta.size()) {
      char e = meta[p + 1];
      p += 2;
      switch (e) {
        case 'n': out->push_back('\n'); break;
        case 't': out->push_back('\t'); break;
        case 'r': out->push_back('\r'); break;
        case 'b': out->push_back('\b'); break;
        case 'f': out->push_back('\f'); break;
        case 'u': {
          if (p + 4 <= meta.size()) {
            unsigned v = (unsigned)strtoul(
                meta.substr(p, 4).c_str(), nullptr, 16);
            p += 4;
            if (v >= 0xd800 && v < 0xdc00 && p + 6 <= meta.size() &&
                meta[p] == '\\' && meta[p + 1] == 'u') {
              // surrogate pair (json.dumps for astral-plane text):
              // combine into one codepoint, emit 4-byte UTF-8
              unsigned lo = (unsigned)strtoul(
                  meta.substr(p + 2, 4).c_str(), nullptr, 16);
              if (lo >= 0xdc00 && lo < 0xe000) {
                p += 6;
                unsigned cp = 0x10000 + ((v - 0xd800) << 10) +
                              (lo - 0xdc00);
                out->push_back((char)(0xf0 | (cp >> 18)));
                out->push_back((char)(0x80 | ((cp >> 12) & 0x3f)));
                out->push_back((char)(0x80 | ((cp >> 6) & 0x3f)));
                out->push_back((char)(0x80 | (cp & 0x3f)));
                break;
              }
            }
            if (v < 0x100) {
              // \u00XX is a raw wire byte (the native writer's
              // escaping, RECORDIO.md) — byte-exact round trip
              out->push_back((char)v);
            } else if (v < 0x800) {
              // higher codepoints (Python json.dumps ensure_ascii on
              // real text) re-encode as the UTF-8 bytes the Python
              // channel would put on the wire
              out->push_back((char)(0xc0 | (v >> 6)));
              out->push_back((char)(0x80 | (v & 0x3f)));
            } else {
              out->push_back((char)(0xe0 | (v >> 12)));
              out->push_back((char)(0x80 | ((v >> 6) & 0x3f)));
              out->push_back((char)(0x80 | (v & 0x3f)));
            }
          }
          break;
        }
        default: out->push_back(e); break;
      }
      continue;
    }
    out->push_back(c);
    p++;
  }
  return p < meta.size();
}

int lane_from_meta(const std::string& meta) {
  std::string lane;
  if (!json_find_string(meta, "lane", &lane)) {
    return NL_ECHO;  // Python rpc_dump files: tpu_std by construction
  }
  for (int i = 0; i < NL_LANE_COUNT; i++) {
    if (lane == nat_stats_lane_name(i)) return i;
  }
  return -1;
}

// ---- recordio reader ------------------------------------------------------

uint32_t rd32(const unsigned char* p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

// Append the replayable records of one .rio file. A clean truncated
// tail (EOF mid-record: the writer was killed mid-capture) is
// tolerated; a bad magic, insane length or CRC mismatch stops this
// file AND counts one `skipped` — the Python reader raises on the
// same bytes, so a corrupt stream must never read as a smaller
// successful load.
void load_file(const char* path, std::vector<ReplayRec>* out,
               uint64_t* loaded, uint64_t* skipped,
               uint64_t* loaded_bytes) {
  FILE* f = fopen(path, "rb");
  if (f == nullptr) return;
  // the claimed record lengths below must also fit the bytes actually
  // on disk: a 16-byte forged file claiming pl=512MB must not force a
  // 512MB zero-filled resize before fread discovers the truncation
  struct stat st;
  if (fstat(fileno(f), &st) != 0 || st.st_size < 0) {
    fclose(f);
    return;
  }
  uint64_t remaining = (uint64_t)st.st_size;
  std::string meta, payload;
  for (;;) {
    unsigned char hdr[16];
    if (fread(hdr, 1, 16, f) != 16) break;  // EOF / truncated tail
    remaining = remaining >= 16 ? remaining - 16 : 0;
    uint32_t ml = NAT_WIRE(rd32(hdr + 4));
    uint32_t pl = NAT_WIRE(rd32(hdr + 8));
    uint32_t crc = NAT_WIRE(rd32(hdr + 12));
    if (memcmp(hdr, "RIO1", 4) != 0 || ml > (1u << 20) ||
        pl > (512u << 20) || (uint64_t)ml + pl > remaining) {
      (*skipped)++;  // corrupt stream: the file's remainder is lost
      break;
    }
    remaining -= (uint64_t)ml + pl;
    meta.resize(ml);
    payload.resize(pl);
    if (ml != 0 && fread(&meta[0], 1, ml, f) != ml) break;
    if (pl != 0 && fread(&payload[0], 1, pl, f) != pl) break;
    if (nat_rio_crc32(meta.data(), ml, payload.data(), pl) != crc) {
      (*skipped)++;  // corrupt record: remainder unparseable
      break;
    }
    (*loaded)++;
    int lane = lane_from_meta(meta);
    ReplayRec rec;
    bool replayable = false;
    if (lane == NL_ECHO) {
      // tpu_std: service + method, re-fired through NatChannel
      if (json_find_string(meta, "service", &rec.service) &&
          json_find_string(meta, "method", &rec.method)) {
        replayable = true;
      }
    } else if (lane == NL_HTTP) {
      if (json_find_string(meta, "method", &rec.method) &&
          !rec.method.empty() && rec.method[0] == '/') {
        json_find_string(meta, "verb", &rec.verb);
        replayable = true;
      }
    } else if (lane == NL_GRPC) {
      if (json_find_string(meta, "method", &rec.method) &&
          !rec.method.empty() && rec.method[0] == '/') {
        replayable = true;
      }
    }
    // redis / worker / client records have no NatChannel client lane
    // to re-fire through: counted, never silently vanished
    if (!replayable) {
      (*skipped)++;
      continue;
    }
    rec.lane = lane;
    rec.payload = payload;
    *loaded_bytes += pl;
    out->push_back(std::move(rec));
    if (*loaded_bytes > kReplayMaxLoadBytes) break;
  }
  fclose(f);
}

// `files` is a ';'-separated list of .rio paths and/or directories
// (directories are scanned for *.rio in name order — capture
// generations sort chronologically by construction).
void load_spec(const char* files, std::vector<ReplayRec>* out,
               uint64_t* loaded, uint64_t* skipped) {
  uint64_t loaded_bytes = 0;
  std::string spec(files != nullptr ? files : "");
  size_t pos = 0;
  while (pos <= spec.size()) {
    size_t sep = spec.find(';', pos);
    if (sep == std::string::npos) sep = spec.size();
    std::string tok = spec.substr(pos, sep - pos);
    pos = sep + 1;
    if (tok.empty()) continue;
    struct stat st;
    if (stat(tok.c_str(), &st) != 0) continue;
    if (S_ISDIR(st.st_mode)) {
      std::vector<std::string> names;
      if (DIR* d = opendir(tok.c_str())) {
        while (struct dirent* e = readdir(d)) {
          size_t n = strlen(e->d_name);
          if (n > 4 && strcmp(e->d_name + n - 4, ".rio") == 0) {
            names.push_back(tok + "/" + e->d_name);
          }
        }
        closedir(d);
      }
      std::sort(names.begin(), names.end());
      for (const std::string& p : names) {
        load_file(p.c_str(), out, loaded, skipped, &loaded_bytes);
      }
    } else {
      load_file(tok.c_str(), out, loaded, skipped, &loaded_bytes);
    }
  }
}

// ---- rate schedule --------------------------------------------------------

// Fire time (seconds from run start) of request k under a linear ramp
// from q0 to q1 qps across N total requests (q1 <= 0 = constant q0).
// Solves the cumulative-count integral q0*t + (q1-q0)/(2T)*t^2 = k.
double fire_time(uint64_t k, double q0, double q1, uint64_t n_total) {
  if (q0 <= 0.0) return 0.0;  // press mode: no schedule
  if (q1 <= 0.0 || q1 == q0 || n_total == 0) return (double)k / q0;
  double T = 2.0 * (double)n_total / (q0 + q1);
  double a = (q1 - q0) / (2.0 * T);
  double disc = q0 * q0 + 4.0 * a * (double)k;
  if (disc < 0.0) disc = 0.0;
  return (-q0 + sqrt(disc)) / (2.0 * a);
}

struct ReplayShared {
  const std::vector<ReplayRec>* recs = nullptr;
  std::atomic<uint64_t> next{0};
  uint64_t total = 0;  // records x times
  double q0 = 0.0, q1 = 0.0;
  int timeout_ms = 0;
  std::chrono::steady_clock::time_point t0;
  void* ch_std = nullptr;
  void* ch_http = nullptr;
  void* ch_grpc = nullptr;
  std::atomic<uint64_t> ok{0};
  std::atomic<uint64_t> failed{0};
  std::atomic<uint64_t> hist[kNatHistBuckets] = {};
};

// Fire one record through its lane's public sync client surface — the
// exact calls a ctypes embedder makes, so a replay run exercises the
// production client path end to end.
bool fire_one(ReplayShared* sh, const ReplayRec& r) {
  char* resp = nullptr;
  size_t rlen = 0;
  char* err = nullptr;
  bool ok = false;
  if (r.lane == NL_ECHO) {
    int rc = nat_channel_call_full(
        sh->ch_std, r.service.c_str(), r.method.c_str(), r.payload.data(),
        r.payload.size(), sh->timeout_ms, 0, 0, &resp, &rlen, &err);
    ok = rc == 0;
  } else if (r.lane == NL_HTTP) {
    const char* verb = !r.verb.empty() ? r.verb.c_str()
                       : r.payload.empty() ? "GET"
                                           : "POST";
    int status = 0;
    int rc = nat_http_call(sh->ch_http, verb, r.method.c_str(), nullptr,
                           r.payload.data(), r.payload.size(),
                           sh->timeout_ms, &status, &resp, &rlen);
    ok = rc == 0 && status / 100 == 2;
  } else {  // NL_GRPC
    int gst = -1;
    int rc = nat_grpc_call(sh->ch_grpc, r.method.c_str(),
                           r.payload.data(), r.payload.size(),
                           sh->timeout_ms, &gst, &resp, &rlen, &err);
    ok = rc == 0 && gst == 0;
  }
  if (resp != nullptr) nat_buf_free(resp);
  if (err != nullptr) nat_buf_free(err);
  return ok;
}

void replay_worker(ReplayShared* sh) {
  const std::vector<ReplayRec>& recs = *sh->recs;
  for (;;) {
    uint64_t k = sh->next.fetch_add(1, std::memory_order_relaxed);
    if (k >= sh->total) return;
    if (sh->q0 > 0.0) {
      auto due = sh->t0 + std::chrono::duration_cast<
                              std::chrono::steady_clock::duration>(
                              std::chrono::duration<double>(fire_time(
                                  k, sh->q0, sh->q1, sh->total)));
      std::this_thread::sleep_until(due);
    }
    const ReplayRec& r = recs[k % recs.size()];
    nat_counter_add(NS_REPLAY_CALLS, 1);
    uint64_t c0 = nat_now_ns();
    bool ok = fire_one(sh, r);
    uint64_t lat = nat_now_ns() - c0;
    if (ok) {
      sh->ok.fetch_add(1, std::memory_order_relaxed);
      sh->hist[nat_hist_bucket(lat)].fetch_add(1,
                                               std::memory_order_relaxed);
    } else {
      sh->failed.fetch_add(1, std::memory_order_relaxed);
      nat_counter_add(NS_REPLAY_ERRORS, 1);
    }
  }
}

// log2-bucket quantile (ns) over the run-local histogram: snapshot the
// atomics, then the SHARED nat_hist_quantile interpolation (nat_stats).
double replay_quantile_ns(const std::atomic<uint64_t>* hist, double q) {
  uint64_t buckets[kNatHistBuckets];
  for (int b = 0; b < kNatHistBuckets; b++) {
    buckets[b] = hist[b].load(std::memory_order_relaxed);
  }
  return nat_hist_quantile(buckets, kNatHistBuckets, q);
}

}  // namespace

// Fuzz seam (nat_fuzz_entry.cpp owns the others; this one lives here
// for the anonymous-namespace load_file): round an arbitrary byte
// image through a temp file into the real recordio CRC/bounds loader.
extern "C" int nat_fuzz_recordio(const char* data, size_t len) {
  char path[] = "/tmp/nat_fuzz_rio_XXXXXX";
  int fd = mkstemp(path);
  if (fd < 0) return 0;
  size_t off = 0;
  while (off < len) {
    ssize_t w = write(fd, data + off, len - off);
    if (w <= 0) break;
    off += (size_t)w;
  }
  ::close(fd);
  std::vector<ReplayRec> recs;
  uint64_t loaded = 0, skipped = 0, loaded_bytes = 0;
  load_file(path, &recs, &loaded, &skipped, &loaded_bytes);
  unlink(path);
  return loaded != 0 ? 1 : 0;
}

}  // namespace brpc_tpu

using namespace brpc_tpu;

extern "C" {

// Replay captured traffic against ip:port. `files` = ';'-separated
// .rio paths / directories. `times` repeats the record list (>= 1).
// qps_from > 0 throttles the fire schedule (qps_to > 0 ramps linearly
// to it across the run); qps_from <= 0 = press mode (no throttle,
// `concurrency` callers back to back). Latency quantiles cover
// successful calls. Returns 0, -1 = no replayable records,
// -2 = channel open failed.
int nat_replay_run(const char* ip, int port, const char* files, int times,
                   double qps_from, double qps_to, int concurrency,
                   int timeout_ms, brpc_tpu::NatReplayResult* out) {
  if (out == nullptr) return -1;
  memset(out, 0, sizeof(*out));
  std::vector<ReplayRec> recs;
  uint64_t loaded = 0, skipped = 0;
  load_spec(files, &recs, &loaded, &skipped);
  if (times < 1) times = 1;
  out->loaded = loaded;
  out->skipped = skipped * (uint64_t)times;
  if (recs.empty()) return -1;

  ReplayShared sh;
  sh.recs = &recs;
  sh.total = (uint64_t)recs.size() * (uint64_t)times;
  sh.q0 = qps_from;
  sh.q1 = qps_to;
  sh.timeout_ms = timeout_ms;
  bool need_std = false, need_http = false, need_grpc = false;
  for (const ReplayRec& r : recs) {
    need_std |= r.lane == NL_ECHO;
    need_http |= r.lane == NL_HTTP;
    need_grpc |= r.lane == NL_GRPC;
  }
  if (need_std) {
    sh.ch_std = nat_channel_open(ip, port, 0, 1, 5000, 0);
    if (sh.ch_std == nullptr) return -2;
  }
  if (need_http) {
    sh.ch_http =
        nat_channel_open_proto(ip, port, 0, 0, 5000, 0, 1, nullptr);
  }
  if (need_grpc) {
    sh.ch_grpc =
        nat_channel_open_proto(ip, port, 0, 0, 5000, 0, 2, nullptr);
  }
  if ((need_http && sh.ch_http == nullptr) ||
      (need_grpc && sh.ch_grpc == nullptr)) {
    if (sh.ch_std != nullptr) nat_channel_close(sh.ch_std);
    if (sh.ch_http != nullptr) nat_channel_close(sh.ch_http);
    return -2;
  }

  if (concurrency < 1) concurrency = 1;
  if (concurrency > 64) concurrency = 64;
  sh.t0 = std::chrono::steady_clock::now();
  std::vector<std::thread> workers;
  workers.reserve((size_t)concurrency);
  for (int i = 0; i < concurrency; i++) {
    workers.emplace_back(replay_worker, &sh);
  }
  for (auto& t : workers) t.join();
  double dt = std::chrono::duration<double>(
                  std::chrono::steady_clock::now() - sh.t0)
                  .count();

  if (sh.ch_std != nullptr) nat_channel_close(sh.ch_std);
  if (sh.ch_http != nullptr) nat_channel_close(sh.ch_http);
  if (sh.ch_grpc != nullptr) nat_channel_close(sh.ch_grpc);

  out->sent = sh.total;
  out->ok = sh.ok.load(std::memory_order_relaxed);
  out->failed = sh.failed.load(std::memory_order_relaxed);
  out->seconds = dt;
  out->qps = dt > 0 ? (double)(out->ok + out->failed) / dt : 0.0;
  out->p50_us = replay_quantile_ns(sh.hist, 0.50) / 1e3;
  out->p99_us = replay_quantile_ns(sh.hist, 0.99) / 1e3;
  return 0;
}

}  // extern "C"
