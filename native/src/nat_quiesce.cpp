// nat_quiesce — the graceful-degradation lifecycle of the native server
// (Server::Stop(timeout)/Join, server.h:426-441, as a wire protocol):
//
//   phase 1  stop accepting: listeners unsubscribe from their dispatcher
//            loops (fd close DEFERRED to the loop thread — the accept-vs-
//            teardown race fix) and the drain gate arms, so new WORK
//            arrivals answer ELIMIT/503/RESOURCE_EXHAUSTED instead of
//            dying with a reset;
//   phase 2  lame-duck signaling on every live connection, per protocol:
//            h2 peers get GOAWAY(last_stream_id) (RFC 7540 §6.8), HTTP
//            sessions mark Connection: close onto their remaining
//            responses, tpu_std connections get a SHUTDOWN-bit control
//            frame (RpcMeta field 8, correlation_id 0), RESP sessions
//            close once their reply window drains;
//   phase 3  drain: admitted work — py-lane tpu_std requests, HTTP/h2/
//            RESP reorder-window responses, shm-worker in-flight — runs
//            to completion under the deadline; stragglers left in the py
//            queue at expiry are 503'd (never reset); sockets close only
//            once their write stack is idle (close_after_drain), so the
//            FIN always trails the last response byte.
//
// The exported entry is nat_server_quiesce(timeout_ms); rpc/server.py
// wires SIGTERM to it via the graceful_quit_on_sigterm option.
#include "nat_internal.h"

namespace brpc_tpu {

std::atomic<uint32_t> g_draining{0};
std::atomic<int64_t> g_tpu_work_live{0};

namespace {

// One pass over the socket slot space (bounded by the allocation
// high-water mark), calling fn on each live socket owned by srv. The
// borrowed reference pins the slot (and its protocol sessions) for the
// duration of fn.
template <typename Fn>
void for_each_server_socket(NatServer* srv, Fn fn) {
  uint32_t hwm;
  {
    std::lock_guard g(g_sock_alloc_mu);
    hwm = g_sock_next_idx;
  }
  for (uint32_t idx = 0; idx < hwm; idx++) {
    NatSocket* cand = sock_at(idx);
    if (cand == nullptr) continue;
    uint64_t id = cand->id;  // racy snapshot; sock_address validates it
    NatSocket* s = sock_address(id);
    if (s == nullptr) continue;
    if (s->server == srv && !s->failed.load(std::memory_order_acquire)) {
      fn(s);
    }
    NAT_REF_RELEASE(s, sock.borrow);
  }
}

// Lame-duck one connection on its own protocol. Returns true when a
// signal actually went out (the NS_QUIESCE_LAME_DUCK_SENT unit).
// Session pointers are written once by the reading thread at sniff time
// and never change until the socket recycles (which our borrowed ref
// forbids) — a connection still mid-sniff is simply missed here and
// learns about the drain from its first rejection instead.
bool socket_lame_duck(NatSocket* s) {
  if (s->h2 != nullptr) {
    h2_send_goaway(s);
    return true;
  }
  if (s->http != nullptr) {
    http_session_lame_duck(s);
    return true;
  }
  if (s->redis != nullptr) {
    redis_session_lame_duck(s);
    return true;
  }
  if (s->spoke_tpu_std.load(std::memory_order_relaxed)) {
    IOBuf f;
    build_shutdown_frame(&f);
    s->write(std::move(f));
    return true;
  }
  // raw-fallback / streaming / not-yet-sniffed connections have no
  // native protocol to speak — the final close pass flushes whatever
  // their Python responders queued, then FINs.
  return false;
}

// Count the work still owed on srv's connections. Approximate by
// design: the per-session counters under their mutexes are exact, the
// reading-thread-only halves (next_req_seq) are racy reads — the drain
// loop requires TWO consecutive quiet polls, so a transiently-torn
// read cannot end the drain early.
int drain_pending(NatServer* srv) {
  int busy = 0;
  {
    std::lock_guard g(srv->py_mu);
    busy += (int)srv->py_q.size();
  }
  int64_t live = g_tpu_work_live.load(std::memory_order_acquire);
  if (live > 0) busy += (int)live;
  if (!shm_lane_inflight_empty()) busy++;
  for_each_server_socket(srv, [&busy](NatSocket* s) {
    if (s->http != nullptr && http_session_busy(s)) busy++;
    if (s->h2 != nullptr && h2_session_busy(s)) busy++;
    if (s->redis != nullptr && redis_session_busy(s)) busy++;
    if (!s->write_idle()) busy++;
  });
  return busy;
}

}  // namespace

extern "C" {

// True while a quiesce is in progress or completed on the running
// server (observability/tests).
int nat_server_draining(void) {
  return g_draining.load(std::memory_order_acquire) != 0 ? 1 : 0;
}

// Graceful quiesce of the running native server: stop accepting,
// lame-duck every connection, drain admitted work, reject new arrivals,
// close sockets only once flushed. Blocks up to timeout_ms (<= 0 uses a
// 5s default). Returns 0 (drained clean), 1 (deadline expired —
// stragglers were 503'd), -1 (no running server). Call
// nat_rpc_server_stop afterwards to release the server; the py lane
// keeps serving during the drain.
int nat_server_quiesce(int timeout_ms) {
  NatServer* srv;
  {
    std::lock_guard g(g_rt_mu);
    srv = g_rpc_server;
    if (srv == nullptr) return -1;
    NAT_REF_ACQUIRE(srv, srv.quiesce);
    // phase 1: unsubscribe the listener from its dispatcher. The fd
    // CLOSE is deferred to the loop thread (remove_listener), so a
    // concurrently-dispatched accept can never run on a recycled fd.
    if (srv->listen_fd >= 0) {
      g_disp->remove_listener(srv->listen_fd);
      srv->listen_fd = -1;  // stop() must not tear it down again
    }
    // multi-port servers (swarm backends) stop accepting on EVERY port
    server_remove_extra_ports_locked(srv);
  }
  // arm the drain gate BEFORE signaling: a request racing the lame-duck
  // frame is rejected (wire answer), never silently dropped
  g_draining.store(1, std::memory_order_release);

  // phase 2: lame-duck every live connection on its own protocol
  for_each_server_socket(srv, [](NatSocket* s) {
    if (socket_lame_duck(s)) {
      nat_counter_add(NS_QUIESCE_LAME_DUCK_SENT, 1);
    }
  });

  // phase 3: drain admitted work under the deadline
  if (timeout_ms <= 0) timeout_ms = 5000;
  uint64_t deadline = nat_now_ns() + (uint64_t)timeout_ms * 1000000ull;
  bool expired = false;
  int quiet_polls = 0;
  while (true) {
    // natfault shutdown site: err = forced drain-deadline expiry NOW
    // (the chaos lane's straggler-drop driver), delay stretches a poll
    NatFaultAct fa = NAT_FAULT_POINT(NF_SHUTDOWN);
    if (fa.action == NF_DELAY) nat_fault_delay_ms(fa.delay_ms);
    if (fa.action == NF_ERR) {
      expired = true;
      break;
    }
    if (drain_pending(srv) == 0) {
      // two consecutive quiet polls: the racy session reads settled
      if (++quiet_polls >= 2) break;
    } else {
      quiet_polls = 0;
    }
    if (nat_now_ns() >= deadline) {
      expired = true;
      break;
    }
    struct timespec ts = {0, 2 * 1000 * 1000};  // 2ms poll
    nanosleep(&ts, nullptr);
  }

  // deadline expired: requests still queued for the py lane will never
  // be served — answer each with the overload wire shape (503/ELIMIT),
  // never a bare reset, and count the drops
  if (expired) {
    std::deque<PyRequest*> stragglers;
    {
      std::lock_guard g(srv->py_mu);
      for (auto it = srv->py_q.begin(); it != srv->py_q.end();) {
        PyRequest* r = *it;
        if (is_work_kind(r->kind)) {
          stragglers.push_back(r);
          it = srv->py_q.erase(it);
        } else {
          ++it;
        }
      }
    }
    for (PyRequest* r : stragglers) {
      nat_counter_add(NS_QUIESCE_DRAIN_DEADLINE_DROPS, 1);
      drain_reject(r);
    }
    // give the reject fibers a moment to put their 503s on the wire
    // before the close pass arms FINs behind them
    struct timespec ts = {0, 20 * 1000 * 1000};
    nanosleep(&ts, nullptr);
  } else {
    nat_counter_add(NS_QUIESCE_DRAINED_OK, 1);
  }

  // final: graceful close on every remaining connection — queued bytes
  // (the last responses, the straggler 503s) flush, then FIN
  for_each_server_socket(srv, [](NatSocket* s) {
    s->arm_close_after_drain();
  });

  NAT_REF_RELEASE(srv, srv.quiesce);
  return expired ? 1 : 0;
}

}  // extern "C"

}  // namespace brpc_tpu
