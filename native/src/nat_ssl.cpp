// Native TLS lane — SSL integrated into NatSocket itself, the reference's
// Socket-level SSLState design (socket.h:539-540, details/ssl_helper.cpp):
// the same port answers TLS and plaintext (sniffed from the first record
// byte), the handshake and record layer run as a memory-BIO filter inside
// the event loop, and every protocol lane (tpu_std, HTTP, h2, streaming,
// raw fallback) rides on the decrypted stream unchanged.
//
// The image ships libssl.so.3 without development headers, so the needed
// slice of the stable OpenSSL ABI is declared here and resolved with
// dlopen — the same functions every TLS-speaking program links.
#include <dlfcn.h>

#include "nat_internal.h"

namespace brpc_tpu {

// ---------------------------------------------------------------------------
// dlopen'd OpenSSL surface (stable exported symbols, OpenSSL 1.1+/3.x)
// ---------------------------------------------------------------------------

namespace ossl {
using SSL_CTX = void;
using SSL = void;
using BIO = void;
using SSL_METHOD = void;
using BIO_METHOD = void;

static const int kFiletypePem = 1;      // SSL_FILETYPE_PEM
static const int kErrorWantRead = 2;    // SSL_ERROR_WANT_READ
static const int kErrorWantWrite = 3;   // SSL_ERROR_WANT_WRITE
static const int kErrorZeroReturn = 6;  // SSL_ERROR_ZERO_RETURN

struct Lib {
  bool ok = false;
  int (*init_ssl)(uint64_t, const void*) = nullptr;
  const SSL_METHOD* (*tls_server_method)() = nullptr;
  SSL_CTX* (*ctx_new)(const SSL_METHOD*) = nullptr;
  int (*ctx_use_cert_chain)(SSL_CTX*, const char*) = nullptr;
  int (*ctx_use_privkey)(SSL_CTX*, const char*, int) = nullptr;
  SSL* (*ssl_new)(SSL_CTX*) = nullptr;
  void (*set_accept_state)(SSL*) = nullptr;
  const BIO_METHOD* (*bio_s_mem)() = nullptr;
  BIO* (*bio_new)(const BIO_METHOD*) = nullptr;
  void (*set_bio)(SSL*, BIO*, BIO*) = nullptr;
  int (*ssl_read)(SSL*, void*, int) = nullptr;
  int (*ssl_write)(SSL*, const void*, int) = nullptr;
  int (*get_error)(const SSL*, int) = nullptr;
  int (*bio_write)(BIO*, const void*, int) = nullptr;
  int (*bio_read)(BIO*, void*, int) = nullptr;
  size_t (*bio_ctrl_pending)(BIO*) = nullptr;
  void (*ssl_free)(SSL*) = nullptr;
  void (*ctx_set_alpn_select_cb)(
      SSL_CTX*,
      int (*)(SSL*, const unsigned char**, unsigned char*,
              const unsigned char*, unsigned int, void*),
      void*) = nullptr;
};

static Lib g_lib;
static std::once_flag g_lib_once;

template <typename T>
static bool sym(void* h, const char* name, T* out) {
  *out = (T)dlsym(h, name);
  return *out != nullptr;
}

static void lib_load() {
  void* h = nullptr;
  for (const char* name :
       {"libssl.so.3", "libssl.so.1.1", "libssl.so"}) {
    h = dlopen(name, RTLD_NOW | RTLD_GLOBAL);
    if (h != nullptr) break;
  }
  if (h == nullptr) return;
  Lib l;
  bool ok =
      sym(h, "OPENSSL_init_ssl", &l.init_ssl) &&
      sym(h, "TLS_server_method", &l.tls_server_method) &&
      sym(h, "SSL_CTX_new", &l.ctx_new) &&
      sym(h, "SSL_CTX_use_certificate_chain_file", &l.ctx_use_cert_chain) &&
      sym(h, "SSL_CTX_use_PrivateKey_file", &l.ctx_use_privkey) &&
      sym(h, "SSL_new", &l.ssl_new) &&
      sym(h, "SSL_set_accept_state", &l.set_accept_state) &&
      sym(h, "BIO_s_mem", &l.bio_s_mem) &&
      sym(h, "BIO_new", &l.bio_new) &&
      sym(h, "SSL_set_bio", &l.set_bio) &&
      sym(h, "SSL_read", &l.ssl_read) &&
      sym(h, "SSL_write", &l.ssl_write) &&
      sym(h, "SSL_get_error", &l.get_error) &&
      sym(h, "BIO_write", &l.bio_write) &&
      sym(h, "BIO_read", &l.bio_read) &&
      sym(h, "BIO_ctrl_pending", &l.bio_ctrl_pending) &&
      sym(h, "SSL_free", &l.ssl_free);
  // optional (present since 1.0.2); h2 clients need ALPN
  sym(h, "SSL_CTX_set_alpn_select_cb", &l.ctx_set_alpn_select_cb);
  if (!ok) return;
  l.init_ssl(0, nullptr);
  l.ok = true;
  g_lib = l;
}

static Lib& lib() {
  std::call_once(g_lib_once, lib_load);
  return g_lib;
}
}  // namespace ossl

// ---------------------------------------------------------------------------
// per-connection TLS session
// ---------------------------------------------------------------------------

struct SslSessionN {
  NatMutex<kLockRankSslSess> ssl_mu;  // feed (reading thread) vs SSL_write (any responder)
  ossl::SSL* ssl = nullptr;
  ossl::BIO* rbio = nullptr;  // ciphertext in (we write, SSL reads)
  ossl::BIO* wbio = nullptr;  // ciphertext out (SSL writes, we drain)
  bool failed = false;
  // plaintext written before the handshake finished (rare server-side);
  // flushed by the next feed that completes the handshake
  IOBuf pending_plain;

  ~SslSessionN() {
    if (ssl != nullptr) ossl::lib().ssl_free(ssl);  // frees both BIOs
  }
};

void ssl_session_free(SslSessionN* s) { delete s; }

// Requires sess->ssl_mu. Drains handshake/record output into *out.
static void ssl_drain_wbio_locked(SslSessionN* sess, IOBuf* out) {
  ossl::Lib& l = ossl::lib();
  char buf[16384];
  while (l.bio_ctrl_pending(sess->wbio) > 0) {
    int n = l.bio_read(sess->wbio, buf, sizeof(buf));
    if (n <= 0) break;
    out->append(buf, (size_t)n);
  }
}

// Requires sess->ssl_mu. Encrypts `plain` (fully — memory BIOs always accept)
// into *cipher_out. Returns false on TLS failure.
static bool ssl_encrypt_locked(NatSocket* s, SslSessionN* sess,
                               IOBuf&& plain, IOBuf* cipher_out) {
  ossl::Lib& l = ossl::lib();
  char tmp[16384];
  while (!plain.empty()) {
    size_t n = plain.length() < sizeof(tmp) ? plain.length() : sizeof(tmp);
    const char* p = plain.fetch(tmp, n);
    int w = l.ssl_write(sess->ssl, p, (int)n);
    if (w <= 0) {
      int err = l.get_error(sess->ssl, w);
      if (err == ossl::kErrorWantRead || err == ossl::kErrorWantWrite) {
        // handshake not finished: park the remainder; the feed path
        // flushes it once SSL_read completes the handshake
        sess->pending_plain.append(std::move(plain));
        ssl_drain_wbio_locked(sess, cipher_out);
        return true;
      }
      sess->failed = true;
      return false;
    }
    plain.pop_front((size_t)w);
  }
  ssl_drain_wbio_locked(sess, cipher_out);
  return true;
}

// Feed `n` ciphertext bytes; decrypted plaintext appends to s->in_buf and
// any TLS output (handshake records, parked responses) queues on the
// socket. Returns false on fatal TLS error (caller fails the socket).
bool ssl_feed(NatSocket* s, const char* data, size_t n) {
  SslSessionN* sess = s->ssl_sess;
  ossl::Lib& l = ossl::lib();
  IOBuf out;
  {
    std::lock_guard g(sess->ssl_mu);
    if (sess->failed) return false;
    size_t off = 0;
    while (off < n) {
      int w = l.bio_write(sess->rbio, data + off, (int)(n - off));
      if (w <= 0) {
        sess->failed = true;
        return false;
      }
      off += (size_t)w;
    }
    char buf[16384];
    while (true) {
      int r = l.ssl_read(sess->ssl, buf, sizeof(buf));
      if (r > 0) {
        s->in_buf.append(buf, (size_t)r);
        continue;
      }
      int err = l.get_error(sess->ssl, r);
      if (err == ossl::kErrorWantRead || err == ossl::kErrorWantWrite) {
        break;  // need more records (or to flush ours)
      }
      if (err == ossl::kErrorZeroReturn) {
        break;  // close_notify; EOF follows on the TCP level
      }
      sess->failed = true;
      return false;
    }
    ssl_drain_wbio_locked(sess, &out);
    if (!sess->pending_plain.empty()) {
      // the handshake may have just finished: flush parked plaintext
      IOBuf plain;
      plain.append(std::move(sess->pending_plain));
      if (!ssl_encrypt_locked(s, sess, std::move(plain), &out)) {
        return false;
      }
    }
    // queue while still holding sess->ssl_mu: record order on the wire must
    // match production order even against concurrent encrypt_and_write
    // callers (the wait-free push keeps wire order == queue order)
    if (!out.empty()) s->write_raw(std::move(out));
  }
  return true;
}

// Public encrypt entry for the write path (takes the session lock).
bool ssl_encrypt(NatSocket* s, IOBuf&& plain, IOBuf* cipher_out) {
  SslSessionN* sess = s->ssl_sess;
  std::lock_guard g(sess->ssl_mu);
  if (sess->failed) return false;
  return ssl_encrypt_locked(s, sess, std::move(plain), cipher_out);
}

// Encrypt AND queue under ONE session lock: record order on the wire
// must match encryption order, and two concurrent writers that encrypt
// A-then-B but queue B-then-A would corrupt the record stream (the peer
// MACs records sequentially). The MPSC write push happens under ssl_mu,
// so wire order is fixed here; the drain itself is lock-free.
int ssl_encrypt_and_write(NatSocket* s, IOBuf&& plain) {
  SslSessionN* sess = s->ssl_sess;
  std::lock_guard g(sess->ssl_mu);
  if (sess->failed) return -1;
  IOBuf cipher;
  if (!ssl_encrypt_locked(s, sess, std::move(plain), &cipher)) return -1;
  if (cipher.empty()) return 0;  // parked pre-handshake
  return s->write_raw(std::move(cipher));
}

// Sniffed a TLS record on a TLS-enabled server port: build the session.
bool ssl_accept_begin(NatSocket* s) {
  ossl::Lib& l = ossl::lib();
  if (!l.ok || s->server == nullptr || s->server->ssl_ctx == nullptr) {
    return false;
  }
  SslSessionN* sess = new SslSessionN();
  sess->ssl = l.ssl_new((ossl::SSL_CTX*)s->server->ssl_ctx);
  if (sess->ssl == nullptr) {
    delete sess;
    return false;
  }
  sess->rbio = l.bio_new(l.bio_s_mem());
  sess->wbio = l.bio_new(l.bio_s_mem());
  l.set_bio(sess->ssl, sess->rbio, sess->wbio);  // SSL owns the BIOs
  l.set_accept_state(sess->ssl);
  s->ssl_sess = sess;
  return true;
}

// ALPN selection (the next_protos of ServerSSLOptions): prefer h2 when
// the client offers it (gRPC requires the negotiation), else http/1.1,
// else accept without ALPN.
static int alpn_select(ossl::SSL*, const unsigned char** out,
                       unsigned char* outlen, const unsigned char* in,
                       unsigned int inlen, void*) {
  static const unsigned char kH2[] = "h2";
  static const unsigned char kH11[] = "http/1.1";
  for (const unsigned char* want : {kH2, kH11}) {
    size_t wl = strlen((const char*)want);
    unsigned int i = 0;
    while (i < inlen) {
      unsigned int plen = in[i];
      if (i + 1 + plen > inlen) break;
      if (plen == wl && memcmp(in + i + 1, want, wl) == 0) {
        *out = in + i + 1;
        *outlen = (unsigned char)plen;
        return 0;  // SSL_TLSEXT_ERR_OK
      }
      i += 1 + plen;
    }
  }
  return 3;  // SSL_TLSEXT_ERR_NOACK: proceed without ALPN
}

extern "C" {

// Configure TLS on the running native server (ServerSSLOptions role):
// PEM cert chain + private key. Returns 0, -1 when no server is running
// or the files are unusable, -2 when libssl is unavailable.
int nat_rpc_server_ssl(const char* cert_path, const char* key_path) {
  ossl::Lib& l = ossl::lib();
  if (!l.ok) return -2;
  std::lock_guard g(g_rt_mu);
  NatServer* srv = g_rpc_server;
  if (srv == nullptr) return -1;
  ossl::SSL_CTX* ctx = l.ctx_new(l.tls_server_method());
  if (ctx == nullptr) return -1;
  if (l.ctx_use_cert_chain(ctx, cert_path) != 1 ||
      l.ctx_use_privkey(ctx, key_path, ossl::kFiletypePem) != 1) {
    return -1;  // ctx intentionally not freed: no SSL_CTX_free needed
                // on this failure path more than once per process
  }
  if (l.ctx_set_alpn_select_cb != nullptr) {
    l.ctx_set_alpn_select_cb(ctx, alpn_select, nullptr);
  }
  srv->ssl_ctx = ctx;
  return 0;
}

}  // extern "C"

}  // namespace brpc_tpu
