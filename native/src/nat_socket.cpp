// NatSocket + versioned-id registry + the io_uring datapath seam.
//
// This is the native counterpart of brpc::Socket (socket.cpp): a
// versioned-id registry (socket_inl.h:28-185), a single-writer write queue
// with inline first attempt + KeepWrite fiber on partial writes (the
// lock+deque rendition of the wait-free design, socket.h:293-333),
// SetFailed draining queued writes, and the RingListener fixed-buffer send
// lane (the fork's io_uring discipline).
#include "nat_internal.h"

namespace brpc_tpu {

// ---------------------------------------------------------------------------
// registry
// ---------------------------------------------------------------------------

std::atomic<std::atomic<NatSocket*>*> g_sock_slab[kSockSlabs];
NatMutex<kLockRankSockAlloc> g_sock_alloc_mu;
// Leaked on purpose: fibers on detached workers allocate/release socket
// slots through exit(); a destructed free list here is a use-after-free.
std::vector<uint32_t>& g_sock_free = *new std::vector<uint32_t>();
uint32_t g_sock_next_idx = 0;

// Allocate (or reuse) a socket slot; the returned socket has refcount 1
// (the registry/creator reference) and a fresh version in both its id and
// its versioned_ref.
NatSocket* sock_create() {
  uint32_t idx;
  NatSocket* s = nullptr;
  {
    std::lock_guard g(g_sock_alloc_mu);
    if (!g_sock_free.empty()) {
      idx = g_sock_free.back();
      g_sock_free.pop_back();
      s = sock_at(idx);
    } else {
      idx = g_sock_next_idx++;
      uint32_t slab_i = idx >> kSockSlabBits;
      if (slab_i >= kSockSlabs) return nullptr;
      if (g_sock_slab[slab_i].load(std::memory_order_relaxed) == nullptr) {
        auto* slab = new std::atomic<NatSocket*>[kSockSlabSize]();
        g_sock_slab[slab_i].store(slab, std::memory_order_release);
      }
      // construct + publish while still holding the alloc lock so the
      // hwm-bounded server-stop scan can never see a half-built socket
      // (the slot store is release; sock_at loads acquire)
      s = new NatSocket();  // lives forever in its slot
      g_sock_slab[slab_i].load(std::memory_order_acquire)
          [idx & (kSockSlabSize - 1)]
              .store(s, std::memory_order_release);
      s = nullptr;  // fall through to the common init below
    }
  }
  if (s == nullptr) {
    s = sock_at(idx);
  } else {
    s->reset_for_reuse();
  }
  uint32_t ver = s->next_version++;
  if (ver == 0) ver = s->next_version++;  // version 0 reserved (= dead)
  s->id = ((uint64_t)ver << 32) | idx;
  s->versioned_ref.store(((uint64_t)ver << 32) | 1,
                         std::memory_order_release);
  return s;
}

// Address with a borrowed reference (caller must release()); nullptr once
// the id generation is stale — use-after-free-proof, lock-free.
NatSocket* sock_address(uint64_t id) {
  uint32_t idx = (uint32_t)(id & 0xffffffffu);
  uint32_t ver = (uint32_t)(id >> 32);
  NatSocket* s = sock_at(idx);
  if (s == nullptr) return nullptr;
  uint64_t vr = s->versioned_ref.load(std::memory_order_acquire);
  while ((uint32_t)(vr >> 32) == ver && (uint32_t)vr != 0) {
    if (s->versioned_ref.compare_exchange_weak(vr, vr + 1,
                                               std::memory_order_acq_rel)) {
      return s;
    }
  }
  return nullptr;
}

// Invalidate the id (bump the version, keeping the refcount) so future
// sock_address calls fail; existing references stay valid until released.
void sock_unregister(NatSocket* s) {
  uint64_t vr = s->versioned_ref.load(std::memory_order_acquire);
  while (true) {
    uint64_t bumped = vr + (1ull << 32);
    if (s->versioned_ref.compare_exchange_weak(vr, bumped,
                                               std::memory_order_acq_rel)) {
      s->next_version = (uint32_t)(bumped >> 32) + 1;
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// NatSocket
// ---------------------------------------------------------------------------

RingListener* g_ring = nullptr;
std::atomic<bool> g_use_ring{false};
std::atomic<bool> g_ring_draining{false};
static NatMutex<kLockRankRingRetry> g_ring_retry_mu;
// sockets w/ unsubmitted sends; leaked — the ring poller and workers may
// still push retries while exit() destroys statics
static std::vector<uint64_t>& g_ring_retry = *new std::vector<uint64_t>();

void NatSocket::release() {
  uint64_t prev = versioned_ref.fetch_sub(1, std::memory_order_acq_rel);
  if ((uint32_t)prev == 1) {
    // Deferred close (brpc defers to refcount-zero too, socket.cpp): the
    // fd number is only recycled once no fiber can still syscall on it,
    // so a stale writev can never land on a reused descriptor. The object
    // itself is NEVER freed (ResourcePool discipline) — its slot goes
    // back to the freelist for the next sock_create.
    if (fd >= 0) {
      ::close(fd);
      fd = -1;
    }
    if (channel != nullptr) {
      channel->release();
      channel = nullptr;
    }
    if (server != nullptr) {
      server->release();
      server = nullptr;
    }
    if (http != nullptr) {
      http_session_free(http);
      http = nullptr;
    }
    if (h2 != nullptr) {
      h2_session_free(h2);
      h2 = nullptr;
    }
    if (ssl_sess != nullptr) {
      ssl_session_free(ssl_sess);
      ssl_sess = nullptr;
    }
    if (redis != nullptr) {
      redis_session_free(redis);
      redis = nullptr;
    }
    if (fill_req != nullptr) {  // connection died mid-payload
      delete fill_req;
      fill_req = nullptr;
      fill_off = 0;
    }
    if (httpc != nullptr) {
      http_cli_free(httpc);
      httpc = nullptr;
    }
    if (h2c != nullptr) {
      h2_cli_free(h2c);
      h2c = nullptr;
    }
    in_buf.clear();
    {
      std::lock_guard g(write_mu);
      write_q.clear();
    }
    uint32_t idx = (uint32_t)(id & 0xffffffffu);
    std::lock_guard g(g_sock_alloc_mu);
    g_sock_free.push_back(idx);
  }
}

void NatSocket::reset_for_reuse() {
  fd = -1;
  disp = nullptr;
  server = nullptr;
  channel = nullptr;
  failed.store(false, std::memory_order_relaxed);
  writing = false;
  defer_writes = false;
  epoll_events = 0;
  epollout.value.store(0, std::memory_order_relaxed);
  ring_ref.store(-1, std::memory_order_relaxed);
  ring_sending = false;
  ring_inflight = 0;
  py_raw.store(false, std::memory_order_relaxed);
  py_raw_seq = 0;
  py_streams.store(false, std::memory_order_relaxed);
  stream_seq = 0;
  fill_req = nullptr;
  fill_off = 0;
  http = nullptr;
  h2 = nullptr;
  redis = nullptr;
  httpc = nullptr;
  h2c = nullptr;
  ssl_sess = nullptr;
  ssl_declined = false;
  close_after_drain.store(false, std::memory_order_relaxed);
}

void NatSocket::set_failed() {
  bool was = failed.exchange(true, std::memory_order_seq_cst);
  if (was) return;
  {
    int64_t rr = ring_ref.exchange(-1, std::memory_order_acq_rel);
    if (rr >= 0 && g_ring != nullptr) {
      g_ring->unregister_file((int)(rr & 0xffffffff));  // cancels recv
    }
  }
  {
    std::lock_guard g(write_mu);
    write_q.clear();
    writing = false;
    ring_sending = false;
    ring_inflight = 0;
  }
  if (fd >= 0) {
    epoll_ctl(disp->epfd, EPOLL_CTL_DEL, fd, nullptr);
    // shutdown (not close): in-flight reader/KeepWrite syscalls return
    // with EOF/EPIPE instead of racing a recycled fd number.
    ::shutdown(fd, SHUT_RDWR);
  }
  // wake any KeepWrite parked on EPOLLOUT
  epollout.value.fetch_add(1, std::memory_order_release);
  Scheduler::butex_wake(&epollout, INT32_MAX);
  if ((py_raw.load(std::memory_order_acquire) ||
       py_streams.load(std::memory_order_acquire)) &&
      server != nullptr) {
    // tell the Python protocol stack to drop this connection's session
    PyRequest* r = new PyRequest();
    r->kind = 2;
    r->sock_id = id;
    server->enqueue_py(r);
  }
  if (channel != nullptr) {
    // read-until-close HTTP bodies: EOF IS the response terminator —
    // complete the accumulated call before fail_all can error it
    if (httpc != nullptr) http_cli_on_socket_fail(this);
    if (channel->sock_id.load(std::memory_order_acquire) == id) {
      channel->fail_all(kEFAILEDSOCKET, "socket failed");
      if (channel->health_check_interval_ms > 0 &&
          !channel->closed.load(std::memory_order_acquire) &&
          !channel->hc_pending.exchange(true, std::memory_order_acq_rel)) {
        channel->add_ref();  // held by the revival chain
        // fresh chain: the FIRST retry fires at the base interval; the
        // dial fiber grows the delay exponentially from there
        channel->hc_backoff_shift.store(0, std::memory_order_relaxed);
        TimerThread::instance()->schedule(health_check_fire, channel,
                                          channel->health_check_interval_ms);
      }
    } else {
      // already detached (GOAWAY drain): the channel's other pendings
      // ride the replacement socket and must survive — fail only the
      // streams this socket still owns. DEFERRED to a fiber: set_failed
      // can fire on a thread already inside h2c_mu (the reading thread's
      // window flush writing on a dying socket), and the sweep locks
      // h2c_mu — sweeping inline would self-deadlock (found by
      // tools/natcheck lockorder). With the scheduler stopped no such
      // thread exists (no fibers, no dispatchers feeding this socket),
      // so the inline sweep is both safe and the only way the pendings
      // still complete.
      if (Scheduler::instance()->started()) {
        add_ref();  // released by the sweep fiber
        // natcheck:allow(lock-switch): runs on a fresh fiber stack
        Scheduler::instance()->spawn_detached(
            [](void* raw) {
              NatSocket* s = (NatSocket*)raw;
              h2c_fail_own_streams(s, kEFAILEDSOCKET, "socket failed");
              s->release();
            },
            this);
      } else {
        h2c_fail_own_streams_teardown(this, kEFAILEDSOCKET,
                                      "socket failed");
      }
    }
  }
  if (server != nullptr) server->connections.fetch_sub(1, std::memory_order_relaxed);
  sock_unregister(this);
  release();  // drop the registry's reference
}

void NatSocket::arm_epollout() {
  std::lock_guard g(write_mu);
  if (failed.load(std::memory_order_acquire)) return;
  uint32_t want = EPOLLIN | EPOLLET | EPOLLOUT;
  if (epoll_events == want) return;
  struct epoll_event ev;
  ev.events = want;
  ev.data.u64 = id;
  if (epoll_ctl(disp->epfd, EPOLL_CTL_MOD, fd, &ev) == 0) epoll_events = want;
}

void NatSocket::disarm_epollout() {
  std::lock_guard g(write_mu);
  if (failed.load(std::memory_order_acquire)) return;
  uint32_t want = EPOLLIN | EPOLLET;
  if (epoll_events == want) return;
  struct epoll_event ev;
  ev.events = want;
  ev.data.u64 = id;
  if (epoll_ctl(disp->epfd, EPOLL_CTL_MOD, fd, &ev) == 0) epoll_events = want;
}

bool NatSocket::flush_some() {
  while (true) {
    IOBuf batch;
    {
      std::lock_guard g(write_mu);
      if (write_q.empty()) {
        writing = false;
        if (close_after_drain.load(std::memory_order_acquire) &&
            !failed.load(std::memory_order_acquire)) {
          // Connection: close — everything flushed; FIN follows the
          // last response byte (shutdown flushes kernel-buffered data)
          break;
        }
        return true;
      }
      batch.append(std::move(write_q));  // take the whole queue: syscall
                                         // batching across responses
    }
    while (!batch.empty()) {
      // natfault write site: injected errno (EPIPE/ECONNRESET fail the
      // socket; EINTR/EAGAIN exercise the requeue + KeepWrite path),
      // short writes (1-byte truncation), dropped batches (bytes vanish
      // — the retry/backup machinery must recover). NF_DELAY is NOT
      // honored here: flush_some runs under session locks on the py
      // responder paths, and no NatMutex may be held across a sleep
      // (express slow-writer scenarios as read delays on the peer).
      NatFaultAct fwa = NAT_FAULT_POINT(NF_WRITE);
      ssize_t n;
      if (fwa.action == NF_ERR) {
        errno = fwa.err;
        n = -1;
      } else if (fwa.action == NF_DROP) {
        n = (ssize_t)batch.length();  // pretend the kernel took it all
        batch.clear();
      } else {
        n = batch.cut_into_fd(fd, fwa.action == NF_SHORT ? 1 : SIZE_MAX);
      }
      if (n > 0) nat_counter_add(NS_SOCK_WRITE_BYTES, (uint64_t)n);
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
          // put leftovers back at the FRONT (later writes are behind us)
          std::lock_guard g(write_mu);
          batch.append(std::move(write_q));
          write_q = std::move(batch);
          return false;
        }
        set_failed();
        return true;
      }
    }
  }
  set_failed();  // close_after_drain: queue empty, bytes flushed
  return true;
}

void keep_write_fiber(void* arg) {
  NatSocket* s = (NatSocket*)arg;
  while (!s->failed.load(std::memory_order_acquire)) {
    if (s->flush_some()) break;  // common case: drained, no epoll_ctl
    int32_t expected = s->epollout.value.load(std::memory_order_acquire);
    s->arm_epollout();
    // second attempt covers a became-writable-before-arm race
    if (s->flush_some()) break;
    Scheduler::butex_wait(&s->epollout, expected);
  }
  s->disarm_epollout();
  s->release();
}

// Submits the front of write_q as one fixed-buffer send. Requires
// write_mu. Returns false when no buffer/SQE was free (retry later via
// the drain loop's retry list).
static bool ring_submit_locked(NatSocket* s) {
  if (s->ring_sending || s->write_q.empty()
      || s->failed.load(std::memory_order_acquire)) {
    return true;
  }
  int64_t rr = s->ring_ref.load(std::memory_order_acquire);
  if (rr < 0) return true;  // demoted/failed; bytes drain elsewhere
  uint16_t buf;
  char* dst = g_ring->acquire_send_buffer(&buf);
  if (dst == nullptr) return false;
  size_t n = s->write_q.length();
  if (n > RingListener::kSendBufSize) n = RingListener::kSendBufSize;
  s->write_q.copy_to(dst, n);  // straight into registered memory
  if (!g_ring->submit_send((int)(rr & 0xffffffff), (uint32_t)(rr >> 32),
                           s->id, buf, n)) {
    return false;
  }
  s->ring_sending = true;
  s->ring_inflight = n;
  return true;
}

static void ring_retry_later(uint64_t sock_id) {
  std::lock_guard g(g_ring_retry_mu);
  g_ring_retry.push_back(sock_id);
}

int NatSocket::write(IOBuf&& frame) {
  if (ssl_sess != nullptr) {
    int rc = ssl_encrypt_and_write(this, std::move(frame));
    if (rc < 0) set_failed();
    return rc;
  }
  return write_raw(std::move(frame));
}

int NatSocket::write_raw(IOBuf&& frame) {
  if (failed.load(std::memory_order_acquire)) return -1;
  if (ring_ref.load(std::memory_order_acquire) >= 0) {
    // io_uring lane: queue + submit from registered send memory; ordering
    // is kept by the single-in-flight discipline.
    bool need_retry;
    {
      std::lock_guard g(write_mu);
      if (failed.load(std::memory_order_acquire)) return -1;
      write_q.append(std::move(frame));
      need_retry = !ring_submit_locked(this);
    }
    if (need_retry) ring_retry_later(id);
    return 0;
  }
  bool become_writer = false;
  {
    std::lock_guard g(write_mu);
    if (failed.load(std::memory_order_acquire)) return -1;
    write_q.append(std::move(frame));
    if (!writing) {
      writing = true;
      become_writer = true;
    }
  }
  if (!become_writer) return 0;  // active writer will drain us
  if (defer_writes) {
    // Batch mode: the writer fiber runs AFTER the currently-ready fibers,
    // so their appends coalesce into one writev.
    add_ref();
    Scheduler::instance()->spawn_detached_back(keep_write_fiber, this);
    return 0;
  }
  // Inline first attempt on the caller's thread/fiber (socket.cpp:1287);
  // leftovers go to a KeepWrite fiber waiting on EPOLLOUT.
  if (!flush_some()) {
    add_ref();
    Scheduler::instance()->spawn_detached(keep_write_fiber, this);
  }
  return 0;
}

// ---------------------------------------------------------------------------
// ring lane (completion drain, demotion, adoption)
// ---------------------------------------------------------------------------

// After a socket leaves the ring lane with bytes still queued, no sender
// owns them (ring_submit_locked no-ops on demoted sockets): hand them to
// the epoll KeepWrite lane or the peer hangs waiting for a response.
void kick_epoll_writer_if_stranded(NatSocket* s) {
  bool kick = false;
  {
    std::lock_guard g(s->write_mu);
    if (s->ring_ref.load(std::memory_order_acquire) < 0 &&
        !s->write_q.empty() && !s->writing && !s->ring_sending &&
        !s->failed.load(std::memory_order_acquire)) {
      s->writing = true;
      kick = true;
    }
  }
  if (kick) {
    s->add_ref();
    Scheduler::instance()->spawn_detached(keep_write_fiber, s);
  }
}

// Moves a ring socket to the epoll lane (rearm impossible / multishot
// unsupported); the CAS makes demotion and set_failed mutually exclusive.
static void ring_demote_to_epoll(NatSocket* s, int64_t rr) {
  if (s->ring_ref.compare_exchange_strong(rr, -1,
                                          std::memory_order_seq_cst)) {
    g_ring->unregister_file((int)(rr & 0xffffffff));
    s->disp->add_consumer(s);
    kick_epoll_writer_if_stranded(s);
  }
}

// Drains harvested ring completions — the wait_task drain of the fork
// (task_group.cpp:158-169): recv bytes feed the SAME cut loop the epoll
// readers use; send completions recycle fixed buffers and launch the next
// chunk. Registered as a scheduler idle hook; one worker drains at a time
// so per-socket completion order is preserved.
bool ring_drain() {
  if (g_ring == nullptr) return false;
  if (g_ring_draining.exchange(true, std::memory_order_acquire)) {
    return false;
  }
  bool did = false;
  RingCompletion c;
  while (g_ring->pop_completion(&c)) {
    did = true;
    NatSocket* s = sock_address(c.tag);
    if (c.kind == 0) {  // recv
      if (c.res > 0) {
        if (s != nullptr && !s->failed.load(std::memory_order_acquire)) {
          nat_counter_add(NS_SOCK_READ_BYTES, (uint64_t)c.res);
          if (s->ssl_sess != nullptr) {
            // TLS: ciphertext feeds the session; plaintext lands in
            // in_buf inside ssl_feed
            if (!ssl_feed(s, g_ring->buffer_data(c.buf_id),
                          (size_t)c.res)) {
              g_ring->recycle_buffer(c.buf_id);
              s->set_failed();
              s->release();
              continue;
            }
          } else {
            const char* src = g_ring->buffer_data(c.buf_id);
            size_t len = (size_t)c.res;
            if (s->fill_req != nullptr) {
              // stream fill mode: payload bytes skip in_buf entirely
              size_t took = stream_fill_feed(s, src, len);
              if (took == SIZE_MAX) {  // allocation failed
                g_ring->recycle_buffer(c.buf_id);
                s->set_failed();
                s->release();
                continue;
              }
              src += took;
              len -= took;
            }
            if (len > 0) s->in_buf.append(src, len);
          }
          g_ring->recycle_buffer(c.buf_id);
          int64_t rr = s->ring_ref.load(std::memory_order_acquire);
          if (!process_input(s)) {
            s->set_failed();
          } else if (!c.more && rr >= 0 &&
                     !g_ring->rearm_recv((int)(rr & 0xffffffff),
                                         (uint32_t)(rr >> 32), s->id)) {
            ring_demote_to_epoll(s, rr);  // SQ full: don't go deaf
          }
        } else {
          g_ring->recycle_buffer(c.buf_id);  // owner gone: recycle only
        }
      } else if (s != nullptr) {
        int64_t rr = s->ring_ref.load(std::memory_order_acquire);
        if (c.res == -ENOBUFS) {
          // provided buffers were exhausted; they're recycled as we
          // drain, so re-arm and keep going
          if (rr >= 0 && !g_ring->rearm_recv((int)(rr & 0xffffffff),
                                             (uint32_t)(rr >> 32), s->id)) {
            ring_demote_to_epoll(s, rr);
          }
        } else if (c.res == -EINVAL && rr >= 0) {
          // kernel lacks multishot recv (pre-6.0): demote this
          // connection to the epoll lane instead of killing it
          ring_demote_to_epoll(s, rr);
        } else if (!c.more) {
          s->set_failed();  // EOF (0) or hard error
        }
      }
    } else {  // send
      g_ring->recycle_send_buffer(c.send_buf);
      if (s != nullptr) {
        if (c.res < 0) {
          s->set_failed();
        } else {
          bool need_retry;
          bool drained_close = false;
          {
            std::lock_guard g(s->write_mu);
            size_t done = (size_t)c.res;
            if (done > s->ring_inflight) done = s->ring_inflight;
            nat_counter_add(NS_SOCK_WRITE_BYTES, done);
            s->write_q.pop_front(done);
            s->ring_sending = false;
            s->ring_inflight = 0;
            need_retry = !ring_submit_locked(s);
            drained_close =
                s->write_q.empty() &&
                s->close_after_drain.load(std::memory_order_acquire);
          }
          if (drained_close) {
            s->set_failed();  // Connection: close — all bytes flushed
          } else {
            if (need_retry) ring_retry_later(s->id);
            // a demotion landing between completions leaves queued bytes
            // with no sender: hand them to the epoll write lane
            kick_epoll_writer_if_stranded(s);
          }
        }
      }
    }
    if (s != nullptr) s->release();
  }
  // retry sends that couldn't get a buffer/SQE earlier
  std::vector<uint64_t> retry;
  {
    std::lock_guard g(g_ring_retry_mu);
    retry.swap(g_ring_retry);
  }
  for (uint64_t sid : retry) {
    NatSocket* s = sock_address(sid);
    if (s == nullptr) continue;
    bool again;
    {
      std::lock_guard g(s->write_mu);
      again = !ring_submit_locked(s);
    }
    if (again) ring_retry_later(sid);
    kick_epoll_writer_if_stranded(s);
    s->release();
  }
  g_ring_draining.store(false, std::memory_order_release);
  return did;
}

// Put a freshly-connected fd on the ring lane when it is enabled (both
// directions then ride io_uring and drain on the poller — the accept
// path's twin). Returns true when the ring owns the socket's reads.
bool try_ring_adopt(NatSocket* s) {
  if (!g_use_ring.load(std::memory_order_acquire) || g_ring == nullptr) {
    return false;
  }
  uint32_t gen = 0;
  int fidx = g_ring->register_file(s->fd, &gen);
  if (fidx < 0) return false;
  int64_t rr = ((int64_t)gen << 32) | (uint32_t)fidx;
  s->ring_ref.store(rr, std::memory_order_release);
  if (g_ring->rearm_recv(fidx, gen, s->id)) return true;
  s->ring_ref.store(-1, std::memory_order_release);
  g_ring->unregister_file(fidx);
  return false;
}

}  // namespace brpc_tpu
