// NatSocket + versioned-id registry + the io_uring datapath seam.
//
// This is the native counterpart of brpc::Socket (socket.cpp): a
// versioned-id registry (socket_inl.h:28-185), the WAIT-FREE MPSC write
// stack (socket.h:293-333 — one atomic exchange enqueues, the empty-head
// winner becomes the single drainer; inline writev first attempt,
// leftovers to a KeepWrite fiber), SetFailed handing cleanup to the role
// holder, and the per-dispatcher RingListener fixed-buffer send lane (the
// fork's io_uring discipline).
//
// Drain-role ledger (who continues the drain after each transition):
//   push() == true           the pushing thread (write_raw/wdrive)
//   inline writev EAGAIN     a KeepWrite fiber parked on EPOLLOUT
//   ring send submitted      that send's completion (ring_drain)
//   ring SQE/buffer missing  a g_ring_retry entry (holds a socket ref)
//   socket failed            whoever holds the role: write_release_all
// The role is released ONLY by grab_more's head CAS to nullptr, so
// wstack.empty() is exactly the "all flushed, nobody writing" predicate.
#include "nat_internal.h"

namespace brpc_tpu {

// ---------------------------------------------------------------------------
// registry
// ---------------------------------------------------------------------------

std::atomic<std::atomic<NatSocket*>*> g_sock_slab[kSockSlabs];
NatMutex<kLockRankSockAlloc> g_sock_alloc_mu;
// natcheck:leak(g_sock_free): fibers on detached workers allocate/release
// socket slots through exit(); a destructed free list is a use-after-free.
std::vector<uint32_t>& g_sock_free = *new std::vector<uint32_t>();
uint32_t g_sock_next_idx = 0;

// Allocate (or reuse) a socket slot; the returned socket has refcount 1
// (the registry/creator reference) and a fresh version in both its id and
// its versioned_ref.
NatSocket* sock_create() {
  uint32_t idx;
  NatSocket* s = nullptr;
  {
    std::lock_guard g(g_sock_alloc_mu);
    if (!g_sock_free.empty()) {
      idx = g_sock_free.back();
      g_sock_free.pop_back();
      s = sock_at(idx);
    } else {
      idx = g_sock_next_idx++;
      uint32_t slab_i = idx >> kSockSlabBits;
      if (slab_i >= kSockSlabs) return nullptr;
      if (g_sock_slab[slab_i].load(std::memory_order_relaxed) == nullptr) {
        auto* slab = new std::atomic<NatSocket*>[kSockSlabSize]();
        NAT_RES_ALLOC(NR_SOCK_SLAB,
                      kSockSlabSize * sizeof(std::atomic<NatSocket*>),
                      slab);
        g_sock_slab[slab_i].store(slab, std::memory_order_release);
      }
      // construct + publish while still holding the alloc lock so the
      // hwm-bounded server-stop scan can never see a half-built socket
      // (the slot store is release; sock_at loads acquire)
      // natcheck:leak(sock_create): ResourcePool discipline — sockets
      // and their slabs are never freed; slot indices stay valid forever
      s = new NatSocket();  // lives forever in its slot
      NAT_RES_ALLOC(NR_SOCK_SLAB, sizeof(NatSocket), s);
      g_sock_slab[slab_i].load(std::memory_order_acquire)
          [idx & (kSockSlabSize - 1)]
              .store(s, std::memory_order_release);
      s = nullptr;  // fall through to the common init below
    }
  }
  if (s == nullptr) {
    s = sock_at(idx);
  } else {
    s->reset_for_reuse();
  }
  uint32_t ver = s->next_version++;
  if (ver == 0) ver = s->next_version++;  // version 0 reserved (= dead)
  s->id = ((uint64_t)ver << 32) | idx;
  // the initial refcount IS the creator/registry reference; set_failed
  // retires it after sock_unregister
  NAT_REF_ACQUIRED(s, sock.registry);
  s->versioned_ref.store(((uint64_t)ver << 32) | 1,
                         std::memory_order_release);
  return s;
}

// Address with a borrowed reference (caller must release()); nullptr once
// the id generation is stale — use-after-free-proof, lock-free.
NatSocket* sock_address(uint64_t id) {
  uint32_t idx = (uint32_t)(id & 0xffffffffu);
  uint32_t ver = (uint32_t)(id >> 32);
  NatSocket* s = sock_at(idx);
  if (s == nullptr) return nullptr;
  uint64_t vr = s->versioned_ref.load(std::memory_order_acquire);
  while ((uint32_t)(vr >> 32) == ver && (uint32_t)vr != 0) {
    if (s->versioned_ref.compare_exchange_weak(vr, vr + 1,
                                               std::memory_order_acq_rel)) {
      // the CAS above IS the count change: a sock.borrow the caller
      // must release (the Address/SetFailed discipline's borrow half)
      NAT_REF_ACQUIRED(s, sock.borrow);
      return s;
    }
  }
  return nullptr;
}

// Version-blind pin (nat_conn_snapshot): any nonzero refcount pins the
// slot against recycling; the version is irrelevant because the walker
// starts from the slot, not from an id.
NatSocket* sock_try_pin(NatSocket* s) {
  uint64_t vr = s->versioned_ref.load(std::memory_order_acquire);
  while ((uint32_t)vr != 0) {  // no refs: free / being recycled
    if (s->versioned_ref.compare_exchange_weak(
            vr, vr + 1, std::memory_order_acq_rel)) {
      NAT_REF_ACQUIRED(s, sock.borrow);
      return s;
    }
  }
  return nullptr;
}

// Invalidate the id (bump the version, keeping the refcount) so future
// sock_address calls fail; existing references stay valid until released.
void sock_unregister(NatSocket* s) {
  uint64_t vr = s->versioned_ref.load(std::memory_order_acquire);
  while (true) {
    uint64_t bumped = vr + (1ull << 32);
    if (s->versioned_ref.compare_exchange_weak(vr, bumped,
                                               std::memory_order_acq_rel)) {
      s->next_version = (uint32_t)(bumped >> 32) + 1;
      return;
    }
  }
}

// ---------------------------------------------------------------------------
// WriteReq pool — per-thread freelist (ObjectPool discipline): the per-
// write allocation on the hot path is a TLS pop, and a node freed by the
// drainer on another core re-enters THAT core's cache.
// ---------------------------------------------------------------------------

namespace {
struct WreqCache {
  static const int kCap = 64;
  WriteReq* head = nullptr;
  int n = 0;
  ~WreqCache() {
    while (head != nullptr) {
      WriteReq* next = head->wnext.load(std::memory_order_relaxed);
      NAT_RES_FREE(NR_SOCK_WREQ, sizeof(WriteReq), head);
      delete head;
      head = next;
    }
  }
};
thread_local WreqCache tls_wreq;
}  // namespace

WriteReq* wreq_alloc() {
  WreqCache& c = tls_wreq;
  WriteReq* r;
  if (c.head != nullptr) {
    r = c.head;
    c.head = r->wnext.load(std::memory_order_relaxed);
    c.n--;
  } else {
    r = new WriteReq();
    NAT_RES_ALLOC(NR_SOCK_WREQ, sizeof(WriteReq), r);
  }
  // a live write-stack node until the drainer's wreq_free
  NAT_REF_ACQUIRED(r, wreq.node);
  return r;
}

void wreq_free(WriteReq* r) {
  NAT_REF_RELEASED(r, wreq.node);
  r->data.clear();
  WreqCache& c = tls_wreq;
  if (c.n >= WreqCache::kCap) {
    NAT_RES_FREE(NR_SOCK_WREQ, sizeof(WriteReq), r);
    delete r;
    return;
  }
  r->wnext.store(c.head, std::memory_order_relaxed);
  c.head = r;
  c.n++;
}

// ---------------------------------------------------------------------------
// NatSocket
// ---------------------------------------------------------------------------

// natcheck:leak(g_rings): ring pollers run through exit()
std::vector<RingListener*>& g_rings = *new std::vector<RingListener*>();
// g_rings is built ONCE (under g_rt_mu, only when empty) and never
// mutated again; every lock-free reader gates on this flag (release
// store after the build, acquire loads) so no iteration can race the
// vector's growth reallocations.
std::atomic<bool> g_rings_ready{false};
std::atomic<bool> g_use_ring{false};
static NatMutex<kLockRankRingRetry> g_ring_retry_mu;
// sockets whose parked drain role waits for a free SQE/send buffer; each
// entry holds a socket reference AND the drain role.
// natcheck:leak(g_ring_retry): the ring pollers and workers may still
// push retries while exit() destroys statics.
static std::vector<NatSocket*>& g_ring_retry = *new std::vector<NatSocket*>();

static void ring_retry_park(NatSocket* s) {
  NAT_REF_ACQUIRE(s, sock.ringretry);  // the retry pass inherits the role
  std::lock_guard g(g_ring_retry_mu);
  g_ring_retry.push_back(s);
}

void NatSocket::release() {
  uint64_t prev = versioned_ref.fetch_sub(1, std::memory_order_acq_rel);
  if ((uint32_t)prev == 1) {
    // Deferred close (brpc defers to refcount-zero too, socket.cpp): the
    // fd number is only recycled once no fiber can still syscall on it,
    // so a stale writev can never land on a reused descriptor. The object
    // itself is NEVER freed (ResourcePool discipline) — its slot goes
    // back to the freelist for the next sock_create.
    if (fd >= 0) {
      ::close(fd);
      fd = -1;
    }
    if (channel != nullptr) {
      NAT_REF_RELEASE(channel, chan.sock);
      channel = nullptr;
    }
    if (server != nullptr) {
      NAT_REF_RELEASE(server, srv.sock);
      server = nullptr;
    }
    if (http != nullptr) {
      http_session_free(http);
      http = nullptr;
    }
    if (h2 != nullptr) {
      h2_session_free(h2);
      h2 = nullptr;
    }
    if (ssl_sess != nullptr) {
      ssl_session_free(ssl_sess);
      ssl_sess = nullptr;
    }
    if (redis != nullptr) {
      redis_session_free(redis);
      redis = nullptr;
    }
    if (fill_req != nullptr) {  // connection died mid-payload
      delete fill_req;
      fill_req = nullptr;
      fill_off = 0;
    }
    bulk_fill_abort(this);  // died mid-bulk-frame: slab back to the pool
    if (httpc != nullptr) {
      http_cli_free(httpc);
      httpc = nullptr;
    }
    if (h2c != nullptr) {
      h2_cli_free(h2c);
      h2c = nullptr;
    }
    in_buf.clear();
    // refcount zero: no writer and no drainer can still reference this
    // socket, so any leftover drain state (a failed socket whose role
    // holder already cleaned up leaves none) is safely reclaimed here.
    wbuf.clear();
    NAT_REF_DEAD(this);  // refguard: every tag must balance to zero here
    uint32_t idx = (uint32_t)(id & 0xffffffffu);
    std::lock_guard g(g_sock_alloc_mu);
    g_sock_free.push_back(idx);
  }
}

void NatSocket::reset_for_reuse() {
  fd = -1;
  disp = nullptr;
  server = nullptr;
  channel = nullptr;
  failed.store(false, std::memory_order_relaxed);
  wcur = nullptr;
  wbuf.clear();
  defer_writes = false;
  epoll_events = 0;
  epollout.value.store(0, std::memory_order_relaxed);
  ring_ref.store(-1, std::memory_order_relaxed);
  ring = nullptr;
  ring_sending = false;
  ring_inflight = 0;
  py_raw.store(false, std::memory_order_relaxed);
  py_raw_seq = 0;
  py_streams.store(false, std::memory_order_relaxed);
  stream_seq = 0;
  fill_req = nullptr;
  fill_off = 0;
  bulk_buf = nullptr;
  bulk_cap = 0;
  bulk_len = 0;
  bulk_off = 0;
  http = nullptr;
  h2 = nullptr;
  redis = nullptr;
  httpc = nullptr;
  h2c = nullptr;
  ssl_sess = nullptr;
  ssl_declined = false;
  close_after_drain.store(false, std::memory_order_relaxed);
  spoke_tpu_std.store(false, std::memory_order_relaxed);
  conn_visible.store(false, std::memory_order_relaxed);
  c_in_bytes.store(0, std::memory_order_relaxed);
  c_out_bytes.store(0, std::memory_order_relaxed);
  c_in_msgs.store(0, std::memory_order_relaxed);
  c_out_msgs.store(0, std::memory_order_relaxed);
  c_read_calls.store(0, std::memory_order_relaxed);
  c_write_calls.store(0, std::memory_order_relaxed);
  c_unwritten.store(0, std::memory_order_relaxed);
  c_rdbuf.store(0, std::memory_order_relaxed);
  c_parked.store(0, std::memory_order_relaxed);
  peer[0] = '\0';
}

void sock_set_peer_fd(NatSocket* s) {
  struct sockaddr_in sa;
  socklen_t sl = sizeof(sa);
  if (getpeername(s->fd, (struct sockaddr*)&sa, &sl) != 0 ||
      sa.sin_family != AF_INET) {
    return;
  }
  char ip[INET_ADDRSTRLEN] = {0};
  inet_ntop(AF_INET, &sa.sin_addr, ip, sizeof(ip));
  sock_set_peer(s, ip, (int)ntohs(sa.sin_port));
}

void NatSocket::set_failed() {
  bool was = failed.exchange(true, std::memory_order_seq_cst);
  if (was) return;
  {
    int64_t rr = ring_ref.exchange(-1, std::memory_order_acq_rel);
    if (rr >= 0 && ring != nullptr) {
      ring->unregister_file((int)(rr & 0xffffffff));  // cancels recv
    }
  }
  // Queued writes are NOT touched here: the drain role holder (inline
  // writer, KeepWrite fiber, ring completion, retry entry) observes
  // `failed` and runs write_release_all — cleanup follows the role, so
  // no lock is needed and no chain can leak.
  if (fd >= 0) {
    epoll_ctl(disp->epfd, EPOLL_CTL_DEL, fd, nullptr);
    // shutdown (not close): in-flight reader/KeepWrite syscalls return
    // with EOF/EPIPE instead of racing a recycled fd number.
    ::shutdown(fd, SHUT_RDWR);
  }
  // wake any KeepWrite parked on EPOLLOUT
  epollout.value.fetch_add(1, std::memory_order_release);
  Scheduler::butex_wake(&epollout, INT32_MAX);
  if ((py_raw.load(std::memory_order_acquire) ||
       py_streams.load(std::memory_order_acquire)) &&
      server != nullptr) {
    // tell the Python protocol stack to drop this connection's session
    // natcheck:allow(resacct): PyRequest self-accounts in its ctor
    PyRequest* r = new PyRequest();
    r->kind = 2;
    r->sock_id = id;
    server->enqueue_py(r);
  }
  if (channel != nullptr) {
    // read-until-close HTTP bodies: EOF IS the response terminator —
    // complete the accumulated call before fail_all can error it
    if (httpc != nullptr) http_cli_on_socket_fail(this);
    if (channel->sock_id.load(std::memory_order_acquire) == id) {
      channel->fail_all(kEFAILEDSOCKET, "socket failed");
      if (channel->health_check_interval_ms > 0 &&
          !channel->closed.load(std::memory_order_acquire) &&
          !channel->hc_pending.exchange(true, std::memory_order_acq_rel)) {
        NAT_REF_ACQUIRE(channel, chan.revival);
        // fresh chain: the FIRST retry fires at the base interval; the
        // dial fiber grows the delay exponentially from there
        channel->hc_backoff_shift.store(0, std::memory_order_relaxed);
        TimerThread::instance()->schedule(health_check_fire, channel,
                                          channel->health_check_interval_ms);
      }
    } else {
      // already detached (GOAWAY drain): the channel's other pendings
      // ride the replacement socket and must survive — fail only the
      // streams this socket still owns. DEFERRED to a fiber: set_failed
      // can fire on a thread already inside h2c_mu (the reading thread's
      // window flush writing on a dying socket), and the sweep locks
      // h2c_mu — sweeping inline would self-deadlock (found by
      // tools/natcheck lockorder). With the scheduler stopped no such
      // thread exists (no fibers, no dispatchers feeding this socket),
      // so the inline sweep is both safe and the only way the pendings
      // still complete.
      if (Scheduler::instance()->started()) {
        NAT_REF_ACQUIRE(this, sock.sweep);
        // natcheck:allow(lock-switch): runs on a fresh fiber stack
        Scheduler::instance()->spawn_detached(
            [](void* raw) {
              NatSocket* s = (NatSocket*)raw;
              h2c_fail_own_streams(s, kEFAILEDSOCKET, "socket failed");
              // lame-duck-drained HTTP socket: its pipeline FIFO's
              // stragglers complete as planned errors, not hangs
              http_cli_fail_own(s, kEFAILEDSOCKET, "connection drained");
              NAT_REF_RELEASE(s, sock.sweep);
            },
            this);
      } else {
        h2c_fail_own_streams_teardown(this, kEFAILEDSOCKET,
                                      "socket failed");
        http_cli_fail_own(this, kEFAILEDSOCKET, "connection drained",
                          /*teardown=*/true);
      }
    }
  }
  if (server != nullptr) server->connections.fetch_sub(1, std::memory_order_relaxed);
  if (disp != nullptr) {
    disp->sockets_owned.fetch_sub(1, std::memory_order_relaxed);
  }
  sock_unregister(this);
  NAT_REF_RELEASE(this, sock.registry);
}

// Connection-close arming — the store-buffer (Dekker) pairing with the
// drain-role release: we STORE the flag then LOAD the stack head; the
// role holder STORES the head (grab_more's CAS to nullptr) then LOADS
// the flag — with a seq_cst fence between each side's store and load,
// at least one side must observe the other, so a Connection: close can
// never be missed by both (the atomicity the old write_mu provided).
void NatSocket::arm_close_after_drain() {
  close_after_drain.store(true, std::memory_order_seq_cst);
  std::atomic_thread_fence(std::memory_order_seq_cst);
  if (write_idle() && !failed.load(std::memory_order_acquire)) {
    set_failed();
  }
}

void NatSocket::arm_epollout() {
  std::lock_guard g(epollctl_mu);
  if (failed.load(std::memory_order_acquire)) return;
  uint32_t want = EPOLLIN | EPOLLET | EPOLLOUT;
  if (epoll_events == want) return;
  struct epoll_event ev;
  ev.events = want;
  ev.data.u64 = id;
  if (epoll_ctl(disp->epfd, EPOLL_CTL_MOD, fd, &ev) == 0) epoll_events = want;
}

void NatSocket::disarm_epollout() {
  std::lock_guard g(epollctl_mu);
  if (failed.load(std::memory_order_acquire)) return;
  // a non-idle stack means a SUCCESSOR role holder exists (this fiber
  // already released the role) — it may just have armed EPOLLOUT for
  // its own park; disarming here would strand it without a wake (a
  // pre-existing race the role ledger makes checkable)
  if (!write_idle()) return;
  uint32_t want = EPOLLIN | EPOLLET;
  if (epoll_events == want) return;
  struct epoll_event ev;
  ev.events = want;
  ev.data.u64 = id;
  if (epoll_ctl(disp->epfd, EPOLL_CTL_MOD, fd, &ev) == 0) epoll_events = want;
}

// ---------------------------------------------------------------------------
// drain-role machinery (all functions below: role holder only)
// ---------------------------------------------------------------------------

// Fold every FIFO-linked node's bytes into wbuf, freeing the nodes as
// they empty — EXCEPT the chain terminator (wnext == nullptr), whose
// address doubles as the stack-head identity grab_more needs. Safe to
// call repeatedly: already-folded nodes are empty, new nodes linked by
// grab_more (or late-arriving pushers behind the terminator... which
// cannot happen — pushers go through the head) are appended in order.
void NatSocket::wgather() {
  WriteReq* r = wcur;
  while (true) {
    wbuf.append(std::move(r->data));
    WriteReq* next = r->wnext.load(std::memory_order_acquire);
    if (next == nullptr) {
      wcur = r;
      return;
    }
    wreq_free(r);
    r = next;
  }
}

// wbuf is empty: try to release the role. True = released (stack empty,
// terminator freed). False = fresh pushes arrived; they are gathered
// into wbuf and the drain continues.
bool NatSocket::wrefill() {
  WriteReq* last = wcur;
  // null BEFORE the role-releasing CAS: the next push-winner's plain
  // wcur store is ordered after the CAS (see write_push) — nulling
  // after would race it
  wcur = nullptr;
  WriteReq* more = wstack.grab_more(last);
  if (more == nullptr) {
    wreq_free(last);
    return true;
  }
  wcur = more;
  wreq_free(last);
  wgather();
  return false;
}

// Failed socket: free everything queued (including pushes racing in) and
// release the role. A writer that pushes AFTER this released checks
// `failed` post-push and cleans up after itself (write_raw).
void NatSocket::write_release_all() {
  // whatever is still queued will never reach the kernel
  c_unwritten.store(0, std::memory_order_relaxed);
  wbuf.clear();
  ring_sending = false;
  ring_inflight = 0;
  if (wcur == nullptr) return;
  while (true) {
    wgather();
    wbuf.clear();
    if (wrefill()) return;
  }
}

// Epoll-lane drain: gather + writev until empty (role released), EAGAIN
// (false: role retained, caller parks on EPOLLOUT) or failure (cleaned).
bool NatSocket::flush_chain() {
  while (true) {
    if (failed.load(std::memory_order_acquire)) {
      write_release_all();
      return true;
    }
    wgather();
    while (!wbuf.empty()) {
      // natfault write site: injected errno (EPIPE/ECONNRESET fail the
      // socket; EINTR/EAGAIN exercise the KeepWrite path), short writes
      // (1-byte truncation), dropped batches (bytes vanish — the
      // retry/backup machinery must recover). NF_DELAY is NOT honored
      // here: the inline first attempt runs under protocol session
      // locks on the py responder paths (express slow-writer scenarios
      // as read delays on the peer).
      NatFaultAct fwa = NAT_FAULT_POINT(NF_WRITE);
      ssize_t n;
      if (fwa.action == NF_ERR) {
        errno = fwa.err;
        n = -1;
      } else if (fwa.action == NF_DROP) {
        n = (ssize_t)wbuf.length();  // pretend the kernel took it all
        wbuf.clear();
      } else {
        n = wbuf.cut_into_fd(fd, fwa.action == NF_SHORT ? 1 : SIZE_MAX);
      }
      if (n > 0) {
        nat_counter_add(NS_SOCK_WRITE_BYTES, (uint64_t)n);
        c_out_bytes.fetch_add((uint64_t)n, std::memory_order_relaxed);
        c_write_calls.fetch_add(1, std::memory_order_relaxed);
        conn_unwritten_sub((uint64_t)n);
      }
      if (n < 0) {
        if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
          return false;  // role retained; caller parks on EPOLLOUT
        }
        set_failed();
        write_release_all();
        return true;
      }
    }
    if (wrefill()) {
      // role released: fence pairs with arm_close_after_drain (its
      // flag store + fence precede its head load — Dekker)
      std::atomic_thread_fence(std::memory_order_seq_cst);
      if (close_after_drain.load(std::memory_order_seq_cst) &&
          !failed.load(std::memory_order_acquire)) {
        // Connection: close — everything flushed; FIN follows the last
        // response byte (shutdown flushes kernel-buffered data)
        set_failed();
      }
      return true;
    }
  }
}

void keep_write_fiber(void* arg) {
  NatSocket* s = (NatSocket*)arg;
  while (true) {
    if (s->flush_chain()) break;  // drained or failed-and-cleaned
    int32_t expected = s->epollout.value.load(std::memory_order_acquire);
    s->arm_epollout();
    // second attempt covers a became-writable-before-arm race
    if (s->flush_chain()) break;
    Scheduler::butex_wait(&s->epollout, expected);
  }
  s->disarm_epollout();
  NAT_REF_RELEASE(s, sock.keepwrite);
}

// Ring-lane submission step — entered by a fresh drainer, a send
// completion, or the retry pass; the role holder either parks (send in
// flight / retry list) or finishes (released / failed / demoted-to-
// epoll continuation).
void NatSocket::wring_continue() {
  while (true) {
    if (failed.load(std::memory_order_acquire)) {
      write_release_all();
      return;
    }
    if (ring_sending) return;  // the completion continues the role
    wgather();
    int64_t rr = ring_ref.load(std::memory_order_acquire);
    if (rr < 0 || ring == nullptr) {
      // demoted mid-drain: the bytes continue on the epoll lane
      if (!flush_chain()) {
        NAT_REF_ACQUIRE(this, sock.keepwrite);
        Scheduler::instance()->spawn_detached(keep_write_fiber, this);
      }
      return;
    }
    if (wbuf.empty()) {
      if (wrefill()) {
        // role released: Dekker fence vs arm_close_after_drain
        std::atomic_thread_fence(std::memory_order_seq_cst);
        if (close_after_drain.load(std::memory_order_seq_cst) &&
            !failed.load(std::memory_order_acquire)) {
          set_failed();
        }
        return;
      }
      continue;
    }
    uint16_t buf;
    char* dst = ring->acquire_send_buffer(&buf);
    if (dst == nullptr) {
      ring_retry_park(this);
      return;
    }
    size_t n = wbuf.length();
    if (n > RingListener::kSendBufSize) n = RingListener::kSendBufSize;
    wbuf.copy_to(dst, n);  // straight into registered memory
    // in-flight state published BEFORE the submit: the completion (the
    // role's next holder) may run the instant the SQE is visible, and
    // nothing here may be touched after a successful submit. The send
    // owns a reference AND the drain role; its tag is the socket
    // POINTER (slabs are never freed, and the ref pins the slot against
    // recycling), so the completion needs no id lookup.
    ring_sending = true;
    ring_inflight = n;
    NAT_REF_ACQUIRE(this, sock.ringsend);
    if (!ring->submit_send((int)(rr & 0xffffffff), (uint32_t)(rr >> 32),
                           (uint64_t)(uintptr_t)this, buf, n)) {
      ring_sending = false;  // no completion will come: undo + park
      ring_inflight = 0;
      NAT_REF_RELEASE(this, sock.ringsend);
      ring_retry_park(this);
      return;
    }
    return;
  }
}

// A push just made the caller the drainer: drive the drain one step on
// the right lane.
void NatSocket::wdrive() {
  if (ring_ref.load(std::memory_order_acquire) >= 0 && ring != nullptr) {
    wring_continue();
    return;
  }
  // Inline first attempt on the caller's thread/fiber (socket.cpp:1287);
  // leftovers go to a KeepWrite fiber waiting on EPOLLOUT.
  if (!flush_chain()) {
    NAT_REF_ACQUIRE(this, sock.keepwrite);
    Scheduler::instance()->spawn_detached(keep_write_fiber, this);
  }
}

// ---------------------------------------------------------------------------
// write entries
// ---------------------------------------------------------------------------

int NatSocket::write(IOBuf&& frame) {
  if (ssl_sess != nullptr) {
    int rc = ssl_encrypt_and_write(this, std::move(frame));
    if (rc < 0) set_failed();
    return rc;
  }
  return write_raw(std::move(frame));
}

// Enqueue only (wait-free). True = caller became the drainer (wcur is
// set to the pushed node) and must drive the drain — after releasing any
// session locks it holds: order on the wire is fixed at PUSH time, so
// the drain itself needs no lock.
bool NatSocket::write_push(IOBuf&& frame) {
  WriteReq* r = wreq_alloc();
  c_unwritten.fetch_add(frame.length(), std::memory_order_relaxed);
  r->data = std::move(frame);
  if (wstack.push(r)) {
    // safe plain store: the push exchange that made us the drainer
    // happens-after the previous drainer's role-releasing CAS, which
    // happens-after it nulled wcur (wrefill nulls BEFORE the CAS)
    wcur = r;
    return true;
  }
  return false;
}

int NatSocket::write_raw(IOBuf&& frame) {
  if (failed.load(std::memory_order_acquire)) return -1;
  if (!write_push(std::move(frame))) {
    return 0;  // active drainer will take it
  }
  // became the drainer; a failure that raced the pre-push check is OUR
  // cleanup now (the failed side's release_all has already run or never
  // held the role)
  if (failed.load(std::memory_order_acquire)) {
    write_release_all();
    return -1;
  }
  if (defer_writes) {
    // Batch mode: the writer fiber runs AFTER the currently-ready fibers,
    // so their appends coalesce into one writev.
    NAT_REF_ACQUIRE(this, sock.keepwrite);
    Scheduler::instance()->spawn_detached_back(keep_write_fiber, this);
    return 0;
  }
  wdrive();
  return 0;
}

// ---------------------------------------------------------------------------
// ring lane (completion drain, demotion, adoption)
// ---------------------------------------------------------------------------

// Moves a ring socket to the epoll lane (rearm impossible / multishot
// unsupported); the CAS makes demotion and set_failed mutually exclusive.
// Queued bytes need no hand-off: the drain role is continuous, and every
// role holder re-checks ring_ref before submitting (a parked role on the
// retry list or an in-flight completion continues on the epoll lane).
static void ring_demote_to_epoll(NatSocket* s, int64_t rr) {
  if (s->ring_ref.compare_exchange_strong(rr, -1,
                                          std::memory_order_seq_cst)) {
    s->ring->unregister_file((int)(rr & 0xffffffff));
    s->disp->add_consumer(s);
  }
}

// Drains one ring's harvested completions — the wait_task drain of the
// fork (task_group.cpp:158-169): recv bytes feed the SAME cut loop the
// epoll readers use; send completions recycle fixed buffers and continue
// the owning socket's drain role. One drainer per ring at a time (the
// per-ring baton) keeps per-socket completion order.
bool ring_drain_one(RingListener* ring) {
  if (ring == nullptr) return false;
  if (ring->draining.exchange(true, std::memory_order_acquire)) {
    return false;
  }
  bool did = false;
  RingCompletion c;
  while (ring->pop_completion(&c)) {
    did = true;
    if (c.kind == 0) {  // recv
      NatSocket* s = sock_address(c.tag);
      if (c.res > 0) {
        if (s != nullptr && !s->failed.load(std::memory_order_acquire)) {
          nat_counter_add(NS_SOCK_READ_BYTES, (uint64_t)c.res);
          s->c_in_bytes.fetch_add((uint64_t)c.res,
                                  std::memory_order_relaxed);
          s->c_read_calls.fetch_add(1, std::memory_order_relaxed);
          if (s->ssl_sess != nullptr) {
            // TLS: ciphertext feeds the session; plaintext lands in
            // in_buf inside ssl_feed
            if (!ssl_feed(s, ring->buffer_data(c.buf_id),
                          (size_t)c.res)) {
              ring->recycle_buffer(c.buf_id);
              s->set_failed();
              NAT_REF_RELEASE(s, sock.borrow);
              continue;
            }
          } else {
            const char* src = ring->buffer_data(c.buf_id);
            size_t len = (size_t)c.res;
            if (s->fill_req != nullptr) {
              // stream fill mode: payload bytes skip in_buf entirely
              size_t took = stream_fill_feed(s, src, len);
              if (took == SIZE_MAX) {  // allocation failed
                ring->recycle_buffer(c.buf_id);
                s->set_failed();
                NAT_REF_RELEASE(s, sock.borrow);
                continue;
              }
              src += took;
              len -= took;
            }
            if (s->bulk_buf != nullptr && len > 0) {
              // bulk-frame fill: body bytes land in the pooled slab;
              // the remainder (next frame) takes the normal path
              size_t took = bulk_fill_feed(s, src, len);
              src += took;
              len -= took;
            }
            if (len > 0) s->in_buf.append(src, len);
          }
          ring->recycle_buffer(c.buf_id);
          int64_t rr = s->ring_ref.load(std::memory_order_acquire);
          bool in_ok = process_input(s);
          s->c_rdbuf.store(s->in_buf.length(), std::memory_order_relaxed);
          if (!in_ok) {
            s->set_failed();
          } else if (!c.more && rr >= 0 &&
                     !ring->rearm_recv((int)(rr & 0xffffffff),
                                       (uint32_t)(rr >> 32), s->id)) {
            ring_demote_to_epoll(s, rr);  // SQ full: don't go deaf
          }
        } else {
          ring->recycle_buffer(c.buf_id);  // owner gone: recycle only
        }
      } else if (s != nullptr) {
        int64_t rr = s->ring_ref.load(std::memory_order_acquire);
        if (c.res == -ENOBUFS) {
          // provided buffers were exhausted; they're recycled as we
          // drain, so re-arm and keep going
          if (rr >= 0 && !ring->rearm_recv((int)(rr & 0xffffffff),
                                           (uint32_t)(rr >> 32), s->id)) {
            ring_demote_to_epoll(s, rr);
          }
        } else if (c.res == -EINVAL && rr >= 0) {
          // kernel lacks multishot recv (pre-6.0): demote this
          // connection to the epoll lane instead of killing it
          ring_demote_to_epoll(s, rr);
        } else if (!c.more) {
          s->set_failed();  // EOF (0) or hard error
        }
      }
      if (s != nullptr) NAT_REF_RELEASE(s, sock.borrow);
    } else {  // send: the completion IS the drain-role continuation
      ring->recycle_send_buffer(c.send_buf);
      NatSocket* s = (NatSocket*)(uintptr_t)c.tag;
      // non-owning pointer use justified by the sock.ringsend reference
      // the submit took (slabs never free; the ref pins the slot)
      NAT_REF_BORROW(s);
      if (s != nullptr) {
        s->ring_sending = false;
        if (c.res < 0) {
          s->set_failed();
          s->write_release_all();
        } else {
          size_t done = (size_t)c.res;
          if (done > s->ring_inflight) done = s->ring_inflight;
          nat_counter_add(NS_SOCK_WRITE_BYTES, done);
          s->c_out_bytes.fetch_add(done, std::memory_order_relaxed);
          s->c_write_calls.fetch_add(1, std::memory_order_relaxed);
          s->conn_unwritten_sub(done);
          s->wbuf.pop_front(done);
          s->ring_inflight = 0;
          s->wring_continue();  // next chunk / refill / release / close
        }
        NAT_REF_RELEASE(s, sock.ringsend);
      }
    }
  }
  // resume drains parked for a free SQE/send buffer (every entry owns
  // its socket's drain role and a reference)
  std::vector<NatSocket*> retry;
  {
    std::lock_guard g(g_ring_retry_mu);
    retry.swap(g_ring_retry);
  }
  for (NatSocket* s : retry) {
    s->wring_continue();
    NAT_REF_RELEASE(s, sock.ringretry);
  }
  ring->draining.store(false, std::memory_order_release);
  return did;
}

// Idle-hook drain: every per-dispatcher ring in turn.
bool ring_drain() {
  if (!g_rings_ready.load(std::memory_order_acquire)) return false;
  bool did = false;
  for (RingListener* r : g_rings) did |= ring_drain_one(r);
  return did;
}

// Put a freshly-connected fd on its dispatcher's ring when the lane is
// enabled (both directions then ride io_uring and drain on the poller —
// the accept path's twin). Returns true when the ring owns the reads.
bool try_ring_adopt(NatSocket* s) {
  if (!g_use_ring.load(std::memory_order_acquire)) return false;
  RingListener* ring = s->disp != nullptr ? s->disp->ring : nullptr;
  if (ring == nullptr) return false;
  uint32_t gen = 0;
  int fidx = ring->register_file(s->fd, &gen);
  if (fidx < 0) return false;
  s->ring = ring;  // published before ring_ref: completions read it
  int64_t rr = ((int64_t)gen << 32) | (uint32_t)fidx;
  s->ring_ref.store(rr, std::memory_order_release);
  if (ring->rearm_recv(fidx, gen, s->id)) return true;
  s->ring_ref.store(-1, std::memory_order_release);
  ring->unregister_file(fidx);
  return false;
}

// ---------------------------------------------------------------------------
// native /connections snapshot (connections_service.cpp role): walk the
// registry's high-water mark and fill one row per live socket. Lock-free
// — liveness is judged by the versioned_ref refcount, counters are
// relaxed atomics, and the protocol column is derived from the session
// pointers the single reading thread owns (a mid-recycle row can at
// worst show a freshly-reset socket's zeros; this is a debug page).
// ---------------------------------------------------------------------------

// no_sanitize: the SERVER-side protocol session pointers (http/h2/redis)
// and ssl_sess are sniff-assigned by the owning dispatcher thread after
// the conn_visible gate; this walker only null-tests them (never
// dereferences) to derive the protocol column, and a stale null at
// worst labels a just-sniffed socket "?" for one scrape. Everything
// else read here is either an atomic or ordered by conn_visible.
__attribute__((no_sanitize("thread")))
static void conn_fill_row(NatSocket* s, NatConnRow* r) {
  r->sock_id = s->id.load(std::memory_order_relaxed);
  r->in_bytes = s->c_in_bytes.load(std::memory_order_relaxed);
  r->out_bytes = s->c_out_bytes.load(std::memory_order_relaxed);
  r->in_msgs = s->c_in_msgs.load(std::memory_order_relaxed);
  r->out_msgs = s->c_out_msgs.load(std::memory_order_relaxed);
  r->read_calls = s->c_read_calls.load(std::memory_order_relaxed);
  r->write_calls = s->c_write_calls.load(std::memory_order_relaxed);
  r->unwritten_bytes = s->c_unwritten.load(std::memory_order_relaxed);
  r->mem_bytes = r->unwritten_bytes +
                 s->c_rdbuf.load(std::memory_order_relaxed) +
                 s->c_parked.load(std::memory_order_relaxed);
  r->fd = s->fd;
  r->disp_idx = s->disp != nullptr ? s->disp->idx : -1;
  r->server_side = s->server != nullptr ? 1 : 0;
  const char* proto = "?";
  if (s->http != nullptr) proto = "http";
  else if (s->h2 != nullptr) proto = "h2";
  else if (s->redis != nullptr) proto = "redis";
  else if (s->httpc != nullptr) proto = "http_cli";
  else if (s->h2c != nullptr) proto = "h2_cli";
  else if (s->spoke_tpu_std.load(std::memory_order_relaxed)) proto = "tpu_std";
  else if (s->py_streams.load(std::memory_order_relaxed)) proto = "stream";
  else if (s->py_raw.load(std::memory_order_relaxed)) proto = "raw";
  else if (s->channel != nullptr) proto = "tpu_std";
  if (s->ssl_sess != nullptr) proto = "tls";
  snprintf(r->protocol, sizeof(r->protocol), "%s", proto);
  memcpy(r->remote, s->peer, sizeof(r->remote) < sizeof(s->peer)
                                 ? sizeof(r->remote)
                                 : sizeof(s->peer));
  r->remote[sizeof(r->remote) - 1] = '\0';
}

}  // namespace brpc_tpu

using namespace brpc_tpu;

extern "C" {

// Fill up to `max` rows with the live native sockets; returns rows
// written. A row is "live" when its registry slot holds a reference and
// an open fd (closed/recycled slots are skipped). Each row is filled
// under a borrowed reference (the sock_address discipline): the CAS from
// a nonzero refcount pins the socket so release()'s teardown — which
// frees sessions and closes the fd the row reads — cannot run mid-fill.
int nat_conn_snapshot(brpc_tpu::NatConnRow* out, int max) {
  int n = 0;
  uint32_t hwm;
  {
    std::lock_guard g(g_sock_alloc_mu);
    hwm = g_sock_next_idx;
  }
  for (uint32_t idx = 0; idx < hwm && n < max; idx++) {
    NatSocket* s = sock_at(idx);
    if (s == nullptr) continue;
    if (sock_try_pin(s) == nullptr) continue;
    // conn_visible (acquire) orders every setup write — fd, peer, disp,
    // channel/server, client session attach — before this row's reads:
    // the pin alone is not enough, sock_create publishes versioned_ref
    // before the creating thread has filled those plain fields
    if (s->conn_visible.load(std::memory_order_acquire) &&
        !s->failed.load(std::memory_order_acquire) && s->fd >= 0) {
      conn_fill_row(s, &out[n]);
      if (out[n].sock_id != 0) n++;
    }
    NAT_REF_RELEASE(s, sock.borrow);
  }
  return n;
}

}  // extern "C"
