// Fuzz target: drives the production nat_fuzz_rpc_meta seam (see
// native/src/nat_fuzz_entry.cpp / nat_replay.cpp) under ASan+UBSan.
#include "fuzz_common.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  nat_fuzz_rpc_meta((const char*)data, size);
  return 0;
}
