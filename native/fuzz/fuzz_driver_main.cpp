// Standalone fuzz driver for toolchains without libFuzzer (plain g++):
// gives every fuzz_*.cpp target a main() with libFuzzer-compatible
// replay semantics plus a bounded, DETERMINISTIC mutation loop so CI
// can run a fixed-work fuzz pass with stable results:
//
//   fuzz_x FILE...                 replay each input once, exit 0/crash
//   fuzz_x [--budget-ms M] [--seed S] [--max-len N] DIR...
//       load every file under each DIR as the seed corpus, replay all,
//       then mutate seeds with a seeded xorshift64 until the budget
//       expires. Same seed + same corpus => same byte sequences.
//
// Crashes are the sanitizer's business (the target links the
// ASan+UBSan .so); this driver only schedules inputs. It prints one
// summary line so tools/natcheck/fuzzlane.py can assert liveness.
#ifndef NAT_FUZZ_STANDALONE
#error "fuzz_driver_main.cpp is only built for the standalone (no-libFuzzer) lane"
#endif

#include <dirent.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <sys/stat.h>
#include <time.h>

#include <string>
#include <vector>

#include "fuzz_common.h"

namespace {

uint64_t g_rng = 0x9e3779b97f4a7c15ull;

uint64_t rng_next() {
  // xorshift64: deterministic, seedable, no libc rand() state
  uint64_t x = g_rng;
  x ^= x << 13;
  x ^= x >> 7;
  x ^= x << 17;
  g_rng = x;
  return x;
}

uint64_t now_ms() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return (uint64_t)ts.tv_sec * 1000u + (uint64_t)(ts.tv_nsec / 1000000);
}

bool load_file(const std::string& path, std::vector<uint8_t>* out) {
  FILE* f = fopen(path.c_str(), "rb");
  if (f == nullptr) return false;
  out->clear();
  uint8_t buf[4096];
  size_t n;
  while ((n = fread(buf, 1, sizeof(buf), f)) > 0) {
    out->insert(out->end(), buf, buf + n);
  }
  fclose(f);
  return true;
}

void load_dir(const std::string& dir,
              std::vector<std::vector<uint8_t>>* corpus) {
  DIR* d = opendir(dir.c_str());
  if (d == nullptr) return;
  std::vector<std::string> names;
  struct dirent* e;
  while ((e = readdir(d)) != nullptr) {
    if (e->d_name[0] == '.') continue;
    names.push_back(dir + "/" + e->d_name);
  }
  closedir(d);
  // sorted load order: the corpus replay sequence is part of determinism
  for (size_t i = 0; i < names.size(); i++) {
    for (size_t j = i + 1; j < names.size(); j++) {
      if (names[j] < names[i]) names[i].swap(names[j]);
    }
  }
  for (const auto& n : names) {
    struct stat st;
    if (stat(n.c_str(), &st) == 0 && S_ISREG(st.st_mode)) {
      std::vector<uint8_t> data;
      if (load_file(n, &data)) corpus->push_back(std::move(data));
    }
  }
}

// One mutation step: start from a corpus pick, apply 1-8 edits drawn
// from the classic set (bit flip, byte set, chunk erase/insert/splice,
// interesting integer splat) — structure-unaware but effective against
// length/offset parsers when the corpus is structure-aware.
void mutate(const std::vector<std::vector<uint8_t>>& corpus,
            std::vector<uint8_t>* out, size_t max_len) {
  const std::vector<uint8_t>& base =
      corpus[rng_next() % corpus.size()];
  *out = base;
  size_t edits = 1 + rng_next() % 8;
  static const uint64_t kInteresting[] = {
      0, 1, 0x7f, 0x80, 0xff, 0x100, 0xffff, 0x10000, 0x7fffffff,
      0x80000000ull, 0xffffffffull, 0x100000000ull, 0x7fffffffffffffffull,
      0xffffffffffffffffull};
  for (size_t i = 0; i < edits; i++) {
    switch (rng_next() % 6) {
      case 0:  // bit flip
        if (!out->empty()) {
          size_t p = rng_next() % out->size();
          (*out)[p] ^= (uint8_t)(1u << (rng_next() % 8));
        }
        break;
      case 1:  // byte set
        if (!out->empty()) {
          (*out)[rng_next() % out->size()] = (uint8_t)rng_next();
        }
        break;
      case 2: {  // chunk erase
        if (out->size() > 1) {
          size_t p = rng_next() % out->size();
          size_t n = 1 + rng_next() % (out->size() - p);
          out->erase(out->begin() + (long)p, out->begin() + (long)(p + n));
        }
        break;
      }
      case 3: {  // chunk insert (random bytes)
        size_t p = out->empty() ? 0 : rng_next() % out->size();
        size_t n = 1 + rng_next() % 16;
        std::vector<uint8_t> ins(n);
        for (auto& b : ins) b = (uint8_t)rng_next();
        out->insert(out->begin() + (long)p, ins.begin(), ins.end());
        break;
      }
      case 4: {  // splice from another corpus entry
        const std::vector<uint8_t>& other =
            corpus[rng_next() % corpus.size()];
        if (!other.empty()) {
          size_t p = out->empty() ? 0 : rng_next() % out->size();
          size_t so = rng_next() % other.size();
          size_t n = 1 + rng_next() % (other.size() - so);
          out->insert(out->begin() + (long)p, other.begin() + (long)so,
                      other.begin() + (long)(so + n));
        }
        break;
      }
      case 5: {  // interesting integer splat (1/2/4/8 bytes, LE and BE)
        uint64_t v = kInteresting[rng_next() %
                                  (sizeof(kInteresting) / sizeof(uint64_t))];
        size_t w = (size_t)1 << (rng_next() % 4);
        if (out->size() >= w) {
          size_t p = rng_next() % (out->size() - w + 1);
          bool be = (rng_next() & 1) != 0;
          for (size_t k = 0; k < w; k++) {
            size_t sh = be ? (w - 1 - k) * 8 : k * 8;
            (*out)[p + k] = (uint8_t)(v >> sh);
          }
        }
        break;
      }
    }
  }
  if (out->size() > max_len) out->resize(max_len);
}

}  // namespace

int main(int argc, char** argv) {
  uint64_t budget_ms = 0;
  uint64_t seed = 1;
  size_t max_len = 1 << 16;
  std::vector<std::string> paths;
  for (int i = 1; i < argc; i++) {
    std::string a = argv[i];
    if (a == "--budget-ms" && i + 1 < argc) {
      budget_ms = strtoull(argv[++i], nullptr, 10);
    } else if (a == "--seed" && i + 1 < argc) {
      seed = strtoull(argv[++i], nullptr, 10);
    } else if (a == "--max-len" && i + 1 < argc) {
      max_len = strtoull(argv[++i], nullptr, 10);
    } else if (a.rfind("-", 0) == 0) {
      fprintf(stderr, "unknown flag: %s\n", a.c_str());
      return 2;
    } else {
      paths.push_back(a);
    }
  }
  g_rng = seed ? seed : 1;

  std::vector<std::vector<uint8_t>> corpus;
  for (const auto& p : paths) {
    struct stat st;
    if (stat(p.c_str(), &st) == 0 && S_ISDIR(st.st_mode)) {
      load_dir(p, &corpus);
    } else {
      std::vector<uint8_t> data;
      if (load_file(p, &data)) corpus.push_back(std::move(data));
    }
  }

  // phase 1: replay the corpus (every committed seed + regress input)
  uint64_t execs = 0;
  for (const auto& in : corpus) {
    LLVMFuzzerTestOneInput(in.data(), in.size());
    execs++;
  }

  // phase 2: bounded deterministic mutation loop
  if (budget_ms > 0 && !corpus.empty()) {
    uint64_t deadline = now_ms() + budget_ms;
    std::vector<uint8_t> buf;
    while (now_ms() < deadline) {
      mutate(corpus, &buf, max_len);
      LLVMFuzzerTestOneInput(buf.data(), buf.size());
      execs++;
    }
  }
  printf("fuzz-driver: %llu execs, %zu corpus seeds, seed=%llu: OK\n",
         (unsigned long long)execs, corpus.size(),
         (unsigned long long)seed);
  return 0;
}
