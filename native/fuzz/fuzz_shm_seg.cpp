// Fuzz target: drives the production nat_fuzz_shm_seg seam (see
// native/src/nat_fuzz_entry.cpp / nat_replay.cpp) under ASan+UBSan.
#include "fuzz_common.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size) {
  nat_fuzz_shm_seg((const char*)data, size);
  return 0;
}
