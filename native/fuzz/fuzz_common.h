// Shared by the per-parser fuzz targets (fuzz_*.cpp): pulls in the
// production fuzz seams (nat_api.h nat_fuzz_*, implemented inside the
// instrumented .so the target links against) and the libFuzzer entry
// signature. Each target defines LLVMFuzzerTestOneInput; with clang the
// real libFuzzer drives it (coverage-guided), with g++ the bundled
// deterministic driver (fuzz_driver_main.cpp) does (corpus replay +
// fixed-seed mutation loop) — same target code either way.
#pragma once

#include <stddef.h>
#include <stdint.h>

#include "nat_api.h"

extern "C" int LLVMFuzzerTestOneInput(const uint8_t* data, size_t size);
