"""Root conftest: force JAX onto a virtual 8-device CPU mesh for all tests.

Mirrors the reference's test strategy (SURVEY.md section 4): "distributed"
behavior is exercised with many in-process endpoints before real hardware —
here, an 8-device host-platform mesh standing in for a TPU slice.

The environment's sitecustomize registers the axon TPU platform and sets
jax_platforms via jax.config (which overrides the JAX_PLATFORMS env var), so
we must override it back through jax.config before any backend initializes.
"""
import faulthandler
faulthandler.enable()

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
