"""Fleet observatory (ISSUE 16): wire-native stats scrape, mergeable
log2 histograms, and the SLO burn-rate engine.

Covers the tentpole end to end — the builtin.stats snapshot served by
the native server over its own wire, the Python histogram twin pinned
against the native quantile walker, the fleet collector's exact merge
with per-backend drill-down, the /fleet console page, the fleet_*
Prometheus drift contract, the multi-window burn-rate engine — and the
acceptance drill: a 3-process swarm under a replayed flood with
injected ELIMIT overload and one rolling restart, where the merged p99
must sit within one log2 bucket of per-server truth, the burn-rate
alert must fire during the flood and clear after it, and the
restarting member's state must be visible in the rollup.
"""
import http.client
import json
import os
import random
import signal
import tempfile
import threading
import time

import pytest

from brpc_tpu.fleet import hist
from brpc_tpu.fleet.slo import SloEngine, SloObjective

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

GOLDEN = os.path.join(os.path.dirname(__file__), "data",
                      "golden_capture_1k.rio")


# ---------------------------------------------------------------------------
# histogram math: the merge property the whole design rests on
# ---------------------------------------------------------------------------

def _bucketize(samples):
    h = [0] * hist.NBUCKETS
    for ns in samples:
        h[hist.bucket_of(ns)] += 1
    return h


def test_bucket_bounds_roundtrip():
    for b in range(1, hist.NBUCKETS):
        lo = int(hist.bucket_lo(b))
        hi = int(hist.bucket_hi(b)) - 1
        assert hist.bucket_of(lo) == b
        assert hist.bucket_of(hi) == b
    assert hist.bucket_of(0) == 0
    assert hist.bucket_of(1) == 0 or hist.bucket_of(1) == 1
    # over-range clamps into the last bucket instead of dropping
    assert hist.bucket_of(1 << 60) == hist.NBUCKETS - 1


def test_merge_is_exact_bucketwise_sum():
    rng = random.Random(16)
    streams = [[rng.randrange(100, 10_000_000) for _ in range(500)]
               for _ in range(4)]
    hists = [_bucketize(s) for s in streams]
    merged = hist.merge(*hists)
    for b in range(hist.NBUCKETS):
        assert merged[b] == sum(h[b] for h in hists)
    assert hist.total(merged) == sum(len(s) for s in streams)


def test_histogram_merge_quantile_property():
    """THE merge contract: for many random per-server streams, the
    quantile computed from the MERGED buckets equals the quantile of
    the concatenated raw stream to within one log2 bucket — while the
    average of per-server percentiles (the thing this design forbids)
    can be arbitrarily wrong."""
    rng = random.Random(1606)
    for trial in range(20):
        nservers = rng.randrange(2, 8)
        streams = []
        for _ in range(nservers):
            # heterogeneous shapes: some members fast, some slow, some
            # bimodal — exactly where averaged percentiles lie
            base = rng.choice([1_000, 50_000, 2_000_000])
            n = rng.randrange(50, 800)
            s = [max(1, int(rng.lognormvariate(0, 1.0) * base))
                 for _ in range(n)]
            if rng.random() < 0.3:
                s += [base * 64] * rng.randrange(1, 20)
            streams.append(s)
        merged = hist.merge(*[_bucketize(s) for s in streams])
        concat = sorted(x for s in streams for x in s)
        for q in (0.5, 0.9, 0.99):
            est = hist.quantile(merged, q)
            true = concat[min(len(concat) - 1,
                              int(q * len(concat)))]
            # within one log2 bucket of the true sample quantile
            assert abs(hist.bucket_of(int(est))
                       - hist.bucket_of(true)) <= 1, (
                trial, q, est, true)


def test_fraction_above_agrees_with_quantile():
    rng = random.Random(7)
    samples = [max(1, int(rng.lognormvariate(0, 1.5) * 40_000))
               for _ in range(3000)]
    buckets = _bucketize(samples)
    for q in (0.5, 0.9, 0.99):
        ceiling = hist.quantile(buckets, q)
        bad, tot = hist.fraction_above(buckets, ceiling)
        assert tot == len(samples)
        # the interpolations are the same line: bad/tot ~ 1-q
        assert abs(bad / tot - (1.0 - q)) < 0.02, (q, bad / tot)


def test_dense_expands_sparse_wire_form():
    assert hist.dense([[0, 3], [7, 2], [43, 1]])[0] == 3
    assert hist.dense([[7, 2]])[7] == 2
    assert sum(hist.dense([[0, 3], [7, 2], [43, 1]])) == 6
    # out-of-range buckets on the wire are dropped, not a crash
    assert sum(hist.dense([[99, 5], [-1, 5]])) == 0


# ---------------------------------------------------------------------------
# SLO burn-rate engine
# ---------------------------------------------------------------------------

def _merged_with(count, errors, buckets=None):
    return {"methods": {"echo/EchoService.Echo": {
        "lane": "echo", "method": "EchoService.Echo",
        "count": count, "errors": errors,
        "buckets": buckets or [0] * hist.NBUCKETS}}}


def test_slo_error_burn_fires_and_clears():
    obj = SloObjective(name="err", kind="errors", lane="echo",
                       budget=0.01, fast_window_s=10, slow_window_s=60)
    eng = SloEngine([obj])
    t0 = 1000.0
    eng.ingest(_merged_with(1000, 0), now=t0)
    # a hard outage: every new sample is an error -> burn 100x budget
    eng.ingest(_merged_with(1100, 100), now=t0 + 5)
    st = eng.status()["err"]
    assert st["fast_burn"] >= obj.fast_burn
    assert st["slow_burn"] >= obj.slow_burn
    assert st["alert"] and st["fired_total"] == 1
    # recovery: the stream moves on clean; once the windows slide past
    # the bad minute the burn decays and the alert clears
    t = t0 + 5
    while t < t0 + 120:
        t += 5
        eng.ingest(_merged_with(1100 + int(t - t0) * 10, 100), now=t)
    st = eng.status()["err"]
    assert not st["alert"]
    assert st["cleared_total"] == 1


def test_slo_multiwindow_suppresses_blips():
    """A short blip trips the fast window but cannot spend the slow
    window's budget — no page (the whole point of multi-window)."""
    obj = SloObjective(name="blip", kind="errors", budget=0.001,
                       fast_window_s=10, slow_window_s=1000,
                       fast_burn=14.4, slow_burn=6.0)
    eng = SloEngine([obj])
    t0 = 5000.0
    eng.ingest(_merged_with(100_000, 0), now=t0)
    for i in range(1, 200):  # long clean history
        eng.ingest(_merged_with(100_000 + i * 1000, 0), now=t0 + i)
    # a blip: 500 bad of the fast window's ~10k new samples (5% >>
    # budget there) but only 0.25% of the slow window's 200k
    eng.ingest(_merged_with(300_000, 500), now=t0 + 200)
    st = eng.status()["blip"]
    assert st["fast_burn"] >= obj.fast_burn
    assert st["slow_burn"] < obj.slow_burn
    assert not st["alert"]


def test_slo_latency_kind_counts_from_merged_buckets():
    obj = SloObjective(name="lat", kind="latency", ceiling_ms=1.0,
                       budget=0.05, fast_window_s=10, slow_window_s=20)
    eng = SloEngine([obj])
    fast = _bucketize([100_000] * 900)        # 0.1ms: under ceiling
    slow = _bucketize([100_000_000] * 100)    # 100ms: over ceiling
    t0 = 100.0
    eng.ingest(_merged_with(900, 0, fast), now=t0)
    eng.ingest(_merged_with(1000, 0, hist.merge(fast, slow)),
               now=t0 + 5)
    st = eng.status()["lat"]
    # all 100 new samples are over the 1ms ceiling: burn = 1.0/0.05
    assert st["alert"]
    assert abs(st["fast_burn"] - 20.0) < 0.5


def test_slo_restart_clamps_negative_deltas():
    """A member restart shrinks cumulative merged counts; the burn must
    read 'no new samples', never a negative rate or a phantom page."""
    obj = SloObjective(name="rst", kind="errors", budget=0.01,
                       fast_window_s=10, slow_window_s=20)
    eng = SloEngine([obj])
    eng.ingest(_merged_with(5000, 50), now=10.0)
    eng.ingest(_merged_with(100, 1), now=15.0)  # restart: counts drop
    st = eng.status()["rst"]
    assert st["fast_burn"] == 0.0
    assert not st["alert"]


def test_slo_objective_validation():
    with pytest.raises(ValueError):
        SloObjective(name="x", kind="availability")
    with pytest.raises(ValueError):
        SloObjective(name="x", budget=0.0)
    with pytest.raises(ValueError):
        SloEngine([SloObjective(name="dup"), SloObjective(name="dup")])


# ---------------------------------------------------------------------------
# single-process integration: wire snapshot, python/native pinning,
# scrape+merge, /fleet page, metrics drift
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def served_port():
    port = native.rpc_server_start(native_echo=True)
    ch = native.channel_open("127.0.0.1", port)
    assert ch
    try:
        for _ in range(300):
            rc, _resp, _err = native.channel_call(
                ch, "EchoService", "Echo", b"fleet", timeout_ms=5000)
            assert rc == 0
    finally:
        native.channel_close(ch)
    yield port
    native.rpc_server_stop()


def test_builtin_stats_snapshot_on_the_wire(served_port):
    """The wire-native endpoint: one tpu_std call returns the versioned
    snapshot with RAW buckets, server state and the mem ledger."""
    ch = native.channel_open("127.0.0.1", served_port)
    try:
        rc, body, _err = native.channel_call(ch, "builtin", "stats",
                                             b"", timeout_ms=5000)
    finally:
        native.channel_close(ch)
    assert rc == 0
    snap = json.loads(body)
    assert snap["v"] == 1
    assert snap["counters"]["nat_stats_snapshots"] >= 1
    rows = {f"{m['lane']}/{m['method']}": m for m in snap["methods"]}
    echo = rows["echo/EchoService.Echo"]
    assert echo["count"] >= 300
    assert sum(c for _b, c in echo["buckets"]) == echo["count"]
    assert "inflight" in snap["server"] and "draining" in snap["server"]
    assert isinstance(snap["mem"], dict) and snap["mem"]
    assert isinstance(snap["channels"], list)


def test_python_quantile_pins_native_walker(served_port):
    """hist.quantile is a line-for-line port of nat_hist_quantile; the
    two must agree exactly on the same live buckets."""
    lane = native.stats_lane_names().index("echo")
    buckets = native.method_hist(lane, "EchoService.Echo")
    assert buckets and sum(buckets) >= 300
    for q in (0.5, 0.9, 0.99, 0.999):
        py = hist.quantile(buckets, q)
        nat = native.method_quantile(lane, "EchoService.Echo", q)
        assert py == pytest.approx(nat, rel=1e-9), q


def test_scrape_merge_and_drilldown(served_port):
    from brpc_tpu.fleet import FleetObservatory

    ep = f"127.0.0.1:{served_port}"
    with FleetObservatory(endpoints=[ep], register_bvars=False) as obs:
        merged = obs.scrape_once()
        assert merged["backends"][ep]["up"]
        row = merged["methods"]["echo/EchoService.Echo"]
        assert row["count"] >= 300
        assert row["per_backend"][ep]["count"] == row["count"]
        # merged == the one member's raw buckets (exact)
        lane = native.stats_lane_names().index("echo")
        assert row["buckets"] == native.method_hist(lane,
                                                    "EchoService.Echo")
        assert obs.method_quantile("EchoService.Echo", 0.99) > 0
        s, e = obs.scrape_counts()
        assert (s, e) == (1, 0)


def test_scrape_marks_dead_backend_down(served_port):
    from brpc_tpu.fleet import FleetObservatory

    live = f"127.0.0.1:{served_port}"
    dead = "127.0.0.1:1"
    with FleetObservatory(endpoints=[live, dead],
                          register_bvars=False) as obs:
        merged = obs.scrape_once()
        assert merged["backends"][live]["up"]
        assert not merged["backends"][dead]["up"]
        s, e = obs.scrape_counts()
        assert s == 1 and e == 1


def test_fleet_console_page(served_port):
    """/fleet on the Python console: rollup + drill-down + JSON dump."""
    from brpc_tpu import rpc
    from brpc_tpu.fleet import FleetObservatory, SloObjective as Obj

    srv = rpc.Server(rpc.ServerOptions(num_threads=1))
    assert srv.start("127.0.0.1:0") == 0
    ep = f"127.0.0.1:{served_port}"
    try:
        with FleetObservatory(endpoints=[ep], register_bvars=False,
                              objectives=[Obj(name="page-p99")]) as obs:
            obs.scrape_once()

            def get(path):
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.listen_endpoint.port, timeout=10)
                conn.request("GET", path)
                r = conn.getresponse()
                body = r.read().decode()
                conn.close()
                return r.status, body

            status, body = get("/fleet")
            assert status == 200
            assert ep in body
            assert "echo/EchoService.Echo" in body
            assert "page-p99" in body
            status, body = get(f"/fleet?backend={ep}")
            assert status == 200 and "snapshot v1" in body
            status, body = get("/fleet?json=1")
            doc = json.loads(body)
            assert ep in doc[obs.name]["backends"]
    finally:
        srv.stop()


def test_fleet_metrics_drift(served_port):
    """Every fleet_*/SLO variable the module exposes shows up in the
    Prometheus dump, and no unlisted fleet_* row exists — additions
    must land in FLEET_VAR_NAMES or this fails (the drift contract)."""
    from brpc_tpu import fleet
    from brpc_tpu.bvar.variable import dump_prometheus

    ep = f"127.0.0.1:{served_port}"
    with fleet.FleetObservatory(
            endpoints=[ep],
            objectives=[fleet.SloObjective(name="drift-p99")]) as obs:
        obs.scrape_once()
        prom = dump_prometheus()
        rows = [ln for ln in prom.splitlines()
                if ln.startswith("fleet_") and not ln.startswith("# ")]
        present = {ln.split("{")[0].split(" ")[0] for ln in rows}
        missing = set(fleet.FLEET_VAR_NAMES) - present
        assert not missing, f"registered but not exported: {missing}"
        unlisted = present - set(fleet.FLEET_VAR_NAMES)
        assert not unlisted, (
            f"fleet_* rows not declared in FLEET_VAR_NAMES: {unlisted}")
        # the labeled dimensions carry real labels
        assert any(f'backend="{ep}"' in ln for ln in rows)
        assert any('slo="drift-p99"' in ln for ln in rows)


def test_find_trace_fans_out_over_consoles(served_port):
    """find_trace queries every member's /rpcz; a member whose console
    holds spans for the id contributes to the stitched chain."""
    from brpc_tpu import rpc
    from brpc_tpu.fleet import FleetObservatory

    srv = rpc.Server(rpc.ServerOptions(num_threads=1))
    assert srv.start("127.0.0.1:0") == 0
    console = f"127.0.0.1:{srv.listen_endpoint.port}"
    ep = f"127.0.0.1:{served_port}"
    try:
        with FleetObservatory(endpoints=[ep], register_bvars=False,
                              console_map={ep: console}) as obs:
            assert obs.console_of(ep) == console
            # unknown id: clean empty answer from the whole fleet
            parts = obs.find_trace(0xdeadbeef)
            assert parts == [] or all("trace=" in p["body"]
                                      for p in parts)
            assert "no spans" in obs.stitched_trace(0x1)
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the acceptance drill: 3-process swarm, replayed flood, injected
# ELIMIT overload, one rolling restart
# ---------------------------------------------------------------------------

def _flood_member(port, results, idx):
    try:
        results[idx] = native.replay_run("127.0.0.1", port, GOLDEN,
                                         times=2, concurrency=8,
                                         timeout_ms=5000)
    except Exception as exc:  # pragma: no cover - drill diagnostics
        results[idx] = {"error": str(exc)}


def _elimit_probe(port, n=6):
    """Flood the py-lane (no consumer, constant:1 limiter): the first
    call parks on the single admission slot, the rest shed with real
    ELIMIT on the wire."""
    def one():
        ch = native.channel_open("127.0.0.1", port)
        if ch:
            try:
                native.channel_call(ch, "PyLane", "Blocked", b"x",
                                    timeout_ms=400)
            finally:
                native.channel_close(ch)
    ts = [threading.Thread(target=one) for _ in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=10)


@pytest.mark.skipif(not os.path.exists(GOLDEN),
                    reason="golden capture missing")
def test_three_process_flood_drill():
    from brpc_tpu.bench import _spawn_swarm_server
    from brpc_tpu.fleet import FleetObservatory, SloObjective

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["BRPC_TPU_SWARM_LIMITER"] = "constant:1"  # the ELIMIT injector
    procs, ports = [], []
    nf_path = None
    obs = None
    try:
        for base in (23300, 25300, 27300, 29300, 21300, 19300):
            proc = _spawn_swarm_server(base, 1, repo_root, env)
            if proc is not None:
                procs.append(proc)
                ports.append(base)
            if len(procs) == 3:
                break
        if len(procs) < 3:
            pytest.skip("no free port ranges for the 3-server group")
        eps = [f"127.0.0.1:{p}" for p in ports]

        nf = tempfile.NamedTemporaryFile("w", suffix=".fleet.ns",
                                         delete=False)
        nf_path = nf.name
        for ep in eps:
            nf.write(ep + "\n")
        nf.close()

        # sub-microsecond ceiling: during the flood every sample is
        # "bad", so the burn is budget^-1 = 100x — fires; after the
        # flood the windows drain and it clears. Short windows keep the
        # drill under test time; the engine logic is window-agnostic.
        obs = FleetObservatory(
            naming_url=f"file://{nf_path}", interval_s=10.0,
            objectives=[SloObjective(name="drill-p99", kind="latency",
                                     lane="echo",
                                     method="EchoService.Echo",
                                     ceiling_ms=0.0001, budget=0.01,
                                     fast_window_s=2.0,
                                     slow_window_s=4.0)],
            register_bvars=False)
        deadline = time.time() + 15
        merged = obs.scrape_once()
        while (sum(1 for b in merged["backends"].values() if b["up"])
               < 3 and time.time() < deadline):
            time.sleep(0.3)
            merged = obs.scrape_once()
        assert sum(1 for b in merged["backends"].values()
                   if b["up"]) == 3, merged["backends"]

        # -- replayed flood over the whole group, scraping at ~5Hz ----
        results = [None] * 3
        threads = [threading.Thread(target=_flood_member,
                                    args=(p, results, i))
                   for i, p in enumerate(ports)]
        for t in threads:
            t.start()
        fired = False
        while any(t.is_alive() for t in threads):
            merged = obs.scrape_once()
            fired = fired or obs.slo.status()["drill-p99"]["alert"]
            time.sleep(0.2)
        for t in threads:
            t.join(timeout=30)
        for r in results:
            assert r and not r.get("error") and r.get("failed") == 0, \
                results

        # keep scraping past the flood so the alert latches even if the
        # loop above raced the last window
        for _ in range(4):
            merged = obs.scrape_once()
            fired = fired or obs.slo.status()["drill-p99"]["alert"]
            time.sleep(0.2)
        assert fired, obs.slo.status()
        assert obs.slo.alerts_fired_total() >= 1

        # -- merged p99 within one log2 bucket of per-server truth ----
        row = merged["methods"]["echo/EchoService.Echo"]
        assert row["count"] >= 3 * 2000  # 1k capture x2 x3 members
        member_hists = []
        for snap in obs.snapshots().values():
            assert snap.ok
            for m in snap.data["methods"]:
                if m["method"] == "EchoService.Echo":
                    member_hists.append(hist.dense(m["buckets"]))
        assert len(member_hists) == 3
        truth = hist.merge(*member_hists)
        assert row["buckets"] == truth  # the merge is EXACT
        merged_p99_b = hist.bucket_of(int(hist.quantile(row["buckets"],
                                                        0.99)))
        per_server_b = [hist.bucket_of(int(hist.quantile(h, 0.99)))
                        for h in member_hists]
        assert (min(per_server_b) - 1 <= merged_p99_b
                <= max(per_server_b) + 1), (merged_p99_b, per_server_b)

        # -- injected ELIMIT overload is visible in the rollup --------
        _elimit_probe(ports[0])
        merged = obs.scrape_once()
        ep0 = eps[0]
        assert merged["backends"][ep0]["elimit_rejects"] > 0, \
            merged["backends"][ep0]
        assert merged["counters"]["nat_elimit_rejects"] > 0

        # -- one rolling restart: the member's departure shows in the
        #    rollup (down/draining/lame-duck/breaker), then it rejoins -
        victim = procs[2]
        victim.send_signal(signal.SIGTERM)
        saw_departure = False
        deadline = time.time() + 25
        while time.time() < deadline:
            merged = obs.scrape_once()
            b = merged["backends"].get(eps[2], {})
            if (not b.get("up", True)) or b.get("draining") \
                    or b.get("lame_duck") or b.get("breaker_open"):
                saw_departure = True
            if saw_departure and victim.poll() is not None:
                break
            time.sleep(0.2)
        assert saw_departure, merged["backends"].get(eps[2])
        victim.wait(timeout=20)
        fresh = _spawn_swarm_server(ports[2], 1, repo_root, env)
        assert fresh is not None, "restarted member failed to bind"
        procs[2] = fresh
        deadline = time.time() + 15
        while time.time() < deadline:
            merged = obs.scrape_once()
            if merged["backends"].get(eps[2], {}).get("up"):
                break
            time.sleep(0.3)
        assert merged["backends"][eps[2]]["up"], merged["backends"]

        # -- quiet period: the windows drain, the alert clears --------
        deadline = time.time() + 12
        cleared = False
        while time.time() < deadline:
            obs.scrape_once()
            st = obs.slo.status()["drill-p99"]
            if not st["alert"] and st["cleared_total"] >= 1:
                cleared = True
                break
            time.sleep(0.4)
        assert cleared, obs.slo.status()
    finally:
        if obs is not None:
            obs.close()
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.send_signal(signal.SIGTERM)
        for proc in procs:
            if proc is not None:
                try:
                    proc.wait(timeout=15)
                except Exception:
                    proc.kill()
                    proc.wait(timeout=10)
        if nf_path is not None:
            try:
                os.unlink(nf_path)
            except OSError:
                pass
