"""Tools + rpc_dump tests — the tools/ suite exercised in-process and via
subprocess against a live server (SURVEY.md section 2.11).
"""
import subprocess
import sys
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.butil import flags as flags_mod
from brpc_tpu.butil.recordio import RecordReader, RecordWriter
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "x.rio")
    with RecordWriter(path) as w:
        for i in range(5):
            w.write({"service": "S", "method": "M", "i": i},
                    f"payload-{i}".encode())
    with RecordReader(path) as r:
        records = list(r)
    assert len(records) == 5
    assert records[3][0]["i"] == 3
    assert records[3][1] == b"payload-3"


def test_recordio_detects_corruption(tmp_path):
    path = str(tmp_path / "bad.rio")
    with RecordWriter(path) as w:
        w.write({"a": 1}, b"data")
    raw = bytearray(open(path, "rb").read())
    raw[-1] ^= 0xFF  # flip a payload byte
    open(path, "wb").write(raw)
    with RecordReader(path) as r:
        with pytest.raises(ValueError):
            r.read()


def test_rpc_dump_and_replay(server, tmp_path):
    from brpc_tpu.rpc import rpc_dump

    rpc_dump.reset_for_tests()
    flags_mod.set_flag("rpc_dump_dir", str(tmp_path))
    flags_mod.set_flag("rpc_dump", True)
    try:
        ch = rpc.Channel()
        assert ch.init(str(server.listen_endpoint)) == 0
        for i in range(5):
            cntl, _ = ch.call("EchoService.Echo",
                              echo_pb2.EchoRequest(message=f"dump{i}"),
                              echo_pb2.EchoResponse)
            assert not cntl.failed()
    finally:
        flags_mod.set_flag("rpc_dump", False)
        rpc_dump.reset_for_tests()
    files = list(tmp_path.glob("*.rio"))
    assert files
    records = []
    for f in files:
        with RecordReader(str(f)) as r:
            records.extend(r)
    assert len(records) == 5
    assert records[0][0]["service"] == "EchoService"
    # replay them via the tool
    proc = subprocess.run(
        [sys.executable, "tools/rpc_replay.py", "--dir", str(tmp_path),
         "--server", str(server.listen_endpoint)],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert "ok=5" in proc.stdout, proc.stdout


def test_rpc_press_tool(server):
    proc = subprocess.run(
        [sys.executable, "tools/rpc_press.py",
         "--server", str(server.listen_endpoint),
         "--method", "EchoService.Echo",
         "--input", '{"message": "press"}',
         "--duration", "1", "--threads", "2"],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert "qps=" in proc.stdout
    assert "errors=0" in proc.stdout


def test_rpc_view_tool(server):
    proc = subprocess.run(
        [sys.executable, "tools/rpc_view.py", str(server.listen_endpoint),
         "status"],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert "EchoService.Echo" in proc.stdout


def test_parallel_http_tool(server):
    url = f"http://{server.listen_endpoint}/health"
    proc = subprocess.run(
        [sys.executable, "tools/parallel_http.py", "--url", url, "-n", "20",
         "--concurrency", "4"],
        capture_output=True, text=True, timeout=60, cwd="/root/repo",
    )
    assert proc.returncode == 0, proc.stderr
    assert "ok=20" in proc.stdout
