"""Memcache binary protocol tests — brpc_memcache_unittest shape: codec
units + client against the in-process binary-protocol server."""
import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.memcache import (
    MemcacheRequest,
    MemcacheResponse,
    MemcacheService,
    OP_GET,
    pack_op,
    parse_op,
)


def test_pack_parse_roundtrip():
    pkt = pack_op(OP_GET, b"key", b"", b"", opaque=77)
    op, pos = parse_op(pkt, 0)
    assert pos == len(pkt)
    assert op["opcode"] == OP_GET and op["key"] == b"key"
    assert op["opaque"] == 77
    assert parse_op(pkt[:10], 0) is None  # incomplete header
    assert parse_op(pkt[:-1], 0) is None  # incomplete body


@pytest.fixture(scope="module")
def mc_server():
    srv = rpc.Server(rpc.ServerOptions(memcache_service=MemcacheService(),
                                       num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _call(server, req):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="memcache",
                                        timeout_ms=3000))
    assert ch.init(str(server.listen_endpoint)) == 0
    resp = MemcacheResponse()
    cntl = rpc.Controller()
    ch.call_method("memcache", cntl, req, resp)
    assert not cntl.failed(), cntl.error_text
    return resp


def test_set_get_delete(mc_server):
    req = MemcacheRequest()
    req.set("k1", "v1").get("k1").delete("k1").get("k1")
    resp = _call(mc_server, req)
    assert resp.result_count == 4
    assert resp.pop_set()
    ok, value = resp.pop_get()
    assert ok and value == b"v1"
    assert resp.pop_delete()
    ok, _ = resp.pop_get()
    assert not ok  # deleted


def test_incr_decr(mc_server):
    req = MemcacheRequest()
    req.incr("counter", 5, initial=10).incr("counter", 5).decr("counter", 3)
    resp = _call(mc_server, req)
    ok, v = resp.pop_counter()
    assert ok and v == 10  # initial on first touch
    ok, v = resp.pop_counter()
    assert ok and v == 15
    ok, v = resp.pop_counter()
    assert ok and v == 12


def test_add_replace_semantics(mc_server):
    req = MemcacheRequest()
    req.add("ar", "first").add("ar", "second").replace("ar", "third") \
       .replace("missing", "x").get("ar")
    resp = _call(mc_server, req)
    assert resp.pop_store()       # add new: ok
    assert not resp.pop_store()   # add existing: KEY_EXISTS
    assert resp.pop_store()       # replace existing: ok
    assert not resp.pop_store()   # replace missing: NOT_STORED
    ok, v = resp.pop_get()
    assert ok and v == b"third"


def test_version(mc_server):
    resp = _call(mc_server, MemcacheRequest().version())
    ok, v = resp.pop_version()
    assert ok and "memcache" in v
