"""dsched model-pass golden tests (slow: compiles native/model/).

The interleaving checker must (1) run green and deterministically on
the shipped lock-free primitives, and (2) catch each seeded defect: a
relaxed-order bug in a WSQ copy (the fence dropped from pop/steal — the
classic Chase-Lev weakening, caught through dsched's stale-read
modeling), a butex waker missing its publish fence (lost wake =>
deadlock), and a descriptor-ring publish escaping the producer lock
(recovery wedges a cell; caught by the post-recovery refill probe).
"""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.natcheck import model  # noqa: E402

pytestmark = pytest.mark.slow

NATIVE = os.path.join(REPO, "native")


def _have_toolchain():
    return shutil.which("make") and shutil.which("g++")


@pytest.fixture(scope="module", autouse=True)
def toolchain():
    if not _have_toolchain():
        pytest.skip("native toolchain unavailable")
    yield
    # leave a CLEAN nat_model behind no matter which test ran last (the
    # seeded-bug test builds against a doctored header)
    subprocess.run(["make", "-C", NATIVE, "nat_model", "-B"],
                   capture_output=True, timeout=600)


def test_model_clean_and_deterministic():
    rc1, out1 = model.build_and_run(
        args=("--mode", "random", "--seed", "7", "--execs", "150"))
    assert rc1 == 0, out1
    assert "FAIL" not in out1, out1
    rc2, out2 = model.build_and_run(
        args=("--mode", "random", "--seed", "7", "--execs", "150"))
    assert rc2 == 0
    # same seed => same schedules => same trace hashes, line for line
    assert out1 == out2


def test_model_dfs_explores_shipped_tree_green():
    rc, out = model.build_and_run(
        args=("--mode", "dfs", "--execs", "600"))
    assert rc == 0, out
    assert out.count("ok") >= 8, out  # incl. the quiesce scenario


def test_model_catches_relaxed_order_wsq_bug(tmp_path):
    # weaken a COPY of wsq.h: drop the seq_cst fences from pop/steal.
    # The model must observe a stale top_/bottom_ read and report an
    # item consumed twice (or lost).
    src = os.path.join(NATIVE, "src", "wsq.h")
    with open(src) as f:
        text = f.read()
    assert "nat::atomic_thread_fence(std::memory_order_seq_cst);" in text
    (tmp_path / "wsq.h").write_text(text.replace(
        "nat::atomic_thread_fence(std::memory_order_seq_cst);",
        "/* seeded bug: fence dropped */"))
    try:
        rc, out = model.build_and_run(
            args=("--scenario", "wsq", "--mode", "random", "--seed", "1",
                  "--execs", "2000"),
            model_inc=f"-I{tmp_path}")
        assert rc != 0, out
        assert "FAIL" in out, out
        assert "consumed twice" in out or "lost" in out or \
            "check failed" in out, out
    finally:
        subprocess.run(["make", "-C", NATIVE, "nat_model", "-B"],
                       capture_output=True, timeout=600)


def test_model_catches_butex_lost_wake():
    rc, out = model.build_and_run(
        args=("--scenario", "butex", "--bug", "butex-no-fence"))
    assert rc != 0, out
    assert "deadlock" in out, out


def test_model_catches_recovery_late_publish():
    rc, out = model.build_and_run(
        args=("--scenario", "recover", "--bug", "recover-late-publish"))
    assert rc != 0, out
    assert "refused fresh offer" in out or "FAIL" in out, out


def test_model_catches_quiesce_late_arm():
    # arming close_after_drain AFTER the idle check (the TOCTOU the
    # store-then-check Dekker order forbids) must lose the close under
    # some interleaving — the drain-vs-role-release race the quiesce
    # scenario exists to pin down
    rc, out = model.build_and_run(
        args=("--scenario", "quiesce", "--bug", "quiesce-arm-late"))
    assert rc != 0, out
    assert "close LOST" in out, out


def test_model_quiesce_clean():
    # the shipped arm_close_after_drain pairing: close never lost, every
    # response pushed before the close drained first
    rc, out = model.build_and_run(args=("--scenario", "quiesce",))
    assert rc == 0, out
    assert "FAIL" not in out, out


def test_model_catches_refrace_stale_id_pin():
    # a borrower that skips the version half of the versioned-ref CAS
    # (sock_address's use-after-free guard) can pin the RECYCLED socket
    # through a stale id under some interleaving
    rc, out = model.build_and_run(
        args=("--scenario", "refrace", "--bug", "refrace-no-version"))
    assert rc != 0, out
    assert "stale id" in out, out


def test_model_refrace_clean():
    # the shipped borrow protocol: a borrow pins the ORIGINAL object
    # until released or fails; the slot recycles exactly once
    rc, out = model.build_and_run(args=("--scenario", "refrace",))
    assert rc == 0, out
    assert "FAIL" not in out, out


def test_model_catches_refxfer_blind_transfer():
    # transferring the admission token onto the InflightEntry without
    # the presence check orphans the token when the worker answers first
    rc, out = model.build_and_run(
        args=("--scenario", "refxfer", "--bug", "refxfer-blind"))
    assert rc != 0, out
    assert "token count ends at" in out, out


def test_model_refxfer_clean():
    rc, out = model.build_and_run(args=("--scenario", "refxfer",))
    assert rc == 0, out
    assert "FAIL" not in out, out
