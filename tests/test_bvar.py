"""bvar tests — shaped after test/bvar_*_unittest.cpp (SURVEY.md section 4):
real threads exercising the per-thread-agent reducers, windows fed by forced
sampler ticks (no 1s sleeps), percentile distribution sanity.
"""
import threading

import pytest

from brpc_tpu import bvar


def test_adder_basic():
    a = bvar.Adder()
    a.update(1)
    a.update(2)
    a << 3
    assert a.get_value() == 6
    a.update(-6)
    assert a.get_value() == 0


def test_adder_multithreaded():
    a = bvar.Adder()
    n_threads, per_thread = 8, 1000

    def work():
        for _ in range(per_thread):
            a.update(1)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert a.get_value() == n_threads * per_thread


def test_maxer_miner():
    mx, mn = bvar.Maxer(), bvar.Miner()
    for v in (3, 9, 1):
        mx.update(v)
        mn.update(v)
    assert mx.get_value() == 9
    assert mn.get_value() == 1


def test_maxer_across_threads():
    mx = bvar.Maxer()

    def work(v):
        mx.update(v)

    ts = [threading.Thread(target=work, args=(i,)) for i in range(20)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert mx.get_value() == 19


def test_int_recorder_average():
    r = bvar.IntRecorder()
    for v in (10, 20, 30):
        r.update(v)
    assert r.average() == pytest.approx(20.0)
    assert r.get_value().num == 3


def test_reducer_reset():
    a = bvar.Adder()
    a.update(5)
    assert a.reset() == 5
    assert a.get_value() == 0


def test_window_adder_delta():
    a = bvar.Adder()
    w = bvar.Window(a, window_size=10)
    bvar.force_tick_for_tests()  # sample with value 0
    a.update(7)
    assert w.get_value() == 7  # now - oldest = 7 - 0
    w.destroy()


def test_window_maxer_series():
    mx = bvar.Maxer()
    w = bvar.Window(mx, window_size=10)
    mx.update(42)
    bvar.force_tick_for_tests()
    assert w.get_value() == 42
    w.destroy()


def test_per_second_positive():
    a = bvar.Adder()
    ps = bvar.PerSecond(a, window_size=10)
    bvar.force_tick_for_tests()
    import time

    a.update(100)
    time.sleep(0.05)
    assert ps.get_value() > 0
    ps.destroy()


def test_percentile():
    p = bvar.Percentile()
    for v in range(1, 1001):
        p.update(v)
    assert 400 <= p.get_number(0.5) <= 600
    assert p.get_number(0.99) >= 900
    assert p.get_number(0.999) >= p.get_number(0.5)


def test_latency_recorder():
    lr = bvar.LatencyRecorder(window_size=10)
    bvar.force_tick_for_tests()  # baseline sample before any updates
    for v in (100, 200, 300):
        lr.update(v)
    assert lr.count() == 3
    assert lr.latency() == pytest.approx(200.0)
    assert lr.max_latency() == 300
    assert lr.latency_percentile(0.5) in (100, 200, 300)


def test_status_and_passive():
    s = bvar.StatusVar(value="init")
    assert s.get_value() == "init"
    s.set_value("changed")
    assert s.get_value() == "changed"
    p = bvar.PassiveStatus(lambda: 41 + 1)
    assert p.get_value() == 42


def test_registry_expose_hide():
    a = bvar.Adder("test_registry_counter_xyz")
    assert bvar.find_exposed("test_registry_counter_xyz") is a
    assert ("test_registry_counter_xyz", 0) in bvar.dump_exposed()
    a.hide()
    assert bvar.find_exposed("test_registry_counter_xyz") is None


def test_duplicate_expose_rejected():
    a = bvar.Adder("dup_name_abc")
    b = bvar.Adder()
    assert not b.expose("dup_name_abc")
    a.hide()


def test_multi_dimension():
    md = bvar.MultiDimension(["method", "code"], bvar.Adder)
    md.get_stats("echo", "200").update(3)
    md.get_stats("echo", "500").update(1)
    assert md.count_stats() == 2
    v = md.get_value()
    assert v[(("method", "echo"), ("code", "200"))] == 3


def test_prometheus_dump():
    a = bvar.Adder("prom_test_counter")
    a.update(5)
    text = bvar.dump_prometheus()
    assert "prom_test_counter 5" in text
    a.hide()


def test_default_variables():
    bvar.expose_default_variables()
    dump = dict(bvar.dump_exposed())
    assert dump["process_pid"] > 0
    assert dump["process_memory_resident_bytes"] > 0
    assert dump["process_fd_count"] > 0
