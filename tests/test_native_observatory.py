"""Native observatory (ISSUE 9): per-method stats, native sockets in
/connections, and the lock-contention profiler.

Covers the three tentpole surfaces end to end — the per-method
MethodStatus table recorded at the native-handler call sites (/status
rows + labeled /brpc_metrics), the per-NatSocket /connections section
with monotonically-increasing counters under a two-process client, and
/hotspots/contention attributing NatMutex wait time to the contended
site — plus the satellites: the /hotspots/native concurrent-request 503
(Retry-After) and Prometheus label-value escaping for method paths.
"""
import http.client
import socket as pysock
import subprocess
import sys
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    headers = {k.lower(): v for k, v in r.getheaders()}
    conn.close()
    return r.status, body, headers


@pytest.fixture(scope="module")
def server():
    """A native-runtime server carrying echo (native handler), HTTP
    (native /echo usercode) and redis (native store) traffic."""
    from brpc_tpu.rpc.redis import RedisService

    srv = rpc.Server(rpc.ServerOptions(num_threads=2,
                                       use_native_runtime=True,
                                       native_builtin_echo=True,
                                       redis_service=RedisService(),
                                       native_redis_store=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port

    h = native.channel_open("127.0.0.1", port)
    for _ in range(30):
        code, body, text = native.channel_call(h, "EchoService", "Echo",
                                               b"y" * 16)
        assert code == 0, (code, text)
    native.channel_close(h)

    status, body, _ = _get(port, "/echo")
    assert status == 200 and body == "pong"

    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    sk.sendall(b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"
               b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n")
    got = b""
    deadline = time.time() + 3
    while b"$1\r\nv\r\n" not in got and time.time() < deadline:
        got += sk.recv(4096)
    sk.close()

    yield srv, port
    srv.stop()


# ---------------------------------------------------------------------------
# tentpole a: per-method stats
# ---------------------------------------------------------------------------

def test_method_stats_table(server):
    rows = {(r["lane"], r["method"]): r for r in native.method_stats()}
    echo = rows[("echo", "EchoService.Echo")]
    assert echo["count"] >= 30
    assert echo["errors"] == 0
    assert echo["concurrency"] == 0       # nothing mid-flight now
    assert echo["max_concurrency"] >= 1   # high-water was held
    assert ("http", "/echo") in rows
    assert rows[("http", "/echo")]["count"] >= 1
    assert ("redis", "SET") in rows and ("redis", "GET") in rows
    # per-method latency histogram answers quantiles
    lanes = native.stats_lane_names()
    p50 = native.method_quantile(lanes.index("echo"), "EchoService.Echo",
                                 0.5)
    p99 = native.method_quantile(lanes.index("echo"), "EchoService.Echo",
                                 0.99)
    assert 0 < p50 <= p99


def test_method_quantile_unknown_claims_no_slot(server):
    """A read-only quantile query for a method that never ran must not
    burn one of the never-freed table slots (typos would otherwise
    permanently shrink the table)."""
    lanes = native.stats_lane_names()
    before = {(r["lane"], r["method"]) for r in native.method_stats()}
    assert native.method_quantile(lanes.index("echo"),
                                  "NoSuch.Method.Typo", 0.99) == 0.0
    after = {(r["lane"], r["method"]) for r in native.method_stats()}
    assert after == before
    assert ("echo", "NoSuch.Method.Typo") not in after


def test_method_table_overflow_rows_reserved():
    """Method names arrive off the wire (HTTP paths, redis command
    words): the per-lane "(other)" overflow rows are claimed at load so
    a client spraying unique names can degrade attribution but never
    disable it."""
    rows = {(r["lane"], r["method"]) for r in native.method_stats()}
    for lane in native.stats_lane_names():
        assert (lane, "(other)") in rows


def test_redis_unknown_command_claims_no_slot(server):
    """Raw wire bytes in an unknown redis command word must not claim a
    method-table slot (only store-family commands record rows)."""
    srv, port = server
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    sk.sendall(b"*1\r\n$9\r\nBOGUSCMD1\r\n")
    deadline = time.time() + 3
    got = b""
    while b"\r\n" not in got and time.time() < deadline:
        got += sk.recv(4096)
    sk.close()
    assert ("redis", "BOGUSCMD1") not in {
        (r["lane"], r["method"]) for r in native.method_stats()}


def test_status_page_has_method_rows(server):
    srv, port = server
    status, body, _ = _get(port, "/status")
    assert status == 200
    assert "method EchoService.Echo [echo]:" in body
    assert "method /echo [http]:" in body
    # the row shape: count/qps/errors/concurrency/max/latency
    for line in body.splitlines():
        if line.strip().startswith("method EchoService.Echo"):
            assert "count=" in line and "qps=" in line
            assert "max_concurrency=" in line and "p99=" in line
            break
    else:
        pytest.fail("echo method row missing from /status")


def test_prometheus_method_labels(server):
    """ISSUE 9 drift satellite: the per-method/per-socket/contention vars
    appear in the Prometheus exposition with label values — method paths
    contain '/' and survive verbatim."""
    srv, port = server
    native.mu_contend_selftest(4, 50, 20)  # ensure a contention row
    status, body, _ = _get(port, "/brpc_metrics")
    assert status == 200
    assert 'nat_method_count{lane="echo",method="EchoService.Echo"}' \
        in body
    assert 'nat_method_count{lane="http",method="/echo"}' in body
    assert 'nat_method_latency_p99_us{lane="echo"' in body
    assert "nat_connection_in_bytes{sock_id=" in body
    assert 'nat_lock_contention_waits{rank="4",name="mu.selftest"}' \
        in body
    # full-surface presence + escaping drift coverage lives in
    # tests/test_native_stats.py::test_observatory_vars_in_prometheus_exposition


def test_prometheus_label_value_escaping():
    """Label values with '"', '\\' and newlines are escaped per the
    Prometheus exposition format (method paths may carry quotes)."""
    from brpc_tpu.bvar.variable import PassiveStatus, dump_prometheus

    var = PassiveStatus(
        lambda: {(("method", '/echo"x\\y\nz'),): 7},
        "test_escape_metric")
    try:
        text = dump_prometheus()
        assert ('test_escape_metric{method="/echo\\"x\\\\y\\nz"} 7'
                in text), text
    finally:
        var.hide()


def test_windowed_rate_clamps_negative(monkeypatch):
    """nat_stats_reset mid-window would otherwise publish a large
    negative qps/byte rate for up to one window length."""
    from brpc_tpu.bvar import native_vars, window

    w = object.__new__(native_vars._ClampedPerSecond)
    monkeypatch.setattr(window.PerSecond, "get_value",
                        lambda self: -123.4)
    assert w.get_value() == 0.0


# ---------------------------------------------------------------------------
# tentpole b: native /connections
# ---------------------------------------------------------------------------

def test_connections_page_lists_native_sockets(server):
    srv, port = server
    status, body, _ = _get(port, "/connections")
    assert status == 200
    assert "native sockets:" in body
    assert "unwritten" in body
    # the console request itself rides a native http session
    assert "|http" in body.replace(" ", "")


def test_connections_two_process_monotonic_counters(server):
    """ISSUE 9 satellite: a native client in ANOTHER process shows up in
    /connections as a live socket whose in/out byte counters increase
    monotonically while it keeps calling."""
    srv, port = server
    repo_root = __file__.rsplit("/", 2)[0]
    script = (
        "import sys, time; sys.path.insert(0, '.')\n"
        "from brpc_tpu import native\n"
        f"h = native.channel_open('127.0.0.1', {port})\n"
        "print('up', flush=True)\n"
        "t0 = time.time()\n"
        "while time.time() - t0 < 8.0:\n"
        "    code, body, text = native.channel_call(h, 'EchoService',\n"
        "                                           'Echo', b'z' * 64)\n"
        "    assert code == 0, (code, text)\n"
        "    time.sleep(0.005)\n"
        "native.channel_close(h)\n")
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdout=subprocess.PIPE, text=True,
                            cwd=repo_root, env=env)
    try:
        assert proc.stdout.readline().strip() == "up"
        time.sleep(0.5)

        def snap_rows():
            return {r["sock_id"]: r for r in native.conn_snapshot()
                    if r["server_side"] and r["protocol"] == "tpu_std"}

        first = snap_rows()
        assert first, "no accepted tpu_std socket visible"
        time.sleep(1.5)
        second = snap_rows()
        grew = 0
        for sid, r1 in first.items():
            r2 = second.get(sid)
            if r2 is None:
                continue
            assert r2["in_bytes"] >= r1["in_bytes"]
            assert r2["out_bytes"] >= r1["out_bytes"]
            if r2["in_bytes"] > r1["in_bytes"] and \
                    r2["out_bytes"] > r1["out_bytes"]:
                grew += 1
                assert r2["in_msgs"] > r1["in_msgs"]
                assert r2["out_msgs"] > r1["out_msgs"]
                assert r2["remote"].startswith("127.0.0.1:")
        assert grew >= 1, (first, second)
        # the /connections page renders the same socket with its rates
        status, body, _ = _get(port, "/connections")
        assert status == 200
        sid = next(s for s, r1 in first.items()
                   if second.get(s, r1)["in_bytes"] > r1["in_bytes"])
        assert str(sid) in body
    finally:
        proc.kill()
        proc.wait(timeout=10)


# ---------------------------------------------------------------------------
# tentpole c: contention profiler
# ---------------------------------------------------------------------------

def test_contention_profiler_attributes_wait_to_stack():
    """ISSUE 9 satellite: a contended-NatMutex stress run shows up in the
    sampled report with the wait attributed to the right lock site (the
    synthesized "lock:mu.selftest" leaf of the frame-pointer stack)."""
    native.mu_prof_reset()
    assert native.mu_prof_start(0, 1, 42) == 0
    assert native.mu_prof_running()
    # double-start must lose (the window is a shared resource)
    assert native.mu_prof_start(0, 1, 42) == -1
    waits = native.mu_contend_selftest(4, 200, 30)
    assert native.mu_prof_stop() == 0
    assert waits >= 1
    assert native.mu_prof_samples() >= 1
    collapsed = native.mu_prof_report(collapsed=True)
    assert "lock:mu.selftest" in collapsed
    # wait-us weighted: the selftest stack's weight is positive
    weight = 0
    for line in collapsed.splitlines():
        if "lock:mu.selftest" in line and not line.startswith("#"):
            weight += int(line.rsplit(" ", 1)[1])
    assert weight >= 1
    flat = native.mu_prof_report(collapsed=False)
    assert "lock:mu.selftest" in flat and "waits" in flat
    # always-on per-rank totals carry it too
    ranks = {r["name"]: r for r in native.mu_rank_stats()}
    assert ranks["mu.selftest"]["waits"] >= waits
    assert ranks["mu.selftest"]["wait_us"] >= 1
    native.mu_prof_reset()
    assert native.mu_prof_samples() == 0
    assert all(r["name"] != "mu.selftest" for r in native.mu_rank_stats())


def test_mu_prof_reset_samples_keeps_rank_totals():
    """The per-rank wait totals ride /brpc_metrics as counters: the
    samples-only reset (what debug pages use) must not zero them."""
    native.mu_prof_reset()
    assert native.mu_prof_start(0, 1, 42) == 0
    waits = native.mu_contend_selftest(4, 100, 20)
    assert native.mu_prof_stop() == 0
    assert waits >= 1 and native.mu_prof_samples() >= 1
    native.mu_prof_reset_samples()
    assert native.mu_prof_samples() == 0
    ranks = {r["name"]: r for r in native.mu_rank_stats()}
    assert ranks["mu.selftest"]["waits"] >= waits  # totals survived
    native.mu_prof_reset()  # the full hygiene reset still clears them
    assert all(r["name"] != "mu.selftest" for r in native.mu_rank_stats())


def test_hotspots_contention_merges_native_and_python(server):
    srv, port = server
    native.mu_prof_reset()
    waits = native.mu_contend_selftest(4, 60, 20)
    status, body, _ = _get(port, "/hotspots/contention?seconds=0.3")
    assert status == 200
    assert "# native lock contention (nat_mu_prof" in body
    assert "# python wait-frame profile" in body
    # per-rank totals line the page carries (the selftest ran just above)
    assert "mu.selftest" in body
    # the page request must not reset the monotonic per-rank counters
    ranks = {r["name"]: r for r in native.mu_rank_stats()}
    assert ranks["mu.selftest"]["waits"] >= waits


def test_contention_window_during_traffic(server):
    """The armed window samples real traffic's contended waits (or at
    minimum the deliberately-contended selftest) without disturbing the
    serving path."""
    srv, port = server
    native.mu_prof_reset()
    assert native.mu_prof_start(0, 1, 7) == 0
    h = native.channel_open("127.0.0.1", port)
    for _ in range(50):
        code, _, _ = native.channel_call(h, "EchoService", "Echo", b"q")
        assert code == 0
    native.mu_contend_selftest(4, 300, 30)
    native.channel_close(h)
    assert native.mu_prof_stop() == 0
    rep = native.mu_prof_report(collapsed=True)
    assert "lock:" in rep
    native.mu_prof_reset()


# ---------------------------------------------------------------------------
# satellite: /hotspots/native single-window 503
# ---------------------------------------------------------------------------

def test_hotspots_native_concurrent_request_gets_503(server):
    """Regression (ISSUE 9 satellite): the nat_prof window is a single
    shared resource — a second concurrent /hotspots/native request gets
    503 + Retry-After instead of colliding with (or blocking behind) the
    running window."""
    srv, port = server
    results = {}

    def first():
        results["first"] = _get(port, "/hotspots/native?seconds=2.5")

    t = threading.Thread(target=first)
    t.start()
    # wait until the first request's window is ACTUALLY running (its
    # handler starts the in-process profiler), so the second request
    # deterministically collides with it
    deadline = time.time() + 5
    while not native.prof_running() and time.time() < deadline:
        time.sleep(0.02)
    assert native.prof_running(), "first window never started"
    status, body, headers = _get(port, "/hotspots/native?seconds=0.1")
    t.join()
    assert results["first"][0] == 200
    assert status == 503, (status, body)
    assert "busy" in body
    # Retry-After reflects the RUNNING window's remaining time (~2.5s),
    # not the rejected request's own tiny seconds parameter
    assert 2 <= int(headers["retry-after"]) <= 4


def test_hotspots_contention_concurrent_request_gets_503(server):
    """The nat_mu_prof sample window is shared the same way: a second
    concurrent /hotspots/contention request must 503 instead of having
    its aggregate wiped by the first window's stop + reset_samples."""
    srv, port = server
    results = {}

    def first():
        results["first"] = _get(port, "/hotspots/contention?seconds=2.5")

    t = threading.Thread(target=first)
    t.start()
    deadline = time.time() + 5
    while not native.mu_prof_running() and time.time() < deadline:
        time.sleep(0.02)
    assert native.mu_prof_running(), "first window never started"
    status, body, headers = _get(port, "/hotspots/contention?seconds=0.1")
    t.join()
    assert results["first"][0] == 200
    assert status == 503, (status, body)
    assert "busy" in body
    assert 2 <= int(headers["retry-after"]) <= 4
