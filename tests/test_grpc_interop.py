"""Interop with the REAL grpc client library (grpcio): stock gRPC
channels calling our h2 server prove actual wire compatibility, not just
self-consistency — the brpc_grpc_protocol_unittest role with a genuine
third-party peer.
"""
import grpc
import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        if request.code:
            cntl.set_failed(request.code, "requested failure")
            done()
            return
        response.message = request.message.upper()
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def stub(server):
    ch = grpc.insecure_channel(f"127.0.0.1:{server.listen_endpoint.port}")
    yield ch.unary_unary(
        "/EchoService/Echo",
        request_serializer=echo_pb2.EchoRequest.SerializeToString,
        response_deserializer=echo_pb2.EchoResponse.FromString)
    ch.close()


def test_grpcio_unary_roundtrip(stub):
    resp = stub(echo_pb2.EchoRequest(message="via stock grpc"), timeout=10)
    assert resp.message == "VIA STOCK GRPC"


def test_grpcio_many_sequential(stub):
    for i in range(25):
        resp = stub(echo_pb2.EchoRequest(message=f"m{i}"), timeout=10)
        assert resp.message == f"M{i}"


def test_grpcio_error_maps_to_status(stub):
    with pytest.raises(grpc.RpcError) as exc:
        stub(echo_pb2.EchoRequest(message="x", code=errors.EPERM),
             timeout=10)
    # the failure surfaces as a real gRPC status, not a transport error
    assert exc.value.code() != grpc.StatusCode.UNAVAILABLE
    assert "requested failure" in (exc.value.details() or "")


def test_grpcio_unknown_method(server):
    ch = grpc.insecure_channel(f"127.0.0.1:{server.listen_endpoint.port}")
    bad = ch.unary_unary(
        "/EchoService/Nope",
        request_serializer=echo_pb2.EchoRequest.SerializeToString,
        response_deserializer=echo_pb2.EchoResponse.FromString)
    with pytest.raises(grpc.RpcError) as exc:
        bad(echo_pb2.EchoRequest(message="x"), timeout=10)
    assert exc.value.code() in (grpc.StatusCode.UNIMPLEMENTED,
                                grpc.StatusCode.NOT_FOUND,
                                grpc.StatusCode.UNKNOWN)
    ch.close()


def test_our_channel_against_grpcio_server():
    """The reverse direction: OUR h2:grpc channel calling a stock grpcio
    SERVER — client-side wire compatibility."""
    from concurrent import futures

    class Handler(grpc.GenericRpcHandler):
        def service(self, handler_call_details):
            if handler_call_details.method == "/EchoService/Echo":
                def unary(request, context):
                    resp = echo_pb2.EchoResponse()
                    resp.message = request.message[::-1]
                    return resp
                return grpc.unary_unary_rpc_method_handler(
                    unary,
                    request_deserializer=echo_pb2.EchoRequest.FromString,
                    response_serializer=(
                        echo_pb2.EchoResponse.SerializeToString))
            return None

    gsrv = grpc.server(futures.ThreadPoolExecutor(max_workers=2))
    gsrv.add_generic_rpc_handlers((Handler(),))
    port = gsrv.add_insecure_port("127.0.0.1:0")
    gsrv.start()
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="h2:grpc",
                                            timeout_ms=5000))
        assert ch.init(f"127.0.0.1:{port}") == 0
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="ours->theirs"),
                             echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "srieht>-sruo"
        ch.close()
    finally:
        gsrv.stop(None)
