"""RTMP client + digest handshake + cross-server relay pull.

Counterpart of the reference's RtmpClient/RtmpClientStream surface
(rtmp.h:723,797) and the digest handshake of policy/rtmp_protocol.cpp:149.
The relay test is the VERDICT r3 #10 shape: publish into server A (its
own process), server B's CLIENT pulls from A, a player reads from B —
the chunk layer exercised by a second implementation end to end.
"""
import subprocess
import sys
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import rtmp_client as rc
from brpc_tpu.rpc import rtmp_protocol as rp


def _start_rtmp_server():
    svc = rp.RtmpService()
    srv = rpc.Server(rpc.ServerOptions(num_threads=4, rtmp_service=svc))
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


def test_digest_primitives_roundtrip():
    c1, dig = rc.make_digest_c1()
    assert rc.find_digest(c1, rc.FP_KEY) is not None
    assert rc.find_digest(c1, rc.FMS_KEY) is None  # wrong key rejects
    s1, s1_dig = rc.make_digest_s1(0)
    assert rc.find_digest(s1, rc.FMS_KEY) is not None
    s2 = rc.make_chained_reply(dig, rc.FMS_KEY_FULL)
    assert rc.verify_chained_reply(s2, dig, rc.FMS_KEY_FULL)
    assert not rc.verify_chained_reply(s2, s1_dig, rc.FMS_KEY_FULL)


def test_digest_handshake_against_own_server():
    srv, svc = _start_rtmp_server()
    try:
        port = srv.listen_endpoint.port
        client = rc.RtmpClient("127.0.0.1", port, use_digest=True)
        client.connect()
        assert client.digest_mode  # the server answered with FMS digests
        stream = client.create_stream()
        stream.publish("digests")
        assert "digests" in svc.stream_names()
        client.close()
    finally:
        srv.stop()


def test_simple_handshake_still_accepted():
    srv, svc = _start_rtmp_server()
    try:
        port = srv.listen_endpoint.port
        client = rc.RtmpClient("127.0.0.1", port, use_digest=False)
        client.connect()
        assert not client.digest_mode
        stream = client.create_stream()
        stream.publish("plain")
        assert "plain" in svc.stream_names()
        client.close()
    finally:
        srv.stop()


def test_client_publish_then_play_roundtrip():
    """Both halves of the client against our server: publish media on one
    connection, play it back on another."""
    srv, svc = _start_rtmp_server()
    try:
        port = srv.listen_endpoint.port
        pub = rc.RtmpClient("127.0.0.1", port).connect()
        pstream = pub.create_stream().publish("cam0")
        pstream.send_metadata({"width": 640.0, "height": 480.0})
        pstream.send_video(b"\x17\x00AVCSEQ", 0)  # AVC seq header shape

        got = []
        done = threading.Event()

        def on_media(msg_type, ts, payload):
            got.append((msg_type, ts, payload))
            if len(got) >= 4:
                done.set()

        player = rc.RtmpClient("127.0.0.1", port).connect()
        player.start_reader()
        player.create_stream().play("cam0", on_media)
        # late joiner gets cached metadata + AVC header, then live frames
        pstream.send_video(b"\x27frame1", 40)
        pstream.send_audio(b"\xafaudio1", 40)
        assert done.wait(10), f"only received {got}"
        types = [t for t, _, _ in got]
        assert rp.MSG_DATA_AMF0 in types  # metadata replayed
        assert any(p == b"\x27frame1" for _, _, p in got)
        assert any(p == b"\xafaudio1" for _, _, p in got)
        player.close()
        pub.close()
    finally:
        srv.stop()


def test_two_process_relay_pull():
    """VERDICT r3 #10: publish into A (separate process), B pulls from A
    via its RtmpClient, a player reads from B."""
    script = (
        "import sys; sys.path.insert(0, '.')\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from brpc_tpu import rpc\n"
        "from brpc_tpu.rpc import rtmp_protocol as rp\n"
        "svc = rp.RtmpService()\n"
        "srv = rpc.Server(rpc.ServerOptions(num_threads=4,"
        " rtmp_service=svc))\n"
        "assert srv.start('127.0.0.1:0') == 0\n"
        "print(srv.listen_endpoint.port, flush=True)\n"
        "sys.stdin.readline()\n")
    proc = subprocess.Popen([sys.executable, "-c", script],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            text=True, cwd="/root/repo")
    srv_b = None
    clients = []
    try:
        port_a = int(proc.stdout.readline())
        # publisher pushes into A
        pub = rc.RtmpClient("127.0.0.1", port_a).connect()
        clients.append(pub)
        pstream = pub.create_stream().publish("live0")
        pstream.send_metadata({"relay": 1.0})
        pstream.send_video(b"\x17\x00SEQ", 0)

        # server B (this process) pulls live0 from A
        srv_b, svc_b = _start_rtmp_server()
        puller = rc.pull_into_service(svc_b, "live0", "127.0.0.1", port_a)
        clients.append(puller)

        # player reads from B
        got = []
        done = threading.Event()

        def on_media(msg_type, ts, payload):
            got.append((msg_type, ts, payload))
            if any(p == b"\x27relayed" for _, _, p in got):
                done.set()

        player = rc.RtmpClient("127.0.0.1",
                               srv_b.listen_endpoint.port).connect()
        clients.append(player)
        player.start_reader()
        player.create_stream().play("live0", on_media)

        # live media published into A must reach B's player; keep pushing
        # (the pull may still be settling when the first frame goes out)
        deadline = time.monotonic() + 15
        while not done.is_set() and time.monotonic() < deadline:
            pstream.send_video(b"\x27relayed", 80)
            done.wait(0.25)
        assert done.is_set(), f"relay delivered only {got}"
    finally:
        for c in clients:
            try:
                c.close()
            except Exception:
                pass
        if srv_b is not None:
            srv_b.stop()
        proc.stdin.close()
        proc.wait(timeout=10)
