"""json2pb satellite tests — the descriptor-walking JSON<->pb codec
(json_to_pb.cpp / pb_to_json.cpp semantics)."""
import json

import pytest

from brpc_tpu import json2pb
from brpc_tpu.rpc.proto import echo_pb2, rpc_meta_pb2


def test_roundtrip_basic():
    req = echo_pb2.EchoRequest(message="hello", code=42)
    text = json2pb.pb_to_json(req)
    obj = json.loads(text)
    assert obj["message"] == "hello" and obj["code"] == 42
    back = json2pb.json_to_pb(text, echo_pb2.EchoRequest)
    assert back.message == "hello" and back.code == 42


def test_nested_and_repeated():
    meta = rpc_meta_pb2.RpcMeta()
    meta.request.service_name = "S"
    meta.request.method_name = "M"
    meta.correlation_id = 99
    t = meta.tensors.add()
    t.shape.extend([2, 3])
    t.dtype = "float32"
    t.nbytes = 24
    text = json2pb.pb_to_json(meta)
    back = json2pb.json_to_pb(text, rpc_meta_pb2.RpcMeta)
    assert back.request.service_name == "S"
    assert list(back.tensors[0].shape) == [2, 3]
    assert back.correlation_id == 99


def test_bytes_base64():
    from brpc_tpu.rpc.proto import legacy_meta_pb2

    meta = legacy_meta_pb2.HuluRpcRequestMeta()
    meta.service_name = "S"
    meta.method_index = 0
    meta.correlation_id = 1
    meta.credential_data = b"\x00\x01\xffbinary"
    text = json2pb.pb_to_json(meta)
    obj = json.loads(text)
    import base64
    assert base64.b64decode(obj["credential_data"]) == b"\x00\x01\xffbinary"
    back = json2pb.json_to_pb(text, legacy_meta_pb2.HuluRpcRequestMeta)
    assert back.credential_data == b"\x00\x01\xffbinary"


def test_int64_as_string_tolerance():
    back = json2pb.json_to_pb('{"correlation_id": "123456789012345"}',
                              rpc_meta_pb2.RpcMeta)
    assert back.correlation_id == 123456789012345


def test_unknown_fields_ignored():
    back = json2pb.json_to_pb('{"nope": 1, "message": "x"}',
                              echo_pb2.EchoRequest)
    assert back.message == "x"


def test_errors_carry_field_paths():
    with pytest.raises(json2pb.ParseError, match="correlation_id"):
        json2pb.json_to_pb('{"correlation_id": "notanint"}',
                           rpc_meta_pb2.RpcMeta)
    with pytest.raises(json2pb.ParseError, match=r"tensors\[0\].nbytes"):
        json2pb.json_to_pb('{"tensors": [{"nbytes": true}]}',
                           rpc_meta_pb2.RpcMeta)
    with pytest.raises(json2pb.ParseError):
        json2pb.json_to_pb('not json', echo_pb2.EchoRequest)


def test_range_checks():
    with pytest.raises(json2pb.ParseError, match="out of range"):
        json2pb.json_to_pb('{"code": 3000000000}', echo_pb2.EchoRequest)


def test_inplace_returns_false_on_error():
    msg = echo_pb2.EchoRequest()
    assert json2pb.json_to_pb_inplace('{"message": "ok"}', msg)
    assert msg.message == "ok"
    assert not json2pb.json_to_pb_inplace('{"code": "bad"}', msg)


def test_options():
    req = echo_pb2.EchoRequest(message="m")
    # always_print_primitive_fields prints the unset int
    text = json2pb.pb_to_json(req, json2pb.Pb2JsonOptions(
        always_print_primitive_fields=True))
    assert json.loads(text).get("code") == 0
    # default omits it
    assert "code" not in json.loads(json2pb.pb_to_json(req))


def test_repeated_requires_array():
    with pytest.raises(json2pb.ParseError, match="array"):
        json2pb.json_to_pb('{"tensors": {"nbytes": 1}}',
                           rpc_meta_pb2.RpcMeta)


def test_fuzz_never_escapes_parse_error():
    """Adversarial JSON shapes must surface as ParseError (HTTP answers
    400), never as raw TypeError/ValueError/struct errors."""
    import random

    rng = random.Random(7)
    shapes = [
        '{"code": {}}', '{"code": []}', '{"code": [1]}',
        '{"message": 5}', '{"message": {}}', '{"message": null, "code": null}',
        '{"code": 1e999}', '{"code": -1e999}', '{"code": "0x10"}',
        '{"code": true}', '[1,2,3]', '"just a string"', '5', 'true',
        '{"tensors": [null]}', '{"tensors": [[]]}',
        '{"request": []}', '{"request": 5}',
        '{"correlation_id": 1.5}', '{"correlation_id": "1.5"}',
        '{"correlation_id": ' + "9" * 40 + '}',
    ]
    for text in shapes:
        msg = rpc_meta_pb2.RpcMeta()
        try:
            json2pb.json_to_pb_inplace(text, msg)
        except json2pb.ParseError:
            pass  # also acceptable from the raising variant
        try:
            json2pb.json_to_pb(text, echo_pb2.EchoRequest)
        except json2pb.ParseError:
            pass
    # random byte soup through the tolerant entry point
    for _ in range(200):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(64)))
        msg = echo_pb2.EchoRequest()
        json2pb.json_to_pb_inplace(blob.decode("latin-1"), msg)
