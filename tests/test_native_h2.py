"""Native h2/gRPC lane — h2 framing + HPACK in the native cut loop,
usercode in Python (kind-4) or native handlers, stock-grpcio interop.

Reference counterpart: policy/http2_rpc_protocol.cpp + details/hpack.cpp.
"""
import threading

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
grpc = pytest.importorskip("grpc")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def native_grpc_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _stub(channel, path="/EchoService/Echo"):
    return channel.unary_unary(
        path,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=echo_pb2.EchoResponse.FromString)


def test_stock_grpcio_unary_over_native_h2(native_grpc_server):
    port = native_grpc_server.listen_endpoint.port
    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        stub = _stub(channel)
        for i in range(10):
            resp = stub(echo_pb2.EchoRequest(message=f"h2-{i}"), timeout=5)
            assert resp.message == f"h2-{i}"


def test_stock_grpcio_error_codes(native_grpc_server):
    port = native_grpc_server.listen_endpoint.port
    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        with pytest.raises(grpc.RpcError) as ei:
            _stub(channel, "/NoService/NoMethod")(
                echo_pb2.EchoRequest(message="x"), timeout=5)
        assert ei.value.code() == grpc.StatusCode.NOT_FOUND
        with pytest.raises(grpc.RpcError) as ei:
            _stub(channel, "/EchoService/NoMethod")(
                echo_pb2.EchoRequest(message="x"), timeout=5)
        assert ei.value.code() == grpc.StatusCode.UNIMPLEMENTED


def test_stock_grpcio_concurrent_streams(native_grpc_server):
    """Many interleaved streams on one connection: HPACK dynamic table +
    stream bookkeeping under concurrency."""
    port = native_grpc_server.listen_endpoint.port
    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        stub = _stub(channel)
        errs = []

        def worker(tag):
            try:
                for i in range(40):
                    m = f"{tag}:{i}"
                    assert stub(echo_pb2.EchoRequest(message=m),
                                timeout=10).message == m
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs


def test_large_messages_exercise_flow_control(native_grpc_server):
    """Messages far beyond the 65535 initial window force DATA chunking,
    WINDOW_UPDATE replenishment, and the parked-response path."""
    port = native_grpc_server.listen_endpoint.port
    opts = [("grpc.max_receive_message_length", 32 << 20),
            ("grpc.max_send_message_length", 32 << 20)]
    with grpc.insecure_channel(f"127.0.0.1:{port}", options=opts) as ch:
        stub = _stub(ch)
        for size in (70_000, 1_000_000, 4_000_000):
            msg = "z" * size
            assert stub(echo_pb2.EchoRequest(message=msg),
                        timeout=30).message == msg


def test_native_grpc_bench_client(native_grpc_server):
    """The native h2 bench client against the py-lane EchoService (only
    one native server may live per process, so it shares the fixture)."""
    port = native_grpc_server.listen_endpoint.port
    req = echo_pb2.EchoRequest(message="x" * 16)
    res = native.grpc_client_bench("127.0.0.1", port, nconn=2, window=32,
                                   seconds=0.5,
                                   payload=req.SerializeToString())
    assert res["requests"] > 50
