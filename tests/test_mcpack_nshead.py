"""mcpack2pb + nshead tests (mcpack codec roundtrips, pb front-end,
nshead framing client/server, pb-over-mcpack adaptor)."""
import pytest

from brpc_tpu import rpc
from brpc_tpu.mcpack2pb import dumps, loads, mcpack_to_pb, pb_to_mcpack
from brpc_tpu.rpc.nshead_protocol import (
    NsheadMessage,
    NsheadPbServiceAdaptor,
    NsheadService,
)
from brpc_tpu.rpc.proto import echo_pb2


def test_mcpack_scalar_roundtrip():
    obj = {
        "int": 42,
        "negative": -7,
        "big": 1 << 40,
        "float": 2.5,
        "string": "hello",
        "binary": b"\x00\x01\x02",
        "flag": True,
        "none": None,
    }
    assert loads(dumps(obj)) == obj


def test_mcpack_nested():
    obj = {
        "nested": {"a": 1, "b": "two"},
        "list": [1, 2, 3],
        "objlist": [{"x": 1}, {"x": 2}],
        "longstr": "y" * 1000,  # exercises the long head
        "bigbin": b"z" * 1000,
    }
    assert loads(dumps(obj)) == obj


def test_mcpack_pb_front_end():
    msg = echo_pb2.EchoRequest(message="mc", code=7)
    data = pb_to_mcpack(msg)
    back = mcpack_to_pb(data, echo_pb2.EchoRequest)
    assert back.message == "mc" and back.code == 7


def test_nshead_frame_roundtrip():
    m = NsheadMessage(b"body-bytes", id_=3, log_id=99)
    raw = m.serialize()
    assert len(raw) == 36 + len(b"body-bytes")
    from brpc_tpu.butil.iobuf import IOPortal
    from brpc_tpu.rpc.nshead_protocol import parse

    portal = IOPortal()
    portal.append(raw)
    result = parse(portal, None, False, None)
    assert result.message.msg.body == b"body-bytes"
    assert result.message.msg.log_id == 99


@pytest.fixture(scope="module")
def nshead_server():
    class UpperService(NsheadService):
        def process_nshead_request(self, cntl, request, done):
            done(NsheadMessage(request.body.upper()))

    srv = rpc.Server(rpc.ServerOptions(nshead_service=UpperService(),
                                       num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_nshead_client_server(nshead_server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="nshead", timeout_ms=3000))
    assert ch.init(str(nshead_server.listen_endpoint)) == 0
    resp = NsheadMessage()
    cntl = rpc.Controller()
    ch.call_method("nshead", cntl, NsheadMessage(b"hello nshead"), resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.body == b"HELLO NSHEAD"


def test_nshead_pb_adaptor():
    def handler(cntl, req, resp):
        resp.message = f"adapted:{req.message}"

    adaptor = NsheadPbServiceAdaptor(echo_pb2.EchoRequest,
                                     echo_pb2.EchoResponse, handler)
    srv = rpc.Server(rpc.ServerOptions(nshead_service=adaptor,
                                       num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="nshead",
                                            timeout_ms=3000))
        assert ch.init(str(srv.listen_endpoint)) == 0
        body = pb_to_mcpack(echo_pb2.EchoRequest(message="pbmc"))
        resp = NsheadMessage()
        cntl = rpc.Controller()
        ch.call_method("nshead", cntl, NsheadMessage(body), resp)
        assert not cntl.failed(), cntl.error_text
        out = mcpack_to_pb(resp.body, echo_pb2.EchoResponse)
        assert out.message == "adapted:pbmc"
    finally:
        srv.stop()
