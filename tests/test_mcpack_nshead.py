"""mcpack2pb + nshead tests (mcpack codec roundtrips, pb front-end,
nshead framing client/server, pb-over-mcpack adaptor)."""
import pytest

from brpc_tpu import rpc
from brpc_tpu.mcpack2pb import dumps, loads, mcpack_to_pb, pb_to_mcpack
from brpc_tpu.rpc.nshead_protocol import (
    NsheadMessage,
    NsheadPbServiceAdaptor,
    NsheadService,
)
from brpc_tpu.rpc.proto import echo_pb2


def test_mcpack_scalar_roundtrip():
    obj = {
        "int": 42,
        "negative": -7,
        "big": 1 << 40,
        "float": 2.5,
        "string": "hello",
        "binary": b"\x00\x01\x02",
        "flag": True,
        "none": None,
    }
    assert loads(dumps(obj)) == obj


def test_mcpack_nested():
    obj = {
        "nested": {"a": 1, "b": "two"},
        "list": [1, 2, 3],
        "objlist": [{"x": 1}, {"x": 2}],
        "longstr": "y" * 1000,  # exercises the long head
        "bigbin": b"z" * 1000,
    }
    assert loads(dumps(obj)) == obj


def test_mcpack_pb_front_end():
    msg = echo_pb2.EchoRequest(message="mc", code=7)
    data = pb_to_mcpack(msg)
    back = mcpack_to_pb(data, echo_pb2.EchoRequest)
    assert back.message == "mc" and back.code == 7


def test_nshead_frame_roundtrip():
    m = NsheadMessage(b"body-bytes", id_=3, log_id=99)
    raw = m.serialize()
    assert len(raw) == 36 + len(b"body-bytes")
    from brpc_tpu.butil.iobuf import IOPortal
    from brpc_tpu.rpc.nshead_protocol import parse

    portal = IOPortal()
    portal.append(raw)
    result = parse(portal, None, False, None)
    assert result.message.msg.body == b"body-bytes"
    assert result.message.msg.log_id == 99


@pytest.fixture(scope="module")
def nshead_server():
    class UpperService(NsheadService):
        def process_nshead_request(self, cntl, request, done):
            done(NsheadMessage(request.body.upper()))

    srv = rpc.Server(rpc.ServerOptions(nshead_service=UpperService(),
                                       num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_nshead_client_server(nshead_server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="nshead", timeout_ms=3000))
    assert ch.init(str(nshead_server.listen_endpoint)) == 0
    resp = NsheadMessage()
    cntl = rpc.Controller()
    ch.call_method("nshead", cntl, NsheadMessage(b"hello nshead"), resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.body == b"HELLO NSHEAD"


def test_nshead_pb_adaptor():
    def handler(cntl, req, resp):
        resp.message = f"adapted:{req.message}"

    adaptor = NsheadPbServiceAdaptor(echo_pb2.EchoRequest,
                                     echo_pb2.EchoResponse, handler)
    srv = rpc.Server(rpc.ServerOptions(nshead_service=adaptor,
                                       num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="nshead",
                                            timeout_ms=3000))
        assert ch.init(str(srv.listen_endpoint)) == 0
        body = pb_to_mcpack(echo_pb2.EchoRequest(message="pbmc"))
        resp = NsheadMessage()
        cntl = rpc.Controller()
        ch.call_method("nshead", cntl, NsheadMessage(body), resp)
        assert not cntl.failed(), cntl.error_text
        out = mcpack_to_pb(resp.body, echo_pb2.EchoResponse)
        assert out.message == "adapted:pbmc"
    finally:
        srv.stop()


# -- codegen front-end (mcpack2pb/generator.cpp analog) ---------------------

def test_generated_codec_roundtrip():
    from brpc_tpu.mcpack2pb_gen import compile_codec, generate_codec_source

    src = generate_codec_source([echo_pb2.EchoRequest])
    # the emitted code is SPECIALIZED: field names appear literally
    assert "'message'" in src and "enc_str" in src
    mod = compile_codec(src, "echo_codec")
    req = echo_pb2.EchoRequest(message="generated", code=7, sleep_us=12)
    wire = mod.serialize_echo_request(req)
    back = mod.parse_echo_request(wire)
    assert back.message == "generated" and back.code == 7
    assert back.sleep_us == 12
    # typed wire: int32 fields use FIELD_INT32 heads, not auto-sizing
    from brpc_tpu import mcpack2pb as mp

    assert bytes([mp.FIELD_INT32]) in wire


def test_generated_adaptor_serves_nshead(tmp_path):
    """A GENERATED adaptor (not the hand-wired NsheadPbServiceAdaptor)
    round-trips over a real nshead channel."""
    from brpc_tpu.mcpack2pb_gen import (
        compile_codec,
        generate_nshead_adaptor_source,
    )
    from brpc_tpu import mcpack2pb as mp

    class GenEchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message.upper()
            done()

    src = generate_nshead_adaptor_source(GenEchoService)
    mod = compile_codec(src, "gen_adaptor")
    adaptor = mod.GenEchoServiceNsheadAdaptor(GenEchoService())

    srv = rpc.Server(rpc.ServerOptions(nshead_service=adaptor,
                                       num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="nshead"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        body = mp.enc_object("", [mp.enc_str("method", "Echo"),
                                  mp.enc_str("message", "shout this")])
        cntl, resp = ch.call("nshead", NsheadMessage(body), NsheadMessage)
        assert not cntl.failed(), cntl.error_text
        out = mp.loads(resp.body)
        assert out["message"] in ("SHOUT THIS", b"SHOUT THIS")
        ch.close()
    finally:
        srv.stop()


def test_codegen_cli(tmp_path):
    import subprocess
    import sys as _sys

    out = tmp_path / "echo_codec.py"
    rc = subprocess.run(
        [_sys.executable, "tools/mcpack2pb_gen.py",
         "brpc_tpu.rpc.proto.echo_pb2:EchoRequest",
         "brpc_tpu.rpc.proto.echo_pb2:EchoResponse", "-o", str(out)],
        cwd="/root/repo", capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr
    text = out.read_text()
    assert "serialize_echo_request" in text
    assert "parse_echo_response" in text


def test_generated_map_fields():
    from brpc_tpu.mcpack2pb_gen import compile_codec, generate_codec_source
    from brpc_tpu.rpc.proto import mapdemo_pb2 as m

    mod = compile_codec(generate_codec_source([m.MapDemo]), "mapdemo_codec")
    d = m.MapDemo(tags=["a", "b"])
    d.counts["x"] = 3
    d.counts["y"] = 0  # map entries have no presence: must survive
    d.shards[7].label = "seven"
    d.shards[7].rank = 2
    back = mod.parse_map_demo(mod.serialize_map_demo(d))
    assert dict(back.counts) == {"x": 3, "y": 0}
    assert back.shards[7].label == "seven" and back.shards[7].rank == 2
    assert list(back.tags) == ["a", "b"]


def test_codegen_output_imports_standalone(tmp_path):
    """CLI output must be importable in a FRESH process (the generated
    module imports its pb2 sources itself)."""
    import subprocess
    import sys as _sys

    out = tmp_path / "standalone_codec.py"
    rc = subprocess.run(
        [_sys.executable, "tools/mcpack2pb_gen.py",
         "brpc_tpu.rpc.proto.echo_pb2:EchoRequest", "-o", str(out)],
        cwd="/root/repo", capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr
    check = subprocess.run(
        [_sys.executable, "-c",
         f"import sys; sys.path.insert(0, '.'); "
         f"sys.path.insert(0, {str(tmp_path)!r}); "
         "import standalone_codec as c; "
         "from brpc_tpu.rpc.proto import echo_pb2; "
         "w = c.serialize_echo_request("
         "echo_pb2.EchoRequest(message='fresh')); "
         "assert c.parse_echo_request(w).message == 'fresh'; "
         "print('standalone ok')"],
        cwd="/root/repo", capture_output=True, text=True)
    assert check.returncode == 0, check.stderr
    assert "standalone ok" in check.stdout
