"""nat_prof — the in-process native sampling profiler (nat_prof.cpp).

SIGPROF/CPU-time sampling with frame-pointer unwind into lock-free
per-thread rings; flat + collapsed reports; surfaced at
/hotspots/native. The sampler must capture real native stacks while the
scheduler burns CPU, and must be inert (zero samples, no handler) when
stopped.
"""
import threading
import time

import pytest

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


def _burn_native(ms=400):
    """Burn CPU inside the native scheduler so SIGPROF lands on real
    C++ stacks (spawn/join churn + a python loop for interpreter
    frames)."""
    native.sched_start(2)
    deadline = time.time() + ms / 1000.0
    while time.time() < deadline:
        native.bench_spawn_join(32, 50)


def test_start_sample_report_stop_cycle():
    native.prof_reset()
    assert native.prof_start(250) == 0
    assert native.prof_running()
    # double-start is refused while running
    assert native.prof_start(250) == -1
    _burn_native()
    assert native.prof_stop() == 0
    assert not native.prof_running()
    n = native.prof_samples()
    assert n > 0, "no samples captured while burning CPU"

    flat = native.prof_report(collapsed=False)
    assert flat.startswith("# nat_prof:")
    assert "flat self samples" in flat
    # at least one non-comment row: "count pct% symbol"
    rows = [ln for ln in flat.splitlines() if not ln.startswith("#")]
    assert rows
    assert "%" in rows[0]

    collapsed = native.prof_report(collapsed=True)
    assert "collapsed stacks" in collapsed.splitlines()[0]
    body = [ln for ln in collapsed.splitlines() if not ln.startswith("#")]
    assert body
    # each folded line ends with the sample count
    assert body[0].rsplit(" ", 1)[1].isdigit()

    native.prof_reset()
    assert native.prof_samples() == 0
    # a report after reset is just the header
    post = [ln for ln in native.prof_report().splitlines()
            if not ln.startswith("#")]
    assert post == []


def test_stop_without_start_is_noop():
    assert native.prof_stop() == 0
    assert not native.prof_running()


def test_hotspots_native_console_page():
    """/hotspots/native renders a nat_prof report (collapsed by default,
    ?flat=1 for the symbol table)."""
    from brpc_tpu.builtin.hotspots import sample_native

    stop = threading.Event()

    def burner():
        while not stop.is_set():
            native.bench_spawn_join(32, 50)

    native.sched_start(2)
    th = threading.Thread(target=burner, daemon=True)
    th.start()
    try:
        out = sample_native(seconds=0.4, hz=250, collapsed=False)
    finally:
        stop.set()
        th.join(5)
    assert "nat_prof" in out
    assert "flat self samples" in out
