"""Streaming RPC over the NATIVE port — DATA/FEEDBACK/CLOSE frames are cut
in the native loop (kind-5 py-lane requests) instead of riding the raw
fallback; semantics must match the Python port (test_streaming.py).

Reference counterpart: policy/streaming_rpc_protocol.cpp parse +
stream.cpp write/window paths.
"""
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class Collector(rpc.StreamInputHandler):
    def __init__(self):
        self.chunks = []
        self.closed = threading.Event()
        self.lock = threading.Lock()

    def on_received_messages(self, stream, messages):
        with self.lock:
            for m in messages:
                self.chunks.append(m.to_bytes())

    def on_closed(self, stream):
        self.closed.set()


class StreamEchoService(rpc.Service):
    """Accepts a stream and echoes every chunk back on it."""

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def OpenStream(self, cntl, request, response, done):
        class EchoBack(rpc.StreamInputHandler):
            def on_received_messages(self, stream, messages):
                for m in messages:
                    stream.write(m)

        s = rpc.stream_accept(cntl,
                              rpc.StreamOptions(handler=EchoBack(),
                                                max_buf_size=32 << 20))
        if s is None:
            cntl.set_failed(errors.EINVAL, "no stream in request")
        response.message = "stream accepted"
        done()


@pytest.fixture(scope="module")
def native_stream_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(StreamEchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _open_stream(server, handler, **opts):
    ch = rpc.Channel()
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl = rpc.Controller()
    cntl.timeout_ms = 5000
    stream = rpc.stream_create(
        cntl, rpc.StreamOptions(handler=handler, **opts))
    resp = echo_pb2.EchoResponse()
    ch.call_method("StreamEchoService.OpenStream", cntl,
                   echo_pb2.EchoRequest(message="open"), resp)
    assert not cntl.failed(), cntl.error_text
    assert stream.wait_connected(3)
    return ch, stream


def test_ordered_echo_over_native_port(native_stream_server):
    col = Collector()
    ch, stream = _open_stream(native_stream_server, col)
    msgs = [f"chunk-{i}".encode() for i in range(50)]
    for m in msgs:
        assert stream.write(m) == 0
    deadline = time.time() + 10
    while len(col.chunks) < len(msgs) and time.time() < deadline:
        time.sleep(0.01)
    assert col.chunks == msgs  # ordered, complete
    stream.close()
    assert col.closed.wait(5)


def test_large_chunks_echo_and_window(native_stream_server):
    """Multi-MB chunks: the native cut loop reassembles whole frames, the
    window (FEEDBACK frames) keeps the writer from overrunning."""
    col = Collector()
    ch, stream = _open_stream(native_stream_server, col,
                              max_buf_size=8 << 20)
    chunk = b"z" * (1 << 20)
    for _ in range(16):
        assert stream.write(chunk, timeout_s=15) == 0
    deadline = time.time() + 20
    while sum(len(c) for c in col.chunks) < 16 << 20 and \
            time.time() < deadline:
        time.sleep(0.01)
    assert sum(len(c) for c in col.chunks) == 16 << 20
    assert all(c == chunk for c in col.chunks)
    # feedback drained the window
    deadline = time.time() + 5
    while stream.unconsumed_bytes and time.time() < deadline:
        time.sleep(0.01)
    assert stream.unconsumed_bytes == 0
    stream.close()


def test_close_propagates_to_server(native_stream_server):
    col = Collector()
    ch, stream = _open_stream(native_stream_server, col)
    assert stream.write(b"one") == 0
    stream.close()
    # server's CLOSE notification comes back: our handler sees on_closed
    assert col.closed.wait(5)


def test_stream_throughput_sanity(native_stream_server):
    """The kind-5 lane moves multi-MB frames without the Python re-parse;
    assert a floor far above the raw-lane era (~0.1 GB/s locally)."""
    col = Collector()
    ch, stream = _open_stream(native_stream_server, col,
                              max_buf_size=32 << 20)
    chunk = b"x" * (4 << 20)
    total = 32 << 20
    t0 = time.perf_counter()
    sent = 0
    while sent < total:
        assert stream.write(chunk, timeout_s=15) == 0
        sent += len(chunk)
    deadline = time.time() + 30
    while sum(len(c) for c in col.chunks) < total and \
            time.time() < deadline:
        time.sleep(0.005)
    dt = time.perf_counter() - t0
    got = sum(len(c) for c in col.chunks)
    assert got == total
    # echo doubles the wire bytes; even so this must beat the raw lane.
    # Low floor: correctness gate only — the 1-core CI box runs client,
    # native loop and py lane on one core; the real figure is the bench
    # artifact's stream_GBps.
    import os
    floor = 0.05e9
    if os.environ.get("BRPC_TPU_SANITIZED"):
        floor = 0.005e9  # ASan costs ~2-5x; keep only a liveness floor
    assert total / dt > floor, f"{total / dt / 1e9:.3f} GB/s"
    stream.close()
