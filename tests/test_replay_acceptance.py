"""ISSUE 12 acceptance: the two-process flight-recorder round trip.

A native server under MIXED tpu_std + HTTP load from a client in
ANOTHER process, with dump sampling and span sampling armed: the
capture files must carry trace_ids findable in /rpcz for the same
window (a regression arrives with its profile AND the exact requests
that caused it), and a native replay of the capture against a
RESTARTED server must complete with zero failed RPCs and recorded
p50/p99. Kept in its own module: the tests own the whole native server
slot (start/stop/restart), which a module-scope rpc.Server fixture
could not share.
"""
import glob
import os
import subprocess
import sys
import time

import pytest

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from brpc_tpu.butil.recordio import RecordReader  # noqa: E402

N_STD = 25
N_HTTP = 10
TRACE_BASE = 0xACE0_0000


def _client_script(port):
    return (
        "import sys; sys.path.insert(0, '.')\n"
        "from brpc_tpu import native\n"
        f"h = native.channel_open('127.0.0.1', {port})\n"
        f"hh = native.channel_open_http('127.0.0.1', {port})\n"
        "print('up', flush=True)\n"
        f"for i in range({N_STD}):\n"
        f"    with native.trace_scope({TRACE_BASE} + i, 0x5):\n"
        "        code, body, text = native.channel_call(\n"
        "            h, 'EchoService', 'Echo',\n"
        "            b'mixed-load-%04d' % i, timeout_ms=5000)\n"
        "    assert code == 0, (code, text)\n"
        f"for i in range({N_HTTP}):\n"
        f"    with native.trace_scope({TRACE_BASE} + 0x1000 + i, 0x6):\n"
        "        st, body = native.http_call(hh, 'POST', '/echo',\n"
        "                                    b'h%d' % i, timeout_ms=5000)\n"
        "    assert st == 200, st\n"
        "native.channel_close(h)\n"
        "native.channel_close(hh)\n"
        "print('done', flush=True)\n")


def test_two_process_capture_rpcz_correlation_and_replay(tmp_path):
    from brpc_tpu import rpcz

    capture_dir = str(tmp_path / "acc")
    port = native.rpc_server_start(native_echo=True)
    native.rpc_server_native_http(True)
    native.stats_enable_spans(1)
    native.stats_drain_spans()  # drop spans from earlier tests
    assert native.dump_start(capture_dir, every=1, seed=77) == 0
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, "-c", _client_script(port)],
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True, cwd=repo_root, env=env)
    try:
        assert proc.stdout.readline().strip() == "up"
        assert proc.stdout.readline().strip() == "done", proc.stderr.read()
        proc.wait(timeout=30)
        assert proc.returncode == 0, proc.stderr.read()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if native.dump_status()["written"] >= N_STD + N_HTTP:
                break
            time.sleep(0.05)
    finally:
        proc.kill()
        native.dump_stop()
        native.stats_enable_spans(0)

    # ---- capture files carry the window's trace ids ----
    records = []
    for path in sorted(glob.glob(os.path.join(capture_dir, "*.rio"))):
        with RecordReader(path) as reader:
            records.extend(reader)
    std_traces = {m["trace_id"] for m, _ in records if m["lane"] == "echo"}
    http_traces = {m["trace_id"] for m, _ in records
                   if m["lane"] == "http"}
    assert std_traces == {TRACE_BASE + i for i in range(N_STD)}
    assert {TRACE_BASE + 0x1000 + i
            for i in range(N_HTTP)} <= http_traces
    std_payloads = sorted(p for m, p in records if m["lane"] == "echo")
    assert std_payloads == sorted(b"mixed-load-%04d" % i
                                  for i in range(N_STD))

    # ---- the same trace ids resolve in /rpcz (drained native spans):
    # a captured request cross-references its span from the window ----
    correlated = 0
    for tid in list(std_traces)[:10]:
        spans = rpcz.find_trace(tid)
        if any(s.full_method == "EchoService.Echo" for s in spans):
            correlated += 1
    assert correlated >= 8, (correlated, len(std_traces))

    # ---- replay against a RESTARTED server: zero failed RPCs,
    # recorded latency quantiles ----
    native.rpc_server_stop()
    port2 = native.rpc_server_start(native_echo=True)
    native.rpc_server_native_http(True)
    try:
        res = native.replay_run("127.0.0.1", port2, capture_dir, times=1,
                                concurrency=4, timeout_ms=5000)
    finally:
        native.rpc_server_stop()
    assert res["failed"] == 0
    assert res["ok"] == res["sent"] == len(records)
    assert 0 < res["p50_us"] <= res["p99_us"]
