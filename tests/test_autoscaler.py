"""Autoscaler decision engine (ISSUE 20): scripted fake observatory in,
grow/shrink/hold/blocked verdicts out. Pure-Python unit tests — no
sockets, no subprocesses, a hand-cranked clock — so every guard in the
control step (band tracking, p99 forcing, broken-member replacement,
cooldown, at-min/at-max, SLO-burn and draining vetoes) is pinned
deterministically. The live end of the controller runs in the bench
autoscale drill and the chaos `resize` round."""
import threading

from brpc_tpu.fleet import hist
from brpc_tpu.fleet.autoscaler import (Autoscaler, AutoscalerConfig,
                                       swarm_tags)


class FakePool:
    def __init__(self, n=2):
        self.n = n
        self.log = []

    def size(self):
        return self.n

    def grow(self, k):
        self.n += k
        self.log.append(("grow", k))
        return k

    def shrink(self, k):
        self.n -= k
        self.log.append(("shrink", k))
        return k


class FakeSlo:
    def __init__(self):
        self.alert = False

    def status(self):
        return {"drill-p99": {"alert": self.alert}}


class FakeSource:
    """Observatory-shaped script: cumulative echo-lane count/buckets and
    per-member rows, advanced by the test between controller steps."""

    def __init__(self, members=2):
        self.count = 0
        self.buckets = [0] * hist.NBUCKETS
        self.members = [{"up": True} for _ in range(members)]
        self.slo = FakeSlo()

    def push(self, n, latency_ns=1_000_000):
        self.count += n
        self.buckets[hist.bucket_of(latency_ns)] += n

    def merged(self):
        return {
            "backends": {f"127.0.0.1:{26100 + i}": dict(row)
                         for i, row in enumerate(self.members)},
            "methods": {"echo/EchoService.Echo": {
                "count": self.count, "buckets": list(self.buckets)}},
        }


def _mk(pool=None, source=None, **cfg_kw):
    cfg_kw.setdefault("min_backends", 2)
    cfg_kw.setdefault("max_backends", 8)
    cfg_kw.setdefault("target_qps_per_backend", 100.0)
    cfg_kw.setdefault("cooldown_s", 0.0)
    t = [0.0]
    pool = pool or FakePool()
    source = source or FakeSource()
    scaler = Autoscaler(AutoscalerConfig(**cfg_kw), pool, source,
                        clock=lambda: t[0])
    return scaler, pool, source, t


def test_desired_for_tracks_the_band():
    cfg = AutoscalerConfig(min_backends=1, max_backends=8,
                           target_qps_per_backend=100.0)
    # mid-band utilization = (0.40 + 0.85) / 2 = 0.625 of target
    assert cfg.desired_for(0.0) == 1
    assert cfg.desired_for(62.5) == 1
    assert cfg.desired_for(63.0) == 2  # ceil past one backend's mid
    assert cfg.desired_for(400.0) == 7
    assert cfg.desired_for(1e9) == 8  # clamped at max


def test_first_step_holds_then_over_band_grows():
    scaler, pool, source, t = _mk(grow_step=2)
    rec = scaler.step()
    assert rec["action"] == "hold"  # no prior window: qps reads 0
    source.push(400)
    t[0] = 1.0
    rec = scaler.step()
    assert rec["qps"] == 400.0
    assert rec["action"] == "grow" and rec["why"] == "over-band"
    assert rec["delta"] == 2 and pool.n == 4
    assert scaler.grows == 1
    assert pool.log == [("grow", 2)]


def test_cooldown_blocks_consecutive_actions():
    scaler, pool, source, t = _mk(cooldown_s=10.0)
    scaler.step()
    source.push(400)
    t[0] = 1.0
    assert scaler.step()["action"] == "grow"
    source.push(400)
    t[0] = 2.0
    rec = scaler.step()
    assert rec["action"] == "blocked" and rec["why"] == "cooldown"
    assert scaler.blocked == 1


def test_at_max_clamps_growth():
    # desired is clamped to max_backends, so a saturated swarm holds
    # under any overload instead of thrashing against the ceiling
    scaler, pool, source, t = _mk(max_backends=2)
    scaler.step()
    source.push(4000)
    t[0] = 1.0
    rec = scaler.step()
    assert rec["action"] == "hold"
    assert rec["desired"] == 2 and pool.n == 2


def test_under_band_shrinks_to_desired():
    # idle 4-member swarm, floor at 2: the first step already reads the
    # (empty) window as under-band and retires the surplus
    scaler, pool, source, t = _mk(pool=FakePool(4), shrink_step=2)
    rec = scaler.step()
    assert rec["action"] == "shrink" and rec["why"] == "under-band"
    assert rec["delta"] == 2 and pool.n == 2
    assert scaler.shrinks == 1


def test_shrink_vetoed_while_slo_burns():
    scaler, pool, source, t = _mk(pool=FakePool(4))
    source.slo.alert = True
    rec = scaler.step()
    assert rec["action"] == "blocked" and rec["why"] == "slo-burning"
    assert pool.n == 4  # an incident is no time to remove capacity
    assert scaler.blocked == 1


def test_shrink_vetoed_while_member_drains():
    scaler, pool, source, t = _mk(pool=FakePool(4))
    source.members[1] = {"up": True, "draining": True}
    rec = scaler.step()
    assert rec["action"] == "blocked" and rec["why"] == "member-draining"
    assert pool.n == 4


def test_at_min_holds_the_floor():
    # desired is clamped to min_backends: an idle swarm at the floor
    # holds instead of retiring its last capacity
    scaler, pool, source, t = _mk(pool=FakePool(2), min_backends=2)
    rec = scaler.step()
    assert rec["action"] == "hold"
    assert rec["desired"] == 2 and pool.n == 2


def test_p99_breach_forces_grow_and_vetoes_shrink():
    # qps says capacity is fine (even shrinkable) — the latency ceiling
    # overrules it in both directions
    scaler, pool, source, t = _mk(pool=FakePool(2), p99_ceiling_ms=10.0,
                                  grow_step=1)
    scaler.step()
    source.push(50, latency_ns=100_000_000)  # 100ms tail
    t[0] = 1.0
    rec = scaler.step()
    assert rec["p99_ms"] > 10.0
    assert rec["action"] == "grow" and rec["why"] == "p99-ceiling"
    assert pool.n == 3


def test_broken_member_is_replaced():
    scaler, pool, source, t = _mk(pool=FakePool(2), grow_step=1)
    scaler.step()
    source.members[1] = {"up": False}  # the corpse in the rollup
    source.push(100)  # desired_for(100) == 2 == size: in-band
    t[0] = 1.0
    rec = scaler.step()
    assert rec["broken"] == 1
    assert rec["desired"] == 3  # replace the corpse's capacity
    assert rec["action"] == "grow" and pool.n == 3


def test_member_restart_reads_as_empty_window():
    """Cumulative sums shrinking (a member restarted) must clamp to an
    empty window, not a negative qps."""
    scaler, pool, source, t = _mk()
    source.push(500)
    scaler.step()
    source.count = 100  # restart: cumulative count fell
    source.buckets = [0] * hist.NBUCKETS
    t[0] = 1.0
    rec = scaler.step()
    assert rec["qps"] == 0.0 and rec["action"] == "hold"


def test_run_loop_survives_a_wedged_scrape():
    scaler, pool, source, t = _mk()

    calls = [0]

    def bad_merged():
        calls[0] += 1
        raise RuntimeError("scrape wedged")

    source.merged = bad_merged
    stop = threading.Event()
    th = threading.Thread(target=scaler.run, args=(0.01, stop))
    th.start()
    try:
        for _ in range(200):
            if calls[0] >= 2:
                break
            threading.Event().wait(0.01)
    finally:
        stop.set()
        th.join(timeout=5)
    assert calls[0] >= 2  # the controller kept stepping past the error


def test_swarm_tags_layout():
    assert swarm_tags([]) == []
    assert swarm_tags([1]) == ["0/1"]
    assert swarm_tags([1, 2]) == ["0/1", "0/1"]
    # n=3 degenerates to one fully-redundant "0/1" group
    assert swarm_tags([1, 2, 3]) == ["0/1", "0/1", "0/1"]
    assert swarm_tags([1, 2, 3, 4]) == ["0/1", "0/1", "0/2", "1/2"]
    assert swarm_tags(list(range(6))) == \
        ["0/1", "0/1", "0/4", "1/4", "2/4", "3/4"]
    # every grow/shrink changes the elastic total -> a real resize
    for n in range(4, 9):
        a = swarm_tags(list(range(n)))
        b = swarm_tags(list(range(n + 1)))
        assert a != b
