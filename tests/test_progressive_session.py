"""Progressive attachment + session-local data tests
(progressive_attachment.h / simple_data_pool.h shapes)."""
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.data_pools import DataFactory, SimpleDataPool
from brpc_tpu.rpc.progressive import (
    ProgressiveReader,
    attach_progressive_reader,
    create_progressive_attachment,
)
from brpc_tpu.rpc.proto import echo_pb2


class PushService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Download(self, cntl, request, response, done):
        pa = create_progressive_attachment(cntl)
        response.message = "headers-sent"
        done()  # respond first, then keep pushing
        if pa is None:
            return

        def pusher():
            for i in range(5):
                pa.write(f"part-{i};".encode())
                time.sleep(0.01)
            pa.close()

        threading.Thread(target=pusher, daemon=True).start()


@pytest.fixture(scope="module")
def push_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(PushService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_progressive_download(push_server):
    ch = rpc.Channel()
    assert ch.init(str(push_server.listen_endpoint)) == 0
    cntl = rpc.Controller()
    cntl.timeout_ms = 3000
    reader = ProgressiveReader()
    attach_progressive_reader(cntl, reader)
    resp = echo_pb2.EchoResponse()
    ch.call_method("PushService.Download", cntl,
                   echo_pb2.EchoRequest(message="get"), resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "headers-sent"
    body = reader.read_all(timeout=5)
    assert body == b"part-0;part-1;part-2;part-3;part-4;"
    assert reader.ended


def test_progressive_callbacks(push_server):
    ch = rpc.Channel()
    assert ch.init(str(push_server.listen_endpoint)) == 0

    parts = []
    ended = threading.Event()

    class MyReader(ProgressiveReader):
        def on_read_one_part(self, data):
            parts.append(data)

        def on_end_of_message(self):
            ended.set()

    cntl = rpc.Controller()
    cntl.timeout_ms = 3000
    attach_progressive_reader(cntl, MyReader())
    resp = echo_pb2.EchoResponse()
    ch.call_method("PushService.Download", cntl,
                   echo_pb2.EchoRequest(message="get"), resp)
    assert not cntl.failed()
    assert ended.wait(5)
    assert len(parts) == 5


def test_simple_data_pool():
    created = []
    pool = SimpleDataPool(DataFactory(lambda: created.append(1) or {"n": 0}))
    a = pool.borrow()
    b = pool.borrow()
    assert pool.created_count == 2
    pool.return_(a)
    c = pool.borrow()
    assert c is a  # reused
    assert pool.created_count == 2
    pool.return_(b)
    pool.return_(c)
    assert pool.free_count == 2


def test_session_local_data_flows_through_rpc():
    borrowed = []

    class SessionEcho(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            assert cntl.session_local_data is not None
            cntl.session_local_data["hits"] += 1
            borrowed.append(id(cntl.session_local_data))
            response.message = "ok"
            done()

    srv = rpc.Server(rpc.ServerOptions(
        session_local_data_factory=DataFactory(lambda: {"hits": 0})))
    srv.add_service(SessionEcho())
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel()
        assert ch.init(str(srv.listen_endpoint)) == 0
        for _ in range(3):
            cntl, _ = ch.call("SessionEcho.Echo",
                              echo_pb2.EchoRequest(message="s"),
                              echo_pb2.EchoResponse, timeout_ms=3000)
            assert not cntl.failed(), cntl.error_text
        assert srv.session_pool.created_count <= 3
        assert len(borrowed) == 3
    finally:
        srv.stop()
