"""HTTP protocol + builtin console tests — shaped after
brpc_http_rpc_protocol_unittest.cpp and the builtin-service unittests:
plain http.client requests against a started server; JSON RPC over HTTP;
http client channel (SURVEY.md sections 2.5, 2.7).
"""
import http.client
import json

import pytest

from brpc_tpu import rpc
from brpc_tpu.butil import flags as flags_mod
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        if request.code:
            cntl.set_failed(request.code, "requested failure")
            done()
            return
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _get(server, path):
    conn = http.client.HTTPConnection("127.0.0.1",
                                      server.listen_endpoint.port, timeout=5)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, r.getheader("content-type", ""), body


def test_health(server):
    status, _, body = _get(server, "/health")
    assert status == 200 and body == "OK\n"


def test_status_page(server):
    status, _, body = _get(server, "/status")
    assert status == 200
    assert "EchoService.Echo" in body
    assert "connection_count" in body


def test_vars_page(server):
    status, _, body = _get(server, "/vars")
    assert status == 200
    assert "process_pid" in body
    status, _, body = _get(server, "/vars/process_pid")
    assert "process_pid" in body and "socket_in_bytes" not in body


def test_flags_page_and_live_edit(server):
    status, _, body = _get(server, "/flags")
    assert status == 200 and "event_dispatcher_num" in body
    flags_mod.define_int("test_http_flag", 1, "test flag")
    status, _, body = _get(server, "/flags/test_http_flag?setvalue=42")
    assert status == 200
    assert flags_mod.get_flag("test_http_flag") == 42


def test_prometheus_metrics(server):
    status, ctype, body = _get(server, "/brpc_metrics")
    assert status == 200
    assert "# TYPE" in body and "process_cpu_seconds" in body


def test_index_version_list(server):
    status, _, body = _get(server, "/index")
    assert status == 200 and "/status" in body
    status, _, body = _get(server, "/version")
    assert body.startswith("brpc_tpu/")
    status, _, body = _get(server, "/list")
    assert json.loads(body) == {"EchoService": ["Echo"]}


def test_connections_bthreads_sockets_protobufs(server):
    for page in ("connections", "bthreads", "sockets", "protobufs"):
        status, _, body = _get(server, f"/{page}")
        assert status == 200, page
        assert body


def test_404(server):
    status, _, body = _get(server, "/no/such/page")
    assert status == 404


def test_json_rpc_over_http(server):
    conn = http.client.HTTPConnection("127.0.0.1",
                                      server.listen_endpoint.port, timeout=5)
    conn.request("POST", "/EchoService/Echo",
                 body=json.dumps({"message": "http-hello"}),
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 200
    assert json.loads(r.read()) == {"message": "http-hello"}
    conn.close()


def test_json_rpc_error_maps_status(server):
    conn = http.client.HTTPConnection("127.0.0.1",
                                      server.listen_endpoint.port, timeout=5)
    conn.request("POST", "/EchoService/Echo",
                 body=json.dumps({"message": "x", "code": errors.ENOMETHOD}),
                 headers={"Content-Type": "application/json"})
    r = conn.getresponse()
    assert r.status == 404  # ENOMETHOD → 404
    conn.close()


def test_query_params_populate_request(server):
    status, _, body = _get(server, "/EchoService/Echo?message=via-query")
    assert status == 200
    assert json.loads(body) == {"message": "via-query"}


def test_pb_body_over_http(server):
    conn = http.client.HTTPConnection("127.0.0.1",
                                      server.listen_endpoint.port, timeout=5)
    conn.request("POST", "/EchoService/Echo",
                 body=echo_pb2.EchoRequest(message="pb-body").SerializeToString(),
                 headers={"Content-Type": "application/proto"})
    r = conn.getresponse()
    assert r.status == 200
    resp = echo_pb2.EchoResponse()
    resp.ParseFromString(r.read())
    assert resp.message == "pb-body"
    conn.close()


def test_http_client_channel(server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="http"))
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="via-http-channel"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "via-http-channel"
    assert cntl.http_response.status_code == 200


def test_http_client_channel_error(server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="http"))
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl, _ = ch.call("EchoService.Echo",
                      echo_pb2.EchoRequest(message="x", code=errors.EPERM),
                      echo_pb2.EchoResponse, timeout_ms=3000)
    assert cntl.failed()
    assert cntl.error_code == errors.EPERM  # carried via x-error-code


def test_vars_chart_svg(server):
    """?chart=1 renders a windowed var's per-second trend as inline SVG
    (the in-browser series charts of the reference's vars_service)."""
    import json as _json
    import time as _time

    from brpc_tpu import bvar

    adder = bvar.Adder("chart_demo_total")
    win = bvar.PerSecond(adder, 5)
    win.expose("chart_demo_qps")
    try:
        # feed the sampler a few 1s ticks
        for _ in range(3):
            adder.update(50)
            win._sampler.take_sample()
            _time.sleep(0.01)
        status, ctype, body = _get(server, "/vars/chart_demo_qps?chart=1")
        assert status == 200 and ctype.startswith("image/svg")
        assert "<svg" in body and "chart_demo_qps" in body
        status, ctype, body = _get(server,
                                   "/vars/chart_demo_qps?chart=1&format=json")
        assert status == 200
        data = _json.loads(body)
        assert data["var"] == "chart_demo_qps"
        assert len(data["points"]) >= 1
        status, _, _ = _get(server, "/vars/zz_missing?chart=1")
        assert status == 404
    finally:
        win.destroy()
        adder.hide()  # drop the registry reference (no /vars pollution)


def test_bad_method_page(server):
    """/EchoService (no method) lists callable methods
    (builtin/bad_method_service.cpp)."""
    status, _, body = _get(server, "/EchoService")
    assert status == 404
    assert "Available methods" in body
    assert "rpc Echo (EchoRequest) returns (EchoResponse);" in body
    status, _, body = _get(server, "/NoSuchService")
    assert status == 404 and "no such page" in body
