"""Auth + RESTful + butil-misc tests (authenticator.h, restful.cpp,
flat_map/fast_rand/crc32c/raw_pack shapes)."""
import http.client
import json

import pytest

from brpc_tpu import rpc
from brpc_tpu.butil.containers import (
    FlatMap,
    RawPacker,
    RawUnpacker,
    ThreadLocal,
    crc32c,
    fast_rand,
    fast_rand_less_than,
)
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.authenticator import AuthContext, HmacAuthenticator
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        user = cntl.auth_context.user if cntl.auth_context else "anon"
        response.message = f"{request.message}@{user}"
        done()


def test_auth_accepts_and_identifies():
    auth = HmacAuthenticator(b"secret", user="alice")
    srv = rpc.Server(rpc.ServerOptions(auth=auth))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel(rpc.ChannelOptions(auth=auth))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="hi"),
                             echo_pb2.EchoResponse, timeout_ms=3000)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "hi@alice"
    finally:
        srv.stop()


def test_auth_rejects_bad_credential():
    srv = rpc.Server(rpc.ServerOptions(auth=HmacAuthenticator(b"server-secret")))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    try:
        # client signs with the wrong secret
        ch = rpc.Channel(rpc.ChannelOptions(
            auth=HmacAuthenticator(b"wrong-secret")))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl, _ = ch.call("EchoService.Echo",
                          echo_pb2.EchoRequest(message="x"),
                          echo_pb2.EchoResponse, timeout_ms=3000)
        assert cntl.error_code == errors.EAUTH
        # no credential at all
        ch2 = rpc.Channel()
        assert ch2.init(str(srv.listen_endpoint)) == 0
        cntl2, _ = ch2.call("EchoService.Echo",
                            echo_pb2.EchoRequest(message="x"),
                            echo_pb2.EchoResponse, timeout_ms=3000)
        assert cntl2.error_code == errors.EAUTH
    finally:
        srv.stop()


def test_restful_mapping():
    srv = rpc.Server(rpc.ServerOptions(
        restful_mappings="/v1/echo => EchoService.Echo"))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    try:
        conn = http.client.HTTPConnection("127.0.0.1",
                                          srv.listen_endpoint.port, timeout=5)
        conn.request("POST", "/v1/echo",
                     body=json.dumps({"message": "rest"}),
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        assert r.status == 200
        assert json.loads(r.read())["message"].startswith("rest@")
        conn.close()
    finally:
        srv.stop()


def test_flat_map():
    m = FlatMap()
    assert m.init(64)
    m.insert("a", 1)
    m["b"] = 2
    assert m.seek("a") == 1 and m.seek("zz") is None
    assert "b" in m and len(m) == 2
    assert m.erase("a") == 1 and m.erase("a") == 0
    assert dict(iter(m)) == {"b": 2}
    m.clear()
    assert m.empty()


def test_fast_rand():
    vals = {fast_rand() for _ in range(10)}
    assert len(vals) == 10
    assert all(0 <= fast_rand_less_than(7) < 7 for _ in range(100))


def test_crc32c_known_vectors():
    # RFC 3720 test vectors for CRC32C
    assert crc32c(b"") == 0
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(bytes(32)) == 0x8A9136AA


def test_raw_pack_unpack():
    data = RawPacker().pack32(0xDEADBEEF).pack64(0x0123456789ABCDEF).bytes()
    u = RawUnpacker(data)
    assert u.unpack32() == 0xDEADBEEF
    assert u.unpack64() == 0x0123456789ABCDEF


def test_thread_local():
    import threading

    tl = ThreadLocal(list)
    tl.get().append(1)
    seen = {}

    def other():
        seen["val"] = list(tl.get())

    t = threading.Thread(target=other)
    t.start()
    t.join()
    assert seen["val"] == []  # fresh per thread
    assert tl.get() == [1]
