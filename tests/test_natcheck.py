"""natcheck golden tests — the checker must fail on seeded defects.

A checker that never fires is indistinguishable from one that works, so
each pass gets a deliberate defect injected into a temp copy and must
flag it: an ABI struct-field reorder, a missing-argtypes declaration, a
wrong scalar width, a memory_order-less atomic, a nontrivial-destructor
static in a thread-spawning file, and a seqlock reader with no re-check.
The shipped tree itself must come back clean.
"""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.natcheck import abi, lint, lockorder  # noqa: E402

BINDINGS = os.path.join(REPO, "brpc_tpu", "native", "__init__.py")


# ---------------------------------------------------------------------------
# ABI pass (needs the toolchain to build the manifest generator)
# ---------------------------------------------------------------------------

def _have_toolchain():
    return shutil.which("make") and shutil.which("g++")


@pytest.fixture(scope="module")
def manifest():
    if not _have_toolchain():
        pytest.skip("native toolchain unavailable")
    try:
        return abi.build_manifest()
    except subprocess.CalledProcessError as e:
        pytest.fail("nat_abi build failed: %s" % e.stderr[-500:])


@pytest.fixture()
def bindings_src():
    with open(BINDINGS, "r", encoding="utf-8") as f:
        return f.read()


def test_abi_clean_on_shipped_tree(manifest):
    findings = abi.check_abi(manifest, [BINDINGS])
    assert findings == [], "\n".join(str(f) for f in findings)


def test_abi_flags_struct_field_reorder(manifest, bindings_src, tmp_path):
    old = ('("trace_id", ctypes.c_uint64),\n'
           '        ("span_id", ctypes.c_uint64),')
    new = ('("span_id", ctypes.c_uint64),\n'
           '        ("trace_id", ctypes.c_uint64),')
    assert old in bindings_src
    p = tmp_path / "reorder.py"
    p.write_text(bindings_src.replace(old, new))
    findings = abi.check_abi(manifest, [str(p)])
    assert any(f.rule == "struct-layout" for f in findings), findings


def test_abi_flags_missing_argtypes(manifest, bindings_src, tmp_path):
    line = "        lib.nat_sched_start.argtypes = [ctypes.c_int]\n"
    assert line in bindings_src
    p = tmp_path / "noargs.py"
    p.write_text(bindings_src.replace(line, ""))
    findings = abi.check_abi(manifest, [str(p)])
    assert any(f.rule == "missing-argtypes" and "nat_sched_start"
               in f.message for f in findings), findings


def test_abi_flags_wrong_scalar_width(manifest, bindings_src, tmp_path):
    old = "lib.nat_sched_start.argtypes = [ctypes.c_int]"
    p = tmp_path / "badtype.py"
    p.write_text(bindings_src.replace(
        old, "lib.nat_sched_start.argtypes = [ctypes.c_uint64]"))
    findings = abi.check_abi(manifest, [str(p)])
    assert any(f.rule == "argtype-mismatch" for f in findings), findings


def test_abi_fields_may_reference_module_constants(manifest, bindings_src,
                                                   tmp_path):
    # `("method", ctypes.c_char * METHOD_LEN)` with a module-level
    # constant is a natural refactor and must parse (not crash the pass)
    old = '("method", ctypes.c_char * 48),'
    assert old in bindings_src
    p = tmp_path / "const.py"
    p.write_text("METHOD_LEN = 48\n" + bindings_src.replace(
        old, '("method", ctypes.c_char * METHOD_LEN),'))
    findings = abi.check_abi(manifest, [str(p)])
    assert findings == [], findings


def test_abi_unresolvable_fields_is_finding_not_crash(manifest,
                                                      bindings_src,
                                                      tmp_path):
    p = tmp_path / "badconst.py"
    p.write_text(bindings_src.replace(
        '("method", ctypes.c_char * 48),',
        '("method", ctypes.c_char * NO_SUCH_CONSTANT),'))
    findings = abi.check_abi(manifest, [str(p)])
    assert any(f.rule == "struct-parse" for f in findings), findings


def test_abi_flags_unknown_symbol(manifest, bindings_src, tmp_path):
    p = tmp_path / "ghost.py"
    p.write_text(bindings_src +
                 "\n_g = None\n"
                 "def _declare(lib):\n"
                 "    lib.nat_no_such_export.restype = ctypes.c_int\n")
    findings = abi.check_abi(manifest, [str(p)])
    assert any(f.rule == "unknown-symbol" for f in findings), findings


def test_abi_flags_fully_undeclared_symbol(manifest, bindings_src,
                                           tmp_path):
    # dropping BOTH argtypes and restype must still be a finding: the
    # symbol would run through CDLL's unchecked attribute fallback
    src = bindings_src.replace(
        "        lib.nat_sched_start.argtypes = [ctypes.c_int]\n", ""
    ).replace("        lib.nat_sched_start.restype = ctypes.c_int\n", "")
    assert "nat_sched_start.argtypes" not in src
    p = tmp_path / "undeclared.py"
    p.write_text(src)
    findings = abi.check_abi(manifest, [str(p)])
    assert any(f.rule == "unbound-symbol" and "nat_sched_start"
               in f.message for f in findings), findings


def test_abi_flags_stale_manifest_vs_exports(manifest):
    exports = set(manifest["symbols"]) | {"nat_added_without_decl"}
    findings = abi.check_abi(manifest, [BINDINGS], exports)
    assert any(f.rule == "unmanifested-export" for f in findings), findings


# ---------------------------------------------------------------------------
# lint pass (pure Python, no toolchain needed)
# ---------------------------------------------------------------------------

def _lint_one(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    # mirror lint.run(): class-body analysis sees scrubbed text only
    nontrivial = lint._nontrivial_classes({str(p): lint._scrub(text)})
    return lint.lint_file(str(p), text, nontrivial)


def test_lint_clean_on_shipped_tree():
    findings = lint.run()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lint_flags_missing_memory_order(tmp_path):
    findings = _lint_one(tmp_path, "a.cpp", """
#include <atomic>
std::atomic<int> g{0};
int f() { return g.load(); }
void h() { g.store(1, std::memory_order_release); }
""")
    assert [f.rule for f in findings] == ["atomic-order"], findings


def test_lint_allows_suppressed_atomic(tmp_path):
    findings = _lint_one(tmp_path, "a.cpp", """
#include <atomic>
std::atomic<int> g{0};
// natcheck:allow(atomic-order): probe only, any order is fine
int f() { return g.load(); }
""")
    assert findings == [], findings


def test_lint_flags_static_dtor_in_thread_spawner(tmp_path):
    findings = _lint_one(tmp_path, "b.cpp", """
#include <string>
#include <thread>
static std::string g_name = "boom";  // destroyed under live threads
void start() { std::thread([] {}).detach(); }
""")
    assert any(f.rule == "static-dtor" for f in findings), findings


def test_lint_static_dtor_needs_thread_spawn(tmp_path):
    # same static, no thread construction in the file: not this rule
    findings = _lint_one(tmp_path, "c.cpp", """
#include <string>
static std::string g_name = "fine";
""")
    assert findings == [], findings


def test_lint_static_dtor_skips_functions_and_pointers(tmp_path):
    findings = _lint_one(tmp_path, "d.cpp", """
#include <string>
#include <thread>
static std::string helper(int a, const std::string& b) { return b; }
static std::string* g_leaked = new std::string("ok");
void start() { std::thread([] {}).detach(); }
""")
    assert findings == [], findings


def test_lint_flags_repo_class_with_nontrivial_member(tmp_path):
    findings = _lint_one(tmp_path, "e.cpp", """
#include <thread>
#include <vector>
struct Pool { std::vector<int> items; };
static Pool g_pool;
void start() { std::thread([] {}).detach(); }
""")
    assert any(f.rule == "static-dtor" and "Pool" in f.message
               for f in findings), findings


def test_lint_static_dtor_ignores_pointer_members(tmp_path):
    # a pointer member (or a parameter/return type mention) of a
    # nontrivial class must not taint the holder
    findings = _lint_one(tmp_path, "i.cpp", """
#include <thread>
#include <vector>
struct Pool { std::vector<int> items; };
struct Reg { int id; Pool* owner; Pool* find(int a); };
static Reg g_reg;
void start() { std::thread([] {}).detach(); }
""")
    assert findings == [], findings


def test_lint_flags_seqlock_reader_without_recheck(tmp_path):
    findings = _lint_one(tmp_path, "f.cpp", """
#include <atomic>
struct Slot { std::atomic<unsigned long> seq; long rec; };
Slot g_slot;
long read_once() {
  if (g_slot.seq.load(std::memory_order_acquire) & 1) return 0;
  return g_slot.rec;  // no seq re-check: torn read undetected
}
""")
    assert any(f.rule == "seqlock-recheck" for f in findings), findings


def test_lint_seqlock_allow_escape_suppresses(tmp_path):
    # the allow() comment must work on the line above the seq load, and
    # the finding must anchor at the load even when the object's name
    # appears earlier as a substring of another identifier
    findings = _lint_one(tmp_path, "f2.cpp", """
#include <atomic>
struct Slot { std::atomic<unsigned long> seq; long rec; };
Slot sl;
long read_once(long cached_slx) {
  (void)cached_slx;
  // natcheck:allow(seqlock-recheck): single-reader mode, writer stopped
  if (sl.seq.load(std::memory_order_acquire) & 1) return 0;
  return sl.rec;
}
""")
    assert findings == [], findings


def test_lint_static_dtor_ignores_class_names_in_comments(tmp_path):
    # a comment mentioning a nontrivial class must not taint the type
    findings = _lint_one(tmp_path, "h.cpp", """
#include <thread>
#include <vector>
struct Pool { std::vector<int> items; };
struct Reg { int id;  /* freed by the Pool owner */ };
static Reg g_reg;
void start() { std::thread([] {}).detach(); }
""")
    assert findings == [], findings


def test_lint_flags_ungated_fault_hook(tmp_path):
    # a seeded hook that calls the fault table directly (skipping the
    # NAT_FAULT_POINT one-branch gate) must be flagged
    findings = _lint_one(tmp_path, "hook.cpp", """
#include "nat_fault.h"
long do_read(int fd) {
  brpc_tpu::NatFaultAct fa = brpc_tpu::nat_fault_hit(brpc_tpu::NF_READ);
  (void)fa;
  return 0;
}
""")
    assert any(f.rule == "fault-gate" for f in findings), findings


def test_lint_flags_ungated_hook_at_new_sites(tmp_path):
    # the quiesce/accept fault sites added with the graceful-drain
    # lifecycle are gated like every other site: a direct table call at
    # either site name must be flagged
    findings = _lint_one(tmp_path, "hook_new.cpp", """
#include "nat_fault.h"
int do_accept() {
  return brpc_tpu::nat_fault_hit(brpc_tpu::NF_ACCEPT).action;
}
int do_drain_poll() {
  return brpc_tpu::nat_fault_hit(brpc_tpu::NF_SHUTDOWN).action;
}
""")
    assert sum(1 for f in findings if f.rule == "fault-gate") == 2, findings


def test_lint_gated_fault_hook_passes(tmp_path):
    # the sanctioned macro shape (and the definition site itself, which
    # lives in nat_fault.h and is exempt) must come back clean
    findings = _lint_one(tmp_path, "hook2.cpp", """
#include "nat_fault.h"
long do_read(int fd) {
  brpc_tpu::NatFaultAct fa = NAT_FAULT_POINT(brpc_tpu::NF_READ);
  (void)fa;
  return 0;
}
""")
    assert findings == [], findings


def test_lint_fault_gate_allow_escape(tmp_path):
    findings = _lint_one(tmp_path, "hook3.cpp", """
#include "nat_fault.h"
long probe() {
  // natcheck:allow(fault-gate): cold diagnostics path, gate irrelevant
  return brpc_tpu::nat_fault_hit(brpc_tpu::NF_READ).action;
}
""")
    assert findings == [], findings


def test_lint_sigsafe_flags_malloc_in_handler(tmp_path):
    # allocation inside a *_sighandler body is the canonical
    # signal-handler deadlock (interrupted allocator lock)
    findings = _lint_one(tmp_path, "sig.cpp", """
#include <cstdlib>
void prof_sighandler(int sig) {
  void* p = malloc(64);
  (void)p;
}
""")
    assert any(f.rule == "sigsafe" for f in findings), findings


def test_lint_sigsafe_follows_infile_callees(tmp_path):
    # the forbidden op hides one call down: the closure scan must reach it
    findings = _lint_one(tmp_path, "sig2.cpp", """
#include <cstdio>
static void helper(int n) {
  printf("%d", n);
}
void timer_sighandler(int sig) {
  helper(sig);
}
""")
    assert any(f.rule == "sigsafe" and "helper" in f.message
               for f in findings), findings


def test_lint_sigsafe_clean_handler_passes(tmp_path):
    # syscalls + lock-free atomics + mem* are the legal vocabulary
    findings = _lint_one(tmp_path, "sig3.cpp", """
#include <atomic>
#include <cstring>
static std::atomic<unsigned long> g_n{0};
void prof_sighandler(int sig) {
  char buf[16];
  memset(buf, 0, sizeof(buf));
  g_n.fetch_add(1, std::memory_order_relaxed);
}
""")
    assert findings == [], findings


def test_lint_sigsafe_keywords_are_not_callees(tmp_path):
    # `if (...)` / `while (...)` inside the handler must not resolve to
    # the file's lexically-first if-block as a "callee": the malloc in
    # the UNRELATED function below must not be attributed to the handler
    findings = _lint_one(tmp_path, "sig6.cpp", """
#include <cstdlib>
#include <atomic>
static std::atomic<int> g_x{0};
void* unrelated(unsigned long n) {
  if (n > 0) {
    return malloc(n);
  }
  return nullptr;
}
void prof_sighandler(int sig) {
  if (sig > 0) {
    g_x.fetch_add(1, std::memory_order_relaxed);
  }
  while (g_x.load(std::memory_order_relaxed) < 0) {
    g_x.store(0, std::memory_order_relaxed);
  }
}
""")
    assert not any(f.rule == "sigsafe" for f in findings), findings


def test_lint_sigsafe_ignores_non_handlers(tmp_path):
    # malloc in ordinary functions is none of this rule's business
    findings = _lint_one(tmp_path, "sig4.cpp", """
#include <cstdlib>
void* grow(unsigned long n) {
  return malloc(n);
}
""")
    assert not any(f.rule == "sigsafe" for f in findings), findings


def test_lint_sigsafe_allow_escape(tmp_path):
    findings = _lint_one(tmp_path, "sig5.cpp", """
#include <cstdlib>
void dump_sighandler(int sig) {
  // natcheck:allow(sigsafe): crash-path dump, process is dying anyway
  void* p = malloc(64);
  (void)p;
}
""")
    assert findings == [], findings


def test_lint_seqlock_reader_with_recheck_passes(tmp_path):
    findings = _lint_one(tmp_path, "g.cpp", """
#include <atomic>
struct Slot { std::atomic<unsigned long> seq; long rec; };
Slot g_slot;
long read_ok() {
  unsigned long s1 = g_slot.seq.load(std::memory_order_acquire);
  long v = g_slot.rec;
  if (g_slot.seq.load(std::memory_order_acquire) != s1) return -1;
  return v;
}
""")
    assert findings == [], findings


# ---------------------------------------------------------------------------
# lockorder pass (pure Python, no toolchain needed)
# ---------------------------------------------------------------------------

_LOCKORDER_PRELUDE = """
#include <mutex>
template <int R> struct NatMutex { void lock(); void unlock(); };
"""


def _lockorder_one(tmp_path, text):
    (tmp_path / "seed.cpp").write_text(_LOCKORDER_PRELUDE + text)
    return lockorder.check(str(tmp_path))


def test_lockorder_clean_on_shipped_tree():
    findings = lockorder.run()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_lockorder_flags_rank_cycle(tmp_path):
    # f1 nests a->b, f2 nests b->a: with ranks total, at least one edge
    # must violate monotonicity — the seeded-cycle detection contract
    findings = _lockorder_one(tmp_path, """
NatMutex<10> mu_a;
NatMutex<20> mu_b;
void f1() { std::lock_guard g1(mu_a); std::lock_guard g2(mu_b); }
void f2() { std::lock_guard g1(mu_b); std::lock_guard g2(mu_a); }
""")
    assert any(f.rule == "lock-order" and "mu_a" in f.message
               for f in findings), findings


def test_lockorder_flags_undeclared_lock(tmp_path):
    findings = _lockorder_one(tmp_path, """
std::mutex naked_mu;
void g() { std::lock_guard g1(naked_mu); }
""")
    assert any(f.rule == "lock-undeclared" and "naked_mu" in f.message
               for f in findings), findings


def test_lockorder_rank_comment_declares_raw_mutex(tmp_path):
    findings = _lockorder_one(tmp_path, """
std::mutex cv_mu;  // natcheck:rank(test.cv, 40)
void g() { std::lock_guard g1(cv_mu); }
""")
    assert findings == [], findings


def test_lockorder_flags_lock_held_across_switch(tmp_path):
    findings = _lockorder_one(tmp_path, """
NatMutex<30> mu_c;
void h() { std::lock_guard g1(mu_c); yield(); }
""")
    assert any(f.rule == "lock-switch" for f in findings), findings


def test_lockorder_switch_allow_escape(tmp_path):
    findings = _lockorder_one(tmp_path, """
NatMutex<30> mu_c;
void h() {
  std::lock_guard g1(mu_c);
  // natcheck:allow(lock-switch): test reason
  yield();
}
""")
    assert findings == [], findings


def test_lockorder_guard_unlock_ends_held_range(tmp_path):
    # the tree's discipline: unlock deliberately before a blocking call
    findings = _lockorder_one(tmp_path, """
NatMutex<30> mu_c;
void h() {
  std::unique_lock g1(mu_c);
  g1.unlock();
  yield();
}
""")
    assert findings == [], findings


def test_lockorder_try_lock_exempt_from_rank_order(tmp_path):
    # a failed try_lock cannot deadlock: out-of-rank try acquisitions
    # are the hot paths' deliberate idiom (push_to_some_worker)
    findings = _lockorder_one(tmp_path, """
NatMutex<10> mu_a;
NatMutex<20> mu_b;
void f() {
  std::lock_guard g1(mu_b);
  std::unique_lock g2(mu_a, std::try_to_lock);
}
""")
    assert findings == [], findings


def test_lockorder_interprocedural_edge(tmp_path):
    findings = _lockorder_one(tmp_path, """
NatMutex<10> mu_a;
NatMutex<20> mu_b;
void inner() { std::lock_guard g(mu_a); }
void outer() { std::lock_guard g(mu_b); inner(); }
""")
    assert any(f.rule == "lock-order" and "via inner" in f.message
               for f in findings), findings


# ---------------------------------------------------------------------------
# entrypoint wiring
# ---------------------------------------------------------------------------

def test_cli_lint_exits_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.natcheck", "lint"],
        cwd=REPO, capture_output=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_cli_lockorder_exits_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.natcheck", "lockorder"],
        cwd=REPO, capture_output=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr

# ---------------------------------------------------------------------------
# lint: resacct (ISSUE 14 — raw allocations in accounted subsystem TUs
# must route through the nat_res ledger or carry a reviewed escape)
# ---------------------------------------------------------------------------


def test_lint_resacct_flags_unaccounted_malloc(tmp_path):
    # the TU uses the accounting macros (self-selecting rule) but one
    # malloc bypasses the ledger: invisible to /heap/native + nat_mem_*
    findings = _lint_one(tmp_path, "res1.cpp", """
#include <cstdlib>
void stray() {
  void* b = malloc(128);
  (void)b;
}
// ---- padding so the stray site sits outside the pairing window ----
// (the rule accepts a NAT_RES_* within 3 lines before / 6 after)
//
//
void seam() {
  void* a = malloc(64);
  NAT_RES_ALLOC(0, 64, a);
}
""")
    flagged = [f for f in findings if f.rule == "resacct"]
    assert len(flagged) == 1 and "res1.cpp:4" in flagged[0].where, \
        findings


def test_lint_resacct_flags_unaccounted_new_and_mmap(tmp_path):
    findings = _lint_one(tmp_path, "res2.cpp", """
#include <sys/mman.h>
struct Obj {};
void seam(int n) {
  NAT_RES_STATIC(1, 4096);
}
Obj* grow() {



  return new Obj();
}
void* seg(size_t n) {



  return mmap(nullptr, n, 0, 0, -1, 0);
}
""")
    rules = [f.rule for f in findings]
    assert rules.count("resacct") == 2, findings


def test_lint_resacct_nearby_macro_pairs(tmp_path):
    # accounting within 3 lines before / 6 after (room for the
    # idiomatic error-check block) pairs the allocation
    findings = _lint_one(tmp_path, "res3.cpp", """
#include <sys/mman.h>
#include <cstdlib>
void seam(size_t n) {
  void* mem = mmap(nullptr, n, 0, 0, -1, 0);
  if (mem == (void*)-1) {
    return;
  }
  NAT_RES_ALLOC(2, n, mem);
}
void rel(void* p, size_t n) {
  NAT_RES_FREE(2, n, p);
  free(p);
}
""")
    assert [f for f in findings if f.rule == "resacct"] == [], findings


def test_lint_resacct_allow_escape(tmp_path):
    findings = _lint_one(tmp_path, "res4.cpp", """
#include <cstdlib>
void seam() {
  void* a = malloc(64);
  NAT_RES_ALLOC(0, 64, a);
}
char* ffi_out() {



  // natcheck:allow(resacct): FFI buffer, freed by the caller
  return (char*)malloc(32);
}
""")
    assert [f for f in findings if f.rule == "resacct"] == [], findings


def test_lint_resacct_leak_declaration_escapes(tmp_path):
    # a declared deliberate leak (the refown leak registry) is reviewed
    # surface — including when the `new` sits on a continuation line
    findings = _lint_one(tmp_path, "res5.cpp", """
#include <map>
void seam() {
  NAT_RES_STATIC(0, 64);
}
// natcheck:leak(g_tbl): detached threads may record through exit()
std::map<int, int>& g_tbl =
    *new std::map<int, int>();
""")
    assert [f for f in findings if f.rule == "resacct"] == [], findings


def test_lint_resacct_only_in_accounted_tus(tmp_path):
    # a TU that never touches the macros is not an accounted subsystem:
    # its raw allocations are out of the rule's jurisdiction
    findings = _lint_one(tmp_path, "res6.cpp", """
#include <cstdlib>
void* plain() {
  return malloc(64);
}
""")
    assert [f for f in findings if f.rule == "resacct"] == [], findings
