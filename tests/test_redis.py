"""Redis protocol tests — brpc_redis_unittest.cpp shape: RESP codec units,
then a brpc_tpu server SPEAKING redis (DictRedisService) exercised by the
framework's own redis client AND by a raw socket speaking vanilla RESP.
"""
import socket

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.redis import (
    DictRedisService,
    RedisReply,
    RedisRequest,
    RedisResponse,
    encode_command,
    parse_reply,
)


def test_resp_encode_command():
    assert encode_command(("SET", "k", "v")) == \
        b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"


def test_resp_parse_scalars():
    r, pos = parse_reply(b"+OK\r\n", 0)
    assert r.kind == "status" and r.value == "OK" and pos == 5
    r, _ = parse_reply(b"-ERR boom\r\n", 0)
    assert r.is_error()
    r, _ = parse_reply(b":42\r\n", 0)
    assert r.value == 42
    r, _ = parse_reply(b"$5\r\nhello\r\n", 0)
    assert r.value == b"hello"
    r, _ = parse_reply(b"$-1\r\n", 0)
    assert r.is_nil()


def test_resp_parse_array_and_partial():
    data = b"*2\r\n$1\r\na\r\n:7\r\n"
    r, pos = parse_reply(data, 0)
    assert r.kind == "array" and r.value[0].value == b"a"
    assert r.value[1].value == 7 and pos == len(data)
    assert parse_reply(b"*2\r\n$1\r\na\r\n", 0) is None  # incomplete
    assert parse_reply(b"$10\r\nabc", 0) is None


def test_reply_encode_roundtrip():
    for reply in (RedisReply.status("OK"), RedisReply.error("ERR x"),
                  RedisReply.integer(-3), RedisReply.string(b"bin\x00ary"),
                  RedisReply.nil(),
                  RedisReply.array([RedisReply.integer(1),
                                    RedisReply.string(b"two")])):
        parsed, pos = parse_reply(reply.encode(), 0)
        assert parsed.kind == reply.kind
        assert pos == len(reply.encode())


@pytest.fixture(scope="module")
def redis_server():
    srv = rpc.Server(rpc.ServerOptions(redis_service=DictRedisService(),
                                       num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_redis_client_through_channel(redis_server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="redis", timeout_ms=3000))
    assert ch.init(str(redis_server.listen_endpoint)) == 0
    req = RedisRequest()
    req.add_command("SET", "name", "brpc_tpu")
    req.add_command("GET", "name")
    req.add_command("INCR", "counter")
    req.add_command("GET missing")
    resp = RedisResponse()
    cntl = rpc.Controller()
    ch.call_method("redis", cntl, req, resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.reply_count == 4
    assert resp.reply(0).value == "OK"
    assert resp.reply(1).value == b"brpc_tpu"
    assert resp.reply(2).value == 1
    assert resp.reply(3).is_nil()


def test_redis_vanilla_client_interop(redis_server):
    """A plain RESP client (what redis-cli sends) must work against the
    multi-protocol port."""
    s = socket.create_connection(
        ("127.0.0.1", redis_server.listen_endpoint.port), timeout=5)
    s.sendall(encode_command(("PING",)))
    assert s.recv(100) == b"+PONG\r\n"
    s.sendall(encode_command(("SET", "k1", "v1")))
    assert s.recv(100) == b"+OK\r\n"
    s.sendall(encode_command(("GET", "k1")))
    assert s.recv(100) == b"$2\r\nv1\r\n"
    s.sendall(encode_command(("DEL", "k1", "k2")))
    assert s.recv(100) == b":1\r\n"
    s.sendall(encode_command(("NOSUCHCMD",)))
    assert s.recv(100).startswith(b"-ERR unknown command")
    s.close()


def test_redis_unknown_command_via_channel(redis_server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="redis", timeout_ms=3000))
    assert ch.init(str(redis_server.listen_endpoint)) == 0
    req = RedisRequest()
    req.add_command("BOGUS")
    resp = RedisResponse()
    cntl = rpc.Controller()
    ch.call_method("redis", cntl, req, resp)
    assert cntl.failed()
    assert "unknown command" in cntl.error_text


def test_custom_handler():
    svc = DictRedisService()
    svc.add_command_handler(
        "double", lambda args: RedisReply.integer(int(args[0]) * 2))
    assert svc.dispatch([b"double", b"21"]).value == 42


def test_concurrent_pipelined_correlation(redis_server):
    """Many threads sharing ONE connection: pipeline entries are pushed
    under the socket write lock, so every reply matches its own RPC."""
    import threading

    from brpc_tpu import rpc
    from brpc_tpu.rpc.redis import RedisRequest, RedisResponse

    ch = rpc.Channel(rpc.ChannelOptions(protocol="redis", timeout_ms=5000))
    assert ch.init(str(redis_server.listen_endpoint)) == 0
    errs = []

    def worker(i):
        for j in range(10):
            req = RedisRequest()
            req.add_command("SET", f"ck{i}", str(i))
            req.add_command("GET", f"ck{i}")
            resp = RedisResponse()
            cntl = rpc.Controller()
            cntl.timeout_ms = 5000
            ch.call_method("redis", cntl, req, resp)
            if cntl.failed():
                errs.append(cntl.error_text)
                return
            got = resp.reply(1).value
            if got != str(i).encode():
                errs.append(f"thread {i} got {got!r}")
                return

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
