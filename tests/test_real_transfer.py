"""REAL jax.experimental.transfer smoke — the xfer lane WITHOUT the fake
fabric (BRPC_TPU_FAKE_XFER unset).

Today's environment blocks cross-process device transfer (the axon
tunnel exposes one chip to one process), so these tests usually SKIP —
the point is that the proof becomes automatic the day the environment
allows it, with no code change (the reference gates its RDMA unittest
the same way: brpc_rdma_unittest.cpp #if BRPC_WITH_RDMA).
"""
import os
import subprocess
import sys

import numpy as np
import pytest


def _real_transfer_probe() -> str:
    """Empty string when a real transfer server can start AND serve a
    loopback pull; else the reason to skip."""
    if os.environ.get("BRPC_TPU_FAKE_XFER"):
        return "BRPC_TPU_FAKE_XFER forces the fake fabric"
    try:
        import jax
        from jax.experimental import transfer  # noqa: F401
    except Exception as e:
        return f"jax.experimental.transfer unavailable: {e}"
    try:
        import jax

        srv = transfer.start_transfer_server(jax.devices()[0].client)
        addr = srv.address()
        if not addr:
            return "transfer server reports no address"
        # loopback self-connect: the cheapest proof the fabric works
        conn = srv.connect(addr)
        arr = jax.numpy.arange(16, dtype=jax.numpy.float32)
        srv.await_pull(1, [arr])
        out = conn.pull(1, [jax.ShapeDtypeStruct(arr.shape, arr.dtype)])
        got = np.asarray(out[0])
        if not np.array_equal(got, np.asarray(arr)):
            return "loopback pull returned wrong bytes"
        return ""
    except Exception as e:
        return f"transfer fabric unusable here: {type(e).__name__}: {e}"


_SKIP_REASON = _real_transfer_probe()

pytestmark = pytest.mark.skipif(
    bool(_SKIP_REASON), reason=_SKIP_REASON or "real transfer usable")


XFER_SERVER_SCRIPT = r"""
import sys
sys.path.insert(0, ".")
from brpc_tpu import rpc
from brpc_tpu.rpc.tensor_service import TensorStoreService

svc = TensorStoreService()
srv = rpc.Server(rpc.ServerOptions(num_threads=2))
srv.add_service(svc)
assert srv.start("127.0.0.1:0") == 0
print(srv.listen_endpoint.port, flush=True)
sys.stdin.readline()
srv.stop()
"""


def test_two_process_real_xfer_push_pull():
    """The full xfer-lane pull path across a process boundary on the
    REAL transfer fabric: publish on the sender's transfer server, peer
    pulls device-to-device, zero payload bytes on the RPC wire."""
    from brpc_tpu.butil import flags as _flags
    from brpc_tpu.rpc import device_transport as dt
    from brpc_tpu.rpc.tensor_service import TensorClient, make_device_channel

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("BRPC_TPU_FAKE_XFER", None)
    proc = subprocess.Popen([sys.executable, "-c", XFER_SERVER_SCRIPT],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            text=True, cwd=repo_root, env=env)
    _flags.set_flag("device_transport_prefer_xfer", True)
    try:
        port = int(proc.stdout.readline())
        ch = make_device_channel(f"127.0.0.1:{port}")
        client = TensorClient(ch)

        xfer0 = dt.lane_counters()["xfer"]
        arr = np.arange(4096, dtype=np.float32).reshape(64, 64) * 0.5
        cntl, resp = client.push("real-xw", [arr])
        assert not cntl.failed(), cntl.error_text
        assert resp.ok
        assert dt.lane_counters()["xfer"] == xfer0 + 1
        assert len(cntl.request_attachment) == 0  # bytes rode the fabric

        cntl2, pulled = client.pull("real-xw")
        assert not cntl2.failed(), cntl2.error_text
        np.testing.assert_array_equal(np.asarray(pulled[0]), arr)
        ch.close()
    finally:
        _flags.set_flag("device_transport_prefer_xfer", False)
        proc.stdin.close()
        proc.wait(timeout=10)
