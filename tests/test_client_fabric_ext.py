"""Client-fabric breadth tests: HTTP-backed naming services
(consul/discovery/nacos/remotefile), the _dynpart LB, and the cluster
recover policy — the brpc_naming_service_unittest.cpp pattern with a local
HTTP registry double.
"""
import http.server
import json
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.cluster_recover import (
    DefaultClusterRecoverPolicy,
    recover_policy_from_params,
)
from brpc_tpu.rpc.load_balancer import create_load_balancer
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def echo_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    assert srv.add_service(EchoService()) == 0
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()
    srv.join(1)


@pytest.fixture(scope="module")
def registry(echo_server):
    """An HTTP registry double answering consul/discovery/nacos/remotefile
    queries, all pointing at the echo server."""
    ep = echo_server.listen_endpoint
    addr, port = ep.ip, ep.port

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            if self.path.startswith("/v1/health/service/"):
                body = json.dumps([{ "Service": {
                    "Address": addr, "Port": port, "Tags": ["0/1"]}}])
            elif self.path.startswith("/discovery/fetchs"):
                body = json.dumps({"data": {"echo.app": {"instances": [
                    {"addrs": [f"grpc://{addr}:{port}"]}]}}})
            elif self.path.startswith("/nacos/v1/ns/instance/list"):
                body = json.dumps({"hosts": [
                    {"ip": addr, "port": port, "weight": 2.0,
                     "healthy": True, "enabled": True}]})
            elif self.path.startswith("/files/"):
                body = f"{addr}:{port}\n# comment line\n"
            else:
                self.send_response(404)
                self.end_headers()
                return
            raw = body.encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

    httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    yield httpd.server_address[1]
    httpd.shutdown()


@pytest.mark.parametrize("url_fmt", [
    "consul://127.0.0.1:{p}/echo",
    "discovery://127.0.0.1:{p}/echo.app",
    "nacos://127.0.0.1:{p}/echo",
    "remotefile://127.0.0.1:{p}/files/servers.txt",
])
def test_http_naming_services(registry, url_fmt):
    ch = rpc.Channel()
    assert ch.init(url_fmt.format(p=registry), "rr") == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="via ns"),
                         echo_pb2.EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "via ns"
    ch.close()


def test_ns_parsers_reject_garbage():
    """Unreachable registries / malformed replies resolve to empty lists,
    never raise (the NS thread must survive registry outages)."""
    from brpc_tpu.rpc import naming_service as ns

    for cls, path in [(ns.ConsulNamingService, "127.0.0.1:1/none"),
                      (ns.DiscoveryNamingService, "127.0.0.1:1/none"),
                      (ns.NacosNamingService, "127.0.0.1:1/none"),
                      (ns.RemoteFileNamingService, "127.0.0.1:1/none")]:
        assert cls().get_servers(path) == []


def test_dynpart_lb_weights_by_capacity():
    lb = create_load_balancer("_dynpart")
    caps = {10: 3, 20: 1, 30: 0}
    lb.set_capacity_fn(lambda sid: caps[sid])
    for sid in caps:
        lb.add_server(sid)
    picks = [lb.select_server() for _ in range(400)]
    assert 30 not in picks  # capacity 0 never chosen
    n10 = picks.count(10)
    n20 = picks.count(20)
    assert n10 + n20 == 400
    assert n10 > n20  # 3:1 expected ratio, loosely checked
    caps[10] = 0
    caps[20] = 0
    assert lb.select_server() is None


def test_recover_policy_params():
    p = recover_policy_from_params("min_working_instances=2 hold_seconds=3")
    assert isinstance(p, DefaultClusterRecoverPolicy)
    assert recover_policy_from_params("hold_seconds=3") is None
    assert create_load_balancer("rr:bogus") is None
    lb = create_load_balancer("rr:min_working_instances=2 hold_seconds=3")
    assert lb is not None and lb.cluster_recover_policy is not None


def test_recover_policy_rejects_then_heals(monkeypatch):
    policy = DefaultClusterRecoverPolicy(min_working_instances=4,
                                         hold_seconds=0.2)
    # healthy: no rejects
    assert not policy.do_reject([])
    policy.start_recover()
    assert policy.stop_recover_if_necessary()

    # all servers down -> everything rejected (usable=0)
    monkeypatch.setattr(policy, "_usable_count", lambda now, ids: 0)
    assert all(policy.do_reject([1, 2]) for _ in range(50))

    # half back -> some pass, some rejected
    policy._usable_cache_t = 0.0
    monkeypatch.setattr(policy, "_usable_count", lambda now, ids: 2)
    results = [policy.do_reject([1, 2]) for _ in range(200)]
    assert any(results) and not all(results)

    # stable usable count for hold_seconds -> recovery ends
    time.sleep(0.25)
    assert not policy.stop_recover_if_necessary()
    assert not policy.recovering
    assert not policy.do_reject([1, 2])


def test_channel_enters_recovery_when_cluster_down(echo_server):
    """End-to-end: LB with recover params; all sockets failed -> select
    triggers start_recover; subsequent calls see EREJECT or fail-fast."""
    ep = echo_server.listen_endpoint
    ch = rpc.Channel()
    assert ch.init(f"list://{ep.ip}:{ep.port}",
                   "rr:min_working_instances=1 hold_seconds=0.1") == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="ok"),
                         echo_pb2.EchoResponse)
    assert not cntl.failed()

    policy = ch._lb.cluster_recover_policy
    assert policy is not None and not policy.recovering
    # kill every server socket the NS created
    from brpc_tpu.rpc.socket import Socket

    for sid in ch._lb.server_ids():
        Socket.address(sid).set_failed(errors.EFAILEDSOCKET, "induced")
    cntl2, _ = ch.call("EchoService.Echo",
                       echo_pb2.EchoRequest(message="x"),
                       echo_pb2.EchoResponse, timeout_ms=500)
    assert cntl2.failed()
    assert policy.recovering  # the dead cluster flipped it on
    ch.close()
