"""TLS on the NATIVE lane — SSL integrated into NatSocket (the
socket.h:539-540 SSLState design): the same native port answers TLS and
plaintext, and every native protocol lane (tpu_std, HTTP, h2, raw
fallback) rides the decrypted stream unchanged.
"""
import os
import socket
import ssl as pyssl
import subprocess

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("nat_certs")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    proc = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=127.0.0.1",
         # grpcio validates the SAN, not the CN
         "-addext", "subjectAltName=IP:127.0.0.1"],
        capture_output=True, timeout=60,
    )
    if proc.returncode != 0:
        pytest.skip("openssl unavailable")
    return cert, key


@pytest.fixture(scope="module")
def tls_server(certs):
    cert, key = certs
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       ssl_certfile=cert,
                                       ssl_keyfile=key))
    srv.add_service(EchoService())
    rc = srv.start("127.0.0.1:0")
    if rc != 0:
        pytest.skip("native TLS unavailable (libssl missing?)")
    yield srv
    srv.stop()


def _tls_connect(port):
    ctx = pyssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = pyssl.CERT_NONE
    raw = socket.create_connection(("127.0.0.1", port), timeout=5)
    return ctx.wrap_socket(raw)


def test_https_through_native_http_lane(tls_server):
    port = tls_server.listen_endpoint.port
    tls = _tls_connect(port)
    tls.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
    data = tls.recv(65536)
    assert b"200" in data and data.endswith(b"OK\n")
    # keep-alive RPC-over-HTTPS on the same TLS connection
    body = b'{"message": "https"}'
    tls.sendall(b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
                b"Content-Type: application/json\r\n"
                b"Content-Length: %d\r\n\r\n" % len(body) + body)
    data = tls.recv(65536)
    assert b'"https"' in data
    tls.close()


def test_plaintext_coexists_on_same_port(tls_server):
    port = tls_server.listen_endpoint.port
    c = socket.create_connection(("127.0.0.1", port), timeout=5)
    c.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
    assert b"200" in c.recv(65536)
    c.close()


def test_tpu_std_rpc_over_native_tls(tls_server):
    ch = rpc.Channel(rpc.ChannelOptions(use_ssl=True, timeout_ms=5000,
                                        connect_timeout_ms=5000))
    assert ch.init(str(tls_server.listen_endpoint)) == 0
    for i in range(5):
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message=f"ntls{i}"),
                             echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == f"ntls{i}"


def test_large_payload_over_native_tls(tls_server):
    """Multi-record messages both directions: the memory-BIO filter must
    reassemble across TLS record boundaries."""
    ch = rpc.Channel(rpc.ChannelOptions(use_ssl=True, timeout_ms=15000,
                                        connect_timeout_ms=5000))
    assert ch.init(str(tls_server.listen_endpoint)) == 0
    big = "s" * 300_000
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message=big),
                         echo_pb2.EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == big


def test_concurrent_tls_connections(tls_server):
    """Several TLS clients at once: the per-session lock must keep the
    record layer sane while responders (py lane) and the reading thread
    interleave."""
    import threading

    errs = []

    def worker(tag):
        try:
            ch = rpc.Channel(rpc.ChannelOptions(use_ssl=True,
                                                timeout_ms=10000,
                                                connect_timeout_ms=5000))
            assert ch.init(str(tls_server.listen_endpoint)) == 0
            for i in range(20):
                m = f"t{tag}-{i}"
                cntl, resp = ch.call("EchoService.Echo",
                                     echo_pb2.EchoRequest(message=m),
                                     echo_pb2.EchoResponse)
                assert not cntl.failed(), cntl.error_text
                assert resp.message == m
        except Exception as e:  # pragma: no cover
            errs.append(e)

    ts = [__import__("threading").Thread(target=worker, args=(t,))
          for t in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs


def test_grpc_over_native_tls(tls_server, certs):
    grpc = pytest.importorskip("grpc")
    cert, _ = certs
    port = tls_server.listen_endpoint.port
    creds = grpc.ssl_channel_credentials(
        root_certificates=open(cert, "rb").read())
    with grpc.secure_channel(f"127.0.0.1:{port}", creds) as channel:
        stub = channel.unary_unary(
            "/EchoService/Echo",
            request_serializer=lambda m: m.SerializeToString(),
            response_deserializer=echo_pb2.EchoResponse.FromString)
        resp = stub(echo_pb2.EchoRequest(message="grpc+tls"), timeout=10)
        assert resp.message == "grpc+tls"


def test_tls_record_garbage_keeps_server_alive(tls_server):
    """Hostile TLS records against the native SSL session: the parser is
    C++, so surviving garbage IS the test — afterwards both a plaintext
    and a clean TLS request must still answer."""
    import random

    port = tls_server.listen_endpoint.port
    rng = random.Random(11)
    payloads = [
        b"\x16\x03\x01" + b"\xff" * 100,  # bogus ClientHello
        b"\x16\x03",                      # truncated record header
        b"\x16\x03\x01\xff\xff" + b"A" * 200,  # huge declared record
    ]
    for _ in range(25):
        payloads.append(b"\x16\x03" + bytes(
            rng.randrange(256) for _ in range(rng.randrange(1, 300))))
    for p in payloads:
        try:
            sk = socket.create_connection(("127.0.0.1", port), timeout=5)
            sk.settimeout(0.25)
            sk.sendall(p)
            try:
                sk.recv(4096)
            except OSError:
                pass
            sk.close()
        except OSError:
            pass
    # plaintext lane still answers...
    c = socket.create_connection(("127.0.0.1", port), timeout=5)
    c.settimeout(5)
    c.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
    assert b"200" in c.recv(65536)
    c.close()
    # ...and so does a REAL TLS handshake
    tls = _tls_connect(port)
    tls.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
    assert b"200" in tls.recv(65536)
    tls.close()
