"""End-to-end RPC tests over loopback — the minimum slice of SURVEY.md
section 7 stage 4, shaped after brpc_server_unittest.cpp:168-417 /
brpc_channel_unittest.cpp: client and server in one process over 127.0.0.1.
"""
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        if request.code:
            cntl.set_failed(request.code, "requested failure")
            done()
            return
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        response.message = request.message
        # echo the attachment back (brpc echo example behavior)
        cntl.response_attachment.append(cntl.request_attachment)
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    assert srv.add_service(EchoService()) == 0
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()
    srv.join(1)


@pytest.fixture(scope="module")
def channel(server):
    ch = rpc.Channel()
    assert ch.init(str(server.listen_endpoint)) == 0
    return ch


def test_sync_echo(channel):
    cntl, resp = channel.call(
        "EchoService.Echo", echo_pb2.EchoRequest(message="hello tpu"),
        echo_pb2.EchoResponse,
    )
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "hello tpu"
    assert cntl.latency_us > 0


def test_many_sequential(channel):
    for i in range(50):
        cntl, resp = channel.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message=f"m{i}"),
            echo_pb2.EchoResponse,
        )
        assert not cntl.failed(), cntl.error_text
        assert resp.message == f"m{i}"


def test_async_echo(channel):
    done_ev = threading.Event()
    results = {}

    def on_done(cntl):
        results["failed"] = cntl.failed()
        done_ev.set()

    cntl = rpc.Controller()
    resp = echo_pb2.EchoResponse()
    channel.call_method(
        "EchoService.Echo", cntl,
        echo_pb2.EchoRequest(message="async"), resp, on_done,
    )
    assert done_ev.wait(5)
    assert results["failed"] is False
    assert resp.message == "async"


def test_concurrent_calls(channel):
    n = 20
    failures = []
    done = threading.Event()
    remaining = [n]
    lock = threading.Lock()

    def one(i):
        cntl, resp = channel.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message=f"c{i}"),
            echo_pb2.EchoResponse, timeout_ms=5000,
        )
        with lock:
            if cntl.failed() or resp.message != f"c{i}":
                failures.append((i, cntl.error_code, cntl.error_text))
            remaining[0] -= 1
            if remaining[0] == 0:
                done.set()

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    assert done.wait(20)
    for t in threads:
        t.join(5)
    assert not failures, failures


def test_attachment_roundtrip(channel):
    cntl = rpc.Controller()
    cntl.request_attachment.append(b"tensor-bytes-here" * 100)
    resp = echo_pb2.EchoResponse()
    channel.call_method(
        "EchoService.Echo", cntl, echo_pb2.EchoRequest(message="att"), resp,
    )
    assert not cntl.failed(), cntl.error_text
    assert cntl.response_attachment.to_bytes() == b"tensor-bytes-here" * 100


def test_large_payload(channel):
    big = "x" * (1 << 20)  # 1MB message
    cntl, resp = channel.call(
        "EchoService.Echo", echo_pb2.EchoRequest(message=big),
        echo_pb2.EchoResponse, timeout_ms=10000,
    )
    assert not cntl.failed(), cntl.error_text
    assert resp.message == big


def test_server_side_error_propagates(channel):
    cntl, _ = channel.call(
        "EchoService.Echo",
        echo_pb2.EchoRequest(message="boom", code=errors.EPERM),
        echo_pb2.EchoResponse,
    )
    assert cntl.failed()
    assert cntl.error_code == errors.EPERM
    assert "requested failure" in cntl.error_text


def test_unknown_method(channel):
    cntl, _ = channel.call(
        "EchoService.NoSuchMethod", echo_pb2.EchoRequest(message="x"),
        echo_pb2.EchoResponse,
    )
    assert cntl.error_code == errors.ENOMETHOD


def test_unknown_service(channel):
    cntl, _ = channel.call(
        "NoSuchService.Echo", echo_pb2.EchoRequest(message="x"),
        echo_pb2.EchoResponse,
    )
    assert cntl.error_code == errors.ENOSERVICE


def test_rpc_timeout(server, channel):
    st = server.method_statuses()["EchoService.Echo"]
    before = st.latency_recorder.count()
    cntl, _ = channel.call(
        "EchoService.Echo",
        echo_pb2.EchoRequest(message="slow", sleep_us=500_000),
        echo_pb2.EchoResponse, timeout_ms=50,
    )
    assert cntl.error_code == errors.ERPCTIMEDOUT
    # latency should be ~timeout, far below the server sleep
    assert cntl.latency_us < 400_000
    # Drain the server-side straggler HERE, at its source: the client
    # timed out but the handler is still mid-sleep, and its completion
    # bumps this method's status ~450ms from now — leaking that into a
    # later test made test_method_status_tracks's before/after count
    # read flake (the known inter-module flake: the bump landed inside
    # the later test's one-call window).
    deadline = time.monotonic() + 5.0
    while st.latency_recorder.count() <= before and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert st.latency_recorder.count() > before


def test_connection_refused_fails_fast():
    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=2000, max_retry=0))
    assert ch.init("127.0.0.1:1") == 0  # nothing listens there
    cntl, _ = ch.call(
        "EchoService.Echo", echo_pb2.EchoRequest(message="x"),
        echo_pb2.EchoResponse,
    )
    assert cntl.failed()


def test_compression_roundtrip(channel):
    from brpc_tpu.rpc.controller import COMPRESS_GZIP

    cntl = rpc.Controller()
    cntl.compress_type = COMPRESS_GZIP
    resp = echo_pb2.EchoResponse()
    channel.call_method(
        "EchoService.Echo", cntl,
        echo_pb2.EchoRequest(message="z" * 10000), resp,
    )
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "z" * 10000


def test_pooled_connection_type(server):
    ch = rpc.Channel(rpc.ChannelOptions(connection_type="pooled"))
    assert ch.init(str(server.listen_endpoint)) == 0
    for i in range(5):
        cntl, resp = ch.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message=f"p{i}"),
            echo_pb2.EchoResponse,
        )
        assert not cntl.failed(), cntl.error_text


def test_short_connection_type(server):
    ch = rpc.Channel(rpc.ChannelOptions(connection_type="short"))
    assert ch.init(str(server.listen_endpoint)) == 0
    for i in range(3):
        cntl, resp = ch.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message=f"s{i}"),
            echo_pb2.EchoResponse,
        )
        assert not cntl.failed(), cntl.error_text


def test_method_status_tracks(server, channel):
    statuses = server.method_statuses()
    st = statuses["EchoService.Echo"]
    before = st.latency_recorder.count()
    channel.call("EchoService.Echo", echo_pb2.EchoRequest(message="t"),
                 echo_pb2.EchoResponse)
    assert st.latency_recorder.count() == before + 1
