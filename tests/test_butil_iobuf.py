"""IOBuf tests — modeled on the reference's iobuf_unittest.cpp shape."""
import os
import socket

import pytest

from brpc_tpu.butil.iobuf import (
    IOBuf,
    IOBufAppender,
    IOBufCutter,
    IOPortal,
    DEFAULT_BLOCK_SIZE,
)


def test_append_and_read():
    b = IOBuf()
    b.append(b"hello ")
    b.append("world")
    assert len(b) == 11
    assert b.to_bytes() == b"hello world"
    assert b == b"hello world"


def test_append_iobuf_is_zero_copy():
    a = IOBuf(b"x" * 100)
    b = IOBuf()
    b.append(a)
    assert len(a) == 100 and len(b) == 100
    # Shares blocks: cutting from b must not disturb a.
    b.cut(50)
    assert len(a) == 100


def test_cut_zero_copy_split():
    b = IOBuf(b"0123456789")
    front = b.cut(4)
    assert front.to_bytes() == b"0123"
    assert b.to_bytes() == b"456789"
    assert len(b) == 6


def test_cut_across_blocks():
    b = IOBuf()
    big = bytes(range(256)) * 100  # > 1 block
    b.append(big)
    assert len(b) == len(big)
    front = b.cut(DEFAULT_BLOCK_SIZE + 17)
    assert front.to_bytes() == big[: DEFAULT_BLOCK_SIZE + 17]
    assert b.to_bytes() == big[DEFAULT_BLOCK_SIZE + 17 :]


def test_pop_front_back():
    b = IOBuf(b"abcdefgh")
    assert b.pop_front(3) == 3
    assert b.to_bytes() == b"defgh"
    assert b.pop_back(2) == 2
    assert b.to_bytes() == b"def"
    assert b.pop_front(100) == 3
    assert b.empty()


def test_copy_to_bytes_with_pos():
    b = IOBuf(b"0123456789")
    assert b.copy_to_bytes(3, pos=2) == b"234"
    assert b.copy_to_bytes() == b"0123456789"
    assert len(b) == 10  # non-destructive


def test_user_data_zero_copy_and_meta():
    freed = []
    mem = bytearray(b"tensor-bytes")
    b = IOBuf()
    b.append_user_data(mem, deleter=lambda m: freed.append(m), meta=0xDEAD)
    assert b.to_bytes() == b"tensor-bytes"
    assert b._refs[0].block.meta == 0xDEAD


def test_appender_and_cutter():
    app = IOBufAppender()
    app.append(b"\x00\x00\x00\x05")
    app.append(b"hello")
    buf = app.take()
    cut = IOBufCutter(buf)
    n = cut.cut_uint32_be()
    assert n == 5
    assert cut.cutn(5) == b"hello"
    assert cut.remaining() == 0
    with pytest.raises(EOFError):
        cut.cutn(1)


def test_fd_io_roundtrip():
    r, w = socket.socketpair()
    try:
        src = IOBuf()
        payload = os.urandom(DEFAULT_BLOCK_SIZE * 3 + 123)
        src.append(payload)
        total = len(src)
        while not src.empty():
            src.cut_into_socket(w)
        w.close()
        portal = IOPortal()
        while True:
            n = portal.append_from_socket(r)
            if n == 0:
                break
        assert len(portal) == total
        assert portal.to_bytes() == payload
    finally:
        r.close()


def test_device_block_materializes_once():
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp
    import numpy as np

    arr = jnp.arange(16, dtype=jnp.float32)
    b = IOBuf()
    b.append_device_array(arr, meta=7)
    assert len(b) == arr.nbytes
    assert b.device_arrays()[0] is arr
    host = b.to_bytes()
    assert np.frombuffer(host, dtype=np.float32).tolist() == list(range(16))
