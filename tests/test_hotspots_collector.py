"""Hotspots profiler + Collector + trackme tests (builtin/hotspots_service,
bvar/collector, details/trackme shapes)."""
import http.client
import json
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.builtin.hotspots import sample_cpu, thread_dump
from brpc_tpu.bvar.collector import Collectable, Collector
from brpc_tpu.rpc.proto import echo_pb2


def test_sample_cpu_sees_busy_thread():
    stop = threading.Event()

    def busy_loop_marker_fn():
        while not stop.is_set():
            sum(range(100))

    t = threading.Thread(target=busy_loop_marker_fn, name="busy")
    t.start()
    try:
        out = sample_cpu(seconds=0.3, hz=200)
        assert "busy_loop_marker_fn" in out
        assert "# cpu profile" in out
    finally:
        stop.set()
        t.join()


def test_thread_dump():
    out = thread_dump()
    assert "thread" in out and "test_hotspots_collector" in out


def test_hotspots_http_endpoint():
    class S(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = "x"
            done()

    srv = rpc.Server()
    srv.add_service(S())
    assert srv.start("127.0.0.1:0") == 0
    try:
        conn = http.client.HTTPConnection("127.0.0.1",
                                          srv.listen_endpoint.port,
                                          timeout=10)
        conn.request("GET", "/hotspots/cpu?seconds=0.2")
        r = conn.getresponse()
        assert r.status == 200
        assert b"cpu profile" in r.read()
        conn.request("GET", "/threads")
        r = conn.getresponse()
        assert r.status == 200 and b"thread" in r.read()
        conn.request("GET", "/pprof/profile?seconds=0.2")
        r = conn.getresponse()
        assert r.status == 200
        conn.close()
    finally:
        srv.stop()


def test_collector_budget():
    c = Collector(max_samples_per_second=10)
    kept = sum(1 for _ in range(100) if c.submit(object()))
    assert kept == 10  # budget enforced within the 1s window
    assert c.submitted_count == 100
    assert len(c.drain()) == 10
    assert c.pending_count == 0


def test_collector_destroys_dropped():
    destroyed = []

    class Obj(Collectable):
        def destroy(self):
            destroyed.append(1)

    c = Collector(max_samples_per_second=1)
    c.submit(Obj())
    c.submit(Obj())  # over budget: destroyed
    assert len(destroyed) == 1


def test_trackme_ping():
    from brpc_tpu.butil import flags
    from brpc_tpu.rpc import trackme

    received = []

    def handler(server, req):
        received.append(json.loads(req.body.to_bytes()))
        return 200, "application/json", json.dumps({"ok": True,
                                                    "notice": "hello"})

    srv = rpc.Server()
    assert srv.start("127.0.0.1:0") == 0
    srv._builtin_handlers["trackme"] = handler
    try:
        flags.set_flag("trackme_server", str(srv.listen_endpoint))
        assert trackme._ping_once()
        assert received and "version" in received[0]
    finally:
        flags.set_flag("trackme_server", "")
        srv.stop()
