"""Sanitizer lane (slow): the native smoke subset under ASan+UBSan / TSan.

Builds the instrumented .so + smoke driver (`make -C native asan|tsan`)
and runs echo / http / redis / stats / clean-exit under each, with the
checked-in suppressions applied. Any unsuppressed report fails. Marked
slow: two full instrumented builds; run via NATCHECK_SLOW=1 tools/check.sh
or `pytest -m slow tests/test_natcheck_sanitizers.py`.
"""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.natcheck import san  # noqa: E402

pytestmark = pytest.mark.slow

if not (shutil.which("make") and shutil.which("g++")):
    pytest.skip("native toolchain unavailable", allow_module_level=True)


def _sanitizer_available(flag: str) -> bool:
    probe = "int main(){return 0;}"
    proc = subprocess.run(
        ["g++", "-x", "c++", "-", flag, "-o", os.devnull],
        input=probe.encode(), capture_output=True, timeout=120)
    return proc.returncode == 0


@pytest.mark.parametrize("kind,flag", [
    ("asan", "-fsanitize=address"),
    ("tsan", "-fsanitize=thread"),
])
def test_sanitizer_smoke(kind, flag):
    if not _sanitizer_available(flag):
        pytest.skip(f"{flag} unsupported by this toolchain")
    rc, out = san.build_and_run(kind)
    bad = [ln for ln in out.splitlines()
           if any(mk in ln for mk in san._BAD_MARKERS)]
    assert rc == 0 and not bad, (
        f"{kind} smoke rc={rc}\n" + "\n".join(bad[:10]) + "\n" + out[-1500:])
    assert "nat_smoke: ok" in out
