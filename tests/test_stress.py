"""Stress tests — the reference's real-concurrency unittest style
(bthread_ping_pong_unittest / brpc_socket_unittest fault-injection): a
multi-protocol request storm on one port, and failure/revival churn under
load. Bounded to a few seconds each.
"""
import json
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.proto import echo_pb2
from brpc_tpu.rpc.redis import DictRedisService, RedisRequest, RedisResponse
from brpc_tpu.rpc.thrift import T_STRING, ThriftMessage, ThriftService


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


def _make_server():
    tsvc = ThriftService()
    tsvc.add_method("Echo", lambda body: {
        0: (T_STRING, body.get(1, (T_STRING, b""))[1])})
    srv = rpc.Server(rpc.ServerOptions(
        num_threads=4,
        redis_service=DictRedisService(),
        thrift_service=tsvc,
    ))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    return srv


def test_mixed_protocol_storm():
    """Six protocols hammer ONE port concurrently for ~3s; every call must
    succeed and the console must stay responsive afterwards."""
    srv = _make_server()
    target = str(srv.listen_endpoint)
    stop = threading.Event()
    stats = {}
    thread_errors = []
    lock = threading.Lock()

    def record(kind, ok):
        with lock:
            good, bad = stats.get(kind, (0, 0))
            stats[kind] = (good + ok, bad + (not ok))

    def guarded(fn, *args):
        # worker exceptions must FAIL the test, not die silently
        def run():
            try:
                fn(*args)
            except Exception as e:  # noqa: BLE001
                with lock:
                    thread_errors.append(f"{fn.__name__}: {e!r}")
        return run

    def pb_loop(protocol):
        ch = rpc.Channel(rpc.ChannelOptions(protocol=protocol,
                                            timeout_ms=8000))
        assert ch.init(target) == 0
        i = 0
        while not stop.is_set():
            cntl, resp = ch.call("EchoService.Echo",
                                 echo_pb2.EchoRequest(message=f"{protocol}{i}"),
                                 echo_pb2.EchoResponse)
            record(protocol, (not cntl.failed()
                              and resp.message == f"{protocol}{i}"))
            i += 1
        ch.close()

    def redis_loop():
        ch = rpc.Channel(rpc.ChannelOptions(protocol="redis",
                                            timeout_ms=8000))
        assert ch.init(target) == 0
        i = 0
        while not stop.is_set():
            req = RedisRequest()
            req.add_command("SET", f"k{i % 8}", f"v{i}")
            req.add_command("GET", f"k{i % 8}")
            resp = RedisResponse()
            cntl = rpc.Controller()
            ch.call_method("redis", cntl, req, resp)
            record("redis", not cntl.failed() and resp.reply_count == 2)
            i += 1
        ch.close()

    def thrift_loop():
        ch = rpc.Channel(rpc.ChannelOptions(protocol="thrift",
                                            timeout_ms=8000))
        assert ch.init(target) == 0
        i = 0
        while not stop.is_set():
            resp = ThriftMessage()
            cntl = rpc.Controller()
            ch.call_method("thrift", cntl,
                           ThriftMessage("Echo",
                                         {1: (T_STRING, f"t{i}".encode())}),
                           resp)
            record("thrift", not cntl.failed())
            i += 1
        ch.close()

    def http_loop():
        import http.client

        i = 0
        conn = http.client.HTTPConnection("127.0.0.1",
                                          srv.listen_endpoint.port,
                                          timeout=10)
        while not stop.is_set():
            try:
                conn.request("POST", "/EchoService/Echo",
                             body=json.dumps({"message": f"h{i}"}),
                             headers={"Content-Type": "application/json"})
                r = conn.getresponse()
                body = r.read()
            except (http.client.RemoteDisconnected, ConnectionError,
                    TimeoutError):
                # a storm harness reconnects (keep-alive may drop under
                # contention); liveness is asserted by the ok counts
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", srv.listen_endpoint.port, timeout=10)
                continue
            record("http", r.status == 200
                   and json.loads(body)["message"] == f"h{i}")
            i += 1
        conn.close()

    threads = [threading.Thread(target=guarded(pb_loop, p))
               for p in ("tpu_std", "hulu_pbrpc", "sofa_pbrpc")]
    threads += [threading.Thread(target=guarded(redis_loop)),
                threading.Thread(target=guarded(thrift_loop)),
                threading.Thread(target=guarded(http_loop))]
    for t in threads:
        t.start()
    time.sleep(3.0)
    stop.set()
    for t in threads:
        t.join(10)

    assert not thread_errors, f"worker threads raised: {thread_errors}"
    total = sum(g + b for g, b in stats.values())
    failures = {k: v for k, v in stats.items() if v[1]}
    assert not failures, f"failures under storm: {failures} of {stats}"
    assert total > 200, f"storm barely ran: {stats}"
    assert len(stats) == 6

    # console still healthy after the storm
    import urllib.request

    body = urllib.request.urlopen(
        f"http://127.0.0.1:{srv.listen_endpoint.port}/status",
        timeout=5).read()
    assert b"connection_count" in body
    srv.stop()


def test_failure_revival_churn():
    """Sockets are repeatedly SetFailed (the fault-injection-by-API style
    of brpc_socket_unittest) and the health check must revive them.

    Deterministic by design (VERDICT r3 #9): discrete kill->recover
    rounds with EVENT-DRIVEN waits — each round asserts an actual state
    transition (a call succeeding after the kill), never a wall-clock
    call count or success ratio, so CPU contention on the CI box can
    slow the test but not change its verdict. A background caller keeps
    concurrent traffic flowing through every transition; its only
    obligation is to not raise."""
    from brpc_tpu.rpc.socket import Socket

    srv = _make_server()
    ep = srv.listen_endpoint
    ch = rpc.Channel(rpc.ChannelOptions(
        timeout_ms=2000, health_check_interval_s=0.05))
    assert ch.init(f"list://{ep.ip}:{ep.port}", "rr") == 0

    stop = threading.Event()
    churn_errors = []

    def caller():
        i = 0
        try:
            while not stop.is_set():
                ch.call("EchoService.Echo",
                        echo_pb2.EchoRequest(message=f"c{i}"),
                        echo_pb2.EchoResponse)
                i += 1
        except Exception as e:  # noqa: BLE001
            churn_errors.append(f"caller: {e!r}")

    def call_until_ok(tag, deadline_s=20.0):
        """Event-driven: retry until a call round-trips (or hard fail)."""
        deadline = time.monotonic() + deadline_s
        while time.monotonic() < deadline:
            cntl, resp = ch.call("EchoService.Echo",
                                 echo_pb2.EchoRequest(message=tag),
                                 echo_pb2.EchoResponse)
            if not cntl.failed() and resp.message == tag:
                return True
            time.sleep(0.02)
        return False

    t1 = threading.Thread(target=caller)
    t1.start()
    try:
        for round_no in range(4):
            assert call_until_ok(f"pre{round_no}"), \
                f"round {round_no}: no healthy connection to kill"
            for sid in ch._lb.server_ids():
                s = Socket.address(sid)
                if s is not None and not s.failed():
                    s.set_failed(errors.EFAILEDSOCKET, "chaos monkey")
            # the transition under test: the health checker re-dials and
            # a call succeeds again — however long the loaded box takes
            assert call_until_ok(f"post{round_no}"), \
                f"round {round_no}: no revival after SetFailed"
    finally:
        stop.set()
        t1.join(15)
    assert not churn_errors, f"caller raised: {churn_errors}"
    ch.close()
    srv.stop()


def test_ring_lane_storm():
    """Concurrent Python channels + raw HTTP console GETs hammer a
    ring-enabled native port: exercises ring drain concurrency, fixed-send
    recycling, and the mixed tpu_std/HTTP cut loop under load."""
    from brpc_tpu import native

    if not native.available() or native.use_io_uring(True) != 1:
        pytest.skip("io_uring unavailable")
    try:
        port = native.rpc_server_start("127.0.0.1", 0, nworkers=2,
                                       native_echo=True)
        stop = threading.Event()
        errors_seen = []
        counts = [0, 0]

        def rpc_loop(slot):
            try:
                ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=5000))
                assert ch.init(f"127.0.0.1:{port}") == 0
                i = 0
                while not stop.is_set():
                    cntl, resp = ch.call(
                        "EchoService.Echo",
                        echo_pb2.EchoRequest(message=f"r{slot}.{i}"),
                        echo_pb2.EchoResponse)
                    if cntl.failed() or resp.message != f"r{slot}.{i}":
                        errors_seen.append(cntl.error_text or "bad echo")
                        return
                    counts[slot] += 1
                    i += 1
                ch.close()
            except Exception as e:  # noqa: BLE001
                errors_seen.append(repr(e))

        def http_loop():
            import urllib.request

            try:
                while not stop.is_set():
                    body = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/status", timeout=5).read()
                    if b"nat_server_requests" not in body:
                        errors_seen.append("bad /status body")
                        return
            except Exception as e:  # noqa: BLE001
                errors_seen.append(repr(e))

        threads = [threading.Thread(target=rpc_loop, args=(0,)),
                   threading.Thread(target=rpc_loop, args=(1,)),
                   threading.Thread(target=http_loop)]
        for t in threads:
            t.start()
        time.sleep(2.5)
        stop.set()
        for t in threads:
            t.join(10)
        assert not errors_seen, errors_seen[:3]
        assert sum(counts) > 100
        recv, send = native.ring_counters()
        assert recv > 0 and send > 0  # traffic really rode the ring
    finally:
        native.rpc_server_stop()
        native.use_io_uring(False)


def test_native_lane_storm():
    """Every native lane at once on ONE use_native_runtime port: tpu_std
    via Python channels, HTTP through the native parser (native + py
    usercode), gRPC through the native h2 session, and streaming frames —
    the cross-lane concurrency soak for the round-4 native data path."""
    from brpc_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")

    class StreamSink(rpc.StreamInputHandler):
        def __init__(self):
            self.nbytes = 0

        def on_received_messages(self, stream, messages):
            for m in messages:
                self.nbytes += len(m)

    sink = StreamSink()

    class StormService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message
            done()

        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def OpenStream(self, cntl, request, response, done):
            rpc.stream_accept(cntl, rpc.StreamOptions(handler=sink,
                                                      max_buf_size=8 << 20))
            response.message = "ok"
            done()

    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       native_builtin_echo=True))
    srv.add_service(StormService())
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port
    stop = threading.Event()
    errors_seen = []
    progress = {"std": 0, "http": 0, "grpc": 0, "stream": 0}

    def guard(fn, tag):
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001
                errors_seen.append(f"{tag}: {e!r}")
        return run

    def std_loop():
        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=10000))
        assert ch.init(f"127.0.0.1:{port}") == 0
        i = 0
        while not stop.is_set():
            cntl, resp = ch.call("StormService.Echo",
                                 echo_pb2.EchoRequest(message=f"s{i}"),
                                 echo_pb2.EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == f"s{i}"
            progress["std"] += 1
            i += 1
        ch.close()

    def http_loop():
        r = native.http_client_bench("127.0.0.1", port, nconn=1,
                                     pipeline=8, seconds=1.8,
                                     path="/echo", post_body=b"h" * 16)
        progress["http"] += r["requests"]

    def grpc_loop():
        from brpc_tpu.rpc.proto import echo_pb2 as _pb

        req = _pb.EchoRequest(message="g" * 16)
        r = native.grpc_client_bench("127.0.0.1", port, nconn=1,
                                     window=8, seconds=1.8,
                                     path="/StormService/Echo",
                                     payload=req.SerializeToString())
        progress["grpc"] += r["requests"]

    def stream_loop():
        ch = rpc.Channel()
        assert ch.init(f"127.0.0.1:{port}") == 0
        cntl = rpc.Controller()
        cntl.timeout_ms = 10000
        st = rpc.stream_create(cntl, rpc.StreamOptions(max_buf_size=8 << 20))
        resp = echo_pb2.EchoResponse()
        ch.call_method("StormService.OpenStream", cntl,
                       echo_pb2.EchoRequest(message="open"), resp)
        assert not cntl.failed(), cntl.error_text
        assert st.wait_connected(5)
        chunk = b"z" * 65536
        while not stop.is_set():
            assert st.write(chunk, timeout_s=10) == 0
            progress["stream"] += 1
        st.close()

    threads = [threading.Thread(target=guard(std_loop, "std")),
               threading.Thread(target=guard(http_loop, "http")),
               threading.Thread(target=guard(grpc_loop, "grpc")),
               threading.Thread(target=guard(stream_loop, "stream"))]
    for t in threads:
        t.start()
    time.sleep(2.0)
    stop.set()
    for t in threads:
        t.join(20)
    assert not errors_seen, errors_seen[:3]
    # every lane made real progress through the one port
    assert progress["std"] > 10, progress
    assert progress["http"] > 10, progress
    assert progress["grpc"] > 10, progress
    assert progress["stream"] > 2, progress
    assert sink.nbytes > 0
    srv.stop()
