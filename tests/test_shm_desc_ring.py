"""Descriptor-ring shm transport (nat_shm_lane.cpp): transport-level
tests for the zero-copy lane — ring wrap + payload integrity, arena
exhaustion backpressure, the record-size throughput sweep, and
worker-SIGKILL mid-record recovery through the robust lifetime fence.

(The end-to-end server tests — usercode across worker processes, crash
recovery under live HTTP/gRPC traffic, pipelined ordering — live in
tests/test_shm_workers.py.)
"""
import ctypes
import signal
import subprocess
import sys
import time

import pytest

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

lib = native.load()


def _fresh_lane(arena_bytes):
    # a previous test's lane in this process must be fully shut down
    # (shutdown + unlink) before a new segment can replace it
    lib.nat_shm_lane_enable(0)
    assert lib.nat_shm_lane_create(arena_bytes) == 0
    return lib.nat_shm_lane_name().decode()


def _spawn_drainer(name, idle_exit_ms=4000):
    """Worker subprocess that attaches and drains records natively."""
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import sys; sys.path.insert(0, '.')\n"
            "from brpc_tpu import native\n"
            "lib = native.load()\n"
            f"assert lib.nat_shm_worker_attach({name!r}.encode()) == 0\n"
            f"print(lib.nat_shm_worker_drain_bench({idle_exit_ms}),"
            " flush=True)\n")],
        stdout=subprocess.PIPE, text=True, cwd=".")
    deadline = time.time() + 30
    while lib.nat_shm_lane_workers() < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert lib.nat_shm_lane_workers() >= 1, "worker attach timed out"
    return child


def _take_payload(h):
    n = ctypes.c_size_t(0)
    p = lib.nat_req_field(h, 2, ctypes.byref(n))
    return ctypes.string_at(p, n.value) if p and n.value else b""


def test_ring_wrap_integrity():
    """300KB records through a 1MB arena: spans wrap the arena edge many
    times over; every payload must come back byte-identical (the wrap
    filler and reclaim cursor do their jobs)."""
    _fresh_lane(1 << 20)
    assert lib.nat_shm_worker_attach(
        lib.nat_shm_lane_name()) == 0  # same-process worker
    payload = bytes(range(256)) * 1200  # 300KB
    for i in range(100):
        assert lib.nat_shm_push_tensor(payload, len(payload), i) == 0, i
        h = lib.nat_shm_take_request(2000)
        assert h, f"record {i} not delivered"
        assert lib.nat_req_kind(h) == 8
        assert lib.nat_req_aux(h) == i
        assert _take_payload(h) == payload, f"record {i} corrupted"
        lib.nat_req_free(h)


def test_arena_exhaustion_backpressure():
    """Pushing without draining must fail cleanly once the blob arena is
    full (the backpressure bound), and succeed again after a drain frees
    spans — no wedge, no crash, no lost records."""
    _fresh_lane(1 << 20)
    assert lib.nat_shm_worker_attach(lib.nat_shm_lane_name()) == 0
    payload = b"x" * (300 << 10)
    pushed = 0
    while lib.nat_shm_push_tensor(payload, len(payload), pushed) == 0:
        pushed += 1
        assert pushed < 64, "arena never reported exhaustion"
    assert pushed >= 2  # ~3 x 300KB spans fit a 1MB arena
    drained = 0
    while True:
        h = lib.nat_shm_take_request(200)
        if not h:
            break
        lib.nat_req_free(h)
        drained += 1
    assert drained == pushed
    # space reclaimed: the lane accepts records again
    assert lib.nat_shm_push_tensor(payload, len(payload), 0) == 0
    h = lib.nat_shm_take_request(2000)
    assert h
    lib.nat_req_free(h)


def test_record_size_sweep_monotone_throughput():
    """Per-record overhead must not dominate: pushing bigger records
    through the two-process lane yields more bytes/s (with slack for CI
    noise). This is the regression guard on the descriptor lane's whole
    point — the old byte rings paid lock+copy+futex per record and fell
    off a cliff on small records."""
    name = _fresh_lane(8 << 20)
    child = _spawn_drainer(name)
    try:
        gbps = []
        for size in (4 << 10, 64 << 10, 1 << 20):
            r = native.shm_push_bench(size, 0.6)
            assert r["records"] > 0, f"no records moved at {size}B"
            gbps.append(r["GBps"])
        # monotone with 25% slack: strict monotonicity flakes on a noisy
        # 1-2 CPU CI host, a real per-record-overhead cliff does not
        assert gbps[1] >= gbps[0] * 0.75, gbps
        assert gbps[2] >= gbps[1] * 0.75, gbps
        assert gbps[2] > 0.05, gbps  # large records must move real bytes
    finally:
        lib.nat_shm_lane_enable(0)  # shutdown: the child drain loop exits
        child.wait(timeout=15)


def test_fabric_lease_out_of_order_release_and_wrap():
    """Tensor-fabric leases (ISSUE 15): the receiver holds record spans
    past the drain loop and releases OUT OF ORDER; a held lease pins the
    arena head (content stays intact while later records churn), and
    after release the ring wraps cleanly with byte-identical payloads.
    Leased payload bytes sit in the shm.span nat_res ledger row — the
    structural zero-copy witness (payload bytes accounted ONCE)."""
    _fresh_lane(1 << 20)
    name = lib.nat_shm_lane_name()
    assert lib.nat_shm_producer_attach(name) >= 0  # in-process producer

    def span_row():
        return {r["subsystem"]: r for r in native.res_stats()}["shm.span"]

    live0 = span_row()["live_bytes"]
    pat_a = bytes(range(256)) * 800   # 200KB
    pat_b = b"B" * (200 << 10)
    assert lib.nat_shm_fabric_push(pat_a, len(pat_a), 1) == 0
    assert lib.nat_shm_fabric_push(pat_b, len(pat_b), 2) == 0
    la = native.fabric_take(2000)
    lb = native.fabric_take(2000)
    assert la is not None and lb is not None
    assert la.tag == 1 and lb.tag == 2
    # both spans pinned: the ledger carries exactly the leased bytes
    assert span_row()["live_bytes"] - live0 == len(pat_a) + len(pat_b)
    # zero-copy: the lease view IS the arena span (no staging buffer)
    import numpy as np

    va = np.frombuffer(la.view(), dtype=np.uint8)
    assert va.ctypes.data == la._ptr
    lb.release()  # OUT OF ORDER: b released while a (earlier) is held
    # churn more records past the held lease: the arena head is pinned
    # at a, but tail space still serves pushes until exhaustion
    churned = 0
    for i in range(3, 10):
        if lib.nat_shm_fabric_push(pat_b, len(pat_b), i) != 0:
            break
        h = native.fabric_take(2000)
        assert h is not None
        h.release()
        churned += 1
    assert churned >= 1
    # the held span's content is untouched by the churn
    assert bytes(va[:1024]) == pat_a[:1024]
    assert va[-1] == pat_a[-1]
    la.release()
    # head unpinned: the ring now wraps the arena edge many times over
    for i in range(12):
        assert lib.nat_shm_fabric_push(pat_a, len(pat_a), 100 + i) == 0, i
        h = native.fabric_take(2000)
        assert h is not None and h.tag == 100 + i
        assert h.tobytes() == pat_a, f"wrap corrupted record {i}"
        h.release()
    assert span_row()["live_bytes"] == live0  # every lease retired


def test_producer_sigkill_lease_epoch_guard():
    """SIGKILL a PRODUCER process while the receiver holds one of its
    leases: the robust fence surfaces EOWNERDEAD, recovery waits the
    lease out (bounded), drops the untaken record (counted), and the
    slot serves a fresh producer. The stale lease's release after
    recovery is epoch-fenced — no scribble on the recycled arena."""
    _fresh_lane(1 << 20)
    name = lib.nat_shm_lane_name().decode()
    drops0 = native.stats_counters().get("nat_fabric_recover_drops", 0)
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import sys, time; sys.path.insert(0, '.')\n"
            "from brpc_tpu import native\n"
            "lib = native.load()\n"
            f"assert lib.nat_shm_producer_attach({name!r}.encode()) >= 0\n"
            "assert lib.nat_shm_fabric_push(b'x' * 100000, 100000, 1) == 0\n"
            "assert lib.nat_shm_fabric_push(b'y' * 100000, 100000, 2) == 0\n"
            "print('PUSHED', flush=True)\n"
            "time.sleep(60)\n")],
        stdout=subprocess.PIPE, text=True, cwd=".")
    assert child.stdout.readline().strip() == "PUSHED"
    lease = native.fabric_take(5000)
    assert lease is not None and lease.tag == 1
    child.send_signal(signal.SIGKILL)
    child.wait(timeout=10)
    # recovery: EOWNERDEAD on the probe; the held lease is waited out
    # (bounded 5s) and then epoch-fenced; record 2 is dropped + counted
    t0 = time.time()
    recovered = 0
    while recovered == 0 and time.time() - t0 < 20:
        recovered = lib.nat_shm_lane_recover_probe()
        if recovered == 0:
            time.sleep(0.1)
    assert recovered == 1, "dead producer's fence was not recovered"
    assert native.stats_counters()["nat_fabric_recover_drops"] \
        >= drops0 + 1
    lease.release()  # stale epoch: must be a harmless no-op
    # the freed slot serves a replacement producer end to end
    child2 = subprocess.Popen(
        [sys.executable, "-c", (
            "import sys; sys.path.insert(0, '.')\n"
            "from brpc_tpu import native\n"
            "lib = native.load()\n"
            f"assert lib.nat_shm_producer_attach({name!r}.encode()) >= 0\n"
            "assert lib.nat_shm_fabric_push(b'z' * 50000, 50000, 9) == 0\n"
            "print('OK', flush=True)\n")],
        stdout=subprocess.PIPE, text=True, cwd=".")
    assert child2.stdout.readline().strip() == "OK"
    child2.wait(timeout=10)
    fresh = native.fabric_take(5000)
    assert fresh is not None and fresh.tag == 9
    assert fresh.tobytes() == b"z" * 50000
    fresh.release()


def test_worker_sigkill_mid_record_recovery():
    """SIGKILL a worker that consumed a record but never released its
    span or answered: the robust lifetime fence must surface the death
    (EOWNERDEAD on the recovery probe), the slot must be scrubbed and
    reusable, and the lane must keep accepting + delivering records to a
    replacement worker."""
    name = _fresh_lane(1 << 20)
    child = subprocess.Popen(
        [sys.executable, "-c", (
            "import sys, time; sys.path.insert(0, '.')\n"
            "from brpc_tpu import native\n"
            "lib = native.load()\n"
            f"assert lib.nat_shm_worker_attach({name!r}.encode()) == 0\n"
            "h = lib.nat_shm_take_request(10000)\n"
            "assert h\n"
            "print('TOOK', flush=True)\n"
            "time.sleep(60)\n")],  # holds the span + fence until killed
        stdout=subprocess.PIPE, text=True, cwd=".")
    deadline = time.time() + 30
    while lib.nat_shm_lane_workers() < 1 and time.time() < deadline:
        time.sleep(0.05)
    assert lib.nat_shm_lane_workers() >= 1
    payload = b"y" * (200 << 10)
    assert lib.nat_shm_push_tensor(payload, len(payload), 7) == 0
    assert child.stdout.readline().strip() == "TOOK"
    # kill MID-RECORD: descriptor consumed, span held, nothing answered
    child.send_signal(signal.SIGKILL)
    child.wait(timeout=10)
    # fence probe sees EOWNERDEAD and recovers exactly one slot
    deadline = time.time() + 10
    recovered = 0
    while recovered == 0 and time.time() < deadline:
        recovered = lib.nat_shm_lane_recover_probe()
        if recovered == 0:
            time.sleep(0.1)
    assert recovered == 1, "dead worker's fence was not recovered"
    assert lib.nat_shm_lane_workers() == 0
    # the freed slot serves a replacement worker; the scrubbed arena
    # accepts and delivers fresh records end to end
    child2 = _spawn_drainer(name, idle_exit_ms=2000)
    try:
        pushed = 0
        for i in range(20):
            if lib.nat_shm_push_tensor(payload, len(payload), i) == 0:
                pushed += 1
            time.sleep(0.01)
        assert pushed >= 10, "lane wedged after recovery"
    finally:
        lib.nat_shm_lane_enable(0)
        drained = int(child2.stdout.readline().strip())
        child2.wait(timeout=15)
        assert drained >= pushed  # replacement worker saw every record
