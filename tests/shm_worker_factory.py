"""Service factory for the shm worker-process tests (imported by the
worker subprocesses as tests.shm_worker_factory:make)."""


def make():
    from brpc_tpu import rpc
    from brpc_tpu.rpc.proto import echo_pb2

    class EchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            import os

            response.message = f"{request.message}@{os.getpid()}"
            done()

    return [EchoService()]


def make_slow():
    """Echo that parks ~400ms per request — lets the crash tests SIGKILL
    a worker deterministically MID-RECORD (descriptor consumed from the
    ring, response not yet published), exercising the robust-fence
    recovery path rather than the idle-worker one."""
    from brpc_tpu import rpc
    from brpc_tpu.rpc.proto import echo_pb2

    class EchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            import os
            import time

            time.sleep(0.4)
            response.message = f"{request.message}@{os.getpid()}"
            done()

    return [EchoService()]
