"""Fuzz regression replay — every committed crasher must stay fixed.

native/fuzz/regress/<target>/ holds minimized inputs that once crashed
(or pathologically bloated) a wire parser. The fast test replays every
one through the plain .so via the ctypes-reachable nat_fuzz_* seams —
the production entry points the fuzzers drive — so a regression aborts
this process and the suite. The corpus seeds replay too: a seed the
parser can no longer digest means the corpus (or the parser) rotted.

The slow test runs the real bounded fuzz lane (build + budgeted run per
target, libFuzzer under clang++ or the bundled deterministic driver
under g++), skipping gracefully when no C++ toolchain is available.
"""
import os
import shutil
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import brpc_tpu.native as native  # noqa: E402

FUZZ_DIR = os.path.join(REPO, "native", "fuzz")

TARGETS = ("rpc_meta", "http", "h2", "redis", "hpack", "recordio",
           "shm_seg")

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native .so unavailable")


def _inputs(kind):
    """Yield (target, filename, bytes) under regress/ or corpus/."""
    root = os.path.join(FUZZ_DIR, kind)
    for target in TARGETS:
        d = os.path.join(root, target)
        if not os.path.isdir(d):
            continue
        for name in sorted(os.listdir(d)):
            p = os.path.join(d, name)
            if os.path.isfile(p):
                with open(p, "rb") as f:
                    yield target, name, f.read()


def test_regress_inputs_exist():
    found = list(_inputs("regress"))
    assert found, "native/fuzz/regress/ is empty — the fixed crashers " \
                  "must be committed"


def test_regress_replays_clean():
    lib = native.load()
    ran = 0
    for target, name, data in _inputs("regress"):
        fn = getattr(lib, "nat_fuzz_" + target)
        rc = fn(data, len(data))
        # surviving the call IS the gate (a regression dies in-process);
        # additionally every committed crasher documents a rejected
        # input, so the seam must report it rejected, not consumed
        assert rc == 0, f"regress/{target}/{name}: rc={rc} " \
                        f"(crasher now parses as valid?)"
        ran += 1
    assert ran >= 4


def test_corpus_seeds_replay():
    lib = native.load()
    ran = 0
    for target, name, data in _inputs("corpus"):
        fn = getattr(lib, "nat_fuzz_" + target)
        fn(data, len(data))  # survival is the assertion
        ran += 1
    assert ran >= len(TARGETS), "every target needs committed seeds"


def test_every_target_has_seeds():
    for target in TARGETS:
        d = os.path.join(FUZZ_DIR, "corpus", target)
        assert os.path.isdir(d) and os.listdir(d), \
            f"no corpus seeds for {target}"


@pytest.mark.slow
def test_bounded_fuzz_budget():
    if not (shutil.which("clang++") or shutil.which("g++")):
        pytest.skip("no C++ toolchain for the fuzz lane")
    from tools.natcheck import fuzzlane
    findings = fuzzlane.run(budget_ms=2000)
    assert findings == [], "\n".join(str(f) for f in findings)
