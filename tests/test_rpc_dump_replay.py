"""Traffic flight recorder (ISSUE 12): always-on native rpc_dump
capture + the native replay/press lane.

Covers the tentpole surfaces end to end — the sampled dump tap at the
native seams writing butil/recordio.py-compatible files (byte-identical
payloads under the Python reader), the native replay client re-firing
captures through the real client lanes (both interop directions: native
capture -> Python reader, Python rpc_dump files -> native replay, with
a through-the-wire byte-identity check), the /rpc_dump console page
with its one-window 503+Retry-After guard, and the nat_dump_* /
nat_replay_* counter surface. The two-process acceptance test (capture
<-> /rpcz correlation + replay against a restarted server) lives in
tests/test_replay_acceptance.py — it needs exclusive ownership of the
native server slot.
"""
import glob
import http.client
import os
import socket as pysock
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.butil import flags as flags_mod
from brpc_tpu.butil.recordio import RecordReader, RecordWriter

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=15)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    headers = {k.lower(): v for k, v in r.getheaders()}
    conn.close()
    return r.status, body, headers


@pytest.fixture(scope="module")
def server():
    """A native-runtime server carrying every tapped seam: tpu_std echo
    (native handler), native HTTP usercode (/echo), and the native
    redis store."""
    from brpc_tpu.rpc.redis import RedisService

    srv = rpc.Server(rpc.ServerOptions(num_threads=2,
                                       use_native_runtime=True,
                                       native_builtin_echo=True,
                                       redis_service=RedisService(),
                                       native_redis_store=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv, srv.listen_endpoint.port
    if native.dump_running():  # a failed test must not leak the window
        native.dump_stop()
    srv.stop()


def _read_all(capture_dir):
    out = []
    for path in sorted(glob.glob(os.path.join(capture_dir, "*.rio"))):
        with RecordReader(path) as reader:
            out.extend(reader)
    return out


def _wait_written(n, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if native.dump_status()["written"] >= n:
            return
        time.sleep(0.05)


# ---------------------------------------------------------------------------
# tentpole a: capture at the native seams, Python-readable files
# ---------------------------------------------------------------------------

def test_capture_all_seams_python_reader_byte_identity(server, tmp_path):
    """Native-written recordio is readable by the existing Python reader
    with BYTE-IDENTICAL payloads, and every tapped seam (tpu_std, native
    HTTP usercode, redis store) lands records carrying its lane + the
    wire trace context."""
    srv, port = server
    d = str(tmp_path / "cap")
    assert native.dump_start(d, every=1, seed=11) == 0
    assert native.dump_running()
    try:
        sent = []
        h = native.channel_open("127.0.0.1", port)
        for i in range(12):
            payload = (b"dump-%04d|" % i) * (1 + i % 4)
            with native.trace_scope(0xD0D0 + i, 0x7):
                code, body, text = native.channel_call(
                    h, "EchoService", "Echo", payload, timeout_ms=5000)
            assert code == 0, (code, text)
            assert body == payload
            sent.append(payload)
        native.channel_close(h)

        hh = native.channel_open_http("127.0.0.1", port)
        st, body = native.http_call(hh, "POST", "/echo", b"http-dump-body")
        assert st == 200 and body == b"http-dump-body"
        native.channel_close(hh)

        sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
        sk.sendall(b"*3\r\n$3\r\nSET\r\n$2\r\ndk\r\n$2\r\ndv\r\n")
        got = b""
        deadline = time.time() + 3
        while b"+OK" not in got and time.time() < deadline:
            got += sk.recv(4096)
        sk.close()

        _wait_written(len(sent) + 2)
    finally:
        native.dump_stop()

    records = _read_all(d)
    echo = [(m, p) for m, p in records if m["lane"] == "echo"]
    assert [p for _, p in echo] == sent  # byte identity, capture order
    for i, (m, _) in enumerate(echo):
        assert m["service"] == "EchoService" and m["method"] == "Echo"
        assert m["trace_id"] == 0xD0D0 + i  # cross-references /rpcz
        assert m["ts"] > 0
    http_recs = [(m, p) for m, p in records
                 if m["lane"] == "http" and m["method"] == "/echo"]
    assert http_recs and http_recs[0][0]["verb"] == "POST"
    assert http_recs[0][1] == b"http-dump-body"
    redis_recs = [(m, p) for m, p in records if m["lane"] == "redis"]
    assert redis_recs and redis_recs[0][0]["method"] == "SET"
    assert redis_recs[0][1] == b"*3\r\n$3\r\nSET\r\n$2\r\ndk\r\n$2\r\ndv\r\n"

    st = native.dump_status()
    assert st["written"] >= len(sent) + 2
    assert st["drops"] == 0


def test_capture_decimation_is_sampled(server, tmp_path):
    """every=N keeps roughly 1-in-N (seeded, deterministic): the tap is
    cheap enough to leave always-on because most requests never record."""
    srv, port = server
    d = str(tmp_path / "dec")
    assert native.dump_start(d, every=8, seed=3) == 0
    try:
        h = native.channel_open("127.0.0.1", port)
        for i in range(160):
            code, _, _ = native.channel_call(h, "EchoService", "Echo",
                                             b"x", timeout_ms=5000)
            assert code == 0
        native.channel_close(h)
        time.sleep(0.3)
    finally:
        native.dump_stop()
    st = native.dump_status()
    # binomial(160, 1/8): ~20 expected; the band is generous, the point
    # is "decimated, not all and not none"
    assert 2 <= st["samples"] <= 80, st


def test_oversize_payloads_skipped_whole(server, tmp_path):
    """A payload past max_payload is skipped WHOLE and counted — a
    truncated request is not replayable, so truncation is never an
    option."""
    srv, port = server
    d = str(tmp_path / "big")
    assert native.dump_start(d, every=1, seed=5, max_payload=1024) == 0
    try:
        h = native.channel_open("127.0.0.1", port)
        code, _, _ = native.channel_call(h, "EchoService", "Echo",
                                         b"B" * 4096, timeout_ms=5000)
        assert code == 0
        code, _, _ = native.channel_call(h, "EchoService", "Echo",
                                         b"small", timeout_ms=5000)
        assert code == 0
        native.channel_close(h)
        _wait_written(1)
    finally:
        native.dump_stop()
    st = native.dump_status()
    assert st["oversize"] == 1
    payloads = [p for _, p in _read_all(d)]
    assert b"small" in payloads
    assert all(len(p) <= 1024 for p in payloads)


def test_dump_start_contract(server, tmp_path):
    srv, port = server
    d = str(tmp_path / "c")
    assert native.dump_start(d, every=1) == 0
    try:
        assert native.dump_start(d, every=1) == -1  # double start loses
    finally:
        native.dump_stop()
    assert native.dump_stop() == 0  # idempotent
    assert native.dump_start("/proc/no-such-dir/x", every=1) == -2


# ---------------------------------------------------------------------------
# tentpole b: native replay — both interop directions
# ---------------------------------------------------------------------------

def test_python_rpc_dump_files_replay_natively_byte_identical(
        server, tmp_path):
    """Python rpc_dump files are replayable through the native replay
    client — proven through the wire: the server-side tap re-captures
    the replayed traffic and the payloads match the originals byte for
    byte."""
    from brpc_tpu.rpc import rpc_dump

    srv, port = server
    py_dir = str(tmp_path / "pydump")
    flags_mod.set_flag("rpc_dump", "true")
    flags_mod.set_flag("rpc_dump_dir", py_dir)
    flags_mod.set_flag("rpc_dump_sample_every", "1")
    originals = [(b"py-dump-%03d!" % i) * (1 + i % 3) for i in range(9)]
    try:
        for p in originals:
            rpc_dump.maybe_dump_request("EchoService.Echo", p)
    finally:
        rpc_dump.reset_for_tests()
        flags_mod.set_flag("rpc_dump", "false")
    assert glob.glob(py_dir + "/*.rio")

    recap_dir = str(tmp_path / "recap")
    assert native.dump_start(recap_dir, every=1, seed=2) == 0
    try:
        res = native.replay_run("127.0.0.1", port, py_dir, times=1,
                                concurrency=1, timeout_ms=5000)
        _wait_written(len(originals))
    finally:
        native.dump_stop()
    assert res["loaded"] == len(originals)
    assert res["failed"] == 0 and res["ok"] == len(originals)
    recaptured = [p for m, p in _read_all(recap_dir)
                  if m["lane"] == "echo"]
    # concurrency=1 preserves order; identity must hold byte for byte
    assert recaptured == originals


def test_native_capture_replayed_by_python_tool(server, tmp_path):
    """The OTHER interop direction: native-written capture files replay
    through the existing Python tools/rpc_replay.py (its tpu_std
    Channel) with zero failures."""
    import subprocess
    import sys

    srv, port = server
    d = str(tmp_path / "nat4py")
    assert native.dump_start(d, every=1, seed=13) == 0
    try:
        h = native.channel_open("127.0.0.1", port)
        for i in range(6):
            code, _, _ = native.channel_call(
                h, "EchoService", "Echo",
                echo_pb2.EchoRequest(
                    message=f"tool-{i}").SerializeToString(),
                timeout_ms=5000)
            assert code == 0
        native.channel_close(h)
        _wait_written(6)
    finally:
        native.dump_stop()

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "tools/rpc_replay.py", "--dir", d,
         "--server", f"127.0.0.1:{port}", "--timeout-ms", "5000"],
        capture_output=True, text=True, cwd=repo_root, env=env,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    assert "failed=0" in proc.stdout, proc.stdout
    assert "ok=6" in proc.stdout, proc.stdout


def test_native_replay_tool_entrypoint(server, tmp_path):
    """tools/rpc_replay.py --native drives nat_replay_run and reports
    quantiles + failure-derived exit code."""
    import subprocess
    import sys

    srv, port = server
    d = str(tmp_path / "toolnat")
    assert native.dump_start(d, every=1, seed=17) == 0
    try:
        h = native.channel_open("127.0.0.1", port)
        for _ in range(5):
            code, _, _ = native.channel_call(h, "EchoService", "Echo",
                                             b"tool-native",
                                             timeout_ms=5000)
            assert code == 0
        native.channel_close(h)
        _wait_written(5)
    finally:
        native.dump_stop()
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "tools/rpc_replay.py", "--dir", d,
         "--server", f"127.0.0.1:{port}", "--native", "--times", "2",
         "--concurrency", "2", "--timeout-ms", "5000"],
        capture_output=True, text=True, cwd=repo_root, env=env,
        timeout=120)
    assert proc.returncode == 0, (proc.stdout, proc.stderr)
    assert "ok=10 failed=0" in proc.stdout, proc.stdout
    assert "p99=" in proc.stdout


def test_replay_rate_throttle_and_ramp(server, tmp_path):
    """qps throttling paces the fire schedule; a ramp's average rate is
    the mean of its endpoints (the cumulative-count integral)."""
    srv, port = server
    d = str(tmp_path / "rate")
    assert native.dump_start(d, every=1, seed=23) == 0
    try:
        h = native.channel_open("127.0.0.1", port)
        for _ in range(30):
            code, _, _ = native.channel_call(h, "EchoService", "Echo",
                                             b"r", timeout_ms=5000)
            assert code == 0
        native.channel_close(h)
        _wait_written(30)
    finally:
        native.dump_stop()
    res = native.replay_run("127.0.0.1", port, d, times=1, qps=200.0,
                            concurrency=4)
    assert res["failed"] == 0
    # 30 records at 200 qps = ~0.15s of schedule
    assert 0.1 <= res["seconds"] <= 2.0, res
    ramp = native.replay_run("127.0.0.1", port, d, times=2, qps=100.0,
                             qps_to=300.0, concurrency=4)
    assert ramp["failed"] == 0
    # 60 fires at mean 200 qps = ~0.3s
    assert 0.2 <= ramp["seconds"] <= 3.0, ramp
    assert ramp["p99_us"] >= ramp["p50_us"] > 0


def test_replay_empty_capture_raises(server, tmp_path):
    srv, port = server
    with pytest.raises(ValueError):
        native.replay_run("127.0.0.1", port, str(tmp_path / "nothing"))


# ---------------------------------------------------------------------------
# tentpole c: /rpc_dump page + counters
# ---------------------------------------------------------------------------

def test_rpc_dump_page_status(server):
    srv, port = server
    status, body, _ = _get(port, "/rpc_dump")
    assert status == 200
    assert "traffic flight recorder" in body
    assert "native recorder:" in body
    assert "python lane: -rpc_dump=" in body


def test_rpc_dump_page_capture_window_and_503_guard(server, tmp_path):
    """ISSUE 12 satellite: /rpc_dump?seconds=N arms a bounded capture
    window behind the SAME one-window guard as /hotspots/* — the second
    concurrent request gets 503 with Retry-After derived from the
    RUNNING window's remaining time."""
    srv, port = server
    d = str(tmp_path / "page")
    results = {}

    def first():
        results["first"] = _get(port,
                                f"/rpc_dump?seconds=2.5&dir={d}&every=1")

    t = threading.Thread(target=first)
    t.start()
    deadline = time.time() + 5
    while not native.dump_running() and time.time() < deadline:
        time.sleep(0.02)
    assert native.dump_running(), "page window never armed the recorder"
    status, body, headers = _get(port, "/rpc_dump?seconds=0.1")
    t.join()
    assert results["first"][0] == 200
    assert "capture files" in results["first"][1]
    assert status == 503, (status, body)
    assert "busy" in body
    assert 2 <= int(headers["retry-after"]) <= 4
    assert not native.dump_running()
    # the page's own GET rode the native HTTP seam while armed: the
    # window captured its console traffic into the requested dir
    recs = _read_all(d)
    assert any(m["lane"] == "http" for m, _ in recs), recs


def test_dump_replay_counters_in_vars_and_metrics(server):
    """The nat_dump_* / nat_replay_* counters ride /vars and
    /brpc_metrics like every other native counter (the enum drift guard
    in test_native_stats.py covers the full set; here the live values
    prove the earlier tests' traffic landed in them)."""
    srv, port = server
    snap = native.stats_counters()
    for name in ("nat_dump_samples", "nat_dump_records_written",
                 "nat_dump_bytes_written", "nat_dump_drops",
                 "nat_dump_oversize", "nat_dump_rotations",
                 "nat_replay_calls", "nat_replay_errors"):
        assert name in snap, name
    assert snap["nat_dump_samples"] > 0
    assert snap["nat_dump_bytes_written"] > 0
    assert snap["nat_replay_calls"] > 0
    status, body, _ = _get(port, "/vars")
    assert status == 200
    assert "nat_dump_samples" in body
    assert "nat_replay_calls" in body
    status, body, _ = _get(port, "/brpc_metrics")
    assert status == 200
    assert "nat_dump_records_written" in body
    assert "nat_replay_errors" in body


def test_file_rotation_keeps_generations(server, tmp_path):
    """Files rotate past max_file_bytes and only `generations` newest
    stay on disk (the rpcz SpanDB rotation shape)."""
    srv, port = server
    d = str(tmp_path / "rot")
    # ~600B payloads against a 2KB rotation threshold: every few
    # records rolls a generation
    assert native.dump_start(d, every=1, seed=29, max_file_bytes=2048,
                             generations=2) == 0
    try:
        h = native.channel_open("127.0.0.1", port)
        for i in range(30):
            code, _, _ = native.channel_call(h, "EchoService", "Echo",
                                             b"R" * 600, timeout_ms=5000)
            assert code == 0
        native.channel_close(h)
        _wait_written(30)
    finally:
        native.dump_stop()
    st = native.dump_status()
    assert st["rotations"] >= 3, st
    files = sorted(glob.glob(d + "/*.rio"))
    assert 1 <= len(files) <= 2, files  # older generations unlinked
    # the surviving files still parse cleanly
    for m, p in _read_all(d):
        assert m["method"] == "Echo" and p == b"R" * 600
