"""bthread layer tests — shaped after the reference suite (SURVEY.md
section 4): real threads, real timing; ping-pong, stealing queue, butex,
execution queue, timer, bthread_id tests mirror
bthread_*_unittest.cpp shapes.
"""
import threading
import time

import pytest

from brpc_tpu import bthread
from brpc_tpu.bthread import bthread_id


def test_start_and_join():
    out = []
    tid = bthread.start_background(out.append, 42)
    assert bthread.bthread_join(tid, timeout=5)
    assert out == [42]


def test_many_tasks_all_run():
    n = 200
    counter = []
    lock = threading.Lock()

    def work(i):
        with lock:
            counter.append(i)

    tids = [bthread.start_background(work, i) for i in range(n)]
    for t in tids:
        assert bthread.bthread_join(t, timeout=10)
    assert sorted(counter) == list(range(n))


def test_urgent_runs():
    done = threading.Event()
    bthread.start_urgent(done.set)
    assert done.wait(5)


def test_ping_pong():
    """bthread_ping_pong_unittest shape: two tasks alternating via butex."""
    b1, b2 = bthread.Butex(0), bthread.Butex(0)
    rounds = 50
    trace = []

    def ping():
        for i in range(rounds):
            trace.append("ping")
            b2.value += 1
            b2.wake(1)
            b1.wait(i, timeout=5)

    def pong():
        for i in range(rounds):
            b2.wait(i, timeout=5)
            trace.append("pong")
            b1.value += 1
            b1.wake(1)

    t1 = bthread.start_background(ping)
    t2 = bthread.start_background(pong)
    assert bthread.bthread_join(t1, 10) and bthread.bthread_join(t2, 10)
    assert trace.count("ping") == rounds and trace.count("pong") == rounds


def test_work_stealing_queue():
    q = bthread.WorkStealingQueue()
    for i in range(10):
        assert q.push(i)
    assert q.pop() == 9  # owner LIFO
    assert q.steal() == 0  # thief FIFO
    assert len(q) == 8


def test_butex_wait_wake():
    b = bthread.Butex(0)
    woken = []

    def waiter():
        woken.append(b.wait(0, timeout=5))

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    b.value = 1
    assert b.wake(1) == 1
    t.join(5)
    assert woken == [True]


def test_butex_value_changed_no_block():
    b = bthread.Butex(7)
    t0 = time.monotonic()
    assert b.wait(3, timeout=5) is False  # EWOULDBLOCK
    assert time.monotonic() - t0 < 1


def test_butex_requeue():
    src, dst = bthread.Butex(0), bthread.Butex(0)
    results = []

    def waiter():
        results.append(src.wait(0, timeout=5) or dst.wait(0, timeout=5))

    ts = [threading.Thread(target=waiter) for _ in range(3)]
    for t in ts:
        t.start()
    time.sleep(0.1)
    src.requeue(dst)  # wakes 1, moves 2
    time.sleep(0.05)
    dst.wake_all()
    for t in ts:
        t.join(5)
    assert len(results) == 3


def test_mutex_mutual_exclusion():
    m = bthread.Mutex()
    counter = [0]

    def work():
        for _ in range(200):
            with m:
                counter[0] += 1

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert counter[0] == 800


def test_countdown_event():
    ev = bthread.CountdownEvent(3)
    for _ in range(3):
        bthread.start_background(ev.signal)
    assert ev.wait(5)


def test_timer_fires():
    fired = threading.Event()
    bthread.timer_add(0.05, fired.set)
    assert fired.wait(5)


def test_timer_unschedule():
    fired = []
    tid = bthread.timer_add(0.3, lambda: fired.append(1))
    assert bthread.timer_del(tid) == 0
    time.sleep(0.5)
    assert not fired


def test_timer_ordering():
    order = []
    bthread.timer_add(0.15, lambda: order.append(2))
    bthread.timer_add(0.05, lambda: order.append(1))
    time.sleep(0.4)
    assert order == [1, 2]


def test_execution_queue_serial_and_batched():
    seen = []

    def consume(it):
        batch = list(it)
        seen.append(batch)
        return 0

    q = bthread.execution_queue_start(consume)
    for i in range(50):
        q.execute(i)
    q.stop()
    assert q.join(5)
    flat = [x for b in seen for x in b]
    assert sorted(flat) == list(range(50))  # every task delivered once


def test_execution_queue_high_priority():
    seen = []
    gate = threading.Event()

    def consume(it):
        for x in it:
            if x == "wait":
                gate.wait(5)
            seen.append(x)
        return 0

    q = bthread.execution_queue_start(consume, batch_size=1)
    q.execute("wait")
    time.sleep(0.05)  # consumer now blocked inside first batch
    q.execute("normal")
    q.execute("urgent", high_priority=True)
    gate.set()
    q.stop()
    assert q.join(5)
    assert seen.index("urgent") < seen.index("normal")


def test_bthread_id_lifecycle():
    calls = []

    def on_error(idv, data, code, text):
        calls.append((data, code))
        bthread_id.unlock_and_destroy(idv)

    idv = bthread_id.create("payload", on_error)
    assert bthread_id.lock(idv) == "payload"
    bthread_id.unlock(idv)
    assert bthread_id.error(idv, 112)
    assert bthread_id.is_destroyed(idv)
    assert calls == [("payload", 112)]
    assert bthread_id.join(idv, 1)
    # stale id now rejected everywhere
    assert not bthread_id.error(idv, 1)
    with pytest.raises(KeyError):
        bthread_id.lock(idv)


def test_bthread_id_error_queued_while_locked():
    calls = []

    def on_error(idv, data, code, text):
        calls.append(code)
        bthread_id.unlock_and_destroy(idv)

    idv = bthread_id.create(None, on_error)
    bthread_id.lock(idv)
    assert bthread_id.error(idv, 7)  # queued, not yet delivered
    assert calls == []
    bthread_id.unlock(idv)  # delivers queued error under lock
    assert calls == [7]
    assert bthread_id.is_destroyed(idv)


def test_bthread_id_ranged_versions():
    idv = bthread_id.create_ranged("d", lambda i, d, c, t: bthread_id.unlock_and_destroy(i), 4)
    # id+1..+3 address the same slot (CallId+nretry trick)
    assert bthread_id.lock(idv + 2) == "d"
    bthread_id.unlock(idv + 2)
    bthread_id.lock(idv)
    bthread_id.unlock_and_destroy(idv)
    assert bthread_id.is_destroyed(idv + 3)


def test_bthread_id_join_blocks_until_destroy():
    idv = bthread_id.create()
    t0 = time.monotonic()

    def destroyer():
        time.sleep(0.1)
        bthread_id.lock(idv)
        bthread_id.unlock_and_destroy(idv)

    threading.Thread(target=destroyer).start()
    assert bthread_id.join(idv, 5)
    assert time.monotonic() - t0 >= 0.09


def test_idle_hook_runs():
    control = bthread.get_task_control()
    ran = threading.Event()

    def hook():
        ran.set()
        return False

    control.add_idle_hook(hook)
    try:
        assert ran.wait(5)
    finally:
        control.idle_hooks.remove(hook)


def test_bthread_local_keys():
    key = bthread.key_create()
    results = {}

    def work(name):
        bthread.setspecific(key, name)
        time.sleep(0.01)
        results[name] = bthread.getspecific(key)

    t1 = bthread.start_background(work, "a")
    t2 = bthread.start_background(work, "b")
    bthread.bthread_join(t1, 5)
    bthread.bthread_join(t2, 5)
    assert results == {"a": "a", "b": "b"}


def test_detached_tasks_are_reaped():
    """Fire-and-forget tasks must not accumulate TaskMetas (the per-request
    leak on the socket read path): after completion the registry shrinks
    back, without requiring join()."""
    import time

    from brpc_tpu.bthread.task_control import get_task_control

    tc = get_task_control()
    done = []
    before = len(tc._metas)
    for _ in range(500):
        tc.start_background(lambda: done.append(1))
    deadline = time.monotonic() + 10
    while len(done) < 500 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(done) == 500
    time.sleep(0.1)
    assert len(tc._metas) <= before + 4  # only still-running strangers remain
