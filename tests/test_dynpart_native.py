"""Native DynamicPartitionChannel (ISSUE 20): the `_dynpart` scheme
pick ported into nat_lb/nat_cluster.

Covers the elastic-capacity contracts: native-vs-Python equivalence
(same list + capacity -> same partition count and group assignment),
the whole-scheme capacity rule (one empty group zeroes the scheme),
resize publication as a new server-list version (nat_dynpart_resizes
bumps on layout change, NOT on a weight-only refresh), the
DynamicPartitionChannel(native=True) fast path, and the slow
resize-under-fault matrix (grow/shrink x SIGKILL/write:err storms,
zero failed RPCs once the bounded retry settles) that the chaos lane's
`resize` round replays with destructive seeds armed in the members."""
import os
import threading
import time

import pytest

from brpc_tpu import rpc  # noqa: F401 (protocol registry init)
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from brpc_tpu.rpc.native_cluster import NativeCluster  # noqa: E402


@pytest.fixture()
def swarm_server():
    """One native echo server on 8 ports (the multi-port swarm seam)."""
    port = native.rpc_server_start(native_echo=True)
    ports = [port]
    for _ in range(7):
        ports.append(native.rpc_server_add_port())
    yield ports
    native.rpc_server_stop()


def _tagged(ports, tags):
    return [(f"127.0.0.1:{p}", 1, t) for p, t in zip(ports, tags)]


# ---------------------------------------------------------------------------
# the verb + the pick
# ---------------------------------------------------------------------------

def test_dynpart_call_fans_the_chosen_scheme(swarm_server):
    """Scheme picked per call from the live totals; the fan covers every
    group of the chosen scheme exactly once (echo merge = one response
    per group), capacity-weighted so both schemes serve traffic."""
    with NativeCluster(lb="_dynpart") as c:
        c.update(_tagged(swarm_server[:3], ["0/1", "0/2", "1/2"]))
        seen = set()
        for i in range(30):
            rc, body, err, failed, scheme = c.dynpart_call(
                "EchoService.Echo", b"D", timeout_ms=3000)
            assert rc == 0, err
            assert failed == 0
            assert scheme in (1, 2)
            assert body == b"D" * scheme  # one sub-response per group
            seen.add(scheme)
        # capacity 1 vs 2: over 30 weighted picks both schemes serve
        assert seen == {1, 2}


def test_dynpart_pick_matches_python_lb(swarm_server, monkeypatch):
    """Equivalence probe: the native pick at a fixed point x01 chooses
    the same partition count the Python DynPartLB does for the same
    scheme/capacity table (same ascending walk, same x <= acc rule)."""
    from brpc_tpu.rpc import load_balancer as lb_mod

    tags = ["0/1", "0/1",              # scheme 1: one group of 2
            "0/2", "1/2",              # scheme 2: two groups of 1
            "0/4", "1/4", "2/4", "3/4"]  # scheme 4: four groups of 1
    with NativeCluster(lb="_dynpart") as c:
        c.update(_tagged(swarm_server, tags))
        dbg = c.dynpart_debug(0.0)
        assert dbg["schemes"] == [(1, 2), (2, 2), (4, 4)]

        pylb = lb_mod.create_load_balancer("_dynpart")
        caps = dict(dbg["schemes"])
        for total in sorted(caps):
            pylb.add_server(total)
        pylb.set_capacity_fn(lambda sid: caps[sid])

        point = [0.0]
        monkeypatch.setattr(lb_mod.random, "uniform",
                            lambda a, b: point[0] * b)
        for i in range(97):
            point[0] = i / 97.0
            want = pylb.select_server()
            got = c.dynpart_debug(point[0])["chosen"]
            assert got == want, f"x01={point[0]}: native {got} != py {want}"


def test_dynpart_group_assignment_matches_python_channel(swarm_server):
    """Same list -> same group assignment: the per-scheme capacity the
    native cluster derives from the tag grammar equals what the Python
    DynamicPartitionChannel's sub-channels count for the same feed."""
    import tempfile

    from brpc_tpu.rpc.combo_channels import DynamicPartitionChannel

    tags = ["0/1", "0/1", "0/2", "1/2", "0/3", "1/3", "2/3"]
    ports = swarm_server[:len(tags)]
    with tempfile.NamedTemporaryFile("w", suffix=".ns",
                                     delete=False) as f:
        for p, t in zip(ports, tags):
            f.write(f"127.0.0.1:{p} {t}\n")
        naming = f.name
    try:
        with NativeCluster(lb="_dynpart") as c:
            c.watch(f"file://{naming}")
            dbg = c.dynpart_debug(0.0)
            assert dbg["schemes"] == [(1, 2), (2, 2), (3, 3)]
            pc = DynamicPartitionChannel()
            assert pc.init(f"file://{naming}") == 0
            for total, cap in dbg["schemes"]:
                assert pc._scheme_capacity(total) == cap, total
    finally:
        os.unlink(naming)


def test_dynpart_empty_group_zeroes_the_scheme(swarm_server):
    """The whole-scheme capacity rule: a scheme with ANY unpopulated
    group reports capacity 0 and is never picked (it could not answer
    for every partition), leaving the complete scheme to serve."""
    with NativeCluster(lb="_dynpart") as c:
        c.update(_tagged(swarm_server[:2], ["0/1", "0/2"]))  # no 1/2
        dbg = c.dynpart_debug(0.99)
        assert (2, 0) in dbg["schemes"]
        assert (1, 1) in dbg["schemes"]
        assert dbg["chosen"] == 1
        for _ in range(8):
            rc, body, err, failed, scheme = c.dynpart_call(
                "EchoService.Echo", b"z", timeout_ms=2000)
            assert rc == 0 and scheme == 1, err


def test_dynpart_no_capacity_fails_fast(swarm_server):
    """No scheme with capacity: the verb must answer promptly with a
    clear error, not hang an empty fan."""
    with NativeCluster(lb="_dynpart") as c:
        c.update(_tagged(swarm_server[:1], ["0/2"]))  # incomplete only
        t0 = time.time()
        rc, _, err, failed, scheme = c.dynpart_call(
            "EchoService.Echo", b"x", timeout_ms=2000)
        assert rc != 0 and "capacity" in err
        assert scheme == 0
        assert time.time() - t0 < 1.0


def test_dynpart_resize_counter_tracks_layout_changes(swarm_server):
    """nat_dynpart_resizes bumps when a publish CHANGES the partition
    layout; a weight-only refresh publishes a new version without being
    a resize."""
    def resizes():
        return native.stats_counters().get("nat_dynpart_resizes", 0)

    with NativeCluster(lb="_dynpart") as c:
        c.update(_tagged(swarm_server[:2], ["0/1", "0/1"]))
        base = resizes()
        # weight-only refresh: same layout, new weights -> not a resize
        c.update([(f"127.0.0.1:{p}", 5, "0/1")
                  for p in swarm_server[:2]])
        assert resizes() == base
        # layout change: the elastic scheme appears -> a resize
        c.update(_tagged(swarm_server[:4], ["0/1", "0/1", "0/2", "1/2"]))
        assert resizes() == base + 1
        # and shrinking back is another
        c.update(_tagged(swarm_server[:2], ["0/1", "0/1"]))
        assert resizes() == base + 2


def test_dynamic_partition_channel_native_fast_path(swarm_server,
                                                    tmp_path):
    from brpc_tpu.rpc.combo_channels import (DynamicPartitionChannel,
                                             PartitionParser)

    nf = tmp_path / "dynparts.ns"
    nf.write_text(f"127.0.0.1:{swarm_server[0]} 0/1\n"
                  f"127.0.0.1:{swarm_server[1]} 0/2\n"
                  f"127.0.0.1:{swarm_server[2]} 1/2\n")
    dpc = DynamicPartitionChannel(native=True)
    assert dpc.init(f"file://{nf}") == 0
    try:
        for i in range(6):
            cntl = rpc.Controller()
            cntl.timeout_ms = 3000
            resp = echo_pb2.EchoResponse()
            dpc.call_method("EchoService.Echo", cntl,
                            echo_pb2.EchoRequest(message="dyn"), resp)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == "dyn"
            assert cntl.partition_count in (1, 2)
    finally:
        dpc.stop()
    # the C++ core speaks the default "i/n" grammar only: a custom
    # parser must be refused loudly, not silently misgrouped

    class _HexParser(PartitionParser):
        pass

    with pytest.raises(ValueError):
        DynamicPartitionChannel(native=True).init(
            f"file://{nf}", parser=_HexParser())


# ---------------------------------------------------------------------------
# resize-under-fault matrix (slow): grow/shrink x SIGKILL/write-error
# storms, zero failed RPCs once the bounded retry settles. The chaos
# lane's `resize` round re-runs exactly these with CHURN_SPEC armed.
# ---------------------------------------------------------------------------

_RESIZE_BASE_PORT = {
    ("grow", "sigkill"): 27200,
    ("grow", "write_err"): 27260,
    ("shrink", "sigkill"): 27320,
    ("shrink", "write_err"): 27380,
}


@pytest.mark.slow
@pytest.mark.parametrize("fault", ["sigkill", "write_err"])
@pytest.mark.parametrize("op", ["grow", "shrink"])
def test_resize_under_fault_zero_failed(op, fault, tmp_path):
    """A dynpart resize is never caller-visible: a client flood rides
    through a live grow/shrink with a destructive fault landing
    mid-resize (SIGKILL of the freshest member, or EPIPE storms in
    every member), and zero calls fail once the bounded retry (the
    fanout swarm drill's idiom) settles."""
    from brpc_tpu.fleet.autoscaler import SwarmPool

    env = dict(os.environ)
    env.pop("NAT_FAULT", None)  # the CLIENT side stays clean
    if fault == "write_err":
        env["BRPC_TPU_CHURN_FAULT"] = "seed=42;write:err=EPIPE:p=0.002"
    else:
        env.pop("BRPC_TPU_CHURN_FAULT", None)

    naming = str(tmp_path / "resize.ns")
    holder = []

    def republish():
        if holder:
            holder[0].refresh()

    resizes0 = native.stats_counters().get("nat_dynpart_resizes", 0)
    pool = SwarmPool(naming, base_port=_RESIZE_BASE_PORT[(op, fault)],
                     publish_cb=republish, env=env)
    cluster = None
    stop = threading.Event()
    calls, failed = [0], []

    def flood():
        while not stop.is_set():
            rc, err = 1, ""
            for _ in range(3):  # bounded retry: a re-pick moves the
                rc, _b, err, _n, _s = cluster.dynpart_call(  # rr cursor
                    "EchoService.Echo", b"rz", timeout_ms=3000)
                if rc == 0:
                    break
            calls[0] += 1
            if rc != 0:
                failed.append((rc, err))
            time.sleep(0.005)

    try:
        # anchor "0/1" x2 + elastic "0/2","1/2"
        assert pool.grow(4) == 4, "swarm spawn failed"
        cluster = NativeCluster(lb="_dynpart", connect_timeout_ms=1000,
                                health_check_ms=100,
                                name=f"resize-{op}-{fault}")
        holder.append(cluster.watch(f"file://{naming}"))
        t = threading.Thread(target=flood, daemon=True)
        t.start()
        time.sleep(0.8)  # flood settles: connections dialed

        if op == "grow":
            assert pool.grow(2) == 2  # elastic resizes 2-way -> 4-way
        else:
            assert pool.shrink(1) == 1  # collapses to one "0/1" of 3
        if fault == "sigkill":
            # the crash lands right on the heels of the resize, on the
            # freshest member, and is never announced to the feed
            assert pool.kill_one() is not None
            time.sleep(1.0)  # cool-down routes around the corpse
            pool.publish()  # then the feed catches up (autoscaler role)
        time.sleep(1.5)

        stop.set()
        t.join(timeout=10)
        assert not failed, f"{len(failed)} failed: {failed[:5]}"
        assert calls[0] > 100, calls[0]
        assert native.stats_counters().get("nat_dynpart_resizes", 0) \
            > resizes0
    finally:
        stop.set()
        if cluster is not None:
            cluster.close()
        pool.close()
