"""Device/tensor transport tests — the RDMA-subsystem test role
(brpc_rdma_unittest.cpp shape, SURVEY.md section 4): handshake state
machine, pool accounting, push/pull roundtrips with numerical equality,
zero-copy same-process path, retention-until-ACK.
"""
import numpy as np
import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import device_transport as dt
from brpc_tpu.rpc.proto import rpc_meta_pb2
from brpc_tpu.rpc.tensor_service import (
    TensorClient,
    TensorStoreService,
    make_device_channel,
)


@pytest.fixture(scope="module")
def store_server():
    svc = TensorStoreService()
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(svc)
    assert srv.start("127.0.0.1:0") == 0
    yield srv, svc
    srv.stop()


def test_local_device_info():
    info = dt.local_device_info()
    assert info["device_count"] >= 1
    assert info["platform"] in ("cpu", "tpu")


def test_block_pool_acquire_release():
    pool = dt.DeviceBlockPool(blocks_per_class=2)
    stats0 = pool.stats()
    assert all(v == 2 for v in stats0.values())
    got = pool.acquire(10_000)  # → 64KB class
    assert got is not None
    size, buf = got
    assert size == 64 << 10
    assert pool.stats()[size] == 1
    pool.release(size, buf)
    assert pool.stats()[size] == 2
    assert pool.acquire(10 << 20) is None  # above the largest class


def test_endpoint_prepare_and_receive_wire():
    ep = dt.DeviceEndpoint()
    ep.state = dt.FALLBACK_TCP
    from brpc_tpu.butil.iobuf import IOBuf

    meta = rpc_meta_pb2.RpcMeta()
    att = IOBuf()
    arrays = [np.arange(12, dtype=np.float32).reshape(3, 4),
              np.ones((2, 2), dtype=np.int32)]
    assert ep.prepare_send(arrays, meta, att)
    assert len(meta.tensors) == 2
    assert ep.inflight_bytes == sum(a.nbytes for a in arrays)
    assert ep.retained_count == 1
    out, seq = dt.receive_tensors(meta, att)
    np.testing.assert_array_equal(out[0], arrays[0])
    np.testing.assert_array_equal(out[1], arrays[1])
    ep.on_ack(seq)
    assert ep.inflight_bytes == 0
    assert ep.retained_count == 0


def test_endpoint_window_blocks():
    ep = dt.DeviceEndpoint(window_bytes=100)
    ep.state = dt.FALLBACK_TCP
    from brpc_tpu.butil.iobuf import IOBuf

    meta = rpc_meta_pb2.RpcMeta()
    big = np.zeros(200, dtype=np.uint8)
    assert not ep.prepare_send([big], meta, IOBuf(), timeout_s=0.05)


def test_push_pull_roundtrip(store_server):
    srv, svc = store_server
    ch = make_device_channel(str(srv.listen_endpoint))
    assert ch is not None
    client = TensorClient(ch)
    arrays = [np.random.default_rng(0).normal(size=(8, 16)).astype(np.float32)]
    cntl, resp = client.push("w0", arrays)
    assert not cntl.failed(), cntl.error_text
    assert resp.ok
    stored = svc.get("w0")
    assert stored is not None
    np.testing.assert_allclose(np.asarray(stored[0]), arrays[0])
    cntl2, pulled = client.pull("w0")
    assert not cntl2.failed(), cntl2.error_text
    np.testing.assert_allclose(np.asarray(pulled[0]), arrays[0])


def test_pull_missing(store_server):
    srv, _ = store_server
    ch = make_device_channel(str(srv.listen_endpoint))
    client = TensorClient(ch)
    cntl, arrays = client.pull("no-such-tensor")
    assert not cntl.failed()
    assert arrays is None


def test_handshake_establishes(store_server):
    """The device handshake upgrades the connection: client endpoint must
    be ESTABLISHED (both sides have jax devices) and see the peer."""
    srv, _ = store_server
    ch = make_device_channel(str(srv.listen_endpoint))
    client = TensorClient(ch)
    cntl, _ = client.push("hs", [np.ones(4, np.float32)])
    assert not cntl.failed(), cntl.error_text
    sock = cntl._current_sock
    ep = sock.app_state
    assert isinstance(ep, dt.DeviceEndpoint)
    assert ep.state == dt.ESTABLISHED
    assert ep.peer_info["device_count"] >= 1


def test_same_process_zero_copy(store_server):
    """In-process transfer passes the SAME array object through (the
    loopback-ICI path)."""
    srv, svc = store_server
    ch = make_device_channel(str(srv.listen_endpoint))
    client = TensorClient(ch)
    import jax.numpy as jnp

    arr = jnp.arange(32, dtype=jnp.float32)
    cntl, resp = client.push("zc", [arr])
    assert not cntl.failed(), cntl.error_text
    stored = svc.get("zc")
    assert stored[0] is arr  # identity: no copy was made


def test_device_jax_array_roundtrip(store_server):
    srv, svc = store_server
    ch = make_device_channel(str(srv.listen_endpoint))
    client = TensorClient(ch)
    import jax.numpy as jnp

    arr = jnp.linspace(0, 1, 64, dtype=jnp.float32).reshape(8, 8)
    cntl, _ = client.push("jx", [arr])
    assert not cntl.failed(), cntl.error_text
    cntl2, pulled = client.pull("jx")
    assert not cntl2.failed()
    np.testing.assert_allclose(np.asarray(pulled[0]), np.asarray(arr))


def test_block_pool_put_via_pool_roundtrip():
    """Transfer bytes must land in pooled HBM (donating fill) and come
    back out as the right typed array; pool counters show the traffic."""
    import jax

    pool = dt.DeviceBlockPool(blocks_per_class=2)
    src = np.arange(640, dtype=np.float32).reshape(16, 40)
    raw = np.frombuffer(src.tobytes(), dtype=np.uint8)
    before = pool.stats()
    arr = pool.put_via_pool(raw, np.float32, (16, 40),
                            jax.devices()[0])
    np.testing.assert_array_equal(np.asarray(arr), src)
    # every block is back home after the put
    assert pool.stats() == before
    # int8 path (itemsize 1, no bitcast)
    src8 = np.arange(100, dtype=np.uint8)
    arr8 = pool.put_via_pool(src8.copy(), np.uint8, (100,),
                             jax.devices()[0])
    np.testing.assert_array_equal(np.asarray(arr8), src8)
    # oversized falls back to a plain device_put
    big = np.zeros(4 << 20, dtype=np.uint8)
    arr_big = pool.put_via_pool(big, np.uint8, (4 << 20,),
                                jax.devices()[0])
    assert arr_big.shape == (4 << 20,)
