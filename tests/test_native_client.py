"""Native CLIENT lanes (nat_client.cpp): HTTP/1.1 and h2/gRPC request
framing + response parsing in C++, riding the NatChannel pending-call
table.

Parity targets: the reference's client halves of
policy/http_rpc_protocol.cpp:663 (PackHttpRequest) and
policy/http2_rpc_protocol.h:133,285 (H2UnsentRequest/PackH2Request).
Interop oracle: a stock grpcio SERVER must answer the native h2 client,
including multi-MB payloads through real flow control.
"""
import threading

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def native_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_grpc_client_vs_native_server(native_server):
    port = native_server.listen_endpoint.port
    h = native.channel_open_grpc("127.0.0.1", port)
    try:
        req = echo_pb2.EchoRequest(message="native-h2-client")
        st, resp, msg = native.grpc_call(h, "/EchoService/Echo",
                                         req.SerializeToString(),
                                         timeout_ms=5000)
        assert st == 0
        assert echo_pb2.EchoResponse.FromString(resp).message == \
            "native-h2-client"
    finally:
        native.channel_close(h)


def test_grpc_client_flow_control_big_payload(native_server):
    port = native_server.listen_endpoint.port
    h = native.channel_open_grpc("127.0.0.1", port)
    try:
        big = echo_pb2.EchoRequest(message="B" * 524288)
        st, resp, msg = native.grpc_call(h, "/EchoService/Echo",
                                         big.SerializeToString(),
                                         timeout_ms=30000)
        assert st == 0, (st, msg)
        assert len(echo_pb2.EchoResponse.FromString(resp).message) == 524288
    finally:
        native.channel_close(h)


def test_grpc_client_unimplemented_status(native_server):
    port = native_server.listen_endpoint.port
    h = native.channel_open_grpc("127.0.0.1", port)
    try:
        st, resp, msg = native.grpc_call(h, "/NoSuch/Method", b"",
                                         timeout_ms=5000)
        # our py lane maps no-such-method to NOT_FOUND(5); a pure-native
        # port answers UNIMPLEMENTED(12) — either way a clean gRPC error
        assert st in (5, 12)
    finally:
        native.channel_close(h)


def test_grpc_client_concurrent_streams(native_server):
    """Interleaved unary streams on ONE h2 connection: per-sid
    correlation must route every response to its own call."""
    port = native_server.listen_endpoint.port
    h = native.channel_open_grpc("127.0.0.1", port)
    errors = []

    def worker(i):
        for j in range(20):
            m = f"w{i}-{j}" * 5
            req = echo_pb2.EchoRequest(message=m)
            st, resp, _ = native.grpc_call(h, "/EchoService/Echo",
                                           req.SerializeToString(),
                                           timeout_ms=10000)
            got = echo_pb2.EchoResponse.FromString(resp).message
            if st != 0 or got != m:
                errors.append((i, j, st, got))
                return

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
    finally:
        native.channel_close(h)


def test_http_client_vs_native_server(native_server):
    port = native_server.listen_endpoint.port
    h = native.channel_open_http("127.0.0.1", port)
    try:
        status, body = native.http_call(h, "GET", "/health",
                                        timeout_ms=5000)
        assert status == 200 and body == b"OK\n"
        status, body = native.http_call(
            h, "POST", "/EchoService/Echo",
            body=b'{"message": "http-cli"}',
            headers="Content-Type: application/json\r\n",
            timeout_ms=5000)
        assert status == 200 and b"http-cli" in body
        status, body = native.http_call(h, "GET", "/no/such/page",
                                        timeout_ms=5000)
        assert status == 404
    finally:
        native.channel_close(h)


def test_http_client_head_does_not_desync(native_server):
    """A HEAD response carries Content-Length but NO body; the pipeline
    must not consume the next response as the HEAD's body."""
    port = native_server.listen_endpoint.port
    h = native.channel_open_http("127.0.0.1", port)
    try:
        status, body = native.http_call(h, "HEAD", "/health",
                                        timeout_ms=5000)
        assert status == 200 and body == b""
        # the very next response on the same connection must be intact
        status, body = native.http_call(h, "GET", "/health",
                                        timeout_ms=5000)
        assert status == 200 and body == b"OK\n"
    finally:
        native.channel_close(h)


def test_grpc_client_timeout_then_recover(native_server):
    """Timed-out calls must not wedge the h2 session: late responses are
    dropped via the pending-call CAS and their stream state is swept."""
    port = native_server.listen_endpoint.port
    h = native.channel_open_grpc("127.0.0.1", port)
    try:
        timed_out = 0
        for _ in range(20):
            try:
                native.grpc_call(h, "/EchoService/Echo",
                                 echo_pb2.EchoRequest(
                                     message="t").SerializeToString(),
                                 timeout_ms=1)
            except ConnectionError:
                timed_out += 1
        # the channel must still answer normal calls afterwards
        st, resp, _ = native.grpc_call(
            h, "/EchoService/Echo",
            echo_pb2.EchoRequest(message="after").SerializeToString(),
            timeout_ms=10000)
        assert st == 0
        assert echo_pb2.EchoResponse.FromString(resp).message == "after"
    finally:
        native.channel_close(h)


def test_http_client_pipelined_correlation(native_server):
    """Many threads on one keep-alive connection: FIFO correlation must
    hand every response to the right caller."""
    port = native_server.listen_endpoint.port
    h = native.channel_open_http("127.0.0.1", port)
    errors = []

    def worker(i):
        for j in range(20):
            m = f"p{i}-{j}"
            status, body = native.http_call(
                h, "POST", "/EchoService/Echo",
                body=('{"message": "%s"}' % m).encode(),
                headers="Content-Type: application/json\r\n",
                timeout_ms=10000)
            if status != 200 or m.encode() not in body:
                errors.append((i, j, status, body[:64]))
                return

    try:
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors[:3]
    finally:
        native.channel_close(h)


def test_grpc_client_vs_stock_grpcio_server():
    """THE interop oracle: our native h2 client against a stock grpcio
    server — small echo, 4MB flow-controlled echo, error status."""
    grpc = pytest.importorskip("grpc")
    from concurrent import futures

    class Handler(grpc.GenericRpcHandler):
        def service(self, details):
            if details.method == "/EchoService/Echo":
                def echo(req, ctx):
                    return echo_pb2.EchoResponse(message=req.message)
                return grpc.unary_unary_rpc_method_handler(
                    echo,
                    request_deserializer=echo_pb2.EchoRequest.FromString,
                    response_serializer=lambda m: m.SerializeToString())
            return None

    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=4),
        options=[("grpc.max_receive_message_length", 32 << 20),
                 ("grpc.max_send_message_length", 32 << 20)])
    server.add_generic_rpc_handlers((Handler(),))
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    h = native.channel_open_grpc("127.0.0.1", port)
    try:
        st, resp, msg = native.grpc_call(
            h, "/EchoService/Echo",
            echo_pb2.EchoRequest(message="interop").SerializeToString(),
            timeout_ms=10000)
        assert st == 0
        assert echo_pb2.EchoResponse.FromString(resp).message == "interop"

        big = echo_pb2.EchoRequest(message="G" * (4 << 20))
        st, resp, msg = native.grpc_call(h, "/EchoService/Echo",
                                         big.SerializeToString(),
                                         timeout_ms=60000)
        assert st == 0, (st, msg)
        assert len(echo_pb2.EchoResponse.FromString(resp).message) == \
            (4 << 20)

        st, resp, msg = native.grpc_call(h, "/NoSuch/Method", b"",
                                         timeout_ms=10000)
        assert st == 12 and "not found" in msg.lower()
    finally:
        native.channel_close(h)
        server.stop(0)


def test_http_client_vs_stdlib_http_server():
    """Native HTTP client against python's stdlib HTTPServer."""
    import http.server

    class H(http.server.BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def do_GET(self):
            body = f"path={self.path}".encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):
            n = int(self.headers.get("Content-Length", "0"))
            body = self.rfile.read(n)
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), H)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    h = native.channel_open_http("127.0.0.1", srv.server_port)
    try:
        status, body = native.http_call(h, "GET", "/hello", timeout_ms=5000)
        assert status == 200 and body == b"path=/hello"
        blob = b"z" * 100000
        status, body = native.http_call(h, "POST", "/up", body=blob,
                                        timeout_ms=10000)
        assert status == 200 and body == blob
    finally:
        native.channel_close(h)
        srv.shutdown()


def test_grpc_client_timeout():
    """A dead peer must surface ERPCTIMEDOUT through the native deadline,
    not hang."""
    import socket as pysock

    lst = pysock.socket()
    lst.bind(("127.0.0.1", 0))
    lst.listen(1)  # accepts but never answers
    port = lst.getsockname()[1]
    h = native.channel_open_grpc("127.0.0.1", port)
    try:
        with pytest.raises(ConnectionError):
            native.grpc_call(h, "/EchoService/Echo", b"x",
                             timeout_ms=300)
    finally:
        native.channel_close(h)
        lst.close()


def test_client_lane_bench_smoke(native_server):
    """The BENCH_r05 client rows' machinery must run: a short window of
    async calls through both client lanes."""
    port = native_server.listen_endpoint.port
    payload = echo_pb2.EchoRequest(message="x" * 16).SerializeToString()
    r = native.grpc_channel_bench("127.0.0.1", port, nconn=1, window=32,
                                  seconds=0.5, payload=payload)
    assert r["requests"] > 100, r
    r2 = native.http_channel_bench("127.0.0.1", port, nconn=1, window=32,
                                   seconds=0.5, path="/EchoService/Echo",
                                   body=b'{"message": "b"}')
    assert r2["requests"] > 100, r2


def _one_shot_http_server(response_bytes, close_after=True):
    """Raw-socket HTTP server: accepts one connection, reads the request
    head, writes `response_bytes`, then closes (or lingers)."""
    import socket as pysock

    lsock = pysock.socket()
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(1)
    port = lsock.getsockname()[1]

    def serve():
        conn, _ = lsock.accept()
        try:
            buf = b""
            while b"\r\n\r\n" not in buf:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
            conn.sendall(response_bytes)
            if close_after:
                conn.shutdown(pysock.SHUT_WR)
                # linger until the client saw EOF and hung up
                conn.settimeout(5)
                try:
                    while conn.recv(4096):
                        pass
                except OSError:
                    pass
        finally:
            conn.close()
            lsock.close()

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    return port, t


def test_http_client_read_until_close_body():
    """A response with no Content-Length and no chunked framing but
    Connection: close is CLOSE-DELIMITED (ADVICE r5): the client must
    accumulate until EOF and complete with the full body — not report a
    silent empty 200."""
    body = b"close-delimited " * 700  # ~11KB, several read rounds
    port, t = _one_shot_http_server(
        b"HTTP/1.1 200 OK\r\nConnection: close\r\n\r\n" + body)
    h = native.channel_open_http("127.0.0.1", port)
    try:
        status, out = native.http_call(h, "GET", "/blob", timeout_ms=5000)
        assert status == 200
        assert out == body
    finally:
        native.channel_close(h)
        t.join(timeout=5)


def test_http_client_read_until_close_http10():
    """HTTP/1.0 with no framing headers defaults to close-delimited."""
    body = b"ten-dot-zero body"
    port, t = _one_shot_http_server(b"HTTP/1.0 200 OK\r\n\r\n" + body)
    h = native.channel_open_http("127.0.0.1", port)
    try:
        status, out = native.http_call(h, "GET", "/", timeout_ms=5000)
        assert status == 200
        assert out == body
    finally:
        native.channel_close(h)
        t.join(timeout=5)


def test_http_client_unframed_keepalive_fails_explicitly():
    """A keep-alive response with NO framing at all is undecodable: the
    call must fail explicitly (failed socket), never complete with wrong
    (empty) data — the ADVICE r5 'silently empty body' half."""
    port, t = _one_shot_http_server(
        b"HTTP/1.1 200 OK\r\nConnection: keep-alive\r\n\r\nstealth-body",
        close_after=False)
    h = native.channel_open_http("127.0.0.1", port)
    try:
        with pytest.raises(ConnectionError):
            native.http_call(h, "GET", "/", timeout_ms=5000)
    finally:
        native.channel_close(h)
        t.join(timeout=5)
