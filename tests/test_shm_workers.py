"""Usercode worker-process lane (nat_shm_lane.cpp + rpc/shm_worker.py):
kind-3/4 dispatch fans out over shm rings to N Python processes — the
reference's usercode-on-all-N-workers concurrency (server.h:59-285,
details/usercode_backup_pool.h:29-72) without this process's GIL.
"""
import os
import subprocess
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from tests.shm_worker_factory import make  # noqa: E402


@pytest.fixture(scope="module")
def worker_server():
    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=2,
        py_worker_factory="tests.shm_worker_factory:make"))
    for s in make():
        srv.add_service(s)
    assert srv.start("127.0.0.1:0") == 0
    # requests a killed worker consumed are reaped fast, inside the
    # tests' call deadlines (default 30s); start() already waited for
    # the workers' attach barrier so this can't fire during boot
    native.load().nat_shm_lane_set_timeout_ms(2000)
    yield srv
    srv.stop()


def _grpc_stub(port):
    grpc = pytest.importorskip("grpc")
    chan = grpc.insecure_channel(f"127.0.0.1:{port}")
    return chan, chan.unary_unary(
        "/EchoService/Echo",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=echo_pb2.EchoResponse.FromString)


def test_http_usercode_runs_in_workers(worker_server):
    port = worker_server.listen_endpoint.port
    out = subprocess.run(
        ["curl", "-s", "-X", "POST", "-H",
         "Content-Type: application/json", "--data",
         '{"message": "hi"}',
         f"http://127.0.0.1:{port}/EchoService/Echo"],
        capture_output=True, timeout=15)
    assert b"hi@" in out.stdout, out.stdout
    pid = int(out.stdout.split(b"@")[1].split(b'"')[0])
    assert pid != os.getpid()  # usercode ran OUTSIDE this process


def test_grpc_usercode_spreads_across_workers(worker_server):
    port = worker_server.listen_endpoint.port
    chan, call = _grpc_stub(port)
    try:
        pids = set()
        for _ in range(30):
            r = call(echo_pb2.EchoRequest(message="x"), timeout=15)
            assert r.message.startswith("x@")
            pids.add(r.message.split("@")[1])
        # both workers served some of the load
        assert len(pids) >= 2, pids
        assert str(os.getpid()) not in pids
    finally:
        chan.close()


def test_worker_crash_recovers(worker_server):
    """Killing one worker must not wedge the server: the robust shm
    mutex recovers, requests the dead worker consumed are reaped with an
    error, and the remaining worker keeps serving."""
    port = worker_server.listen_endpoint.port
    mount = worker_server._native_mount
    victim = mount._shm_workers[0]
    victim.kill()
    victim.wait(timeout=5)
    chan, call = _grpc_stub(port)
    try:
        # transient failures are allowed while the reaper clears the
        # dead worker's consumed requests; then service must be steady
        deadline = time.time() + 15
        streak = 0
        while time.time() < deadline and streak < 5:
            try:
                r = call(echo_pb2.EchoRequest(message="alive"), timeout=5)
                streak = streak + 1 if r.message.startswith("alive@") else 0
            except Exception:
                streak = 0
                time.sleep(0.2)
        assert streak >= 5, "server did not recover after worker death"
    finally:
        chan.close()


def test_all_workers_dead_falls_back_in_process(worker_server):
    """With EVERY worker dead, the heartbeat check must route requests
    to the in-process py lane (the parent has the same services), not
    queue them for the reaper."""
    port = worker_server.listen_endpoint.port
    mount = worker_server._native_mount
    for p in mount._shm_workers:
        p.kill()
    for p in mount._shm_workers:
        p.wait(timeout=5)
    time.sleep(2.5)  # heartbeat staleness threshold
    chan, call = _grpc_stub(port)
    try:
        me = str(os.getpid())
        deadline = time.time() + 15
        served_inproc = 0
        while time.time() < deadline and served_inproc < 5:
            try:
                r = call(echo_pb2.EchoRequest(message="fb"), timeout=5)
                if r.message == f"fb@{me}":
                    served_inproc += 1
            except Exception:
                time.sleep(0.2)
        assert served_inproc >= 5, "in-process fallback did not engage"
    finally:
        chan.close()


def test_pipelined_http_order_through_workers(worker_server):
    """Concurrent worker processes may answer out of request order; the
    parent's reorder window must still emit pipelined responses in
    order."""
    import socket as pysock

    port = worker_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=10)
    try:
        body = b'{"message": "m%d"}'
        reqs = b""
        for i in range(12):
            b_i = body % i
            reqs += (b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
                     b"Content-Type: application/json\r\n"
                     b"Content-Length: %d\r\n\r\n%s" % (len(b_i), b_i))
        sk.sendall(reqs)
        buf = b""
        sk.settimeout(20)
        deadline = time.time() + 20
        while buf.count(b"HTTP/1.1 200") < 12 and time.time() < deadline:
            chunk = sk.recv(65536)
            if not chunk:
                break
            buf += chunk
        assert buf.count(b"HTTP/1.1 200") == 12
        # responses must reference m0..m11 in order
        positions = [buf.find(b'"m%d@' % i) for i in range(12)]
        assert all(p >= 0 for p in positions), buf[:400]
        assert positions == sorted(positions)
    finally:
        sk.close()

