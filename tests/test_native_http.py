"""Native HTTP/1.1 lane — parse in the native cut loop, usercode in Python
(kind-3 py lane) or native handlers, responses ordered across pipelining.

Reference counterpart: brpc parses HTTP natively in InputMessenger
(details/http_parser.cpp) and keeps pipelined responses in request order
(policy/http_rpc_protocol.cpp); builtin services run in C++
(server.cpp:468-563).
"""
import json
import socket
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


class SlowFirstService(rpc.Service):
    """First call stalls; later calls answer immediately — exercises the
    native response-reorder window under pipelining."""

    def __init__(self):
        super().__init__()
        self.calls = 0
        self.lock = threading.Lock()

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        with self.lock:
            self.calls += 1
            first = self.calls == 1
        if first:
            time.sleep(0.4)
        response.message = request.message
        done()


@pytest.fixture()
def http_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _recv_until(sk, n_responses, timeout=5.0):
    """Read until n_responses complete HTTP responses are buffered."""
    sk.settimeout(timeout)
    buf = b""
    deadline = time.time() + timeout
    while time.time() < deadline:
        parsed = 0
        scan = buf
        bodies = []
        while True:
            he = scan.find(b"\r\n\r\n")
            if he < 0:
                break
            head = scan[:he].lower()
            cl = 0
            for line in head.split(b"\r\n"):
                if line.startswith(b"content-length:"):
                    cl = int(line.split(b":")[1])
            if len(scan) < he + 4 + cl:
                break
            bodies.append((scan[:he], scan[he + 4: he + 4 + cl]))
            scan = scan[he + 4 + cl:]
            parsed += 1
        if parsed >= n_responses:
            return bodies
        try:
            chunk = sk.recv(65536)
        except socket.timeout:
            break
        if not chunk:
            break
        buf += chunk
    raise AssertionError(f"wanted {n_responses} responses, buffered {buf!r}")


def test_rpc_over_http_rides_native_lane(http_server):
    port = http_server.listen_endpoint.port
    sk = socket.create_connection(("127.0.0.1", port))
    body = json.dumps({"message": "native-http"}).encode()
    req = (b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
           b"Content-Type: application/json\r\n"
           b"Content-Length: %d\r\n\r\n" % len(body)) + body
    sk.sendall(req)
    (head, resp_body), = _recv_until(sk, 1)
    assert b"200" in head.split(b"\r\n")[0]
    assert json.loads(resp_body)["message"] == "native-http"
    # keep-alive: same connection serves the console too
    sk.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
    (_, body2), = _recv_until(sk, 1)
    assert body2 == b"OK\n"
    sk.close()


def test_pipelined_responses_stay_in_request_order():
    svc = SlowFirstService()
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(svc)
    assert srv.start("127.0.0.1:0") == 0
    try:
        port = srv.listen_endpoint.port
        sk = socket.create_connection(("127.0.0.1", port))
        reqs = b""
        for i in range(3):
            body = json.dumps({"message": f"m{i}"}).encode()
            reqs += (b"POST /SlowFirstService/Echo HTTP/1.1\r\nHost: x\r\n"
                     b"Content-Type: application/json\r\n"
                     b"Content-Length: %d\r\n\r\n" % len(body)) + body
        sk.sendall(reqs)  # one write: truly pipelined
        bodies = _recv_until(sk, 3)
        got = [json.loads(b)["message"] for _, b in bodies]
        # the first (slow) response must still arrive first
        assert got == ["m0", "m1", "m2"]
        sk.close()
    finally:
        srv.stop()


def test_chunked_request_body(http_server):
    port = http_server.listen_endpoint.port
    sk = socket.create_connection(("127.0.0.1", port))
    body = json.dumps({"message": "chunky"}).encode()
    half = len(body) // 2
    chunked = (b"%x\r\n" % half) + body[:half] + b"\r\n" + \
              (b"%x\r\n" % (len(body) - half)) + body[half:] + b"\r\n" + \
              b"0\r\n\r\n"
    sk.sendall(b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
               b"Content-Type: application/json\r\n"
               b"Transfer-Encoding: chunked\r\n\r\n" + chunked)
    (head, resp_body), = _recv_until(sk, 1)
    assert json.loads(resp_body)["message"] == "chunky"
    sk.close()


def test_connection_close_gets_fin_after_response(http_server):
    port = http_server.listen_endpoint.port
    sk = socket.create_connection(("127.0.0.1", port))
    sk.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\nConnection: close\r\n\r\n")
    sk.settimeout(5.0)
    data = b""
    while True:
        chunk = sk.recv(4096)
        if not chunk:
            break  # FIN after the response — graceful close
        data += chunk
    assert b"200" in data and data.endswith(b"OK\n")
    sk.close()


def test_native_http_echo_handler_and_bench():
    """The native-usercode lane: /echo runs in C++, no Python in the loop."""
    port = native.rpc_server_start(native_echo=True)
    try:
        native.rpc_server_native_http(True)
        sk = socket.create_connection(("127.0.0.1", port))
        sk.sendall(b"POST /echo HTTP/1.1\r\nHost: x\r\n"
                   b"Content-Length: 5\r\n\r\nhello")
        (head, body), = _recv_until(sk, 1)
        assert body == b"hello"
        sk.close()
        res = native.http_client_bench("127.0.0.1", port, nconn=2,
                                       pipeline=32, seconds=0.5,
                                       path="/echo", post_body=16)
        assert res["requests"] > 100  # sanity: the lane moves
    finally:
        native.rpc_server_stop()


def test_stock_curl_interop(http_server):
    """A stock client against the native lane: plain GET, keep-alive, and
    a POST with Expect: 100-continue (curl waits for the interim reply
    before sending the body — the lane must emit it)."""
    import shutil
    import subprocess

    if shutil.which("curl") is None:
        pytest.skip("curl unavailable")
    port = http_server.listen_endpoint.port
    r = subprocess.run(["curl", "-s", f"http://127.0.0.1:{port}/health"],
                       capture_output=True, text=True, timeout=15)
    assert r.stdout.strip() == "OK"
    big = json.dumps({"message": "x" * 2000})
    r = subprocess.run(
        ["curl", "-s", "-X", "POST",
         "-H", "Content-Type: application/json",
         "-H", "Expect: 100-continue", "-d", big,
         f"http://127.0.0.1:{port}/EchoService/Echo"],
        capture_output=True, text=True, timeout=15)
    assert json.loads(r.stdout)["message"] == "x" * 2000
    # two URLs in one invocation reuse the connection (keep-alive)
    r = subprocess.run(["curl", "-s", f"http://127.0.0.1:{port}/health",
                        f"http://127.0.0.1:{port}/version"],
                       capture_output=True, text=True, timeout=15)
    assert "OK" in r.stdout
    r.check_returncode()


def test_404_and_bad_method_pages_still_work(http_server):
    port = http_server.listen_endpoint.port
    sk = socket.create_connection(("127.0.0.1", port))
    sk.sendall(b"GET /EchoService/NoSuch HTTP/1.1\r\nHost: x\r\n\r\n")
    (head, body), = _recv_until(sk, 1)
    assert b"404" in head.split(b"\r\n")[0]
    assert b"Echo" in body  # bad_method page lists available methods
    sk.close()
