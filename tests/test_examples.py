"""Smoke-run every example as a subprocess — the examples double as
integration tests, as the reference's example/ suite does in its CI."""
import subprocess
import sys

import pytest

EXAMPLES = [
    ("examples/echo.py", ["demo"]),
    ("examples/parallel_echo.py", []),
    ("examples/partition_echo.py", []),
    ("examples/streaming_echo.py", []),
    ("examples/backup_request.py", []),
    ("examples/cascade_echo.py", []),
    ("examples/auto_concurrency_limiter.py", []),
    ("examples/http_server.py", []),
    ("examples/tensor_transport.py", ["--mb", "1", "--iters", "3"]),
    ("examples/multi_threaded_echo.py", ["--threads", "2",
                                         "--seconds", "1"]),
    ("examples/asynchronous_echo.py", []),
    ("examples/selective_echo.py", []),
    ("examples/dynamic_partition_echo.py", []),
    ("examples/grpc_echo.py", []),
    ("examples/redis_kv.py", []),
    ("examples/memcache_kv.py", []),
    ("examples/thrift_echo.py", []),
    ("examples/nshead_extension.py", []),
    ("examples/session_data.py", []),
    ("examples/legacy_pbrpc_echo.py", []),
    ("examples/device_performance.py", ["--threads", "2", "--mb", "1",
                                        "--iters", "3"]),
    ("examples/io_uring_echo.py", ["--seconds", "1"]),
    ("examples/native_client.py", []),
    ("examples/native_protocol_clients.py", []),
    ("examples/usercode_workers.py", []),
    ("examples/rtmp_relay.py", []),
    ("examples/fanout_swarm.py", ["--backends", "6", "--seconds", "2"]),
]


@pytest.mark.parametrize("script,args", EXAMPLES,
                         ids=[e[0].split("/")[-1] for e in EXAMPLES])
def test_example_runs(script, args):
    # one retry: examples carry real RPC deadlines, and the full suite's
    # compile phases can starve a subprocess on the 1-core CI box long
    # enough to miss one — a second clean run is the signal that matters
    for attempt in (1, 2):
        proc = subprocess.run(
            [sys.executable, script, *args],
            capture_output=True, text=True, timeout=180, cwd="/root/repo",
        )
        if proc.returncode == 0:
            return
    assert proc.returncode == 0, \
        f"stdout:\n{proc.stdout}\nstderr:\n{proc.stderr}"
