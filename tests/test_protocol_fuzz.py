"""Robustness: garbage, truncated frames, and protocol-magic prefixes
thrown at a multi-protocol port must never hang or kill the server —
corrupt streams end with the CONNECTION failed, and well-formed traffic
keeps working throughout (the parse-error discipline of
input_messenger.cpp: PARSE_ERROR_TRY_OTHERS vs terminal errors).
"""
import random
import socket as pysocket
import struct

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _poke(port, payload: bytes, read: bool = True) -> bytes:
    with pysocket.create_connection(("127.0.0.1", port), timeout=2) as s:
        s.sendall(payload)
        if not read:
            return b""
        try:
            return s.recv(4096)
        except (TimeoutError, ConnectionResetError, OSError):
            return b""


def _echo_works(server) -> bool:
    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=3000))
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="alive"),
                         echo_pb2.EchoResponse)
    ch.close()
    return not cntl.failed() and resp.message == "alive"


def test_random_garbage(server):
    rng = random.Random(42)
    port = server.listen_endpoint.port
    for _ in range(30):
        blob = rng.randbytes(rng.randrange(1, 512))
        _poke(port, blob)
    assert _echo_works(server)


def test_magic_prefixed_corruption(server):
    """Each protocol's magic followed by garbage: parsers must reject or
    wait, never crash the process or wedge other connections."""
    port = server.listen_endpoint.port
    rng = random.Random(7)
    magics = [
        b"TRPC" + struct.pack(">II", 0xFFFFFFFF, 0xEEEEEEEE),  # huge body
        b"HULU" + struct.pack("<II", 0xFFFFFFFF, 0xFFFFFFF0),
        b"SOFA" + rng.randbytes(20),
        b"PRI * HTTP/2.0\r\n\r\nXXXX",       # h2 preface then junk
        b"GET /\x00\xff garbage HTTP/1.1\r\n\r\n",
        b"*9999\r\n$-5\r\nxx\r\n",            # corrupt RESP
        b"\x80\xff" + rng.randbytes(30),      # memcache magic + junk
        struct.pack("<HHI", 1, 2, 3) + b"P" * 16
        + struct.pack("<III", 0xFB709394, 0, 0xFFFFFFF0),  # nshead huge len
    ]
    for blob in magics:
        _poke(port, blob)
    assert _echo_works(server)


def test_truncated_then_closed(server):
    """Half a valid frame then EOF: the read loop must not spin or leak
    the connection."""
    port = server.listen_endpoint.port
    meta_stub = b"\x08\x01"
    frame = b"TRPC" + struct.pack(">II", 100, len(meta_stub)) + meta_stub
    _poke(port, frame[: len(frame) // 2], read=False)
    _poke(port, b"GET /status HTTP/1.1\r\n", read=False)  # headers cut off
    assert _echo_works(server)


def test_slow_dribble(server):
    """A valid request delivered one byte at a time still completes."""
    from brpc_tpu.rpc.proto import rpc_meta_pb2

    meta = rpc_meta_pb2.RpcMeta()
    meta.request.service_name = "EchoService"
    meta.request.method_name = "Echo"
    meta.correlation_id = 1
    mb = meta.SerializeToString()
    payload = echo_pb2.EchoRequest(message="dribble").SerializeToString()
    frame = (b"TRPC" + struct.pack(">II", len(mb) + len(payload), len(mb))
             + mb + payload)
    port = server.listen_endpoint.port
    with pysocket.create_connection(("127.0.0.1", port), timeout=5) as s:
        for i in range(0, len(frame), 3):
            s.sendall(frame[i:i + 3])
        out = b""
        while len(out) < 12:
            chunk = s.recv(4096)
            if not chunk:
                break
            out += chunk
    assert out[:4] == b"TRPC"
