"""Worker-crash fast-reap (descriptor-ring robust fence), end to end.

Lives in its own module: it needs a server of its OWN (py_workers=1 with
the slow factory), and the native runtime hosts one server per process —
test_shm_workers.py's module fixture must not be live concurrently.
"""
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


def _grpc_stub(port):
    grpc = pytest.importorskip("grpc")
    chan = grpc.insecure_channel(f"127.0.0.1:{port}")
    return chan, chan.unary_unary(
        "/EchoService/Echo",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=echo_pb2.EchoResponse.FromString)


def test_worker_sigkill_mid_request_fast_reap():
    """SIGKILL the ONLY worker while it is processing (descriptor
    consumed, response unpublished): the robust-fence recovery must reap
    the in-flight request promptly — an UNAVAILABLE answer (or an
    in-process retry success) well before the 30s reaper deadline — and
    the server must keep serving via the in-process fallback."""
    grpc = pytest.importorskip("grpc")
    from tests.shm_worker_factory import make_slow

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=1,
        py_worker_factory="tests.shm_worker_factory:make_slow"))
    for s in make_slow():
        srv.add_service(s)
    assert srv.start("127.0.0.1:0") == 0
    lib = native.load()
    # deliberately LONG reaper deadline: the pass condition is that the
    # crash-recovery path answers, not the timeout reaper
    lib.nat_shm_lane_set_timeout_ms(30000)
    try:
        port = srv.listen_endpoint.port
        mount = srv._native_mount
        chan, call = _grpc_stub(port)
        try:
            fut = call.future(echo_pb2.EchoRequest(message="boom"),
                              timeout=25)
            time.sleep(0.15)  # worker consumed it, parked in usercode
            victim = mount._shm_workers[0]
            victim.kill()
            victim.wait(timeout=5)
            t0 = time.time()
            try:
                r = fut.result(timeout=20)
                assert r.message.startswith("boom@")
            except grpc.RpcError as e:
                assert e.code() == grpc.StatusCode.UNAVAILABLE, e
            # recovery (fence probe + immediate slot reap) answered it —
            # nowhere near the 30s reaper deadline
            assert time.time() - t0 < 10
            # the lane falls back in-process (sole worker dead) and
            # keeps serving
            deadline = time.time() + 15
            ok = 0
            while time.time() < deadline and ok < 3:
                try:
                    r = call(echo_pb2.EchoRequest(message="after"),
                             timeout=5)
                    ok += 1 if r.message.startswith("after@") else 0
                except Exception:
                    time.sleep(0.2)
            assert ok >= 3, "server did not keep serving after the kill"
        finally:
            chan.close()
    finally:
        lib.nat_shm_lane_set_timeout_ms(2000)  # module-fixture setting
        srv.stop()
