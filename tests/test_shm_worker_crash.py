"""Worker-crash fast-reap (descriptor-ring robust fence), end to end.

Lives in its own module: it needs a server of its OWN (py_workers=1 with
the slow factory), and the native runtime hosts one server per process —
test_shm_workers.py's module fixture must not be live concurrently.
"""
import os
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


def _grpc_stub(port):
    grpc = pytest.importorskip("grpc")
    chan = grpc.insecure_channel(f"127.0.0.1:{port}")
    return chan, chan.unary_unary(
        "/EchoService/Echo",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=echo_pb2.EchoResponse.FromString)


def test_worker_sigkill_mid_request_fast_reap():
    """SIGKILL the ONLY worker while it is processing (descriptor
    consumed, response unpublished): the robust-fence recovery must reap
    the in-flight request promptly — an UNAVAILABLE answer (or an
    in-process retry success) well before the 30s reaper deadline — and
    the server must keep serving via the in-process fallback."""
    grpc = pytest.importorskip("grpc")
    from tests.shm_worker_factory import make_slow

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=1,
        py_worker_factory="tests.shm_worker_factory:make_slow"))
    for s in make_slow():
        srv.add_service(s)
    assert srv.start("127.0.0.1:0") == 0
    lib = native.load()
    # deliberately LONG reaper deadline: the pass condition is that the
    # crash-recovery path answers, not the timeout reaper
    lib.nat_shm_lane_set_timeout_ms(30000)
    try:
        port = srv.listen_endpoint.port
        mount = srv._native_mount
        chan, call = _grpc_stub(port)
        try:
            fut = call.future(echo_pb2.EchoRequest(message="boom"),
                              timeout=25)
            time.sleep(0.15)  # worker consumed it, parked in usercode
            victim = mount._shm_workers[0]
            victim.kill()
            victim.wait(timeout=5)
            t0 = time.time()
            try:
                r = fut.result(timeout=20)
                assert r.message.startswith("boom@")
            except grpc.RpcError as e:
                assert e.code() == grpc.StatusCode.UNAVAILABLE, e
            # recovery (fence probe + immediate slot reap) answered it —
            # nowhere near the 30s reaper deadline
            assert time.time() - t0 < 10
            # the lane falls back in-process (sole worker dead) and
            # keeps serving
            deadline = time.time() + 15
            ok = 0
            while time.time() < deadline and ok < 3:
                try:
                    r = call(echo_pb2.EchoRequest(message="after"),
                             timeout=5)
                    ok += 1 if r.message.startswith("after@") else 0
                except Exception:
                    time.sleep(0.2)
            assert ok >= 3, "server did not keep serving after the kill"
        finally:
            chan.close()
    finally:
        lib.nat_shm_lane_set_timeout_ms(2000)  # module-fixture setting
        srv.stop()


def test_worker_sigkill_via_fault_table():
    """The same SIGKILL-mid-request scenario, driven through natfault's
    seeded schedule instead of an ad-hoc os.kill: the worker process
    inherits NAT_FAULT and raises SIGKILL on its 3rd take — descriptor
    consumed, response unpublished — and the parent's robust-fence
    recovery must answer the victim request and keep the server serving.
    The parent never calls nat_shm_take_request, so the worker:kill rule
    cannot fire in this process."""
    grpc = pytest.importorskip("grpc")
    ambient_spec = os.environ.get("NAT_FAULT")  # restored on teardown
    os.environ["NAT_FAULT"] = "seed=5;worker:kill@3"
    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=1,
        py_worker_factory="tests.shm_worker_factory:make"))
    from tests.shm_worker_factory import make
    for s in make():
        srv.add_service(s)
    try:
        assert srv.start("127.0.0.1:0") == 0
        native.load().nat_shm_lane_set_timeout_ms(30000)
        port = srv.listen_endpoint.port
        chan, call = _grpc_stub(port)
        try:
            outcomes = []
            t0 = time.time()
            for i in range(6):
                try:
                    r = call(echo_pb2.EchoRequest(message=f"m{i}"),
                             timeout=20)
                    outcomes.append(("ok", r.message))
                except grpc.RpcError as e:
                    outcomes.append(("err", e.code()))
            # the seeded kill fired somewhere in the burst: at most the
            # victim request errored (UNAVAILABLE from the fast-reap),
            # everything else was answered — well before the 30s reaper
            assert time.time() - t0 < 25, outcomes
            errs = [o for o in outcomes if o[0] == "err"]
            assert len(errs) <= 1, outcomes
            for o in errs:
                assert o[1] == grpc.StatusCode.UNAVAILABLE, outcomes
            # and the server keeps serving (in-process fallback after
            # the sole worker died)
            deadline = time.time() + 15
            ok = 0
            while time.time() < deadline and ok < 3:
                try:
                    r = call(echo_pb2.EchoRequest(message="alive"),
                             timeout=5)
                    ok += 1 if r.message.startswith("alive@") else 0
                except Exception:
                    time.sleep(0.2)
            assert ok >= 3, "server did not keep serving after the kill"
        finally:
            chan.close()
    finally:
        if ambient_spec is None:
            del os.environ["NAT_FAULT"]
        else:
            os.environ["NAT_FAULT"] = ambient_spec
        native.load().nat_shm_lane_set_timeout_ms(2000)
        srv.stop()
