"""natfault — deterministic fault injection for the native runtime.

Drives the retry / backup-request / health-check machinery the client
lane grew in earlier PRs through INJECTED faults (native/src/nat_fault.*):
dropped writes, injected ECONNRESET/EPIPE, short reads/writes, EINTR,
connect refusal. Each test installs its own spec via nat_fault_configure
and restores the ambient NAT_FAULT env spec (the chaos lane arms one) on
teardown.
"""
import os
import threading
import time

import pytest

from brpc_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


@pytest.fixture(autouse=True)
def _restore_env_spec():
    yield
    # back to the ambient spec (empty when not under the chaos lane)
    native.fault_configure(os.environ.get("NAT_FAULT", ""))


@pytest.fixture(scope="module")
def echo_server():
    port = native.rpc_server_start(native_echo=True)
    yield port
    native.fault_configure(os.environ.get("NAT_FAULT", ""))
    native.rpc_server_stop()


def test_spec_parse_and_gate():
    assert native.fault_configure(
        "seed=42;read:p=0.01:err=ECONNRESET;write:short;"
        "connect:delay_ms=200;worker:kill@7") == 0
    assert native.fault_enabled()
    assert native.fault_configure("") == 0
    assert not native.fault_enabled()
    # parse errors leave the table untouched and report -1
    assert native.fault_configure("nosuchsite:drop") == -1
    assert native.fault_configure("read:nosuchaction") == -1


def test_same_seed_same_schedule(echo_server):
    """The p= decision for op k is a pure function of (seed, site, rule,
    k): two identical runs over the same op sequence inject identically."""
    ch = native.channel_open("127.0.0.1", echo_server)
    counts = []
    for _ in range(2):
        native.fault_configure("seed=1234;read:short:p=0.5")
        base = native.fault_injected()
        for _ in range(30):
            rc, body, _ = native.channel_call(ch, "EchoService", "Echo",
                                              b"deterministic",
                                              timeout_ms=5000)
            assert rc == 0 and body == b"deterministic"
        counts.append(native.fault_injected() - base)
        native.fault_configure("")
    native.channel_close(ch)
    assert counts[0] > 0
    # op counts can differ by a handful of background read ops (idle
    # console sockets), but the schedule is seed-stable: the two runs
    # must land within a few ops of each other, not diverge randomly
    assert abs(counts[0] - counts[1]) <= 4, counts


def test_echo_survives_short_reads_writes_eintr(echo_server):
    """Semantics-preserving faults: 1-byte reads/writes and EINTR must
    cost only latency — every parser is incremental, every drain loop
    retries. 100% correct completion is the assertion."""
    ch = native.channel_open("127.0.0.1", echo_server)
    native.fault_configure(
        "seed=7;read:short:p=0.3;write:short:p=0.3;"
        "read:err=EINTR:p=0.05;write:err=EINTR:p=0.05")
    payload = b"y" * 700
    for _ in range(60):
        rc, body, _ = native.channel_call(ch, "EchoService", "Echo",
                                          payload, timeout_ms=5000)
        assert rc == 0 and body == payload
    assert native.fault_injected() > 0
    native.fault_configure("")
    native.channel_close(ch)


def test_multiwriter_burst_survives_write_faults(echo_server):
    """The wait-free MPSC write-stack enqueue path under injected write
    faults (ISSUE 7 chaos satellite): N threads hammer ONE channel
    socket while write:short truncates every drain to 1 byte and
    write:err=EINTR/EAGAIN bounces the drainer into the KeepWrite
    handoff. Concurrent pushes race the drainer's role-release CAS on
    every call; the assertion is 100% exactly-once completion — a lost
    node, a double drain, or wire reordering would fail/corrupt calls."""
    ch = native.channel_open("127.0.0.1", echo_server)
    native.fault_configure(
        "seed=21;write:short:p=0.4;write:err=EINTR:p=0.1;"
        "write:err=EAGAIN:p=0.1")
    errs = []
    done = [0] * 4

    def writer(idx):
        payload = b"w%d-" % idx + b"z" * 120
        for _ in range(40):
            rc, body, text = native.channel_call(
                ch, "EchoService", "Echo", payload, timeout_ms=8000)
            if rc != 0 or body != payload:
                errs.append((idx, rc, text))
                return
            done[idx] += 1

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    assert done == [40] * 4, done
    assert native.fault_injected() > 0
    native.fault_configure("")
    native.channel_close(ch)


def test_multiwriter_socket_fail_mid_drain(echo_server):
    """Write faults that KILL the socket mid-drain while a burst of
    writers is still pushing (the release_all arm of the drain role):
    every in-flight call must complete exactly once — as an error (the
    fail_all sweep) or via retry on the re-dialed socket — and the
    channel must come back clean once faults clear. Exercises the
    drainer-exit vs fresh-push window the dsched `wstack` scenario
    models, with real sockets dying under it."""
    ch = native.channel_open("127.0.0.1", echo_server)
    native.fault_configure("seed=33;write:err=EPIPE:p=0.03;"
                           "write:short:p=0.3")
    outcomes = []
    lock = threading.Lock()

    def writer(idx):
        for i in range(30):
            rc, body, _ = native.channel_call(
                ch, "EchoService", "Echo", b"k%d-%d" % (idx, i),
                timeout_ms=8000, max_retry=3)
            with lock:
                outcomes.append(rc)

    threads = [threading.Thread(target=writer, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(outcomes) == 120  # every call completed exactly once
    native.fault_configure("")
    # the channel recovers: the write stack of the dead socket was fully
    # released (a leaked drain role would wedge every later call). The
    # dead-socket re-dial cool-down doubles up to 3.2s, so back-to-back
    # attempts can all land inside the window under load — space them out.
    for _ in range(12):
        rc, body, _ = native.channel_call(ch, "EchoService", "Echo",
                                          b"post", timeout_ms=5000,
                                          max_retry=2)
        if rc == 0:
            break
        time.sleep(0.4)
    assert rc == 0 and body == b"post"
    native.channel_close(ch)


def test_backup_request_wins_after_dropped_primary(echo_server):
    """The backup-request lifecycle under an injected fault: the primary
    write VANISHES (write:drop@1), the backup timer re-sends the same
    correlation id once the fault clears, and the call completes through
    the backup — no timeout, no double completion."""
    ch = native.channel_open("127.0.0.1", echo_server)
    res = {}

    def call():
        t0 = time.time()
        res["r"] = native.channel_call(ch, "EchoService", "Echo", b"bk",
                                       timeout_ms=5000, backup_ms=150)
        res["dt"] = time.time() - t0

    native.fault_configure("seed=1;write:drop@1")
    t = threading.Thread(target=call)
    t.start()
    time.sleep(0.06)  # primary dropped by now; backup not yet fired
    native.fault_configure("")
    t.join()
    rc, body, _ = res["r"]
    assert rc == 0 and body == b"bk", res["r"]
    assert res["dt"] >= 0.14, res  # the BACKUP answered, not the primary
    native.channel_close(ch)


def test_late_primary_no_double_completion(echo_server):
    """backup_ms=1 against a fast echo: primary and backup responses
    race for the same call slot on nearly every request. The versioned
    pending-bit CAS must make the loser a no-op — no crash, no double
    free, and calls == completions in the stats."""
    ch = native.channel_open("127.0.0.1", echo_server)
    for i in range(200):
        rc, body, _ = native.channel_call(ch, "EchoService", "Echo",
                                          b"dup%d" % i, timeout_ms=5000,
                                          backup_ms=1)
        assert rc == 0 and body == b"dup%d" % i
    native.channel_close(ch)


def test_injected_socket_death_rides_retry(echo_server):
    """Both-fail then retry path: write:err=EPIPE on the first write
    kills the socket (fail_all errors the call); max_retry re-dials and
    the second attempt lands clean."""
    ch = native.channel_open("127.0.0.1", echo_server)
    native.fault_configure("seed=3;write:err=EPIPE:nth=1")
    rc, body, _ = native.channel_call(ch, "EchoService", "Echo", b"rt",
                                      timeout_ms=5000, max_retry=2)
    assert rc == 0 and body == b"rt"
    native.fault_configure("")
    # and with no retries both attempts fail: the error surfaces
    native.fault_configure("seed=3;write:err=EPIPE:p=1")
    rc, _, _ = native.channel_call(ch, "EchoService", "Echo", b"rt2",
                                   timeout_ms=2000)
    assert rc != 0
    native.fault_configure("")
    # the channel recovers once faults clear
    rc, body, _ = native.channel_call(ch, "EchoService", "Echo", b"rt3",
                                      timeout_ms=5000, max_retry=2)
    assert rc == 0 and body == b"rt3"
    native.channel_close(ch)


def test_retry_budget_clamps_storms_and_replenishes(echo_server):
    """An injected failure burst must not amplify into a retry storm:
    the channel-wide budget (10 deci-tokens per retry) runs dry, the
    exhaustion surfaces as a stat cell, and successes replenish it."""
    ch = native.channel_open("127.0.0.1", echo_server)
    assert native.channel_retry_budget(ch) == 100
    native.fault_configure("seed=5;write:err=EPIPE:p=1")
    before = native.stats_counters()["nat_retry_budget_exhausted"]
    for _ in range(8):
        rc, _, _ = native.channel_call(ch, "EchoService", "Echo", b"x",
                                       timeout_ms=1000, max_retry=3)
        assert rc != 0
    native.fault_configure("")
    after = native.stats_counters()["nat_retry_budget_exhausted"]
    assert after > before, (before, after)
    drained = native.channel_retry_budget(ch)
    assert drained < 20, drained  # burst drained the budget
    # successes pay it back (+1 deci-token each, capped)
    for _ in range(60):
        rc, _, _ = native.channel_call(ch, "EchoService", "Echo", b"ok",
                                       timeout_ms=5000, max_retry=1)
        assert rc == 0
    assert native.channel_retry_budget(ch) > drained
    native.channel_close(ch)


def test_connect_refusal_and_health_check_backoff(echo_server):
    """A dead peer must not be hammered at a fixed rate: with
    health_check_ms=50 and every dial refused by the fault table, the
    revival chain's exponential backoff caps the attempts far below the
    fixed-rate count (2s / 50ms = 40)."""
    ch = native.channel_open("127.0.0.1", echo_server, health_check_ms=50)
    rc, body, _ = native.channel_call(ch, "EchoService", "Echo", b"pre",
                                      timeout_ms=5000)
    assert rc == 0
    # kill the connection (server side scans sockets on injected reset)
    native.fault_configure("seed=9;read:err=ECONNRESET:nth=1")
    rc, _, _ = native.channel_call(ch, "EchoService", "Echo", b"die",
                                   timeout_ms=1000)
    # now refuse every re-dial and count attempts via the fault counter
    native.fault_configure("seed=9;connect:err=ECONNREFUSED:p=1")
    base = native.fault_injected()
    time.sleep(2.0)
    dials = native.fault_injected() - base
    native.fault_configure("")
    assert 1 <= dials <= 15, dials  # backoff, not a fixed-rate hammer
    # once dials succeed again, the chain (or on-demand re-dial) revives
    deadline = time.time() + 10
    while time.time() < deadline:
        rc, body, _ = native.channel_call(ch, "EchoService", "Echo",
                                          b"back", timeout_ms=2000,
                                          max_retry=2)
        if rc == 0 and body == b"back":
            break
        time.sleep(0.1)
    assert rc == 0, rc
    native.channel_close(ch)
