"""Combo-channel tests — shaped after example/parallel_echo_c++,
example/partition_echo_c++, example/selective_echo_c++ and
brpc_channel_unittest.cpp's combo coverage (SURVEY.md sections 2.6, 4).
"""
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.proto import echo_pb2


class TaggedEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, name):
        self.name = name

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = f"{self.name}"
        done()


def _start(name):
    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    srv.add_service(TaggedEcho(name))
    assert srv.start("127.0.0.1:0") == 0
    return srv


@pytest.fixture(scope="module")
def trio():
    servers = [_start(f"n{i}") for i in range(3)]
    yield servers
    for s in servers:
        s.stop()


class ConcatMerger(rpc.ResponseMerger):
    def merge(self, main_response, sub_response):
        main_response.message += sub_response.message + ";"
        return 0


def test_parallel_channel_fans_out(trio):
    pc = rpc.ParallelChannel()
    for srv in trio:
        ch = rpc.Channel()
        assert ch.init(str(srv.listen_endpoint)) == 0
        pc.add_channel(ch, response_merger=ConcatMerger())
    cntl, resp = pc.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="x"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    assert not cntl.failed(), cntl.error_text
    parts = set(filter(None, resp.message.split(";")))
    assert parts == {"n0", "n1", "n2"}


def test_parallel_channel_tolerates_partial_failure(trio):
    pc = rpc.ParallelChannel()  # default fail_limit = all
    for srv in trio[:2]:
        ch = rpc.Channel()
        assert ch.init(str(srv.listen_endpoint)) == 0
        pc.add_channel(ch, response_merger=ConcatMerger())
    dead = rpc.Channel(rpc.ChannelOptions(max_retry=0, timeout_ms=300))
    assert dead.init("127.0.0.1:1") == 0
    pc.add_channel(dead, response_merger=ConcatMerger())
    cntl, resp = pc.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="x"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    assert not cntl.failed(), cntl.error_text  # 2/3 succeeded
    assert set(filter(None, resp.message.split(";"))) == {"n0", "n1"}


def test_parallel_channel_fail_limit_one(trio):
    pc = rpc.ParallelChannel(fail_limit=1)
    ch = rpc.Channel()
    assert ch.init(str(trio[0].listen_endpoint)) == 0
    pc.add_channel(ch)
    dead = rpc.Channel(rpc.ChannelOptions(max_retry=0, timeout_ms=300))
    assert dead.init("127.0.0.1:1") == 0
    pc.add_channel(dead)
    cntl, _ = pc.call("EchoService.Echo", echo_pb2.EchoRequest(message="x"),
                      echo_pb2.EchoResponse, timeout_ms=3000)
    assert cntl.error_code == errors.ETOOMANYFAILS


def test_parallel_channel_call_mapper(trio):
    class IndexMapper(rpc.CallMapper):
        def map(self, i, method, request, response):
            if i == 2:
                return rpc.SubCall.skip_call()
            return rpc.SubCall(
                method, echo_pb2.EchoRequest(message=f"sub{i}"),
                echo_pb2.EchoResponse(),
            )

    pc = rpc.ParallelChannel()
    for srv in trio:
        ch = rpc.Channel()
        assert ch.init(str(srv.listen_endpoint)) == 0
        pc.add_channel(ch, call_mapper=IndexMapper(),
                       response_merger=ConcatMerger())
    cntl, resp = pc.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="main"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    assert not cntl.failed(), cntl.error_text
    assert set(filter(None, resp.message.split(";"))) == {"n0", "n1"}


def test_selective_channel_failover(trio):
    sc = rpc.SelectiveChannel(max_retry=2)
    dead = rpc.Channel(rpc.ChannelOptions(max_retry=0, timeout_ms=200))
    assert dead.init("127.0.0.1:1") == 0
    sc.add_channel(dead)
    live = rpc.Channel()
    assert live.init(str(trio[0].listen_endpoint)) == 0
    sc.add_channel(live)
    ok = 0
    for _ in range(4):
        cntl, resp = sc.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="s"),
                             echo_pb2.EchoResponse, timeout_ms=2000)
        if not cntl.failed():
            ok += 1
            assert resp.message == "n0"
    assert ok == 4  # failover makes every call succeed


def test_partition_channel(trio):
    # 3 partitions in a 3-way scheme, one server each, tags "i/3"
    url = "list://" + ",".join(
        f"{srv.listen_endpoint} {i}/3" for i, srv in enumerate(trio)
    )
    pc = rpc.PartitionChannel()
    assert pc.init(3, url, "rr") == 0
    assert pc.channel_count == 3
    cntl = rpc.Controller()
    cntl.timeout_ms = 3000
    resp = echo_pb2.EchoResponse()

    class Merger(rpc.ResponseMerger):
        def merge(self, main, sub):
            main.message += sub.message + ","
            return 0

    pc2 = rpc.PartitionChannel()
    assert pc2.init(3, url, "rr") == 0
    for i in range(len(pc2._subs)):
        ch, m, _ = pc2._subs[i]
        pc2._subs[i] = (ch, m, Merger())
    cntl, resp = pc2.call("EchoService.Echo",
                          echo_pb2.EchoRequest(message="p"),
                          echo_pb2.EchoResponse, timeout_ms=3000)
    assert not cntl.failed(), cntl.error_text
    assert set(filter(None, resp.message.split(","))) == {"n0", "n1", "n2"}
    pc.stop()
    pc2.stop()


def test_partition_parser_rejects_garbage():
    p = rpc.PartitionParser()
    assert p.parse("2/4") == (2, 4)
    assert p.parse("4/4") is None
    assert p.parse("x/4") is None
    assert p.parse("") is None


def test_dynamic_partition_channel(trio):
    # two schemes: 1-way (n0) and 2-way (n1, n2)
    url = (f"list://{trio[0].listen_endpoint} 0/1,"
           f"{trio[1].listen_endpoint} 0/2,"
           f"{trio[2].listen_endpoint} 1/2")
    dc = rpc.DynamicPartitionChannel()
    assert dc.init(url, "rr") == 0
    assert sorted(dc._schemes.keys()) == [1, 2]
    seen = set()
    # enough samples that P(one scheme takes them all) < 1e-7 — 12 picks
    # flaked at the (2/3)^12 ~ 0.8% rate on a weighted 1:2 split
    for _ in range(40):
        cntl, resp = dc.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="d"),
                             echo_pb2.EchoResponse, timeout_ms=3000)
        assert not cntl.failed(), cntl.error_text
        seen.add(resp.message)
        if "n0" in seen and ("n1" in seen or "n2" in seen):
            break  # both schemes served: the property holds
    # over many calls both schemes should serve (capacity-weighted pick)
    assert "n0" in seen and ("n1" in seen or "n2" in seen)
    dc.stop()
