"""Native server overload protection + client circuit breaker.

The native server lane's admission control (nat_overload.cpp: constant +
gradient limiters ported from rpc/concurrency_limiter.py, queue-deadline
drop, real ELIMIT wire responses) and the native client circuit breaker
(two-EMA-window isolation mirroring rpc/circuit_breaker.py, revived by
the health-check chain).
"""
import os
import threading
import time

import pytest

from brpc_tpu import native
from brpc_tpu.rpc.errors import ELIMIT

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


class PyLaneEcho:
    """Minimal py-lane consumer: echoes payloads after `delay` seconds;
    `serving` gates whether requests are taken at all."""

    def __init__(self, delay=0.0):
        self.delay = delay
        self.stop = False
        self.serving = threading.Event()
        self.serving.set()
        self.thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        while not self.stop:
            if not self.serving.is_set():
                time.sleep(0.01)
                continue
            r = native.take_request(50)
            if r is None:
                continue
            h, kind = r[0], r[1]
            if kind != 0:
                native.req_free(h)
                continue
            if self.delay:
                time.sleep(self.delay)
            native.respond(h, 0, "", r[3])

    def __enter__(self):
        self.thread.start()
        return self

    def __exit__(self, *exc):
        self.stop = True
        self.thread.join()


@pytest.fixture
def server():
    port = native.rpc_server_start()
    yield port
    native.rpc_server_limiter("")
    native.rpc_server_queue_deadline_ms(0)
    native.fault_configure(os.environ.get("NAT_FAULT", ""))
    native.rpc_server_stop()


def _flood(port, n, timeout_ms=5000, payload=b"p"):
    results = []
    lock = threading.Lock()

    def one():
        ch = native.channel_open("127.0.0.1", port)
        r = native.channel_call(ch, "S", "M", payload,
                                timeout_ms=timeout_ms)
        with lock:
            results.append(r)
        native.channel_close(ch)

    threads = [threading.Thread(target=one) for _ in range(n)]
    t0 = time.time()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, time.time() - t0


def test_constant_limiter_sheds_with_elimit(server):
    """Flooding past the limit yields real ELIMIT(2004) rejections on
    the wire, the accepted requests complete promptly (no hang, no
    unbounded queue), and the server keeps serving afterwards."""
    assert native.rpc_server_limiter("constant:2") == 0
    assert native.rpc_server_limit() == 2
    before = native.stats_counters()["nat_elimit_rejects"]
    with PyLaneEcho(delay=0.05):
        results, dt = _flood(server, 12)
        rcs = [r[0] for r in results]
        assert rcs.count(0) >= 2, rcs          # admitted work completed
        assert ELIMIT in rcs, rcs              # and the rest was shed
        assert dt < 3.0, dt                    # bounded, not queued
        # the rejected calls carry the reference error text
        texts = [r[2] for r in results if r[0] == ELIMIT]
        assert any("concurrency" in t for t in texts), texts
        # post-storm: a fresh call sails through
        ch = native.channel_open("127.0.0.1", server)
        rc, body, _ = native.channel_call(ch, "S", "M", b"after",
                                          timeout_ms=5000)
        assert rc == 0 and body == b"after"
        native.channel_close(ch)
    assert native.stats_counters()["nat_elimit_rejects"] > before
    assert native.rpc_server_inflight() == 0  # accounting drained


def test_queue_deadline_drops_expired_before_dispatch(server):
    """Requests that sat in the py queue past the deadline are rejected
    with ELIMIT when a worker would take them — stale work never reaches
    usercode, so accepted-request latency stays bounded."""
    native.rpc_server_queue_deadline_ms(100)
    before = native.stats_counters()["nat_queue_deadline_drops"]
    consumer = PyLaneEcho()
    consumer.serving.clear()  # stall: let the queue age
    with consumer:
        done = []

        def caller():
            ch = native.channel_open("127.0.0.1", server)
            done.append(native.channel_call(ch, "S", "M", b"q",
                                            timeout_ms=5000)[0])
            native.channel_close(ch)

        threads = [threading.Thread(target=caller) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.3)  # all four are now older than the deadline
        consumer.serving.set()
        for t in threads:
            t.join()
        assert all(rc == ELIMIT for rc in done), done
        assert native.stats_counters()["nat_queue_deadline_drops"] - \
            before >= 4
        # fresh (young) requests still go through
        ch = native.channel_open("127.0.0.1", server)
        rc, body, _ = native.channel_call(ch, "S", "M", b"fresh",
                                          timeout_ms=5000)
        assert rc == 0 and body == b"fresh"
        native.channel_close(ch)


def test_auto_limiter_converges_and_serves(server):
    """The gradient limiter measures capacity from the 1s windows and
    keeps serving; the computed limit is exposed for observability."""
    assert native.rpc_server_limiter("auto") == 0
    assert native.rpc_server_limit() > 0  # seeded initial limit
    with PyLaneEcho(delay=0.001):
        ch = native.channel_open("127.0.0.1", server)
        deadline = time.time() + 4.0
        ok = 0
        while time.time() < deadline:
            rc, _, _ = native.channel_call(ch, "S", "M", b"a",
                                           timeout_ms=5000)
            ok += 1 if rc == 0 else 0
        native.channel_close(ch)
        assert ok > 100
    assert native.rpc_server_limit() >= 4  # window rollover computed one


def test_breaker_trips_fails_fast_and_revives(server):
    """The native circuit breaker isolates a peer that stops answering
    (timeout storm trips the short EMA window), calls fail fast through
    the isolation, and the health-check chain revives + resets it once
    the peer serves again."""
    consumer = PyLaneEcho()
    consumer.serving.clear()  # nobody answers: every call times out
    with consumer:
        ch = native.channel_open("127.0.0.1", server, health_check_ms=50)
        native.channel_set_breaker(ch, True)
        before = native.stats_counters()["nat_breaker_isolations"]
        for _ in range(30):
            native.channel_call(ch, "S", "M", b"t", timeout_ms=40)
            if native.channel_breaker_state(ch) == 1:
                break
        assert native.channel_breaker_state(ch) == 1
        assert native.stats_counters()["nat_breaker_isolations"] > before
        # isolated: fail fast, no dial, no 40ms timeout wait
        t0 = time.time()
        rc, _, _ = native.channel_call(ch, "S", "M", b"ff",
                                       timeout_ms=2000)
        assert rc != 0
        assert time.time() - t0 < 0.05
        # peer comes back: isolation (>=100ms) expires, the hc chain
        # re-dials, the breaker resets, calls flow again
        consumer.serving.set()
        deadline = time.time() + 5
        while time.time() < deadline and \
                native.channel_breaker_state(ch) == 1:
            time.sleep(0.05)
        assert native.channel_breaker_state(ch) == 0, "no revival"
        rc, body, _ = native.channel_call(ch, "S", "M", b"back",
                                          timeout_ms=5000, max_retry=2)
        assert rc == 0 and body == b"back"
        assert native.stats_counters()["nat_breaker_revivals"] >= 1
        native.channel_close(ch)


def test_breaker_isolates_fault_injected_flapping_peer(server):
    """The acceptance scenario: a fault-injected flapping connection
    (every write EPIPEs, so every call errors) trips the breaker; after
    the faults clear the health-check chain brings the node back."""
    with PyLaneEcho():
        ch = native.channel_open("127.0.0.1", server, health_check_ms=50)
        native.channel_set_breaker(ch, True)
        native.fault_configure("seed=21;write:err=EPIPE:p=1")
        for _ in range(40):
            native.channel_call(ch, "S", "M", b"f", timeout_ms=500)
            if native.channel_breaker_state(ch) == 1:
                break
        assert native.channel_breaker_state(ch) == 1
        native.fault_configure("")  # faults clear: revival chain works
        deadline = time.time() + 5
        while time.time() < deadline and \
                native.channel_breaker_state(ch) == 1:
            time.sleep(0.05)
        assert native.channel_breaker_state(ch) == 0
        rc, body, _ = native.channel_call(ch, "S", "M", b"healed",
                                          timeout_ms=5000, max_retry=2)
        assert rc == 0 and body == b"healed"
        native.channel_close(ch)
