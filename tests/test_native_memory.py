"""Native memory observatory tests (ISSUE 14) — the nat_res ledger,
allocation-site heap/growth profiler, /heap/native + /growth/native
console pages, the /status RSS reconciliation, the /connections memory
column, and the churn-balance contract (every accounted subsystem
returns to its pre-churn live balance after dial/call/close churn and a
shm-worker SIGKILL+recover round)."""
import ctypes
import http.client
import threading
import time

import pytest

from brpc_tpu import rpc

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from brpc_tpu.rpc.proto import echo_pb2  # noqa: E402


def _res_rows():
    return {r["subsystem"]: r for r in native.res_stats()}


def _native_echo_traffic(port, n=20, payload=b"m" * 600):
    lib = native.load()
    h = lib.nat_channel_open(b"127.0.0.1", port, 0, 0, 0, 0)
    assert h
    resp = ctypes.c_char_p()
    rlen = ctypes.c_size_t(0)
    err = ctypes.c_char_p()
    try:
        for _ in range(n):
            rc = lib.nat_channel_call(h, b"EchoService", b"Echo", payload,
                                      len(payload), 3000,
                                      ctypes.byref(resp),
                                      ctypes.byref(rlen), ctypes.byref(err))
            assert rc == 0 and rlen.value == len(payload)
            if resp:
                lib.nat_buf_free(resp)
                resp = ctypes.c_char_p()
            if err:
                lib.nat_buf_free(err)
                err = ctypes.c_char_p()
    finally:
        lib.nat_channel_close(h)


# ---------------------------------------------------------------------------
# ledger surface
# ---------------------------------------------------------------------------


def test_ledger_rows_and_names():
    rows = native.res_stats()
    names = native.res_names()
    assert len(rows) == len(names) >= 10
    assert [r["subsystem"] for r in rows] == names
    for want in ("iobuf.block", "sock.slab", "sock.wreq", "srv.pyreq",
                 "sched.stack", "shm.seg", "dump.spill", "prof.cells",
                 "cluster", "stats.cell"):
        assert want in names, names
    for r in rows:
        assert r["hwm_bytes"] >= r["live_bytes"], r
        assert r["cum_allocs"] >= r["cum_frees"] or \
            r["live_objects"] == 0, r


def test_selftest_balances_under_concurrency():
    # 4 churner threads + a concurrent snapshot/report reader; the C
    # side asserts exact live/cum balance on the selftest subsystem
    assert native.res_selftest(4, 300) == 0


def test_accounted_bytes_totals_live():
    rows = native.res_stats()
    total = sum(r["live_bytes"] for r in rows)
    acct = native.res_accounted_bytes()
    # same quantity read through two paths (cells vs the global pairs):
    # equal modulo racing allocations
    assert abs(acct - total) < max(1 << 20, total // 4), (acct, total)


def test_traffic_populates_allocator_subsystems():
    port = native.rpc_server_start(native_echo=True)
    try:
        _native_echo_traffic(port)
        rows = _res_rows()
        assert rows["iobuf.block"]["live_bytes"] > 0
        assert rows["sock.slab"]["live_bytes"] > 0
        assert rows["sched.stack"]["live_bytes"] > 0
        assert rows["cluster"]["cum_allocs"] > 0
    finally:
        native.rpc_server_stop()


# ---------------------------------------------------------------------------
# churn balance — the leak-trend detector in test form
# ---------------------------------------------------------------------------


def test_churn_balance_dial_call_close():
    """Dial/call/close N channels over SIX identical rounds and assert
    the ledger CONVERGES: releases are deferred to dispatcher wakeups
    (the ResourcePool way) and close-sweep fibers run lazily, so any
    two-point comparison races the backlog — but a real leak (say one
    channel per round) grows the live series EVERY round without bound,
    while pools and deferred releases plateau once warmed. The last
    round must not exceed the mid-series plateau."""
    port = native.rpc_server_start(native_echo=True)
    lib = native.load()
    watched = ("cluster", "srv.pyreq", "dump.spill", "iobuf.block",
               "sock.wreq", "sock.slab", "sched.stack")
    try:
        def churn_round():
            hs = []
            for _ in range(6):
                h = lib.nat_channel_open(b"127.0.0.1", port, 0, 0, 0, 0)
                hs.append(h)
            for h in hs:
                resp = ctypes.c_char_p()
                rlen = ctypes.c_size_t(0)
                err = ctypes.c_char_p()
                for _ in range(10):
                    rc = lib.nat_channel_call(
                        h, b"EchoService", b"Echo", b"m" * 600, 600,
                        3000, ctypes.byref(resp), ctypes.byref(rlen),
                        ctypes.byref(err))
                    assert rc == 0
                    if resp:
                        lib.nat_buf_free(resp)
                        resp = ctypes.c_char_p()
                    if err:
                        lib.nat_buf_free(err)
                        err = ctypes.c_char_p()
            for h in hs:
                lib.nat_channel_close(h)

        def drain(deadline_s=30.0):
            # deferred releases complete on dispatcher wakeups over the
            # seconds after the mutual EOFs; poll until the transient
            # subsystems stop moving (two settled polls)
            prev = None
            end = time.time() + deadline_s
            while time.time() < end:
                time.sleep(1.0)
                rows = _res_rows()
                cur = tuple(rows[s]["live_objects"]
                            for s in ("cluster", "srv.pyreq",
                                      "dump.spill"))
                if cur == prev:
                    return rows
                prev = cur
            return _res_rows()

        series = []
        for _ in range(6):
            churn_round()
            rows = drain()
            series.append({s: (rows[s]["live_objects"],
                               rows[s]["live_bytes"]) for s in watched})
        rows = drain()
        # transient subsystems fully drain: every channel/slab/request
        # the six rounds allocated was released (a leak of even one
        # object per round would leave >= 6 here)
        for sub in ("cluster", "srv.pyreq", "dump.spill"):
            assert rows[sub]["live_objects"] <= 4, (sub, rows[sub],
                                                    series)
        # pooled subsystems plateau: the last round must not exceed the
        # mid-series high-water (pools warm, then stop growing)
        for sub in ("iobuf.block", "sock.wreq", "sock.slab",
                    "sched.stack"):
            plateau = max(series[i][sub][1] for i in (2, 3, 4))
            assert series[-1][sub][1] <= plateau + 8 * 8248, \
                (sub, series)
    finally:
        native.rpc_server_stop()


@pytest.mark.slow
def test_churn_balance_fabric_leases():
    """Tensor-fabric lease churn (ISSUE 15): six rounds of push -> take
    -> (held, then out-of-order released) leases leave the shm.span
    ledger row exactly balanced, and while leases are held the row
    carries exactly the leased payload bytes — the zero-copy structural
    contract (payload bytes accounted once per transfer)."""
    lib = native.load()
    lib.nat_shm_lane_enable(0)
    assert lib.nat_shm_lane_create(1 << 20) == 0
    assert lib.nat_shm_producer_attach(lib.nat_shm_lane_name()) >= 0
    base = _res_rows()["shm.span"]
    payload = b"t" * (64 << 10)
    for _ in range(6):
        held = []
        for i in range(4):
            assert lib.nat_shm_fabric_push(payload, len(payload), i) == 0
            lease = native.fabric_take(2000)
            assert lease is not None
            held.append(lease)
        row = _res_rows()["shm.span"]
        assert row["live_bytes"] - base["live_bytes"] \
            == 4 * len(payload)
        assert row["live_objects"] - base["live_objects"] == 4
        for lease in reversed(held):  # out-of-order vs take order
            lease.release()
        row = _res_rows()["shm.span"]
        assert row["live_bytes"] == base["live_bytes"]
        assert row["live_objects"] == base["live_objects"]
    final = _res_rows()["shm.span"]
    assert final["cum_allocs"] - base["cum_allocs"] == 24
    assert final["cum_frees"] - base["cum_frees"] == 24


def test_churn_balance_shm_worker_sigkill_recover():
    """The shm half of the churn-balance contract: a worker SIGKILLed
    mid-request is recovered (fence probe, arena scrub, slot reap) and
    the transient subsystems return to balance — recovery must not leak
    PyRequests or span contexts, and no new segment may appear."""
    pytest.importorskip("grpc")
    import grpc

    from tests.shm_worker_factory import make_slow

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=1,
        py_worker_factory="tests.shm_worker_factory:make_slow"))
    for s in make_slow():
        srv.add_service(s)
    assert srv.start("127.0.0.1:0") == 0
    lib = native.load()
    lib.nat_shm_lane_set_timeout_ms(30000)
    try:
        port = srv.listen_endpoint.port
        chan = grpc.insecure_channel(f"127.0.0.1:{port}")
        call = chan.unary_unary(
            "/EchoService/Echo",
            request_serializer=echo_pb2.EchoRequest.SerializeToString,
            response_deserializer=echo_pb2.EchoResponse.FromString)
        call(echo_pb2.EchoRequest(message="warm"), timeout=20)
        time.sleep(0.2)
        before = _res_rows()
        fut = call.future(echo_pb2.EchoRequest(message="boom"),
                          timeout=25)
        time.sleep(0.15)  # worker consumed it, parked in usercode
        victim = srv._native_mount._shm_workers[0]
        victim.kill()
        victim.wait(timeout=5)
        try:
            fut.result(timeout=20)
        except grpc.RpcError as e:
            assert e.code() == grpc.StatusCode.UNAVAILABLE, e
        # server keeps serving (in-process fallback)
        deadline = time.time() + 15
        ok = 0
        while time.time() < deadline and ok < 3:
            try:
                r = call(echo_pb2.EchoRequest(message="after"),
                         timeout=5)
                ok += 1 if r.message.startswith("after@") else 0
            except Exception:
                time.sleep(0.2)
        assert ok >= 3
        time.sleep(0.5)
        after = _res_rows()
        # recovery leaked nothing transient; the mapped segments are
        # untouched (recovery scrubs arenas in place, never remaps)
        assert after["srv.pyreq"]["live_objects"] <= \
            before["srv.pyreq"]["live_objects"] + 2, (before, after)
        assert after["shm.seg"]["live_bytes"] == \
            before["shm.seg"]["live_bytes"], (before, after)
        chan.close()
    finally:
        lib.nat_shm_lane_set_timeout_ms(2000)
        srv.stop()


# ---------------------------------------------------------------------------
# connection-scale drill (the 20k lane's test-sized twin)
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_conn_scale_drill_small():
    """The bench.py conn_scale lane at test size: every connection
    accepted AND answered through the accept storm, zero failed RPCs on
    the live subset, per-connection cost recorded from the accounting,
    and no transient-subsystem leak after teardown."""
    from brpc_tpu.bench import conn_scale_bench

    out = conn_scale_bench(target_conns=240, client_procs=2, idle_s=1.0)
    assert out, "lane disabled?"
    assert out.get("conn_scale_error") is None, out
    assert out["conn_scale_conns"] == 240, out
    assert out["conn_scale_failed"] == 0
    assert out["conn_live_failed"] == 0 and out["conn_live_ok"] > 0
    assert out["conn_per_conn_bytes"] > 0
    assert out["conn_accept_storm_s"] > 0
    assert out["conn_per_conn_fds"] == pytest.approx(1.0, abs=0.3)
    assert out["conn_balance_leaked"] == {}
    assert "sock.slab" in out["conn_mem_by_subsystem"]


# ---------------------------------------------------------------------------
# /heap/native + /growth/native + /status + /connections
# ---------------------------------------------------------------------------


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def console():
    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    nport = native.rpc_server_start(native_echo=True)
    yield srv, nport
    native.rpc_server_stop()
    srv.stop()


def _get(srv, path):
    conn = http.client.HTTPConnection(
        "127.0.0.1", srv.listen_endpoint.port, timeout=15)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    headers = dict(r.getheaders())
    conn.close()
    return r.status, body, headers


def test_heap_native_page_end_to_end(console):
    srv, nport = console
    status, body, _ = _get(srv, "/heap/native")  # arms the tracker
    assert status == 200 and "# nat_res heap:" in body
    _native_echo_traffic(nport, n=40, payload=b"z" * 3000)
    status, body, _ = _get(srv, "/heap/native")
    assert status == 200
    # collapsed stacks with the synthesized subsystem leaf
    assert "res:" in body, body[:400]
    status, flat, _ = _get(srv, "/heap/native?flat=1")
    assert status == 200 and "flat live bytes by leaf" in flat


def test_growth_native_page_windows(console):
    srv, nport = console
    _get(srv, "/heap/native")  # ensure armed
    status, body, _ = _get(srv, "/growth/native")
    assert status == 200 and "# nat_res growth:" in body
    # a bounded window: re-baseline, churn while it watches, report
    done = threading.Event()

    def churn():
        _native_echo_traffic(nport, n=30, payload=b"g" * 2000)
        done.set()

    t = threading.Thread(target=churn)
    t.start()
    status, body, _ = _get(srv, "/growth/native?seconds=1.0")
    t.join()
    assert status == 200 and "# nat_res growth:" in body


def test_heap_growth_python_pages(console):
    srv, _ = console
    status, body, _ = _get(srv, "/heap")
    assert status == 200 and "heap profile" in body
    status, body, _ = _get(srv, "/growth")
    assert status == 200 and "growth profile" in body


def test_heap_native_one_window_503():
    """The shared one-window guard: while one /heap/native or
    /growth/native window runs, the second gets 503 + Retry-After
    derived from the RUNNING window's remaining time."""
    from brpc_tpu.builtin import hotspots

    started = threading.Event()
    release = threading.Event()

    def long_window(_s):
        started.set()
        release.wait(timeout=10)
        return "done\n"

    results = []
    t = threading.Thread(
        target=lambda: results.append(
            hotspots._res_prof_window.run(5.0, long_window)))
    t.start()
    assert started.wait(timeout=5)
    second = hotspots._res_prof_window.run(1.0, lambda s: "nope\n")
    release.set()
    t.join()
    assert second[0] == 503
    assert "busy" in second[2]
    assert int(second[3]["Retry-After"]) >= 1


def test_status_rss_reconciliation_line(console):
    srv, nport = console
    _native_echo_traffic(nport, n=5)
    status, body, _ = _get(srv, "/status")
    assert status == 200
    assert "nat_mem: accounted=" in body, body
    assert "rss_delta_since_native_load=" in body
    assert "nat_mem subsystems:" in body


def test_connections_memory_column(console):
    srv, nport = console
    lib = native.load()
    h = lib.nat_channel_open(b"127.0.0.1", nport, 0, 0, 0, 0)
    try:
        _native_echo_traffic(nport, n=3)
        rows = native.conn_snapshot()
        assert rows, "no native sockets visible"
        assert all("mem_bytes" in r for r in rows)
        status, body, _ = _get(srv, "/connections")
        assert status == 200
        assert "mem_bytes" in body
        assert "native socket buffered memory:" in body
    finally:
        lib.nat_channel_close(h)


def test_metrics_drift_every_nat_mem_row(console):
    """ISSUE 14 drift satellite: every subsystem enum must surface as a
    labeled row in every nat_mem_* Prometheus family — a subsystem
    added to nat_res.h without its ledger rows is drift, not a choice
    (mirrors the counter-enum drift tests)."""
    from brpc_tpu import bvar
    from brpc_tpu.bvar.native_vars import register_native_bvars

    assert register_native_bvars()
    dump = bvar.dump_prometheus()
    names = native.res_names()
    assert len(names) == len(set(names))  # label values must be unique
    for fam in ("nat_mem_live_bytes", "nat_mem_live_objects",
                "nat_mem_cum_allocs", "nat_mem_cum_frees",
                "nat_mem_hwm_bytes"):
        for sub in names:
            row = f'{fam}{{subsystem="{sub}"}}'
            assert row in dump, f"missing {row}"
    # the per-connection memory column rides /brpc_metrics too
    assert "nat_mem_live_bytes" in dump
