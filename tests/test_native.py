"""Native core tests — C++ selftests surfaced through ctypes, scheduler
correctness probes, and Python↔native wire interop over the tpu_std
framing (the conditional-hardware-test pattern of SURVEY.md section 4:
skipped cleanly when the toolchain is absent).
"""
import pytest

native = pytest.importorskip("brpc_tpu.native")

if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


@pytest.fixture(scope="module", autouse=True)
def sched():
    native.sched_start(4)
    yield
    # scheduler is shared/global; leave running for other native users


def test_wsq_selftest():
    assert native.load().nat_wsq_selftest() == 0


def test_iobuf_selftest():
    assert native.load().nat_iobuf_selftest() == 0


def test_meta_selftest():
    assert native.load().nat_meta_selftest() == 0


def test_spawn_join_counts():
    assert native.bench_spawn_join(8, 1000) == 8000
    assert native.bench_spawn_join(50, 100) == 5000


def test_ping_pong_runs():
    ns = native.bench_ping_pong(2000)
    assert ns > 0
    # generous sanity bound: a fiber round trip must beat 1ms by far
    assert ns < 1_000_000


def test_switch_counter_advances():
    before = native.load().nat_sched_switches()
    native.bench_spawn_join(4, 100)
    assert native.load().nat_sched_switches() > before


class TestEchoInterop:
    """Native server, Python client — proves the native runtime speaks the
    same tpu_std wire format."""

    @pytest.fixture(scope="class")
    def native_port(self):
        port = native.echo_server_start()
        yield port
        native.echo_server_stop()

    def test_python_client_native_server(self, native_port):
        from brpc_tpu import rpc
        from brpc_tpu.rpc.proto import echo_pb2

        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=3000))
        assert ch.init(f"127.0.0.1:{native_port}") == 0
        for i in range(10):
            cntl, resp = ch.call(
                "EchoService.Echo", echo_pb2.EchoRequest(message=f"n{i}"),
                echo_pb2.EchoResponse,
            )
            assert not cntl.failed(), cntl.error_text
            assert resp.message == f"n{i}"

    def test_attachment_roundtrip(self, native_port):
        from brpc_tpu import rpc
        from brpc_tpu.rpc.proto import echo_pb2

        ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=3000))
        assert ch.init(f"127.0.0.1:{native_port}") == 0
        cntl = rpc.Controller()
        cntl.request_attachment.append(b"att-bytes" * 10)
        resp = echo_pb2.EchoResponse()
        ch.call_method("EchoService.Echo", cntl,
                       echo_pb2.EchoRequest(message="a"), resp)
        assert not cntl.failed(), cntl.error_text
        # native echo returns payload+attachment concatenated in the body;
        # the response parse keeps the pb payload and the rest is attachment
        assert cntl.response_attachment.to_bytes() == b"att-bytes" * 10

    def test_native_client_bench_runs(self, native_port):
        stats = native.echo_client_bench("127.0.0.1", native_port,
                                         nconn=2, seconds=0.5, pipeline=8)
        assert stats["requests"] > 0
        assert stats["qps"] > 1000  # native floor, generous
