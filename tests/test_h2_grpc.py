"""h2/gRPC tests — brpc_grpc_protocol_unittest / http2 unittest shapes:
frame+grpc codec units, unary calls over h2, error mapping through
grpc-status trailers, timeout propagation, concurrent streams on one
connection.
"""
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.h2_protocol import (
    GRPC_DEADLINE_EXCEEDED,
    GRPC_UNIMPLEMENTED,
    error_to_grpc_status,
    grpc_status_to_error,
    grpc_unwrap,
    grpc_wrap,
    pack_frame,
    _parse_grpc_timeout,
)
from brpc_tpu.rpc.proto import echo_pb2


def test_grpc_frame_roundtrip():
    msg = b"payload-bytes"
    wrapped = grpc_wrap(msg)
    assert wrapped[0] == 0 and len(wrapped) == 5 + len(msg)
    assert grpc_unwrap(wrapped) == msg
    assert grpc_unwrap(b"\x00\x00\x00") is None


def test_frame_header_layout():
    f = pack_frame(0x1, 0x5, 7, b"abc")
    assert f[:3] == b"\x00\x00\x03"  # 24-bit length
    assert f[3] == 0x1 and f[4] == 0x5
    assert f[5:9] == b"\x00\x00\x00\x07"


def test_status_mapping():
    assert error_to_grpc_status(0) == 0
    assert error_to_grpc_status(errors.ERPCTIMEDOUT) == GRPC_DEADLINE_EXCEEDED
    assert error_to_grpc_status(errors.ENOMETHOD) == GRPC_UNIMPLEMENTED
    assert grpc_status_to_error(GRPC_DEADLINE_EXCEEDED) == errors.ERPCTIMEDOUT
    assert grpc_status_to_error(99) == errors.EINVAL


def test_grpc_timeout_parse():
    assert _parse_grpc_timeout("100m") == 100.0
    assert _parse_grpc_timeout("2S") == 2000.0
    assert _parse_grpc_timeout("1M") == 60000.0


class GrpcEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        if request.code:
            cntl.set_failed(request.code, "requested failure")
            done()
            return
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def grpc_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(GrpcEcho())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


@pytest.fixture(scope="module")
def grpc_channel(grpc_server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="h2:grpc",
                                        timeout_ms=3000))
    assert ch.init(str(grpc_server.listen_endpoint)) == 0
    return ch


def test_unary_call(grpc_channel):
    cntl, resp = grpc_channel.call(
        "EchoService.Echo", echo_pb2.EchoRequest(message="grpc-hello"),
        echo_pb2.EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "grpc-hello"


def test_many_sequential_on_one_connection(grpc_channel):
    for i in range(20):
        cntl, resp = grpc_channel.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message=f"s{i}"),
            echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == f"s{i}"


def test_concurrent_streams(grpc_channel):
    n = 10
    failures = []
    lock = threading.Lock()

    def one(i):
        cntl, resp = grpc_channel.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message=f"c{i}"),
            echo_pb2.EchoResponse, timeout_ms=5000)
        with lock:
            if cntl.failed() or resp.message != f"c{i}":
                failures.append((i, cntl.error_text))

    threads = [threading.Thread(target=one, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(15)
    assert not failures, failures


def test_error_maps_through_trailers(grpc_channel):
    cntl, _ = grpc_channel.call(
        "EchoService.Echo",
        echo_pb2.EchoRequest(message="x", code=errors.ELIMIT),
        echo_pb2.EchoResponse)
    assert cntl.failed()
    # ELIMIT -> RESOURCE_EXHAUSTED -> back to ELIMIT
    assert cntl.error_code == errors.ELIMIT
    assert "requested failure" in cntl.error_text


def test_unknown_method_is_unimplemented(grpc_channel):
    cntl, _ = grpc_channel.call(
        "EchoService.Nope", echo_pb2.EchoRequest(message="x"),
        echo_pb2.EchoResponse)
    assert cntl.error_code == errors.ENOMETHOD


def test_deadline_exceeded(grpc_channel):
    cntl, _ = grpc_channel.call(
        "EchoService.Echo",
        echo_pb2.EchoRequest(message="slow", sleep_us=500_000),
        echo_pb2.EchoResponse, timeout_ms=80)
    assert cntl.error_code == errors.ERPCTIMEDOUT


def test_larger_payload(grpc_channel):
    big = "g" * 200_000  # spans multiple DATA frames server->client
    cntl, resp = grpc_channel.call(
        "EchoService.Echo", echo_pb2.EchoRequest(message=big),
        echo_pb2.EchoResponse, timeout_ms=10000)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == big


def test_hpack_rejects_truncated_string():
    """RFC 7541: a declared string length past the block end is a decode
    error, not a silently-short header."""
    from brpc_tpu.rpc.hpack import decode_str, encode_int

    blob = encode_int(10, 7, 0x00) + b"abc"  # says 10 bytes, has 3
    with pytest.raises(ValueError):
        decode_str(blob, 0)


def test_hpack_rejects_bad_huffman_padding():
    """RFC 7541 5.2: trailing padding must be the all-ones EOS prefix."""
    from brpc_tpu.rpc.hpack import huffman_decode, huffman_encode

    good = huffman_encode(b"www.example.com")
    assert huffman_decode(good) == b"www.example.com"
    # 'a' = 5-bit code 00011 -> 3 zero padding bits: invalid
    with pytest.raises(ValueError):
        huffman_decode(bytes([0b00011_000]))
