"""One port, every protocol — brpc's signature multi-protocol port
(server.cpp:576): the same server simultaneously answers tpu_std RPC,
JSON-over-HTTP, gRPC-over-h2, redis, memcache and framed thrift."""
import http.client
import json
import socket as pysocket

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.memcache import MemcacheRequest, MemcacheResponse, MemcacheService
from brpc_tpu.rpc.redis import DictRedisService, RedisRequest, RedisResponse, encode_command
from brpc_tpu.rpc.thrift import T_STRING, ThriftMessage, ThriftService
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module", params=[False, True],
                ids=["python_port", "native_port"])
def omni_server(request):
    """Both runtimes must keep the one-port-all-protocols capability: the
    Python port natively, the native port via its tpu_std fast path plus
    the raw fallback lane feeding the Python protocol stack."""
    use_native = request.param
    if use_native:
        from brpc_tpu import native

        if not native.available():
            pytest.skip("native toolchain unavailable")
    tsvc = ThriftService()
    tsvc.add_method("Echo", lambda body: {0: body.get(1, (T_STRING, b""))})
    srv = rpc.Server(rpc.ServerOptions(
        num_threads=4,
        redis_service=DictRedisService(),
        memcache_service=MemcacheService(),
        thrift_service=tsvc,
        use_native_runtime=use_native,
    ))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_all_protocols_on_one_port(omni_server):
    ep = str(omni_server.listen_endpoint)
    port = omni_server.listen_endpoint.port

    # 1. tpu_std
    ch = rpc.Channel()
    assert ch.init(ep) == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="std"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    assert not cntl.failed() and resp.message == "std"

    # 2. HTTP JSON
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    conn.request("POST", "/EchoService/Echo",
                 body=json.dumps({"message": "http"}),
                 headers={"Content-Type": "application/json"})
    assert json.loads(conn.getresponse().read())["message"] == "http"
    conn.close()

    # 3. gRPC over h2
    gch = rpc.Channel(rpc.ChannelOptions(protocol="h2:grpc",
                                         timeout_ms=3000))
    assert gch.init(ep) == 0
    cntl, resp = gch.call("EchoService.Echo",
                          echo_pb2.EchoRequest(message="grpc"),
                          echo_pb2.EchoResponse)
    assert not cntl.failed() and resp.message == "grpc"

    # 4. redis (raw RESP like redis-cli)
    s = pysocket.create_connection(("127.0.0.1", port), timeout=5)
    s.sendall(encode_command(("PING",)))
    assert s.recv(64) == b"+PONG\r\n"
    s.close()

    # 5. memcache binary
    mch = rpc.Channel(rpc.ChannelOptions(protocol="memcache",
                                         timeout_ms=3000))
    assert mch.init(ep) == 0
    mresp = MemcacheResponse()
    mcntl = rpc.Controller()
    mch.call_method("memcache", mcntl,
                    MemcacheRequest().set("k", "v").get("k"), mresp)
    assert not mcntl.failed()
    assert mresp.pop_set()
    ok, v = mresp.pop_get()
    assert ok and v == b"v"

    # 6. framed thrift
    tch = rpc.Channel(rpc.ChannelOptions(protocol="thrift",
                                         timeout_ms=3000))
    assert tch.init(ep) == 0
    tresp = ThriftMessage()
    tcntl = rpc.Controller()
    tch.call_method("thrift", tcntl,
                    ThriftMessage("Echo", {1: (T_STRING, b"th")}), tresp)
    assert not tcntl.failed(), tcntl.error_text
    assert tresp.body[0][1] == b"th"
