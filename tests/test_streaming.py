"""Streaming RPC tests — shaped after brpc_streaming_rpc_unittest.cpp /
example/streaming_echo_c++: setup piggybacked on an RPC, ordered delivery,
window flow control, close propagation (SURVEY.md section 2.8).
"""
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.proto import echo_pb2


class Collector(rpc.StreamInputHandler):
    def __init__(self):
        self.chunks = []
        self.closed = threading.Event()
        self.lock = threading.Lock()

    def on_received_messages(self, stream, messages):
        with self.lock:
            for m in messages:
                self.chunks.append(m.to_bytes())

    def on_closed(self, stream):
        self.closed.set()


class StreamEchoService(rpc.Service):
    """Accepts a stream and echoes every chunk back on it."""

    def __init__(self):
        self.server_streams = []

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def OpenStream(self, cntl, request, response, done):
        outer = self

        class EchoBack(rpc.StreamInputHandler):
            def on_received_messages(self, stream, messages):
                for m in messages:
                    stream.write(m)

            def on_closed(self, stream):
                pass

        s = rpc.stream_accept(cntl, rpc.StreamOptions(handler=EchoBack()))
        if s is None:
            cntl.set_failed(errors.EINVAL, "no stream in request")
        else:
            outer.server_streams.append(s)
        response.message = "stream accepted"
        done()


@pytest.fixture(scope="module")
def stream_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    svc = StreamEchoService()
    srv.add_service(svc)
    assert srv.start("127.0.0.1:0") == 0
    yield srv, svc
    srv.stop()


def _open_stream(server, handler, **opts):
    ch = rpc.Channel()
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl = rpc.Controller()
    cntl.timeout_ms = 3000
    stream = rpc.stream_create(
        cntl, rpc.StreamOptions(handler=handler, **opts))
    resp = echo_pb2.EchoResponse()
    ch.call_method("StreamEchoService.OpenStream", cntl,
                   echo_pb2.EchoRequest(message="open"), resp)
    assert not cntl.failed(), cntl.error_text
    assert stream.wait_connected(3)
    return ch, stream


def test_stream_setup_and_echo(stream_server):
    srv, _ = stream_server
    col = Collector()
    ch, stream = _open_stream(srv, col)
    for i in range(10):
        assert stream.write(f"chunk-{i}".encode()) == 0
    deadline = time.monotonic() + 5
    while len(col.chunks) < 10 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert col.chunks == [f"chunk-{i}".encode() for i in range(10)]  # ordered
    stream.close()


def test_stream_large_transfer(stream_server):
    srv, _ = stream_server
    col = Collector()
    ch, stream = _open_stream(srv, col)
    payload = b"x" * 100_000
    n = 30  # 3MB total > default 2MB window: exercises feedback
    for _ in range(n):
        assert stream.write(payload, timeout_s=10) == 0
    deadline = time.monotonic() + 10
    while len(col.chunks) < n and time.monotonic() < deadline:
        time.sleep(0.02)
    assert len(col.chunks) == n
    assert all(c == payload for c in col.chunks)
    stream.close()


def test_stream_window_blocks_without_consumer(stream_server):
    srv, svc = stream_server
    col = Collector()
    ch, stream = _open_stream(srv, col, max_buf_size=64 * 1024)
    # fill beyond the window with a tiny timeout: must hit EOVERCROWDED
    rc = 0
    for _ in range(200):
        rc = stream.write(b"y" * 8192, timeout_s=0.05)
        if rc != 0:
            break
    # either the remote consumed fast enough (all ok) or we got flow-control
    # pushback; with echo-back traffic both directions share the window, so
    # pushback is the expected outcome here
    assert rc in (0, errors.EOVERCROWDED)
    stream.close()


def test_stream_close_propagates(stream_server):
    srv, svc = stream_server
    col = Collector()
    ch, stream = _open_stream(srv, col)
    server_stream = svc.server_streams[-1]
    stream.close()
    deadline = time.monotonic() + 5
    while not server_stream.closed and time.monotonic() < deadline:
        time.sleep(0.01)
    assert server_stream.closed


def test_stream_write_after_close_fails(stream_server):
    srv, _ = stream_server
    col = Collector()
    ch, stream = _open_stream(srv, col)
    stream.close()
    assert stream.write(b"late") == errors.EEOF


def test_no_stream_accept_without_request_stream(stream_server):
    srv, _ = stream_server
    ch = rpc.Channel()
    assert ch.init(str(srv.listen_endpoint)) == 0
    cntl, resp = ch.call("StreamEchoService.OpenStream",
                         echo_pb2.EchoRequest(message="nostream"),
                         echo_pb2.EchoResponse, timeout_ms=3000)
    assert cntl.failed()
    assert cntl.error_code == errors.EINVAL
