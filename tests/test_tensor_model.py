"""Tensor-layer tests on the virtual 8-device CPU mesh (conftest.py).

Mirrors the reference's strategy (SURVEY.md section 4): distributed behavior
exercised with many in-process devices — SPMD output must match the
single-device path bit-for-bit-ish (fp32 tolerance).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu.tensor.config import MeshSpec, ModelConfig
from brpc_tpu.tensor.model import (
    Params,
    forward_local,
    init_params,
    make_spmd_forward,
    make_spmd_train_step,
)
from brpc_tpu.tensor.ring_attention import local_attention, ring_attention


# expert_capacity_factor == n_experts guarantees zero token drops, so the
# sharded MoE (per-device routing, smaller local capacity) is exactly
# equivalent to the local path.
CFG = ModelConfig(
    vocab=64, d_model=32, n_heads=4, d_head=8, d_ff=32, n_layers=1,
    n_experts=4, expert_capacity_factor=4.0, dtype="float32",
)


def test_eight_devices_available():
    assert len(jax.devices()) == 8


def test_ring_attention_matches_local():
    from jax.sharding import Mesh, PartitionSpec as P

    B, T, H, Dh = 2, 32, 4, 8
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (B, T, H, Dh), dtype=jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    expect = local_attention(q, k, v, causal=True)

    mesh = Mesh(np.array(jax.devices()[:4]), ("sp",))
    from brpc_tpu.jaxcompat import shard_map

    ring = shard_map(
        lambda q, k, v: ring_attention(q, k, v, "sp", causal=True),
        mesh=mesh,
        in_specs=(P(None, "sp"), P(None, "sp"), P(None, "sp")),
        out_specs=P(None, "sp"),
        check=False,
    )
    got = jax.jit(ring)(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=2e-5)


def test_forward_local_shapes_and_finite():
    params = init_params(CFG, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, CFG.vocab)
    logits = jax.jit(lambda p, t: forward_local(p, t, CFG))(params, tokens)
    assert logits.shape == (2, 16, CFG.vocab)
    assert bool(jnp.isfinite(logits).all())


@pytest.mark.parametrize(
    "spec",
    [
        MeshSpec(dp=2, tp=2, sp=2),  # 8 devices
        MeshSpec(dp=2, pp=2, ep=2),
        MeshSpec(pp=2, tp=2, sp=2),
    ],
    ids=["dp-tp-sp", "dp-pp-ep", "pp-tp-sp"],
)
def test_spmd_forward_matches_local(spec):
    params = init_params(CFG, jax.random.PRNGKey(0), pp_stages=spec.pp)
    batch = spec.dp * 2
    seq = spec.sp * 8
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, CFG.vocab)
    expect = forward_local(params, tokens, CFG)
    _, fwd = make_spmd_forward(CFG, spec, n_microbatches=1)
    got = fwd(params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expect), atol=3e-4)


def test_spmd_train_step_decreases_loss():
    spec = MeshSpec(dp=2, pp=2, tp=2)
    cfg = CFG
    mesh, step = make_spmd_train_step(cfg, spec, n_microbatches=2, lr=0.1)
    params = init_params(cfg, jax.random.PRNGKey(0), pp_stages=spec.pp)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab)
    labels = jnp.roll(tokens, -1, axis=1)
    loss0, params = step(params, tokens, labels)
    loss = loss0
    for _ in range(5):
        loss, params = step(params, tokens, labels)
    assert bool(jnp.isfinite(loss0)) and bool(jnp.isfinite(loss))
    assert float(loss) < float(loss0)


def test_graft_entry_contract():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[0] == args[1].shape[0]
    ge.dryrun_multichip(8)
