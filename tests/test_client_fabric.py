"""Client-fabric tests — LB/NS/failover/circuit-breaker/limiters, shaped
after brpc_load_balancer_unittest.cpp and brpc_naming_service_unittest.cpp:
many in-process servers, list:// and file:// naming doubling as fixtures
(SURVEY.md section 4).
"""
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.circuit_breaker import CircuitBreaker
from brpc_tpu.rpc.concurrency_limiter import (
    AutoLimiter,
    TimeoutLimiter,
    create_concurrency_limiter,
)
from brpc_tpu.rpc.load_balancer import create_load_balancer
from brpc_tpu.rpc.proto import echo_pb2


class NamedEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    def __init__(self, name="srv"):
        self.name = name
        self.hits = 0

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        self.hits += 1
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        response.message = f"{self.name}:{request.message}"
        done()


def _start_server(name):
    svc = NamedEcho(name)
    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    srv.add_service(svc)
    assert srv.start("127.0.0.1:0") == 0
    return srv, svc


@pytest.fixture(scope="module")
def cluster():
    servers = [_start_server(f"s{i}") for i in range(3)]
    yield servers
    for srv, _ in servers:
        srv.stop()


def _cluster_url(servers):
    return "list://" + ",".join(str(s.listen_endpoint) for s, _ in servers)


def test_round_robin_spreads(cluster):
    ch = rpc.Channel()
    assert ch.init(_cluster_url(cluster), "rr") == 0
    replies = set()
    for i in range(12):
        cntl, resp = ch.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message="x"),
            echo_pb2.EchoResponse, timeout_ms=3000,
        )
        assert not cntl.failed(), cntl.error_text
        replies.add(resp.message.split(":")[0])
    assert replies == {"s0", "s1", "s2"}


def test_random_lb_works(cluster):
    ch = rpc.Channel()
    assert ch.init(_cluster_url(cluster), "random") == 0
    for _ in range(6):
        cntl, resp = ch.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message="r"),
            echo_pb2.EchoResponse, timeout_ms=3000,
        )
        assert not cntl.failed(), cntl.error_text


def test_locality_aware_lb(cluster):
    ch = rpc.Channel()
    assert ch.init(_cluster_url(cluster), "la") == 0
    for _ in range(9):
        cntl, resp = ch.call(
            "EchoService.Echo", echo_pb2.EchoRequest(message="la"),
            echo_pb2.EchoResponse, timeout_ms=3000,
        )
        assert not cntl.failed(), cntl.error_text


def test_consistent_hash_stability(cluster):
    ch = rpc.Channel()
    assert ch.init(_cluster_url(cluster), "c_murmurhash") == 0
    lb = ch._lb
    # same request_code must pick the same node every time
    picks = {lb.select_server(request_code=12345) for _ in range(20)}
    assert len(picks) == 1
    # different codes spread over multiple nodes
    spread = {lb.select_server(request_code=c) for c in range(200)}
    assert len(spread) >= 2


def test_weighted_round_robin():
    lb = create_load_balancer("wrr")
    from brpc_tpu.rpc.socket import Socket

    sids = [Socket.create() for _ in range(2)]
    # make them addressable + healthy-looking (no fd needed for selection)
    lb.add_server(sids[0], weight=3)
    lb.add_server(sids[1], weight=1)
    picks = [lb.select_server() for _ in range(40)]
    c0, c1 = picks.count(sids[0]), picks.count(sids[1])
    assert c0 == 30 and c1 == 10


def test_failover_on_server_death(cluster):
    servers = [_start_server(f"d{i}") for i in range(2)]
    try:
        ch = rpc.Channel(rpc.ChannelOptions(max_retry=2))
        assert ch.init(_cluster_url(servers), "rr") == 0
        # warm: both reachable
        for _ in range(4):
            cntl, _ = ch.call("EchoService.Echo",
                              echo_pb2.EchoRequest(message="w"),
                              echo_pb2.EchoResponse, timeout_ms=3000)
            assert not cntl.failed(), cntl.error_text
        # kill one server; calls must keep succeeding via the other
        servers[0][0].stop()
        ok = 0
        for _ in range(8):
            cntl, resp = ch.call("EchoService.Echo",
                                 echo_pb2.EchoRequest(message="f"),
                                 echo_pb2.EchoResponse, timeout_ms=3000)
            if not cntl.failed():
                ok += 1
                assert resp.message.startswith("d1:")
        assert ok >= 6
    finally:
        for srv, _ in servers:
            srv.stop()


def test_file_naming_service(tmp_path, cluster):
    path = tmp_path / "servers.txt"
    path.write_text("\n".join(str(s.listen_endpoint) for s, _ in cluster[:2]))
    ch = rpc.Channel()
    assert ch.init(f"file://{path}", "rr") == 0
    replies = set()
    for _ in range(6):
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="fns"),
                             echo_pb2.EchoResponse, timeout_ms=3000)
        assert not cntl.failed(), cntl.error_text
        replies.add(resp.message.split(":")[0])
    assert replies == {"s0", "s1"}
    ch._ns_thread.stop()


def test_naming_service_update_adds_and_removes(tmp_path, cluster):
    path = tmp_path / "dyn.txt"
    path.write_text(str(cluster[0][0].listen_endpoint))
    ch = rpc.Channel()
    assert ch.init(f"file://{path}", "rr") == 0
    assert ch._lb.server_count() == 1
    path.write_text("\n".join(str(s.listen_endpoint) for s, _ in cluster))
    ch._ns_thread.refresh()
    assert ch._lb.server_count() == 3
    path.write_text(str(cluster[2][0].listen_endpoint))
    ch._ns_thread.refresh()
    assert ch._lb.server_count() == 1
    ch._ns_thread.stop()


class SlowEcho(NamedEcho):
    """Sleeps server-side regardless of the request (slow node fixture)."""

    SERVICE_NAME = "EchoService"

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        time.sleep(0.6)
        response.message = f"{self.name}:{request.message}"
        done()


def test_backup_request():
    """Slow node + backup_request_ms → the backup attempt wins quickly
    (controller.cpp:1256 backup timer path)."""
    slow_srv = rpc.Server()
    slow_srv.add_service(SlowEcho("slow"))
    assert slow_srv.start("127.0.0.1:0") == 0
    fast_srv, _ = _start_server("fast")
    try:
        url = (f"list://{slow_srv.listen_endpoint},"
               f"{fast_srv.listen_endpoint}")
        ch = rpc.Channel(rpc.ChannelOptions(backup_request_ms=80,
                                            max_retry=2))
        assert ch.init(url, "rr") == 0
        got_fast_via_backup = False
        for _ in range(6):
            cntl = rpc.Controller()
            cntl.timeout_ms = 3000
            resp = echo_pb2.EchoResponse()
            ch.call_method(
                "EchoService.Echo", cntl,
                echo_pb2.EchoRequest(message="b"), resp,
            )
            if (not cntl.failed() and cntl.has_backup_request
                    and resp.message.startswith("fast:")
                    and cntl.latency_us < 550_000):
                got_fast_via_backup = True
                break
        assert got_fast_via_backup
    finally:
        slow_srv.stop()
        fast_srv.stop()


def test_max_concurrency_rejects():
    svc = NamedEcho("lim")
    srv = rpc.Server(rpc.ServerOptions(num_threads=4, max_concurrency=1))
    srv.add_service(svc)
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel()
        assert ch.init(str(srv.listen_endpoint)) == 0
        results = []
        lock = threading.Lock()

        def one():
            cntl, _ = ch.call(
                "EchoService.Echo",
                echo_pb2.EchoRequest(message="c", sleep_us=200_000),
                echo_pb2.EchoResponse, timeout_ms=3000,
            )
            with lock:
                results.append(cntl.error_code)

        ts = [threading.Thread(target=one) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(10)
        assert errors.ELIMIT in results  # some rejected
        assert 0 in results  # some served
    finally:
        srv.stop()


def test_circuit_breaker_isolates():
    cb = CircuitBreaker()
    for _ in range(200):
        cb.on_call_end(errors.EFAILEDSOCKET, 1000)
        if cb.is_broken():
            break
    assert cb.is_broken()
    assert cb.remaining_isolation_s() >= 0
    cb.reset()
    assert not cb.is_broken()
    assert cb.on_call_end(0, 1000)


def test_circuit_breaker_tolerates_low_error_rate():
    cb = CircuitBreaker()
    for i in range(500):
        code = errors.EFAILEDSOCKET if i % 100 == 0 else 0  # 1% errors
        cb.on_call_end(code, 1000)
    assert not cb.is_broken()


def test_auto_limiter_adapts():
    lim = AutoLimiter()
    assert lim.on_requested(0)
    for _ in range(50):
        lim.on_response(0, 5000)
    assert lim.max_concurrency() >= AutoLimiter.MIN_LIMIT


def test_timeout_limiter():
    lim = TimeoutLimiter(timeout_ms=100)
    for _ in range(5):
        lim.on_response(0, 60_000)  # 60ms average
    assert lim.on_requested(0)
    assert lim.on_requested(1)
    assert not lim.on_requested(5)  # 5*60ms > 100ms budget


def test_limiter_factory():
    assert create_concurrency_limiter(10).max_concurrency() == 10
    assert isinstance(create_concurrency_limiter("auto"), AutoLimiter)
    assert isinstance(create_concurrency_limiter("timeout:200"), TimeoutLimiter)
    assert create_concurrency_limiter("constant:7").max_concurrency() == 7
