"""Native Redis lane (nat_redis.cpp): RESP parsed in the native cut
loop, replies in strict command order, GET/SET family on a native store
(mode 2) or everything on the Python RedisService (mode 1, kind-6).

Parity: the fork wires redis into its io_uring datapath
(policy/redis_protocol.cpp:38,175); RedisService handler surface is
redis.h:173.
"""
import socket as pysock
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.redis import DictRedisService, RedisReply, RedisService

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


def _cmd_bytes(*args) -> bytes:
    out = b"*%d\r\n" % len(args)
    for a in args:
        a = a if isinstance(a, bytes) else str(a).encode()
        out += b"$%d\r\n%s\r\n" % (len(a), a)
    return out


def _roundtrip(sk, *args, wait=0.2) -> bytes:
    sk.sendall(_cmd_bytes(*args))
    deadline = time.time() + wait
    buf = b""
    sk.settimeout(0.05)
    while time.time() < deadline:
        try:
            chunk = sk.recv(65536)
        except (TimeoutError, pysock.timeout):
            if buf:
                break
            continue
        if not chunk:
            break
        buf += chunk
        if buf.endswith(b"\r\n"):
            break
    return buf


@pytest.fixture()
def py_redis_server():
    svc = DictRedisService()
    svc.add_command_handler(
        "upper", lambda args: RedisReply.string(args[0].upper()))
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       redis_service=svc))
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


@pytest.fixture()
def store_redis_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       redis_service=RedisService(),
                                       native_redis_store=True))
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_py_lane_commands(py_redis_server):
    port = py_redis_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        assert _roundtrip(sk, "PING") == b"+PONG\r\n"
        assert _roundtrip(sk, "SET", "k", "v1") == b"+OK\r\n"
        assert _roundtrip(sk, "GET", "k") == b"$2\r\nv1\r\n"
        assert _roundtrip(sk, "UPPER", "abc") == b"$3\r\nABC\r\n"
        assert b"ERR unknown command" in _roundtrip(sk, "NOPE")
        assert _roundtrip(sk, "INCR", "ctr") == b":1\r\n"
    finally:
        sk.close()


def test_native_store_commands(store_redis_server):
    port = store_redis_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        assert _roundtrip(sk, "SET", "k", "v") == b"+OK\r\n"
        assert _roundtrip(sk, "GET", "k") == b"$1\r\nv\r\n"
        assert _roundtrip(sk, "GET", "missing") == b"$-1\r\n"
        assert _roundtrip(sk, "EXISTS", "k", "missing") == b":1\r\n"
        assert _roundtrip(sk, "INCR", "n") == b":1\r\n"
        assert _roundtrip(sk, "INCRBY", "n", 41) == b":42\r\n"
        assert _roundtrip(sk, "DECR", "n") == b":41\r\n"
        assert _roundtrip(sk, "APPEND", "k", "22") == b":3\r\n"
        assert _roundtrip(sk, "STRLEN", "k") == b":3\r\n"
        assert _roundtrip(sk, "MSET", "a", "1", "b", "2") == b"+OK\r\n"
        assert _roundtrip(sk, "MGET", "a", "b", "zz") == \
            b"*3\r\n$1\r\n1\r\n$1\r\n2\r\n$-1\r\n"
        assert _roundtrip(sk, "DEL", "a", "b") == b":2\r\n"
        assert _roundtrip(sk, "PING", "hi") == b"$2\r\nhi\r\n"
        assert _roundtrip(sk, "FLUSHDB") == b"+OK\r\n"
        assert _roundtrip(sk, "DBSIZE") == b":0\r\n"
    finally:
        sk.close()


def test_pipelined_burst_ordering(store_redis_server):
    """One write carrying many commands: replies must come back 1:1 in
    command order."""
    port = store_redis_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        batch = b"".join(
            _cmd_bytes("SET", f"k{i}", f"v{i}") + _cmd_bytes("GET", f"k{i}")
            for i in range(50))
        sk.sendall(batch)
        want = b"".join(b"+OK\r\n$%d\r\nv%d\r\n" % (len(str(i)) + 1, i)
                        for i in range(50))
        buf = b""
        sk.settimeout(2)
        while len(buf) < len(want):
            chunk = sk.recv(65536)
            if not chunk:
                break
            buf += chunk
        assert buf == want
    finally:
        sk.close()


def test_mixed_native_py_ordering():
    """On a store server, commands alternating between slow py handlers
    and native-store execution must still reply in command order (the
    reorder window + round-end discipline)."""
    svc = RedisService()
    svc.add_command_handler(
        "slowecho",
        lambda args: (time.sleep(0.01), RedisReply.string(args[0]))[1])
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       redis_service=svc,
                                       native_redis_store=True))
    assert srv.start("127.0.0.1:0") == 0
    try:
        port = srv.listen_endpoint.port
        sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
        # burst: py(slow), native, py(slow), native — order must hold
        sk.sendall(_cmd_bytes("SLOWECHO", "a") + _cmd_bytes("SET", "x", "1")
                   + _cmd_bytes("SLOWECHO", "b") + _cmd_bytes("GET", "x"))
        want = b"$1\r\na\r\n+OK\r\n$1\r\nb\r\n$1\r\n1\r\n"
        buf = b""
        sk.settimeout(3)
        while len(buf) < len(want):
            chunk = sk.recv(65536)
            if not chunk:
                break
            buf += chunk
        assert buf == want
        sk.close()
    finally:
        srv.stop()


def test_big_bulk_value_trickle(store_redis_server):
    """A multi-MB SET value arriving in many small writes must parse
    once complete (the need_bytes copy-free wait) and echo back."""
    port = store_redis_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=10)
    try:
        val = b"x" * (4 << 20)
        cmd = _cmd_bytes("SET", "big", val)
        for i in range(0, len(cmd), 256 << 10):
            sk.sendall(cmd[i:i + (256 << 10)])
        sk.settimeout(5)

        def recv_line():
            # read to CRLF: one recv() returning a whole reply is not a
            # TCP guarantee (and the chaos lane's write:short seeds
            # split replies on purpose)
            buf = b""
            while not buf.endswith(b"\r\n"):
                chunk = sk.recv(64)
                assert chunk, f"peer closed mid-reply: {buf!r}"
                buf += chunk
            return buf

        assert recv_line() == b"+OK\r\n"
        sk.sendall(_cmd_bytes("STRLEN", "big"))
        assert recv_line() == b":%d\r\n" % len(val)
    finally:
        sk.close()


def test_incrby_rejects_garbage(store_redis_server):
    port = store_redis_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        assert b"not an integer" in _roundtrip(sk, "INCRBY", "g", "abc")
        assert _roundtrip(sk, "INCRBY", "g", "7") == b":7\r\n"
    finally:
        sk.close()


def test_short_command_on_fresh_connection(store_redis_server):
    """A complete RESP command under 12 bytes must dispatch immediately
    (the tpu_std 12-byte header wait must not swallow it)."""
    port = store_redis_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        sk.sendall(b"*1\r\n$4\r\nPING\r\n"[:11])  # "*1\r\n$4\r\nPIN"
        time.sleep(0.05)
        sk.sendall(b"G\r\n")
        sk.settimeout(2)
        # read to the reply terminator: TCP guarantees no message
        # boundaries (the chaos lane's short-write faults legitimately
        # deliver the reply one byte at a time)
        buf = b""
        while not buf.endswith(b"\r\n"):
            got = sk.recv(64)
            assert got, buf
            buf += got
        assert buf == b"+PONG\r\n"
        # genuinely sub-12-byte complete command via DBSIZE? shortest is
        # e.g. *1\r\n$1\r\n? -> unknown; use an 11-byte unknown command
        sk.sendall(b"*1\r\n$1\r\nX\r\n")
        buf = b""
        while not buf.endswith(b"\r\n"):
            got = sk.recv(256)
            assert got, buf
            buf += got
        assert buf.startswith(b"-ERR")  # answered, not hung
    finally:
        sk.close()


def test_quit_closes_connection(store_redis_server):
    port = store_redis_server.listen_endpoint.port
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        assert _roundtrip(sk, "QUIT") == b"+OK\r\n"
        sk.settimeout(2)
        assert sk.recv(64) == b""  # server closed after the reply
    finally:
        sk.close()


def test_resp_garbage_rejected(store_redis_server):
    """Hostile RESP shapes must not crash the native parser; liveness
    oracle afterwards."""
    port = store_redis_server.listen_endpoint.port
    for payload in [
        b"*9999999999\r\n",           # absurd argc
        b"*2\r\n$-5\r\nxx\r\n",       # negative bulk length
        b"*1\r\n$999999999999\r\n",   # absurd bulk length
        b"*1\r\nhello\r\n",           # non-bulk element
        b"*x\r\n",                    # non-numeric argc
    ]:
        sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
        try:
            sk.sendall(payload)
            sk.settimeout(0.3)
            try:
                sk.recv(4096)
            except (TimeoutError, pysock.timeout):
                pass
        finally:
            sk.close()
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        assert _roundtrip(sk, "PING") == b"+PONG\r\n"
    finally:
        sk.close()


def test_redis_python_client_still_works(py_redis_server):
    """The Python redis client (through Channel) must interop with the
    native lane unchanged."""
    from brpc_tpu.rpc.redis import RedisRequest, RedisResponse

    port = py_redis_server.listen_endpoint.port
    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=5000,
                                        protocol="redis"))
    assert ch.init(f"127.0.0.1:{port}") == 0
    req = RedisRequest()
    req.add_command("SET", "ck", "cv")
    req.add_command("GET", "ck")
    resp = RedisResponse()
    cntl = rpc.Controller()
    ch.call_method("redis", cntl, req, resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.reply_count == 2
    assert resp.reply(1).value == b"cv"
