"""wiretrust golden tests — the wire-input taint pass must fire.

Each violation class the pass claims to catch gets a deliberate defect
seeded into a temp tree and must be flagged: an unbounded memcpy length
from wire bytes, an unclamped wire-sized allocation, a wire integer
used as an array index, taint flowing through a helper into a sink in
the caller (interprocedural), and a wire-bounded loop with no clamp.
The allow-escape must suppress, a dominating bounds check must
suppress, the shipped tree must come back clean, and the annotation
surface must hold its breadth floor (>=6 wire sources across >=5 TUs).
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.natcheck import wiretrust  # noqa: E402


def _check(tmp_path, src, name="case.cpp"):
    p = tmp_path / name
    p.write_text(src)
    return wiretrust.check(str(tmp_path))


def _rules(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# the five golden violation classes
# ---------------------------------------------------------------------------

def test_flags_unbounded_memcpy_length(tmp_path):
    findings = _check(tmp_path, """
        void drain(const char* buf, char* dst) {
            unsigned len = NAT_WIRE(rd32(buf));
            memcpy(dst, buf + 4, len);
        }
    """)
    assert "wire-int-unbounded" in _rules(findings), findings


def test_flags_unclamped_alloc(tmp_path):
    findings = _check(tmp_path, """
        void grow(const char* buf, std::string* out) {
            unsigned long n = NAT_WIRE(rd32(buf));
            out->resize(n);
        }
    """)
    assert "wire-alloc-unclamped" in _rules(findings), findings


def test_flags_wire_array_index(tmp_path):
    findings = _check(tmp_path, """
        int pick(const char* buf, int* table) {
            unsigned idx = NAT_WIRE(buf[0]);
            return table[idx];
        }
    """)
    assert "wire-int-unbounded" in _rules(findings), findings


def test_flags_taint_through_helper(tmp_path):
    # taint enters in the caller, the SINK lives in the helper: the
    # finding needs the interprocedural summary (helper's param 0 is a
    # memcpy length) plus the call-site taint match
    findings = _check(tmp_path, """
        void helper_sink(char* dst, const char* src, unsigned n) {
            memcpy(dst, src, n);
        }
        unsigned helper_mid(unsigned v) { return v + 2; }
        void drain(const char* buf, char* dst) {
            unsigned len = NAT_WIRE(rd32(buf));
            unsigned adj = helper_mid(len);
            helper_sink(dst, buf, adj);
        }
    """)
    assert "wire-int-unbounded" in _rules(findings), findings


def test_flags_unbounded_wire_loop(tmp_path):
    findings = _check(tmp_path, """
        void walk(const char* buf, int* out) {
            unsigned count = NAT_WIRE(rd32(buf));
            for (unsigned i = 0; i < count; i++) {
                out[0] += 1;
            }
        }
    """)
    assert "wire-loop-unbounded" in _rules(findings), findings


# ---------------------------------------------------------------------------
# suppression: bounds checks and the allow escape
# ---------------------------------------------------------------------------

def test_dominating_bounds_check_suppresses(tmp_path):
    findings = _check(tmp_path, """
        void drain(const char* buf, char* dst, unsigned cap) {
            unsigned len = NAT_WIRE(rd32(buf));
            if (len > cap) return;
            memcpy(dst, buf + 4, len);
        }
    """)
    assert findings == [], findings


def test_clamp_suppresses_alloc(tmp_path):
    findings = _check(tmp_path, """
        void grow(const char* buf, std::string* out) {
            unsigned long n = NAT_WIRE(rd32(buf));
            out->resize(std::min(n, 4096ul));
        }
    """)
    assert findings == [], findings


def test_allow_escape_suppresses(tmp_path):
    findings = _check(tmp_path, """
        void drain(const char* buf, char* dst) {
            unsigned len = NAT_WIRE(rd32(buf));
            // natcheck:allow(wiretrust): dst is always 2^32 bytes
            memcpy(dst, buf + 4, len);
        }
    """)
    assert findings == [], findings


def test_comment_grammar_seeds_taint(tmp_path):
    # the comment form must work where no expression site exists
    findings = _check(tmp_path, """
        void drain(char* scan, char* dst) {
            // natcheck:wire: scan — raw bytes off the socket drain
            unsigned len = rd32(scan);
            memcpy(dst, scan + 4, len);
        }
    """)
    assert "wire-int-unbounded" in _rules(findings), findings


def test_untainted_code_is_clean(tmp_path):
    findings = _check(tmp_path, """
        void copy(char* dst, const char* src) {
            unsigned len = rd32(src);
            memcpy(dst, src + 4, len);
        }
    """)
    assert findings == [], findings


# ---------------------------------------------------------------------------
# the shipped tree and the annotation surface
# ---------------------------------------------------------------------------

def test_shipped_tree_clean():
    findings = wiretrust.run()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_wire_source_breadth_floor():
    # the annotation surface must actually cover the wire-facing
    # parsers: >=6 declared wire sources spread over >=5 TUs
    sources = wiretrust.collect_wire_sources(wiretrust.SRC_DIR)
    assert len(sources) >= 6, sources
    tus = {path for path, _line, _names in sources}
    assert len(tus) >= 5, tus
