"""Collective/mesh-channel tests on the virtual 8-device CPU mesh —
the in-process multi-"chip" pattern of SURVEY.md section 4 (fake transport
before real ICI).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brpc_tpu import parallel

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device test mesh"
)


@pytest.fixture(scope="module")
def mesh():
    return parallel.make_mesh({"dp": 8})


def test_allreduce_add(mesh):
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    out = parallel.allreduce(mesh, "dp", x, "add")
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).sum(0))


def test_allreduce_max_mean(mesh):
    x = jnp.arange(8.0).reshape(8, 1)
    assert float(parallel.allreduce(mesh, "dp", x, "max")[0]) == 7.0
    assert float(parallel.allreduce(mesh, "dp", x, "mean")[0]) == 3.5


def test_allgather(mesh):
    x = jnp.arange(8 * 3, dtype=jnp.float32).reshape(8, 3)
    out = parallel.allgather(mesh, "dp", x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x))


def test_reduce_scatter(mesh):
    x = jnp.ones((8, 16), jnp.float32)
    out = parallel.reduce_scatter(mesh, "dp", x)
    assert out.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(out), 8.0)


def test_ring_shift(mesh):
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
    out = parallel.ring_shift(mesh, "dp", x, shift=1)
    expect = np.roll(np.arange(8, dtype=np.float32), 1).reshape(8, 1)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_all_to_all(mesh):
    x = jnp.arange(8 * 8 * 2, dtype=jnp.float32).reshape(8, 8, 2)
    out = parallel.all_to_all(mesh, "dp", x)
    np.testing.assert_allclose(np.asarray(out),
                               np.swapaxes(np.asarray(x), 0, 1))


def test_mesh_channel_parallel_call(mesh):
    mc = parallel.MeshChannel(mesh, "dp")
    x = jnp.ones((8, 4), jnp.float32)
    out = mc.parallel_call(lambda s: s * 2.0, x, merger="add")
    np.testing.assert_allclose(np.asarray(out), 16.0)


def test_mesh_channel_concat_merger(mesh):
    mc = parallel.MeshChannel(mesh, "dp")
    x = jnp.arange(8.0).reshape(8, 1)
    out = mc.parallel_call(lambda s: s + 1.0, x, merger="concat")
    np.testing.assert_allclose(np.asarray(out).ravel(), np.arange(1.0, 9.0))


def test_mesh_channel_ring_call(mesh):
    mc = parallel.MeshChannel(mesh, "dp")
    x = jnp.arange(8.0).reshape(8, 1)
    out = mc.ring_call(lambda s: s * 10.0, x)
    expect = np.roll(np.arange(8.0) * 10.0, 1).reshape(8, 1)
    np.testing.assert_allclose(np.asarray(out), expect)


def test_mesh_channel_partition_call(mesh):
    mc = parallel.MeshChannel(mesh, "dp")
    x = jnp.arange(16.0).reshape(8, 2)
    out = mc.partition_call(lambda s: s.sum(axis=1, keepdims=True), x)
    np.testing.assert_allclose(
        np.asarray(out).ravel(), np.asarray(x).sum(1)
    )


def test_bandwidth_probe(mesh):
    mc = parallel.MeshChannel(mesh, "dp")
    stats = mc.bandwidth_probe(nbytes=1 << 16, iters=2)
    assert stats["axis_size"] == 8
    assert stats["allreduce_GBps"] > 0


def test_grad_merge_matches_parallel_channel_semantics(mesh):
    """DP gradient merge == ParallelChannel fan-out + add-merger
    (SURVEY.md 2.12 row 1)."""
    mc = parallel.MeshChannel(mesh, "dp")
    w = jnp.float32(2.0)

    def local_grad(batch):  # d/dw of sum(w * x) = sum(x)
        return jax.grad(lambda w_, b: (w_ * b).sum())(w, batch)

    batches = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)
    merged = mc.parallel_call(local_grad, batches, merger="add")
    np.testing.assert_allclose(float(merged), float(batches.sum()))
