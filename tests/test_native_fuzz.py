"""Fuzz the NATIVE parsers (HTTP/1.1, h2, TLS sniff, tpu_std cut loop)
with hostile bytes — these run in C++, so a parser bug is a process
crash, not an exception. After every volley the server must still answer
a well-formed request (the liveness oracle).

Deterministic seeds: failures reproduce.
"""
import json
import random
import socket
import struct

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def fuzz_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _alive(port) -> bool:
    """Liveness oracle: a clean HTTP request round-trips."""
    try:
        sk = socket.create_connection(("127.0.0.1", port), timeout=5)
        sk.settimeout(5)
        sk.sendall(b"GET /health HTTP/1.1\r\nHost: x\r\n\r\n")
        data = sk.recv(65536)
        sk.close()
        return b"200" in data
    except OSError:
        return False


def _volley(port, payloads):
    """Throw each payload on its own connection; tolerate resets."""
    for p in payloads:
        try:
            sk = socket.create_connection(("127.0.0.1", port), timeout=5)
            sk.settimeout(0.25)  # long enough to elicit a reply/reset;
            sk.sendall(p)        # the oracle, not the recv, proves health
            try:
                sk.recv(4096)
            except OSError:
                pass
            sk.close()
        except OSError:
            pass


def test_random_bytes_storm(fuzz_server):
    port = fuzz_server.listen_endpoint.port
    rng = random.Random(0xBADC0DE)
    payloads = [bytes(rng.randrange(256) for _ in range(rng.randrange(1, 600)))
                for _ in range(60)]
    _volley(port, payloads)
    assert _alive(port)


def test_http_shaped_garbage(fuzz_server):
    port = fuzz_server.listen_endpoint.port
    rng = random.Random(7)
    base = (b"POST /EchoService/Echo HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\nContent-Length: 18\r\n\r\n"
            b'{"message": "ok!"}')
    payloads = [
        b"GET " + b"/" * 70000 + b" HTTP/1.1\r\n\r\n",  # oversized header
        b"POST / HTTP/1.1\r\nContent-Length: 99999999999999999999\r\n\r\n",
        b"POST / HTTP/1.1\r\nContent-Length: -5\r\n\r\nxxxx",
        b"POST / HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
        b"ZZZ\r\njunk\r\n0\r\n\r\n",  # bad chunk size
        b"POST / HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
        b"ffffffffffffffff\r\n",  # absurd chunk size
        # absurd chunk size WITH buffered body bytes: sz near SIZE_MAX must
        # be rejected before `hdr_end + sz + 2` wraps and "passes"
        b"POST / HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
        b"ffffffffffffffff\r\nAAAABBBB\r\n0\r\n\r\n",
        b"POST / HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
        b"fffffffffffffff0\r\n" + b"C" * 64,
        b"POST / HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
        b"qq\r\nnothex\r\n",  # non-hex chunk-size line
        b"GET /\r\n\r\n",  # missing version
        b"GET  HTTP/1.1\r\n\r\n",  # missing path
        b"POST / HTTP/1.1\r\nExpect: 100-continue\r\n"
        b"Content-Length: 10\r\n\r\n",  # body never arrives
    ]
    # mutations of a valid request: bit flips + truncations
    for _ in range(40):
        b = bytearray(base)
        for _ in range(rng.randrange(1, 6)):
            b[rng.randrange(len(b))] ^= 1 << rng.randrange(8)
        payloads.append(bytes(b[:rng.randrange(1, len(b) + 1)]))
    _volley(port, payloads)
    assert _alive(port)


def test_h2_frame_garbage(fuzz_server):
    port = fuzz_server.listen_endpoint.port
    rng = random.Random(42)
    preface = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

    def frame(ftype, flags, sid, payload):
        return (struct.pack(">I", len(payload))[1:] +
                bytes([ftype, flags]) + struct.pack(">I", sid) + payload)

    payloads = [
        preface[:10],  # truncated preface
        preface + frame(1, 0x4, 1, b"\xff" * 64),  # bad HPACK
        preface + frame(1, 0x4, 1, b"\x82\x84"),   # headers w/o :path value
        preface + frame(4, 0, 0, b"\x00\x04" + b"\xff" * 4),  # huge window
        preface + frame(4, 0, 0, b"123"),          # bad SETTINGS length
        preface + frame(6, 0, 0, b"x" * 3),        # bad PING length
        preface + frame(8, 0, 0, b"\x00\x00\x00\x00"),  # zero window inc
        preface + frame(0, 0x1, 99, b"\x00\x00\x00\x00\x05hello"),  # DATA
        preface + frame(9, 0x4, 1, b"junk"),       # CONTINUATION w/o HEADERS
        preface + frame(5, 0, 2, b"push"),         # client PUSH_PROMISE
        preface + frame(1, 0x8 | 0x4, 1, b"\xf0\x01\x82"),  # padded > len
        preface + frame(0, 0x1, 0, b"\x00" * 10),   # DATA on sid 0
        preface + frame(0, 0, 7, b"\x00" * 10),     # DATA on unopened sid
        preface + frame(1, 0x4, 2, b"\x82"),        # HEADERS on even sid
        preface + frame(1, 0x4, 0, b"\x82"),        # HEADERS on sid 0
        # duplicate END_STREAM DATA on one stream (double-dispatch probe)
        preface + frame(1, 0x4, 1,
                        b"\x83\x86\x44\x01/")       # POST, scheme, :path=/
        + frame(0, 0x1, 1, b"") + frame(0, 0x1, 1, b"\x00" * 5),
    ]
    for _ in range(30):
        payloads.append(preface + bytes(
            rng.randrange(256) for _ in range(rng.randrange(9, 120))))
    _volley(port, payloads)
    assert _alive(port)


def test_tpu_std_frame_garbage(fuzz_server):
    port = fuzz_server.listen_endpoint.port
    rng = random.Random(3)
    payloads = [
        b"TRPC" + struct.pack(">II", 0xFFFFFFFF, 0),   # absurd body size
        b"TRPC" + struct.pack(">II", 8, 16),           # meta > body
        b"TRPC" + struct.pack(">II", 64, 32) + b"\xff" * 64,  # bad meta
        b"TSTR" + struct.pack(">I", 3),                # stream body < 9
        b"TSTR" + struct.pack(">I", 0xFFFFFFFF),       # stream too big
        b"TST",                                        # partial magic
    ]
    for _ in range(30):
        hdr = b"TRPC" + struct.pack(
            ">II", rng.randrange(0, 1 << 16), rng.randrange(0, 1 << 10))
        payloads.append(hdr + bytes(rng.randrange(256) for _ in
                                    range(rng.randrange(0, 200))))
    _volley(port, payloads)
    assert _alive(port)
