"""SSL/TLS tests — the brpc_ssl_unittest role: self-signed cert generated
on the fly (the test/cert1.* fixture pattern), full RPC over TLS."""
import subprocess

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def certs(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    proc = subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", cert, "-days", "1",
         "-subj", "/CN=127.0.0.1"],
        capture_output=True, timeout=60,
    )
    if proc.returncode != 0:
        pytest.skip("openssl unavailable")
    return cert, key


@pytest.fixture(scope="module")
def ssl_server(certs):
    cert, key = certs
    srv = rpc.Server(rpc.ServerOptions(num_threads=4, ssl_certfile=cert,
                                       ssl_keyfile=key))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_rpc_over_tls(ssl_server):
    ch = rpc.Channel(rpc.ChannelOptions(use_ssl=True, timeout_ms=5000,
                                        connect_timeout_ms=5000))
    assert ch.init(str(ssl_server.listen_endpoint)) == 0
    for i in range(5):
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message=f"tls{i}"),
                             echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == f"tls{i}"


def test_large_payload_over_tls(ssl_server):
    ch = rpc.Channel(rpc.ChannelOptions(use_ssl=True, timeout_ms=10000,
                                        connect_timeout_ms=5000))
    assert ch.init(str(ssl_server.listen_endpoint)) == 0
    big = "s" * 300_000
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message=big),
                         echo_pb2.EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == big


def test_plaintext_client_rejected_by_tls_server(ssl_server):
    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=1500, max_retry=0))
    assert ch.init(str(ssl_server.listen_endpoint)) == 0
    cntl, _ = ch.call("EchoService.Echo",
                      echo_pb2.EchoRequest(message="plain"),
                      echo_pb2.EchoResponse)
    assert cntl.failed()  # handshake never completes for raw frames


def test_https_console(ssl_server, certs):
    import http.client
    import ssl as pyssl

    ctx = pyssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = pyssl.CERT_NONE
    conn = http.client.HTTPSConnection(
        "127.0.0.1", ssl_server.listen_endpoint.port, context=ctx, timeout=5)
    conn.request("GET", "/health")
    r = conn.getresponse()
    assert r.status == 200 and r.read() == b"OK\n"
    conn.close()
