"""refown golden tests — the ownership-contract checker must fail on
seeded defects (a checker that never fires is indistinguishable from one
that works), the shipped tree must come back clean, and the refguard
runtime twin must abort on the deliberately-broken smoke scenario.

Seeded defect classes (each written into a temp source dir and checked
with refown.check): a straight-line double release, a leak on an
early-return error path, a borrow used after its release, an undeclared
transfer, and a raw add_ref()/release() call outside the macro surface.
The declared-leak registry half gets its own goldens: an unannotated
leaked static, and an lsan.supp entry with no backing declaration.
"""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.natcheck import refown  # noqa: E402

NATIVE = os.path.join(REPO, "native")


def _write_and_check(tmp_path, src):
    (tmp_path / "golden.cpp").write_text(src)
    return refown.check(str(tmp_path), lsan_path="")


def test_refown_clean_on_shipped_tree():
    findings = refown.check()
    assert findings == [], "\n".join(str(f) for f in findings)


def test_refown_flags_double_release(tmp_path):
    fs = _write_and_check(tmp_path, """
void f(NatSocket* s) {
  NAT_REF_ACQUIRE(s, sock.borrow);
  NAT_REF_RELEASE(s, sock.borrow);
  NAT_REF_RELEASE(s, sock.borrow);
}
""")
    assert any(f.rule == "refown-double-release" for f in fs), fs


def test_refown_double_release_reacquire_is_clean(tmp_path):
    fs = _write_and_check(tmp_path, """
void f(NatSocket* s) {
  NAT_REF_ACQUIRE(s, sock.borrow);
  NAT_REF_RELEASE(s, sock.borrow);
  NAT_REF_ACQUIRE(s, sock.borrow);
  NAT_REF_RELEASE(s, sock.borrow);
}
""")
    assert not any(f.rule == "refown-double-release" for f in fs), fs


def test_refown_flags_leak_on_error_path(tmp_path):
    fs = _write_and_check(tmp_path, """
int g(NatSocket* s, int bad) {
  NAT_REF_ACQUIRE(s, sock.borrow);
  if (bad) return -1;
  NAT_REF_RELEASE(s, sock.borrow);
  return 0;
}
""")
    assert any(f.rule == "refown-leak-path" for f in fs), fs


def test_refown_error_path_with_release_is_clean(tmp_path):
    fs = _write_and_check(tmp_path, """
int g(NatSocket* s, int bad) {
  NAT_REF_ACQUIRE(s, sock.borrow);
  if (bad) {
    NAT_REF_RELEASE(s, sock.borrow);
    return -1;
  }
  NAT_REF_RELEASE(s, sock.borrow);
  return 0;
}
""")
    assert not any(f.rule == "refown-leak-path" for f in fs), fs


def test_refown_handoff_to_releasing_fn_is_clean(tmp_path):
    # the keep_write_fiber shape: the acquire escapes into a function
    # handed off BY NAME (spawn_detached arg); its closure releases
    fs = _write_and_check(tmp_path, """
void drain_fiber(void* arg) {
  NatSocket* s = (NatSocket*)arg;
  NAT_REF_RELEASE(s, sock.keepwrite);
}
int g(NatSocket* s, int bad) {
  NAT_REF_ACQUIRE(s, sock.keepwrite);
  spawn_detached(drain_fiber, s);
  if (bad) return -1;
  return 0;
}
""")
    assert not any(f.rule == "refown-leak-path" for f in fs), fs


def test_refown_flags_borrow_after_release(tmp_path):
    fs = _write_and_check(tmp_path, """
void h(NatSocket* s) {
  NAT_REF_ACQUIRE(s, sock.borrow);
  NAT_REF_RELEASE(s, sock.borrow);
  NAT_REF_BORROW(s);
}
""")
    assert any(f.rule == "refown-borrow-after-release" for f in fs), fs


def test_refown_flags_undeclared_transfer(tmp_path):
    fs = _write_and_check(tmp_path, """
void k(NatSocket* s) {
  NAT_REF_TRANSFER(s, bogus.from, bogus.to);
}
""")
    assert any(f.rule == "refown-undeclared-tag" for f in fs), fs
    # a transfer OUT of a never-acquired tag is also an orphan release
    assert any(f.rule == "refown-no-acquire" for f in fs), fs


def test_refown_flags_unreleased_acquire(tmp_path):
    fs = _write_and_check(tmp_path, """
void k(NatSocket* s) {
  NAT_REF_ACQUIRED(s, selftest.b);
}
""")
    assert any(f.rule == "refown-no-release" for f in fs), fs


def test_refown_flags_raw_call(tmp_path):
    fs = _write_and_check(tmp_path, """
void m(NatSocket* s) {
  s->add_ref();
}
""")
    assert any(f.rule == "refown-raw" for f in fs), fs


def test_refown_raw_definition_is_not_a_call(tmp_path):
    fs = _write_and_check(tmp_path, """
struct X {
  void add_ref() { refs++; }
  void release() { refs--; }
  int refs = 0;
};
""")
    assert not any(f.rule == "refown-raw" for f in fs), fs


def test_refown_raw_allow_escape(tmp_path):
    fs = _write_and_check(tmp_path, """
void m(NatSocket* s) {
  // natcheck:allow(refown-raw): the borrow primitive itself
  s->add_ref();
}
""")
    assert not any(f.rule == "refown-raw" for f in fs), fs


def test_refown_flags_undeclared_leak(tmp_path):
    fs = _write_and_check(tmp_path, """
static std::vector<int>& g_leaked = *new std::vector<int>();
""")
    assert any(f.rule == "refown-leak-undeclared" for f in fs), fs


def test_refown_declared_leak_is_clean(tmp_path):
    fs = _write_and_check(tmp_path, """
// natcheck:leak(g_leaked): detached threads use it through exit()
static std::vector<int>& g_leaked = *new std::vector<int>();
""")
    assert not any(f.rule == "refown-leak-undeclared" for f in fs), fs


def test_refown_flags_unbacked_lsan_entry(tmp_path):
    (tmp_path / "golden.cpp").write_text("""
// natcheck:leak(real_leak): declared
static std::vector<int>& g_leaked = *new std::vector<int>();
""")
    supp = tmp_path / "lsan.supp"
    supp.write_text("leak:brpc_tpu::real_leak\nleak:brpc_tpu::ghost_leak\n")
    fs = refown.check(str(tmp_path), lsan_path=str(supp))
    unbacked = [f for f in fs if f.rule == "refown-lsan-unbacked"]
    assert len(unbacked) == 1 and "ghost_leak" in unbacked[0].message, fs


def test_refown_shipped_lsan_entries_all_backed():
    fs = [f for f in refown.check() if f.rule == "refown-lsan-unbacked"]
    assert fs == [], fs


def test_refown_tag_table_parsed():
    tags = refown.parse_tag_table(refown.SRC_DIR)
    # the acceptance floor: >= 25 declared contracts
    assert len(tags) >= 25, sorted(tags)
    assert "sock.borrow" in tags and "adm.inflight" in tags


def test_refown_contract_breadth():
    """>= 25 acquire/release/transfer contract sites across >= 10 TUs —
    the adoption floor the ISSUE sets (prose comments replaced by
    checkable macros)."""
    from tools.natcheck.lockorder import (_strip_comments_and_strings,
                                          collect_sources)
    sources = collect_sources(refown.SRC_DIR)
    sites = []
    for path, text in sources.items():
        if os.path.basename(path) == "nat_refown.h":
            continue
        scrubbed = "\n".join(_strip_comments_and_strings(ln)
                             for ln in text.splitlines())
        sites.extend((path, st.kind) for st in refown._sites_in(
            scrubbed, path))
    tus = {os.path.basename(p) for p, _ in sites}
    assert len(sites) >= 25, f"only {len(sites)} NAT_REF_* sites"
    assert len(tus) >= 10, f"only {len(tus)} TUs adopted: {sorted(tus)}"


def test_cli_refown_exits_zero_on_clean_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.natcheck", "refown"],
        capture_output=True, cwd=REPO, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# refguard runtime twin (needs the toolchain; builds the .so — slow)
# ---------------------------------------------------------------------------

def _have_toolchain():
    return shutil.which("make") and shutil.which("g++")


@pytest.mark.slow
def test_refguard_smoke_clean_and_break_fires():
    if not _have_toolchain():
        pytest.skip("native toolchain unavailable")
    subprocess.run(["make", "-C", NATIVE, "refguard"], check=True,
                   capture_output=True, timeout=900)
    smoke = os.path.join(NATIVE, "nat_smoke_refguard")
    # the shipped tree's contracts balance through the full smoke
    proc = subprocess.run([smoke], capture_output=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # the deliberately-broken scenario must ABORT with the tag pair
    env = dict(os.environ)
    env["NAT_REFGUARD_BREAK"] = "1"
    proc = subprocess.run([smoke], capture_output=True, timeout=120,
                          env=env)
    err = proc.stderr.decode(errors="replace")
    assert proc.returncode != 0, "seeded double release did not abort"
    assert "nat_refguard:" in err and "selftest.dbl" in err, err
