"""Graceful degradation end-to-end: the native quiesce/drain lifecycle
(nat_quiesce.cpp), lame-duck wire signaling per protocol, and client
failover under server churn.

Matrix:
  * per-protocol lame duck — tpu_std SHUTDOWN meta bit (native channel
    detaches, no breaker/budget penalty), h2 GOAWAY honored (in-flight
    completes, new calls re-dial), HTTP Connection: close on remaining
    responses, RESP reply-then-FIN;
  * drain: admitted work (py lane + shm workers) completes before the
    FIN; drain-deadline expiry 503s stragglers instead of resetting;
  * SIGTERM -> graceful_quit_on_sigterm drains and exits 0 with no
    ECONNRESET for well-behaved clients;
  * the accept-vs-teardown race fix (listener close deferred to the
    dispatcher loop) under a connect flood;
  * rolling restart: a client flood across restarting servers completes
    with zero failed requests once retries settle (the churn test the
    chaos lane re-runs under fault seeds).
"""
import os
import signal
import socket as pysocket
import subprocess
import sys
import threading
import time

import pytest

from brpc_tpu import native

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class PyLaneWorker:
    """Py-lane consumer serving kinds 0 (tpu_std echo), 3 (HTTP echo) and
    4 (gRPC echo) with an optional per-request delay."""

    def __init__(self, delay=0.0, nthreads=2, batch=8):
        self.delay = delay
        self.batch = batch
        self.stop = False
        self.served = 0
        self.threads = [threading.Thread(target=self._loop, daemon=True)
                        for _ in range(nthreads)]

    def _loop(self):
        while not self.stop:
            items = native.take_requests(self.batch, 50)
            for item in items:
                h, kind = item[0], item[1]
                payload, sock_id, seq = item[3], item[5], item[6]
                if self.delay:
                    time.sleep(self.delay)
                if kind == 0:
                    native.respond(h, 0, "", payload)
                elif kind == 3:
                    native.req_free(h)
                    body = payload or b"pong"
                    resp = (b"HTTP/1.1 200 OK\r\nContent-Length: " +
                            str(len(body)).encode() + b"\r\n\r\n" + body)
                    native.http_respond(sock_id, seq, resp)
                elif kind == 4:
                    native.req_free(h)
                    # payload is the gRPC-framed body: strip the 5-byte
                    # message prefix before echoing
                    body = payload[5:] if len(payload) >= 5 else payload
                    native.grpc_respond(sock_id, seq, body)
                elif h is not None:
                    native.req_free(h)
                self.served += 1

    def __enter__(self):
        for t in self.threads:
            t.start()
        return self

    def __exit__(self, *exc):
        self.stop = True
        for t in self.threads:
            t.join(timeout=3)


@pytest.fixture
def server():
    port = native.rpc_server_start()
    yield port
    native.fault_configure(os.environ.get("NAT_FAULT", ""))
    native.rpc_server_stop()


def _quiesce_counters():
    c = native.stats_counters()
    return {k: v for k, v in c.items() if "quiesce" in k}


# ---------------------------------------------------------------------------
# per-protocol lame duck
# ---------------------------------------------------------------------------

def test_tpu_std_shutdown_bit_detaches_channel(server):
    """An in-flight tpu_std call completes on the draining connection;
    the SHUTDOWN control frame detaches the channel (draining_redials)
    and charges neither the breaker nor the retry budget."""
    with PyLaneWorker(delay=0.5):
        ch = native.channel_open("127.0.0.1", server)
        native.channel_set_breaker(ch, True)
        rc, body, _ = native.channel_call(ch, "S", "M", b"warm",
                                          timeout_ms=3000)
        assert rc == 0 and body == b"warm"
        before = _quiesce_counters()
        budget_before = native.channel_retry_budget(ch)

        results = []

        def slow_call():
            results.append(native.channel_call(ch, "S", "M", b"inflight",
                                               timeout_ms=5000))

        t = threading.Thread(target=slow_call)
        t.start()
        time.sleep(0.15)  # the call is in the py lane now
        assert native.server_quiesce(4000) == 0
        t.join(timeout=8)
        assert results and results[0][0] == 0, results
        assert results[0][1] == b"inflight"
        after = _quiesce_counters()
        assert after["nat_quiesce_lame_duck_sent"] > \
            before["nat_quiesce_lame_duck_sent"]
        assert after["nat_quiesce_draining_redials"] > \
            before["nat_quiesce_draining_redials"]
        assert after["nat_quiesce_drained_ok"] > \
            before["nat_quiesce_drained_ok"]
        # planned drain: breaker stays closed, budget unspent
        assert native.channel_breaker_state(ch) == 0
        assert native.channel_retry_budget(ch) == budget_before
        native.channel_close(ch)


def test_grpc_goaway_honored_inflight_completes(server):
    """The h2 lane's lame duck is GOAWAY: the in-flight stream is <=
    last_stream_id and must complete; the channel detaches for new
    calls."""
    native.rpc_server_native_http(True)
    with PyLaneWorker(delay=0.5):
        ch = native.channel_open_grpc("127.0.0.1", server)
        st, body, _ = native.grpc_call(ch, "/S/M", b"warm",
                                       timeout_ms=3000)
        assert st == 0 and body == b"warm"
        results = []

        def slow_call():
            try:
                results.append(native.grpc_call(ch, "/S/M", b"inflight",
                                                timeout_ms=5000))
            except ConnectionError as e:
                results.append(e)

        t = threading.Thread(target=slow_call)
        t.start()
        time.sleep(0.15)
        assert native.server_quiesce(4000) == 0
        t.join(timeout=8)
        assert results, "in-flight call never completed"
        assert not isinstance(results[0], Exception), results
        st, body, _ = results[0]
        assert st == 0 and body == b"inflight", results
        native.channel_close(ch)


def test_http_lame_duck_connection_close_on_response(server):
    """HTTP lame duck: the response that drains during quiesce carries an
    injected Connection: close header, and the FIN follows the last
    response byte (clean EOF, no reset)."""
    native.rpc_server_native_http(True)
    with PyLaneWorker(delay=0.5):
        c = pysocket.create_connection(("127.0.0.1", server), timeout=5)
        c.settimeout(5)
        # warm request: keep-alive, no close header
        c.sendall(b"GET /a HTTP/1.1\r\nHost: x\r\n\r\n")
        warm = c.recv(65536)
        assert b"200 OK" in warm and b"connection: close" not in warm.lower()
        # in-flight request, then quiesce while it sits in the py lane
        c.sendall(b"GET /b HTTP/1.1\r\nHost: x\r\n\r\n")
        time.sleep(0.15)
        assert native.server_quiesce(4000) == 0
        data = b""
        while True:
            try:
                got = c.recv(65536)
            except (ConnectionResetError, pysocket.timeout) as e:
                pytest.fail(f"lame-duck close was not graceful: {e!r}")
            if not got:
                break  # clean FIN after the last response byte
            data += got
        assert b"200 OK" in data
        assert b"connection: close" in data.lower(), data
        c.close()


def test_close_per_response_server_is_not_lame_duck():
    """A backend that closes after EVERY response (HTTP/1.0 style,
    keepalive off) is NOT draining: the lame-duck classification needs
    the keep-alive -> Connection: close TRANSITION, or such a server
    would permanently bypass breaker/retry-budget sampling."""
    lsock = pysocket.socket()
    lsock.setsockopt(pysocket.SOL_SOCKET, pysocket.SO_REUSEADDR, 1)
    lsock.bind(("127.0.0.1", 0))
    lsock.listen(16)
    port = lsock.getsockname()[1]
    stop = False

    def serve():
        while not stop:
            try:
                c, _ = lsock.accept()
            except OSError:
                return
            try:
                c.settimeout(2)
                buf = b""
                while b"\r\n\r\n" not in buf:
                    got = c.recv(4096)
                    if not got:
                        break
                    buf += got
                if buf:
                    c.sendall(b"HTTP/1.1 200 OK\r\nConnection: close\r\n"
                              b"Content-Length: 2\r\n\r\nok")
            except OSError:
                pass
            finally:
                try:
                    c.close()
                except OSError:
                    pass

    t = threading.Thread(target=serve, daemon=True)
    t.start()
    h = native.channel_open_http("127.0.0.1", port)
    try:
        before = _quiesce_counters()["nat_quiesce_draining_redials"]
        for _ in range(4):
            status, body = native.http_call(h, "GET", "/x",
                                            timeout_ms=5000)
            assert status == 200 and body == b"ok"
        # every response carried Connection: close, none followed a
        # keep-alive exchange on its connection: no lame-duck detach
        after = _quiesce_counters()["nat_quiesce_draining_redials"]
        assert after == before
    finally:
        stop = True
        lsock.close()
        native.channel_close(h)
        t.join(timeout=3)


def test_resp_lame_duck_reply_then_fin(server):
    """RESP lame duck: the reply for an admitted command still goes out,
    then the connection closes cleanly."""
    native.rpc_server_redis(2)  # native in-memory store
    c = pysocket.create_connection(("127.0.0.1", server), timeout=5)
    c.settimeout(5)
    c.sendall(b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n")
    assert c.recv(4096).startswith(b"+OK")
    # an admitted command (in the server before the quiesce): its reply
    # must precede the FIN
    c.sendall(b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n")
    time.sleep(0.15)
    assert native.server_quiesce(4000) == 0
    data = b""
    while True:
        try:
            got = c.recv(4096)
        except (ConnectionResetError, pysocket.timeout) as e:
            pytest.fail(f"RESP lame-duck close was not graceful: {e!r}")
        if not got:
            break
        data += got
    # the reply either raced ahead of the quiesce or drained through it;
    # either way it must be a complete $1 v bulk string, then EOF
    assert b"$1\r\nv\r\n" in data or data.startswith(b"+OK"), data
    c.close()


# ---------------------------------------------------------------------------
# drain semantics
# ---------------------------------------------------------------------------

def _pack_tpu_std_request(cid, payload=b"x"):
    import struct

    from brpc_tpu.rpc.proto import rpc_meta_pb2

    meta = rpc_meta_pb2.RpcMeta()
    meta.request.service_name = "S"
    meta.request.method_name = "M"
    meta.correlation_id = cid
    mb = meta.SerializeToString()
    return (b"TRPC" + struct.pack(">II", len(mb) + len(payload), len(mb)) +
            mb + payload)


def _read_tpu_std_frames(sock, want, deadline_s=8):
    """Read frames until `want` response cids were seen (or EOF/timeout).
    Returns {cid: (error_code, shutdown_bit)}."""
    import struct

    from brpc_tpu.rpc.proto import rpc_meta_pb2
    from brpc_tpu.rpc.tpu_std_protocol import _meta_shutdown_bit

    buf = b""
    out = {}
    end = time.time() + deadline_s
    sock.settimeout(0.5)
    while len(out) < want and time.time() < end:
        try:
            got = sock.recv(65536)
        except pysocket.timeout:
            continue
        if not got:
            break
        buf += got
        while len(buf) >= 12 and buf[:4] == b"TRPC":
            body, msz = struct.unpack(">II", buf[4:12])
            if len(buf) < 12 + body:
                break
            mb = buf[12:12 + msz]
            buf = buf[12 + body:]
            meta = rpc_meta_pb2.RpcMeta()
            meta.ParseFromString(mb)
            out[meta.correlation_id] = (meta.response.error_code,
                                        _meta_shutdown_bit(mb))
    return out


def test_new_arrivals_rejected_with_elimit_not_reset(server):
    """After the lame-duck pass, a NEW tpu_std request arriving on the
    still-open connection answers a real ELIMIT frame carrying the
    SHUTDOWN bit — never a reset — while the admitted request
    completes."""
    with PyLaneWorker(delay=1.0, nthreads=1, batch=1):
        c = pysocket.create_connection(("127.0.0.1", server), timeout=5)
        c.sendall(_pack_tpu_std_request(1, b"admitted"))
        time.sleep(0.15)  # cid 1 is inside the worker now
        qres = []
        qt = threading.Thread(
            target=lambda: qres.append(native.server_quiesce(5000)))
        qt.start()
        time.sleep(0.2)  # lame duck sent, drain gate armed, socket open
        c.sendall(_pack_tpu_std_request(2, b"late"))
        # cid 0 control frame (shutdown) + cid 2 rejection + cid 1 reply
        frames = _read_tpu_std_frames(c, want=3)
        qt.join(timeout=10)
        assert qres == [0], qres
        assert frames.get(0, (0, False))[1], \
            f"no SHUTDOWN control frame: {frames}"
        assert frames.get(2, (None,))[0] == 2004, frames  # ELIMIT
        assert frames[2][1], "drain rejection must carry the SHUTDOWN bit"
        assert frames.get(1, (None,))[0] == 0, frames  # admitted: served
        c.close()


def test_drain_deadline_expiry_503s_stragglers(server):
    """Work still queued when the drain deadline expires is answered with
    the overload wire shape (never a bare reset) and counted."""
    with PyLaneWorker(delay=1.5, nthreads=1, batch=1):
        before = _quiesce_counters()
        chans = [native.channel_open("127.0.0.1", server) for _ in range(3)]
        results = []
        lock = threading.Lock()

        def call(ch):
            r = native.channel_call(ch, "S", "M", b"x", timeout_ms=8000)
            with lock:
                results.append(r)

        threads = [threading.Thread(target=call, args=(ch,))
                   for ch in chans]
        for t in threads:
            t.start()
        time.sleep(0.2)  # one taken by the worker, the rest queued
        rc = native.server_quiesce(300)
        assert rc == 1  # deadline expired
        after = _quiesce_counters()
        assert after["nat_quiesce_drain_deadline_drops"] > \
            before["nat_quiesce_drain_deadline_drops"]
        for t in threads:
            t.join(timeout=10)
        # stragglers got ELIMIT frames; the one inside usercode overran
        # the deadline and its connection closed (EFAILEDSOCKET) — but
        # nobody may hang or see an unexplained empty result
        codes = sorted(r[0] for r in results)
        assert len(codes) == 3
        assert any(c == 2004 for c in codes), codes
        for ch in chans:
            native.channel_close(ch)


def test_shm_worker_inflight_completes_before_exit():
    """A request riding the shm worker rings when quiesce starts runs to
    completion (the PR-3 inflight table is part of the drain predicate)."""
    from brpc_tpu import rpc

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=1,
        py_worker_factory="tests.shm_worker_factory:make_slow"))
    from tests.shm_worker_factory import make

    for s in make():
        srv.add_service(s)
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port
    try:
        ch = native.channel_open_http("127.0.0.1", port)
        results = []

        def call():
            try:
                results.append(native.http_call(
                    ch, "POST", "/EchoService/Echo",
                    b'{"message": "drainme"}',
                    headers="Content-Type: application/json\r\n",
                    timeout_ms=8000))
            except ConnectionError as e:
                results.append(e)

        t = threading.Thread(target=call)
        t.start()
        time.sleep(0.15)  # the request is inside the 400ms worker sleep
        # graceful stop: quiesce drains the shm in-flight BEFORE the
        # worker processes are torn down
        srv.stop()
        t.join(timeout=10)
        assert results, "in-flight worker request never completed"
        assert not isinstance(results[0], Exception), results
        status, body = results[0]
        assert status == 200 and b"drainme@" in body, results
        native.channel_close(ch)
    finally:
        if srv.is_running:
            srv.stop()


# ---------------------------------------------------------------------------
# SIGTERM path + teardown race + rolling restart
# ---------------------------------------------------------------------------

_SERVER_SCRIPT = r"""
import sys
from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

delay = float(sys.argv[2])
workers = int(sys.argv[3]) if len(sys.argv) > 3 else 0

class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        import time
        if delay:
            time.sleep(delay)
        response.message = request.message
        done()

opts = rpc.ServerOptions(
    num_threads=2, use_native_runtime=True,
    graceful_quit_on_sigterm=True, graceful_shutdown_timeout_ms=4000)
if workers:
    opts.py_workers = workers
    opts.py_worker_factory = "tests.shm_worker_factory:make"
srv = rpc.Server(opts)
srv.add_service(EchoService())
assert srv.start("127.0.0.1:%s" % sys.argv[1]) == 0
print("READY", srv.listen_endpoint.port, flush=True)
srv.run_until_asked_to_quit()
"""


def _spawn_server(port=0, delay=0.0, extra_env=None, workers=0):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.setdefault("JAX_PLATFORMS", "cpu")
    if extra_env:
        env.update(extra_env)
    p = subprocess.Popen(
        [sys.executable, "-c", _SERVER_SCRIPT, str(port), str(delay),
         str(workers)],
        env=env, cwd=REPO, stdout=subprocess.PIPE,
        stderr=subprocess.DEVNULL, text=True)
    line = p.stdout.readline()
    assert line.startswith("READY"), f"server failed to start: {line!r}"
    return p, int(line.split()[1])


def test_sigterm_drains_inflight_and_exits_zero():
    """SIGTERM under load: the admitted in-flight call completes, the
    client sees a response + clean close (no ECONNRESET), the process
    exits 0 within the deadline."""
    p, port = _spawn_server(delay=0.5)
    try:
        ch = native.channel_open("127.0.0.1", port)
        rc, body, _ = native.channel_call(
            ch, "EchoService", "Echo",
            _echo_req(b"warm"), timeout_ms=5000)
        assert rc == 0
        results = []

        def call():
            results.append(native.channel_call(
                ch, "EchoService", "Echo", _echo_req(b"inflight"),
                timeout_ms=8000))

        t = threading.Thread(target=call)
        t.start()
        time.sleep(0.15)
        p.send_signal(signal.SIGTERM)
        t.join(timeout=10)
        assert results and results[0][0] == 0, results
        assert p.wait(timeout=10) == 0
        native.channel_close(ch)
    finally:
        if p.poll() is None:
            p.kill()
            p.wait()


def _echo_req(message: bytes) -> bytes:
    from brpc_tpu.rpc.proto import echo_pb2

    return echo_pb2.EchoRequest(
        message=message.decode()).SerializeToString()


def test_accept_vs_teardown_race_under_connect_flood(server):
    """Listener teardown is a dispatcher-loop task: a connect flood
    racing quiesce/stop must end with refused or cleanly-closed
    connections — never a crash or a connection accepted on a recycled
    fd. The accept:delay fault widens the window."""
    native.fault_configure("accept:delay_ms=5:p=0.5")
    stop = threading.Event()
    errors = []

    def flood():
        while not stop.is_set():
            try:
                c = pysocket.create_connection(("127.0.0.1", server),
                                               timeout=0.5)
                c.close()
            except OSError:
                pass  # refused mid-teardown: expected
            except Exception as e:  # anything else is the bug
                errors.append(e)
                return

    threads = [threading.Thread(target=flood) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.2)
    assert native.server_quiesce(1000) in (0, 1)
    time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    native.fault_configure(os.environ.get("NAT_FAULT", ""))
    assert errors == []


def test_python_acceptor_stop_under_connect_flood():
    """The pure-Python port's twin: Acceptor.stop_accept vs a concurrent
    accept — the deferred close (event_dispatcher.remove_and_close)
    means no fd is closed while the loop may still poll it."""
    from brpc_tpu import rpc
    from brpc_tpu.rpc.proto import echo_pb2

    class EchoService(rpc.Service):
        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message
            done()

    for _ in range(3):  # repeat: the race window is scheduling-dependent
        srv = rpc.Server(rpc.ServerOptions(num_threads=2))
        srv.add_service(EchoService())
        assert srv.start("127.0.0.1:0") == 0
        port = srv.listen_endpoint.port
        stop = threading.Event()
        errors = []

        def flood():
            while not stop.is_set():
                try:
                    c = pysocket.create_connection(("127.0.0.1", port),
                                                   timeout=0.5)
                    c.close()
                except OSError:
                    pass
                except Exception as e:
                    errors.append(e)
                    return

        threads = [threading.Thread(target=flood) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.1)
        srv.stop()
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert errors == []


def _flood_with_failover(ports, n_requests, deadline_s=60):
    """App-level failover client: each request tries the endpoints
    round-robin with retries until it succeeds or the budget is gone.
    Returns the number of ULTIMATE failures (0 = retries settled)."""
    chans = {}

    def get_chan(port):
        ch = chans.get(port)
        if ch is None:
            try:
                ch = native.channel_open("127.0.0.1", port,
                                         connect_timeout_ms=500)
            except RuntimeError:
                return None
            chans[port] = ch
        return ch

    failures = 0
    for i in range(n_requests):
        ok = False
        for attempt in range(12):
            port = ports[(i + attempt) % len(ports)]
            ch = get_chan(port)
            if ch is None:
                time.sleep(0.05)
                continue
            rc, body, _ = native.channel_call(
                ch, "EchoService", "Echo", _echo_req(b"m%d" % i),
                timeout_ms=3000, max_retry=1)
            if rc == 0:
                ok = True
                break
            # channel may be pinned to a dead dial cache: drop it so the
            # next attempt re-opens
            if rc != 2004:
                native.channel_close(chans.pop(port))
            time.sleep(0.05)
        if not ok:
            failures += 1
    for ch in chans.values():
        native.channel_close(ch)
    return failures


def _free_ports(n):
    socks = [pysocket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


def test_rolling_restart_zero_failed_requests():
    """One graceful restart mid-flood over two backends: every request
    completes once retries settle (the light in-tier version of the
    chaos lane's 3-server churn round)."""
    ports = _free_ports(2)
    servers = [_spawn_server(port=p, delay=0.02)[0] for p in ports]
    try:
        result = {}

        def flood():
            result["failures"] = _flood_with_failover(ports, 60)

        t = threading.Thread(target=flood)
        t.start()
        time.sleep(0.5)
        # rolling restart of server 0: SIGTERM (drains), wait, respawn
        servers[0].send_signal(signal.SIGTERM)
        assert servers[0].wait(timeout=15) == 0
        servers[0] = _spawn_server(port=ports[0], delay=0.02)[0]
        t.join(timeout=90)
        assert not t.is_alive(), "flood wedged"
        assert result.get("failures") == 0
    finally:
        for p in servers:
            if p.poll() is None:
                p.kill()
                p.wait()


def _http_flood_with_failover(ports, n_requests):
    """HTTP failover twin of _flood_with_failover: POSTs ride the shm
    worker lane on the servers, so a worker:kill seed surfaces as 503s
    that the retry loop must absorb."""
    chans = {}

    def get_chan(port):
        ch = chans.get(port)
        if ch is None:
            try:
                ch = native.channel_open_http("127.0.0.1", port,
                                              connect_timeout_ms=500)
            except RuntimeError:
                return None
            chans[port] = ch
        return ch

    failures = 0
    for i in range(n_requests):
        ok = False
        # retry pacing must SPAN the recovery windows chaos opens: a
        # worker:kill leaves a backend's shm lane dead for ~2s before
        # the in-process fallback engages — 16 x 0.25s rides it out
        for attempt in range(16):
            port = ports[(i + attempt) % len(ports)]
            ch = get_chan(port)
            if ch is None:
                time.sleep(0.25)
                continue
            try:
                status, body = native.http_call(
                    ch, "POST", "/EchoService/Echo",
                    b'{"message": "m%d"}' % i,
                    headers="Content-Type: application/json\r\n",
                    timeout_ms=3000)
            except ConnectionError:
                native.channel_close(chans.pop(port))
                time.sleep(0.25)
                continue
            # worker-lane responses carry "m<i>@<pid>", the in-process
            # fallback (all workers dead) plain "m<i>" — both are served
            if status == 200 and b"m%d" % i in body:
                ok = True
                break
            time.sleep(0.25)  # 503 (draining / reaped worker): retry
        if not ok:
            failures += 1
    for ch in chans.values():
        native.channel_close(ch)
    return failures


@pytest.mark.slow
def test_churn_three_servers_round_robin_restarts():
    """The full churn drill (the chaos lane re-runs this under
    write:err/worker:kill fault seeds via BRPC_TPU_CHURN_FAULT): a
    tpu_std flood plus an HTTP flood through the shm worker lane, across
    3 servers restarted round-robin — zero failed requests once retries
    settle."""
    fault = os.environ.get("BRPC_TPU_CHURN_FAULT", "")
    extra_env = {"NAT_FAULT": fault} if fault else None
    ports = _free_ports(3)
    servers = [_spawn_server(port=p, delay=0.01, extra_env=extra_env,
                             workers=1)[0]
               for p in ports]
    try:
        result = {}

        def flood_std():
            result["std"] = _flood_with_failover(ports, 150)

        def flood_http():
            result["http"] = _http_flood_with_failover(ports, 100)

        threads = [threading.Thread(target=flood_std),
                   threading.Thread(target=flood_http)]
        for t in threads:
            t.start()
        for i in range(3):  # restart each server once, round-robin
            time.sleep(1.0)
            servers[i].send_signal(signal.SIGTERM)
            assert servers[i].wait(timeout=25) == 0, f"server {i} dirty exit"
            servers[i] = _spawn_server(port=ports[i], delay=0.01,
                                       extra_env=extra_env, workers=1)[0]
        for t in threads:
            t.join(timeout=240)
            assert not t.is_alive(), "flood wedged"
        assert result.get("std") == 0, result
        assert result.get("http") == 0, result
    finally:
        for p in servers:
            if p.poll() is None:
                p.kill()
                p.wait()
