"""EndPoint / Status / flags / pools / DoublyBufferedData tests."""
import threading

import pytest

from brpc_tpu.butil import flags
from brpc_tpu.butil.dbd import DoublyBufferedData
from brpc_tpu.butil.endpoint import DeviceCoord, EndPoint
from brpc_tpu.butil.pools import INVALID_RESOURCE_ID, ObjectPool, ResourcePool
from brpc_tpu.butil.status import Status


def test_endpoint_parse_roundtrip():
    ep = EndPoint.parse("10.0.0.1:8000")
    assert ep.ip == "10.0.0.1" and ep.port == 8000 and not ep.is_ici()
    assert str(ep) == "10.0.0.1:8000"
    ep2 = EndPoint.parse("10.0.0.1:8000/tpu:0.1.2.0")
    assert ep2.is_ici()
    assert ep2.device == DeviceCoord(0, 1, 2, 0)
    assert EndPoint.parse(str(ep2)) == ep2


def test_endpoint_invalid():
    for bad in ("nohost", "a:b", "1.2.3.4:99999"):
        with pytest.raises(ValueError):
            EndPoint.parse(bad)


def test_status():
    assert Status.ok().is_ok()
    s = Status.error(1008, "rpc timed out")
    assert not s
    assert s.code == 1008
    with pytest.raises(ValueError):
        Status.error(0, "not an error")


def test_flags_define_set_validate():
    flags.define_int("test_timeout_ms", 500, "test flag")
    assert flags.get_flag("test_timeout_ms") == 500
    assert flags.set_flag("test_timeout_ms", "750")
    assert flags.get_flag("test_timeout_ms") == 750
    flags.define_int(
        "test_positive", 1, validator=lambda v: v > 0
    )
    assert not flags.set_flag("test_positive", -5)
    assert flags.get_flag("test_positive") == 1
    assert not flags.set_flag("no_such_flag", 1)
    with pytest.raises(ValueError):
        flags.define_int("test_timeout_ms", 1)


def test_object_pool_reuse():
    pool = ObjectPool(list)
    a = pool.get()
    pool.put(a)
    b = pool.get()
    assert a is b


def test_resource_pool_versioned_ids():
    pool = ResourcePool(dict)
    rid, obj = pool.get_resource()
    obj["k"] = 1
    assert pool.address(rid) is obj
    assert pool.return_resource(rid)
    # Stale id no longer addresses anything — the SocketId trick.
    assert pool.address(rid) is None
    assert not pool.return_resource(rid)
    rid2, obj2 = pool.get_resource()
    assert (rid2 & 0xFFFFFFFF) == (rid & 0xFFFFFFFF)  # slot reused
    assert rid2 != rid  # version differs
    assert pool.address(rid) is None
    assert pool.address(INVALID_RESOURCE_ID) is None


def test_dbd_concurrent_read_modify():
    dbd = DoublyBufferedData(list)
    stop = threading.Event()
    errors = []

    def reader():
        while not stop.is_set():
            with dbd.read() as servers:
                snapshot = list(servers)
                # A snapshot must always be a consistent prefix.
                if snapshot != sorted(snapshot):
                    errors.append(snapshot)

    threads = [threading.Thread(target=reader) for _ in range(4)]
    for t in threads:
        t.start()
    for i in range(200):
        dbd.modify(lambda lst, i=i: lst.append(i) if (not lst or lst[-1] != i) else None)
    stop.set()
    for t in threads:
        t.join()
    assert not errors
    with dbd.read() as servers:
        assert servers == list(range(200))


def test_flatmap_one_level_hashing():
    """FlatMap is a real bucket table (flat_map_inl.h shape): embedded
    first slots + chained collisions + load-factor resize."""
    from brpc_tpu.butil.containers import FlatMap

    m = FlatMap(nbucket=4, load_factor=80)
    for i in range(100):
        m.insert(i, i * 10)
    assert len(m) == 100
    assert m.nbucket > 4  # resized as the load factor was crossed
    for i in range(100):
        assert m.seek(i) == i * 10
        assert i in m
    assert m.seek(1000) is None
    # erase unlinks both embedded and chained nodes
    for i in range(0, 100, 2):
        assert m.erase(i) == 1
    assert m.erase(0) == 0
    assert len(m) == 50
    assert sorted(k for k, _ in m) == list(range(1, 100, 2))
    # operator[] default-constructs (None), and None values are contained
    assert m[777] is None
    assert 777 in m and len(m) == 51
    m[777] = 7
    assert m.seek(777) == 7
    m.clear()
    assert m.empty() and m.seek(1) is None


def test_flatmap_collisions_chain():
    from brpc_tpu.butil.containers import FlatMap

    m = FlatMap(nbucket=1, load_factor=10**9)  # force one bucket: all chain
    for i in range(32):
        m.insert(f"k{i}", i)
    assert m.nbucket == 1 and len(m) == 32
    assert all(m.seek(f"k{i}") == i for i in range(32))
    assert m.erase("k31") == 1 and m.erase("k0") == 1
    assert m.seek("k30") == 30 and len(m) == 30
