"""End-to-end trace stitching (the flight recorder's rpcz leg).

trace_id / span_id / parent_span_id now survive process boundaries on
every native lane: the tpu_std RpcMeta trace fields, HTTP x-bd-trace-*
headers, gRPC x-bd-* metadata, and the shm worker lane's descriptor
records — so /rpcz ``find_trace`` returns the full client -> native
server -> shm worker chain with correct parent edges. These tests pin
the per-lane client/server linkage and the two-process worker chain
(ISSUE 6 acceptance: one trace_id, >= 3 linked spans through the shm
worker lane).
"""
import json
import time

import pytest

from brpc_tpu import rpc

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


@pytest.fixture()
def nat_server():
    """Bare native server: echo native handler + native HTTP/h2 lanes +
    span sampling on (every call). Function-scoped: the native runtime
    owns ONE server at a time, and the shm-worker test below needs the
    port back for its own full rpc.Server."""
    port = native.rpc_server_start(native_echo=True)
    native.rpc_server_native_http(True)
    native.stats_enable_spans(1)
    native.stats_drain_spans()  # drop spans from earlier tests
    yield port
    native.stats_enable_spans(0)
    native.rpc_server_stop()


def _spans_for(trace_id, tries=20):
    """Drain native spans until the trace shows up (the write side is
    asynchronous for client completions)."""
    got = []
    for _ in range(tries):
        got += [r for r in native.stats_drain_spans()
                if r["trace_id"] == trace_id]
        kinds = {r["lane"] for r in got}
        if "client" in kinds and len(kinds) >= 2:
            break
        time.sleep(0.05)
    return got


def _assert_linked(spans, server_lane, trace_id, parent_span):
    client = [r for r in spans if r["lane"] == "client"]
    server = [r for r in spans if r["lane"] == server_lane]
    assert client, spans
    assert server, spans
    assert client[0]["trace_id"] == trace_id
    assert client[0]["parent_span_id"] == parent_span
    assert server[0]["trace_id"] == trace_id
    assert server[0]["parent_span_id"] == client[0]["span_id"]
    return client[0], server[0]


def test_tpu_std_client_server_spans_linked(nat_server):
    t, s = 0x1011, 0x77
    ch = native.channel_open("127.0.0.1", nat_server)
    try:
        with native.trace_scope(t, s):
            rc, body, _ = native.channel_call(ch, "EchoService", "Echo",
                                              b"x", timeout_ms=5000)
        assert rc == 0
    finally:
        native.channel_close(ch)
    cl, sv = _assert_linked(_spans_for(t), "echo", t, s)
    assert cl["method"] == "EchoService.Echo"
    assert sv["method"] == "EchoService.Echo"


def test_http_client_server_spans_linked(nat_server):
    t, s = 0x1012, 0x78
    ch = native.channel_open_http("127.0.0.1", nat_server)
    try:
        with native.trace_scope(t, s):
            status, out = native.http_call(ch, "GET", "/echo",
                                           timeout_ms=5000)
        assert status == 200
    finally:
        native.channel_close(ch)
    cl, sv = _assert_linked(_spans_for(t), "http", t, s)
    assert cl["method"] == "GET /echo"
    assert sv["method"] == "/echo"


def test_grpc_client_server_spans_linked(nat_server):
    t, s = 0x1013, 0x79
    ch = native.channel_open_grpc("127.0.0.1", nat_server)
    try:
        with native.trace_scope(t, s):
            gst, out, msg = native.grpc_call(ch, "/EchoService/Echo",
                                             b"hi", timeout_ms=5000)
        assert gst == 0, msg
    finally:
        native.channel_close(ch)
    _assert_linked(_spans_for(t), "grpc", t, s)


def test_trace_scope_restores_enclosing_context(nat_server):
    """A nested scope must restore the ENCLOSING ambient context on
    exit, not clobber it to zero — calls after the inner scope keep
    propagating the outer trace."""
    t_outer, s_outer = 0x2020, 0x31
    ch = native.channel_open("127.0.0.1", nat_server)
    try:
        with native.trace_scope(t_outer, s_outer):
            with native.trace_scope(0x2021, 0x32):
                pass  # inner scope closes...
            rc, _, _ = native.channel_call(ch, "EchoService", "Echo",
                                           b"n", timeout_ms=5000)
            assert rc == 0
    finally:
        native.channel_close(ch)
    spans = _spans_for(t_outer)
    cl = [r for r in spans if r["lane"] == "client"]
    assert cl and cl[0]["trace_id"] == t_outer
    assert cl[0]["parent_span_id"] == s_outer


def test_untraced_calls_start_fresh_roots(nat_server):
    """No ambient context: the sampled spans still record, with a fresh
    trace id and a 0 parent (a root), never trace_id 0."""
    ch = native.channel_open("127.0.0.1", nat_server)
    try:
        rc, _, _ = native.channel_call(ch, "EchoService", "Echo", b"y",
                                       timeout_ms=5000)
        assert rc == 0
    finally:
        native.channel_close(ch)
    time.sleep(0.1)
    recs = native.stats_drain_spans()
    assert recs
    for r in recs:
        assert r["trace_id"] != 0


def test_rpcz_find_trace_returns_parent_edges(nat_server):
    """The Python /rpcz surface: drained native spans carry
    parent_span_id, and find_trace stitches them into one trace."""
    from brpc_tpu import rpcz

    rpcz.clear_for_tests()
    t, s = 0x1014, 0x80
    ch = native.channel_open("127.0.0.1", nat_server)
    try:
        with native.trace_scope(t, s):
            rc, _, _ = native.channel_call(ch, "EchoService", "Echo",
                                           b"z", timeout_ms=5000)
        assert rc == 0
    finally:
        native.channel_close(ch)
    time.sleep(0.1)
    spans = rpcz.find_trace(t)
    assert len(spans) >= 2
    client = [x for x in spans if x.kind == "client"][0]
    server = [x for x in spans if x.kind == "server"][0]
    assert client.parent_span_id == s
    assert server.parent_span_id == client.span_id
    # and the page renders the trace
    body = rpcz.describe_recent_spans({"trace_id": f"{t:x}"})
    assert f"trace={t:016x}" in body


def test_kind8_descriptor_carries_trace_context():
    """Bulk-tensor (kind-8) shm descriptors: the unused sock_id/cid
    fields carry the ambient (trace_id, parent span) across the process
    boundary."""
    lib = native.load()
    lib.nat_shm_lane_enable(0)
    assert lib.nat_shm_lane_create(1 << 20) == 0
    assert lib.nat_shm_worker_attach(lib.nat_shm_lane_name()) == 0
    try:
        with native.trace_scope(0xbead, 0x42):
            assert lib.nat_shm_push_tensor(b"tensor-bytes", 12, 7) == 0
        h = lib.nat_shm_take_request(2000)
        assert h
        assert lib.nat_req_kind(h) == 8
        assert lib.nat_req_sock_id(h) == 0xbead   # trace_id
        assert lib.nat_req_cid(h) == 0x42         # parent span id
        assert lib.nat_req_aux(h) == 7            # caller tag untouched
        native.req_free(h)
    finally:
        lib.nat_shm_lane_enable(0)


def test_shm_worker_chain_three_linked_spans():
    """ISSUE 6 acceptance: a two-process echo through the shm worker
    lane yields ONE trace_id with >= 3 linked spans in /rpcz find_trace
    — client -> native server -> shm worker, correct parent edges."""
    from brpc_tpu import rpcz
    from tests.shm_worker_factory import make

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, use_native_runtime=True, py_workers=1,
        py_worker_factory="tests.shm_worker_factory:make"))
    for s in make():
        srv.add_service(s)
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port
    try:
        rpcz.clear_for_tests()
        t, s_parent = 0xfeed01, 0x99
        ch = native.channel_open_http("127.0.0.1", port)
        body = json.dumps({"message": "hi"}).encode()
        try:
            with native.trace_scope(t, s_parent):
                status, out = native.http_call(
                    ch, "POST", "/EchoService/Echo", body,
                    headers="Content-Type: application/json\r\n",
                    timeout_ms=15000)
            assert status == 200, out
        finally:
            native.channel_close(ch)
        deadline = time.time() + 10
        spans = []
        while time.time() < deadline:
            spans = rpcz.find_trace(t)
            if len(spans) >= 3:
                break
            time.sleep(0.2)
        assert len(spans) >= 3, [x.describe() for x in spans]
        client = [x for x in spans if x.kind == "client"][0]
        server = [x for x in spans
                  if "native:http" in str(x.remote_side)][0]
        worker = [x for x in spans
                  if "native:worker" in str(x.remote_side)][0]
        assert client.parent_span_id == s_parent
        assert server.parent_span_id == client.span_id
        assert worker.parent_span_id == server.span_id
        # one trace end to end
        assert {x.trace_id for x in spans} == {t}
    finally:
        srv.stop()
