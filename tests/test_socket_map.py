"""SocketMap + bthread fd helper tests (details/socket_map, bthread/fd.cpp
shapes)."""
import socket as pysocket

import pytest

from brpc_tpu import bthread, rpc
from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.rpc.proto import echo_pb2
from brpc_tpu.rpc.socket_map import SocketMap, get_global_socket_map


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_channels_share_single_connection(server):
    """Two channels, same endpoint, 'single' type → ONE shared socket."""
    ch1, ch2 = rpc.Channel(), rpc.Channel()
    assert ch1.init(str(server.listen_endpoint)) == 0
    assert ch2.init(str(server.listen_endpoint)) == 0
    c1, _ = ch1.call("EchoService.Echo", echo_pb2.EchoRequest(message="a"),
                     echo_pb2.EchoResponse, timeout_ms=3000)
    c2, _ = ch2.call("EchoService.Echo", echo_pb2.EchoRequest(message="b"),
                     echo_pb2.EchoResponse, timeout_ms=3000)
    assert not c1.failed() and not c2.failed()
    assert ch1._single_sid == ch2._single_sid  # shared via SocketMap
    ch1.close()
    ch2.close()


def test_different_protocols_get_different_connections(server):
    """SocketMapKey includes the protocol (socket_map.h): a tpu_std channel
    and an http channel to the SAME endpoint must NOT share a socket."""
    ch1 = rpc.Channel()
    ch2 = rpc.Channel(rpc.ChannelOptions(protocol="http"))
    assert ch1.init(str(server.listen_endpoint)) == 0
    assert ch2.init(str(server.listen_endpoint)) == 0
    c1, _ = ch1.call("EchoService.Echo", echo_pb2.EchoRequest(message="a"),
                     echo_pb2.EchoResponse, timeout_ms=3000)
    assert not c1.failed()
    c2, r2 = ch2.call("EchoService.Echo", echo_pb2.EchoRequest(message="h"),
                      echo_pb2.EchoResponse, timeout_ms=3000)
    assert not c2.failed(), c2.error_text
    assert r2.message == "h"
    assert ch1._single_sid != ch2._single_sid
    ch1.close()
    ch2.close()


def test_ssl_and_device_transport_keyed_separately():
    """ssl / device-transport channels never share a plain connection."""
    from brpc_tpu.rpc.socket_map import make_key

    ep = EndPoint("127.0.0.1", 1)
    plain = make_key(ep, protocol="tpu_std")
    ssl = make_key(ep, protocol="tpu_std", ssl=True)
    dev = make_key(ep, protocol="tpu_std", app_connect_id="device")
    assert len({plain, ssl, dev}) == 3
    smap = SocketMap()
    assert smap.insert(ep, key=plain) != smap.insert(ep, key=dev)
    assert smap.count() == 2


def test_socket_map_refcounting():
    smap = SocketMap()
    ep = EndPoint("127.0.0.1", 1)  # never connected: just identity mgmt
    sid1 = smap.insert(ep)
    sid2 = smap.insert(ep)
    assert sid1 == sid2
    assert smap.count() == 1
    smap.remove(ep)  # ref 2 -> 1
    assert smap.count() == 1
    smap.remove(ep)  # ref 1 -> 0: recycled
    assert smap.count() == 0
    sid3 = smap.insert(ep)
    assert sid3 != sid1  # new socket identity after recycle


def test_fd_wait_and_connect(server):
    s = bthread.connect(("127.0.0.1", server.listen_endpoint.port),
                        timeout_s=2)
    # writable right after connect
    assert bthread.fd_wait(s.fileno(), "w", timeout_s=2)
    # not readable yet (no data): timeout path
    assert not bthread.fd_wait(s.fileno(), "r", timeout_s=0.05)
    s.close()
