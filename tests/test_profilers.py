"""Profiler-suite + SpanDB + console-page tests — the
hotspots_service.h:38-68 surface (heap/growth/contention/tpu), the on-disk
rpcz SpanDB (span.h:206-224), and the /vlog /dir /ids pages.
"""
import http.client
import threading
import time

import pytest

from brpc_tpu import rpc, rpcz
from brpc_tpu.butil import flags as flags_mod
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


@pytest.fixture(scope="module", autouse=True)
def _stop_tracemalloc_after():
    """tracemalloc roughly doubles allocation cost; don't tax the rest of
    the suite once these tests are done."""
    yield
    import tracemalloc

    from brpc_tpu.builtin import profilers

    if tracemalloc.is_tracing():
        tracemalloc.stop()
    with profilers._baseline_lock:
        profilers._growth_baseline = None


def _get(server, path, timeout=15):
    conn = http.client.HTTPConnection(
        "127.0.0.1", server.listen_endpoint.port, timeout=timeout)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, r.getheader("content-type", ""), body


def test_heap_profile(server):
    # First call may just start tracemalloc; second sees our allocation.
    _get(server, "/hotspots/heap")
    keep = [bytearray(256 * 1024) for _ in range(8)]  # noqa: F841
    status, ctype, body = _get(server, "/hotspots/heap")
    text = body.decode()
    assert status == 200
    assert "heap profile" in text and "bytes live" in text
    assert any(line.rsplit(" ", 1)[-1].isdigit()
               for line in text.splitlines() if not line.startswith("#"))


def test_growth_profile(server):
    _get(server, "/hotspots/heap")  # ensures tracing + baseline
    hog = [dict(x=i) for i in range(20000)]  # noqa: F841
    status, _, body = _get(server, "/hotspots/growth")
    assert status == 200
    assert b"growth profile" in body


def test_contention_profile(server):
    # Python-level waits (Condition/Event — what butex, execution queues
    # and bthread ids block on) are what the sampler can see; raw C-level
    # Lock.acquire leaves no Python frame.
    evt = threading.Event()

    def waiter_in_test():
        evt.wait()

    blocked = threading.Thread(target=waiter_in_test, daemon=True)
    blocked.start()
    time.sleep(0.05)
    try:
        status, _, body = _get(server, "/hotspots/contention?seconds=0.3")
        assert status == 200
        text = body.decode()
        assert "contention profile" in text
        assert "waiter_in_test" in text  # the blocked thread was observed
    finally:
        evt.set()
        blocked.join(1)


def test_tpu_trace_endpoint(server):
    status, ctype, body = _get(server, "/hotspots/tpu?seconds=0.2",
                               timeout=60)
    assert status == 200
    # jax profiler produces a zip of xplane files; if the backend refuses
    # (no profiler support), the endpoint explains in text instead.
    assert ctype in ("application/zip", "text/plain")
    if ctype == "application/zip":
        assert body[:2] == b"PK"


def test_pprof_heap(server):
    status, _, body = _get(server, "/pprof/heap")
    assert status == 200 and b"heap profile" in body


def test_span_db_persists_and_rotates(tmp_path):
    flags_mod.set_flag("rpcz_database_dir", str(tmp_path))
    try:
        span = rpcz.Span("server", "T.M", log_id=7)
        span.annotate("stage one")
        span.end(0)
        trace_id = span.trace_id
        rpcz.clear_for_tests()  # drop the memory window
        found = rpcz.find_trace(trace_id)
        assert len(found) == 1
        s = found[0]
        assert s.full_method == "T.M" and s.log_id == 7
        assert s.annotations and s.annotations[0][1] == "stage one"
        # rotation keeps the db bounded across generations
        db = rpcz._get_span_db()
        db._max = 1000  # rotate every 500
        last = None
        for i in range(1200):
            sp = rpcz.Span("client", f"T.M{i}")
            sp.end(0)
            last = sp
        assert rpcz.find_trace(last.trace_id)  # recent span still findable
        db.drain()
        import os

        files = os.listdir(tmp_path)
        assert "rpcz.0.recordio" in files and "rpcz.1.recordio" in files
    finally:
        flags_mod.set_flag("rpcz_database_dir", "")
        rpcz.clear_for_tests()


def test_vlog_page(server):
    import logging

    logging.getLogger("brpc_tpu.test_vlog")  # materialize a logger
    status, _, body = _get(server, "/vlog")
    assert status == 200
    assert b"brpc_tpu.test_vlog" in body
    status, _, body = _get(server, "/vlog?setlevel=brpc_tpu.test_vlog=DEBUG")
    assert status == 200
    assert logging.getLogger("brpc_tpu.test_vlog").level == 10


def test_dir_page(server, tmp_path):
    (tmp_path / "hello.txt").write_bytes(b"console dir page")
    status, _, body = _get(server, f"/dir{tmp_path}")
    assert status == 200 and b"hello.txt" in body
    status, _, body = _get(server, f"/dir{tmp_path}/hello.txt")
    assert status == 200 and body == b"console dir page"
    status, _, _ = _get(server, "/dir/no/such/path/zz")
    assert status == 404


def test_ids_page(server):
    status, _, body = _get(server, "/ids")
    assert status == 200 and b"id_slots:" in body
    from brpc_tpu.bthread import id as bthread_id

    idv = bthread_id.create_ranged(None, None, 3)
    try:
        status, _, body = _get(server, f"/ids?id={idv}")
        assert status == 200
        assert b"range=3" in body and b"destroyed=False" in body
    finally:
        bthread_id.lock(idv)
        bthread_id.unlock_and_destroy(idv)


def test_span_db_merges_across_eviction_boundary(tmp_path):
    """A trace with spans BOTH still in memory and aged to disk returns
    complete (the eviction-boundary merge in find_trace)."""
    flags_mod.set_flag("rpcz_database_dir", str(tmp_path))
    try:
        s1 = rpcz.Span("server", "T.First", log_id=1)
        s1.end(0)
        trace_id = s1.trace_id
        db = rpcz._get_span_db()
        db.drain()
        rpcz.clear_for_tests()  # s1 now lives only on disk
        s2 = rpcz.Span("client", "T.Second", trace_id=trace_id)
        s2.end(0)  # s2 in memory (and queued to disk)
        found = rpcz.find_trace(trace_id)
        methods = sorted(s.full_method for s in found)
        assert methods == ["T.First", "T.Second"]
        # no duplicate for s2 even though it is in memory AND on disk
        assert len(found) == 2
    finally:
        flags_mod.set_flag("rpcz_database_dir", "")
        rpcz.clear_for_tests()


def test_per_second_series_matches_get_value_semantics():
    """PerSecond.series plots the same quantity get_value reports (the
    SUM rate for IntRecorder-backed windows, not the average)."""
    from brpc_tpu import bvar

    rec = bvar.IntRecorder()
    win = bvar.PerSecond(rec, 5)
    try:
        import time as _t

        win._sampler.take_sample()  # baseline
        for v in (10, 20, 30):  # sum=60, num=3, avg=20
            rec.update(v)
        _t.sleep(0.05)
        win._sampler.take_sample()
        series = win.series()
        assert series, "series empty"
        samples = win._sampler.samples_in(5)
        # integrate rate over each pair's own dt: immune to extra samples
        # the background 1Hz collector may inject mid-test
        total = sum(rate * (samples[i + 1][0] - samples[i][0])
                    for i, (_, rate) in enumerate(series))
        # must integrate back to the SUM delta (60), not the avg (20)
        assert total == pytest.approx(60.0, rel=0.05), \
            f"integrated {total}, sum semantics expect 60 (avg would be 20)"
    finally:
        win.destroy()
