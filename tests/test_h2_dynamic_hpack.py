"""Encoder-side HPACK dynamic table (VERDICT r4 weak #5): responses
emitted on the reading thread (native handlers) index repeated headers
into a per-session dynamic table; py-thread responses stay on the
order-independent static encoding. A stock grpcio client's HPACK
decoder is the oracle — it tracks our table across every response on
the connection, so any state/order bug decodes as garbage headers.
"""
import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
grpc = pytest.importorskip("grpc")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class PyEchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = "py:" + request.message
        done()


@pytest.fixture(scope="module")
def mixed_server():
    # EchoService.Echo runs NATIVE (builtin handler, reading thread,
    # dynamic-table responses); PyEchoService.Echo runs on py pthreads
    # (static responses) — both on one connection.
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True,
                                       native_builtin_echo=True))
    srv.add_service(PyEchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _stub(channel, path):
    return channel.unary_unary(
        path,
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=echo_pb2.EchoResponse.FromString)


def test_many_native_responses_on_one_connection(mixed_server):
    """30 sequential native-handler responses: after the first, the
    content-type header rides a dynamic-table index — the grpcio
    decoder must follow."""
    port = mixed_server.listen_endpoint.port
    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        nat = _stub(channel, "/EchoService/Echo")
        for i in range(30):
            r = nat(echo_pb2.EchoRequest(message=f"d{i}"), timeout=10)
            assert r.message == f"d{i}"


def test_settings_table_size_zero_signals_update(mixed_server):
    """A client announcing HEADER_TABLE_SIZE=0 before any response must
    see a dynamic-table-size update(0) prefixing the first
    reading-thread header block, and no incremental-indexing
    instructions afterwards (RFC 7541 §4.2 / §6.3)."""
    import socket as pysock
    import struct

    port = mixed_server.listen_endpoint.port

    def frame(ftype, flags, sid, payload):
        return (struct.pack(">I", len(payload))[1:] +
                bytes([ftype, flags]) + struct.pack(">I", sid) + payload)

    # static-only request block for POST /EchoService/Echo
    blk = b"\x83\x86"  # :method POST, :scheme http
    path = b"/EchoService/Echo"
    blk += b"\x04" + bytes([len(path)]) + path  # :path literal
    body = b"\x00\x00\x00\x00\x00"  # empty gRPC message
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    try:
        sk.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n" +
                   frame(4, 0, 0, struct.pack(">HI", 1, 0)) +  # tbl=0
                   frame(1, 0x4, 1, blk) +
                   frame(0, 0x1, 1, body))
        sk.settimeout(5)
        buf = b""
        hdr_payload = None
        while hdr_payload is None:
            chunk = sk.recv(65536)
            if not chunk:
                break
            buf += chunk
            pos = 0
            while pos + 9 <= len(buf):
                ln = int.from_bytes(buf[pos:pos + 3], "big")
                if pos + 9 + ln > len(buf):
                    break
                ftype = buf[pos + 3]
                flags = buf[pos + 4]
                if ftype == 1 and not (flags & 0x1):  # response HEADERS
                    hdr_payload = buf[pos + 9:pos + 9 + ln]
                pos += 9 + ln
        assert hdr_payload is not None, "no response HEADERS seen"
        # first instruction: dynamic table size update to 0 (0x20)
        assert hdr_payload[0] == 0x20, hdr_payload.hex()
        # walk the block instruction by instruction: nothing may use
        # incremental indexing — the decoder has no table to store into
        assert "incr" not in _hpack_ops(hdr_payload), hdr_payload.hex()
    finally:
        sk.close()


def _hpack_ops(block: bytes):
    """Minimal HPACK instruction walker: returns the op kind sequence
    (idx / incr / resize / lit) so tests can assert on instruction
    boundaries instead of single bytes."""
    ops = []
    i = 0

    def rdint(prefix):
        nonlocal i
        v = block[i] & ((1 << prefix) - 1)
        i += 1
        if v == (1 << prefix) - 1:
            shift = 0
            while True:
                b = block[i]
                i += 1
                v += (b & 0x7F) << shift
                shift += 7
                if not b & 0x80:
                    break
        return v

    def rdstr():
        nonlocal i
        n = rdint(7)
        i += n

    while i < len(block):
        b = block[i]
        if b & 0x80:
            ops.append("idx")
            rdint(7)
        elif (b & 0xC0) == 0x40:
            ops.append("incr")
            if rdint(6) == 0:
                rdstr()
            rdstr()
        elif (b & 0xE0) == 0x20:
            ops.append("resize")
            rdint(5)
        else:
            ops.append("lit")
            if rdint(4) == 0:
                rdstr()
            rdstr()
    return ops


def test_interleaved_native_and_py_responses(mixed_server):
    """Dynamic (native) and static (py) response blocks interleave on
    one connection; static blocks must not perturb the decoder's table
    and dynamic refs must stay valid throughout."""
    port = mixed_server.listen_endpoint.port
    with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
        nat = _stub(channel, "/EchoService/Echo")
        py = _stub(channel, "/PyEchoService/Echo")
        for i in range(15):
            rn = nat(echo_pb2.EchoRequest(message=f"n{i}"), timeout=10)
            assert rn.message == f"n{i}"
            rp = py(echo_pb2.EchoRequest(message=f"p{i}"), timeout=10)
            assert rp.message == f"py:p{i}"


def test_mid_connection_shrink_update_leads_next_block(mixed_server):
    """SETTINGS_HEADER_TABLE_SIZE shrink mid-connection: the §4.2 size
    update must lead the NEXT header block on the wire even when that
    block is a py-thread STATIC response (ADVICE r5) — a strict decoder
    treats a block without the owed update as COMPRESSION_ERROR."""
    import socket as pysock
    import struct

    port = mixed_server.listen_endpoint.port

    def frame(ftype, flags, sid, payload):
        return (struct.pack(">I", len(payload))[1:] +
                bytes([ftype, flags]) + struct.pack(">I", sid) + payload)

    def req_block(path):
        blk = b"\x83\x86"  # :method POST, :scheme http
        return blk + b"\x04" + bytes([len(path)]) + path

    body = b"\x00\x00\x00\x00\x00"  # empty gRPC message

    def read_headers_frames(sk, buf, want_streams):
        """Drain frames until every stream in want_streams delivered at
        least one HEADERS; returns ({sid: [payload, ...]}, leftover)."""
        import time as _time

        got = {}
        deadline = _time.time() + 10
        while (_time.time() < deadline and
               not all(s in got for s in want_streams)):
            pos = 0
            while pos + 9 <= len(buf):
                ln = int.from_bytes(buf[pos:pos + 3], "big")
                if pos + 9 + ln > len(buf):
                    break
                ftype = buf[pos + 3]
                sid = int.from_bytes(buf[pos + 5:pos + 9], "big") & 0x7FFFFFFF
                if ftype == 1:
                    got.setdefault(sid, []).append(buf[pos + 9:pos + 9 + ln])
                pos += 9 + ln
            buf = buf[pos:]
            if all(s in got for s in want_streams):
                break
            chunk = sk.recv(65536)
            if not chunk:
                break
            buf += chunk
        return got, buf

    sk = pysock.create_connection(("127.0.0.1", port), timeout=10)
    try:
        # default table; a NATIVE response warms the dynamic encoder
        sk.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n" +
                   frame(4, 0, 0, b"") +
                   frame(1, 0x4, 1, req_block(b"/EchoService/Echo")) +
                   frame(0, 0x1, 1, body))
        sk.settimeout(10)
        got, buf = read_headers_frames(sk, b"", {1})
        assert 1 in got
        # shrink to 0, then a PY-LANE request (static response path):
        # whichever block goes out next must carry the update in front
        sk.sendall(frame(4, 0, 0, struct.pack(">HI", 1, 0)) +
                   frame(1, 0x4, 3, req_block(b"/PyEchoService/Echo")) +
                   frame(0, 0x1, 3, body))
        got, buf = read_headers_frames(sk, buf, {3})
        assert 3 in got, "no py response HEADERS seen"
        first_block = got[3][0]
        ops = _hpack_ops(first_block)
        assert ops and ops[0] == "resize", (ops, first_block.hex())
        # shrunk to 0: nothing may incrementally index afterwards
        assert "incr" not in ops, (ops, first_block.hex())
    finally:
        sk.close()
