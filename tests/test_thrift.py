"""Thrift protocol tests — codec units + framed client/server echo
(brpc_thrift* test shape)."""
import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.thrift import (
    MSG_CALL,
    T_BOOL,
    T_DOUBLE,
    T_I32,
    T_I64,
    T_LIST,
    T_STRING,
    T_STRUCT,
    ThriftMessage,
    ThriftService,
    pack_message,
    unpack_message,
)


def test_codec_roundtrip():
    body = {
        1: (T_STRING, b"hello"),
        2: (T_I32, -42),
        3: (T_I64, 1 << 40),
        4: (T_BOOL, True),
        5: (T_DOUBLE, 3.25),
        6: (T_LIST, (T_I32, [1, 2, 3])),
        7: (T_STRUCT, {1: (T_STRING, b"nested")}),
    }
    framed = pack_message("Method", MSG_CALL, 7, body)
    import struct

    (length,) = struct.unpack(">I", framed[:4])
    assert length == len(framed) - 4
    name, mtype, seqid, decoded = unpack_message(framed[4:])
    assert (name, mtype, seqid) == ("Method", MSG_CALL, 7)
    assert decoded == body


@pytest.fixture(scope="module")
def thrift_server():
    svc = ThriftService()

    def echo(body):
        msg = body.get(1, (T_STRING, b""))[1]
        return {0: (T_STRUCT, {1: (T_STRING, b"echo:" + msg)})}

    def add(body):
        a = body.get(1, (T_I32, 0))[1]
        b = body.get(2, (T_I32, 0))[1]
        return {0: (T_I32, a + b)}

    svc.add_method("Echo", echo)
    svc.add_method("Add", add)
    srv = rpc.Server(rpc.ServerOptions(thrift_service=svc, num_threads=2))
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def _thrift_channel(server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="thrift", timeout_ms=3000))
    assert ch.init(str(server.listen_endpoint)) == 0
    return ch


def test_thrift_echo(thrift_server):
    ch = _thrift_channel(thrift_server)
    req = ThriftMessage("Echo", {1: (T_STRING, b"hi")})
    resp = ThriftMessage()
    cntl = rpc.Controller()
    ch.call_method("thrift", cntl, req, resp)
    assert not cntl.failed(), cntl.error_text
    result = resp.body[0][1]  # field 0 = success struct
    assert result[1][1] == b"echo:hi"


def test_thrift_add(thrift_server):
    ch = _thrift_channel(thrift_server)
    req = ThriftMessage("Add", {1: (T_I32, 20), 2: (T_I32, 22)})
    resp = ThriftMessage()
    cntl = rpc.Controller()
    ch.call_method("thrift", cntl, req, resp)
    assert not cntl.failed(), cntl.error_text
    assert resp.body[0] == (T_I32, 42)


def test_thrift_unknown_method_raises_exception(thrift_server):
    ch = _thrift_channel(thrift_server)
    req = ThriftMessage("Missing", {})
    resp = ThriftMessage()
    cntl = rpc.Controller()
    ch.call_method("thrift", cntl, req, resp)
    assert cntl.failed()
    assert "thrift exception" in cntl.error_text


def test_thrift_sequential_calls(thrift_server):
    ch = _thrift_channel(thrift_server)
    for i in range(10):
        req = ThriftMessage("Add", {1: (T_I32, i), 2: (T_I32, i)})
        resp = ThriftMessage()
        cntl = rpc.Controller()
        ch.call_method("thrift", cntl, req, resp)
        assert not cntl.failed(), cntl.error_text
        assert resp.body[0] == (T_I32, 2 * i)
