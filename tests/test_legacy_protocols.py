"""Legacy protocol family tests — hulu_pbrpc, sofa_pbrpc, mongo server
adaptor, esp — loopback in one process, the brpc_*_protocol_unittest.cpp
pattern. All four join the multi-protocol port alongside tpu_std.
"""
import socket as pysocket
import struct

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.esp_protocol import EspMessage, EspService
from brpc_tpu.rpc.mongo import (
    HEAD_SIZE,
    MongoHead,
    MongoResponse,
    MongoServiceAdaptor,
    OP_QUERY,
    OP_REPLY,
    bson_decode,
    bson_encode,
)
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        if request.code:
            cntl.set_failed(request.code, "requested failure")
            done()
            return
        response.message = request.message
        done()


class PingAdaptor(MongoServiceAdaptor):
    def __init__(self):
        self.contexts_created = 0

    def create_socket_context(self):
        self.contexts_created += 1
        return {"n": self.contexts_created}

    def process_mongo_request(self, cntl, request, response, done):
        if request.query and "ping" in request.query:
            response.documents = [{"ok": 1.0}]
        else:
            response.documents = [{"you_said": request.collection, "ok": 1.0}]
        done()


@pytest.fixture(scope="module")
def server():
    srv = rpc.Server(rpc.ServerOptions(
        num_threads=4,
        mongo_service_adaptor=PingAdaptor(),
        esp_service=EspService(),
    ))
    assert srv.add_service(EchoService()) == 0
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()
    srv.join(1)


def _echo_check(server, protocol, msg="hi legacy"):
    ch = rpc.Channel(rpc.ChannelOptions(protocol=protocol))
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message=msg),
                         echo_pb2.EchoResponse)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == msg
    ch.close()


def test_hulu_echo(server):
    _echo_check(server, "hulu_pbrpc")


def test_sofa_echo(server):
    _echo_check(server, "sofa_pbrpc")


def test_hulu_error_propagates(server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="hulu_pbrpc"))
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl, _ = ch.call("EchoService.Echo",
                      echo_pb2.EchoRequest(message="x", code=42),
                      echo_pb2.EchoResponse)
    assert cntl.failed() and cntl.error_code_value == 42
    # unknown method -> ENOMETHOD from the server
    cntl2, _ = ch.call("EchoService.Nope",
                       echo_pb2.EchoRequest(message="x"),
                       echo_pb2.EchoResponse)
    assert cntl2.failed() and cntl2.error_code_value == errors.ENOMETHOD
    ch.close()


def test_sofa_error_propagates(server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="sofa_pbrpc"))
    assert ch.init(str(server.listen_endpoint)) == 0
    cntl, _ = ch.call("NoSuchService.Echo",
                      echo_pb2.EchoRequest(message="x"),
                      echo_pb2.EchoResponse)
    assert cntl.failed() and cntl.error_code_value == errors.ENOSERVICE
    ch.close()


def test_hulu_many_pipelined(server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="hulu_pbrpc"))
    assert ch.init(str(server.listen_endpoint)) == 0
    for i in range(30):
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message=f"m{i}"),
                             echo_pb2.EchoResponse)
        assert not cntl.failed() and resp.message == f"m{i}"
    ch.close()


def test_bson_roundtrip():
    doc = {"s": "str", "i": 5, "big": 1 << 40, "f": 2.5, "b": True,
           "n": None, "sub": {"k": "v"}, "arr": [1, "two", 3.0],
           "bin": b"\x00\x01\x02"}
    enc = bson_encode(doc)
    dec, end = bson_decode(enc)
    assert end == len(enc)
    assert dec == doc


def _mongo_query(port, collection, query_doc, request_id=7):
    """A raw OP_QUERY client (what a mongo driver sends)."""
    body = struct.pack("<i", 0) + collection.encode() + b"\x00"
    body += struct.pack("<ii", 0, 1) + bson_encode(query_doc)
    head = MongoHead(HEAD_SIZE + len(body), request_id, 0, OP_QUERY)
    with pysocket.create_connection(("127.0.0.1", port), timeout=5) as s:
        s.sendall(head.pack() + body)
        raw = b""
        while len(raw) < HEAD_SIZE:
            raw += s.recv(4096)
        rhead = MongoHead.unpack(raw)
        while len(raw) < rhead.message_length:
            raw += s.recv(4096)
    assert rhead.op_code == OP_REPLY
    assert rhead.response_to == request_id
    flags, cursor, start, nret = struct.unpack_from("<iqii", raw, HEAD_SIZE)
    doc, _ = bson_decode(raw, HEAD_SIZE + 20)
    return flags, nret, doc


def test_mongo_ping(server):
    port = server.listen_endpoint.port
    flags, nret, doc = _mongo_query(port, "admin.$cmd", {"ping": 1})
    assert flags == 0 and nret == 1
    assert doc == {"ok": 1.0}


def test_mongo_context_attached(server):
    adaptor = server.options.mongo_service_adaptor
    before = adaptor.contexts_created
    _mongo_query(server.listen_endpoint.port, "db.c", {"find": "c"})
    assert adaptor.contexts_created == before + 1  # one context per conn


def test_esp_roundtrip(server):
    ch = rpc.Channel(rpc.ChannelOptions(protocol="esp",
                                        connection_type="pooled"))
    assert ch.init(str(server.listen_endpoint)) == 0
    req = EspMessage(b"esp payload", to_addr=9, msg=3, msg_id=77)
    cntl, resp = ch.call("esp.msg", req, EspMessage)
    assert not cntl.failed(), cntl.error_text
    assert resp.body == b"esp payload"
    assert resp.msg_id == 77
    ch.close()


def test_legacy_protocols_share_port_with_tpu_std(server):
    """hulu + sofa + tpu_std + mongo + esp all on ONE port."""
    _echo_check(server, "hulu_pbrpc", "via hulu")
    _echo_check(server, "sofa_pbrpc", "via sofa")
    _echo_check(server, "tpu_std", "via std")
    _, _, doc = _mongo_query(server.listen_endpoint.port, "x", {"ping": 1})
    assert doc["ok"] == 1.0


def test_snappy_codec():
    from brpc_tpu.rpc import compress as c

    for data in (b"", b"a", b"abc", b"x" * 100000,
                 b"the quick brown fox " * 500,
                 bytes(range(256)) * 40):
        enc = c.snappy_compress(data)
        assert c.snappy_decompress(enc) == data
    # repetitive data actually compresses
    rep = b"hello world, hello world! " * 1000
    assert len(c.snappy_compress(rep)) < len(rep) // 4
    # corrupt offsets rejected
    with pytest.raises(ValueError):
        c.snappy_decompress(b"\x05\x09\x00\x01")


@pytest.mark.parametrize("protocol", ["hulu_pbrpc", "sofa_pbrpc", "tpu_std"])
@pytest.mark.parametrize("ctype", [1, 2, 3])  # gzip, zlib, snappy
def test_compression_negotiation(server, protocol, ctype):
    """Per-protocol compression: request+response ride the negotiated
    codec (hulu/sofa remap to their own enum values on the wire)."""
    ch = rpc.Channel(rpc.ChannelOptions(protocol=protocol))
    assert ch.init(str(server.listen_endpoint)) == 0
    msg = "compress me " * 200
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message=msg),
                         echo_pb2.EchoResponse, compress_type=ctype)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == msg
    ch.close()


# -- nshead-framed pb-rpc variants (nova/public/ubrpc) ----------------------

def _variant_server(adaptor_cls):
    from brpc_tpu.rpc import legacy_nshead_family as fam  # noqa: F401

    class VEcho(rpc.Service):
        SERVICE_NAME = "EchoService"

        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            response.message = request.message[::-1]
            done()

    srv = rpc.Server(rpc.ServerOptions(
        num_threads=2, nshead_service=adaptor_cls(VEcho())))
    assert srv.start("127.0.0.1:0") == 0
    return srv


def test_nova_pbrpc_roundtrip():
    from brpc_tpu.rpc.legacy_nshead_family import NovaServiceAdaptor

    srv = _variant_server(NovaServiceAdaptor)
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="nova_pbrpc",
                                            connection_type="pooled"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="nova"),
                             echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "avon"
        # snappy lane: nshead.version flag drives body compression
        cntl2, resp2 = ch.call("EchoService.Echo",
                               echo_pb2.EchoRequest(message="nova" * 100),
                               echo_pb2.EchoResponse, compress_type=3)
        assert not cntl2.failed(), cntl2.error_text
        assert resp2.message == ("nova" * 100)[::-1]
        ch.close()
    finally:
        srv.stop()


def test_public_pbrpc_roundtrip():
    from brpc_tpu.rpc.legacy_nshead_family import PublicPbrpcServiceAdaptor

    srv = _variant_server(PublicPbrpcServiceAdaptor)
    try:
        # correlation rides the envelope body.id: single connections work
        ch = rpc.Channel(rpc.ChannelOptions(protocol="public_pbrpc"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        for i in range(5):
            cntl, resp = ch.call("EchoService.Echo",
                                 echo_pb2.EchoRequest(message=f"pub{i}"),
                                 echo_pb2.EchoResponse)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == f"pub{i}"[::-1]
        ch.close()
    finally:
        srv.stop()


def test_ubrpc_roundtrip():
    from brpc_tpu.rpc.legacy_nshead_family import UbrpcServiceAdaptor

    srv = _variant_server(UbrpcServiceAdaptor)
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="ubrpc",
                                            connection_type="pooled"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="ubrpc!"),
                             echo_pb2.EchoResponse)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "!cprbu"
        # unknown method surfaces the mcpack error object
        cntl2, _ = ch.call("EchoService.Nope",
                           echo_pb2.EchoRequest(message="x"),
                           echo_pb2.EchoResponse)
        assert cntl2.failed() and cntl2.error_code_value == errors.ENOMETHOD
        ch.close()
    finally:
        srv.stop()


def test_nova_unknown_method_fails():
    from brpc_tpu.rpc.legacy_nshead_family import NovaServiceAdaptor

    srv = _variant_server(NovaServiceAdaptor)
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="nova_pbrpc",
                                            connection_type="pooled"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl, _ = ch.call("EchoService.Nope",
                          echo_pb2.EchoRequest(message="x"),
                          echo_pb2.EchoResponse)
        assert cntl.failed() and cntl.error_code_value == errors.ENOMETHOD
        ch.close()
    finally:
        srv.stop()


def test_public_unknown_method_fails():
    from brpc_tpu.rpc.legacy_nshead_family import PublicPbrpcServiceAdaptor

    srv = _variant_server(PublicPbrpcServiceAdaptor)
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="public_pbrpc"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl, _ = ch.call("EchoService.Nope",
                          echo_pb2.EchoRequest(message="x"),
                          echo_pb2.EchoResponse)
        assert cntl.failed() and cntl.error_code_value == errors.ENOMETHOD
        ch.close()
    finally:
        srv.stop()


class AttachEchoService(rpc.Service):
    SERVICE_NAME = "AttachEcho"

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        # bounce the request attachment back as the response attachment
        att = cntl.request_attachment.copy_to_bytes(
            len(cntl.request_attachment))
        response.message = request.message
        cntl.response_attachment.append(att.upper())
        done()


def test_hulu_attachment_roundtrip():
    """user_message_size splits pb bytes from the attachment on BOTH
    directions (hulu_pbrpc_protocol.cpp:354-359)."""
    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    assert srv.add_service(AttachEchoService()) == 0
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="hulu_pbrpc"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl = rpc.Controller()
        cntl.request_attachment.append(b"raw-bytes")
        resp = echo_pb2.EchoResponse()
        ch.call_method("AttachEcho.Echo", cntl,
                       echo_pb2.EchoRequest(message="att"), resp)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "att"
        got = cntl.response_attachment.copy_to_bytes(
            len(cntl.response_attachment))
        assert got == b"RAW-BYTES"
        ch.close()
    finally:
        srv.stop()


def test_hulu_method_index_honored():
    """cntl.hulu_method_index rides the wire (the nova_method_index
    discipline) so multi-method stock hulu services dispatch correctly."""
    from brpc_tpu.rpc import hulu_protocol
    from brpc_tpu.rpc.proto import legacy_meta_pb2

    cntl = rpc.Controller()
    cntl._method_full_name = "EchoService.Echo"
    cntl.hulu_method_index = 3
    buf = hulu_protocol.pack_request(b"", cntl, 7)
    raw = buf.copy_to_bytes(len(buf))
    meta = legacy_meta_pb2.HuluRpcRequestMeta()
    import struct as _struct
    _, meta_size = _struct.unpack("<II", raw[4:12])
    meta.ParseFromString(raw[12:12 + meta_size])
    assert meta.method_index == 3
    assert meta.method_name == "Echo"


def test_hulu_attachment_with_compression():
    """The attachment split happens on COMPRESSED pb bytes: gzip + a raw
    attachment must both survive the round trip."""
    from brpc_tpu.rpc import compress as compress_mod

    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    assert srv.add_service(AttachEchoService()) == 0
    assert srv.start("127.0.0.1:0") == 0
    try:
        ch = rpc.Channel(rpc.ChannelOptions(protocol="hulu_pbrpc"))
        assert ch.init(str(srv.listen_endpoint)) == 0
        cntl = rpc.Controller()
        cntl.compress_type = compress_mod.COMPRESS_GZIP
        cntl.request_attachment.append(b"zip-side-raw")
        resp = echo_pb2.EchoResponse()
        ch.call_method("AttachEcho.Echo", cntl,
                       echo_pb2.EchoRequest(message="gz" * 300), resp)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "gz" * 300
        got = cntl.response_attachment.copy_to_bytes(
            len(cntl.response_attachment))
        assert got == b"ZIP-SIDE-RAW"
        ch.close()
    finally:
        srv.stop()
