"""Golden tests for the perf regression gate (tools/natcheck/benchgate).

The gate's verdict logic is a pure function over two schema'd artifacts,
so every contract is pinned with seeded artifact pairs: clean run,
one-lane regression (hard fail, with the regressing run's profile
attached), silently-missing lane, schema drift, a failed bench process,
and the wider tolerance bands on the documented-noisy lanes. The
shipped tree must be green: the committed BENCH_r06 baseline compared
against itself produces no findings.
"""
import copy
import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.natcheck import REPO_ROOT, benchgate  # noqa: E402


def _bench_result():
    """A plausible bench.py output covering every headline lane."""
    return {
        "metric": "echo_qps_framework_native",
        "value": 2300000.0,
        "unit": "qps",
        "vs_baseline": 4.6,
        "extra": {
            "epoll_qps": 880000.0,
            "io_uring_qps": 900000.0,
            "io_uring_async_qps": 2300000.0,
            "async_windowed_qps": 2070000.0,
            "http_qps": 604000.0,
            "http_py_qps": 8400.0,
            "grpc_qps": 491000.0,
            "grpc_py_qps": 15800.0,
            "grpc_client_qps": 257000.0,
            "http_client_qps": 364000.0,
            "redis_qps": 1430000.0,
            "redis_py_qps": 39700.0,
            "http_py_workers_qps": 2051.0,
            "stream_GBps": 0.86,
            "native_bulk_GBps": 1.66,
            "shm_desc_GBps": 1.45,
            "shm_desc_small_GBps": 0.19,
            "fanout_qps": 4500.0,
            "fanout_p99_us": 3200.0,
            "fanout_py_qps": 130.0,
            "fanout1000_qps": 60.0,
            "swarm_qps": 38000.0,
            "swarm_p99_us": 820.0,
            "conn_scale_conns": 19000.0,
            "conn_per_conn_bytes": 14000.0,
            "conn_accept_storm_s": 12.0,
            "fleet_scrape_overhead_pct": 1.1,
            "native_latency_us": {"echo": {"p50": 10.0, "p99": 50.0,
                                           "p999": 200.0}},
            "nat_prof": {"samples": 1234,
                         "flat": ["     100  10.0%  drain_socket_inline",
                                  "      80   8.0%  process_input"]},
            "scaling": {"1": 250000.0, "2": 437500.0,
                        "host_parallel_x": 1.9,
                        "cpu_sets": {"1": {"server": [0],
                                           "clients": [[0]]},
                                     "2": {"server": [0, 1],
                                           "clients": [[0], [1]]}}},
        },
    }


@pytest.fixture()
def pair():
    base = benchgate.make_artifact(_bench_result(), round_n=6,
                                   git_sha="abc123")
    cur = copy.deepcopy(base)
    return base, cur


def _rules(findings):
    return sorted(f.rule for f in findings)


def test_clean_pair_passes(pair):
    base, cur = pair
    assert benchgate.compare(base, cur) == []


def test_improvement_passes(pair):
    base, cur = pair
    cur["lanes"]["http_qps"] *= 1.5
    assert benchgate.compare(base, cur) == []


def test_one_lane_regression_fails_with_profile_attached(pair):
    base, cur = pair
    cur["lanes"]["http_qps"] *= 0.80  # -20% > the 15% band
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["regression"]
    msg = findings[0].message
    assert "http_qps" in msg and "20.0%" in msg
    # the regressing run's nat_prof flat profile rides the report
    assert "drain_socket_inline" in msg


def test_within_band_regression_passes(pair):
    base, cur = pair
    cur["lanes"]["http_qps"] *= 0.90  # -10% < the 15% band
    assert benchgate.compare(base, cur) == []


def test_noisy_lane_wider_band(pair):
    base, cur = pair
    # worker lane documented at 50%: -40% passes, -60% fails
    cur["lanes"]["http_py_workers_qps"] = \
        base["lanes"]["http_py_workers_qps"] * 0.60
    assert benchgate.compare(base, cur) == []
    cur["lanes"]["http_py_workers_qps"] = \
        base["lanes"]["http_py_workers_qps"] * 0.40
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["regression"]


def test_missing_lane_fails(pair):
    base, cur = pair
    del cur["lanes"]["grpc_qps"]
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["missing-lane"]
    assert "grpc_qps" in findings[0].message


def test_zero_baseline_lane_is_skipped(pair):
    """An unmeasurable baseline lane (io_uring refused by the kernel)
    holds nothing against later runs."""
    base, cur = pair
    base["lanes"]["io_uring_qps"] = 0.0
    del cur["lanes"]["io_uring_qps"]
    assert benchgate.compare(base, cur) == []


def test_scaling_lane_unmeasurable_on_one_cpu_host(pair):
    """A 1-cpu container cannot measure a 2-cpu scaling ratio: a
    missing cpus2_scaling_x with extra.host_cpus == 1 is unmeasurable
    (the current-side twin of the zero-baseline skip), while a >= 2 cpu
    host dropping it is still a finding."""
    base, cur = pair
    del cur["lanes"]["cpus2_scaling_x"]
    cur["bench"]["extra"]["host_cpus"] = 1
    assert benchgate.compare(base, cur) == []
    cur["bench"]["extra"]["host_cpus"] = 2
    assert _rules(benchgate.compare(base, cur)) == ["missing-lane"]


def test_fanout_lane_regression_fails(pair):
    """The native fan-out verb lane holds its 30% band; a zero-qps run
    (the zero-failed-RPC contract reporting failures as 0) hard-fails."""
    base, cur = pair
    cur["lanes"]["fanout_qps"] = base["lanes"]["fanout_qps"] * 0.70
    assert benchgate.compare(base, cur) == []
    cur["lanes"]["fanout_qps"] = base["lanes"]["fanout_qps"] * 0.60
    assert _rules(benchgate.compare(base, cur)) == ["regression"]
    cur["lanes"]["fanout_qps"] = 0.0  # a failed drill reports 0 qps
    assert _rules(benchgate.compare(base, cur)) == ["regression"]


def test_swarm_zero_failed_contract_trips_gate(pair):
    base, cur = pair
    cur["lanes"]["swarm_qps"] = 0.0
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["regression"]
    assert "swarm_qps" in findings[0].message


def test_latency_ceiling_lane_regresses_upward(pair):
    """fanout_p99_us is a CEILING lane: falling is fine, rising past
    baseline * (1 + band) is a tail regression even when qps held."""
    base, cur = pair
    cur["lanes"]["fanout_p99_us"] = base["lanes"]["fanout_p99_us"] * 0.5
    assert benchgate.compare(base, cur) == []
    cur["lanes"]["fanout_p99_us"] = base["lanes"]["fanout_p99_us"] * 1.4
    assert benchgate.compare(base, cur) == []  # inside the 50% band
    cur["lanes"]["fanout_p99_us"] = base["lanes"]["fanout_p99_us"] * 1.7
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["regression"]
    assert "upward" in findings[0].message


def test_conn_scale_zero_failed_contract_trips_gate(pair):
    # the conn-scale drill reports 0 connections when ANY live-subset
    # RPC failed, the storm left connections unanswered, or a transient
    # subsystem leaked — the gate must read that as a collapse
    base, cur = pair
    cur["lanes"]["conn_scale_conns"] = 0.0
    findings = benchgate.compare(base, cur)
    assert "regression" in _rules(findings)
    assert any("conn_scale_conns" in f.message for f in findings)


def test_conn_per_conn_bytes_ceiling_regresses_upward(pair):
    # per-connection memory cost is a CEILING lane: regressing UPWARD
    # past baseline * (1 + band) fails even when every qps lane held
    base, cur = pair
    cur["lanes"]["conn_per_conn_bytes"] = 14000.0 * 1.8  # +80% > 50%
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["regression"]
    assert "conn_per_conn_bytes" in findings[0].message
    assert "upward" in findings[0].message


def test_conn_ceilings_within_band_pass(pair):
    base, cur = pair
    cur["lanes"]["conn_per_conn_bytes"] = 14000.0 * 1.3   # < 50% band
    cur["lanes"]["conn_accept_storm_s"] = 12.0 * 1.7      # < 100% band
    assert benchgate.compare(base, cur) == []


def test_accept_storm_ceiling_regresses_upward(pair):
    base, cur = pair
    cur["lanes"]["conn_accept_storm_s"] = 12.0 * 2.5  # +150% > 100%
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["regression"]
    assert "conn_accept_storm_s" in findings[0].message


def test_conn_ceiling_baseline_takes_max():
    # make_baseline records the credible WORST case for ceiling lanes
    arts = []
    for v in (9.0, 14.0, 11.0):
        b = _bench_result()
        b["extra"]["conn_accept_storm_s"] = v
        arts.append(benchgate.make_artifact(b, round_n=1))
    base = benchgate.make_baseline(arts, round_n=9)
    assert base["lanes"]["conn_accept_storm_s"] == 14.0


def test_ceiling_lane_baseline_takes_max():
    """make_baseline composes latency ceilings from the MAXIMUM over
    clean rounds (the worst credible case), not the minimum."""
    a1 = benchgate.make_artifact(_bench_result(), round_n=1)
    a2 = copy.deepcopy(a1)
    a1["lanes"]["fanout_p99_us"] = 1000.0
    a2["lanes"]["fanout_p99_us"] = 3000.0
    a1["lanes"]["fanout_qps"] = 5000.0
    a2["lanes"]["fanout_qps"] = 4000.0
    base = benchgate.make_baseline([a1, a2], round_n=8)
    assert base["lanes"]["fanout_p99_us"] == 3000.0  # ceiling: max
    assert base["lanes"]["fanout_qps"] == 4000.0     # floor: min


def test_schema_drift_fails(pair):
    base, cur = pair
    cur["schema"] = "brpc_tpu-bench-artifact/999"
    findings = benchgate.compare(base, cur)
    assert "schema-drift" in _rules(findings)


def test_failed_bench_process_fails(pair):
    base, cur = pair
    cur["rc"] = 139  # the BENCH_r05 class
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["bench-failed"]
    assert "139" in findings[0].message


def test_artifact_schema_fields():
    art = benchgate.make_artifact(_bench_result(), round_n=6,
                                  git_sha="abc123")
    assert art["schema"] == benchgate.SCHEMA
    assert art["git_sha"] == "abc123"
    assert art["lanes"]["value"] == 2300000.0
    assert art["rpcz_percentiles"]["echo"]["p99"] == 50.0
    assert art["nat_prof"]["samples"] == 1234


def test_make_baseline_takes_lane_floor(pair):
    """The committed baseline is the per-lane MINIMUM over N clean runs
    (the host's credible floor against shared-container noise)."""
    a, b = pair
    b = copy.deepcopy(b)
    b["lanes"]["http_qps"] = a["lanes"]["http_qps"] * 0.7
    b["lanes"]["grpc_qps"] = a["lanes"]["grpc_qps"] * 1.4
    base = benchgate.make_baseline([a, b], round_n=6)
    assert base["n"] == 6
    assert base["baseline_runs"] == 2
    assert base["lanes"]["http_qps"] == b["lanes"]["http_qps"]
    assert base["lanes"]["grpc_qps"] == a["lanes"]["grpc_qps"]
    # failed runs are excluded from the floor
    dead = copy.deepcopy(a)
    dead["rc"] = 139
    dead["lanes"]["http_qps"] = 1.0
    base2 = benchgate.make_baseline([a, b, dead], round_n=6)
    assert base2["lanes"]["http_qps"] == b["lanes"]["http_qps"]
    with pytest.raises(ValueError):
        benchgate.make_baseline([dead], round_n=6)


def test_scaling_lane_derived_from_curve():
    """The cpus2_scaling_x lane = qps(2)/qps(1) out of extra.scaling,
    and the raw curve rides the artifact for the record."""
    art = benchgate.make_artifact(_bench_result(), round_n=7)
    assert art["lanes"]["cpus2_scaling_x"] == pytest.approx(1.75)
    assert art["scaling"]["host_parallel_x"] == 1.9
    assert art["scaling"]["1"] == 250000.0


def test_scaling_regression_beyond_band_fails(pair):
    base, cur = pair
    # 1.75x baseline, 35% band -> floor 1.1375; a 1.0x run fails
    cur["lanes"]["cpus2_scaling_x"] = 1.0
    cur["scaling"] = dict(cur["scaling"], host_parallel_x=1.3)  # host capped:
    # only the banded comparison fires, not the absolute floor
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["regression"]
    assert "cpus2_scaling_x" in findings[0].message


def test_sublinear_scaling_with_host_headroom_fails(pair):
    """The absolute floor: host probe shows real parallel capacity but
    the runtime scaled < 1.15x — fails EVEN when within the baseline
    band (and even with no baseline scaling lane at all)."""
    base, cur = pair
    del base["lanes"]["cpus2_scaling_x"]  # pre-scaling baseline (r06)
    cur["lanes"]["cpus2_scaling_x"] = 1.05
    cur["scaling"] = dict(cur["scaling"], host_parallel_x=1.9)
    findings = benchgate.compare(base, cur)
    assert _rules(findings) == ["sublinear-scaling"]
    assert "1.05x" in findings[0].message


def test_sublinear_scaling_on_overcommitted_host_passes(pair):
    """No parallel headroom on the host (shared-container probe below
    the bar): a flat curve is the host's fault, not a finding."""
    base, cur = pair
    del base["lanes"]["cpus2_scaling_x"]
    cur["lanes"]["cpus2_scaling_x"] = 1.05
    cur["scaling"] = dict(cur["scaling"], host_parallel_x=1.4)
    assert benchgate.compare(base, cur) == []


def test_make_baseline_takes_scaling_best():
    """Scaling ratios bake the best ACHIEVED ratio into the baseline
    (min would enshrine a crushed shared-host round as the bar)."""
    a = benchgate.make_artifact(_bench_result(), round_n=7)
    b = copy.deepcopy(a)
    b["lanes"]["cpus2_scaling_x"] = 1.02
    b["lanes"]["http_qps"] = a["lanes"]["http_qps"] * 0.9
    base = benchgate.make_baseline([a, b], round_n=7)
    assert base["lanes"]["cpus2_scaling_x"] == \
        a["lanes"]["cpus2_scaling_x"]  # max for ratios
    assert base["lanes"]["http_qps"] == b["lanes"]["http_qps"]  # min for qps


def test_committed_baseline_is_green():
    """The shipped tree: the newest committed BENCH_r*.json speaks the
    artifact schema and passes the gate against itself (the baseline the
    next round diffs against)."""
    path = benchgate.find_baseline()
    assert path is not None, \
        "no schema'd BENCH_r*.json committed (expected BENCH_r06.json)"
    with open(path, "r", encoding="utf-8") as f:
        doc = json.load(f)
    assert doc["rc"] == 0
    assert doc["lanes"], "baseline carries no headline lanes"
    assert benchgate.compare(doc, doc) == []


def test_old_artifacts_are_not_baselines():
    """Pre-gate rounds (BENCH_r05 and earlier) have no schema field and
    must never be picked as the diff baseline."""
    path = benchgate.find_baseline()
    if path is None:
        pytest.skip("no schema'd baseline committed yet")
    n = int(os.path.basename(path)[len("BENCH_r"):-len(".json")])
    assert n >= 6


def test_schema_bump_is_backward_compatible(pair):
    """ISSUE 9 satellite: the /2 bump (extra.contention) must keep
    gating against committed /1 baselines (BENCH_r07) — only a genuinely
    foreign schema is a drift finding."""
    base, cur = pair
    base["schema"] = "brpc_tpu-bench-artifact/1"
    assert "brpc_tpu-bench-artifact/1" in benchgate.SCHEMA_COMPAT
    assert benchgate.compare(base, cur) == []
    # and the other direction (re-diffing an old artifact) still works
    assert benchgate.compare(cur, base) == []


def test_artifact_records_contention(pair):
    """The gated artifact carries extra.contention (top lock-wait
    stacks), and a sublinear-scaling finding attaches both the
    dispatcher-balance rows and the lock-wait stacks as evidence."""
    bench = _bench_result()
    bench["extra"]["contention"] = {
        "samples": 9,
        "ranks": [{"rank": 40, "name": "http.sess", "waits": 9,
                   "wait_us": 1200}],
        "collapsed": ["flush_chain;lock:http.sess<40> 1200"],
    }
    bench["extra"]["scaling"]["disp_stats"] = {
        "2": [{"sockets": 2, "wakeups": 900, "sqpoll": -1},
              {"sockets": 0, "wakeups": 3, "sqpoll": -1}]}
    art = benchgate.make_artifact(bench, round_n=9)
    assert art["schema"] == benchgate.SCHEMA
    assert art["contention"]["samples"] == 9
    base, cur = copy.deepcopy(art), copy.deepcopy(art)
    cur["lanes"]["cpus2_scaling_x"] = 1.0
    cur["scaling"]["host_parallel_x"] = 1.9
    findings = benchgate.compare(base, cur)
    sub = [f for f in findings if f.rule == "sublinear-scaling"]
    assert sub, _rules(findings)
    assert "per-dispatcher rows" in sub[0].message
    assert "lock:http.sess" in sub[0].message


def test_fleet_scrape_lane_is_carried():
    """extract_lanes picks the fleet-observatory overhead lane out of
    extra, and make_baseline keeps the MAX (the worst credible cost)."""
    art = benchgate.make_artifact(_bench_result(), round_n=9)
    assert art["lanes"]["fleet_scrape_overhead_pct"] == 1.1
    a2 = copy.deepcopy(art)
    a2["lanes"]["fleet_scrape_overhead_pct"] = 2.4
    base = benchgate.make_baseline([art, a2], round_n=9)
    assert base["lanes"]["fleet_scrape_overhead_pct"] == 2.4


def test_fleet_scrape_overhead_absolute_ceiling(pair):
    """The 1Hz-scrape <=3% contract is ABSOLUTE: it trips on the fixed
    bar even when the committed baseline itself is above it."""
    base, cur = pair
    cur["lanes"]["fleet_scrape_overhead_pct"] = 3.4
    base["lanes"]["fleet_scrape_overhead_pct"] = 4.0  # bad baseline
    findings = benchgate.compare(base, cur)
    assert "abs-ceiling" in _rules(findings)
    msg = [f for f in findings if f.rule == "abs-ceiling"][0].message
    assert "fleet_scrape_overhead_pct" in msg and "3.00" in msg


def test_fleet_scrape_overhead_under_bar_passes(pair):
    base, cur = pair
    cur["lanes"]["fleet_scrape_overhead_pct"] = 2.9
    assert benchgate.compare(base, cur) == []
    # unmeasured (lane absent) is a skip, not a finding
    del cur["lanes"]["fleet_scrape_overhead_pct"]
    del base["lanes"]["fleet_scrape_overhead_pct"]
    assert benchgate.compare(base, cur) == []
