"""rpcz tracing tests — span creation on both sides, parent/child chaining
through nested calls (the tls_bls parenting of span.h:76,116), trace-id
propagation over the wire, /rpcz page (SURVEY.md section 5).
"""
import http.client
import time

import pytest

from brpc_tpu import rpc, rpcz
from brpc_tpu.rpc.proto import echo_pb2


class FrontService(rpc.Service):
    """Calls a backend inside its handler — the cascade shape that must
    chain spans."""

    backend_channel = None

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Front(self, cntl, request, response, done):
        assert rpcz.current_parent() is not None  # server span active
        back_cntl, back_resp = self.backend_channel.call(
            "BackService.Back", echo_pb2.EchoRequest(message=request.message),
            echo_pb2.EchoResponse, timeout_ms=3000,
        )
        response.message = f"front({back_resp.message})"
        done()


class BackService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Back(self, cntl, request, response, done):
        response.message = f"back({request.message})"
        done()


@pytest.fixture(scope="module")
def cascade():
    back_srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    back_srv.add_service(BackService())
    assert back_srv.start("127.0.0.1:0") == 0
    back_ch = rpc.Channel()
    assert back_ch.init(str(back_srv.listen_endpoint)) == 0
    front_svc = FrontService()
    front_svc.backend_channel = back_ch
    front_srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    front_srv.add_service(front_svc)
    assert front_srv.start("127.0.0.1:0") == 0
    yield front_srv, back_srv
    front_srv.stop()
    back_srv.stop()


def test_spans_collected(cascade):
    front_srv, _ = cascade
    rpcz.clear_for_tests()
    ch = rpc.Channel()
    assert ch.init(str(front_srv.listen_endpoint)) == 0
    cntl, resp = ch.call("FrontService.Front",
                         echo_pb2.EchoRequest(message="t"),
                         echo_pb2.EchoResponse, timeout_ms=5000)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "front(back(t))"
    time.sleep(0.1)
    spans = rpcz.recent_spans()
    kinds = [(s.kind, s.full_method) for s in spans]
    assert ("server", "FrontService.Front") in kinds
    assert ("server", "BackService.Back") in kinds
    assert ("client", "FrontService.Front") in kinds
    assert ("client", "BackService.Back") in kinds


def test_trace_chains_across_hops(cascade):
    front_srv, _ = cascade
    rpcz.clear_for_tests()
    ch = rpc.Channel()
    assert ch.init(str(front_srv.listen_endpoint)) == 0
    cntl, _ = ch.call("FrontService.Front",
                      echo_pb2.EchoRequest(message="x"),
                      echo_pb2.EchoResponse, timeout_ms=5000)
    assert not cntl.failed()
    time.sleep(0.1)
    spans = rpcz.recent_spans()
    front_server = next(s for s in spans
                        if (s.kind, s.full_method) == ("server",
                                                       "FrontService.Front"))
    back_client = next(s for s in spans
                       if (s.kind, s.full_method) == ("client",
                                                      "BackService.Back"))
    back_server = next(s for s in spans
                       if (s.kind, s.full_method) == ("server",
                                                      "BackService.Back"))
    # One trace end to end; back_client is a child of the front server span
    assert back_client.trace_id == front_server.trace_id
    assert back_client.parent_span_id == front_server.span_id
    assert back_server.trace_id == front_server.trace_id
    assert back_server.parent_span_id == back_client.span_id
    assert front_server.latency_us > 0


def test_span_annotations():
    span = rpcz.Span("server", "X.Y")
    span.annotate("step one")
    span.annotate("step two")
    span.end(0)
    text = span.describe()
    assert "step one" in text and "step two" in text


def test_rpcz_page(cascade):
    front_srv, _ = cascade
    ch = rpc.Channel()
    assert ch.init(str(front_srv.listen_endpoint)) == 0
    ch.call("FrontService.Front", echo_pb2.EchoRequest(message="p"),
            echo_pb2.EchoResponse, timeout_ms=5000)
    time.sleep(0.1)
    conn = http.client.HTTPConnection("127.0.0.1",
                                      front_srv.listen_endpoint.port,
                                      timeout=5)
    conn.request("GET", "/rpcz")
    r = conn.getresponse()
    body = r.read().decode()
    assert r.status == 200
    assert "FrontService.Front" in body
    conn.close()


def test_rpcz_disable_flag(cascade):
    from brpc_tpu.butil import flags

    front_srv, _ = cascade
    rpcz.clear_for_tests()
    assert flags.set_flag("enable_rpcz", False)
    try:
        ch = rpc.Channel()
        assert ch.init(str(front_srv.listen_endpoint)) == 0
        ch.call("FrontService.Front", echo_pb2.EchoRequest(message="d"),
                echo_pb2.EchoResponse, timeout_ms=5000)
        time.sleep(0.1)
        assert rpcz.recent_spans() == []
    finally:
        flags.set_flag("enable_rpcz", True)
