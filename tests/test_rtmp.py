"""RTMP family tests — AMF0 codec, FLV muxer, chunk layer, and an
end-to-end publish->play relay over a real multi-protocol server port
(the rtmp_protocol.cpp + amf.cpp + rtmp.cpp coverage slots)."""
import json
import socket as pysocket
import struct
import threading
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import amf, flv
from brpc_tpu.rpc import rtmp_protocol as rtmp
from brpc_tpu.rpc.proto import echo_pb2


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


# ---------------------------------------------------------------------------
# AMF0
# ---------------------------------------------------------------------------

def test_amf0_roundtrip():
    values = ["connect", 1.0, {"app": "live", "flashVer": "v1",
                               "nested": {"a": 2.5, "b": True}},
              None, [1.0, "two", False], "x" * 70000]
    blob = amf.encode_many(*values)
    back = amf.decode_all(blob)
    assert back == values


def test_amf0_ecma_array_and_errors():
    # ECMA array decodes as a dict (count hint + end marker)
    blob = bytes([amf.AMF0_ECMA_ARRAY]) + struct.pack(">I", 1)
    blob += struct.pack(">H", 3) + b"key" + amf.encode(5.0)
    blob += struct.pack(">H", 0) + bytes([amf.AMF0_OBJECT_END])
    v, pos = amf.decode(blob)
    assert v == {"key": 5.0} and pos == len(blob)
    with pytest.raises(amf.AmfError):
        amf.decode(b"\x00\x01")  # truncated number
    with pytest.raises(amf.AmfError):
        amf.decode(b"\x42")  # unknown marker


# ---------------------------------------------------------------------------
# FLV
# ---------------------------------------------------------------------------

def test_flv_roundtrip(tmp_path):
    path = tmp_path / "t.flv"
    with open(path, "wb") as fp:
        w = flv.FlvWriter(fp)
        w.write_metadata(0, amf.encode_many("onMetaData", {"fps": 30.0}))
        w.write_video(10, b"\x17\x00cfg")
        w.write_audio(12, b"\xaf\x00cfg")
        w.write_video(40, b"\x27\x01frame" * 3)
    data = open(path, "rb").read()
    assert flv.probe(data) == {"version": 1, "has_audio": True,
                               "has_video": True}
    tags = list(flv.read_tags(data))
    assert [t[0] for t in tags] == [flv.FLV_TAG_SCRIPT, flv.FLV_TAG_VIDEO,
                                    flv.FLV_TAG_AUDIO, flv.FLV_TAG_VIDEO]
    assert tags[3][1] == 40 and tags[3][2] == b"\x27\x01frame" * 3


# ---------------------------------------------------------------------------
# live client (the public client-session API doubles as the test client)
# ---------------------------------------------------------------------------

def _rtmp_connect(port, app="live"):
    return rtmp.rtmp_client_connect("127.0.0.1", port, app)


@pytest.fixture(scope="module")
def rtmp_server():
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       rtmp_service=rtmp.RtmpService()))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_rtmp_publish_play_relay(rtmp_server):
    port = rtmp_server.listen_endpoint.port

    # publisher
    pconn, pub = _rtmp_connect(port)
    pub.send_command("createStream", 2.0, None)
    pub.pump(want=1)
    assert any(c[0] == "_result" for c in pub.commands())
    pub.inbox.clear()
    pub.send_command("publish", 3.0, None, "cam1", "live", stream_id=1)
    pub.pump(want=1)
    codes = [c[3]["code"] for c in pub.commands() if c[0] == "onStatus"]
    assert "NetStream.Publish.Start" in codes

    # publish metadata + an AVC sequence header + a frame BEFORE the
    # player joins (late-joiner priming must replay them)
    meta = amf.encode_many("onMetaData", {"width": 64.0, "height": 48.0})
    pub.send_message(rtmp.MSG_DATA_AMF0, 0, meta, stream_id=1)
    avc_cfg = b"\x17\x00\x00\x00\x00cfg-bytes"
    pub.send_message(rtmp.MSG_VIDEO, 0, avc_cfg, stream_id=1)
    pub.send_message(rtmp.MSG_VIDEO, 33, b"\x27\x01frame-early",
                     stream_id=1)
    time.sleep(0.3)  # let the relay ingest before the player joins

    # player joins late
    vconn, ply = _rtmp_connect(port)
    ply.send_command("createStream", 2.0, None)
    ply.send_command("play", 4.0, None, "cam1", stream_id=1)
    # priming: cached metadata + AVC header arrive before live frames
    assert ply.pump_until(
        lambda s: any(t == rtmp.MSG_DATA_AMF0 for t, _, _ in s.inbox)
        and any(p == avc_cfg for t, _, p in s.inbox
                if t == rtmp.MSG_VIDEO)), ply.inbox
    ply.inbox.clear()

    # live frames flow publisher -> player, timestamps preserved
    frame = b"\x27\x01live-frame-payload" * 40  # multi-chunk (>128B)
    pub.send_message(rtmp.MSG_VIDEO, 1000, frame, stream_id=1)
    pub.send_message(rtmp.MSG_AUDIO, 1010, b"\xaf\x01audio", stream_id=1)
    assert ply.pump_until(
        lambda s: any(t == rtmp.MSG_VIDEO for t, _, _ in s.inbox)
        and any(t == rtmp.MSG_AUDIO for t, _, _ in s.inbox)), ply.inbox
    vids = [(ts, p) for t, ts, p in ply.inbox if t == rtmp.MSG_VIDEO]
    auds = [(ts, p) for t, ts, p in ply.inbox if t == rtmp.MSG_AUDIO]
    assert (1000, frame) in vids
    assert (1010, b"\xaf\x01audio") in auds

    # FLV interop: the relayed payloads mux straight into FLV tags
    blob = flv.file_header() + flv.encode_tag(flv.FLV_TAG_VIDEO, 1000,
                                              frame)
    tags = list(flv.read_tags(blob))
    assert tags == [(flv.FLV_TAG_VIDEO, 1000, frame)]

    pconn.close()
    vconn.close()


def test_rtmp_shares_the_port(rtmp_server):
    """The multi-protocol port keeps answering RPC + HTTP while RTMP
    sessions run (one-port-all-protocols with rtmp enabled)."""
    ep = str(rtmp_server.listen_endpoint)
    ch = rpc.Channel(rpc.ChannelOptions(timeout_ms=5000))
    assert ch.init(ep) == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="beside-rtmp"),
                         echo_pb2.EchoResponse)
    assert not cntl.failed() and resp.message == "beside-rtmp"
    ch.close()

    import http.client

    conn = http.client.HTTPConnection("127.0.0.1",
                                      rtmp_server.listen_endpoint.port,
                                      timeout=5)
    conn.request("POST", "/EchoService/Echo",
                 body=json.dumps({"message": "http-beside-rtmp"}),
                 headers={"Content-Type": "application/json"})
    assert json.loads(conn.getresponse().read())[
        "message"] == "http-beside-rtmp"
    conn.close()


def test_rtmp_bad_second_publisher(rtmp_server):
    port = rtmp_server.listen_endpoint.port
    c1, s1 = _rtmp_connect(port)
    s1.send_command("createStream", 2.0, None)
    s1.send_command("publish", 3.0, None, "solo", "live", stream_id=1)
    assert s1.pump_until(
        lambda s: any(c[0] == "onStatus"
                      and c[3]["code"] == "NetStream.Publish.Start"
                      for c in s.commands()))
    c2, s2 = _rtmp_connect(port)
    s2.send_command("createStream", 2.0, None)
    s2.send_command("publish", 3.0, None, "solo", "live", stream_id=1)
    assert s2.pump_until(
        lambda s: any(c[0] == "onStatus"
                      and c[3]["code"] == "NetStream.Publish.BadName"
                      for c in s.commands()))
    c1.close()
    c2.close()


def test_rtmp_not_claimed_without_service():
    """A server WITHOUT rtmp_service must not claim 0x03 bytes — the
    connection fails as an unknown protocol instead of hanging."""
    srv = rpc.Server(rpc.ServerOptions(num_threads=2))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    try:
        conn = pysocket.create_connection(
            ("127.0.0.1", srv.listen_endpoint.port), timeout=5)
        conn.sendall(bytes([3]) + b"\x00" * rtmp.HANDSHAKE_SIZE)
        conn.settimeout(3)
        try:
            data = conn.recv(64)
        except (TimeoutError, pysocket.timeout):
            data = b"none"
        assert data == b"", "connection should be closed, not answered"
        conn.close()
    finally:
        srv.stop()


def test_rtmp_on_native_port():
    """RTMP rides the native port's raw fallback lane like every other
    non-tpu_std protocol: the C++ runtime owns the socket, the Python
    protocol stack runs the session."""
    from brpc_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       rtmp_service=rtmp.RtmpService(),
                                       use_native_runtime=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    try:
        port = srv.listen_endpoint.port
        pconn, pub = _rtmp_connect(port)
        pub.send_command("createStream", 2.0, None)
        pub.send_command("publish", 3.0, None, "ncam", "live", stream_id=1)
        assert pub.pump_until(
            lambda s: any(c[0] == "onStatus" and
                          c[3]["code"] == "NetStream.Publish.Start"
                          for c in s.commands()))
        vconn, ply = _rtmp_connect(port)
        ply.send_command("createStream", 2.0, None)
        ply.send_command("play", 4.0, None, "ncam", stream_id=1)
        ply.pump(want=1)
        ply.inbox.clear()
        pub.send_message(rtmp.MSG_VIDEO, 500, b"\x27\x01native-frame",
                         stream_id=1)
        assert ply.pump_until(
            lambda s: (rtmp.MSG_VIDEO, 500, b"\x27\x01native-frame")
            in s.inbox), ply.inbox
        pconn.close()
        vconn.close()
    finally:
        srv.stop()


def test_chunk_split_reparse_and_abort():
    """Regression: a chunk whose header and body arrive in different TCP
    reads must not double-advance the timestamp on reparse; ABORT must
    discard its csid's partial message (spec 5.4.2)."""
    class _Sink:
        def write(self, buf, id_wait=None):
            return 0

        def failed(self):
            return False

    got = []

    class _Collect(rtmp.RtmpSession):
        def _on_message(self, t, sid, ts, payload):
            if t in (rtmp.MSG_AUDIO, rtmp.MSG_VIDEO, rtmp.MSG_DATA_AMF0):
                got.append((t, ts, payload))
            else:  # control messages (ABORT!) keep their semantics
                super()._on_message(t, sid, ts, payload)

    sess = _Collect(_Sink(), rtmp.RtmpService())
    sess.state = sess.ST_ESTABLISHED

    m0 = (bytes([3]) + (1000).to_bytes(3, "big") + (4).to_bytes(3, "big")
          + bytes([9]) + (1).to_bytes(4, "little") + b"AAAA")
    m1 = (bytes([(1 << 6) | 3]) + (33).to_bytes(3, "big")
          + (4).to_bytes(3, "big") + bytes([9]) + b"BBBB")
    data = bytearray(m0 + m1[:9])  # m1's header arrives; body later
    used = sess.consume(data)
    assert used == len(m0)
    del data[:used]
    data += m1[9:]
    sess.consume(data)
    assert got == [(9, 1000, b"AAAA"), (9, 1033, b"BBBB")], got

    # partial 300-byte message (one 128B chunk lands), then ABORT(csid=3)
    part = (bytes([3]) + (10).to_bytes(3, "big") + (300).to_bytes(3, "big")
            + bytes([9]) + (1).to_bytes(4, "little") + b"x" * 128)
    assert sess.consume(bytearray(part)) == len(part)
    abort = (bytes([2]) + (0).to_bytes(3, "big") + (4).to_bytes(3, "big")
             + bytes([2]) + (0).to_bytes(4, "little")
             + (3).to_bytes(4, "big"))
    sess.consume(bytearray(abort))
    fresh = (bytes([3]) + (2000).to_bytes(3, "big") + (2).to_bytes(3, "big")
             + bytes([9]) + (1).to_bytes(4, "little") + b"ZZ")
    sess.consume(bytearray(fresh))
    assert got[-1] == (9, 2000, b"ZZ")


def test_mpegts_roundtrip():
    """TS muxer/demuxer (the ts.h role): PES packetization with PTS,
    multi-packet payloads, adaptation-field stuffing, PSI tables with
    valid MPEG CRC32 — and the RTMP->FLV->TS pipeline shape."""
    from brpc_tpu.rpc import mpegts

    mux = mpegts.TsMuxer(has_audio=True)
    video1 = b"\x00\x00\x00\x01\x65" + bytes(range(256)) * 3  # ~770B, 5 pkts
    video2 = b"\x00\x00\x00\x01\x41" + b"delta-frame"
    audio1 = b"\xff\xf1AAC-frame-bytes"
    mux.write_video(0, video1, keyframe=True)
    mux.write_audio(23, audio1)
    mux.write_video(33, video2)
    data = mux.packets()
    assert len(data) % mpegts.TS_PACKET == 0
    assert all(data[i] == mpegts.SYNC
               for i in range(0, len(data), mpegts.TS_PACKET))

    got = list(mpegts.demux(data))
    vids = [(pts, es) for pid, pts, es in got if pid == mpegts.PID_VIDEO]
    auds = [(pts, es) for pid, pts, es in got if pid == mpegts.PID_AUDIO]
    assert vids == [(0, video1), (33, video2)]
    assert auds == [(23, audio1)]

    # the PSI tables carry valid MPEG CRCs (a set-top demuxer rejects
    # tables whose CRC fails — CRC over table_id..body must equal the
    # trailing 4 bytes)
    pat = mpegts._pat_table()
    assert mpegts._crc32_mpeg(pat[:-4]) == int.from_bytes(pat[-4:], "big")
    pmt = mpegts._pmt_table(True)
    assert mpegts._crc32_mpeg(pmt[:-4]) == int.from_bytes(pmt[-4:], "big")

    # sync loss raises rather than desyncing silently
    with pytest.raises(ValueError):
        list(mpegts.demux(b"\x00" * mpegts.TS_PACKET))


def test_mpegts_error_contract():
    """PAT layout is the 4-byte program-entry form; oversized audio and
    truncated streams fail with ValueError, never struct/Index errors."""
    from brpc_tpu.rpc import mpegts

    pat = mpegts._pat_table()
    # program entry = program_number(2) + reserved|PMT PID(2)
    assert pat[8:12] == bytes([0, 1]) + bytes([0xF0, 0x00])
    mux = mpegts.TsMuxer()
    with pytest.raises(ValueError, match="audio"):
        mux.write_audio(0, b"a" * 70000)
    mux.write_video(0, b"v" * 70000)  # unbounded video PES is legal
    data = mux.packets()
    with pytest.raises(ValueError, match="truncated"):
        list(mpegts.demux(data[:-7]))


def test_amf0_fuzz_never_crashes():
    """Random bytes through the AMF0 decoder: AmfError or a value, never
    an uncontrolled exception (the command path feeds it wire bytes)."""
    import random

    rng = random.Random(11)
    for _ in range(500):
        blob = bytes(rng.randrange(256) for _ in range(rng.randrange(48)))
        try:
            amf.decode_all(blob)
        except amf.AmfError:
            pass


def test_mpegts_demux_fuzz():
    """Packet-aligned random bytes: ValueError or clean output, never
    Index/struct errors."""
    import random

    from brpc_tpu.rpc import mpegts

    rng = random.Random(13)
    for _ in range(100):
        npkts = rng.randrange(1, 5)
        blob = bytearray(rng.randrange(256)
                         for _ in range(npkts * mpegts.TS_PACKET))
        if rng.random() < 0.7:
            for i in range(npkts):  # valid sync most of the time
                blob[i * mpegts.TS_PACKET] = mpegts.SYNC
        try:
            list(mpegts.demux(bytes(blob)))
        except ValueError:
            pass


def test_mpegts_pcr_and_truncated_pes():
    from brpc_tpu.rpc import mpegts

    # a stream written WITHOUT keyframe flags still carries a PCR
    mux = mpegts.TsMuxer(has_audio=False)
    mux.write_video(0, b"frame-a")
    mux.write_video(33, b"frame-b")
    data = mux.packets()
    pcr_seen = False
    for off in range(0, len(data), mpegts.TS_PACKET):
        pkt = data[off:off + mpegts.TS_PACKET]
        if (pkt[3] >> 4) & 0x2 and pkt[4] > 0 and pkt[5] & 0x10:
            pcr_seen = True
    assert pcr_seen, "PMT advertises PCR but none was emitted"

    # PTS flag set with the PTS bytes missing -> ValueError, not IndexError
    with pytest.raises(ValueError, match="truncated"):
        mpegts._finish_pes(mpegts.PID_VIDEO,
                           b"\x00\x00\x01\xe0\x00\x00\x80\x80\x05")
