"""Native-runtime observability (nat_stats.cpp): per-thread stat cells,
log2 latency histograms and the bounded span ring, surfaced through the
Python bvar registry and console pages — /vars, /status, /brpc_metrics
(Prometheus) and /rpcz show native traffic beside the Python lanes.

Also the clean-exit regression for the BENCH_r05 rc-139 class: a process
that ran the full native stack must exit 0 (static destructors must not
race detached runtime threads).
"""
import http.client
import socket as pysock
import subprocess
import sys
import time

import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)


class EchoService(rpc.Service):
    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        response.message = request.message
        done()


def _get(port, path):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=5)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    conn.close()
    return r.status, body


@pytest.fixture(scope="module")
def server():
    """A native-runtime server carrying echo (native handler), HTTP
    (native /echo usercode) and redis (native store) traffic."""
    from brpc_tpu import rpcz
    from brpc_tpu.rpc.redis import RedisService

    rpcz.clear_for_tests()
    srv = rpc.Server(rpc.ServerOptions(num_threads=2,
                                       use_native_runtime=True,
                                       native_builtin_echo=True,
                                       redis_service=RedisService(),
                                       native_redis_store=True))
    srv.add_service(EchoService())
    assert srv.start("127.0.0.1:0") == 0
    port = srv.listen_endpoint.port

    # echo lane: 40 native framework calls
    h = native.channel_open("127.0.0.1", port)
    for _ in range(40):
        code, body, text = native.channel_call(h, "EchoService", "Echo",
                                               b"x" * 16)
        assert code == 0, (code, text)
    native.channel_close(h)

    # http lane: native-usercode GETs
    for _ in range(5):
        status, body = _get(port, "/echo")
        assert status == 200 and body == "pong"

    # redis lane: native-store SET/GET
    sk = pysock.create_connection(("127.0.0.1", port), timeout=5)
    sk.sendall(b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"
               b"*2\r\n$3\r\nGET\r\n$1\r\nk\r\n")
    got = b""
    deadline = time.time() + 3
    while b"$1\r\nv\r\n" not in got and time.time() < deadline:
        got += sk.recv(4096)
    sk.close()
    assert b"+OK\r\n" in got and b"$1\r\nv\r\n" in got

    yield srv, port
    srv.stop()


def test_vars_lists_native_counters(server):
    srv, port = server
    status, body = _get(port, "/vars")
    assert status == 200
    vals = {}
    for line in body.splitlines():
        if line.startswith("nat_") and " : " in line:
            name, _, v = line.partition(" : ")
            try:
                vals[name.strip()] = float(v)
            except ValueError:
                pass
    assert vals["nat_tpu_std_msgs_in"] >= 40
    assert vals["nat_tpu_std_responses_out"] >= 40
    assert vals["nat_http_msgs_in"] >= 5
    assert vals["nat_redis_msgs_in"] >= 2
    assert vals["nat_client_calls"] >= 40
    assert vals["nat_connections_accepted"] >= 3
    # bytes moved: every request carries at least its frame
    assert vals["nat_socket_read_bytes"] > 40 * 12
    assert vals["nat_socket_write_bytes"] > 0
    # percentile vars are exposed and plausible for the echo lane
    assert 0 < vals["nat_echo_latency_p50_us"] <= \
        vals["nat_echo_latency_p99_us"] + 0.1


def test_brpc_metrics_prometheus_exposition(server):
    srv, port = server
    status, body = _get(port, "/brpc_metrics")
    assert status == 200
    metrics = {}
    for line in body.splitlines():
        if line.startswith("nat_") and " " in line:
            name, _, v = line.partition(" ")
            metrics[name] = float(v)
    assert metrics["nat_tpu_std_msgs_in"] >= 40
    assert metrics["nat_redis_responses_out"] >= 2
    assert "# TYPE nat_tpu_std_msgs_in gauge" in body


def test_every_counter_enum_in_prometheus_exposition(server):
    """Drift guard (ISSUE 6): EVERY NatStats counter enum must appear in
    the /brpc_metrics Prometheus exposition — a counter added to the C++
    enum without surfacing here is a silent observability hole (the PR-5
    sextet was the motivating case)."""
    srv, port = server
    status, body = _get(port, "/brpc_metrics")
    assert status == 200
    exposed = {line.partition(" ")[0] for line in body.splitlines()
               if line and not line.startswith("#")}
    missing = [n for n in native.stats_counter_names() if n not in exposed]
    assert not missing, f"counters absent from /brpc_metrics: {missing}"
    # the PR-5 robustness counters specifically (the ISSUE 6 satellite)
    for name in ("nat_faults_injected", "nat_elimit_rejects",
                 "nat_queue_deadline_drops", "nat_retry_budget_exhausted",
                 "nat_breaker_isolations", "nat_breaker_revivals"):
        assert name in exposed, name
    # the flight-recorder counters specifically (the ISSUE 12 satellite:
    # every nat_dump_* / nat_replay_* counter rides the exposition)
    for name in ("nat_dump_samples", "nat_dump_records_written",
                 "nat_dump_bytes_written", "nat_dump_drops",
                 "nat_dump_oversize", "nat_dump_rotations",
                 "nat_replay_calls", "nat_replay_errors"):
        assert name in exposed, name
    # the fan-out cluster counters specifically (the ISSUE 13 satellite:
    # every LB/fan-out/naming-feed counter rides the exposition)
    for name in ("nat_lb_selects", "nat_fanout_calls",
                 "nat_fanout_subcalls", "nat_fanout_subcall_errors",
                 "nat_fanout_fails", "nat_cluster_updates",
                 "nat_cluster_backends_added",
                 "nat_cluster_backends_removed"):
        assert name in exposed, name
    # the elastic-capacity counters specifically (the ISSUE 20 satellite:
    # dynpart resizes + the autoscaler's grow/shrink/blocked verdicts)
    for name in ("nat_dynpart_resizes", "nat_autoscale_grows",
                 "nat_autoscale_shrinks", "nat_autoscale_blocked"):
        assert name in exposed, name


def test_observatory_vars_in_prometheus_exposition(server):
    """Drift guard extension (ISSUE 9): the per-method, per-socket and
    lock-contention vars must appear in the /brpc_metrics exposition as
    labeled rows, with label values escaped (method paths contain '/';
    quotes/backslashes must be escaped per the exposition format)."""
    import re

    from brpc_tpu.bvar.variable import _prom_label_escape

    srv, port = server
    native.mu_contend_selftest(4, 50, 20)  # ensure a contention row
    # a live native cluster (ISSUE 13): its per-backend rows must ride
    # the same exposition under the nat_cluster_backend_* names
    from brpc_tpu.rpc.native_cluster import NativeCluster

    cluster = NativeCluster(lb="rr", name="driftcluster")
    try:
        cluster.update([f"127.0.0.1:{port}"])
        cluster.call("EchoService.Echo", b"drift", timeout_ms=2000)
        # settle the 0.25s-TTL snapshot caches: an exposition rendered
        # within the TTL of an earlier test's dump replays that test's
        # conn/cluster snapshot, which predates the rows asserted below
        from brpc_tpu.bvar import native_vars

        native_vars.settle_for_tests()
        status, body = _get(port, "/brpc_metrics")
        assert status == 200
        for vname in ("nat_method_count", "nat_method_errors",
                      "nat_method_qps", "nat_method_concurrency",
                      "nat_method_max_concurrency",
                      "nat_method_latency_p99_us",
                      "nat_connection_in_bytes",
                      "nat_connection_out_bytes",
                      "nat_connection_unwritten_bytes",
                      "nat_connection_mem_bytes",
                      "nat_mem_live_bytes",
                      "nat_mem_live_objects",
                      "nat_mem_cum_allocs",
                      "nat_mem_cum_frees",
                      "nat_mem_hwm_bytes",
                      "nat_lock_contention_waits",
                      "nat_lock_contention_wait_us",
                      "nat_cluster_backend_selects",
                      "nat_cluster_backend_errors",
                      "nat_cluster_backend_inflight",
                      "nat_cluster_backend_breaker_open",
                      "nat_cluster_backend_lame_duck",
                      "nat_cluster_backend_ema_latency_us"):
            labeled = [ln for ln in body.splitlines()
                       if ln.startswith(vname + "{")]
            assert labeled, f"{vname} has no labeled rows in /brpc_metrics"
        assert ('nat_cluster_backend_selects{cluster="driftcluster",'
                f'backend="127.0.0.1:{port}"}}') in body
    finally:
        cluster.close()
    # (concrete live-traffic row values are asserted in
    # tests/test_native_observatory.py::test_prometheus_method_labels)
    # no label value may contain an UNESCAPED quote: every labeled row
    # must re-parse with the exposition's escaping rules
    lab_re = re.compile(r'^\w+\{((?:\w+="(?:[^"\\]|\\.)*",?)+)\} ')
    for ln in body.splitlines():
        if "{" in ln and ln.startswith("nat_"):
            assert lab_re.match(ln), f"malformed labeled row: {ln!r}"
    # escaping helper round-trips the nasty cases
    assert _prom_label_escape('a"b\\c\nd') == 'a\\"b\\\\c\\nd'


def test_multicore_counters_and_dispatcher_rows(server):
    """ISSUE 7 observability satellite: the dispatcher/scheduler scale-out
    counters ride the same drift-guarded enum (so /brpc_metrics carries
    them via the test above), and /vars carries one row triple per
    dispatcher loop (sockets owned, wakeups, SQPOLL state)."""
    srv, port = server
    snap = native.stats_counters()
    # every new counter exists in the snapshot surface
    for name in ("nat_dispatcher_wakeups", "nat_wsq_steals",
                 "nat_worker_parks", "nat_sqpoll_rings"):
        assert name in snap, name
    # the traffic above came through epoll rounds; workers idled between
    # bursts at least once
    assert snap["nat_dispatcher_wakeups"] > 0
    assert snap["nat_worker_parks"] > 0
    # per-dispatcher rows: pool size matches the export, and the rows'
    # wakeup total covers the counter snapshot taken above (both sides
    # increment at the same site in Dispatcher::run, and the rows are
    # read after the snapshot)
    ndisp = native.dispatcher_count()
    rows = native.dispatcher_stats()
    assert ndisp >= 1 and len(rows) == ndisp
    assert sum(r["wakeups"] for r in rows) >= snap["nat_dispatcher_wakeups"]
    for r in rows:
        assert r["sqpoll"] in (-1, 0, 1)
    # and /vars renders them
    status, body = _get(port, "/vars")
    assert status == 200
    for i in range(ndisp):
        assert f"nat_dispatcher_{i}_sockets" in body
        assert f"nat_dispatcher_{i}_wakeups" in body
        assert f"nat_dispatcher_{i}_sqpoll" in body


def test_status_summarizes_overload_counters(server):
    """/status carries a one-line overload/faults summary the moment any
    of the PR-5 counters moves (snapshot injected: the formatting
    contract, not the traffic)."""
    from brpc_tpu.bvar.native_vars import native_status_lines

    snap = {"nat_socket_read_bytes": 1, "nat_faults_injected": 7,
            "nat_elimit_rejects": 3, "nat_breaker_isolations": 1}
    joined = "\n".join(native_status_lines(snap=snap))
    assert "overload/faults:" in joined
    assert "faults_injected=7" in joined
    assert "elimit_rejects=3" in joined
    assert "breaker_isolations=1" in joined
    # all six keys render (zeros included once the line triggers)
    assert "queue_deadline_drops=0" in joined
    assert "breaker_revivals=0" in joined
    # quiet counters -> no line
    quiet = "\n".join(native_status_lines(
        snap={"nat_socket_read_bytes": 1}))
    assert "overload/faults:" not in quiet


def test_rpcz_shows_native_spans_with_ordered_timeline(server):
    from brpc_tpu import rpcz

    srv, port = server
    status, body = _get(port, "/rpcz")
    assert status == 200
    assert "native:" in body, body[:400]
    native_spans = [s for s in rpcz.recent_spans(4096)
                    if s.remote_side and s.remote_side.startswith("native:")]
    assert native_spans
    lanes_seen = set()
    for s in native_spans:
        lanes_seen.add(s.remote_side.split("/")[0])
        # recv <= parse <= dispatch <= write, carried as start_time plus
        # three timeline annotations ending at end_time
        times = [s.start_time] + [ts for ts, _ in s.annotations]
        assert times == sorted(times), (s.full_method, times)
        assert abs(s.annotations[-1][0] - s.end_time) < 1e-9
        assert s.end_time >= s.start_time
    assert "native:echo" in lanes_seen
    echo_spans = [s for s in native_spans
                  if s.full_method == "EchoService.Echo"]
    assert echo_spans and echo_spans[0].request_size == 16


def test_histogram_percentiles_monotone(server):
    lanes = native.stats_lane_names()
    assert lanes == ["echo", "http", "redis", "grpc", "client", "worker"]
    nonempty = 0
    for idx, lane in enumerate(lanes):
        hist = native.stats_hist(idx)
        if not any(hist):
            continue
        nonempty += 1
        p50 = native.stats_quantile(idx, 0.50)
        p99 = native.stats_quantile(idx, 0.99)
        p999 = native.stats_quantile(idx, 0.999)
        assert 0 < p50 <= p99 <= p999, (lane, p50, p99, p999)
        # the histogram total matches what the quantile walk saw
        assert sum(hist) > 0
    # echo, redis and client lanes definitely carried traffic
    assert nonempty >= 3


def test_status_page_has_native_section(server):
    srv, port = server
    status, body = _get(port, "/status")
    assert status == 200
    assert "native runtime:" in body
    assert "tpu_std: in=" in body
    assert "echo_latency_us: p50=" in body


def test_native_stack_exits_clean():
    """BENCH_r05 rc-139 regression: spin up the full native stack (server,
    scheduler workers, dispatchers, client channel, py lane), do work,
    stop, and exit — the process must not SIGSEGV in static destructors
    racing detached runtime threads."""
    script = (
        "import sys; sys.path.insert(0, '.')\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from brpc_tpu import rpc, native\n"
        "from brpc_tpu.rpc.proto import echo_pb2\n"
        "class E(rpc.Service):\n"
        "    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)\n"
        "    def Echo(self, cntl, request, response, done):\n"
        "        response.message = request.message\n"
        "        done()\n"
        "srv = rpc.Server(rpc.ServerOptions(num_threads=2,\n"
        "                 use_native_runtime=True,\n"
        "                 native_builtin_echo=True))\n"
        "srv.add_service(E())\n"
        "assert srv.start('127.0.0.1:0') == 0\n"
        "port = srv.listen_endpoint.port\n"
        "h = native.channel_open('127.0.0.1', port)\n"
        "for _ in range(100):\n"
        "    code, body, text = native.channel_call(h, 'EchoService',\n"
        "                                           'Echo', b'z' * 16)\n"
        "    assert code == 0, (code, text)\n"
        "native.channel_close(h)\n"
        "srv.stop()\n"
        "print('clean', flush=True)\n")
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    res = subprocess.run([sys.executable, "-c", script],
                         capture_output=True, text=True, timeout=180,
                         cwd=repo_root, env=env)
    assert res.returncode == 0, (res.returncode, res.stderr[-2000:])
    assert "clean" in res.stdout


def test_lockrank_names_track_header():
    """Drift guard: every kLockRank constant in nat_lockrank.h (and
    every raw-rank `// N: name` comment row) must resolve through
    nat_mu_rank_name — the hand-mirrored switch in nat_prof.cpp is the
    only thing turning /hotspots/contention ranks into names, and a
    rank added to the header without a row would silently report as
    "rank<N>"."""
    import os
    import re

    hdr = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "src", "nat_lockrank.h")
    with open(hdr) as f:
        text = f.read()
    # same shape the natcheck lockorder pass parses
    ranks = {int(v): k for k, v in
             re.findall(r"\b(kLockRank\w+)\s*=\s*(\d+)", text)}
    assert len(ranks) >= 30, "lockrank header parse came up short"
    missing = [f"{name}={rank}" for rank, name in sorted(ranks.items())
               if native.mu_rank_name(rank) is None]
    assert not missing, (
        "nat_lockrank.h ranks without a mu_rank_name row "
        f"(add them to the switch in nat_prof.cpp): {missing}")
