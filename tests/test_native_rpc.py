"""Native RPC runtime tests — the framework data path in C++ (nat_rpc.cpp):
Socket/dispatcher/messenger on fibers + native IOBuf, the py lane
(usercode on pthreads), wire compat with the Python tpu_std stack, and the
framework-path bench."""
import threading

import pytest

from brpc_tpu import native, rpc
from brpc_tpu.rpc.proto import echo_pb2

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native toolchain unavailable")


class PyEcho(rpc.Service):
    SERVICE_NAME = "EchoService"

    @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    def Echo(self, cntl, request, response, done):
        if request.code:
            cntl.set_failed(request.code, "requested failure")
        response.message = request.message
        done()


@pytest.fixture
def native_py_server():
    """A Python Server mounted on the native runtime port."""
    srv = rpc.Server(rpc.ServerOptions(num_threads=2,
                                       use_native_runtime=True))
    srv.add_service(PyEcho())
    assert srv.start("127.0.0.1:0") == 0
    yield srv
    srv.stop()


def test_python_service_on_native_port(native_py_server):
    """Python Channel -> native port -> py lane -> Python service."""
    srv = native_py_server
    ch = rpc.Channel()
    assert ch.init(str(srv.listen_endpoint)) == 0
    cntl, resp = ch.call("EchoService.Echo",
                         echo_pb2.EchoRequest(message="via-native"),
                         echo_pb2.EchoResponse, timeout_ms=5000)
    assert not cntl.failed(), cntl.error_text
    assert resp.message == "via-native"
    ch.close()


def test_python_service_error_on_native_port(native_py_server):
    srv = native_py_server
    ch = rpc.Channel()
    assert ch.init(str(srv.listen_endpoint)) == 0
    cntl, _ = ch.call("EchoService.Echo",
                      echo_pb2.EchoRequest(message="x", code=1003),
                      echo_pb2.EchoResponse, timeout_ms=5000)
    assert cntl.failed()
    assert cntl.error_code == 1003
    ch.close()


def test_unknown_method_on_native_port(native_py_server):
    srv = native_py_server
    ch = rpc.Channel()
    assert ch.init(str(srv.listen_endpoint)) == 0
    cntl, _ = ch.call("NoSuchService.Nope", echo_pb2.EchoRequest(message="x"),
                      echo_pb2.EchoResponse, timeout_ms=5000)
    assert cntl.failed()
    ch.close()


def test_native_client_to_python_service(native_py_server):
    """Native channel (fiber/butex client) against the py lane."""
    srv = native_py_server
    h = native.channel_open("127.0.0.1", srv.listen_endpoint.port)
    try:
        req = echo_pb2.EchoRequest(message="native-client")
        rc, body, err = native.channel_call(
            h, "EchoService", "Echo", req.SerializeToString())
        assert rc == 0, err
        resp = echo_pb2.EchoResponse()
        resp.ParseFromString(body)
        assert resp.message == "native-client"
    finally:
        native.channel_close(h)


def test_native_echo_handler_and_bench():
    """Native handler served zero-copy on fibers; framework-path bench."""
    port = native.rpc_server_start(native_echo=True)
    try:
        h = native.channel_open("127.0.0.1", port)
        rc, body, err = native.channel_call(h, "EchoService", "Echo",
                                            b"raw-bytes")
        assert rc == 0 and body == b"raw-bytes"
        native.channel_close(h)
        stats = native.rpc_client_bench("127.0.0.1", port, nconn=2,
                                        fibers_per_conn=8, seconds=0.5,
                                        payload=16)
        assert stats["requests"] > 100, stats
        assert native.rpc_server_requests() > 100
    finally:
        native.rpc_server_stop()


def test_concurrent_python_clients_on_native_port(native_py_server):
    srv = native_py_server
    errs = []

    def worker(i):
        ch = rpc.Channel()
        if ch.init(str(srv.listen_endpoint)) != 0:
            errs.append("init")
            return
        for j in range(20):
            cntl, resp = ch.call("EchoService.Echo",
                                 echo_pb2.EchoRequest(message=f"m{i}-{j}"),
                                 echo_pb2.EchoResponse, timeout_ms=5000)
            if cntl.failed() or resp.message != f"m{i}-{j}":
                errs.append(f"{i}/{j}: {cntl.error_text}")
                return

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


def test_io_uring_datapath():
    """The RingListener lane (fork ring_listener.h analog): provided-buffer
    multishot receives + fixed-buffer sends, completions drained by the
    scheduler idle loop. Gated on kernel support."""
    rc = native.use_io_uring(True)
    if rc != 1:
        pytest.skip("io_uring unavailable in this kernel/sandbox")
    try:
        port = native.rpc_server_start("127.0.0.1", 0, nworkers=2,
                                       native_echo=True)
        assert port > 0
        ch = rpc.Channel()
        assert ch.init(f"127.0.0.1:{port}") == 0
        recv0, send0 = native.ring_counters()
        for i in range(40):
            cntl, resp = ch.call("EchoService.Echo",
                                 echo_pb2.EchoRequest(message=f"ring{i}"),
                                 echo_pb2.EchoResponse, timeout_ms=5000)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == f"ring{i}"
        recv1, send1 = native.ring_counters()
        # every request arrived via a provided-buffer recv completion and
        # every response left via a fixed-buffer send completion
        assert recv1 > recv0
        assert send1 > send0
        ch.close()
    finally:
        native.rpc_server_stop()
        native.use_io_uring(False)


def test_native_port_http_console():
    """The native port answers HTTP console GETs natively (the
    multi-protocol-port discipline): /health /status /vars /version."""
    import urllib.request

    port = native.rpc_server_start("127.0.0.1", 0, nworkers=2,
                                   native_echo=True)
    try:
        base = f"http://127.0.0.1:{port}"
        body = urllib.request.urlopen(f"{base}/health", timeout=5).read()
        assert body == b"OK\n"
        body = urllib.request.urlopen(f"{base}/status", timeout=5).read()
        assert b"nat_server_requests" in body
        assert b"nat_scheduler_workers" in body
        body = urllib.request.urlopen(f"{base}/version", timeout=5).read()
        assert body.startswith(b"brpc_tpu_native/")
        try:
            urllib.request.urlopen(f"{base}/nope", timeout=5)
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
        # tpu_std still works on the same port after HTTP traffic
        ch = rpc.Channel()
        assert ch.init(f"127.0.0.1:{port}") == 0
        cntl, resp = ch.call("EchoService.Echo",
                             echo_pb2.EchoRequest(message="mixed"),
                             echo_pb2.EchoResponse, timeout_ms=5000)
        assert not cntl.failed() and resp.message == "mixed"
        ch.close()
    finally:
        native.rpc_server_stop()


def test_rss_flat_under_sustained_load():
    """VERDICT round-1 item 4's acceptance: memory stays flat over a
    sustained loopback run (TaskMeta reap + IOBuf block recycling + no
    per-request leaks on the native path)."""
    import ctypes
    import os

    if os.environ.get("BRPC_TPU_SANITIZED"):
        # ASan's quarantine + redzones keep RSS climbing by design; leak
        # detection under instrumentation is LSan's job (the C smoke leg
        # of tools/check.sh --soak), not this gate's
        pytest.skip("RSS-flatness gate is meaningless under ASan")

    def current_rss_mb() -> float:
        # CURRENT rss, not ru_maxrss: the high-water mark passes vacuously
        # when an earlier test already peaked higher
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * os.sysconf("SC_PAGE_SIZE") / 1e6

    port = native.rpc_server_start("127.0.0.1", 0, nworkers=2,
                                   native_echo=True)
    try:
        out = ctypes.c_uint64(0)
        lib = native.load()
        # warmup builds steady-state pools/caches
        lib.nat_rpc_client_bench(b"127.0.0.1", port, 2, 32, 1.0, 16,
                                 ctypes.byref(out))
        rss0 = current_rss_mb()
        for _ in range(3):
            lib.nat_rpc_client_bench(b"127.0.0.1", port, 2, 32, 1.0, 16,
                                     ctypes.byref(out))
        grown_mb = current_rss_mb() - rss0
        assert grown_mb < 64, f"RSS grew {grown_mb:.1f}MB under load"
        assert out.value > 10000  # the run actually hammered the path
    finally:
        native.rpc_server_stop()


def test_async_windowed_client():
    """Done-callback completions (PendingCall.cb — the native async-RPC
    surface): a windowed client keeps many requests in flight with no
    parked fiber per call, and every request completes before return."""
    import ctypes

    port = native.rpc_server_start("127.0.0.1", 0, nworkers=2,
                                   native_echo=True)
    try:
        out = ctypes.c_uint64(0)
        qps = native.load().nat_rpc_client_bench_async(
            b"127.0.0.1", port, 2, 128, 1.0, 16, ctypes.byref(out))
        assert qps > 1000, qps
        assert out.value > 1000
    finally:
        native.rpc_server_stop()


def test_native_acall():
    """nat_channel_acall — the exported done-closure call: completion runs
    on a framework thread with the response bytes."""
    import threading

    port = native.rpc_server_start("127.0.0.1", 0, nworkers=2,
                                   native_echo=True)
    ch = None
    try:
        ch = native.channel_open("127.0.0.1", port)
        results = []
        done_evt = threading.Event()

        def done(code, resp):
            results.append((code, resp))
            if len(results) == 8:
                done_evt.set()

        for i in range(8):
            rc = native.channel_acall(ch, "EchoService", "Echo",
                                      f"payload{i}".encode(), done)
            assert rc == 0
        import gc

        gc.collect()  # thunks must survive GC until done fires
        assert done_evt.wait(5)
        assert all(code == 0 for code, _ in results)
        assert sorted(r for _, r in results) == sorted(
            f"payload{i}".encode() for i in range(8))
    finally:
        if ch is not None:
            native.channel_close(ch)
        native.rpc_server_stop()


def _deadline_roundtrip(port, timeout_ms=300):
    """Sync + async native calls against a server that never answers:
    both must complete with ERPCTIMEDOUT in ~timeout_ms."""
    import time

    h = native.channel_open("127.0.0.1", port)
    t0 = time.monotonic()
    rc, _, text = native.channel_call(h, "EchoService", "Echo", b"x",
                                      timeout_ms=timeout_ms)
    dt = time.monotonic() - t0
    assert rc == 1008, (rc, text)  # ERPCTIMEDOUT
    assert timeout_ms / 1000.0 * 0.5 < dt < 5.0, dt

    got = {}
    evt = threading.Event()

    def done(code, resp):
        got["code"] = code
        evt.set()

    t0 = time.monotonic()
    assert native.channel_acall(h, "EchoService", "Echo", b"x", done,
                                timeout_ms=timeout_ms) == 0
    assert evt.wait(10), "acall deadline never fired"
    assert got["code"] == 1008
    assert time.monotonic() - t0 < 5.0
    native.channel_close(h)


def test_native_call_deadline_epoll():
    """A stalled server (py lane enabled, nobody draining) strands the
    request; the native TimerThread must fail the call in ~timeout_ms —
    the controller.cpp:605 deadline semantics, sync and async."""
    port = native.rpc_server_start(native_echo=False)
    assert port > 0
    try:
        _deadline_roundtrip(port)
    finally:
        native.rpc_server_stop()


def test_native_call_deadline_ring():
    """Same deadline contract on the io_uring lane."""
    if native.use_io_uring(True) != 1:
        pytest.skip("io_uring unavailable in this kernel/sandbox")
    try:
        port = native.rpc_server_start(native_echo=False)
        assert port > 0
        try:
            _deadline_roundtrip(port)
        finally:
            native.rpc_server_stop()
    finally:
        native.use_io_uring(False)


def test_native_deadline_does_not_break_completions():
    """A timeout armed but beaten by the response must be a no-op (the
    pending-bit CAS arbitration): hammer calls with generous deadlines."""
    port = native.rpc_server_start(native_echo=True)
    assert port > 0
    try:
        h = native.channel_open("127.0.0.1", port)
        for i in range(200):
            rc, body, text = native.channel_call(
                h, "EchoService", "Echo", b"p%d" % i, timeout_ms=2000)
            assert rc == 0, (rc, text)
            assert body == b"p%d" % i
        native.channel_close(h)
    finally:
        native.rpc_server_stop()


def test_native_kill_and_revive():
    """Native connection robustness (health_check.cpp:146-237 semantics):
    kill the server under a live channel; calls fail fast with a
    deadline; restart the server (clean stop->start, no graveyard); the
    channel re-dials on demand and calls succeed again."""
    import time

    port = native.rpc_server_start(native_echo=True)
    assert port > 0
    h = native.channel_open("127.0.0.1", port, connect_timeout_ms=2000,
                            health_check_ms=50)
    rc, body, _ = native.channel_call(h, "EchoService", "Echo", b"pre",
                                      timeout_ms=3000)
    assert rc == 0 and body == b"pre"

    native.rpc_server_stop()
    # the failed socket must fail calls (not hang); reconnect attempts
    # against a dead port must respect the connect timeout
    rc, _, _ = native.channel_call(h, "EchoService", "Echo", b"mid",
                                   timeout_ms=500)
    assert rc != 0

    # restart on the SAME port (stop->start cycle, server.h:426-441)
    port2 = native.rpc_server_start(port=port, native_echo=True)
    assert port2 == port
    deadline = time.monotonic() + 10
    rc = -1
    while time.monotonic() < deadline:
        rc, body, _ = native.channel_call(h, "EchoService", "Echo", b"post",
                                          timeout_ms=1000)
        if rc == 0:
            break
        time.sleep(0.05)
    assert rc == 0 and body == b"post"
    native.channel_close(h)
    native.rpc_server_stop()


def test_native_retry_rides_over_restart():
    """max_retry + on-demand re-dial: kill the server, restart it, and a
    SINGLE call with retries succeeds without any manual loop (the
    IssueRPC retry state machine role, controller.cpp:554-640)."""
    port = native.rpc_server_start(native_echo=True)
    h = native.channel_open("127.0.0.1", port, connect_timeout_ms=2000)
    rc, body, _ = native.channel_call(h, "EchoService", "Echo", b"a",
                                      timeout_ms=3000)
    assert rc == 0
    native.rpc_server_stop()
    port2 = native.rpc_server_start(port=port, native_echo=True)
    assert port2 == port
    # the first attempt fails on the dead socket; retries re-dial
    rc, body, text = native.channel_call(h, "EchoService", "Echo",
                                         b"retry-me", timeout_ms=10000,
                                         max_retry=5)
    assert rc == 0, (rc, text)
    assert body == b"retry-me"
    native.channel_close(h)
    native.rpc_server_stop()


def test_native_backup_request():
    """backup_ms: a stalled first attempt is overtaken by a duplicate
    send with the SAME correlation id; the first response to arrive wins
    (controller.cpp:1256 semantics). The py-lane service sleeps only on
    its first invocation, so the backup returns fast."""
    import time

    from brpc_tpu import rpc
    from brpc_tpu.rpc.proto import echo_pb2

    calls = []

    class SlowFirst(rpc.Service):
        SERVICE_NAME = "EchoService"

        @rpc.rpc_method(echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        def Echo(self, cntl, request, response, done):
            calls.append(time.monotonic())
            if len(calls) == 1:
                time.sleep(1.5)
            response.message = request.message
            done()

    srv = rpc.Server(rpc.ServerOptions(num_threads=4,
                                       use_native_runtime=True))
    srv.add_service(SlowFirst())
    assert srv.start("127.0.0.1:0") == 0
    try:
        h = native.channel_open("127.0.0.1", srv.listen_endpoint.port)
        req = echo_pb2.EchoRequest(message="backup").SerializeToString()
        t0 = time.monotonic()
        rc, body, text = native.channel_call(h, "EchoService", "Echo", req,
                                             timeout_ms=10000,
                                             backup_ms=150)
        dt = time.monotonic() - t0
        assert rc == 0, (rc, text)
        resp = echo_pb2.EchoResponse()
        resp.ParseFromString(body)
        assert resp.message == "backup"
        # the duplicate (2nd invocation, no sleep) answered well before
        # the stalled 1st attempt's 1.5s sleep finished
        assert dt < 1.2, dt
        assert len(calls) == 2
        native.channel_close(h)
    finally:
        srv.stop()


def test_native_port_survives_garbage():
    """Protocol robustness: random garbage, truncated frames, oversized
    headers, and magic-prefix teases must fail the CONNECTION (or wait
    for more bytes), never the server — and real clients keep working
    throughout (the protocol-error discipline of the cut loop)."""
    import os
    import socket as pysocket
    import struct

    port = native.rpc_server_start(native_echo=True)
    assert port > 0
    try:
        h = native.channel_open("127.0.0.1", port)
        payloads = [
            b"\x00" * 64,                       # zeros
            b"GARBAGE-NOT-A-PROTOCOL" * 10,     # printable junk
            b"TRPC" + b"\xff" * 16,             # oversized body/meta
            b"TRPC" + struct.pack(">II", 10, 200),  # meta > body
            b"TR",                              # magic tease, then EOF
            os.urandom(512),                    # random bytes
        ]
        for junk in payloads:
            c = pysocket.create_connection(("127.0.0.1", port), timeout=5)
            c.sendall(junk)
            c.settimeout(2)
            try:
                while c.recv(4096):
                    pass  # server may answer nothing; wait for close
            except (TimeoutError, pysocket.timeout, ConnectionError):
                pass
            c.close()
            # the port is still healthy for real traffic
            rc, body, text = native.channel_call(h, "EchoService", "Echo",
                                                 b"still-up",
                                                 timeout_ms=3000)
            assert rc == 0, (rc, text, junk[:8])
            assert body == b"still-up"
        native.channel_close(h)
    finally:
        native.rpc_server_stop()
