"""Cross-process device-transport lane tests (the rdma_endpoint/block_pool
cross-machine semantics, exercised across a real process boundary):
HostArena span accounting, the IOBuf blockmem seam, and a two-process
push/pull where tensor payloads ride the shared arena — NOT the TCP wire —
with retention-until-ACK observed on both sides."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from brpc_tpu import rpc
from brpc_tpu.rpc import device_transport as dt
from brpc_tpu.rpc.tensor_service import TensorClient, make_device_channel

SERVER_SCRIPT = r"""
import sys
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")

from brpc_tpu import rpc
from brpc_tpu.rpc.tensor_service import TensorStoreService

svc = TensorStoreService()
srv = rpc.Server(rpc.ServerOptions(num_threads=2))
srv.add_service(svc)
assert srv.start("127.0.0.1:0") == 0
print(srv.listen_endpoint.port, flush=True)
sys.stdin.readline()  # parent closes stdin to stop us
srv.stop()
"""


def test_host_arena_spans():
    arena = dt.HostArena(size=1 << 20)
    try:
        total = arena.free_bytes()
        a = arena.alloc(1000)
        b = arena.alloc(5000)
        assert a is not None and b is not None and a != b
        assert arena.free_bytes() < total
        arena.free(a, 1000)
        arena.free(b, 5000)
        assert arena.free_bytes() == total  # spans coalesce back
    finally:
        arena.close()


def test_iobuf_blockmem_seam():
    """The blockmem_allocate hook: IOBuf appends stage into arena memory."""
    from brpc_tpu.butil import iobuf as iobuf_mod

    arena = dt.HostArena(size=1 << 20)
    try:
        arena.install_as_iobuf_allocator(capacity=4096)
        free0 = arena.free_bytes()
        buf = iobuf_mod.IOBuf()
        buf.append(b"x" * 10000)
        assert bytes(buf.to_bytes()) == b"x" * 10000
        assert arena.free_bytes() < free0  # blocks came from the arena
    finally:
        iobuf_mod.set_block_allocator(None)
        arena.close()


@pytest.fixture
def remote_store():
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen([sys.executable, "-c", SERVER_SCRIPT],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            text=True, cwd=repo_root)
    port = int(proc.stdout.readline())
    yield port
    proc.stdin.close()
    proc.wait(timeout=10)


def test_two_process_ring_transfer(remote_store):
    """Push+pull to a DIFFERENT process rides the descriptor-ring fabric
    by default (ISSUE 15): the payload is written once into the
    receiver's blob arena as kind-8 records, zero payload bytes in the
    attachment, and the receiver consumes the spans in place."""
    port = remote_store
    ch = make_device_channel(f"127.0.0.1:{port}")
    client = TensorClient(ch)

    ring0 = dt._dev_ring.get_value()
    wire0 = dt._dev_wire.get_value()

    arr = np.arange(4096, dtype=np.float32).reshape(64, 64)
    cntl, resp = client.push("w", [arr])
    assert not cntl.failed(), cntl.error_text
    assert resp.ok

    sock = cntl._current_sock
    ep = sock.app_state
    assert isinstance(ep, dt.DeviceEndpoint)
    assert ep.state == dt.ESTABLISHED
    assert not ep.same_process and ep.same_host
    # the server advertised its fabric and the push used it — no wire
    # payload, no send-arena staging
    assert ep.peer_info.get("fabric"), "server did not advertise a fabric"
    assert dt._dev_ring.get_value() == ring0 + 1
    assert dt._dev_wire.get_value() == wire0
    assert len(cntl.request_attachment) == 0  # no payload bytes on the wire
    # push response piggybacked the ACK: retention drained, window open
    assert ep.retained_count == 0
    assert ep.inflight_bytes == 0

    cntl2, pulled = client.pull("w")
    assert not cntl2.failed(), cntl2.error_text
    np.testing.assert_array_equal(pulled[0], arr)
    assert len(cntl2.response_attachment) == 0

    # multi-tensor pushes ride one record per tensor
    arrs = [np.full((32, 32), i, dtype=np.int32) for i in range(3)]
    cntl3, resp3 = client.push("multi", arrs)
    assert not cntl3.failed(), cntl3.error_text
    cntl4, pulled4 = client.pull("multi")
    assert not cntl4.failed(), cntl4.error_text
    for i in range(3):
        np.testing.assert_array_equal(pulled4[i], arrs[i])

    ch.close()


FABRIC_OFF_SERVER_SCRIPT = SERVER_SCRIPT.replace(
    "import sys", "import os, sys\nos.environ['BRPC_TPU_FABRIC'] = '0'", 1)


def test_two_process_shm_arena_fallback():
    """With the fabric disabled on the server (BRPC_TPU_FABRIC=0) the
    same-host lane falls back to the shared HostArena staging path —
    still zero payload bytes on the wire."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.Popen([sys.executable, "-c",
                             FABRIC_OFF_SERVER_SCRIPT],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            text=True, cwd=repo_root)
    try:
        port = int(proc.stdout.readline())
        ch = make_device_channel(f"127.0.0.1:{port}")
        client = TensorClient(ch)

        shm0 = dt._dev_shm.get_value()
        wire0 = dt._dev_wire.get_value()
        arr = np.arange(4096, dtype=np.float32).reshape(64, 64)
        cntl, resp = client.push("w", [arr])
        assert not cntl.failed(), cntl.error_text
        ep = cntl._current_sock.app_state
        assert not ep.peer_info.get("fabric")
        assert dt._dev_shm.get_value() == shm0 + 1
        assert dt._dev_wire.get_value() == wire0
        assert len(cntl.request_attachment) == 0
        cntl2, pulled = client.pull("w")
        np.testing.assert_array_equal(pulled[0], arr)
        ch.close()
    finally:
        proc.stdin.close()
        proc.wait(timeout=10)


def test_two_process_window_retention(remote_store):
    """Several in-flight pushes exercise the sliding window + retention
    across the process boundary; all spans release after the ACKs."""
    port = remote_store
    ch = make_device_channel(f"127.0.0.1:{port}")
    client = TensorClient(ch)
    arena = dt.default_send_arena()
    free0 = arena.free_bytes()
    for i in range(8):
        arr = np.full((256, 256), i, dtype=np.float32)
        cntl, resp = client.push(f"t{i}", [arr])
        assert not cntl.failed(), cntl.error_text
    # every push was acked synchronously -> every span freed
    deadline = time.monotonic() + 5
    while arena.free_bytes() != free0 and time.monotonic() < deadline:
        time.sleep(0.05)
    assert arena.free_bytes() == free0
    cntl, pulled = client.pull("t7")
    np.testing.assert_array_equal(pulled[0], np.full((256, 256), 7,
                                                     dtype=np.float32))
    ch.close()


def test_wire_fallback_still_works():
    """FALLBACK_TCP peers (no arena/host match) use attachment bytes."""
    ep = dt.DeviceEndpoint()
    ep.state = dt.FALLBACK_TCP
    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.rpc.proto import rpc_meta_pb2

    meta = rpc_meta_pb2.RpcMeta()
    att = IOBuf()
    arr = np.arange(16, dtype=np.int32)
    assert ep.prepare_send([arr], meta, att)
    assert len(att) == arr.nbytes
    out, seq = dt.receive_tensors(meta, att)
    np.testing.assert_array_equal(out[0], arr)
    ep.on_ack(seq)
    assert ep.retained_count == 0


def test_transfer_server_lane_plumbing():
    """The jax transfer-server lane (device-to-device; the CROSS-HOST
    path auto-selected when peers are on different machines): publish/
    pull plumbing exercised same-process — the CPU backend's bulk
    transport is same-process-only, so the cross-process form needs real
    device backends (it aborts on CPU, hence no subprocess here)."""
    from brpc_tpu.butil.iobuf import IOBuf
    from brpc_tpu.rpc.proto import rpc_meta_pb2

    server = dt._global_xfer_server()
    if server is None:
        import pytest as _pytest

        _pytest.skip("jax build lacks the transfer server")
    ep = dt.DeviceEndpoint()
    ep.state = dt.ESTABLISHED
    # a fake CROSS-HOST xfer-capable peer selects the lane automatically
    ep.peer_info = {"process": "other-proc", "host": "other-host",
                    "xfer": True, "device_count": 1}
    ep.resolve_xfer_addr("127.0.0.1")
    assert ep._my_xfer_addr.startswith("127.0.0.1:")

    xfer0 = dt.lane_counters()["xfer"]
    meta = rpc_meta_pb2.RpcMeta()
    att = IOBuf()
    arr = np.arange(2048, dtype=np.float32).reshape(32, 64) * 0.5
    assert ep.prepare_send([arr], meta, att)
    spec = meta.tensors[0].sharding_spec
    assert spec.startswith("xfer|")
    assert len(att) == 0  # no payload bytes on the RPC wire
    assert dt.lane_counters()["xfer"] == xfer0 + 1

    out, seq = dt.receive_tensors(meta, att)
    np.testing.assert_array_equal(np.asarray(out[0]), arr)
    ep.on_ack(seq)
    assert ep.retained_count == 0 and ep.inflight_bytes == 0


XFER_SERVER_SCRIPT = r"""
import os, sys
os.environ["BRPC_TPU_FAKE_XFER"] = "1"
sys.path.insert(0, ".")
import jax
jax.config.update("jax_platforms", "cpu")

from brpc_tpu import rpc
from brpc_tpu.rpc.tensor_service import TensorStoreService

svc = TensorStoreService()
srv = rpc.Server(rpc.ServerOptions(num_threads=2))
srv.add_service(svc)
assert srv.start("127.0.0.1:0") == 0
print(srv.listen_endpoint.port, flush=True)
sys.stdin.readline()
srv.stop()
"""


def test_two_process_xfer_transfer():
    """The FULL xfer-lane pull path across a real process boundary via
    the in-repo fake transfer fabric (fake_transfer.py): publish on the
    sender's transfer server, wildcard dial-back address resolution,
    zero payload bytes on the RPC wire, retention released when the
    peer's pull completes, and the xfer counter incrementing."""
    from brpc_tpu.butil import flags as _flags
    from brpc_tpu.rpc.fake_transfer import FakeTransferServer

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, BRPC_TPU_FAKE_XFER="1")
    proc = subprocess.Popen([sys.executable, "-c", XFER_SERVER_SCRIPT],
                            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                            text=True, cwd=repo_root, env=env)
    saved_server = dt._xfer_server
    fake = FakeTransferServer()
    dt._xfer_server = fake
    _flags.set_flag("device_transport_prefer_xfer", True)
    try:
        port = int(proc.stdout.readline())
        ch = make_device_channel(f"127.0.0.1:{port}")
        client = TensorClient(ch)

        xfer0 = dt.lane_counters()["xfer"]
        arr = np.arange(3000, dtype=np.float32).reshape(60, 50) * 1.5
        cntl, resp = client.push("xw", [arr])
        assert not cntl.failed(), cntl.error_text
        assert resp.ok

        ep = cntl._current_sock.app_state
        assert isinstance(ep, dt.DeviceEndpoint)
        assert ep.state == dt.ESTABLISHED
        assert ep._my_xfer_addr.startswith("127.0.0.1:")  # wildcard resolved
        # the lane fired: counter moved, nothing rode the RPC wire
        assert dt.lane_counters()["xfer"] == xfer0 + 1
        assert len(cntl.request_attachment) == 0
        # the peer's pull released the publication (retention-until-pull)
        assert fake.published_count() == 0
        assert ep.retained_count == 0 and ep.inflight_bytes == 0

        # and the values survived the fabric: pull them back over RPC
        cntl2, pulled = client.pull("xw")
        assert not cntl2.failed(), cntl2.error_text
        np.testing.assert_array_equal(np.asarray(pulled[0]), arr)
        ch.close()
    finally:
        _flags.set_flag("device_transport_prefer_xfer", False)
        dt._xfer_server = saved_server
        proc.stdin.close()
        proc.wait(timeout=10)
