"""Native fan-out cluster (ISSUE 13): DoublyBufferedData LB core, the
combo-channel verbs at C++ speed, and the failure-handling contracts.

Covers the satellite checklist: the consistent-hash bounded-remap
property (~K/N keys move on a single-backend removal), partition merge
with fail_limit under injected faults, naming observer add/remove racing
in-flight selects, per-sub-call trace parenting, the Python combo
channels' native=True fast paths, multi-port servers, and the
zero-failed-RPC churn acceptance drill (slow-marked)."""
import collections
import os
import signal
import threading
import time

import pytest

from brpc_tpu import rpc  # noqa: F401 (protocol registry init)
from brpc_tpu.rpc.proto import echo_pb2

native = pytest.importorskip("brpc_tpu.native")
if not native.available():
    pytest.skip("native toolchain unavailable", allow_module_level=True)

from brpc_tpu.rpc.native_cluster import NativeCluster  # noqa: E402


@pytest.fixture()
def swarm_server():
    """One native echo server on 8 ports (the multi-port swarm seam)."""
    port = native.rpc_server_start(native_echo=True)
    ports = [port]
    for _ in range(7):
        ports.append(native.rpc_server_add_port())
    yield ports
    native.rpc_server_stop()


def _mk_cluster(ports, lb="rr", **kw):
    c = NativeCluster(lb=lb, connect_timeout_ms=1000,
                      health_check_ms=100, **kw)
    c.update([f"127.0.0.1:{p}" for p in ports])
    return c


# ---------------------------------------------------------------------------
# verbs
# ---------------------------------------------------------------------------

def test_selective_call_round_robins(swarm_server):
    with _mk_cluster(swarm_server) as c:
        assert c.backend_count() == len(swarm_server)
        for i in range(24):
            rc, body, err = c.call("EchoService.Echo", b"sel-%d" % i,
                                   timeout_ms=2000)
            assert rc == 0, err
            assert body == b"sel-%d" % i
        rows = c.stats()
        # rr spread: every backend served some of the 24 calls
        assert len(rows) == len(swarm_server)
        assert all(r["selects"] >= 1 for r in rows)
        assert all(r["errors"] == 0 for r in rows)


def test_parallel_call_merges_all_backends(swarm_server):
    with _mk_cluster(swarm_server) as c:
        rc, body, err, failed = c.parallel_call("EchoService.Echo",
                                                b"fan!", timeout_ms=3000)
        assert rc == 0, err
        assert failed == 0
        # native merge = concatenation in backend order
        assert body == b"fan!" * len(swarm_server)


def test_parallel_merge_is_protobuf_mergefrom(swarm_server):
    """Concatenated serialized protobufs parse as MergeFrom — the
    native merge IS the default ResponseMerger for proto payloads."""
    with _mk_cluster(swarm_server) as c:
        payload = echo_pb2.EchoRequest(message="pb-merge")
        rc, body, err, failed = c.parallel_call(
            "EchoService.Echo", payload.SerializeToString(),
            timeout_ms=3000)
        assert rc == 0 and failed == 0
        merged = echo_pb2.EchoResponse()
        merged.MergeFromString(body)
        assert merged.message == "pb-merge"


def test_partition_call_groups_by_tag(swarm_server):
    ports = swarm_server[:4]
    with NativeCluster(lb="rr") as c:
        c.update([(f"127.0.0.1:{p}", 1, f"{i % 2}/2")
                  for i, p in enumerate(ports)])
        rc, body, err, failed = c.partition_call(
            "EchoService.Echo", b"P", timeout_ms=3000, partitions=2)
        assert rc == 0, err
        assert failed == 0
        assert body == b"PP"  # one response per partition, merged


def test_partition_missing_partition_counts_failed(swarm_server):
    with NativeCluster(lb="rr") as c:
        # only partition 0 of a declared 2-way scheme has members
        c.update([(f"127.0.0.1:{swarm_server[0]}", 1, "0/2")])
        rc, body, err, failed = c.partition_call(
            "EchoService.Echo", b"x", timeout_ms=2000, partitions=2,
            fail_limit=2)
        assert rc == 0 and failed == 1  # under the limit: succeeds
        rc, _, err, failed = c.partition_call(
            "EchoService.Echo", b"x", timeout_ms=2000, partitions=2,
            fail_limit=1)
        assert rc != 0 and failed == 1  # at the limit: fails loudly
        assert "sub calls failed" in err


def test_partition_call_absent_scheme_fails_fast(swarm_server):
    """A partitions count naming a scheme with NO members must answer
    promptly (review finding: an empty fan once had nothing to wake the
    completion butex — a caller-thread hang with no timeout)."""
    with NativeCluster(lb="rr") as c:
        c.update([(f"127.0.0.1:{p}", 1, f"{i % 2}/2")
                  for i, p in enumerate(swarm_server[:2])])
        t0 = time.time()
        rc, _, err, failed = c.partition_call(
            "EchoService.Echo", b"x", timeout_ms=2000, partitions=3)
        assert rc != 0 and "partition" in err
        assert time.time() - t0 < 1.0  # failed fast, no wedge


def test_wrr_large_weights_never_starve(swarm_server):
    """Summed weights past the schedule cap rescale instead of
    truncating (review finding: a truncated schedule starved any
    backend whose first slot lay past the cap)."""
    ports = swarm_server[:2]
    with NativeCluster(lb="wrr") as c:
        c.update([(f"127.0.0.1:{ports[0]}", 5000, ""),
                  (f"127.0.0.1:{ports[1]}", 1, "")])
        picks = collections.Counter(
            c.select_debug(i) for i in range(2000))
        assert picks[f"127.0.0.1:{ports[1]}"] >= 1  # the tail still rides


def test_two_tuple_node_keeps_empty_tag(swarm_server):
    """(endpoint, weight) 2-tuples must not inherit a bogus tag (review
    finding: naive list padding handed them tag='1')."""
    with NativeCluster(lb="rr") as c:
        c.update([(f"127.0.0.1:{swarm_server[0]}", 5)])
        row = c.stats()[0]
        assert row["weight"] == 5
        assert row["tag"] == ""


def test_parallel_fail_limit_with_dead_backends(swarm_server):
    """fail_limit semantics with deterministic failures: dead ports
    fail their sub-calls, live ones merge."""
    live = swarm_server[:2]
    dead = [1, 2]  # nothing listens on ports 1/2 (reserved range)
    with NativeCluster(lb="rr", connect_timeout_ms=300) as c:
        c.update([f"127.0.0.1:{p}" for p in live + dead])
        rc, body, err, failed = c.parallel_call(
            "EchoService.Echo", b"F", timeout_ms=3000, fail_limit=3)
        assert rc == 0 and failed == 2
        assert body == b"FF"  # the two live responses merged
        rc, _, err, failed = c.parallel_call(
            "EchoService.Echo", b"F", timeout_ms=3000, fail_limit=2)
        assert rc != 0 and failed == 2
        assert "2/4 sub calls failed" in err


def test_partition_fail_limit_under_injected_faults(swarm_server):
    """NAT_FAULT seeds (the PR-5 table) against the fan-out merge: with
    write errors injected, every partition_call outcome must satisfy
    the fail_limit contract — rc==0 iff failed < limit — and recovery
    after clearing the table is complete."""
    ports = swarm_server[:4]
    with NativeCluster(lb="rr") as c:
        c.update([(f"127.0.0.1:{p}", 1, f"{i}/4")
                  for i, p in enumerate(ports)])
        native.fault_configure("seed=42;write:err=EPIPE:p=0.25")
        try:
            saw_failure = False
            for _ in range(40):
                rc, body, err, failed = c.partition_call(
                    "EchoService.Echo", b"f", timeout_ms=2000,
                    partitions=4, fail_limit=2)
                if rc == 0:
                    assert failed < 2
                    assert body == b"f" * (4 - failed)
                else:
                    assert failed >= 2
                    saw_failure = True
            assert saw_failure  # the seed actually injected
        finally:
            native.fault_configure(os.environ.get("NAT_FAULT", ""))
        # recovery: with the table cleared the scheme is whole again
        deadline = time.time() + 10
        while time.time() < deadline:
            rc, body, _, failed = c.partition_call(
                "EchoService.Echo", b"r", timeout_ms=2000, partitions=4)
            if rc == 0 and failed == 0:
                break
            time.sleep(0.1)  # cool-downs from the fault burst lapse
        assert rc == 0 and failed == 0 and body == b"rrrr"


# ---------------------------------------------------------------------------
# LB policies
# ---------------------------------------------------------------------------

def test_wrr_respects_weights(swarm_server):
    ports = swarm_server[:2]
    with NativeCluster(lb="wrr") as c:
        c.update([(f"127.0.0.1:{ports[0]}", 1, ""),
                  (f"127.0.0.1:{ports[1]}", 3, "")])
        picks = collections.Counter(
            c.select_debug(i) for i in range(400))
        heavy = picks[f"127.0.0.1:{ports[1]}"]
        light = picks[f"127.0.0.1:{ports[0]}"]
        assert light > 0 and heavy > 0
        assert 2.0 <= heavy / light <= 4.5  # ~3:1 smooth-wrr split


def test_consistent_hash_routes_by_request_code(swarm_server):
    with _mk_cluster(swarm_server, lb="c_hash") as c:
        # the same request code always lands on the same backend
        for code in (7, 99, 12345):
            first = c.select_debug(code)
            assert first is not None
            assert all(c.select_debug(code) == first for _ in range(10))


def test_consistent_hash_bounded_remap(swarm_server):
    """The bounded-remap property: removing ONE backend from N moves
    only the keys whose ring arc it owned (~K/N), everything else stays
    put. A naive mod-N hash would move ~K*(N-1)/N."""
    eps = [f"127.0.0.1:{40000 + i}" for i in range(20)]  # never dialed
    K = 1500
    with NativeCluster(lb="c_hash") as c:
        c.update(eps)
        before = {code: c.select_debug(code) for code in range(K)}
        victim = eps[7]
        c.update([e for e in eps if e != victim])
        moved = 0
        for code in range(K):
            after = c.select_debug(code)
            assert after != victim
            if before[code] != victim and after != before[code]:
                moved += 1
        # expected K/N = 75; allow generous slack for arc adjacency
        assert moved <= 3 * K // len(eps), \
            f"{moved} of {K} keys moved on one removal"


def test_la_policy_prefers_fast_backends(swarm_server):
    with _mk_cluster(swarm_server[:3], lb="la") as c:
        for i in range(30):
            rc, _, err = c.call("EchoService.Echo", b"la", timeout_ms=2000)
            assert rc == 0, err
        rows = c.stats()
        assert sum(r["selects"] for r in rows) >= 30
        assert all(r["ema_latency_us"] > 0 for r in rows)


# ---------------------------------------------------------------------------
# naming feed + membership races
# ---------------------------------------------------------------------------

def test_naming_update_add_remove(swarm_server):
    with NativeCluster(lb="rr") as c:
        c.update([f"127.0.0.1:{swarm_server[0]}"])
        assert c.backend_count() == 1
        c.update([f"127.0.0.1:{p}" for p in swarm_server])
        assert c.backend_count() == len(swarm_server)
        c.update([f"127.0.0.1:{p}" for p in swarm_server[:2]])
        assert c.backend_count() == 2
        rc, body, err = c.call("EchoService.Echo", b"after-shrink",
                               timeout_ms=2000)
        assert rc == 0, err


def test_naming_watcher_drives_cluster(swarm_server, tmp_path):
    nf = tmp_path / "swarm.ns"
    nf.write_text("".join(f"127.0.0.1:{p}\n" for p in swarm_server[:3]))
    with NativeCluster(lb="rr") as c:
        c.watch(f"file://{nf}")
        assert c.backend_count() == 3  # first resolution is synchronous
        rc, _, err = c.call("EchoService.Echo", b"ns", timeout_ms=2000)
        assert rc == 0, err
        # live add: the file naming service re-resolves on its interval
        nf.write_text("".join(f"127.0.0.1:{p}\n" for p in swarm_server))
        deadline = time.time() + 10
        while time.time() < deadline and \
                c.backend_count() != len(swarm_server):
            time.sleep(0.2)
        assert c.backend_count() == len(swarm_server)


def test_membership_updates_race_inflight_selects(swarm_server):
    """The DoublyBufferedData contract under fire: naming add/remove
    churns the server list from one thread while selects + calls run
    hot from others — no failed call may escape, every pick lands on a
    then-live version."""
    all_eps = [f"127.0.0.1:{p}" for p in swarm_server]
    with NativeCluster(lb="rr") as c:
        c.update(all_eps)
        stop = threading.Event()
        failures = []

        def caller():
            i = 0
            while not stop.is_set():
                rc, _, err = c.call("EchoService.Echo", b"race",
                                    timeout_ms=3000, max_retry=4)
                if rc != 0:
                    failures.append((rc, err))
                i += 1

        def selector():
            while not stop.is_set():
                ep = c.select_debug(0)
                assert ep is None or ep in all_eps

        threads = [threading.Thread(target=caller) for _ in range(2)]
        threads += [threading.Thread(target=selector)]
        for t in threads:
            t.start()
        # 60 membership flaps while the flood runs
        for i in range(60):
            keep = 2 + (i % (len(all_eps) - 2))
            c.update(all_eps[:keep])
            time.sleep(0.005)
        c.update(all_eps)
        time.sleep(0.2)
        stop.set()
        for t in threads:
            t.join(timeout=30)
        assert not failures, failures[:5]


# ---------------------------------------------------------------------------
# health: breaker / cool-down / multi-port lifecycle
# ---------------------------------------------------------------------------

def test_dead_backend_cools_down_and_recovers(swarm_server):
    """Transport failures cool a dead backend out of the candidate set
    (the churn fix); selection keeps succeeding on the live peers."""
    live = [f"127.0.0.1:{p}" for p in swarm_server[:2]]
    dead = "127.0.0.1:1"
    with NativeCluster(lb="rr", connect_timeout_ms=200) as c:
        c.update(live + [dead])
        for _ in range(30):
            rc, _, err = c.call("EchoService.Echo", b"x", timeout_ms=3000,
                                max_retry=4)
            assert rc == 0, err
        dead_row = [r for r in c.stats() if r["endpoint"] == dead][0]
        # the cool-down capped the dead peer's attempts far below the
        # 30-call flood's rr share of repeated failures
        assert dead_row["errors"] <= 10


def test_server_remove_port_refuses_new_connects(swarm_server):
    extra = native.rpc_server_add_port()
    with NativeCluster(lb="rr", connect_timeout_ms=300) as c:
        c.update([f"127.0.0.1:{extra}"])
        rc, _, err = c.call("EchoService.Echo", b"pre", timeout_ms=2000)
        assert rc == 0, err
    assert native.rpc_server_remove_port(extra) == 0
    assert native.rpc_server_remove_port(extra) == -1  # idempotent-ish
    with NativeCluster(lb="rr", connect_timeout_ms=300) as c2:
        c2.update([f"127.0.0.1:{extra}"])
        rc, _, _ = c2.call("EchoService.Echo", b"post", timeout_ms=800,
                           max_retry=0)
        assert rc != 0  # the listener is gone


# ---------------------------------------------------------------------------
# tracing: per-sub-call spans parent under one trace
# ---------------------------------------------------------------------------

def test_parallel_subcall_spans_share_one_trace(swarm_server):
    native.stats_enable_spans(1)
    native.stats_drain_spans()  # flush older spans
    trace_id = 0x1234567
    try:
        with _mk_cluster(swarm_server[:3]) as c:
            with native.trace_scope(trace_id, 0x42):
                rc, _, err, failed = c.parallel_call(
                    "EchoService.Echo", b"span", timeout_ms=3000)
        assert rc == 0 and failed == 0, err
        deadline = time.time() + 5
        spans = []
        while time.time() < deadline:
            spans += native.stats_drain_spans()
            verb = [s for s in spans if s["trace_id"] == trace_id
                    and s["method"].startswith("parallel*")]
            subs = [s for s in spans if s["trace_id"] == trace_id
                    and s["method"] == "EchoService.Echo"
                    and s["lane"] == "client"]
            if verb and len(subs) >= 3:
                break
            time.sleep(0.05)
        assert verb, "fan-out verb span missing"
        assert len(subs) >= 3, f"only {len(subs)} sub-call spans"
        # every sub-call span nests under the verb's span
        assert all(s["parent_span_id"] == verb[0]["span_id"]
                   for s in subs)
        assert verb[0]["parent_span_id"] == 0x42
    finally:
        native.stats_enable_spans(0)


# ---------------------------------------------------------------------------
# the Python combo channels' native fast paths
# ---------------------------------------------------------------------------

def test_parallel_channel_native_fast_path(swarm_server):
    from brpc_tpu.rpc.combo_channels import ParallelChannel

    pch = ParallelChannel(native=True)
    listurl = "list://" + ",".join(f"127.0.0.1:{p}"
                                   for p in swarm_server[:4])
    assert pch.init(listurl) == 0
    try:
        assert pch.channel_count == 4
        cntl = rpc.Controller()
        cntl.timeout_ms = 3000
        resp = echo_pb2.EchoResponse()
        pch.call_method("EchoService.Echo", cntl,
                        echo_pb2.EchoRequest(message="np"), resp)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "np"
        assert cntl.latency_us > 0
        # async shape: done fires exactly once off-thread
        done_ev = threading.Event()
        cntl2 = rpc.Controller()
        cntl2.timeout_ms = 3000
        resp2 = echo_pb2.EchoResponse()
        pch.call_method("EchoService.Echo", cntl2,
                        echo_pb2.EchoRequest(message="async"), resp2,
                        done=lambda c: done_ev.set())
        assert done_ev.wait(10)
        assert not cntl2.failed() and resp2.message == "async"
        with pytest.raises(ValueError):
            pch.add_channel(object())  # mixed modes refuse loudly
    finally:
        pch.stop()


def test_selective_channel_native_fast_path(swarm_server):
    from brpc_tpu.rpc.combo_channels import SelectiveChannel

    sch = SelectiveChannel(max_retry=3, native=True)
    listurl = "list://" + ",".join(f"127.0.0.1:{p}"
                                   for p in swarm_server[:3])
    assert sch.init(listurl, "rr") == 0
    try:
        for i in range(6):
            cntl = rpc.Controller()
            cntl.timeout_ms = 2000
            resp = echo_pb2.EchoResponse()
            sch.call_method("EchoService.Echo", cntl,
                            echo_pb2.EchoRequest(message=f"s{i}"), resp)
            assert not cntl.failed(), cntl.error_text
            assert resp.message == f"s{i}"
    finally:
        sch.stop()


def test_partition_channel_native_fast_path(swarm_server, tmp_path):
    from brpc_tpu.rpc.combo_channels import PartitionChannel

    nf = tmp_path / "parts.ns"
    nf.write_text(f"127.0.0.1:{swarm_server[0]} 0/2\n"
                  f"127.0.0.1:{swarm_server[1]} 1/2\n")
    prt = PartitionChannel(native=True)
    assert prt.init(2, f"file://{nf}") == 0
    try:
        cntl = rpc.Controller()
        cntl.timeout_ms = 3000
        resp = echo_pb2.EchoResponse()
        prt.call_method("EchoService.Echo", cntl,
                        echo_pb2.EchoRequest(message="2way"), resp)
        assert not cntl.failed(), cntl.error_text
        assert resp.message == "2way"
    finally:
        prt.stop()


def test_mesh_channel_host_axis(swarm_server):
    """MeshChannel: the device axis keeps its XLA lowering; the host
    axis fans through the native cluster."""
    jax = pytest.importorskip("jax")
    import numpy as np

    from brpc_tpu.parallel import collectives
    from brpc_tpu.parallel.mesh_channel import MeshChannel

    mesh = collectives.make_mesh({"dp": len(jax.devices())})
    mc = MeshChannel(mesh, "dp")
    # device axis: the fused-collective lowering (brpc_tpu.jaxcompat
    # resolves the jax.shard_map location/kwarg drift)
    out = mc.parallel_call(lambda x: x * 2, np.ones(8, np.float32),
                           merger="add")
    assert float(out[0]) == 2.0 * len(jax.devices())
    # host axis: native fan-out over cluster backends
    with _mk_cluster(swarm_server[:3]) as cluster:
        mc.attach_host_cluster(cluster)
        rc, body, err, failed = mc.host_parallel_call(
            "EchoService.Echo", b"mesh", timeout_ms=3000)
        assert rc == 0 and failed == 0, err
        assert body == b"mesh" * 3
    with pytest.raises(ValueError):
        MeshChannel(mesh, "dp").host_parallel_call("X.Y", b"")


# ---------------------------------------------------------------------------
# churn acceptance (slow): zero failed RPCs through rolling SIGTERM
# restarts + live naming add/remove
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_swarm_churn_zero_failed_rpcs():
    """The ROADMAP item-1 acceptance, scaled for CI: a multi-process
    multi-port swarm behind the native cluster survives rolling SIGTERM
    restarts (graceful quiesce + lame-duck) and live naming updates
    with ZERO failed RPCs and a recorded per-backend distribution."""
    from brpc_tpu.bench import fanout_swarm_bench

    r = fanout_swarm_bench(backends=120, servers=3, bench_seconds=8.0,
                           concurrency=3)
    assert r["swarm_backends"] == 120
    assert r["swarm_restarts"] == 3
    assert r["swarm_failed"] == 0, r
    assert r["swarm_qps"] > 0
    assert r["swarm_calls"] > 1000
    spread = r["swarm_selects_per_backend"]
    assert spread["min"] >= 1  # every backend took load
