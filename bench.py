"""Benchmark driver — prints ONE JSON line with the headline metric.

Headline (BASELINE.md): echo RPC throughput. Until the native echo path
lands this reports the flagship-model forward throughput on the real chip;
once brpc_tpu.rpc + native core are in, this runs the echo benchmark
(multi_threaded_echo analog) and reports QPS vs the reference's 500k QPS
production claim (docs/en/overview.md:88).

JSON schema (one line on stdout):
  metric / value / unit / vs_baseline  — the headline figure
  extra.*_qps                          — per-lane throughput
  extra.native_latency_us              — per-lane tail latency from the
      native log2 histograms (nat_stats.cpp), keyed by lane
      (echo/http/redis/grpc/client), each {p50, p99, p999} in
      microseconds measured parse-complete -> response-write (server
      lanes) or call-begin -> completion (client lane)
  extra.device_lanes                   — device-transport GB/s rows
      (incl. shm_push_* over the descriptor-ring fabric,
      read_arena_grow_GBps prefault-on-grow regression row, and .hops:
      per-hop µs of the zero-copy path — arena-write / ring / consume /
      device_put — so a fabric regression localizes to its hop)
  extra.scaling                        — with --cpus N: the per-core
      scaling curve {"1": qps, ..., "N": qps, "cpu_sets": ...} from
      taskset-pinned two-process echo runs; server and client runtimes
      are pinned to DISJOINT cpu sets from 2 cpus up (schema note: the
      in-process lanes above keep sharing cores — the curve is the
      interference-free measurement). The bench gate derives
      cpus2_scaling_x = qps(2)/qps(1) and bands it like any lane.
The process must exit 0: the artifact of record is untrustworthy if the
bench dies at teardown (BENCH_r05 rc 139).
"""
import argparse
import json
import sys
import time


def bench_echo():
    """Echo QPS over loopback using the framework's RPC stack. Headline is
    the native C++ data path (multi_threaded_echo analog); falls back to
    the pure-Python stack when the native toolchain is absent."""
    try:
        from brpc_tpu import native

        if native.available():
            from brpc_tpu.bench import framework_echo_bench

            return framework_echo_bench()
    except Exception:
        pass
    from brpc_tpu.bench import echo_bench  # implemented with the rpc layer

    return echo_bench()


def bench_model_fwd():
    import jax
    import jax.numpy as jnp

    from brpc_tpu.tensor import ModelConfig, forward_local, init_params

    cfg = ModelConfig(vocab=256, d_model=256, n_heads=8, d_head=32,
                      d_ff=512, n_layers=4, n_experts=4)
    params = init_params(cfg, jax.random.PRNGKey(0))
    B, T = 8, 512
    tokens = jnp.zeros((B, T), dtype=jnp.int32)
    fn = jax.jit(lambda p, t: forward_local(p, t, cfg))
    fn(params, tokens)[0].block_until_ready()  # compile
    n_iters = 20
    t0 = time.perf_counter()
    for _ in range(n_iters):
        out = fn(params, tokens)
    out.block_until_ready()
    dt = time.perf_counter() - t0
    tok_s = B * T * n_iters / dt
    return {
        "metric": "flagship_fwd_tokens_per_s",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": 0.0,
    }


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cpus", type=int, default=0, metavar="N",
                    help="record a per-core scaling curve at {1..N} cpus "
                         "(taskset-pinned two-process echo lane) into "
                         "extra.scaling")
    ap.add_argument("--conn-scale", type=int, default=None, metavar="N",
                    help="override the connection-scale drill's target "
                         "connection count (default 20000, clamped to "
                         "RLIMIT_NOFILE; 0 disables the lane)")
    args = ap.parse_args()
    if args.conn_scale is not None:
        import os

        os.environ["BRPC_TPU_CONN_SCALE"] = str(args.conn_scale)
    try:
        result = bench_echo()
    except (ImportError, ModuleNotFoundError):
        # Echo bench not built yet — report the model-forward metric. Real
        # failures inside an existing echo bench must propagate, not be
        # silently replaced by a different headline metric.
        result = bench_model_fwd()
    # device-side figure riding the extras (the rdma_performance north
    # star): achieved allreduce bandwidth — only meaningful on a REAL
    # multi-device mesh (one device moves zero inter-chip bytes)
    try:
        import jax

        if len(jax.devices()) > 1:
            from brpc_tpu.bench import collective_bench

            coll = collective_bench(nbytes=1 << 24, iters=10)
            result.setdefault("extra", {})["allreduce_GBps"] = coll["value"]
    except Exception:
        pass
    # multicore scaling curve (--cpus N): qps at {1..N} cpus, pinned
    # server/client processes — sublinear scaling is a bench-gate finding
    if args.cpus > 0:
        try:
            from brpc_tpu import native
            from brpc_tpu.bench import scaling_bench

            if native.available():
                result.setdefault("extra", {})["scaling"] = \
                    scaling_bench(args.cpus)
        except Exception:
            pass
    print(json.dumps(result))


if __name__ == "__main__":
    main()
