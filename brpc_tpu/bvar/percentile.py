"""Percentile — reservoir-sampled latency distribution.

Counterpart of bvar::detail::Percentile
(/root/reference/src/bvar/detail/percentile.{h,cpp}): per-interval reservoirs
(bounded random replacement, so hot paths never allocate unboundedly) merged
into a global window from which p50/p90/p99/p99.9 are read.
"""
from __future__ import annotations

import random
import threading
from collections import deque
from typing import Deque, List

SAMPLES_PER_INTERVAL = 254  # reference: 254 samples per ThreadLocalPercentileSamples


class _Interval:
    """One sampling interval's reservoir."""

    __slots__ = ("samples", "num_added")

    def __init__(self):
        self.samples: List[float] = []
        self.num_added = 0

    def add(self, value: float):
        self.num_added += 1
        if len(self.samples) < SAMPLES_PER_INTERVAL:
            self.samples.append(value)
        else:  # reservoir replacement keeps a uniform sample of the interval
            i = random.randrange(self.num_added)
            if i < SAMPLES_PER_INTERVAL:
                self.samples[i] = value


class Percentile:
    def __init__(self, window_size: int = 10):
        self._window_size = window_size
        self._current = _Interval()
        self._history: Deque[_Interval] = deque(maxlen=window_size)
        self._lock = threading.Lock()

    def update(self, value: float):
        with self._lock:
            self._current.add(value)

    __lshift__ = update

    def rotate(self):
        """Close the current interval into history (called by the sampler
        tick, mirroring take_sample of percentile.h)."""
        with self._lock:
            if self._current.num_added:
                self._history.append(self._current)
                self._current = _Interval()

    def _merged(self) -> List[float]:
        with self._lock:
            merged: List[float] = []
            for interval in self._history:
                merged.extend(interval.samples)
            merged.extend(self._current.samples)
        merged.sort()
        return merged

    def get_number(self, ratio: float) -> float:
        """Value at quantile `ratio` in the window (percentile.h
        GetPercentileValue)."""
        merged = self._merged()
        if not merged:
            return 0.0
        idx = min(len(merged) - 1, int(ratio * len(merged)))
        return merged[idx]

    def describe(self) -> str:
        return (
            f"p50={self.get_number(0.5):.0f} p90={self.get_number(0.9):.0f} "
            f"p99={self.get_number(0.99):.0f} p999={self.get_number(0.999):.0f}"
        )
