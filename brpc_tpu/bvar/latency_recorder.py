"""LatencyRecorder — the compound qps+latency+percentile metric.

Counterpart of bvar::LatencyRecorder
(/root/reference/src/bvar/latency_recorder.h:49-139): one `update(latency)`
per request feeds window-averaged latency, max latency, qps, count, and
p50/90/99/99.9 — the standard per-method instrument consumed by MethodStatus
and the /status page.
"""
from __future__ import annotations

from typing import Optional

from brpc_tpu.bvar.percentile import Percentile
from brpc_tpu.bvar.reducer import Adder, IntRecorder, Maxer
from brpc_tpu.bvar.sampler import Sampler
from brpc_tpu.bvar.window import PerSecond, Window


class LatencyRecorder:
    def __init__(self, name: Optional[str] = None, window_size: int = 10):
        self._latency = IntRecorder()
        self._max_latency = Maxer()
        self._count = Adder()
        self._latency_window = Window(self._latency, window_size)
        self._max_window = Window(self._max_latency, window_size)
        self._qps_window = PerSecond(self._count, window_size)
        self._percentile = Percentile(window_size)
        self._percentile_sampler = Sampler(self._rotate_percentile, window_size)
        if name:
            self.expose(name)

    def _rotate_percentile(self):
        self._percentile.rotate()

    def expose(self, name: str):
        self._latency_window.expose(f"{name}_latency")
        self._max_window.expose(f"{name}_max_latency")
        self._qps_window.expose(f"{name}_qps")
        self._count.expose(f"{name}_count")

    # -- hot path ----------------------------------------------------------
    def update(self, latency_us: float):
        self._latency.update(latency_us)
        self._max_latency.update(latency_us)
        self._count.update(1)
        self._percentile.update(latency_us)

    __lshift__ = update

    # -- reads -------------------------------------------------------------
    def latency(self) -> float:
        """Window-averaged latency (us)."""
        v = self._latency_window.get_value()
        return v.average if hasattr(v, "average") else 0.0

    def max_latency(self) -> float:
        return self._max_window.get_value()

    def qps(self) -> float:
        return self._qps_window.get_value()

    def count(self) -> int:
        return self._count.get_value()

    def latency_percentile(self, ratio: float) -> float:
        return self._percentile.get_number(ratio)

    def describe(self) -> str:
        return (
            f"count={self.count()} qps={self.qps():.1f} "
            f"avg={self.latency():.1f}us max={self.max_latency():.0f}us "
            f"{self._percentile.describe()}"
        )
