"""Collector — budgeted sampling of arbitrary objects.

Counterpart of bvar::Collector (/root/reference/src/bvar/collector.h:40-63):
subsystems submit objects (spans, dumped requests, ...) and the collector
keeps a bounded per-second sample budget (~16384 base samples/s in the
reference), downsampling under pressure. rpc_dump and rpcz share this
philosophy; this generic version serves new subsystems.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Deque, List, Optional

COLLECTOR_SAMPLING_BASE = 16384  # collector.h:40


class Collectable:
    """Optional base: override destroy() for cleanup on drop."""

    def destroy(self):
        pass


class Collector:
    def __init__(self, max_samples_per_second: int = COLLECTOR_SAMPLING_BASE,
                 drain_fn: Optional[Callable[[List], None]] = None,
                 max_pending: int = 65536):
        self._budget = max_samples_per_second
        self._drain_fn = drain_fn
        self._pending: Deque = deque(maxlen=max_pending)
        self._lock = threading.Lock()
        self._window_start = time.monotonic()
        self._window_count = 0
        self._submitted = 0
        self._sampled = 0

    def submit(self, obj) -> bool:
        """True if kept; False if dropped by the speed limit."""
        now = time.monotonic()
        with self._lock:
            self._submitted += 1
            if now - self._window_start >= 1.0:
                self._window_start = now
                self._window_count = 0
            if self._window_count >= self._budget:
                if isinstance(obj, Collectable):
                    obj.destroy()
                return False
            self._window_count += 1
            self._sampled += 1
            self._pending.append(obj)
            return True

    def drain(self) -> List:
        """Take everything collected so far (the background-thread pass of
        collector.cpp)."""
        with self._lock:
            out = list(self._pending)
            self._pending.clear()
        if self._drain_fn is not None and out:
            self._drain_fn(out)
        return out

    @property
    def submitted_count(self) -> int:
        return self._submitted

    @property
    def sampled_count(self) -> int:
        return self._sampled

    @property
    def pending_count(self) -> int:
        return len(self._pending)
