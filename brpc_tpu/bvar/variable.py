"""Variable — named, exposable metric base + global registry.

Counterpart of bvar::Variable (/root/reference/src/bvar/variable.h:102-129):
every metric can be exposed under a unique name, hidden, described as text,
and dumped in bulk — the data source behind /vars and /brpc_metrics.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

_registry: "Dict[str, Variable]" = {}
_registry_lock = threading.Lock()


class Variable:
    """Base of all metrics. Subclasses implement get_value()."""

    def __init__(self, name: Optional[str] = None):
        self._name: Optional[str] = None
        if name:
            self.expose(name)

    # -- registry ----------------------------------------------------------
    def expose(self, name: str) -> bool:
        name = name.strip().replace(" ", "_")
        with _registry_lock:
            if name in _registry and _registry[name] is not self:
                return False
            if self._name and self._name != name:
                _registry.pop(self._name, None)
            _registry[name] = self
            self._name = name
            return True

    def expose_as(self, prefix: str, name: str) -> bool:
        return self.expose(f"{prefix}_{name}" if prefix else name)

    def hide(self) -> bool:
        with _registry_lock:
            if self._name and _registry.get(self._name) is self:
                del _registry[self._name]
                self._name = None
                return True
            return False

    @property
    def name(self) -> Optional[str]:
        return self._name

    def is_hidden(self) -> bool:
        return self._name is None

    # -- value -------------------------------------------------------------
    def get_value(self):
        raise NotImplementedError

    def describe(self) -> str:
        return str(self.get_value())

    def __del__(self):
        try:
            self.hide()
        except Exception:
            pass


class StatusVar(Variable):
    """Explicitly-set value (bvar::Status, status.h)."""

    def __init__(self, name: Optional[str] = None, value=None):
        self._value = value
        self._lock = threading.Lock()
        super().__init__(name)

    def set_value(self, value):
        with self._lock:
            self._value = value

    def get_value(self):
        with self._lock:
            return self._value


class PassiveStatus(Variable):
    """Callback-computed value (bvar::PassiveStatus, passive_status.h)."""

    def __init__(self, callback: Callable[[], object], name: Optional[str] = None):
        self._callback = callback
        super().__init__(name)

    def get_value(self):
        return self._callback()


def find_exposed(name: str) -> Optional[Variable]:
    with _registry_lock:
        return _registry.get(name)


def list_exposed() -> List[str]:
    with _registry_lock:
        return sorted(_registry.keys())


def count_exposed() -> int:
    with _registry_lock:
        return len(_registry)


def dump_exposed(filter_fn: Optional[Callable[[str], bool]] = None) -> List[Tuple[str, object]]:
    """Snapshot of (name, value) for every exposed variable — the /vars body."""
    with _registry_lock:
        items = list(_registry.items())
    out = []
    for name, var in sorted(items):
        if filter_fn and not filter_fn(name):
            continue
        try:
            out.append((name, var.get_value()))
        except Exception as e:  # a broken callback must not break /vars
            out.append((name, f"<error: {e}>"))
    return out


def _prom_label_escape(val) -> str:
    """Prometheus exposition-format label-value escaping: backslash,
    double quote and newline must be escaped (method paths contain `/`
    — legal as-is — and may contain `"`)."""
    return (str(val).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def dump_prometheus() -> str:
    """Prometheus text exposition of all exposed scalar variables
    (builtin/prometheus_metrics_service.cpp equivalent)."""
    lines = []
    for name, value in dump_exposed():
        metric = name.replace("-", "_").replace(".", "_").replace("/", "_")
        if isinstance(value, bool):
            value = int(value)
        if isinstance(value, (int, float)):
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {value}")
        elif isinstance(value, dict):  # multi-dimension: labels -> scalar
            lines.append(f"# TYPE {metric} gauge")
            for labels, v in value.items():
                if isinstance(v, (int, float)):
                    label_s = ",".join(
                        f'{k}="{_prom_label_escape(val)}"'
                        for k, val in labels)
                    lines.append(f"{metric}{{{label_s}}} {v}")
    return "\n".join(lines) + "\n"


def clear_registry_for_tests():
    with _registry_lock:
        _registry.clear()
