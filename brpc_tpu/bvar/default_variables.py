"""Process-level system stats exposed as variables.

Counterpart of bvar/default_variables.cpp: process cpu/mem/fd/thread counts
read from /proc, plus TPU-native extras — jax device count/kind and
per-device HBM stats where the backend reports them.
"""
from __future__ import annotations

import os
import time

from brpc_tpu.bvar.variable import PassiveStatus

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100
_PAGE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def _proc_stat_fields():
    try:
        with open("/proc/self/stat", "rb") as f:
            data = f.read().decode()
        # fields after the (comm) — comm may contain spaces
        return data[data.rindex(")") + 2 :].split()
    except OSError:
        return []


def _cpu_seconds() -> float:
    f = _proc_stat_fields()
    if len(f) < 13:
        return 0.0
    utime, stime = int(f[11]), int(f[12])  # fields 14,15 (1-based)
    return (utime + stime) / _CLK_TCK


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm", "rb") as f:
            return int(f.read().split()[1]) * _PAGE
    except OSError:
        return 0


def _fd_count() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return 0


def _thread_count() -> int:
    f = _proc_stat_fields()
    return int(f[17]) if len(f) > 17 else 0


_start_time = time.time()

_exposed = False


def expose_default_variables():
    """Idempotently expose process_* variables (called by Server start)."""
    global _exposed
    if _exposed:
        return
    _exposed = True
    PassiveStatus(_cpu_seconds, "process_cpu_seconds")
    PassiveStatus(_rss_bytes, "process_memory_resident_bytes")
    PassiveStatus(_fd_count, "process_fd_count")
    PassiveStatus(_thread_count, "process_thread_count")
    PassiveStatus(lambda: os.getpid(), "process_pid")
    PassiveStatus(lambda: time.time() - _start_time, "process_uptime_seconds")

    def _device_count():
        try:
            import jax

            return len(jax.devices())
        except Exception:
            return 0

    PassiveStatus(_device_count, "tpu_device_count")
