"""brpc_tpu.bvar — lock-light metrics (SURVEY.md section 2.3).

Per-thread-agent reducers + background sampler + windows + percentiles, the
instrumentation substrate consumed by the scheduler, sockets, servers,
channels, and the builtin console — mirroring how bvar underpins every brpc
layer (/root/reference/src/bvar/).
"""
from brpc_tpu.bvar.variable import (  # noqa: F401
    PassiveStatus,
    StatusVar,
    Variable,
    count_exposed,
    dump_exposed,
    dump_prometheus,
    find_exposed,
    list_exposed,
)
from brpc_tpu.bvar.reducer import Adder, IntRecorder, Maxer, Miner, Stat  # noqa: F401
from brpc_tpu.bvar.window import PerSecond, Window  # noqa: F401
from brpc_tpu.bvar.percentile import Percentile  # noqa: F401
from brpc_tpu.bvar.latency_recorder import LatencyRecorder  # noqa: F401
from brpc_tpu.bvar.multi_dimension import MultiDimension  # noqa: F401
from brpc_tpu.bvar.sampler import force_tick_for_tests  # noqa: F401
from brpc_tpu.bvar.default_variables import expose_default_variables  # noqa: F401
from brpc_tpu.bvar.native_vars import register_native_bvars  # noqa: F401


def expose_flags_as_bvars():
    """gflag bridge (bvar/gflag.{h,cpp}): every defined flag becomes a
    PassiveStatus named flag_<name>."""
    from brpc_tpu.butil import flags as _flags

    for name, f in _flags.all_flags().items():
        bvar_name = f"flag_{name}"
        if find_exposed(bvar_name) is None:
            PassiveStatus(lambda f=f: f.value, bvar_name)
