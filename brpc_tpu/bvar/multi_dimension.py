"""MultiDimension — labelled metrics for Prometheus exposition.

Counterpart of bvar::MultiDimension (/root/reference/src/bvar/multi_dimension.h):
one logical metric fanned out over label tuples; get_stats(labels) lazily
creates the underlying variable per label combination.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from brpc_tpu.bvar.variable import Variable


class MultiDimension(Variable):
    def __init__(
        self,
        label_names: List[str],
        factory: Callable[[], Variable],
        name: Optional[str] = None,
    ):
        self._label_names = tuple(label_names)
        self._factory = factory
        self._stats: Dict[Tuple[str, ...], Variable] = {}
        self._lock = threading.Lock()
        super().__init__(name)

    def get_stats(self, *label_values: str) -> Variable:
        if len(label_values) != len(self._label_names):
            raise ValueError(
                f"expected {len(self._label_names)} labels, got {len(label_values)}"
            )
        key = tuple(str(v) for v in label_values)
        with self._lock:
            var = self._stats.get(key)
            if var is None:
                var = self._factory()
                self._stats[key] = var
            return var

    def has_stats(self, *label_values: str) -> bool:
        with self._lock:
            return tuple(str(v) for v in label_values) in self._stats

    def delete_stats(self, *label_values: str):
        with self._lock:
            self._stats.pop(tuple(str(v) for v in label_values), None)

    def count_stats(self) -> int:
        with self._lock:
            return len(self._stats)

    def get_value(self):
        """Dict of label-tuple -> scalar value; dump_prometheus renders each
        combination as one labelled sample."""
        with self._lock:
            items = list(self._stats.items())
        out = {}
        for key, var in items:
            labels = tuple(zip(self._label_names, key))
            out[labels] = var.get_value()
        return out
