"""Window / PerSecond — time-windowed views over reducers.

Counterpart of bvar::Window / bvar::PerSecond
(/root/reference/src/bvar/window.h:43-197): a Window(reducer, N) shows the
reducer's delta (invertible ops: Adder, IntRecorder) or series-combine
(Maxer/Miner) over the last N seconds, fed by the Sampler thread.
"""
from __future__ import annotations

from typing import Optional

from brpc_tpu.bvar.reducer import Reducer
from brpc_tpu.bvar.sampler import Sampler
from brpc_tpu.bvar.variable import Variable


class Window(Variable):
    def __init__(self, reducer: Reducer, window_size: int = 10,
                 name: Optional[str] = None):
        self._reducer = reducer
        self._window_size = window_size
        self._sampler = Sampler(reducer.get_value, window_size)
        super().__init__(name)

    @property
    def window_size(self) -> int:
        return self._window_size

    def get_value(self):
        if getattr(self._reducer, "invertible", False):
            now = self._reducer.get_value()
            oldest = self._sampler.oldest_in(self._window_size)
            if oldest is None:
                return now
            return now - oldest[1]
        # Non-invertible (Maxer/Miner): series-combine the samples + live.
        samples = self._sampler.samples_in(self._window_size)
        result = self._reducer.get_value()
        for _, v in samples:
            result = self._reducer.series_op(result, v)
        return result

    def get_span(self) -> float:
        """Seconds actually covered (may be < window_size early on)."""
        oldest = self._sampler.oldest_in(self._window_size)
        latest = self._sampler.latest()
        if oldest is None or latest is None:
            return 0.0
        return max(0.0, latest[0] - oldest[0])

    def series(self):
        """Per-second data points for charting (the trend the reference
        plots in-browser with flot, vars_service.cpp ?series): list of
        (ts, value) — consecutive deltas for invertible reducers, raw
        samples otherwise."""
        samples = self._sampler.samples_in(self._window_size)
        if len(samples) < 2:
            return []
        if not getattr(self._reducer, "invertible", False):
            return [(ts, _plain(v)) for ts, v in samples]
        out = []
        for (t0, v0), (t1, v1) in zip(samples, samples[1:]):
            out.append((t1, _plain(v1 - v0)))
        return out

    def destroy(self):
        self._sampler.destroy()
        self.hide()


def _plain(v) -> float:
    """Collapse reducer values (incl. IntRecorder stats) to one number."""
    if hasattr(v, "average"):
        return float(v.average)
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


class PerSecond(Window):
    """Windowed delta divided by elapsed seconds (window.h:174-197)."""

    def series(self):
        samples = self._sampler.samples_in(self._window_size)
        out = []
        for (t0, v0), (t1, v1) in zip(samples, samples[1:]):
            dt = t1 - t0
            if dt <= 0:
                continue
            delta = v1 - v0
            if hasattr(delta, "sum"):  # IntRecorder: rate of the SUM,
                delta = delta.sum      # matching get_value's semantics
            out.append((t1, _plain(delta) / dt))
        return out

    def get_value(self):
        import time

        now = self._reducer.get_value()
        oldest = self._sampler.oldest_in(self._window_size)
        if oldest is None:
            return 0.0
        dt = time.monotonic() - oldest[0]
        if dt <= 0:
            return 0.0
        delta = now - oldest[1]
        if hasattr(delta, "sum"):  # IntRecorder _Stat
            delta = delta.sum
        return delta / dt
