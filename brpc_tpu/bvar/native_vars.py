"""Native-runtime bvars — the C++ stat cells surfaced as first-class vars.

The native core (native/src/nat_stats.{h,cpp}) keeps cache-line-aligned
per-thread cells of monotonic counters and log2 latency histograms, combined
on demand like bvar's AgentCombiner. This module registers that snapshot
surface into the Python bvar registry so native traffic appears in /vars,
/status and /brpc_metrics beside the Python lanes — one pane of glass:

- one PassiveStatus per counter under its native name (nat_*);
- a PerSecond window (``<name>_second``) over each traffic counter, which
  also gives the /vars?chart=1 SVG trend for free;
- per-lane latency percentiles (``nat_<lane>_latency_p50/p99/p999_us``)
  interpolated from the combined log2 histograms (percentile.h's role with
  a deterministic histogram instead of a reservoir).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from brpc_tpu.bvar.variable import PassiveStatus, find_exposed
from brpc_tpu.bvar.window import PerSecond

_lock = threading.Lock()
_registered = False
_vars = []  # keep strong refs: exposed Variables must not be GC'd

class _TtlCache:
    """0.25s-TTL cache over one native snapshot call: /vars, /brpc_metrics
    and the sampler tick read many counters/rows per dump, and each
    uncached fetch walks every native cell. A stale-read race just costs
    a duplicate fetch (same as the pre-class tuple-swap discipline)."""

    def __init__(self, fetch_name: str):
        self._fetch_name = fetch_name  # brpc_tpu.native attribute
        self._ts = 0.0
        self._snap = None

    def get(self):
        now = time.monotonic()
        if self._snap is None or now - self._ts > 0.25:
            from brpc_tpu import native

            self._snap = getattr(native, self._fetch_name)()
            self._ts = now
        return self._snap

    def clear(self):
        self._ts, self._snap = 0.0, None


# one combined-snapshot call per dump, not one per counter
_snap_cache = _TtlCache("stats_counters")


def _snapshot() -> Dict[str, int]:
    return _snap_cache.get()


class _CounterSource:
    """Quacks like an invertible Reducer so Window/PerSecond can sample
    it: get_value() is the combined native counter."""

    invertible = True

    def __init__(self, name: str):
        self._name = name

    def get_value(self) -> int:
        return int(_snapshot().get(self._name, 0))


# gauges / bookkeeping counters whose per-second delta is meaningless
_NO_RATE = {"nat_py_queue_depth", "nat_spans_dropped",
            "nat_connections_accepted", "nat_sqpoll_rings"}

_PCTS = (("p50", 0.50), ("p99", 0.99), ("p999", 0.999))

# ---------------------------------------------------------------------------
# native observatory (ISSUE 9): per-method stats, per-connection rows and
# lock-contention totals surfaced as LABELED vars — each is one
# PassiveStatus whose value is a {((label, value), ...): scalar} dict, the
# MultiDimension shape dump_prometheus renders with escaped label values.
# ---------------------------------------------------------------------------

_method_cache = _TtlCache("method_stats")
_conn_cache = _TtlCache("conn_snapshot")
_res_cache = _TtlCache("res_stats")


def _method_snapshot():
    return _method_cache.get()


def _conn_snapshot():
    return _conn_cache.get()


def _res_snapshot():
    return _res_cache.get()


def _res_dim(field: str):
    return {(("subsystem", r["subsystem"]),): r[field]
            for r in _res_snapshot()}


def _method_labels(row):
    return (("lane", row["lane"]), ("method", row["method"]))


def _method_dim(field: str):
    return {_method_labels(r): r[field] for r in _method_snapshot()}


def _method_p99_dim():
    from brpc_tpu import native

    lanes = {}
    try:
        lanes = {name: i for i, name in
                 enumerate(native.stats_lane_names())}
    except Exception:
        pass
    out = {}
    for r in _method_snapshot():
        li = lanes.get(r["lane"])
        if li is None:
            continue
        out[_method_labels(r)] = round(
            native.method_quantile(li, r["method"], 0.99) / 1e3, 1)
    return out


def _conn_labels(row):
    return (("sock_id", row["sock_id"]), ("remote", row["remote"]),
            ("protocol", row["protocol"]))


def _conn_dim(field: str):
    return {_conn_labels(r): r[field] for r in _conn_snapshot()}


def _lock_dim(field: str):
    from brpc_tpu import native

    return {(("rank", r["rank"]), ("name", r["name"])): r[field]
            for r in native.mu_rank_stats()}


_cluster_rows_cache = {"ts": 0.0, "rows": []}


def _cluster_rows():
    """(cluster_name, backend_row) pairs over every live native cluster
    (the brpc_tpu.rpc.native_cluster registry), cached for 0.25s like
    the other snapshot caches — one /brpc_metrics dump evaluates six
    nat_cluster_backend_* dimensions, and each uncached fetch walks
    every cluster's member map natively. Import is lazy and
    failure-tolerant: a process that never built a cluster pays one
    cheap import check per dump."""
    now = time.monotonic()
    if now - _cluster_rows_cache["ts"] <= 0.25:
        return _cluster_rows_cache["rows"]
    try:
        from brpc_tpu.rpc.native_cluster import live_clusters
    except Exception:
        return []
    out = []
    for c in live_clusters():
        try:
            for row in c.stats():
                out.append((c.name, row))
        except Exception:
            continue
    _cluster_rows_cache["ts"] = now
    _cluster_rows_cache["rows"] = out
    return out


def _cluster_dim(field: str, as_int=None):
    out = {}
    for cname, r in _cluster_rows():
        v = r[field]
        out[(("cluster", cname), ("backend", r["endpoint"]))] = \
            int(v) if as_int else v
    return out


class _ClampedPerSecond(PerSecond):
    """PerSecond over a native counter: monotonic except for
    nat_stats_reset/mu_prof_reset (test/bench hygiene), which would
    otherwise publish a large negative rate for up to one window."""

    def get_value(self):
        return max(0.0, float(super().get_value() or 0.0))


class _KeyedCounterSource:
    """PerSecond source over one keyed row's counter (a method's count, a
    connection's byte counter) — missing keys read 0 so a recycled socket
    window decays instead of raising."""

    invertible = True

    def __init__(self, snap_fn, key_fn, key, field):
        self._snap_fn = snap_fn
        self._key_fn = key_fn
        self._key = key
        self._field = field

    def get_value(self) -> float:
        for r in self._snap_fn():
            if self._key_fn(r) == self._key:
                return float(r[self._field])
        return 0.0


class _KeyedRates:
    """Lazily-created PerSecond windows per key (bvar/window.py over the
    native snapshots): rates(key, fields) returns {field: per-second}.
    Windows for vanished keys are destroyed on the next prune."""

    def __init__(self, snap_fn, key_fn, window_s: int = 10):
        self._snap_fn = snap_fn
        self._key_fn = key_fn
        self._window_s = window_s
        # guards _windows: rate() runs on concurrent request threads
        # (/brpc_metrics scrapes) while prune() runs from /connections
        # renders; unlocked, prune's iteration races rate's insert and
        # a lost check-then-insert race leaks the loser's Sampler
        self._mu = threading.Lock()
        self._windows = {}  # (key, field) -> PerSecond

    def rate(self, key, field) -> float:
        with self._mu:
            w = self._windows.get((key, field))
            if w is None:
                w = _ClampedPerSecond(
                    _KeyedCounterSource(self._snap_fn, self._key_fn, key,
                                        field),
                    self._window_s)
                self._windows[(key, field)] = w
        return float(w.get_value() or 0.0)

    def prune(self, live_keys):
        with self._mu:
            dead = [self._windows.pop(k)
                    for k in list(self._windows) if k[0] not in live_keys]
        for w in dead:  # destroy() talks to the collector; not under _mu
            try:
                w.destroy()
            except Exception:
                pass

    def clear(self):
        """Destroy every window (and its collector Sampler): without
        this, reset_for_tests would orphan samplers that keep polling
        the native snapshots once per second for the process lifetime."""
        with self._mu:
            dead = list(self._windows.values())
            self._windows.clear()
        for w in dead:
            try:
                w.destroy()
            except Exception:
                pass


_method_rates = _KeyedRates(_method_snapshot,
                            lambda r: (r["lane"], r["method"]))
_conn_rates = _KeyedRates(_conn_snapshot, lambda r: r["sock_id"])


def method_qps(lane: str, method: str) -> float:
    """Windowed per-second call rate of one native method row."""
    return _method_rates.rate((lane, method), "count")


def connection_rates(sock_id: int):
    """Windowed per-second byte rates of one native socket (the
    /connections in/out rate columns)."""
    return {"in_Bps": _conn_rates.rate(sock_id, "in_bytes"),
            "out_Bps": _conn_rates.rate(sock_id, "out_bytes")}


def prune_connection_windows(live_sock_ids):
    _conn_rates.prune(set(live_sock_ids))


def register_native_bvars() -> bool:
    """Idempotently expose the native stat surface; False when the native
    library is unavailable."""
    global _registered
    with _lock:
        if _registered:
            # the counter/lane surface is static, but the dispatcher
            # pool may have started AFTER the first registration (e.g.
            # the /vars server came up before any native runtime use):
            # top up the per-dispatcher rows
            _register_dispatcher_rows()
            return True
        try:
            from brpc_tpu import native

            if not native.available():
                return False
            names = native.stats_counter_names()
            lanes = native.stats_lane_names()
        except Exception:
            return False
        for name in names:
            if find_exposed(name) is None:
                _vars.append(PassiveStatus(
                    lambda n=name: int(_snapshot().get(n, 0)), name))
            if name not in _NO_RATE and \
                    find_exposed(f"{name}_second") is None:
                _vars.append(_ClampedPerSecond(_CounterSource(name), 10,
                                               f"{name}_second"))
        for idx, lane in enumerate(lanes):
            for suffix, q in _PCTS:
                vname = f"nat_{lane}_latency_{suffix}_us"
                if find_exposed(vname) is None:
                    _vars.append(PassiveStatus(
                        lambda i=idx, qq=q: round(
                            _stats_quantile_us(i, qq), 1), vname))
        # per-dispatcher rows (multicore scale-out observability): one
        # gauge triple per epoll/io_uring loop — connections owned now,
        # event-delivering wakeup rounds, SQPOLL on/off on its ring
        _register_dispatcher_rows()
        # native observatory (ISSUE 9): labeled multi-dimension vars —
        # per-method stats, per-connection counters and per-rank lock
        # waits ride /brpc_metrics with {label="value"} rows (values
        # escaped by dump_prometheus)
        _LABELED = (
            ("nat_method_count", lambda: _method_dim("count")),
            ("nat_method_errors", lambda: _method_dim("errors")),
            ("nat_method_concurrency",
             lambda: _method_dim("concurrency")),
            ("nat_method_max_concurrency",
             lambda: _method_dim("max_concurrency")),
            ("nat_method_qps",
             lambda: {_method_labels(r):
                      round(method_qps(r["lane"], r["method"]), 1)
                      for r in _method_snapshot()}),
            ("nat_method_latency_p99_us", _method_p99_dim),
            ("nat_connection_in_bytes", lambda: _conn_dim("in_bytes")),
            ("nat_connection_out_bytes", lambda: _conn_dim("out_bytes")),
            ("nat_connection_unwritten_bytes",
             lambda: _conn_dim("unwritten_bytes")),
            ("nat_connection_mem_bytes",
             lambda: _conn_dim("mem_bytes")),
            # native memory observatory (ISSUE 14): the per-resource
            # bvar surface — one row per allocator subsystem from the
            # always-on nat_res ledger
            ("nat_mem_live_bytes", lambda: _res_dim("live_bytes")),
            ("nat_mem_live_objects",
             lambda: _res_dim("live_objects")),
            ("nat_mem_cum_allocs", lambda: _res_dim("cum_allocs")),
            ("nat_mem_cum_frees", lambda: _res_dim("cum_frees")),
            ("nat_mem_hwm_bytes", lambda: _res_dim("hwm_bytes")),
            ("nat_lock_contention_waits", lambda: _lock_dim("waits")),
            ("nat_lock_contention_wait_us",
             lambda: _lock_dim("wait_us")),
            # native fan-out clusters (ISSUE 13): one row per backend of
            # every live cluster — LB selects/errors, in-flight
            # sub-calls, breaker/lame-duck state, EMA latency feedback
            ("nat_cluster_backend_selects",
             lambda: _cluster_dim("selects")),
            ("nat_cluster_backend_errors",
             lambda: _cluster_dim("errors")),
            ("nat_cluster_backend_inflight",
             lambda: _cluster_dim("inflight")),
            ("nat_cluster_backend_breaker_open",
             lambda: _cluster_dim("breaker_open", as_int=True)),
            ("nat_cluster_backend_lame_duck",
             lambda: _cluster_dim("lame_duck", as_int=True)),
            ("nat_cluster_backend_ema_latency_us",
             lambda: _cluster_dim("ema_latency_us")),
        )
        for vname, fn in _LABELED:
            if find_exposed(vname) is None:
                _vars.append(PassiveStatus(fn, vname))
        _registered = True
        return True


def _register_dispatcher_rows():
    """Expose nat_dispatcher_<i>_* rows for every loop that exists NOW;
    called again on later register_native_bvars() calls so a runtime
    started after the first registration still gets its rows (must be
    called with _lock held)."""
    try:
        from brpc_tpu import native

        ndisp = native.dispatcher_count() if native.available() else 0
    except Exception:
        ndisp = 0
    for i in range(ndisp):
        for field in ("sockets", "wakeups", "sqpoll"):
            vname = f"nat_dispatcher_{i}_{field}"
            if find_exposed(vname) is None:
                _vars.append(PassiveStatus(
                    lambda di=i, f=field: _disp_field(di, f), vname))


def _disp_field(idx: int, field: str):
    # one FFI call for the one requested row (a full dispatcher_stats()
    # refetch per field made a /vars render O(ndisp^2) crossings)
    import ctypes

    from brpc_tpu import native

    lib = native.load()
    sockets = ctypes.c_uint64()
    wakeups = ctypes.c_uint64()
    sqpoll = ctypes.c_int()
    if lib.nat_disp_stat(idx, ctypes.byref(sockets), ctypes.byref(wakeups),
                         ctypes.byref(sqpoll)) != 0:
        return 0
    return {"sockets": sockets.value, "wakeups": wakeups.value,
            "sqpoll": sqpoll.value}[field]


def _stats_quantile_us(lane: int, q: float) -> float:
    from brpc_tpu import native

    return native.stats_quantile(lane, q) / 1e3


# ---------------------------------------------------------------------------
# RSS reconciliation (ISSUE 14): /status attributes the accounted share
# of the process's resident growth since the native runtime loaded —
# "do the ledger's bytes explain the RSS the .so added?"
# ---------------------------------------------------------------------------

def _rss_bytes() -> int:
    # the ONE statm reader lives beside the load-time baseline capture
    # (brpc_tpu.native._read_rss) so both ends of the reconciliation
    # parse resident bytes identically
    from brpc_tpu import native

    return native._read_rss()


# fixed BSS sample pools (NAT_RES_STATIC registrations): virtual until a
# sample touches their pages, so the RSS share is computed over the
# HEAP-BACKED subsystems only (the fixed pools still show in the rows)
_FIXED_POOL_SUBSYSTEMS = ("prof.cells",)


def rss_reconciliation_line() -> str:
    """The /status nat_mem line: accounted native bytes, current RSS,
    the RSS delta since just before the .so loaded (the native
    runtime's own memory footprint), and the heap-accounted share of
    that delta. Fixed BSS pools are excluded from the share — they are
    attributed in the rows but only fault in page by page."""
    from brpc_tpu import native

    accounted = native.res_accounted_bytes()
    rows = _res_snapshot()
    fixed = sum(r["live_bytes"] for r in rows
                if r["subsystem"] in _FIXED_POOL_SUBSYSTEMS)
    heap_acct = accounted - fixed
    rss = _rss_bytes()
    base = native.rss_at_load() if hasattr(native, "rss_at_load") else 0
    delta = rss - base if base else 0
    share = f" ({100.0 * heap_acct / delta:.0f}% of rss_delta)" \
        if delta > 0 else ""
    top = sorted(rows, key=lambda r: -r["live_bytes"])[:3]
    top_s = " ".join(f"{r['subsystem']}={r['live_bytes']}"
                     for r in top if r["live_bytes"])
    return (f"  nat_mem: accounted={accounted} bytes "
            f"(heap={heap_acct} fixed_pools={fixed}){share} "
            f"rss={rss} rss_delta_since_native_load={delta}"
            + (f"  top: {top_s}" if top_s else ""))


# the PR-5 robustness counters, summarized on /status as one line the
# moment any of them moves (a fault injection round, an overload shed or
# a breaker trip should be visible at a glance, not only in /vars)
_OVERLOAD_KEYS = ("nat_faults_injected", "nat_elimit_rejects",
                  "nat_queue_deadline_drops", "nat_retry_budget_exhausted",
                  "nat_breaker_isolations", "nat_breaker_revivals")


def native_status_lines(snap: Optional[Dict[str, int]] = None) -> List[str]:
    """The /status page's native section: per-protocol traffic counters
    and tail latency, empty when the native runtime never carried any.
    `snap` overrides the live counter snapshot (tests)."""
    try:
        from brpc_tpu import native

        if not native.available():
            return []
        if snap is None:
            snap = native.stats_counters()
        lanes = native.stats_lane_names()
    except Exception:
        return []
    if not any(snap.values()):
        return []
    lines = ["", "native runtime:"]
    # memory observatory reconciliation (ISSUE 14): the ledger's
    # accounted bytes vs the process's resident delta since native load
    try:
        lines.append(rss_reconciliation_line())
        mem_rows = [r for r in _res_snapshot() if r["live_bytes"]]
        if mem_rows:
            lines.append("  nat_mem subsystems: " + " ".join(
                f"{r['subsystem']}={r['live_bytes']}/"
                f"{r['live_objects']}obj(hwm={r['hwm_bytes']})"
                for r in sorted(mem_rows,
                                key=lambda r: -r["live_bytes"])))
    except Exception:
        pass
    lines.append(
        f"  read_bytes: {snap.get('nat_socket_read_bytes', 0)}  "
        f"write_bytes: {snap.get('nat_socket_write_bytes', 0)}  "
        f"accepted: {snap.get('nat_connections_accepted', 0)}  "
        f"py_queue_depth: {snap.get('nat_py_queue_depth', 0)}")
    proto_keys = (("tpu_std", "nat_tpu_std"), ("http", "nat_http"),
                  ("grpc", "nat_grpc"), ("redis", "nat_redis"),
                  ("client", "nat_client"))
    count_suffix = {"client": ("calls", "responses", "errors")}
    for label, pfx in proto_keys:
        s_in, s_out, s_err = count_suffix.get(
            label, ("msgs_in", "responses_out", "errors"))
        msgs = snap.get(f"{pfx}_{s_in}", 0)
        if msgs == 0:
            continue
        lines.append(
            f"  {label}: in={msgs} out={snap.get(f'{pfx}_{s_out}', 0)} "
            f"errors={snap.get(f'{pfx}_{s_err}', 0)}")
    if any(snap.get(k, 0) for k in _OVERLOAD_KEYS):
        lines.append("  overload/faults: " + " ".join(
            f"{k[4:]}={snap.get(k, 0)}" for k in _OVERLOAD_KEYS))
    # per-method table (the native MethodStatus rows, /status's
    # per-method section for native-dispatched methods)
    try:
        rows = _method_snapshot()
        lane_idx = {name: i for i, name in enumerate(lanes)}
        for r in sorted(rows, key=lambda r: (r["lane"], r["method"])):
            from brpc_tpu import native as _n

            if not (r["count"] or r["concurrency"]
                    or r["max_concurrency"]):
                continue  # claimed but never used (the "(other)" rows)
            li = lane_idx.get(r["lane"])
            p50 = p99 = 0.0
            if li is not None:
                p50 = _n.method_quantile(li, r["method"], 0.50) / 1e3
                p99 = _n.method_quantile(li, r["method"], 0.99) / 1e3
            lines.append(
                f"  method {r['method']} [{r['lane']}]: "
                f"count={r['count']} "
                f"qps={method_qps(r['lane'], r['method']):.1f} "
                f"errors={r['errors']} "
                f"concurrency={r['concurrency']} "
                f"max_concurrency={r['max_concurrency']} "
                f"latency_us: p50={p50:.1f} p99={p99:.1f}")
    except Exception:
        pass
    for idx, lane in enumerate(lanes):
        try:
            from brpc_tpu import native as _n

            if not any(_n.stats_hist(idx)):
                continue
            p50, p99, p999 = (_n.stats_quantile(idx, q) / 1e3
                              for _, q in _PCTS)
        except Exception:
            continue
        lines.append(f"  {lane}_latency_us: p50={p50:.1f} p99={p99:.1f} "
                     f"p999={p999:.1f}")
    # per-cluster tables (ISSUE 13): every live native cluster lists its
    # backends with LB + health state — the /status face of the
    # nat_cluster_* Prometheus rows
    try:
        from brpc_tpu.rpc.native_cluster import live_clusters

        for c in live_clusters():
            rows = c.stats()
            lines.append(f"  cluster {c.name} [{c.lb}]: "
                         f"{len(rows)} backends")
            for r in rows:
                state = []
                if r["breaker_open"]:
                    state.append("BREAKER-OPEN")
                if r["lame_duck"]:
                    state.append("lame-duck")
                if r["tag"]:
                    state.append(f"tag={r['tag']}")
                lines.append(
                    f"    {r['endpoint']} w={r['weight']} "
                    f"selects={r['selects']} errors={r['errors']} "
                    f"inflight={r['inflight']} "
                    f"ema_us={r['ema_latency_us']}"
                    + (" " + " ".join(state) if state else ""))
    except Exception:
        pass
    return lines


def settle_for_tests():
    """Drop the 0.25s-TTL snapshot caches (counters, method/conn/res rows,
    cluster rows) so the NEXT exposition dump reads live native state.

    Tests that open connections or clusters and immediately assert on
    /vars or /brpc_metrics rows race the TTL: an exposition rendered
    within 0.25s of an earlier test's dump replays that test's snapshot,
    which predates the rows being asserted.  Settling here — instead of
    widening the TTL — keeps the production cache behaviour untouched."""
    with _lock:
        _snap_cache.clear()
        _method_cache.clear()
        _conn_cache.clear()
        _res_cache.clear()
    _cluster_rows_cache["ts"] = 0.0
    _cluster_rows_cache["rows"] = []


def reset_for_tests():
    """Drop registration state (the exposed vars stay hidden-on-GC) and
    zero the native cells."""
    global _registered
    with _lock:
        for v in _vars:
            try:
                if hasattr(v, "destroy"):
                    v.destroy()
                else:
                    v.hide()
            except Exception:
                pass
        _vars.clear()
        _method_rates.clear()
        _conn_rates.clear()
        _registered = False
        _snap_cache.clear()
        _method_cache.clear()
        _conn_cache.clear()
        _res_cache.clear()
    try:
        from brpc_tpu import native

        if native.available():
            native.stats_reset()
            native.mu_prof_reset()
    except Exception:
        pass
