"""Native-runtime bvars — the C++ stat cells surfaced as first-class vars.

The native core (native/src/nat_stats.{h,cpp}) keeps cache-line-aligned
per-thread cells of monotonic counters and log2 latency histograms, combined
on demand like bvar's AgentCombiner. This module registers that snapshot
surface into the Python bvar registry so native traffic appears in /vars,
/status and /brpc_metrics beside the Python lanes — one pane of glass:

- one PassiveStatus per counter under its native name (nat_*);
- a PerSecond window (``<name>_second``) over each traffic counter, which
  also gives the /vars?chart=1 SVG trend for free;
- per-lane latency percentiles (``nat_<lane>_latency_p50/p99/p999_us``)
  interpolated from the combined log2 histograms (percentile.h's role with
  a deterministic histogram instead of a reservoir).
"""
from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from brpc_tpu.bvar.variable import PassiveStatus, find_exposed
from brpc_tpu.bvar.window import PerSecond

_lock = threading.Lock()
_registered = False
_vars = []  # keep strong refs: exposed Variables must not be GC'd

# one combined-snapshot call per dump, not one per counter: /vars and the
# sampler tick read ~20 counters at once and each combine walks every cell
_snap_cache = (0.0, None)


def _snapshot() -> Dict[str, int]:
    global _snap_cache
    now = time.monotonic()
    ts, snap = _snap_cache
    if snap is None or now - ts > 0.25:
        from brpc_tpu import native

        snap = native.stats_counters()
        _snap_cache = (now, snap)
    return snap


class _CounterSource:
    """Quacks like an invertible Reducer so Window/PerSecond can sample
    it: get_value() is the combined native counter."""

    invertible = True

    def __init__(self, name: str):
        self._name = name

    def get_value(self) -> int:
        return int(_snapshot().get(self._name, 0))


# gauges / bookkeeping counters whose per-second delta is meaningless
_NO_RATE = {"nat_py_queue_depth", "nat_spans_dropped",
            "nat_connections_accepted", "nat_sqpoll_rings"}

_PCTS = (("p50", 0.50), ("p99", 0.99), ("p999", 0.999))


def register_native_bvars() -> bool:
    """Idempotently expose the native stat surface; False when the native
    library is unavailable."""
    global _registered
    with _lock:
        if _registered:
            # the counter/lane surface is static, but the dispatcher
            # pool may have started AFTER the first registration (e.g.
            # the /vars server came up before any native runtime use):
            # top up the per-dispatcher rows
            _register_dispatcher_rows()
            return True
        try:
            from brpc_tpu import native

            if not native.available():
                return False
            names = native.stats_counter_names()
            lanes = native.stats_lane_names()
        except Exception:
            return False
        for name in names:
            if find_exposed(name) is None:
                _vars.append(PassiveStatus(
                    lambda n=name: int(_snapshot().get(n, 0)), name))
            if name not in _NO_RATE and \
                    find_exposed(f"{name}_second") is None:
                _vars.append(PerSecond(_CounterSource(name), 10,
                                       f"{name}_second"))
        for idx, lane in enumerate(lanes):
            for suffix, q in _PCTS:
                vname = f"nat_{lane}_latency_{suffix}_us"
                if find_exposed(vname) is None:
                    _vars.append(PassiveStatus(
                        lambda i=idx, qq=q: round(
                            _stats_quantile_us(i, qq), 1), vname))
        # per-dispatcher rows (multicore scale-out observability): one
        # gauge triple per epoll/io_uring loop — connections owned now,
        # event-delivering wakeup rounds, SQPOLL on/off on its ring
        _register_dispatcher_rows()
        _registered = True
        return True


def _register_dispatcher_rows():
    """Expose nat_dispatcher_<i>_* rows for every loop that exists NOW;
    called again on later register_native_bvars() calls so a runtime
    started after the first registration still gets its rows (must be
    called with _lock held)."""
    try:
        from brpc_tpu import native

        ndisp = native.dispatcher_count() if native.available() else 0
    except Exception:
        ndisp = 0
    for i in range(ndisp):
        for field in ("sockets", "wakeups", "sqpoll"):
            vname = f"nat_dispatcher_{i}_{field}"
            if find_exposed(vname) is None:
                _vars.append(PassiveStatus(
                    lambda di=i, f=field: _disp_field(di, f), vname))


def _disp_field(idx: int, field: str):
    # one FFI call for the one requested row (a full dispatcher_stats()
    # refetch per field made a /vars render O(ndisp^2) crossings)
    import ctypes

    from brpc_tpu import native

    lib = native.load()
    sockets = ctypes.c_uint64()
    wakeups = ctypes.c_uint64()
    sqpoll = ctypes.c_int()
    if lib.nat_disp_stat(idx, ctypes.byref(sockets), ctypes.byref(wakeups),
                         ctypes.byref(sqpoll)) != 0:
        return 0
    return {"sockets": sockets.value, "wakeups": wakeups.value,
            "sqpoll": sqpoll.value}[field]


def _stats_quantile_us(lane: int, q: float) -> float:
    from brpc_tpu import native

    return native.stats_quantile(lane, q) / 1e3


# the PR-5 robustness counters, summarized on /status as one line the
# moment any of them moves (a fault injection round, an overload shed or
# a breaker trip should be visible at a glance, not only in /vars)
_OVERLOAD_KEYS = ("nat_faults_injected", "nat_elimit_rejects",
                  "nat_queue_deadline_drops", "nat_retry_budget_exhausted",
                  "nat_breaker_isolations", "nat_breaker_revivals")


def native_status_lines(snap: Optional[Dict[str, int]] = None) -> List[str]:
    """The /status page's native section: per-protocol traffic counters
    and tail latency, empty when the native runtime never carried any.
    `snap` overrides the live counter snapshot (tests)."""
    try:
        from brpc_tpu import native

        if not native.available():
            return []
        if snap is None:
            snap = native.stats_counters()
        lanes = native.stats_lane_names()
    except Exception:
        return []
    if not any(snap.values()):
        return []
    lines = ["", "native runtime:"]
    lines.append(
        f"  read_bytes: {snap.get('nat_socket_read_bytes', 0)}  "
        f"write_bytes: {snap.get('nat_socket_write_bytes', 0)}  "
        f"accepted: {snap.get('nat_connections_accepted', 0)}  "
        f"py_queue_depth: {snap.get('nat_py_queue_depth', 0)}")
    proto_keys = (("tpu_std", "nat_tpu_std"), ("http", "nat_http"),
                  ("grpc", "nat_grpc"), ("redis", "nat_redis"),
                  ("client", "nat_client"))
    count_suffix = {"client": ("calls", "responses", "errors")}
    for label, pfx in proto_keys:
        s_in, s_out, s_err = count_suffix.get(
            label, ("msgs_in", "responses_out", "errors"))
        msgs = snap.get(f"{pfx}_{s_in}", 0)
        if msgs == 0:
            continue
        lines.append(
            f"  {label}: in={msgs} out={snap.get(f'{pfx}_{s_out}', 0)} "
            f"errors={snap.get(f'{pfx}_{s_err}', 0)}")
    if any(snap.get(k, 0) for k in _OVERLOAD_KEYS):
        lines.append("  overload/faults: " + " ".join(
            f"{k[4:]}={snap.get(k, 0)}" for k in _OVERLOAD_KEYS))
    for idx, lane in enumerate(lanes):
        try:
            from brpc_tpu import native as _n

            if not any(_n.stats_hist(idx)):
                continue
            p50, p99, p999 = (_n.stats_quantile(idx, q) / 1e3
                              for _, q in _PCTS)
        except Exception:
            continue
        lines.append(f"  {lane}_latency_us: p50={p50:.1f} p99={p99:.1f} "
                     f"p999={p999:.1f}")
    return lines


def reset_for_tests():
    """Drop registration state (the exposed vars stay hidden-on-GC) and
    zero the native cells."""
    global _registered, _snap_cache
    with _lock:
        for v in _vars:
            try:
                if hasattr(v, "destroy"):
                    v.destroy()
                else:
                    v.hide()
            except Exception:
                pass
        _vars.clear()
        _registered = False
        _snap_cache = (0.0, None)
    try:
        from brpc_tpu import native

        if native.available():
            native.stats_reset()
    except Exception:
        pass
