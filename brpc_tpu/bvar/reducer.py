"""Reducer family — contention-free writes via per-thread agents.

Counterpart of bvar::Reducer (/root/reference/src/bvar/reducer.h:69-224) and
its agent machinery (detail/agent_group.h, detail/combiner.h): each writing
thread owns a private cell; readers merge all cells. Writes touch only
thread-local state (no shared cacheline in the reference; no shared lock in
the hot path here), which is what lets every layer of the framework
instrument itself without serializing.

Adder/Maxer/Miner (reducer.h:224,258,308) and IntRecorder (average with a
(sum, num) compound value, int_recorder.h) are provided.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional

from brpc_tpu.bvar.variable import Variable


class _Cell:
    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value


class Reducer(Variable):
    """op must be commutative + associative; identity is its neutral value."""

    def __init__(
        self,
        op: Callable,
        identity,
        name: Optional[str] = None,
        series_op: Optional[Callable] = None,
    ):
        self._op = op
        self._identity = identity
        # series_op combines adjacent window samples; defaults to op
        # (max-of-maxes), while Adder overrides nothing — windows of Adders
        # difference samples instead (see window.py).
        self._series_op = series_op or op
        self._tls = threading.local()
        self._cells: List[_Cell] = []
        self._cells_lock = threading.Lock()
        # value carried over from dead/reset threads
        self._carry = identity
        super().__init__(name)

    # -- hot path ----------------------------------------------------------
    def _cell(self) -> _Cell:
        cell = getattr(self._tls, "cell", None)
        if cell is None:
            cell = _Cell(self._identity)
            self._tls.cell = cell
            with self._cells_lock:
                self._cells.append(cell)
        return cell

    def update(self, value):
        cell = self._cell()
        cell.value = self._op(cell.value, value)

    __lshift__ = update  # brpc idiom: adder << 1

    # -- read path ---------------------------------------------------------
    def get_value(self):
        with self._cells_lock:
            result = self._carry
            for cell in self._cells:
                result = self._op(result, cell.value)
        return result

    def reset(self):
        """Combine-and-clear all agents; returns the combined value
        (Reducer::reset, used by window sampling of non-invertible ops)."""
        with self._cells_lock:
            result = self._carry
            self._carry = self._identity
            for cell in self._cells:
                result = self._op(result, cell.value)
                cell.value = self._identity
        return result

    @property
    def op(self):
        return self._op

    @property
    def series_op(self):
        return self._series_op

    @property
    def identity(self):
        return self._identity


class Adder(Reducer):
    """Summing reducer; supports negative updates (reducer.h:224)."""

    def __init__(self, name: Optional[str] = None):
        super().__init__(lambda a, b: a + b, 0, name)

    # Adders are invertible: window value = now - then (see window.py).
    invertible = True


class Maxer(Reducer):
    def __init__(self, name: Optional[str] = None):
        super().__init__(max, float("-inf"), name)

    invertible = False

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("-inf") else v


class Miner(Reducer):
    def __init__(self, name: Optional[str] = None):
        super().__init__(min, float("inf"), name)

    invertible = False

    def get_value(self):
        v = super().get_value()
        return 0 if v == float("inf") else v


class _Stat:
    __slots__ = ("sum", "num")

    def __init__(self, sum_=0, num=0):
        self.sum = sum_
        self.num = num

    def __add__(self, other):
        return _Stat(self.sum + other.sum, self.num + other.num)

    def __sub__(self, other):
        return _Stat(self.sum - other.sum, self.num - other.num)

    @property
    def average(self) -> float:
        return self.sum / self.num if self.num else 0.0


class IntRecorder(Reducer):
    """Average-of-samples recorder (bvar::IntRecorder, int_recorder.h):
    compound (sum, num) value; get_value() -> _Stat with .average."""

    invertible = True  # _Stat supports __sub__, so windows can difference it

    def __init__(self, name: Optional[str] = None):
        super().__init__(lambda a, b: a + b, _Stat(), name)

    def update(self, sample: float):
        cell = self._cell()
        cell.value = cell.value + _Stat(sample, 1)

    __lshift__ = update

    def average(self) -> float:
        return self.get_value().average

    def describe(self) -> str:
        s = self.get_value()
        return f"avg={s.average:.3f} num={s.num}"


Stat = _Stat
