"""Sampler — the background 1s-tick thread behind every windowed metric.

Counterpart of bvar::detail::Sampler/SamplerCollector
(/root/reference/src/bvar/detail/sampler.{h,cpp}): one daemon thread wakes
every second and asks each registered sampler to take_sample(); Window /
PerSecond / LatencyRecorder read the resulting ring of timestamped samples.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Optional, Tuple

MAX_WINDOW_SIZE = 3600


class Sampler:
    """One sampled series: a ring of (timestamp, value) pairs."""

    def __init__(self, take_fn, window_size: int = 60):
        self._take_fn = take_fn
        self._window = min(max(1, window_size), MAX_WINDOW_SIZE)
        self._samples: Deque[Tuple[float, object]] = deque(maxlen=self._window + 1)
        self._lock = threading.Lock()
        _collector().add(self)

    def take_sample(self):
        value = self._take_fn()
        with self._lock:
            self._samples.append((time.monotonic(), value))

    def latest(self) -> Optional[Tuple[float, object]]:
        with self._lock:
            return self._samples[-1] if self._samples else None

    def oldest_in(self, window_s: int) -> Optional[Tuple[float, object]]:
        """The sample closest to window_s seconds ago (value_at semantics of
        detail/series.h)."""
        cutoff = time.monotonic() - window_s - 0.5
        with self._lock:
            candidate = None
            for ts, v in self._samples:
                if ts >= cutoff:
                    return (ts, v) if candidate is None else candidate
                candidate = (ts, v)
            return candidate

    def samples_in(self, window_s: int):
        cutoff = time.monotonic() - window_s - 0.5
        with self._lock:
            return [(ts, v) for ts, v in self._samples if ts >= cutoff]

    def destroy(self):
        _collector().remove(self)


class _SamplerCollector:
    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._samplers = set()
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    def add(self, sampler: Sampler):
        with self._lock:
            self._samplers.add(sampler)
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._run, name="bvar_sampler", daemon=True
                )
                self._thread.start()

    def remove(self, sampler: Sampler):
        with self._lock:
            self._samplers.discard(sampler)

    def _run(self):
        while not self._stop.wait(1.0):
            with self._lock:
                samplers = list(self._samplers)
            for s in samplers:
                try:
                    s.take_sample()
                except Exception:
                    pass  # one bad sampler must not kill the tick thread

    def force_tick_for_tests(self):
        with self._lock:
            samplers = list(self._samplers)
        for s in samplers:
            s.take_sample()


def _collector() -> _SamplerCollector:
    with _SamplerCollector._instance_lock:
        if _SamplerCollector._instance is None:
            _SamplerCollector._instance = _SamplerCollector()
        return _SamplerCollector._instance


def force_tick_for_tests():
    """Synchronously sample everything — lets tests avoid 1s sleeps."""
    _collector().force_tick_for_tests()
