"""brpc_tpu — a TPU-native RPC and tensor-transport framework.

A brand-new framework with the capabilities of brpc (reference surveyed in
SURVEY.md): zero-copy chained buffers whose blocks can live in TPU HBM, an
M:N user-space scheduler (native C++ core under native/), wait-free
connection writes with pluggable transports (host TCP as baseline, an ICI
endpoint in the role of brpc's RDMA endpoint), multi-protocol framed RPC with
timeouts/retries/backup requests, combo channels whose fan-out maps onto XLA
collectives over a jax.sharding.Mesh, streaming RPC with window flow control
for tensor pipelines, and bvar-style observability with an embedded HTTP
debug console.

Layering mirrors the reference's strict 4-library stack
(/root/reference/src: butil -> bthread+bvar -> brpc):

  brpc_tpu.butil     -- base: IOBuf, pools, DoublyBufferedData, EndPoint, flags
  brpc_tpu.bvar      -- lock-light metrics (per-thread agents + sampler)
  brpc_tpu.rpc       -- Server / Channel / Controller / protocols / LB / NS
  brpc_tpu.parallel  -- combo channels + XLA-collective fan-out over a Mesh
  brpc_tpu.tensor    -- ring attention, MoE, pipeline blocks (transport users)
  brpc_tpu.builtin   -- HTTP debug console (/status /vars /flags /rpcz ...)
  brpc_tpu.native    -- ctypes bindings to the C++ core (libbrpc_tpu.so)
"""

__version__ = "0.1.0"

from brpc_tpu.butil.status import Status  # noqa: F401
from brpc_tpu.butil.endpoint import EndPoint  # noqa: F401
from brpc_tpu.butil.iobuf import IOBuf, IOBufAppender, IOPortal  # noqa: F401
