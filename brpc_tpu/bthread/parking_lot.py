"""ParkingLot — sleep/wake of idle workers.

Counterpart of bthread::ParkingLot
(/root/reference/src/bthread/parking_lot.h:31-77): a 31-bit signal counter
plus a stop bit; workers read the expected state before their final queue
check, then park only if the counter is unchanged (no lost wakeups). The
monographdb fork gives each worker its own lot for precise wakeup
(task_control.h:123-126) — TaskControl here does the same.
"""
from __future__ import annotations

import threading


class ParkingLot:
    STOP_BIT = 1 << 31

    def __init__(self):
        self._pending_signal = 0
        self._cond = threading.Condition()

    def signal(self, num_task: int = 1):
        with self._cond:
            self._pending_signal = (self._pending_signal + (num_task << 1)) & 0xFFFFFFFF
            self._cond.notify(num_task)

    def get_state(self) -> int:
        return self._pending_signal

    def wait(self, expected_state: int, timeout: float = None) -> bool:
        """Park unless a signal arrived since expected_state was read."""
        with self._cond:
            if self._pending_signal != expected_state:
                return False  # state moved: don't sleep, recheck queues
            return not self._cond.wait(timeout)

    def stop(self):
        with self._cond:
            self._pending_signal |= self.STOP_BIT
            self._cond.notify_all()

    def stopped(self) -> bool:
        return bool(self._pending_signal & self.STOP_BIT)
