"""butex — futex-shaped wait/wake on a 32-bit word.

Counterpart of bthread::butex (/root/reference/src/bthread/butex.{h,cpp};
API butex.h:36-71): wait blocks only if the word still equals the expected
value (checked under the wait-queue lock, so a concurrent change-then-wake
cannot be missed); wake moves waiters out. The reference wakes bthreads by
requeueing them to a runqueue and pthreads via a real futex
(butex.cpp:258,297,332,691); without greenlets every Python waiter is a
(worker or user) thread, i.e. the reference's pthread-waiter path.

Foundation of Mutex/Cond/CountdownEvent/bthread-join here exactly as in the
reference.
"""
from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Optional


class _Waiter:
    __slots__ = ("event", "butex")

    def __init__(self, butex: "Butex"):
        self.event = threading.Event()
        self.butex: Optional[Butex] = butex  # None once woken/requeued-out


class Butex:
    __slots__ = ("value", "_waiters", "_lock")

    def __init__(self, value: int = 0):
        self.value = value
        self._waiters: Deque[_Waiter] = deque()
        self._lock = threading.Lock()

    def wait(self, expected_value: int, timeout: Optional[float] = None) -> bool:
        """Block until woken, if value == expected_value at entry.

        Returns False immediately (EWOULDBLOCK) if the value already moved;
        True if woken; False on timeout.
        """
        with self._lock:
            if self.value != expected_value:
                return False
            w = _Waiter(self)
            self._waiters.append(w)
        ok = w.event.wait(timeout)
        if not ok:
            # Timed out: remove self unless a concurrent wake already took us.
            with self._lock:
                if w.butex is self:
                    try:
                        self._waiters.remove(w)
                    except ValueError:
                        pass
                    w.butex = None
        return ok

    def wake(self, n: int = 1) -> int:
        """Wake up to n waiters (butex_wake / butex_wake_all)."""
        woken = 0
        with self._lock:
            while self._waiters and woken < n:
                w = self._waiters.popleft()
                w.butex = None
                w.event.set()
                woken += 1
        return woken

    def wake_all(self) -> int:
        return self.wake(1 << 30)

    def requeue(self, dest: "Butex") -> int:
        """Wake one waiter, move the rest to dest (butex_requeue,
        butex.h:58) — the primitive behind Cond::broadcast without a
        thundering herd."""
        first, moved = None, []
        with self._lock:
            if self._waiters:
                first = self._waiters.popleft()
                first.butex = None
            while self._waiters:
                w = self._waiters.popleft()
                moved.append(w)
        if moved:
            with dest._lock:
                for w in moved:
                    w.butex = dest
                dest._waiters.extend(moved)
        if first is not None:
            first.event.set()
            return 1 + len(moved)
        return len(moved)


def butex_create(value: int = 0) -> Butex:
    return Butex(value)


def butex_wait(b: Butex, expected_value: int, timeout: Optional[float] = None) -> bool:
    return b.wait(expected_value, timeout)


def butex_wake(b: Butex, n: int = 1) -> int:
    return b.wake(n)


def butex_wake_all(b: Butex) -> int:
    return b.wake_all()
